"""Deterministic fault injection for the live agent cluster.

The epidemic kernel can already experience the headline fault family —
``loss``, ``partition_blocks``/``heal_tick``, churn (``sim/epidemic.py``,
``sim/churn.py``) — but until now the real agents could not, so the
sim's degraded-mode predictions were unvalidated against the system they
model.  This module closes that loop the way the BFT-simulation and
CRDT-emulation literature does: faults on the *real* implementation must
be injectable, deterministic, and replayable.

Design:

* a :class:`FaultPlan` is a frozen, seeded description of the fault
  regime: per-link drop probability, added latency, a block partition
  with a heal time, and a crash/restart schedule;
* every per-message decision is a PURE function of
  ``(seed, src, dst, channel, n)`` where ``n`` is the link-local message
  counter — no shared RNG stream, so decisions do not depend on global
  scheduling order.  Replaying the same per-link message sequence yields
  byte-identical decisions (asserted in ``tests/test_faults.py``);
* a :class:`FaultController` binds the plan to a running cluster: nodes
  register by NAME (stable across runs; ports are ephemeral), and each
  agent gets a hook closure that the transport consults on
  ``send_uni``/``open_bi`` and the runtime consults on SWIM datagrams.

Fault semantics mirror the simulator:

* ``drop`` and an active partition are IN-FLIGHT losses: the sender
  believes the send succeeded (uni/udp), the receiver never sees it —
  exactly the sim's ``loss`` model, so anti-entropy is what heals it;
* bi-streams (sync) cannot half-deliver a session, so a partitioned or
  dropped ``open_bi`` surfaces as a connect error — the retryable shape
  the sync client already handles;
* crashes are real: the agent task is stopped (``graceful=False``) and
  later relaunched from the same directory, so peers experience genuine
  connect failures (breaker + backoff territory, not emulation).
"""

from __future__ import annotations

import hashlib
import struct
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

Addr = Tuple[str, int]

_DECISION = struct.Struct("<B d")  # (dropped, delay_s) — the replay log unit


@dataclass(frozen=True)
class FaultAction:
    """One per-message fault decision."""

    drop: bool = False
    delay: float = 0.0
    reason: str = ""  # "loss" | "partition" | ""

    def encode(self) -> bytes:
        return _DECISION.pack(1 if self.drop else 0, self.delay)


_NO_FAULT = FaultAction()


@dataclass(frozen=True)
class CrashEvent:
    """Crash ``node`` at ``at`` seconds after start; restart it at
    ``restart_at`` (None = stays down)."""

    node: str
    at: float
    restart_at: Optional[float] = None


@dataclass(frozen=True)
class LoopStall:
    """Block ``node``'s event loop for ``duration_ms`` at ``at``
    seconds after the schedule clock starts — the stalled-event-loop
    fault family.  Executed by ``devcluster.run_stall_schedule``; the
    agents' own :class:`~corrosion_tpu.agent.health.LoopHealthProbe`
    is what must observe it (the fault exists to exercise the probe
    and the loop-affine paths behind it)."""

    node: str
    at: float
    duration_ms: float


@dataclass(frozen=True)
class SnapFault:
    """One snapshot-install fault on ``node`` (the INSTALLING client):
    kill it at a named install stage — ``"crash_staging"`` (mid chunk
    stream, sidecar partially written), ``"crash_installing"`` (the
    ``installing`` journal marker is durable but the swap has not
    happened), or ``"crash_swapped"`` (``os.replace`` completed, the
    marker not yet cleared) — then restart it ``restart_delay``
    seconds later.  Consumed ONCE: the reborn node's retry runs clean,
    which is exactly the crash-recovery contract under test
    (``snapshot.recover_pending_install``)."""

    node: str
    mode: str  # crash_staging | crash_installing | crash_swapped
    restart_delay: float = 0.5


@dataclass(frozen=True)
class FaultPlan:
    """Seeded, replayable fault regime — the live-cluster analogue of
    ``EpidemicConfig``'s ``loss``/``partition_blocks``/``heal_tick``
    plus a churn (crash/restart) schedule and the adversarial families
    (clock skew, one-way partitions, slow IO, loop stalls) the
    scenario matrix drives (``sim/scenarios.py``)."""

    seed: int = 0
    # per-link, per-message drop probability (sim: EpidemicConfig.loss)
    drop: float = 0.0
    # added one-way latency: base + uniform[0, jitter) per message
    delay: float = 0.0
    delay_jitter: float = 0.0
    # nodes split into `partition_blocks` blocks whose cross-traffic is
    # dropped until `heal_after` seconds (sim: partition_blocks +
    # heal_tick); None = partition never heals by itself (tests drive
    # FaultController.heal() manually for determinism)
    partition_blocks: int = 1
    heal_after: Optional[float] = None
    crashes: Tuple[CrashEvent, ...] = ()
    # -- adversarial families (docs/faults.md, scenario matrix) --------
    # one-way partitions: directed (src_block, dst_block) pairs severed
    # while the partition is active.  Empty = symmetric (every
    # cross-block pair, both directions — the original behavior).
    # `link_decision` is already directional (src and dst are distinct
    # hash inputs); this makes the PARTITION directional too.
    oneway_blocks: Tuple[Tuple[int, int], ...] = ()
    # per-node HLC clock skew: constant offset up to ±clock_skew_max_ns
    # plus linear drift up to ±clock_drift_max_ppm, derived per node by
    # `node_clock` as a pure function of (seed, node) — injected at the
    # HLClock now_ns seam (types/hlc.py skewed_now_ns), so the 300 ms
    # max-delta gossip-clock rule is exercised by real traffic
    clock_skew_max_ns: int = 0
    clock_drift_max_ppm: float = 0.0
    # slow-disk injection (seconds, base + uniform[0, jitter) per
    # operation, seeded per (node, op, n)) at the storage write/collect
    # seams (CrConn.io_fault): the delay runs on the worker/caller
    # thread holding the storage path, never directly on the event loop
    disk_write_delay: float = 0.0
    disk_write_jitter: float = 0.0
    disk_read_delay: float = 0.0
    disk_read_jitter: float = 0.0
    loop_stalls: Tuple[LoopStall, ...] = ()
    # snapshot-install fault knobs (docs/faults.md): per-client crash
    # stages injected at the install seams; truncated/corrupted/
    # divergent snapshot SERVES are modeled by ByzantineSnapshotServer
    snap_faults: Tuple[SnapFault, ...] = ()

    def link_decision(self, src: str, dst: str, channel: str,
                      n: int) -> FaultAction:
        """The pure decision function: same (seed, src, dst, channel, n)
        ⇒ same action, byte for byte, forever."""
        if self.drop <= 0.0 and self.delay <= 0.0 and self.delay_jitter <= 0.0:
            return _NO_FAULT
        h = hashlib.blake2b(
            f"{self.seed}:{src}:{dst}:{channel}:{n}".encode(),
            digest_size=16,
        ).digest()
        drop_draw = int.from_bytes(h[:8], "big") / 2.0**64
        delay_draw = int.from_bytes(h[8:], "big") / 2.0**64
        drop = drop_draw < self.drop
        delay = 0.0
        if not drop and (self.delay or self.delay_jitter):
            delay = self.delay + self.delay_jitter * delay_draw
        if drop:
            return FaultAction(drop=True, delay=0.0, reason="loss")
        if delay:
            return FaultAction(drop=False, delay=delay)
        return _NO_FAULT

    def block_of(self, idx: int, n_nodes: int) -> int:
        """Partition block of node index ``idx`` — identical to the
        sim's ``_partition_ids`` (idx * blocks // n)."""
        if self.partition_blocks <= 1 or n_nodes <= 0:
            return 0
        return idx * self.partition_blocks // n_nodes

    def blocks_severed(self, src_block: int, dst_block: int) -> bool:
        """Is traffic src_block → dst_block cut while the partition is
        active?  Symmetric plans (no ``oneway_blocks``) sever every
        cross-block pair both ways; one-way plans sever exactly the
        listed directed pairs."""
        if src_block == dst_block:
            return False
        if not self.oneway_blocks:
            return True
        return (src_block, dst_block) in self.oneway_blocks

    def node_clock(self, node: str) -> Tuple[int, float]:
        """``(offset_ns, drift_ratio)`` for ``node`` — a pure function
        of (seed, node), so a restart (or a replay) re-derives the
        identical skew.  Offset is uniform in ±clock_skew_max_ns, drift
        uniform in ±clock_drift_max_ppm (returned as a ratio)."""
        if not self.clock_skew_max_ns and not self.clock_drift_max_ppm:
            return (0, 0.0)
        h = hashlib.blake2b(
            f"{self.seed}:{node}:clock".encode(), digest_size=16
        ).digest()
        u1 = int.from_bytes(h[:8], "big") / 2.0**64
        u2 = int.from_bytes(h[8:], "big") / 2.0**64
        offset = int((2.0 * u1 - 1.0) * self.clock_skew_max_ns)
        drift = (2.0 * u2 - 1.0) * self.clock_drift_max_ppm * 1e-6
        return (offset, drift)

    def io_decision(self, node: str, op: str, n: int) -> float:
        """Seeded slow-disk delay (seconds) for ``node``'s nth ``op``
        (``"write"`` | ``"read"``) — pure in (seed, node, op, n), the
        same replay contract as :meth:`link_decision`."""
        if op == "write":
            base, jitter = self.disk_write_delay, self.disk_write_jitter
        else:
            base, jitter = self.disk_read_delay, self.disk_read_jitter
        if base <= 0.0 and jitter <= 0.0:
            return 0.0
        h = hashlib.blake2b(
            f"{self.seed}:{node}:disk_{op}:{n}".encode(), digest_size=8
        ).digest()
        u = int.from_bytes(h, "big") / 2.0**64
        return base + jitter * u

    @classmethod
    def from_dict(cls, d: dict) -> "FaultPlan":
        """Reconstruct a plan from :meth:`FaultController.as_dict`
        output — the introspection surface round-trips (asserted in
        ``tests/test_faults.py``), so a replay can be driven from an
        admin ``faults`` dump."""
        return cls(
            seed=d["seed"],
            drop=d["drop"],
            delay=d["delay"],
            delay_jitter=d["delay_jitter"],
            partition_blocks=d["partition_blocks"],
            heal_after=d["heal_after"],
            crashes=tuple(
                CrashEvent(c["node"], c["at"], c["restart_at"])
                for c in d.get("crashes", ())
            ),
            oneway_blocks=tuple(
                (int(a), int(b)) for a, b in d.get("oneway_blocks", ())
            ),
            clock_skew_max_ns=d.get("clock_skew_max_ns", 0),
            clock_drift_max_ppm=d.get("clock_drift_max_ppm", 0.0),
            disk_write_delay=d.get("disk_write_delay", 0.0),
            disk_write_jitter=d.get("disk_write_jitter", 0.0),
            disk_read_delay=d.get("disk_read_delay", 0.0),
            disk_read_jitter=d.get("disk_read_jitter", 0.0),
            loop_stalls=tuple(
                LoopStall(s["node"], s["at"], s["duration_ms"])
                for s in d.get("loop_stalls", ())
            ),
            snap_faults=tuple(
                SnapFault(s["node"], s["mode"],
                          s.get("restart_delay", 0.5))
                for s in d.get("snap_faults", ())
            ),
        )


class FaultController:
    """Binds a :class:`FaultPlan` to a live cluster.

    Nodes register by name (in a deterministic order — devcluster boots
    in topology order); each agent consults :meth:`filter` through a
    per-node hook.  All decisions are appended to :attr:`decision_log`
    so a replay can be asserted byte-identical.
    """

    def __init__(self, plan: FaultPlan,
                 now: Optional[Callable[[], float]] = None):
        import threading
        import time

        self.plan = plan
        self._now = now or time.monotonic
        self._t0: Optional[float] = None
        self._addr_to_node: Dict[Addr, str] = {}
        self._node_idx: Dict[str, int] = {}
        self._counters: Dict[Tuple[str, str, str], int] = {}
        # io hooks run on WORKER threads (apply pool, serve pool, write
        # callers) unlike the loop-affine link hooks: counter ticks and
        # log appends must be atomic or concurrent IO would lose ticks
        # and break the per-stream replay contract
        self._io_lock = threading.Lock()
        # the partition is armed by split(), not at boot: cluster
        # formation (membership dissemination) happens whole, then the
        # harness splits at measurement start — the live analogue of
        # the sim starting partitioned at tick 0
        self._split_at: Optional[float] = None
        self._healed = False
        self.decision_log = bytearray()
        self.injected: Dict[str, int] = {"drop": 0, "partition": 0,
                                         "delay": 0, "disk": 0,
                                         "stall": 0, "snap_crash": 0}
        # snapshot-install faults are ONE-SHOT per (node, mode): the
        # reborn node's retry must run clean (the recovery contract)
        self._snap_consumed: set = set()
        # crash orchestration bookkeeping (devcluster.run_inprocess)
        self.agents: Optional[Dict[str, object]] = None
        self.respawn: Dict[str, Callable] = {}
        self.crash_log: List[Tuple[float, str, str]] = []
        # loop-stall orchestration (devcluster.run_stall_schedule)
        self.stall_log: List[Tuple[float, str, float]] = []

    # -- registration ---------------------------------------------------

    def register(self, name: str, addr: Addr) -> None:
        self._node_idx.setdefault(name, len(self._node_idx))
        self._addr_to_node[tuple(addr)] = name

    def start(self) -> None:
        if self._t0 is None:
            self._t0 = self._now()

    def restart_clock(self) -> None:
        """Re-zero the schedule clock (measurement start, after cluster
        formation): crash/restart event times are relative to this."""
        self._t0 = self._now()

    def elapsed(self) -> float:
        if self._t0 is None:
            return 0.0
        return self._now() - self._t0

    # -- partition state ------------------------------------------------

    def split(self) -> None:
        """Arm the partition (no-op for partition_blocks<=1).  The
        plan's ``heal_after`` runs from this moment; tests may instead
        heal manually via :meth:`heal` for full determinism.

        Established cross-block connections are SEVERED, not just new
        dials blocked: a real partition stops delivering on live TCP
        connections too, and an anti-entropy session that handshook
        just before the split would otherwise keep legally serving
        across it (its State is read after the split).  The teardown
        surfaces in-flight sessions as resets — the retryable-partial
        shape the sync client is hardened for."""
        if self.plan.partition_blocks <= 1:
            return
        self._split_at = self._now()
        self._healed = False
        self._sever_cross_block()

    def _sever_cross_block(self) -> None:
        # OUTBOUND caches are per-agent, so dropping only the conns in
        # severed DIRECTIONS keeps a one-way partition one-way: with
        # (0, 1) severed, block-1 agents keep their cached conns toward
        # block 0 and stay able to dial it
        if not self.agents:
            return
        n = len(self._node_idx)
        for name, agent in self.agents.items():
            si = self._node_idx.get(name)
            transport = getattr(agent, "transport", None)
            if si is None or transport is None:
                continue
            sb = self.plan.block_of(si, n)
            for addr, peer in list(self._addr_to_node.items()):
                di = self._node_idx.get(peer)
                if di is None:
                    continue
                if self.plan.blocks_severed(sb, self.plan.block_of(di, n)):
                    try:
                        transport.drop(tuple(addr))
                    except Exception:
                        pass

    def heal(self) -> None:
        """Manually end the partition (the deterministic-test path)."""
        self._healed = True

    def partition_active(self) -> bool:
        if self._healed or self._split_at is None:
            return False
        if self.plan.heal_after is not None \
                and self._now() - self._split_at >= self.plan.heal_after:
            self._healed = True
            return False
        return True

    def _partitioned(self, src: str, dst: str) -> bool:
        """Is the DIRECTED link src → dst cut right now?  Symmetric
        plans answer the same for both directions; one-way plans only
        for the listed (src_block, dst_block) pairs."""
        if not self.partition_active():
            return False
        n = len(self._node_idx)
        si = self._node_idx.get(src)
        di = self._node_idx.get(dst)
        if si is None or di is None:
            return False
        return self.plan.blocks_severed(
            self.plan.block_of(si, n), self.plan.block_of(di, n)
        )

    # -- the decision path ----------------------------------------------

    def filter(self, src: str, dst: str, channel: str) -> FaultAction:
        """Decide the fate of the next message on (src → dst, channel).

        Partition drops come first and do NOT consume a link counter
        tick — the heal time is wall-clock, so burning seeded draws on
        partition drops would make post-heal decisions timing-dependent.
        """
        if self._partitioned(src, dst):
            act = FaultAction(drop=True, reason="partition")
            self.injected["partition"] += 1
            self.decision_log += act.encode()
            return act
        if channel == "partition_check":
            # a pure partition probe (transport's post-connect TOCTOU
            # recheck): never consumes a seeded link draw
            return _NO_FAULT
        key = (src, dst, channel)
        n = self._counters.get(key, 0)
        self._counters[key] = n + 1
        act = self.plan.link_decision(src, dst, channel, n)
        if act.drop:
            self.injected["drop"] += 1
        elif act.delay:
            self.injected["delay"] += 1
        self.decision_log += act.encode()
        return act

    def hook_for(self, name: str) -> Callable[[str, Addr], FaultAction]:
        """The per-agent injection hook: ``hook(channel, dst_addr)``.

        Unregistered destinations (admin sockets, external clients) are
        never faulted.
        """

        def hook(channel: str, addr: Addr) -> FaultAction:
            dst = self._addr_to_node.get(tuple(addr))
            if dst is None:
                return _NO_FAULT
            return self.filter(name, dst, channel)

        return hook

    def io_hook_for(self, name: str) -> Callable[[str], float]:
        """The per-agent slow-disk hook for ``CrConn.io_fault``:
        ``hook(op) -> delay_seconds``.  Each decision consumes a
        per-(node, op) counter tick, so every (node, op) STREAM of
        delays replays identically — the same per-stream contract the
        link hooks have.  (Global decision_log order across concurrent
        streams is scheduling-dependent in live runs, for IO and links
        alike; the replay tests drive streams serially.)"""

        def hook(op: str) -> float:
            key = (name, name, f"disk_{op}")
            with self._io_lock:
                n = self._counters.get(key, 0)
                self._counters[key] = n + 1
                d = self.plan.io_decision(name, op, n)
                if d > 0:
                    self.injected["disk"] += 1
                self.decision_log += FaultAction(delay=d).encode()
            return d

        return hook

    def clock_for(self, name: str) -> Tuple[int, float]:
        """``(offset_ns, drift_ratio)`` the node's HLClock should run
        with (``types/hlc.py skewed_now_ns``) — derived, not stored, so
        a respawned node gets its identical skew back."""
        return self.plan.node_clock(name)

    def snap_decision(self, name: str) -> Optional[SnapFault]:
        """The pending snapshot-install fault for ``name``'s NEXT
        install attempt, consumed on return (one-shot: the reborn
        node's retry runs clean).  None = install normally."""
        with self._io_lock:
            for f in self.plan.snap_faults:
                key = (f.node, f.mode)
                if f.node == name and key not in self._snap_consumed:
                    self._snap_consumed.add(key)
                    self.injected["snap_crash"] += 1
                    return f
        return None

    # -- introspection (admin `faults` command) -------------------------

    def as_dict(self) -> dict:
        """Introspection dump (admin ``faults`` command).  The PLAN
        half round-trips through :meth:`FaultPlan.from_dict`."""
        p = self.plan
        return {
            "seed": p.seed,
            "drop": p.drop,
            "delay": p.delay,
            "delay_jitter": p.delay_jitter,
            "partition_blocks": p.partition_blocks,
            "heal_after": p.heal_after,
            "partition_active": self.partition_active(),
            "crashes": [
                {"node": c.node, "at": c.at, "restart_at": c.restart_at}
                for c in p.crashes
            ],
            "oneway_blocks": [list(pair) for pair in p.oneway_blocks],
            "clock_skew_max_ns": p.clock_skew_max_ns,
            "clock_drift_max_ppm": p.clock_drift_max_ppm,
            "disk_write_delay": p.disk_write_delay,
            "disk_write_jitter": p.disk_write_jitter,
            "disk_read_delay": p.disk_read_delay,
            "disk_read_jitter": p.disk_read_jitter,
            "loop_stalls": [
                {"node": s.node, "at": s.at, "duration_ms": s.duration_ms}
                for s in p.loop_stalls
            ],
            "snap_faults": [
                {"node": s.node, "mode": s.mode,
                 "restart_delay": s.restart_delay}
                for s in p.snap_faults
            ],
            "nodes": len(self._node_idx),
            "injected": dict(self.injected),
            "decisions": len(self.decision_log) // _DECISION.size,
            "crash_log": [
                {"t": round(t, 3), "event": ev, "node": node}
                for t, ev, node in self.crash_log
            ],
            "stall_log": [
                {"t": round(t, 3), "node": node, "duration_ms": ms}
                for t, node, ms in self.stall_log
            ],
        }


class EquivocatingPeer:
    """A hostile gossip origin: one actor id, conflicting changesets.

    The scenario matrix's fault family (d): the peer emits

    * **conflicting contents** — two COMPLETE changesets claiming the
      same ``(actor, version)`` with different cell values (a correct
      CRDT origin can never do this: a version is one committed
      transaction);
    * **replayed duplicates** — byte-identical re-sends of an accepted
      changeset (fanout noise amplified; must be absorbed, not
      counted as equivocation);
    * **garbage seq spans** — structurally impossible seq metadata
      (inverted spans, ``last_seq`` below the span end, absurd claimed
      widths) that would poison partial-version buffering.

    Agents must detect the first and third
    (``corro_sync_equivocations_total{kind=}``), quarantine the actor
    through the ``Members`` path, and accept ZERO divergent rows —
    the no-divergence invariant the campaign runner gates on.

    Changesets are crafted with the real wire types, so they are
    indistinguishable from legitimate traffic until inspected.  The
    actor id and payload CONTENTS derive from ``seed``; the HLC
    timestamps are stamped at craft time — provenance lag (wall-now
    minus the changeset ts) needs a live time base, and a seed-derived
    ts would record absurd lags into the cell's p99.
    """

    def __init__(self, seed: int = 0, table: str = "tests",
                 now_ns: Optional[Callable[[], int]] = None,
                 sig_secret: Optional[bytes] = None):
        self.seed = seed
        self.table = table
        # injectable craft-time clock (the Clock seam): a virtual-time
        # campaign stamps hostile changesets on the virtual wall so two
        # runs with one seed emit byte-identical attacks
        self.now_ns = now_ns
        self.actor_id = hashlib.blake2b(
            f"equivocator:{seed}".encode(), digest_size=16
        ).digest()
        # optional Ed25519 identity (types/crypto.py): a KEYED hostile
        # origin — the insider-gone-rogue shape — signs its crafted
        # changesets, so its conflicting pairs become the persistable
        # signed-equivocation proofs the permanent verdict requires
        self.sig_secret = sig_secret
        self._version = 0

    @property
    def sig_public(self) -> Optional[bytes]:
        if self.sig_secret is None:
            return None
        from corrosion_tpu.types import crypto

        return crypto.public_key(self.sig_secret)

    def sign_changeset(self, cv) -> Optional[bytes]:
        """The origin signature for a crafted changeset (None when
        this peer is unkeyed)."""
        if self.sig_secret is None:
            return None
        from corrosion_tpu.agent.runtime import sig_message
        from corrosion_tpu.types import crypto

        return crypto.sign(
            self.sig_secret,
            sig_message(cv.actor_id.bytes, cv.changeset),
        )

    def tampered_copy(self, cv, text: str):
        """A relay-tampered variant of ``cv``: identical claimed
        (actor, version, seqs, last_seq, ts) — the metadata a passed-
        through signature binds — with the cell contents rewritten.
        The framing-relay attack: delivered with the ORIGINAL
        signature, it must convict the delivering transport, never
        the named origin."""
        from dataclasses import replace

        from corrosion_tpu.types import Changeset, ChangeV1

        cs = cv.changeset
        changes = tuple(
            replace(ch, val=text) for ch in cs.changes
        )
        return ChangeV1(
            cv.actor_id,
            Changeset.full(cs.version, changes, cs.seqs, cs.last_seq,
                           cs.ts),
        )

    def _ts(self):
        from corrosion_tpu.types.hlc import Timestamp
        import time

        ns = (self.now_ns or time.time_ns)()
        return Timestamp.pack(ns, 0)

    def _changeset(self, version: int, row_id: int, text: str,
                   seqs=None, last_seq=None, seq: int = 0):
        from corrosion_tpu.agent.pack import pack_values
        from corrosion_tpu.types import ActorId, Changeset, ChangeV1
        from corrosion_tpu.types.base import (
            CrsqlDbVersion,
            CrsqlSeq,
            Version,
        )
        from corrosion_tpu.types.change import Change

        ch = Change(
            table=self.table,
            pk=pack_values([row_id]),
            cid="text",
            val=text,
            col_version=1,
            db_version=CrsqlDbVersion(version),
            seq=CrsqlSeq(seq),
            site_id=self.actor_id,
            cl=1,
        )
        cs = Changeset.full(
            Version(version),
            (ch,),
            seqs if seqs is not None else (CrsqlSeq(0), CrsqlSeq(0)),
            last_seq if last_seq is not None else CrsqlSeq(0),
            self._ts(),
        )
        return ChangeV1(ActorId(self.actor_id), cs)

    def next_version(self) -> int:
        self._version += 1
        return self._version

    def honest(self, row_id: int, text: str):
        """A well-formed changeset (the bait: accepted normally)."""
        return self._changeset(self.next_version(), row_id, text)

    def conflicting_pair(self, row_id: int):
        """Two complete changesets for ONE version with different
        contents — the content-equivocation attack."""
        v = self.next_version()
        a = self._changeset(v, row_id, f"equiv-a-{v}")
        b = self._changeset(v, row_id, f"equiv-b-{v}")
        return a, b

    def garbage_span(self, row_id: int):
        """A changeset whose seq metadata is structurally impossible
        (inverted span + last_seq below the span end)."""
        from corrosion_tpu.types.base import CrsqlSeq

        v = self.next_version()
        return self._changeset(
            v, row_id, f"garbage-{v}",
            seqs=(CrsqlSeq(5), CrsqlSeq(2)), last_seq=CrsqlSeq(1),
            seq=5,
        )

    def absurd_width(self, row_id: int):
        """A changeset claiming a seq span wider than any transaction
        could produce (the unbounded-buffering attack)."""
        from corrosion_tpu.types.base import CrsqlSeq

        v = self.next_version()
        return self._changeset(
            v, row_id, f"wide-{v}",
            seqs=(CrsqlSeq(0), CrsqlSeq(2**40)),
            last_seq=CrsqlSeq(2**40),
        )


class ByzantineSyncServer:
    """A hostile anti-entropy SERVER: the serve-path sibling of
    :class:`EquivocatingPeer` (which attacks with hostile changesets;
    this attacks with hostile needs/ranges/frames on the sync session
    itself).  One instance plays one attack ``mode``:

    * ``lying_ranges``   — advertises a head past any real history
      (``SYNC_MAX_ADVERTISED_HEAD``); a naive client would chunk it
      into ~10^13 need requests.  Defense: the advertised-state screen
      refuses the session outright;
    * ``absurd_needs``   — advertises inverted need/seq spans (the
      wire decoder rejects these; the screen covers the in-process
      path).  Same defense;
    * ``huge_head``      — a head that passes the structural screen
      but is far beyond anything it can serve.  Defense: the
      per-session need cap bounds allocation;
    * ``garbage_frames`` — well-framed, undecodable payload bytes.
      Defense: the frame-validation budget, then the breaker;
    * ``oversized_frame``— a length prefix past ``MAX_FRAME_LEN``.
      Defense: the deframer rejects the stream, breaker trips;
    * ``slow_trickle``   — a serve that never completes (one byte per
      read-timeout window).  Defense: the Clock-driven session
      deadline;
    * ``conflicting_reserve`` — unsolicited re-serves of versions the
      clients already hold, with tampered contents.  Defense: the
      version-ledger dedup drops them (sync re-serves are outside the
      digest defense by design — docs/faults.md).  Fresh hostile
      versions minted under the server's OWN id remain a named
      residual: only signed sync frames could close it, and the
      campaign scopes the mode to re-serves.

    All crafted bytes derive from ``seed`` (+ the injectable clock for
    timestamps), so virtual campaigns replay byte-identically.
    """

    MODES = (
        "lying_ranges", "absurd_needs", "huge_head", "garbage_frames",
        "oversized_frame", "slow_trickle", "conflicting_reserve",
    )

    def __init__(self, seed: int = 0, mode: str = "lying_ranges",
                 now_ns: Optional[Callable[[], int]] = None,
                 reserve_source: Optional[EquivocatingPeer] = None):
        if mode not in self.MODES:
            raise ValueError(f"unknown byzantine mode {mode!r}")
        self.seed = seed
        self.mode = mode
        self.now_ns = now_ns
        self.actor_id = hashlib.blake2b(
            f"byzserver:{seed}:{mode}".encode(), digest_size=16
        ).digest()
        # the hostile actor whose accepted versions the
        # conflicting_reserve mode re-serves tampered
        self.reserve_source = reserve_source

    def advertised_state(self):
        """The SyncStateV1 this server hands a handshaking client."""
        from corrosion_tpu.types.actor import ActorId
        from corrosion_tpu.types.base import Version
        from corrosion_tpu.types.payload import SyncStateV1

        st = SyncStateV1(actor_id=ActorId(self.actor_id))
        if self.mode == "lying_ranges":
            st.heads[ActorId(self.actor_id)] = Version(1 << 52)
        elif self.mode == "absurd_needs":
            st.heads[ActorId(self.actor_id)] = Version(4)
            st.need[ActorId(self.actor_id)] = [(9, 2)]  # inverted
        elif self.mode == "huge_head":
            # below the structural-lie line, far above anything real:
            # the client's need cap must bound the allocation
            st.heads[ActorId(self.actor_id)] = Version((1 << 48) - 1)
        # garbage/oversized/trickle/reserve modes look innocuous at
        # handshake time — the attack is in the serve bytes
        return st

    def serve_duration(self) -> float:
        """Virtual seconds the serve would take to complete — the
        slow-trickle mode never finishes inside any sane deadline."""
        return 1e6 if self.mode == "slow_trickle" else 0.01

    def serve_frames(self, needs) -> bytes:
        """The served byte stream for the client's allocated needs."""
        import struct as _struct

        from corrosion_tpu.bridge import speedy

        if self.mode == "garbage_frames":
            junk = hashlib.blake2b(
                f"byzjunk:{self.seed}".encode(), digest_size=32
            ).digest()
            return b"".join(
                speedy.frame(junk + bytes([i])) for i in range(4)
            )
        if self.mode == "oversized_frame":
            return _struct.pack(">I", speedy.MAX_FRAME_LEN + 1) + b"\x00"
        if self.mode == "conflicting_reserve" \
                and self.reserve_source is not None:
            src = self.reserve_source
            out = []
            for v in range(1, src._version + 1):
                cv = src._changeset(v, 9100, f"byz-reserve-{v}")
                out.append(speedy.frame(speedy.encode_sync_message(cv)))
            return b"".join(out)
        return b""


class ByzantineSnapshotServer:
    """A hostile snapshot SERVER: the snapshot-path sibling of
    :class:`ByzantineSyncServer` — a new, high-leverage Byzantine
    surface (PAPERS.md, "Simulating BFT Protocol Implementations at
    Scale"): a server the dispatch trusts to ship a whole database
    must not be able to install garbage.  One instance plays one
    attack ``mode`` from a REAL cluster node's transport identity:

    * ``truncate``       — advertises the honest digest/size, then the
      stream ends halfway.  Defense: the size/digest check over the
      staged bytes fails, clean abort;
    * ``corrupt_chunk``  — honest advert, one chunk's bytes flipped
      (the staged file is structural garbage).  Same defense;
    * ``divergent_mint`` — a same-length snapshot whose row CONTENTS
      were rewritten, served under the HONEST digest (the server wants
      the tampered state installed as if it were the real one).  The
      whole-snapshot content digest is exactly the gate that kills it.

    All three end in ``corro_sync_client_rejects_total{reason=
    snap_digest}`` + a breaker trip, zero bytes installed, and the
    client's needs falling back to change-by-change via another peer.
    (A hostile server advertising a digest OF its divergent snapshot
    is the unsigned-serve-path residual docs/faults.md names: only
    signed serve attestations close it; the campaign scopes the mode
    to digest-covered tampering.)

    The hostile floors it advertises mirror the honest node's heads,
    so the client-side dispatch genuinely chooses snapshot — the
    containment must come from the install gates, never the harness.
    """

    MODES = ("truncate", "corrupt_chunk", "divergent_mint")

    def __init__(self, seed: int = 0, mode: str = "truncate"):
        if mode not in self.MODES:
            raise ValueError(f"unknown snapshot-byz mode {mode!r}")
        self.seed = seed
        self.mode = mode

    def advertised_state(self, server_agent):
        """The honest node's handshake state with hostile floors
        grafted on: every advertised head becomes a floor, so a behind
        client's dispatch picks snapshot install."""
        import copy

        st = copy.copy(server_agent.generate_sync())
        st.snap_floors = {
            actor: int(head) for actor, head in st.heads.items()
        }
        return st

    def tampered_serve(self, server_agent,
                       chunk_bytes: int) -> Tuple[bytes, int, list]:
        """(advertised_digest, advertised_size, chunks) for one hostile
        serve: the HONEST snapshot's digest/size with tampered chunk
        bytes per the mode.  Deterministic in (seed, db content)."""
        path, digest, size = server_agent._snapshot_build()
        with open(path, "rb") as f:
            blob = f.read()
        if self.mode == "truncate":
            blob = blob[: max(1, len(blob) // 2)]
        elif self.mode == "corrupt_chunk":
            h = hashlib.blake2b(
                f"snapbyz:{self.seed}".encode(), digest_size=8
            ).digest()
            off = int.from_bytes(h, "big") % max(1, len(blob))
            blob = blob[:off] + bytes([blob[off] ^ 0xFF]) + blob[off + 1:]
        else:  # divergent_mint: same length, rewritten row contents
            marker = b"storm-"
            if marker in blob:
                blob = blob.replace(marker, b"evil!!")
            else:
                mid = len(blob) // 2
                blob = blob[:mid] + bytes([blob[mid] ^ 0x5A]) \
                    + blob[mid + 1:]
        chunks = [
            blob[i : i + chunk_bytes]
            for i in range(0, len(blob), max(1, chunk_bytes))
        ]
        return digest, size, chunks
