"""Deterministic fault injection for the live agent cluster.

The epidemic kernel can already experience the headline fault family —
``loss``, ``partition_blocks``/``heal_tick``, churn (``sim/epidemic.py``,
``sim/churn.py``) — but until now the real agents could not, so the
sim's degraded-mode predictions were unvalidated against the system they
model.  This module closes that loop the way the BFT-simulation and
CRDT-emulation literature does: faults on the *real* implementation must
be injectable, deterministic, and replayable.

Design:

* a :class:`FaultPlan` is a frozen, seeded description of the fault
  regime: per-link drop probability, added latency, a block partition
  with a heal time, and a crash/restart schedule;
* every per-message decision is a PURE function of
  ``(seed, src, dst, channel, n)`` where ``n`` is the link-local message
  counter — no shared RNG stream, so decisions do not depend on global
  scheduling order.  Replaying the same per-link message sequence yields
  byte-identical decisions (asserted in ``tests/test_faults.py``);
* a :class:`FaultController` binds the plan to a running cluster: nodes
  register by NAME (stable across runs; ports are ephemeral), and each
  agent gets a hook closure that the transport consults on
  ``send_uni``/``open_bi`` and the runtime consults on SWIM datagrams.

Fault semantics mirror the simulator:

* ``drop`` and an active partition are IN-FLIGHT losses: the sender
  believes the send succeeded (uni/udp), the receiver never sees it —
  exactly the sim's ``loss`` model, so anti-entropy is what heals it;
* bi-streams (sync) cannot half-deliver a session, so a partitioned or
  dropped ``open_bi`` surfaces as a connect error — the retryable shape
  the sync client already handles;
* crashes are real: the agent task is stopped (``graceful=False``) and
  later relaunched from the same directory, so peers experience genuine
  connect failures (breaker + backoff territory, not emulation).
"""

from __future__ import annotations

import hashlib
import struct
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

Addr = Tuple[str, int]

_DECISION = struct.Struct("<B d")  # (dropped, delay_s) — the replay log unit


@dataclass(frozen=True)
class FaultAction:
    """One per-message fault decision."""

    drop: bool = False
    delay: float = 0.0
    reason: str = ""  # "loss" | "partition" | ""

    def encode(self) -> bytes:
        return _DECISION.pack(1 if self.drop else 0, self.delay)


_NO_FAULT = FaultAction()


@dataclass(frozen=True)
class CrashEvent:
    """Crash ``node`` at ``at`` seconds after start; restart it at
    ``restart_at`` (None = stays down)."""

    node: str
    at: float
    restart_at: Optional[float] = None


@dataclass(frozen=True)
class FaultPlan:
    """Seeded, replayable fault regime — the live-cluster analogue of
    ``EpidemicConfig``'s ``loss``/``partition_blocks``/``heal_tick``
    plus a churn (crash/restart) schedule."""

    seed: int = 0
    # per-link, per-message drop probability (sim: EpidemicConfig.loss)
    drop: float = 0.0
    # added one-way latency: base + uniform[0, jitter) per message
    delay: float = 0.0
    delay_jitter: float = 0.0
    # nodes split into `partition_blocks` blocks whose cross-traffic is
    # dropped until `heal_after` seconds (sim: partition_blocks +
    # heal_tick); None = partition never heals by itself (tests drive
    # FaultController.heal() manually for determinism)
    partition_blocks: int = 1
    heal_after: Optional[float] = None
    crashes: Tuple[CrashEvent, ...] = ()

    def link_decision(self, src: str, dst: str, channel: str,
                      n: int) -> FaultAction:
        """The pure decision function: same (seed, src, dst, channel, n)
        ⇒ same action, byte for byte, forever."""
        if self.drop <= 0.0 and self.delay <= 0.0 and self.delay_jitter <= 0.0:
            return _NO_FAULT
        h = hashlib.blake2b(
            f"{self.seed}:{src}:{dst}:{channel}:{n}".encode(),
            digest_size=16,
        ).digest()
        drop_draw = int.from_bytes(h[:8], "big") / 2.0**64
        delay_draw = int.from_bytes(h[8:], "big") / 2.0**64
        drop = drop_draw < self.drop
        delay = 0.0
        if not drop and (self.delay or self.delay_jitter):
            delay = self.delay + self.delay_jitter * delay_draw
        if drop:
            return FaultAction(drop=True, delay=0.0, reason="loss")
        if delay:
            return FaultAction(drop=False, delay=delay)
        return _NO_FAULT

    def block_of(self, idx: int, n_nodes: int) -> int:
        """Partition block of node index ``idx`` — identical to the
        sim's ``_partition_ids`` (idx * blocks // n)."""
        if self.partition_blocks <= 1 or n_nodes <= 0:
            return 0
        return idx * self.partition_blocks // n_nodes


class FaultController:
    """Binds a :class:`FaultPlan` to a live cluster.

    Nodes register by name (in a deterministic order — devcluster boots
    in topology order); each agent consults :meth:`filter` through a
    per-node hook.  All decisions are appended to :attr:`decision_log`
    so a replay can be asserted byte-identical.
    """

    def __init__(self, plan: FaultPlan,
                 now: Optional[Callable[[], float]] = None):
        import time

        self.plan = plan
        self._now = now or time.monotonic
        self._t0: Optional[float] = None
        self._addr_to_node: Dict[Addr, str] = {}
        self._node_idx: Dict[str, int] = {}
        self._counters: Dict[Tuple[str, str, str], int] = {}
        # the partition is armed by split(), not at boot: cluster
        # formation (membership dissemination) happens whole, then the
        # harness splits at measurement start — the live analogue of
        # the sim starting partitioned at tick 0
        self._split_at: Optional[float] = None
        self._healed = False
        self.decision_log = bytearray()
        self.injected: Dict[str, int] = {"drop": 0, "partition": 0,
                                         "delay": 0}
        # crash orchestration bookkeeping (devcluster.run_inprocess)
        self.agents: Optional[Dict[str, object]] = None
        self.respawn: Dict[str, Callable] = {}
        self.crash_log: List[Tuple[float, str, str]] = []

    # -- registration ---------------------------------------------------

    def register(self, name: str, addr: Addr) -> None:
        self._node_idx.setdefault(name, len(self._node_idx))
        self._addr_to_node[tuple(addr)] = name

    def start(self) -> None:
        if self._t0 is None:
            self._t0 = self._now()

    def restart_clock(self) -> None:
        """Re-zero the schedule clock (measurement start, after cluster
        formation): crash/restart event times are relative to this."""
        self._t0 = self._now()

    def elapsed(self) -> float:
        if self._t0 is None:
            return 0.0
        return self._now() - self._t0

    # -- partition state ------------------------------------------------

    def split(self) -> None:
        """Arm the partition (no-op for partition_blocks<=1).  The
        plan's ``heal_after`` runs from this moment; tests may instead
        heal manually via :meth:`heal` for full determinism.

        Established cross-block connections are SEVERED, not just new
        dials blocked: a real partition stops delivering on live TCP
        connections too, and an anti-entropy session that handshook
        just before the split would otherwise keep legally serving
        across it (its State is read after the split).  The teardown
        surfaces in-flight sessions as resets — the retryable-partial
        shape the sync client is hardened for."""
        if self.plan.partition_blocks <= 1:
            return
        self._split_at = self._now()
        self._healed = False
        self._sever_cross_block()

    def _sever_cross_block(self) -> None:
        if not self.agents:
            return
        n = len(self._node_idx)
        for name, agent in self.agents.items():
            si = self._node_idx.get(name)
            transport = getattr(agent, "transport", None)
            if si is None or transport is None:
                continue
            sb = self.plan.block_of(si, n)
            for addr, peer in list(self._addr_to_node.items()):
                di = self._node_idx.get(peer)
                if di is not None and self.plan.block_of(di, n) != sb:
                    try:
                        transport.drop(tuple(addr))
                    except Exception:
                        pass

    def heal(self) -> None:
        """Manually end the partition (the deterministic-test path)."""
        self._healed = True

    def partition_active(self) -> bool:
        if self._healed or self._split_at is None:
            return False
        if self.plan.heal_after is not None \
                and self._now() - self._split_at >= self.plan.heal_after:
            self._healed = True
            return False
        return True

    def _partitioned(self, src: str, dst: str) -> bool:
        if not self.partition_active():
            return False
        n = len(self._node_idx)
        si = self._node_idx.get(src)
        di = self._node_idx.get(dst)
        if si is None or di is None:
            return False
        return (self.plan.block_of(si, n)
                != self.plan.block_of(di, n))

    # -- the decision path ----------------------------------------------

    def filter(self, src: str, dst: str, channel: str) -> FaultAction:
        """Decide the fate of the next message on (src → dst, channel).

        Partition drops come first and do NOT consume a link counter
        tick — the heal time is wall-clock, so burning seeded draws on
        partition drops would make post-heal decisions timing-dependent.
        """
        if self._partitioned(src, dst):
            act = FaultAction(drop=True, reason="partition")
            self.injected["partition"] += 1
            self.decision_log += act.encode()
            return act
        if channel == "partition_check":
            # a pure partition probe (transport's post-connect TOCTOU
            # recheck): never consumes a seeded link draw
            return _NO_FAULT
        key = (src, dst, channel)
        n = self._counters.get(key, 0)
        self._counters[key] = n + 1
        act = self.plan.link_decision(src, dst, channel, n)
        if act.drop:
            self.injected["drop"] += 1
        elif act.delay:
            self.injected["delay"] += 1
        self.decision_log += act.encode()
        return act

    def hook_for(self, name: str) -> Callable[[str, Addr], FaultAction]:
        """The per-agent injection hook: ``hook(channel, dst_addr)``.

        Unregistered destinations (admin sockets, external clients) are
        never faulted.
        """

        def hook(channel: str, addr: Addr) -> FaultAction:
            dst = self._addr_to_node.get(tuple(addr))
            if dst is None:
                return _NO_FAULT
            return self.filter(name, dst, channel)

        return hook

    # -- introspection (admin `faults` command) -------------------------

    def as_dict(self) -> dict:
        p = self.plan
        return {
            "seed": p.seed,
            "drop": p.drop,
            "delay": p.delay,
            "delay_jitter": p.delay_jitter,
            "partition_blocks": p.partition_blocks,
            "heal_after": p.heal_after,
            "partition_active": self.partition_active(),
            "crashes": [
                {"node": c.node, "at": c.at, "restart_at": c.restart_at}
                for c in p.crashes
            ],
            "nodes": len(self._node_idx),
            "injected": dict(self.injected),
            "decisions": len(self.decision_log) // _DECISION.size,
            "crash_log": [
                {"t": round(t, 3), "event": ev, "node": node}
                for t, ev, node in self.crash_log
            ],
        }
