"""Change rows and the byte-budget chunker.

Parity: ``crates/corro-types/src/change.rs:19-29`` (the ``Change`` row — one
cell-level CRDT mutation), ``change.rs:63-171`` (``ChunkedChanges``: split one
version's seq-ordered change stream into ≤8 KiB messages so large
transactions ship as out-of-order reassemblable chunks).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterable, Iterator, List, Tuple

from corrosion_tpu.types.base import CrsqlDbVersion, CrsqlSeq

# Per-message byte budget for broadcast and sync (change.rs:171, peer.rs:344).
MAX_CHANGES_BYTE_SIZE = 8 * 1024

# Sentinel column name used for causal-length-only (delete/resurrect) rows.
SENTINEL_CID = "-1"


@dataclass(frozen=True)
class Change:
    """One cell-level change: (table, pk) row, ``cid`` column, new value.

    ``col_version`` is the per-cell lamport clock, ``db_version`` the
    originating node's storage version, ``seq`` the position inside that
    version's change stream, ``site_id`` the originating actor, and ``cl``
    the row's causal length (odd = live, even = deleted).
    """

    table: str
    pk: bytes
    cid: str
    val: object  # None | int | float | str | bytes
    col_version: int
    db_version: CrsqlDbVersion
    seq: CrsqlSeq
    site_id: bytes
    cl: int

    def is_delete(self) -> bool:
        return self.cl % 2 == 0

    def estimated_byte_size(self) -> int:
        # Mirrors the reference's struct-size + heap-payload estimate used for
        # the 8 KiB budget; exact bytes don't matter, stable accounting does.
        val = self.val
        if isinstance(val, (bytes, bytearray)):
            vsize = len(val)
        elif isinstance(val, str):
            vsize = len(val.encode("utf-8"))
        elif val is None:
            vsize = 1
        else:
            vsize = 8
        return 64 + len(self.table) + len(self.pk) + len(self.cid) + vsize


class ChunkedChanges:
    """Iterate ``(changes, seq_range)`` chunks under a byte budget.

    Yields ``(list_of_changes, (start_seq, end_seq))`` where the seq range is
    *inclusive* and contiguous with the next chunk's range; the final chunk's
    range always extends to ``last_seq`` so receivers can detect completion
    even when trailing changes were elided (empty iterators still yield one
    empty chunk covering the whole range, as the reference does for
    cleared-version serving).
    """

    def __init__(
        self,
        changes: Iterable[Change],
        start_seq: int,
        last_seq: int,
        max_buf_size: int = MAX_CHANGES_BYTE_SIZE,
    ):
        self._iter = iter(changes)
        self._next_start = CrsqlSeq(start_seq)
        self._last_seq = CrsqlSeq(last_seq)
        self._max_buf_size = max_buf_size
        self._done = False

    def __iter__(self) -> Iterator[Tuple[List[Change], Tuple[CrsqlSeq, CrsqlSeq]]]:
        # One-shot: a second iteration would restart the seq accounting from
        # the original start and emit ranges that omit already-yielded rows.
        if self._done:
            raise RuntimeError("ChunkedChanges can only be iterated once")
        self._done = True
        buf: List[Change] = []
        buf_size = 0
        start = self._next_start
        _end = object()
        nxt = next(self._iter, _end)
        while nxt is not _end:
            change = nxt
            nxt = next(self._iter, _end)
            buf.append(change)
            buf_size += change.estimated_byte_size()
            if int(change.seq) >= int(self._last_seq):
                # the advertised range ends here: trailing rows beyond
                # last_seq are elided, never emitted outside the range
                # (change.rs test_change_chunker, last_seq==0 scenario)
                break
            if buf_size >= self._max_buf_size and nxt is not _end:
                # flush on budget only when more rows are coming — an
                # exhausted iterator folds into the final chunk whose
                # range extends to last_seq (gap-absorption semantics)
                yield buf, (start, change.seq)
                start = change.seq.succ()
                buf = []
                buf_size = 0
        yield buf, (start, self._last_seq)
