"""Wire payloads + the sync needs algebra.

Parity: ``crates/corro-types/src/broadcast.rs:37-67`` (``UniPayload`` /
``BiPayload``), ``sync.rs:80-273`` (``SyncStateV1`` / ``SyncNeedV1`` /
``compute_available_needs``).  The needs algebra here is the exact host-side
implementation; :mod:`corrosion_tpu.models.sync` carries the dense-tensor
version used by the simulator, and the two are cross-checked in tests.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from corrosion_tpu.types.actor import ActorId, ClusterId
from corrosion_tpu.types.base import Version
from corrosion_tpu.types.changeset import ChangeV1
from corrosion_tpu.types.hlc import Timestamp
from corrosion_tpu.utils.ranges import RangeSet

Span = Tuple[int, int]


# ---------------------------------------------------------------------------
# Dissemination payloads
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class BroadcastV1:
    """Broadcast payload: a change message (optionally a rebroadcast)."""

    change: ChangeV1


@dataclass(frozen=True)
class UniPayload:
    """Uni-stream payload: broadcast data + originating cluster, priority flag."""

    broadcast: BroadcastV1
    cluster_id: ClusterId = ClusterId(0)
    priority: bool = False


@dataclass(frozen=True)
class BiPayload:
    """Bi-stream (sync session) opener: who wants to sync, with trace ctx."""

    actor_id: ActorId
    trace_ctx: Optional[dict] = None


# ---------------------------------------------------------------------------
# Sync state + needs algebra
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class SyncNeedV1:
    """One need: Full version range | Partial seq ranges | Empty (cleared)."""

    kind: str  # "full" | "partial" | "empty"
    versions: Optional[Span] = None  # full: inclusive version range
    version: Optional[Version] = None  # partial
    seqs: Tuple[Span, ...] = ()  # partial: inclusive seq ranges
    ts: Optional[Timestamp] = None  # empty

    @classmethod
    def full(cls, start: int, end: int) -> "SyncNeedV1":
        return cls(kind="full", versions=(int(start), int(end)))

    @classmethod
    def partial(cls, version: int, seqs) -> "SyncNeedV1":
        return cls(kind="partial", version=Version(version), seqs=tuple(tuple(s) for s in seqs))

    @classmethod
    def empty(cls, ts: Optional[Timestamp] = None) -> "SyncNeedV1":
        return cls(kind="empty", ts=ts)

    def count(self) -> int:
        if self.kind == "full":
            assert self.versions is not None
            return self.versions[1] - self.versions[0] + 1
        return 1


@dataclass
class SyncStateV1:
    """A node's sync handshake: per-actor heads, gaps, partials, cleared ts."""

    actor_id: ActorId = field(default_factory=ActorId)
    heads: Dict[ActorId, Version] = field(default_factory=dict)
    need: Dict[ActorId, List[Span]] = field(default_factory=dict)
    partial_need: Dict[ActorId, Dict[Version, List[Span]]] = field(default_factory=dict)
    last_cleared_ts: Optional[Timestamp] = None
    # snapshot-serve extension (docs/sync.md): per-actor snapshot
    # floors — versions 1..=floor are only obtainable from this node
    # via snapshot install (their per-version bookkeeping is
    # compacted).  Empty = the pre-snapshot wire bytes, exactly.
    snap_floors: Dict[ActorId, int] = field(default_factory=dict)

    def need_len(self) -> int:
        full = sum(e - s + 1 for spans in self.need.values() for s, e in spans)
        partial_seqs = sum(
            e - s + 1
            for partials in self.partial_need.values()
            for spans in partials.values()
            for s, e in spans
        )
        # partial needs count as "chunks" at a nominal 50 seqs/chunk, like the
        # reference's need_len heuristic.
        return full + partial_seqs // 50

    def need_len_for_actor(self, actor_id: ActorId) -> int:
        full = sum(e - s + 1 for s, e in self.need.get(actor_id, []))
        return full + len(self.partial_need.get(actor_id, {}))

    def compute_available_needs(
        self, other: "SyncStateV1"
    ) -> Dict[ActorId, List[SyncNeedV1]]:
        """What WE need that OTHER can serve.

        For every actor the peer has a head for: take the versions the peer
        *fully* has (1..=head minus its own needs and partials), intersect
        with our needed ranges; offer partial-seq completion where either the
        peer has the full version or has complementary seqs of the same
        partial; and ask for everything above our head.
        """
        needs: Dict[ActorId, List[SyncNeedV1]] = {}

        def push(actor: ActorId, need: SyncNeedV1) -> None:
            needs.setdefault(actor, []).append(need)

        for actor_id, head in other.heads.items():
            if actor_id == self.actor_id or int(head) == 0:
                continue

            other_haves = RangeSet([(1, int(head))])
            for s, e in other.need.get(actor_id, []):
                other_haves.remove(s, e)
            for v in other.partial_need.get(actor_id, {}):
                other_haves.remove(int(v), int(v))

            for s, e in self.need.get(actor_id, []):
                for os_, oe in other_haves.intersection_spans(s, e):
                    push(actor_id, SyncNeedV1.full(os_, oe))

            for v, seq_spans in self.partial_need.get(actor_id, {}).items():
                if other_haves.contains(int(v)):
                    push(actor_id, SyncNeedV1.partial(int(v), seq_spans))
                    continue
                other_seqs = other.partial_need.get(actor_id, {}).get(v)
                if other_seqs is None:
                    continue
                ends = [e for _, e in other_seqs] + [e for _, e in seq_spans]
                if not ends:
                    continue
                # seqs the peer HAS within its partial = [0, max_end] minus
                # the seqs it still needs.
                other_seq_haves = RangeSet([(0, max(ends))])
                for s, e in other_seqs:
                    other_seq_haves.remove(s, e)
                overlaps = [
                    clipped
                    for s, e in seq_spans
                    for clipped in other_seq_haves.intersection_spans(s, e)
                ]
                if overlaps:
                    push(actor_id, SyncNeedV1.partial(int(v), overlaps))

            our_head = self.heads.get(actor_id)
            if our_head is None:
                push(actor_id, SyncNeedV1.full(1, int(head)))
            elif int(head) > int(our_head):
                push(actor_id, SyncNeedV1.full(int(our_head) + 1, int(head)))

        return needs
