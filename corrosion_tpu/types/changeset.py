"""Changesets — the unit of dissemination.

Parity: ``crates/corro-types/src/broadcast.rs:104-137`` — ``ChangeV1`` wraps
an actor id plus a ``Changeset`` with three variants: ``Empty`` (versions
cleared/overwritten), ``Full`` (a version's changes with seq range, last_seq
and ts) and ``EmptySet`` (many cleared ranges with a timestamp).  A ``Full``
changeset whose seq range doesn't reach ``last_seq`` is *partial* and gets
buffered until the gaps arrive.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Optional, Tuple

from corrosion_tpu.types.actor import ActorId
from corrosion_tpu.types.base import Version, CrsqlSeq
from corrosion_tpu.types.change import Change
from corrosion_tpu.types.hlc import Timestamp


class ChangeSource(enum.Enum):
    BROADCAST = "broadcast"
    SYNC = "sync"


class ChangesetKind(enum.Enum):
    FULL = "full"
    EMPTY = "empty"
    EMPTY_SET = "empty_set"


@dataclass(frozen=True)
class Changeset:
    """Tagged union with an explicit variant tag.

    * FULL:      ``version`` + ``changes`` + ``seqs`` + ``last_seq`` + ``ts``.
    * EMPTY:     ``versions`` range cleared, optional ``ts``.
    * EMPTY_SET: ``ranges`` (cleared version ranges, may be empty) + ``ts``.
    """

    kind: ChangesetKind
    # Full
    version: Optional[Version] = None
    changes: Tuple[Change, ...] = ()
    seqs: Optional[Tuple[CrsqlSeq, CrsqlSeq]] = None  # inclusive
    last_seq: Optional[CrsqlSeq] = None
    ts: Optional[Timestamp] = None
    # Empty
    versions: Optional[Tuple[Version, Version]] = None  # inclusive range
    # EmptySet
    ranges: Tuple[Tuple[Version, Version], ...] = ()

    @classmethod
    def full(
        cls,
        version: Version,
        changes,
        seqs: Tuple[CrsqlSeq, CrsqlSeq],
        last_seq: CrsqlSeq,
        ts: Timestamp,
    ) -> "Changeset":
        return cls(
            kind=ChangesetKind.FULL,
            version=version,
            changes=tuple(changes),
            seqs=seqs,
            last_seq=last_seq,
            ts=ts,
        )

    @classmethod
    def empty(
        cls, versions: Tuple[Version, Version], ts: Optional[Timestamp] = None
    ) -> "Changeset":
        return cls(kind=ChangesetKind.EMPTY, versions=versions, ts=ts)

    @classmethod
    def empty_set(cls, ranges, ts: Timestamp) -> "Changeset":
        return cls(
            kind=ChangesetKind.EMPTY_SET,
            ranges=tuple(tuple(r) for r in ranges),
            ts=ts,
        )

    @property
    def is_full(self) -> bool:
        return self.kind is ChangesetKind.FULL

    @property
    def is_empty_variant(self) -> bool:
        return self.kind is ChangesetKind.EMPTY

    @property
    def is_empty_set(self) -> bool:
        return self.kind is ChangesetKind.EMPTY_SET

    def is_complete(self) -> bool:
        """A Full changeset is complete iff its seq range covers 0..=last_seq."""
        if not self.is_full:
            return True
        assert self.seqs is not None and self.last_seq is not None
        return int(self.seqs[0]) == 0 and int(self.seqs[1]) == int(self.last_seq)

    def max_db_version(self) -> int:
        return max((int(c.db_version) for c in self.changes), default=0)


@dataclass(frozen=True)
class ChangeV1:
    """Wire change message: originating actor + changeset."""

    actor_id: ActorId
    changeset: Changeset
