"""Actor identity.

Parity: ``crates/corro-types/src/actor.rs:26,133-210,222`` — ``ActorId`` is a
uuid equal to the storage engine's site id; ``Actor`` is the SWIM identity
(id + gossip addr + HLC timestamp + cluster id) whose ``renew()`` bumps the
timestamp so a node declared down can rejoin under the same id, and whose
``has_same_prefix`` compares everything except the timestamp.
"""

from __future__ import annotations

import uuid
from dataclasses import dataclass, replace, field

from corrosion_tpu.types.hlc import Timestamp


class ClusterId(int):
    """u16 cluster id; members of different clusters never gossip."""

    __slots__ = ()
    MAX = (1 << 16) - 1

    def __new__(cls, value: int = 0):
        if not 0 <= int(value) <= cls.MAX:
            raise ValueError(f"ClusterId out of u16 range: {value!r}")
        return super().__new__(cls, value)


@dataclass(frozen=True, order=True)
class ActorId:
    """16-byte actor id == storage site id (uuid)."""

    bytes: bytes = field(default=b"\x00" * 16)

    def __post_init__(self):
        if len(self.bytes) != 16:
            raise ValueError("ActorId must be 16 bytes")

    @classmethod
    def generate(cls) -> "ActorId":
        return cls(uuid.uuid4().bytes)

    @classmethod
    def from_uuid(cls, u: uuid.UUID) -> "ActorId":
        return cls(u.bytes)

    @classmethod
    def from_hex(cls, s: str) -> "ActorId":
        return cls(uuid.UUID(s).bytes)

    def to_uuid(self) -> uuid.UUID:
        return uuid.UUID(bytes=self.bytes)

    def as_u128(self) -> int:
        return int.from_bytes(self.bytes, "big")

    def __str__(self) -> str:
        return str(self.to_uuid())

    def __hash__(self) -> int:
        return hash(self.bytes)


@dataclass(frozen=True)
class Actor:
    """SWIM member identity (the foca ``Identity`` impl in the reference)."""

    id: ActorId
    addr: str  # "host:port" gossip address
    ts: Timestamp = field(default_factory=lambda: Timestamp(0))
    cluster_id: ClusterId = field(default_factory=ClusterId)

    def has_same_prefix(self, other: "Actor") -> bool:
        """Identity equality ignoring the (renewable) timestamp."""
        return (
            self.id == other.id
            and self.addr == other.addr
            and self.cluster_id == other.cluster_id
        )

    def renew(self, now: Timestamp) -> "Actor":
        """Auto-rejoin: same identity, fresh timestamp (actor.rs:199-210)."""
        return replace(self, ts=now)
