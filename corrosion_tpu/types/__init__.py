"""Core value types shared by the simulator and the host agent.

Mirrors the reference's ``corro-base-types`` and ``corro-types`` crates
(`crates/corro-base-types/src/lib.rs`, `crates/corro-types/src/{actor,
broadcast,change,sync}.rs`) — re-designed as plain Python data types whose
array-of-structs forms live in ``corrosion_tpu.ops``.
"""

from corrosion_tpu.types.base import Version, CrsqlDbVersion, CrsqlSeq
from corrosion_tpu.types.actor import ActorId, Actor, ClusterId
from corrosion_tpu.types.hlc import Timestamp, HLClock, MAX_CLOCK_DELTA_NS
from corrosion_tpu.types.change import Change, ChunkedChanges, MAX_CHANGES_BYTE_SIZE
from corrosion_tpu.types.changeset import Changeset, ChangesetKind, ChangeV1, ChangeSource
from corrosion_tpu.types.payload import (
    BroadcastV1,
    UniPayload,
    BiPayload,
    SyncStateV1,
    SyncNeedV1,
)

__all__ = [
    "Version",
    "CrsqlDbVersion",
    "CrsqlSeq",
    "ActorId",
    "Actor",
    "ClusterId",
    "Timestamp",
    "HLClock",
    "MAX_CLOCK_DELTA_NS",
    "Change",
    "ChunkedChanges",
    "MAX_CHANGES_BYTE_SIZE",
    "Changeset",
    "ChangesetKind",
    "ChangeV1",
    "ChangeSource",
    "BroadcastV1",
    "UniPayload",
    "BiPayload",
    "SyncStateV1",
    "SyncNeedV1",
]
