"""Version / sequence newtypes.

Parity: ``crates/corro-base-types/src/lib.rs:18,109,194`` defines ``Version``,
``CrsqlDbVersion`` and ``CrsqlSeq`` as u64 newtypes with successor/predecessor
("Step") support so they can key range maps.  Python ints are unbounded, so
the newtypes here are thin ``int`` subclasses that preserve type identity
through arithmetic used by the range algebra in
:mod:`corrosion_tpu.utils.ranges`.
"""

from __future__ import annotations


class _U64(int):
    """An int constrained to the u64 domain (the wire format is u64)."""

    __slots__ = ()
    MAX = (1 << 64) - 1

    def __new__(cls, value: int = 0):
        if not 0 <= int(value) <= cls.MAX:
            raise ValueError(f"{cls.__name__} out of u64 range: {value!r}")
        return super().__new__(cls, value)

    def __repr__(self) -> str:  # pragma: no cover - cosmetic
        return f"{type(self).__name__}({int(self)})"

    # Step/StepLite parity: successor & predecessor used by range maps.
    def succ(self):
        return type(self)(int(self) + 1)

    def pred(self):
        return type(self)(int(self) - 1)

    def __add__(self, other):
        return type(self)(int(self) + int(other))

    def __sub__(self, other):
        return type(self)(int(self) - int(other))


class Version(_U64):
    """A per-actor broadcast version (one committed local transaction)."""

    __slots__ = ()


class CrsqlDbVersion(_U64):
    """The storage engine's monotonically increasing db_version."""

    __slots__ = ()


class CrsqlSeq(_U64):
    """Sequence number of a single change row within one version."""

    __slots__ = ()
