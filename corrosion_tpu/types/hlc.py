"""Hybrid logical clock.

Parity: the reference uses the ``uhlc`` crate (NTP64 timestamps; see
``crates/corro-types/src/broadcast.rs:283`` and the 300 ms max clock delta at
``crates/corro-agent/src/agent/setup.rs``).  A ``Timestamp`` is a single u64:
the upper 48 bits are physical time (NTP64 truncated) and the low 16 bits a
logical counter, which preserves total ordering and survives wire round-trips
as one integer — the same packing the simulator uses on-device.
"""

from __future__ import annotations

import time
import threading

# Reject remote timestamps more than this far ahead of local physical time
# (reference: 300 ms max HLC delta, setup.rs).
MAX_CLOCK_DELTA_NS = 300_000_000

_LOGICAL_BITS = 16
_LOGICAL_MASK = (1 << _LOGICAL_BITS) - 1


class Timestamp(int):
    """u64 HLC timestamp: (physical_48 << 16) | logical_16."""

    __slots__ = ()
    MAX = (1 << 64) - 1

    def __new__(cls, value: int = 0):
        if not 0 <= int(value) <= cls.MAX:
            raise ValueError(f"Timestamp out of u64 range: {value!r}")
        return super().__new__(cls, value)

    @classmethod
    def pack(cls, physical_ns: int, logical: int) -> "Timestamp":
        # NTP64-style: seconds in the high 32 of the physical field would lose
        # resolution at 48 bits, so we store physical time as ns >> 16 (≈65 µs
        # granularity) — the logical counter disambiguates within a grain.
        return cls(((physical_ns >> _LOGICAL_BITS) << _LOGICAL_BITS) | (logical & _LOGICAL_MASK))

    @property
    def physical_ns(self) -> int:
        return int(self) & ~_LOGICAL_MASK

    @property
    def logical(self) -> int:
        return int(self) & _LOGICAL_MASK

    def wall_seconds(self) -> float:
        """Physical half as Unix seconds (≈65 µs granularity) — the
        provenance time base: origin-commit→apply lag is wall-now minus
        the changeset timestamp's wall seconds."""
        return self.physical_ns / 1e9

    def __repr__(self) -> str:  # pragma: no cover - cosmetic
        return f"Timestamp(phys_ns={self.physical_ns}, logical={self.logical})"


def skewed_now_ns(offset_ns: int = 0, drift: float = 0.0,
                  base=time.time_ns):
    """A ``now_ns`` source with a constant offset plus linear drift —
    the clock-skew fault seam (``faults.FaultPlan.node_clock``).

    ``drift`` is a ratio (e.g. ``50e-6`` = +50 ppm) applied to time
    elapsed since this factory was called, so a long-running node's
    clock error grows the way a real bad oscillator's does.  With both
    parameters zero the base source is returned untouched (the
    production path pays nothing)."""
    if not offset_ns and not drift:
        return base
    t0 = base()

    def now_ns() -> int:
        t = base()
        return int(t + offset_ns + (t - t0) * drift)

    return now_ns


class ClockDriftError(Exception):
    """Remote timestamp too far ahead of local physical time."""


class HLClock:
    """Thread-safe hybrid logical clock.

    ``new_timestamp`` stamps local events; ``update_with_timestamp`` merges a
    remote timestamp on message receipt (rejecting drift beyond
    ``max_delta_ns``, like the agent does for gossip clock updates).
    """

    def __init__(self, max_delta_ns: int = MAX_CLOCK_DELTA_NS, now_ns=time.time_ns):
        self._last = Timestamp(0)
        self._lock = threading.Lock()
        self._now_ns = now_ns
        self.max_delta_ns = max_delta_ns

    @property
    def last(self) -> Timestamp:
        return self._last

    def new_timestamp(self) -> Timestamp:
        with self._lock:
            phys = self._now_ns() & ~_LOGICAL_MASK
            if phys > self._last.physical_ns:
                ts = Timestamp.pack(phys, 0)
            else:
                ts = Timestamp(int(self._last) + 1)
            self._last = ts
            return ts

    def observe_timestamp(self) -> Timestamp:
        """The stamp :meth:`new_timestamp` WOULD mint, without advancing
        the clock — for observations (flight-recorder records,
        provenance first-seen stamps): telemetry must never mutate
        protocol clock state, so e.g. the 'merge rejected, local clock
        unpolluted' invariant of the 300 ms delta rule stays assertable
        to the exact tick.  Two observations inside one ~65 µs grain may
        stamp equal; observation streams are sorted, not deduped."""
        with self._lock:
            phys = self._now_ns() & ~_LOGICAL_MASK
            if phys > self._last.physical_ns:
                return Timestamp.pack(phys, 0)
            return Timestamp(int(self._last) + 1)

    def update_with_timestamp(self, remote: Timestamp) -> None:
        with self._lock:
            now = self._now_ns()
            if remote.physical_ns > now + self.max_delta_ns:
                raise ClockDriftError(
                    f"remote timestamp {remote!r} exceeds local time by more "
                    f"than {self.max_delta_ns} ns"
                )
            if int(remote) > int(self._last):
                self._last = Timestamp(int(remote))
