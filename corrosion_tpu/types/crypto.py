"""Dependency-free Ed25519 (RFC 8032) for signed changeset attribution.

The equivocation defense (docs/faults.md) needs cryptographic actor
identity: a quarantine verdict is only safe to make PERMANENT when the
evidence could not have been forged by a hostile relay.  The container
deliberately carries no crypto wheels (``cryptography`` is absent — see
``agent/tls.py``), so this module implements Ed25519 from the RFC 8032
reference equations in pure Python:

* curve: twisted Edwards ``-x^2 + y^2 = 1 + d x^2 y^2`` over
  ``p = 2^255 - 19``, base point order
  ``L = 2^252 + 27742317777372353535851937790883648493``;
* points in extended homogeneous coordinates ``(X, Y, Z, T)`` with the
  RFC's unified add/double formulas;
* keys/signatures in the standard 32/64-byte encodings, hashes via
  ``hashlib.sha512`` — byte-compatible with every other Ed25519
  implementation (pinned by the RFC 8032 §7.1 test vectors in
  ``tests/test_crypto.py``).

Performance posture: signing uses a precomputed table of base-point
doubles (~0.5 ms/sign on this container); verification is a plain
double-and-add over the decompressed public key (~2 ms).  That is far
too slow for per-message use — which is exactly why the ingest path
verifies on EVIDENCE only (digest conflicts, span-screen trips, and a
rate+interval-bounded spot check; see ``agent/runtime.py``).
"""

from __future__ import annotations

import hashlib
from typing import List, Optional, Tuple

__all__ = [
    "SECRET_LEN", "PUBKEY_LEN", "SIG_LEN",
    "public_key", "sign", "verify", "verify_cached", "seed_keypair",
]

SECRET_LEN = 32
PUBKEY_LEN = 32
SIG_LEN = 64

_P = 2**255 - 19
_L = 2**252 + 27742317777372353535851937790883648493
_D = (-121665 * pow(121666, _P - 2, _P)) % _P
# sqrt(-1) mod p, used by point decompression
_SQRT_M1 = pow(2, (_P - 1) // 4, _P)

Point = Tuple[int, int, int, int]  # extended coords (X, Y, Z, T)

_IDENT: Point = (0, 1, 1, 0)


def _pt_add(a: Point, b: Point) -> Point:
    """RFC 8032 §5.1.4 unified addition (complete on the twisted
    Edwards curve: no exceptional cases to screen)."""
    x1, y1, z1, t1 = a
    x2, y2, z2, t2 = b
    p = _P
    A = ((y1 - x1) * (y2 - x2)) % p
    B = ((y1 + x1) * (y2 + x2)) % p
    C = (2 * t1 * t2 * _D) % p
    D = (2 * z1 * z2) % p
    E = B - A
    F = D - C
    G = D + C
    H = B + A
    return ((E * F) % p, (G * H) % p, (F * G) % p, (E * H) % p)


def _pt_double(a: Point) -> Point:
    """Dedicated doubling (dbl-2008-hwcd, a = -1): 4M + 4S vs the
    unified add's ~9M — doubles dominate the arbitrary-point scalar
    mult that verification pays, so this roughly halves verify time."""
    x1, y1, z1, _t1 = a
    p = _P
    A = (x1 * x1) % p
    B = (y1 * y1) % p
    C = (2 * z1 * z1) % p
    H = A + B
    xy = x1 + y1
    E = H - (xy * xy) % p
    G = A - B
    F = C + G
    return ((E * F) % p, (G * H) % p, (F * G) % p, (E * H) % p)


def _pt_eq(a: Point, b: Point) -> bool:
    # cross-multiply out the projective Z
    return ((a[0] * b[2] - b[0] * a[2]) % _P == 0
            and (a[1] * b[2] - b[1] * a[2]) % _P == 0)


def _recover_x(y: int, sign: int) -> Optional[int]:
    if y >= _P:
        return None
    x2 = ((y * y - 1) * pow(_D * y * y + 1, _P - 2, _P)) % _P
    if x2 == 0:
        return None if sign else 0
    x = pow(x2, (_P + 3) // 8, _P)
    if (x * x - x2) % _P != 0:
        x = (x * _SQRT_M1) % _P
    if (x * x - x2) % _P != 0:
        return None
    if x & 1 != sign:
        x = _P - x
    return x


# base point: y = 4/5, x recovered even
_G_Y = (4 * pow(5, _P - 2, _P)) % _P
_G_X = _recover_x(_G_Y, 0)
assert _G_X is not None
_G: Point = (_G_X, _G_Y, 1, (_G_X * _G_Y) % _P)

# precomputed doubles of the base point: scalar mult of G becomes a
# pure add-chain over this table (no doublings per sign)
_G_DOUBLES: List[Point] = []
_acc = _G
for _ in range(256):  # clamped secrets set bit 254; spare headroom
    _G_DOUBLES.append(_acc)
    _acc = _pt_double(_acc)
del _acc


def _scalar_mul_base(s: int) -> Point:
    q = _IDENT
    i = 0
    while s:
        if s & 1:
            q = _pt_add(q, _G_DOUBLES[i])
        s >>= 1
        i += 1
    return q


def _scalar_mul(s: int, a: Point) -> Point:
    q = _IDENT
    while s:
        if s & 1:
            q = _pt_add(q, a)
        a = _pt_double(a)
        s >>= 1
    return q


def _compress(a: Point) -> bytes:
    zinv = pow(a[2], _P - 2, _P)
    x = (a[0] * zinv) % _P
    y = (a[1] * zinv) % _P
    return int.to_bytes(y | ((x & 1) << 255), 32, "little")


def _decompress(data: bytes) -> Optional[Point]:
    if len(data) != 32:
        return None
    n = int.from_bytes(data, "little")
    sign = n >> 255
    y = n & ((1 << 255) - 1)
    x = _recover_x(y, sign)
    if x is None:
        return None
    return (x, y, 1, (x * y) % _P)


def _sha512_int(*parts: bytes) -> int:
    h = hashlib.sha512()
    for part in parts:
        h.update(part)
    return int.from_bytes(h.digest(), "little")


def _expand_secret(secret: bytes) -> Tuple[int, bytes]:
    if len(secret) != SECRET_LEN:
        raise ValueError(f"Ed25519 secret must be {SECRET_LEN} bytes")
    h = hashlib.sha512(secret).digest()
    a = int.from_bytes(h[:32], "little")
    a &= (1 << 254) - 8
    a |= 1 << 254
    return a, h[32:]


# pubkey memo: deriving A = aB is a full scalar mult (~ms in pure
# Python) and both signing and agent construction re-ask it for the
# same secret constantly (a 512-node signed campaign re-derives its
# whole identity set per determinism run)
_PUB_CACHE: dict = {}


def public_key(secret: bytes) -> bytes:
    """32-byte public key for a 32-byte secret seed (memoized)."""
    secret = bytes(secret)
    pub = _PUB_CACHE.get(secret)
    if pub is None:
        a, _prefix = _expand_secret(secret)
        pub = _compress(_scalar_mul_base(a))
        if len(_PUB_CACHE) >= 4096:
            _PUB_CACHE.pop(next(iter(_PUB_CACHE)))
        _PUB_CACHE[secret] = pub
    return pub


def sign(secret: bytes, msg: bytes) -> bytes:
    """64-byte RFC 8032 signature of ``msg`` under ``secret``."""
    a, prefix = _expand_secret(secret)
    pub = public_key(secret)
    r = _sha512_int(prefix, msg) % _L
    big_r = _compress(_scalar_mul_base(r))
    k = _sha512_int(big_r, pub, msg) % _L
    s = (r + k * a) % _L
    return big_r + int.to_bytes(s, 32, "little")


# decompressed-pubkey memo: point decompression costs a field
# exponentiation, and verifiers re-see the same few directory keys
_PUB_POINT_CACHE: dict = {}


def _pub_point(pub: bytes) -> Optional[Point]:
    pt = _PUB_POINT_CACHE.get(pub)
    if pt is None and pub not in _PUB_POINT_CACHE:
        pt = _decompress(pub)
        if len(_PUB_POINT_CACHE) >= 4096:
            _PUB_POINT_CACHE.pop(next(iter(_PUB_POINT_CACHE)))
        _PUB_POINT_CACHE[bytes(pub)] = pt
    return pt


def verify(pub: bytes, msg: bytes, sig: bytes) -> bool:
    """True iff ``sig`` is a valid signature of ``msg`` under ``pub``.
    Malformed keys/signatures return False, never raise."""
    try:
        if len(sig) != SIG_LEN or len(pub) != PUBKEY_LEN:
            return False
        a_pt = _pub_point(pub)
        r_pt = _decompress(sig[:32])
        if a_pt is None or r_pt is None:
            return False
        s = int.from_bytes(sig[32:], "little")
        if s >= _L:
            return False
        k = _sha512_int(sig[:32], pub, msg) % _L
        return _pt_eq(
            _scalar_mul_base(s), _pt_add(r_pt, _scalar_mul(k, a_pt))
        )
    except Exception:  # noqa: BLE001 - a verifier must never raise
        return False


# process-wide memo of verification outcomes: verify() is a pure
# function of (pub, msg, sig), and the places that call it at scale —
# a tampered wave fanning out to hundreds of in-process virtual
# agents, or broadcast duplicates re-presenting one signed statement —
# re-ask the same triple over and over.  Bounded FIFO; ~2 ms saved per
# hit on this container.
_VERIFY_CACHE: dict = {}
_VERIFY_CACHE_MAX = 4096


def verify_cached(pub: bytes, msg: bytes, sig: bytes) -> bool:
    key = hashlib.blake2b(
        len(pub).to_bytes(2, "big") + pub
        + len(sig).to_bytes(2, "big") + sig + msg,
        digest_size=16,
    ).digest()
    hit = _VERIFY_CACHE.get(key)
    if hit is not None:
        return hit
    ok = verify(pub, msg, sig)
    if len(_VERIFY_CACHE) >= _VERIFY_CACHE_MAX:
        _VERIFY_CACHE.pop(next(iter(_VERIFY_CACHE)))
    _VERIFY_CACHE[key] = ok
    return ok


_KEYPAIR_CACHE: dict = {}


def seed_keypair(material: bytes) -> Tuple[bytes, bytes]:
    """``(secret, public)`` deterministically derived from arbitrary
    seed material (the campaign path: a harness-private secret per
    node).  The secret is a blake2b KDF of the material — NOT derivable
    from the public actor id alone, or a relay could re-sign tampered
    contents and the attribution would prove nothing.  Memoized (pure
    function; a 512-node signed campaign derives its whole key
    directory in one pass and re-derives it per determinism run)."""
    pair = _KEYPAIR_CACHE.get(material)
    if pair is None:
        secret = hashlib.blake2b(
            material, digest_size=SECRET_LEN, person=b"corro-sig-kdf"
        ).digest()
        pair = (secret, public_key(secret))
        if len(_KEYPAIR_CACHE) >= 4096:
            _KEYPAIR_CACHE.pop(next(iter(_KEYPAIR_CACHE)))
        _KEYPAIR_CACHE[bytes(material)] = pair
    return pair
