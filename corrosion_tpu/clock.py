"""The agent's one injectable time source.

Every agent timer — ``asyncio.sleep`` loops (probe / gossip / sync /
broadcast-flush / maintenance / recorder cadence), ``time.monotonic``
state stamps (member ``last_seen``, suspicion deadlines, breaker
cooldowns, equivocation-quarantine windows, sync-session ages), wall
clocks (provenance lag, staleness, flight-record stamps) and the HLC
physical source — reads time through a single :class:`Clock` object
owned by the agent (``AgentConfig.clock``).  Two implementations:

* :class:`SystemClock` (the default, ``SYSTEM_CLOCK``): every method is
  a direct alias of the stdlib callable the code used before the
  refactor — ``time.monotonic`` / ``time.time`` / ``time.time_ns`` /
  ``asyncio.sleep`` / ``asyncio.wait_for`` — so the uninjected path is
  behavior- and wire-byte-identical to the pre-refactor agent;

* :class:`VirtualClock`: a discrete-event scheduler clock.  Time is a
  number that only moves when the owner pops the event heap
  (``advance``), so a cluster of hundreds of in-process agents runs a
  multi-minute fault campaign in however long the *events* take to
  execute — seconds — instead of waiting out timers (LiveStack,
  PAPERS.md: full-stack simulation by putting unmodified node software
  on virtual time).  The wall epoch is a fixed constant by default, so
  two runs with the same seed produce byte-identical timestamps —
  the determinism contract the virtual campaign tests assert
  (``tests/test_vtime.py``).

What is deliberately NOT virtualized (real time even under a
VirtualClock): worker-thread internals that never gate protocol
progress — the storage busy-retry sleep, lock-diagnostic stamps
(``agent/locks.py``), the DNS resolve TTL cache (``swim_foca.py``),
and trace span durations (``agent/tracing.py``).  See
``docs/sim.md`` (virtual time) for the full table.
"""

from __future__ import annotations

import asyncio
import heapq
import time
from typing import Any, Callable, List, Optional


class Clock:
    """The protocol.  ``SystemClock`` and ``VirtualClock`` implement it;
    type annotations reference this base."""

    def monotonic(self) -> float:  # pragma: no cover - interface
        raise NotImplementedError

    def wall(self) -> float:  # pragma: no cover - interface
        raise NotImplementedError

    def wall_ns(self) -> int:  # pragma: no cover - interface
        raise NotImplementedError

    async def sleep(self, delay: float, result: Any = None):
        raise NotImplementedError  # pragma: no cover - interface

    async def wait_for(self, aw, timeout: Optional[float]):
        raise NotImplementedError  # pragma: no cover - interface


class SystemClock(Clock):
    """Real time.  Every method IS the stdlib callable (class-level
    aliases, zero indirection beyond one attribute hop), so the default
    path cannot drift from the pre-refactor behavior."""

    monotonic = staticmethod(time.monotonic)
    wall = staticmethod(time.time)
    wall_ns = staticmethod(time.time_ns)
    sleep = staticmethod(asyncio.sleep)
    wait_for = staticmethod(asyncio.wait_for)


#: the process default — what an Agent uses when no clock is injected
SYSTEM_CLOCK = SystemClock()


#: fixed virtual wall epoch (2020-09-13T12:26:40Z): a CONSTANT, not
#: ``time.time()`` at construction, so two virtual runs with the same
#: seed stamp byte-identical HLC timestamps and journal wall times
VIRTUAL_EPOCH_NS = 1_600_000_000 * 1_000_000_000


class _Event:
    """One heap entry.  ``cancelled`` keeps cancellation O(1) — the pop
    loop skips dead entries."""

    __slots__ = ("due", "seq", "fn", "cancelled")

    def __init__(self, due: float, seq: int, fn: Callable[[float], None]):
        self.due = due
        self.seq = seq
        self.fn = fn
        self.cancelled = False

    def __lt__(self, other: "_Event") -> bool:
        return (self.due, self.seq) < (other.due, other.seq)


class VirtualClock(Clock):
    """Discrete-event virtual time.

    ``monotonic()`` returns the current virtual instant; ``schedule``
    pushes a callback onto the heap; ``advance()`` pops the earliest
    event, moves time to its deadline and runs it.  Callbacks receive
    their *scheduled* due time, so a callback that fired late (because
    a :meth:`jump` — the loop-stall model — moved time past it) can
    measure its own lateness exactly the way the live
    ``LoopHealthProbe`` measures a late wakeup.

    Event order is a pure function of (deadlines, insertion order):
    ties break on a monotone sequence number, never on object identity
    or hash order — the byte-determinism contract of the virtual
    campaigns.

    Single-threaded by design: the scheduler that owns the clock is
    the only driver.  ``sleep``/``wait_for`` integrate with a running
    asyncio loop by resolving futures from heap pops, so real agent
    coroutines *can* be suspended on virtual time when a driver pumps
    ``advance()`` from within the loop.
    """

    def __init__(self, start: float = 0.0,
                 wall0_ns: int = VIRTUAL_EPOCH_NS):
        self._now = float(start)
        self._wall0_ns = int(wall0_ns)
        self._heap: List[_Event] = []
        self._seq = 0

    # -- reading -------------------------------------------------------

    def monotonic(self) -> float:
        return self._now

    def wall(self) -> float:
        return self._wall0_ns / 1e9 + self._now

    def wall_ns(self) -> int:
        return self._wall0_ns + int(round(self._now * 1e9))

    # -- scheduling ----------------------------------------------------

    def schedule(self, delay: float, fn: Callable[[float], None]) -> _Event:
        """Run ``fn(due)`` once virtual time reaches ``now + delay``."""
        return self.schedule_at(self._now + max(0.0, float(delay)), fn)

    def schedule_at(self, at: float, fn: Callable[[float], None]) -> _Event:
        ev = _Event(float(at), self._seq, fn)
        self._seq += 1
        heapq.heappush(self._heap, ev)
        return ev

    @staticmethod
    def cancel(ev: _Event) -> None:
        ev.cancelled = True

    def pending(self) -> int:
        """Live (uncancelled) events still queued."""
        return sum(1 for e in self._heap if not e.cancelled)

    def next_due(self) -> Optional[float]:
        while self._heap and self._heap[0].cancelled:
            heapq.heappop(self._heap)
        return self._heap[0].due if self._heap else None

    # -- driving -------------------------------------------------------

    def jump(self, dt: float) -> None:
        """Move time forward WITHOUT running the events in between —
        the virtual form of a blocked event loop (the stalled-loop
        fault family): everything due inside the jump fires late, and
        a lateness-measuring beat observes exactly ``dt``."""
        self._now += max(0.0, float(dt))

    def advance(self) -> bool:
        """Pop and run the earliest event; False when the heap is
        empty.  Time never moves backwards: an event already overdue
        (scheduled before a :meth:`jump`) runs at the current instant."""
        while self._heap:
            ev = heapq.heappop(self._heap)
            if ev.cancelled:
                continue
            self._now = max(self._now, ev.due)
            ev.fn(ev.due)
            return True
        return False

    def run_until(self, t_stop: float) -> int:
        """Run every event due at or before ``t_stop``; returns how
        many ran.  Ends with ``monotonic() == t_stop`` (idle virtual
        time elapses for free — that is the whole point)."""
        ran = 0
        while True:
            nxt = self.next_due()
            if nxt is None or nxt > t_stop:
                break
            self.advance()
            ran += 1
        self._now = max(self._now, float(t_stop))
        return ran

    # -- asyncio integration ------------------------------------------

    async def sleep(self, delay: float, result: Any = None):
        """Suspend the calling coroutine until virtual time reaches
        ``now + delay``.  Requires a driver pumping :meth:`advance`
        (e.g. the virtual cluster's scheduler) — nothing resolves the
        future otherwise."""
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()

        def _fire(_due: float) -> None:
            if fut.done():
                return
            try:
                fut.set_result(result)
            except RuntimeError:
                # the awaiting loop already closed (e.g. a private
                # serve loop torn down with the timer still queued) —
                # nothing is waiting, nothing to wake
                pass

        self.schedule(delay, _fire)
        return await fut

    async def wait_for(self, aw, timeout: Optional[float]):
        """Virtual-deadline ``wait_for``: the timeout elapses on THIS
        clock, not the loop's."""
        if timeout is None:
            return await aw
        task = asyncio.ensure_future(aw)
        sentinel = object()
        timer = asyncio.ensure_future(self.sleep(timeout, result=sentinel))
        try:
            done, _pending = await asyncio.wait(
                {task, timer}, return_when=asyncio.FIRST_COMPLETED
            )
            if task in done:
                return task.result()
            # stdlib-faithful timeout: cancel the awaitable and WAIT
            # for its cancellation to complete — and if it finished
            # anyway (e.g. a queue.get whose item landed in the same
            # cycle), hand the result back instead of dropping it
            task.cancel()
            try:
                return await task
            except asyncio.CancelledError:
                raise asyncio.TimeoutError() from None
        finally:
            if not timer.done():
                timer.cancel()
