"""Inclusive integer range sets — the backbone of version/gap bookkeeping.

Parity: the reference leans on ``rangemap::RangeInclusiveSet`` everywhere
(needed-version gaps in ``BookedVersions``, seq gaps in partial versions,
cleared-version tracking; e.g. ``crates/corro-types/src/agent.rs:1393-1578``,
``sync.rs:127-248``).  This is our own implementation: a sorted list of
disjoint inclusive ``[start, end]`` spans that coalesces touching spans
(integers are discrete, so ``[1,5]`` + ``[6,9]`` → ``[1,9]``), with the
operations the sync/bookkeeping algebra needs: insert, remove, overlap
query, gap enumeration.

Host-side this is exact; the simulator mirrors it with dense bitmaps in
:mod:`corrosion_tpu.ops.intervals`.
"""

from __future__ import annotations

from bisect import bisect_left, bisect_right
from typing import Iterable, Iterator, List, Tuple

Span = Tuple[int, int]  # inclusive


class RangeSet:
    """Set of integers stored as sorted disjoint inclusive spans."""

    __slots__ = ("_starts", "_ends")

    def __init__(self, spans: Iterable[Span] = ()):
        self._starts: List[int] = []
        self._ends: List[int] = []
        for s, e in spans:
            self.insert(s, e)

    # -- construction -----------------------------------------------------

    def copy(self) -> "RangeSet":
        new = RangeSet()
        new._starts = list(self._starts)
        new._ends = list(self._ends)
        return new

    def insert(self, start: int, end: int) -> None:
        """Insert inclusive [start, end], coalescing with touching spans."""
        if end < start:
            raise ValueError(f"invalid span [{start}, {end}]")
        # find spans overlapping or adjacent to [start-1, end+1]
        lo = bisect_left(self._ends, start - 1)
        hi = bisect_right(self._starts, end + 1)
        if lo < hi:
            start = min(start, self._starts[lo])
            end = max(end, self._ends[hi - 1])
        self._starts[lo:hi] = [start]
        self._ends[lo:hi] = [end]

    def remove(self, start: int, end: int) -> None:
        """Remove all integers in inclusive [start, end]."""
        if end < start:
            raise ValueError(f"invalid span [{start}, {end}]")
        lo = bisect_left(self._ends, start)
        hi = bisect_right(self._starts, end)
        if lo >= hi:
            return
        new_starts: List[int] = []
        new_ends: List[int] = []
        if self._starts[lo] < start:
            new_starts.append(self._starts[lo])
            new_ends.append(start - 1)
        if self._ends[hi - 1] > end:
            new_starts.append(end + 1)
            new_ends.append(self._ends[hi - 1])
        self._starts[lo:hi] = new_starts
        self._ends[lo:hi] = new_ends

    def insert_all(self, other: "RangeSet") -> None:
        for s, e in other:
            self.insert(s, e)

    def remove_all(self, other: "RangeSet") -> None:
        for s, e in other:
            self.remove(s, e)

    # -- queries ----------------------------------------------------------

    def __iter__(self) -> Iterator[Span]:
        return iter(zip(self._starts, self._ends))

    def __len__(self) -> int:
        return len(self._starts)

    def __bool__(self) -> bool:
        return bool(self._starts)

    def __eq__(self, other) -> bool:
        if not isinstance(other, RangeSet):
            return NotImplemented
        return self._starts == other._starts and self._ends == other._ends

    def __repr__(self) -> str:  # pragma: no cover - cosmetic
        return f"RangeSet({[(s, e) for s, e in self]})"

    def spans(self) -> List[Span]:
        return list(self)

    def contains(self, value: int) -> bool:
        i = bisect_right(self._starts, value) - 1
        return i >= 0 and value <= self._ends[i]

    def contains_span(self, start: int, end: int) -> bool:
        """True iff the whole inclusive [start, end] is in one stored span."""
        i = bisect_right(self._starts, start) - 1
        return i >= 0 and end <= self._ends[i]

    def overlapping(self, start: int, end: int) -> Iterator[Span]:
        """Stored spans intersecting inclusive [start, end]."""
        lo = bisect_left(self._ends, start)
        hi = bisect_right(self._starts, end)
        for i in range(lo, hi):
            yield self._starts[i], self._ends[i]

    def intersection_spans(self, start: int, end: int) -> List[Span]:
        """Overlaps clipped to [start, end]."""
        return [
            (max(s, start), min(e, end)) for s, e in self.overlapping(start, end)
        ]

    def gaps(self, start: int, end: int) -> List[Span]:
        """Maximal spans of [start, end] NOT covered by this set."""
        out: List[Span] = []
        cursor = start
        for s, e in self.overlapping(start, end):
            if s > cursor:
                out.append((cursor, s - 1))
            cursor = max(cursor, e + 1)
            if cursor > end:
                break
        if cursor <= end:
            out.append((cursor, end))
        return out

    def count(self) -> int:
        """Total number of integers covered."""
        return sum(e - s + 1 for s, e in self)

    def min(self):
        return self._starts[0] if self._starts else None

    def max(self):
        return self._ends[-1] if self._ends else None
