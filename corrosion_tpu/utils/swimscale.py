"""Cluster-size-scaled SWIM parameters.

The reference rebuilds its foca config whenever cluster size changes —
``make_foca_config(cluster_size)`` calls ``foca::Config::new_wan(size)``
(``crates/corro-agent/src/broadcast/mod.rs:937-946``, driven by the
``FocaInput::ClusterSize`` branch at ``:232-250``) — so suspicion
timeouts and update retransmission limits grow logarithmically with
membership instead of staying fixed.  These helpers implement that
memberlist-lineage scaling (suspicion-mult × ceil(log10(n+1)) ×
probe-period) for both the host agent and the simulator models.
"""

from __future__ import annotations

import math


def swim_scale_factor(cluster_size: int) -> int:
    """ceil(log10(size+1)), minimum 1 — the dissemination/suspicion
    multiplier's growth term."""
    return max(1, math.ceil(math.log10(max(cluster_size, 1) + 1)))


def scaled_suspect_timeout(
    base: float, probe_interval: float, cluster_size: int,
    suspicion_mult: int = 4,
) -> float:
    """Suspect→down deadline: at least ``base`` (small-cluster/testing
    floor), growing as mult × factor × probe-period once the log term
    dominates."""
    return max(
        base,
        suspicion_mult * swim_scale_factor(cluster_size) * probe_interval,
    )


def scaled_update_retransmissions(
    cluster_size: int, retransmit_mult: int = 4
) -> int:
    """How many times one membership update is piggybacked before it
    decays out of the gossip backlog."""
    return retransmit_mult * swim_scale_factor(cluster_size)
