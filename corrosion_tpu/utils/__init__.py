from corrosion_tpu.utils.ranges import RangeSet

__all__ = ["RangeSet"]
