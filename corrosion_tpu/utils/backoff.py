"""Decorrelated-jitter backoff.

Parity: ``crates/backoff`` — the iterator the reference's SWIM announcer
and sync scheduler use: each delay is drawn uniformly from
``[base, prev * 3]``, clamped to ``[base, cap]`` (decorrelated jitter),
optionally with a retry limit.
"""

from __future__ import annotations

import asyncio
import random
from typing import Callable, Iterator, Optional, Tuple, Type


class Backoff:
    def __init__(self, base: float = 0.1, cap: float = 15.0,
                 max_retries: Optional[int] = None,
                 rng: Optional[random.Random] = None):
        self.base = base
        self.cap = cap
        self.max_retries = max_retries
        self.rng = rng or random.Random()

    def __iter__(self) -> Iterator[float]:
        prev = self.base
        n = 0
        while self.max_retries is None or n < self.max_retries:
            delay = min(self.cap, self.rng.uniform(self.base, prev * 3))
            prev = delay
            n += 1
            yield delay

    def reset(self) -> "Backoff":
        return Backoff(self.base, self.cap, self.max_retries, self.rng)


async def retry(
    fn: Callable,
    backoff: Backoff,
    exceptions: Tuple[Type[BaseException], ...] = (
        OSError, ConnectionError, asyncio.TimeoutError,
    ),
    sleep: Callable = asyncio.sleep,
):
    """Call ``await fn()`` until it succeeds, sleeping the backoff's
    next delay after each retryable failure; re-raises the last failure
    once the delays are exhausted (``max_retries`` bounds the RETRIES:
    the first attempt is free, so ``max_retries=2`` means ≤3 attempts).

    Deterministic path: give ``backoff`` a seeded ``random.Random`` —
    delays are drawn only from that rng, in attempt order, so a replay
    with the same seed and the same failure sequence sleeps the same
    schedule.  ``sleep`` is injectable so tests (and the det scheduler)
    can collect the delays instead of waiting them out.
    """
    delays = iter(backoff)
    while True:
        try:
            return await fn()
        except exceptions:
            # NOTE: StopIteration must not escape a coroutine (PEP 479
            # turns it into RuntimeError) — exhausted delays re-raise
            # the ORIGINAL failure instead
            delay = next(delays, None)
            if delay is None:
                raise
            await sleep(delay)
