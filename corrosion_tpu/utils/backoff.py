"""Decorrelated-jitter backoff.

Parity: ``crates/backoff`` — the iterator the reference's SWIM announcer
and sync scheduler use: each delay is drawn uniformly from
``[base, prev * 3]``, clamped to ``[base, cap]`` (decorrelated jitter),
optionally with a retry limit.
"""

from __future__ import annotations

import random
from typing import Iterator, Optional


class Backoff:
    def __init__(self, base: float = 0.1, cap: float = 15.0,
                 max_retries: Optional[int] = None,
                 rng: Optional[random.Random] = None):
        self.base = base
        self.cap = cap
        self.max_retries = max_retries
        self.rng = rng or random.Random()

    def __iter__(self) -> Iterator[float]:
        prev = self.base
        n = 0
        while self.max_retries is None or n < self.max_retries:
            delay = min(self.cap, self.rng.uniform(self.base, prev * 3))
            prev = delay
            n += 1
            yield delay

    def reset(self) -> "Backoff":
        return Backoff(self.base, self.cap, self.max_retries, self.rng)
