"""Native runtime kernels with transparent build + Python fallback.

``load()`` returns the compiled ``_corrosion_native`` module, building
it with the system C++ toolchain on first use (cached beside the
source, keyed by source mtime).  Callers fall back to their pure-Python
twins when no toolchain is available, so the package never hard-depends
on a compiler.

Set ``CORROSION_TPU_NO_NATIVE=1`` to force the Python paths (used by
tests to cross-check both implementations).
"""

from __future__ import annotations

import importlib.util
import os
import subprocess
import sys
import sysconfig
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "_corrosion_native.cc")

_lock = threading.Lock()
_cached = None
_failed = False


def _so_path() -> str:
    tag = sysconfig.get_config_var("EXT_SUFFIX") or ".so"
    return os.path.join(_DIR, f"_corrosion_native{tag}")


def _fail_marker() -> str:
    return _so_path() + ".buildfail"


def _build(so: str) -> bool:
    cxx = os.environ.get("CXX", "g++")
    include = sysconfig.get_path("include")
    # per-process tmp: concurrent first-use builds (several agents, test
    # workers) must not interleave writes into one tmp file — os.replace
    # then installs whichever complete build finishes last
    tmp = f"{so}.{os.getpid()}.tmp"
    cmd = [
        cxx, "-O2", "-fPIC", "-shared", "-std=c++17",
        f"-I{include}", _SRC, "-o", tmp,
    ]
    try:
        proc = subprocess.run(
            cmd, capture_output=True, text=True, timeout=120,
        )
    except (OSError, subprocess.TimeoutExpired):
        _record_failure("toolchain missing or timed out")
        return False
    if proc.returncode != 0:
        sys.stderr.write(
            f"corrosion_tpu.native: build failed, using Python fallback:\n"
            f"{proc.stderr[-2000:]}\n"
        )
        _record_failure(proc.stderr[-500:])
        return False
    os.replace(tmp, so)
    try:
        os.unlink(_fail_marker())
    except OSError:
        pass
    return True


def _record_failure(reason: str) -> None:
    """Persist the failure keyed by source mtime so OTHER processes skip
    the doomed compile instead of each paying for it at import."""
    try:
        with open(_fail_marker(), "w") as f:
            f.write(f"{os.path.getmtime(_SRC)}\n{reason}\n")
    except OSError:
        pass


def _known_bad() -> bool:
    try:
        with open(_fail_marker()) as f:
            recorded = float(f.readline().strip())
        return recorded == os.path.getmtime(_SRC)
    except (OSError, ValueError):
        return False


def load():
    """The native module, or None (build failure / opted out)."""
    global _cached, _failed
    if _cached is not None:
        return _cached
    if _failed or os.environ.get("CORROSION_TPU_NO_NATIVE"):
        return None
    with _lock:
        if _cached is not None or _failed:
            return _cached
        so = _so_path()
        try:
            stale = (not os.path.exists(so)
                     or os.path.getmtime(so) < os.path.getmtime(_SRC))
            if stale and _known_bad():
                _failed = True
                return None
            if stale and not _build(so):
                _failed = True
                return None
            spec = importlib.util.spec_from_file_location(
                "corrosion_tpu.native._corrosion_native", so
            )
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
            _cached = mod
        except Exception as e:  # noqa: BLE001 - any failure -> fallback
            sys.stderr.write(
                f"corrosion_tpu.native: load failed ({e!r}), "
                "using Python fallback\n"
            )
            _failed = True
            return None
    return _cached


def load_or_none():
    """:func:`load`, guaranteed never to raise — THE call-site API: the
    dispatch shims in agent/pack.py and bridge/speedy.py must not let a
    packaging problem break import of the pure-Python paths."""
    try:
        return load()
    except Exception:  # noqa: BLE001 - any failure -> fallback
        return None
