/* Native hot-path kernels for the corrosion-tpu host runtime.
 *
 * The reference implements its entire runtime in Rust; the TPU compute
 * path here is JAX/XLA, and this extension is the native runtime layer
 * around it for the host agent's hottest per-row / per-message work:
 *
 *   - pack_values / unpack_values: the packed-pk codec invoked by the
 *     CRR triggers (corro_pack UDF) on EVERY row write and by change
 *     collection / subscription bookkeeping
 *     (reference: crates/corro-types/src/pubsub.rs:2302-2449);
 *   - value_cmp: cr-sqlite's merge tie-break total order (type-enum
 *     rank first, then within-type comparison);
 *   - deframe: the u32-BE LengthDelimited splitter on the gossip/sync
 *     wire (tokio_util's codec in the reference).
 *
 * Semantics are pinned to the pure-Python twins in agent/pack.py and
 * bridge/speedy.py; tests/test_native.py cross-checks them on random
 * inputs.  Python remains the fallback when no compiler is available.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstdint>
#include <cstring>
#include <string>

namespace {

constexpr uint8_t T_NULL = 0;
constexpr uint8_t T_INT = 1;
constexpr uint8_t T_REAL = 2;
constexpr uint8_t T_TEXT = 3;
constexpr uint8_t T_BLOB = 4;

void put_u32(std::string &out, uint32_t v) {
  char b[4] = {static_cast<char>(v >> 24), static_cast<char>(v >> 16),
               static_cast<char>(v >> 8), static_cast<char>(v)};
  out.append(b, 4);
}

void put_u64(std::string &out, uint64_t v) {
  char b[8];
  for (int i = 0; i < 8; i++) b[i] = static_cast<char>(v >> (56 - 8 * i));
  out.append(b, 8);
}

uint32_t get_u32(const uint8_t *p) {
  return (uint32_t(p[0]) << 24) | (uint32_t(p[1]) << 16) |
         (uint32_t(p[2]) << 8) | uint32_t(p[3]);
}

uint64_t get_u64(const uint8_t *p) {
  uint64_t v = 0;
  for (int i = 0; i < 8; i++) v = (v << 8) | p[i];
  return v;
}

/* -- pack_values ----------------------------------------------------- */

PyObject *pack_values(PyObject *, PyObject *arg) {
  PyObject *seq = PySequence_Fast(arg, "pack_values expects a sequence");
  if (!seq) return nullptr;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
  std::string out;
  out.reserve(16 * static_cast<size_t>(n) + 8);
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *v = PySequence_Fast_GET_ITEM(seq, i);
    if (v == Py_None) {
      out.push_back(static_cast<char>(T_NULL));
    } else if (PyBool_Check(v)) {
      out.push_back(static_cast<char>(T_INT));
      put_u64(out, v == Py_True ? 1 : 0);
    } else if (PyLong_Check(v)) {
      int overflow = 0;
      long long ll = PyLong_AsLongLongAndOverflow(v, &overflow);
      if (overflow != 0 || (ll == -1 && PyErr_Occurred())) {
        if (!PyErr_Occurred())
          PyErr_SetString(PyExc_OverflowError,
                          "int too large for packed i64");
        Py_DECREF(seq);
        return nullptr;
      }
      out.push_back(static_cast<char>(T_INT));
      put_u64(out, static_cast<uint64_t>(ll));
    } else if (PyFloat_Check(v)) {
      double d = PyFloat_AS_DOUBLE(v);
      uint64_t bits;
      std::memcpy(&bits, &d, 8);
      out.push_back(static_cast<char>(T_REAL));
      put_u64(out, bits);
    } else if (PyUnicode_Check(v)) {
      Py_ssize_t len = 0;
      const char *s = PyUnicode_AsUTF8AndSize(v, &len);
      if (!s) {
        Py_DECREF(seq);
        return nullptr;
      }
      out.push_back(static_cast<char>(T_TEXT));
      put_u32(out, static_cast<uint32_t>(len));
      out.append(s, static_cast<size_t>(len));
    } else if (PyBytes_Check(v) || PyByteArray_Check(v) ||
               PyMemoryView_Check(v)) {
      /* exactly the types the Python twin accepts — a generic buffer
       * check would silently pack array/numpy/mmap objects that the
       * fallback rejects with TypeError */
      Py_buffer buf;
      if (PyObject_GetBuffer(v, &buf, PyBUF_SIMPLE) != 0) {
        Py_DECREF(seq);
        return nullptr;
      }
      out.push_back(static_cast<char>(T_BLOB));
      put_u32(out, static_cast<uint32_t>(buf.len));
      out.append(static_cast<const char *>(buf.buf),
                 static_cast<size_t>(buf.len));
      PyBuffer_Release(&buf);
    } else {
      PyErr_Format(PyExc_TypeError, "unsupported SQL value: %R",
                   reinterpret_cast<PyObject *>(Py_TYPE(v)));
      Py_DECREF(seq);
      return nullptr;
    }
  }
  Py_DECREF(seq);
  return PyBytes_FromStringAndSize(out.data(),
                                   static_cast<Py_ssize_t>(out.size()));
}

/* -- unpack_values --------------------------------------------------- */

PyObject *unpack_values(PyObject *, PyObject *arg) {
  Py_buffer buf;
  if (PyObject_GetBuffer(arg, &buf, PyBUF_SIMPLE) != 0) return nullptr;
  const uint8_t *p = static_cast<const uint8_t *>(buf.buf);
  Py_ssize_t n = buf.len;
  PyObject *out = PyList_New(0);
  if (!out) {
    PyBuffer_Release(&buf);
    return nullptr;
  }
  Py_ssize_t i = 0;
  while (i < n) {
    uint8_t tag = p[i];
    i += 1;
    PyObject *item = nullptr;
    if (tag == T_NULL) {
      item = Py_NewRef(Py_None);
    } else if (tag == T_INT || tag == T_REAL) {
      if (i + 8 > n) {
        PyErr_SetString(PyExc_ValueError, "truncated packed value");
        goto fail;
      }
      uint64_t bits = get_u64(p + i);
      i += 8;
      if (tag == T_INT) {
        item = PyLong_FromLongLong(static_cast<long long>(bits));
      } else {
        double d;
        std::memcpy(&d, &bits, 8);
        item = PyFloat_FromDouble(d);
      }
    } else if (tag == T_TEXT || tag == T_BLOB) {
      if (i + 4 > n) {
        PyErr_SetString(PyExc_ValueError, "truncated packed value");
        goto fail;
      }
      uint32_t len = get_u32(p + i);
      i += 4;
      if (i + static_cast<Py_ssize_t>(len) > n) {
        PyErr_SetString(PyExc_ValueError, "truncated packed value");
        goto fail;
      }
      const char *s = reinterpret_cast<const char *>(p + i);
      item = (tag == T_TEXT)
                 ? PyUnicode_DecodeUTF8(s, len, nullptr)
                 : PyBytes_FromStringAndSize(s, len);
      i += len;
    } else {
      PyErr_Format(PyExc_ValueError, "bad tag %d at offset %zd", tag, i - 1);
      goto fail;
    }
    if (!item || PyList_Append(out, item) != 0) {
      Py_XDECREF(item);
      goto fail;
    }
    Py_DECREF(item);
  }
  PyBuffer_Release(&buf);
  return out;
fail:
  PyBuffer_Release(&buf);
  Py_DECREF(out);
  return nullptr;
}

/* -- value_cmp ------------------------------------------------------- */

int type_rank(PyObject *v) {
  if (v == Py_None) return 0;
  if (PyBool_Check(v) || PyLong_Check(v)) return 4;
  if (PyFloat_Check(v)) return 3;
  if (PyUnicode_Check(v)) return 2;
  if (PyBytes_Check(v) || PyByteArray_Check(v) || PyMemoryView_Check(v))
    return 1;
  return -1;
}

PyObject *value_cmp(PyObject *, PyObject *args) {
  PyObject *a, *b;
  if (!PyArg_ParseTuple(args, "OO", &a, &b)) return nullptr;
  int ra = type_rank(a), rb = type_rank(b);
  if (ra < 0 || rb < 0) {
    PyErr_Format(PyExc_TypeError, "unsupported SQL value: %R",
                 reinterpret_cast<PyObject *>(Py_TYPE(ra < 0 ? a : b)));
    return nullptr;
  }
  if (ra != rb) return PyLong_FromLong(ra < rb ? -1 : 1);
  if (ra == 0) return PyLong_FromLong(0);
  if (ra == 2) {
    /* compare UTF-8 bytes, like the Python twin */
    Py_ssize_t la = 0, lb = 0;
    const char *sa = PyUnicode_AsUTF8AndSize(a, &la);
    const char *sb = PyUnicode_AsUTF8AndSize(b, &lb);
    if (!sa || !sb) return nullptr;
    int c = std::memcmp(sa, sb, static_cast<size_t>(la < lb ? la : lb));
    if (c == 0) c = (la > lb) - (la < lb);
    return PyLong_FromLong(c > 0 ? 1 : (c < 0 ? -1 : 0));
  }
  if (ra == 1) {
    Py_buffer ba, bb;
    if (PyObject_GetBuffer(a, &ba, PyBUF_SIMPLE) != 0) return nullptr;
    if (PyObject_GetBuffer(b, &bb, PyBUF_SIMPLE) != 0) {
      PyBuffer_Release(&ba);
      return nullptr;
    }
    int c = std::memcmp(ba.buf, bb.buf,
                        static_cast<size_t>(ba.len < bb.len ? ba.len : bb.len));
    if (c == 0) c = (ba.len > bb.len) - (ba.len < bb.len);
    PyBuffer_Release(&ba);
    PyBuffer_Release(&bb);
    return PyLong_FromLong(c > 0 ? 1 : (c < 0 ? -1 : 0));
  }
  /* numerics: defer to Python comparison (bigints, NaN semantics) */
  int lt = PyObject_RichCompareBool(a, b, Py_LT);
  if (lt < 0) return nullptr;
  int gt = PyObject_RichCompareBool(a, b, Py_GT);
  if (gt < 0) return nullptr;
  return PyLong_FromLong(gt - lt);
}

/* -- deframe --------------------------------------------------------- */

PyObject *deframe(PyObject *, PyObject *args) {
  Py_buffer buf;
  unsigned int max_len = 8 * 1024 * 1024;
  if (!PyArg_ParseTuple(args, "y*|I", &buf, &max_len)) return nullptr;
  const uint8_t *p = static_cast<const uint8_t *>(buf.buf);
  Py_ssize_t n = buf.len;
  PyObject *frames = PyList_New(0);
  if (!frames) {
    PyBuffer_Release(&buf);
    return nullptr;
  }
  Py_ssize_t pos = 0;
  while (pos + 4 <= n) {
    uint32_t len = get_u32(p + pos);
    if (len > max_len) {
      PyErr_Format(PyExc_ValueError, "frame length %u exceeds max %u", len,
                   max_len);
      Py_DECREF(frames);
      PyBuffer_Release(&buf);
      return nullptr;
    }
    if (pos + 4 + static_cast<Py_ssize_t>(len) > n) break;
    PyObject *payload = PyBytes_FromStringAndSize(
        reinterpret_cast<const char *>(p + pos + 4), len);
    if (!payload || PyList_Append(frames, payload) != 0) {
      Py_XDECREF(payload);
      Py_DECREF(frames);
      PyBuffer_Release(&buf);
      return nullptr;
    }
    Py_DECREF(payload);
    pos += 4 + len;
  }
  PyObject *rest = PyBytes_FromStringAndSize(
      reinterpret_cast<const char *>(p + pos), n - pos);
  PyBuffer_Release(&buf);
  if (!rest) {
    Py_DECREF(frames);
    return nullptr;
  }
  PyObject *out = PyTuple_Pack(2, frames, rest);
  Py_DECREF(frames);
  Py_DECREF(rest);
  return out;
}

PyMethodDef methods[] = {
    {"pack_values", pack_values, METH_O,
     "Pack a sequence of SQL values into one self-describing blob."},
    {"unpack_values", unpack_values, METH_O,
     "Inverse of pack_values."},
    {"value_cmp", value_cmp, METH_VARARGS,
     "cr-sqlite merge tie-break comparison (-1/0/1)."},
    {"deframe", deframe, METH_VARARGS,
     "Split complete u32-BE length-delimited frames off the front."},
    {nullptr, nullptr, 0, nullptr},
};

PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_corrosion_native",
    "Native hot-path kernels (packed-pk codec, merge compare, framing).",
    -1, methods,
};

}  // namespace

PyMODINIT_FUNC PyInit__corrosion_native(void) {
  return PyModule_Create(&moduledef);
}
