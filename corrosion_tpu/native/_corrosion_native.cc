/* Native hot-path kernels for the corrosion-tpu host runtime.
 *
 * The reference implements its entire runtime in Rust; the TPU compute
 * path here is JAX/XLA, and this extension is the native runtime layer
 * around it for the host agent's hottest per-row / per-message work:
 *
 *   - pack_values / unpack_values: the packed-pk codec invoked by the
 *     CRR triggers (corro_pack UDF) on EVERY row write and by change
 *     collection / subscription bookkeeping
 *     (reference: crates/corro-types/src/pubsub.rs:2302-2449);
 *   - value_cmp: cr-sqlite's merge tie-break total order (type-enum
 *     rank first, then within-type comparison);
 *   - deframe: the u32-BE LengthDelimited splitter on the gossip/sync
 *     wire (tokio_util's codec in the reference).
 *
 * Semantics are pinned to the pure-Python twins in agent/pack.py and
 * bridge/speedy.py; tests/test_native.py cross-checks them on random
 * inputs.  Python remains the fallback when no compiler is available.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstdint>
#include <cstring>
#include <string>

namespace {

constexpr uint8_t T_NULL = 0;
constexpr uint8_t T_INT = 1;
constexpr uint8_t T_REAL = 2;
constexpr uint8_t T_TEXT = 3;
constexpr uint8_t T_BLOB = 4;

void put_u32(std::string &out, uint32_t v) {
  char b[4] = {static_cast<char>(v >> 24), static_cast<char>(v >> 16),
               static_cast<char>(v >> 8), static_cast<char>(v)};
  out.append(b, 4);
}

void put_u64(std::string &out, uint64_t v) {
  char b[8];
  for (int i = 0; i < 8; i++) b[i] = static_cast<char>(v >> (56 - 8 * i));
  out.append(b, 8);
}

uint32_t get_u32(const uint8_t *p) {
  return (uint32_t(p[0]) << 24) | (uint32_t(p[1]) << 16) |
         (uint32_t(p[2]) << 8) | uint32_t(p[3]);
}

uint64_t get_u64(const uint8_t *p) {
  uint64_t v = 0;
  for (int i = 0; i < 8; i++) v = (v << 8) | p[i];
  return v;
}

/* -- pack_values ----------------------------------------------------- */

PyObject *pack_values(PyObject *, PyObject *arg) {
  PyObject *seq = PySequence_Fast(arg, "pack_values expects a sequence");
  if (!seq) return nullptr;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
  std::string out;
  out.reserve(16 * static_cast<size_t>(n) + 8);
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *v = PySequence_Fast_GET_ITEM(seq, i);
    if (v == Py_None) {
      out.push_back(static_cast<char>(T_NULL));
    } else if (PyBool_Check(v)) {
      out.push_back(static_cast<char>(T_INT));
      put_u64(out, v == Py_True ? 1 : 0);
    } else if (PyLong_Check(v)) {
      int overflow = 0;
      long long ll = PyLong_AsLongLongAndOverflow(v, &overflow);
      if (overflow != 0 || (ll == -1 && PyErr_Occurred())) {
        if (!PyErr_Occurred())
          PyErr_SetString(PyExc_OverflowError,
                          "int too large for packed i64");
        Py_DECREF(seq);
        return nullptr;
      }
      out.push_back(static_cast<char>(T_INT));
      put_u64(out, static_cast<uint64_t>(ll));
    } else if (PyFloat_Check(v)) {
      double d = PyFloat_AS_DOUBLE(v);
      uint64_t bits;
      std::memcpy(&bits, &d, 8);
      out.push_back(static_cast<char>(T_REAL));
      put_u64(out, bits);
    } else if (PyUnicode_Check(v)) {
      Py_ssize_t len = 0;
      const char *s = PyUnicode_AsUTF8AndSize(v, &len);
      if (!s) {
        Py_DECREF(seq);
        return nullptr;
      }
      out.push_back(static_cast<char>(T_TEXT));
      put_u32(out, static_cast<uint32_t>(len));
      out.append(s, static_cast<size_t>(len));
    } else if (PyBytes_Check(v) || PyByteArray_Check(v) ||
               PyMemoryView_Check(v)) {
      /* exactly the types the Python twin accepts — a generic buffer
       * check would silently pack array/numpy/mmap objects that the
       * fallback rejects with TypeError */
      Py_buffer buf;
      if (PyObject_GetBuffer(v, &buf, PyBUF_SIMPLE) != 0) {
        Py_DECREF(seq);
        return nullptr;
      }
      out.push_back(static_cast<char>(T_BLOB));
      put_u32(out, static_cast<uint32_t>(buf.len));
      out.append(static_cast<const char *>(buf.buf),
                 static_cast<size_t>(buf.len));
      PyBuffer_Release(&buf);
    } else {
      PyErr_Format(PyExc_TypeError, "unsupported SQL value: %R",
                   reinterpret_cast<PyObject *>(Py_TYPE(v)));
      Py_DECREF(seq);
      return nullptr;
    }
  }
  Py_DECREF(seq);
  return PyBytes_FromStringAndSize(out.data(),
                                   static_cast<Py_ssize_t>(out.size()));
}

/* -- unpack_values --------------------------------------------------- */

PyObject *unpack_values(PyObject *, PyObject *arg) {
  Py_buffer buf;
  if (PyObject_GetBuffer(arg, &buf, PyBUF_SIMPLE) != 0) return nullptr;
  const uint8_t *p = static_cast<const uint8_t *>(buf.buf);
  Py_ssize_t n = buf.len;
  PyObject *out = PyList_New(0);
  if (!out) {
    PyBuffer_Release(&buf);
    return nullptr;
  }
  Py_ssize_t i = 0;
  while (i < n) {
    uint8_t tag = p[i];
    i += 1;
    PyObject *item = nullptr;
    if (tag == T_NULL) {
      item = Py_NewRef(Py_None);
    } else if (tag == T_INT || tag == T_REAL) {
      if (i + 8 > n) {
        PyErr_SetString(PyExc_ValueError, "truncated packed value");
        goto fail;
      }
      uint64_t bits = get_u64(p + i);
      i += 8;
      if (tag == T_INT) {
        item = PyLong_FromLongLong(static_cast<long long>(bits));
      } else {
        double d;
        std::memcpy(&d, &bits, 8);
        item = PyFloat_FromDouble(d);
      }
    } else if (tag == T_TEXT || tag == T_BLOB) {
      if (i + 4 > n) {
        PyErr_SetString(PyExc_ValueError, "truncated packed value");
        goto fail;
      }
      uint32_t len = get_u32(p + i);
      i += 4;
      if (i + static_cast<Py_ssize_t>(len) > n) {
        PyErr_SetString(PyExc_ValueError, "truncated packed value");
        goto fail;
      }
      const char *s = reinterpret_cast<const char *>(p + i);
      item = (tag == T_TEXT)
                 ? PyUnicode_DecodeUTF8(s, len, nullptr)
                 : PyBytes_FromStringAndSize(s, len);
      i += len;
    } else {
      PyErr_Format(PyExc_ValueError, "bad tag %d at offset %zd", tag, i - 1);
      goto fail;
    }
    if (!item || PyList_Append(out, item) != 0) {
      Py_XDECREF(item);
      goto fail;
    }
    Py_DECREF(item);
  }
  PyBuffer_Release(&buf);
  return out;
fail:
  PyBuffer_Release(&buf);
  Py_DECREF(out);
  return nullptr;
}

/* -- value_cmp ------------------------------------------------------- */

int type_rank(PyObject *v) {
  if (v == Py_None) return 0;
  if (PyBool_Check(v) || PyLong_Check(v)) return 4;
  if (PyFloat_Check(v)) return 3;
  if (PyUnicode_Check(v)) return 2;
  if (PyBytes_Check(v) || PyByteArray_Check(v) || PyMemoryView_Check(v))
    return 1;
  return -1;
}

PyObject *value_cmp(PyObject *, PyObject *args) {
  PyObject *a, *b;
  if (!PyArg_ParseTuple(args, "OO", &a, &b)) return nullptr;
  int ra = type_rank(a), rb = type_rank(b);
  if (ra < 0 || rb < 0) {
    PyErr_Format(PyExc_TypeError, "unsupported SQL value: %R",
                 reinterpret_cast<PyObject *>(Py_TYPE(ra < 0 ? a : b)));
    return nullptr;
  }
  if (ra != rb) return PyLong_FromLong(ra < rb ? -1 : 1);
  if (ra == 0) return PyLong_FromLong(0);
  if (ra == 2) {
    /* compare UTF-8 bytes, like the Python twin */
    Py_ssize_t la = 0, lb = 0;
    const char *sa = PyUnicode_AsUTF8AndSize(a, &la);
    const char *sb = PyUnicode_AsUTF8AndSize(b, &lb);
    if (!sa || !sb) return nullptr;
    int c = std::memcmp(sa, sb, static_cast<size_t>(la < lb ? la : lb));
    if (c == 0) c = (la > lb) - (la < lb);
    return PyLong_FromLong(c > 0 ? 1 : (c < 0 ? -1 : 0));
  }
  if (ra == 1) {
    Py_buffer ba, bb;
    if (PyObject_GetBuffer(a, &ba, PyBUF_SIMPLE) != 0) return nullptr;
    if (PyObject_GetBuffer(b, &bb, PyBUF_SIMPLE) != 0) {
      PyBuffer_Release(&ba);
      return nullptr;
    }
    int c = std::memcmp(ba.buf, bb.buf,
                        static_cast<size_t>(ba.len < bb.len ? ba.len : bb.len));
    if (c == 0) c = (ba.len > bb.len) - (ba.len < bb.len);
    PyBuffer_Release(&ba);
    PyBuffer_Release(&bb);
    return PyLong_FromLong(c > 0 ? 1 : (c < 0 ? -1 : 0));
  }
  /* numerics: defer to Python comparison (bigints, NaN semantics) */
  int lt = PyObject_RichCompareBool(a, b, Py_LT);
  if (lt < 0) return nullptr;
  int gt = PyObject_RichCompareBool(a, b, Py_GT);
  if (gt < 0) return nullptr;
  return PyLong_FromLong(gt - lt);
}

/* -- deframe --------------------------------------------------------- */

PyObject *deframe(PyObject *, PyObject *args) {
  Py_buffer buf;
  unsigned int max_len = 8 * 1024 * 1024;
  if (!PyArg_ParseTuple(args, "y*|I", &buf, &max_len)) return nullptr;
  const uint8_t *p = static_cast<const uint8_t *>(buf.buf);
  Py_ssize_t n = buf.len;
  PyObject *frames = PyList_New(0);
  if (!frames) {
    PyBuffer_Release(&buf);
    return nullptr;
  }
  Py_ssize_t pos = 0;
  while (pos + 4 <= n) {
    uint32_t len = get_u32(p + pos);
    if (len > max_len) {
      PyErr_Format(PyExc_ValueError, "frame length %u exceeds max %u", len,
                   max_len);
      Py_DECREF(frames);
      PyBuffer_Release(&buf);
      return nullptr;
    }
    if (pos + 4 + static_cast<Py_ssize_t>(len) > n) break;
    PyObject *payload = PyBytes_FromStringAndSize(
        reinterpret_cast<const char *>(p + pos + 4), len);
    if (!payload || PyList_Append(frames, payload) != 0) {
      Py_XDECREF(payload);
      Py_DECREF(frames);
      PyBuffer_Release(&buf);
      return nullptr;
    }
    Py_DECREF(payload);
    pos += 4 + len;
  }
  PyObject *rest = PyBytes_FromStringAndSize(
      reinterpret_cast<const char *>(p + pos), n - pos);
  PyBuffer_Release(&buf);
  if (!rest) {
    Py_DECREF(frames);
    return nullptr;
  }
  PyObject *out = PyTuple_Pack(2, frames, rest);
  Py_DECREF(frames);
  Py_DECREF(rest);
  return out;
}

/* -- speedy change-array codec ---------------------------------------
 *
 * The live gossip/sync wire serializes Vec<Change> with the Rust
 * `speedy` layout (little-endian; bridge/speedy.py documents the full
 * format).  The change array is the bulk of every broadcast frame and
 * sync chunk, so the per-row field packing runs here; the Python twin
 * (_w_change/_r_change) stays the fallback and the semantic reference.
 */

void put_u32le(std::string &out, uint32_t v) {
  char b[4] = {static_cast<char>(v), static_cast<char>(v >> 8),
               static_cast<char>(v >> 16), static_cast<char>(v >> 24)};
  out.append(b, 4);
}

void put_u64le(std::string &out, uint64_t v) {
  char b[8];
  for (int i = 0; i < 8; i++) b[i] = static_cast<char>(v >> (8 * i));
  out.append(b, 8);
}

uint32_t get_u32le(const uint8_t *p) {
  return uint32_t(p[0]) | (uint32_t(p[1]) << 8) | (uint32_t(p[2]) << 16) |
         (uint32_t(p[3]) << 24);
}

uint64_t get_u64le(const uint8_t *p) {
  uint64_t v = 0;
  for (int i = 7; i >= 0; i--) v = (v << 8) | p[i];
  return v;
}

bool put_lp_str(std::string &out, PyObject *v, const char *field) {
  if (!PyUnicode_Check(v)) {
    PyErr_Format(PyExc_TypeError, "%s must be str, not %R", field,
                 reinterpret_cast<PyObject *>(Py_TYPE(v)));
    return false;
  }
  Py_ssize_t len = 0;
  const char *s = PyUnicode_AsUTF8AndSize(v, &len);
  if (!s) return false;
  put_u32le(out, static_cast<uint32_t>(len));
  out.append(s, static_cast<size_t>(len));
  return true;
}

bool put_i64_attr(std::string &out, PyObject *obj, PyObject *name) {
  PyObject *v = PyObject_GetAttr(obj, name);
  if (!v) return false;
  long long ll = PyLong_AsLongLong(v);
  Py_DECREF(v);
  if (ll == -1 && PyErr_Occurred()) return false;
  put_u64le(out, static_cast<uint64_t>(ll));
  return true;
}

bool put_u64_attr(std::string &out, PyObject *obj, PyObject *name) {
  /* db_version/seq span the full u64 domain (Python twin uses '<Q') */
  PyObject *v = PyObject_GetAttr(obj, name);
  if (!v) return false;
  unsigned long long u = PyLong_AsUnsignedLongLong(v);
  Py_DECREF(v);
  if (u == static_cast<unsigned long long>(-1) && PyErr_Occurred())
    return false;
  put_u64le(out, static_cast<uint64_t>(u));
  return true;
}

bool put_lp_buffer(std::string &out, PyObject *v, const char *field) {
  /* bytes/bytearray/memoryview, matching the Python twin's accepts */
  if (!PyBytes_Check(v) && !PyByteArray_Check(v) && !PyMemoryView_Check(v)) {
    PyErr_Format(PyExc_TypeError, "%s must be bytes-like, not %R", field,
                 reinterpret_cast<PyObject *>(Py_TYPE(v)));
    return false;
  }
  Py_buffer buf;
  if (PyObject_GetBuffer(v, &buf, PyBUF_SIMPLE) != 0) return false;
  put_u32le(out, static_cast<uint32_t>(buf.len));
  out.append(static_cast<const char *>(buf.buf),
             static_cast<size_t>(buf.len));
  PyBuffer_Release(&buf);
  return true;
}

struct ChangeAttrs {
  PyObject *table, *pk, *cid, *val, *col_version, *db_version, *seq,
      *site_id, *cl;
  bool init() {
    table = PyUnicode_InternFromString("table");
    pk = PyUnicode_InternFromString("pk");
    cid = PyUnicode_InternFromString("cid");
    val = PyUnicode_InternFromString("val");
    col_version = PyUnicode_InternFromString("col_version");
    db_version = PyUnicode_InternFromString("db_version");
    seq = PyUnicode_InternFromString("seq");
    site_id = PyUnicode_InternFromString("site_id");
    cl = PyUnicode_InternFromString("cl");
    return table && pk && cid && val && col_version && db_version && seq &&
           site_id && cl;
  }
};

ChangeAttrs g_attrs;

PyObject *speedy_encode_changes(PyObject *, PyObject *arg) {
  PyObject *seq = PySequence_Fast(arg, "expects a sequence of Change");
  if (!seq) return nullptr;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
  std::string out;
  out.reserve(96 * static_cast<size_t>(n));
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *c = PySequence_Fast_GET_ITEM(seq, i);
    PyObject *table = PyObject_GetAttr(c, g_attrs.table);
    PyObject *pk = table ? PyObject_GetAttr(c, g_attrs.pk) : nullptr;
    PyObject *cid = pk ? PyObject_GetAttr(c, g_attrs.cid) : nullptr;
    PyObject *val = cid ? PyObject_GetAttr(c, g_attrs.val) : nullptr;
    PyObject *site = val ? PyObject_GetAttr(c, g_attrs.site_id) : nullptr;
    bool ok = site != nullptr;
    if (ok) ok = put_lp_str(out, table, "table");
    if (ok) ok = put_lp_buffer(out, pk, "pk");
    if (ok) ok = put_lp_str(out, cid, "cid");
    if (ok) {
      /* SqliteValue: u8 tag then the value (bridge/speedy.py _w_value) */
      if (val == Py_None) {
        out.push_back(0);
      } else if (PyBool_Check(val)) {
        out.push_back(1);
        put_u64le(out, val == Py_True ? 1 : 0);
      } else if (PyLong_Check(val)) {
        long long ll = PyLong_AsLongLong(val);
        if (ll == -1 && PyErr_Occurred()) {
          ok = false;
        } else {
          out.push_back(1);
          put_u64le(out, static_cast<uint64_t>(ll));
        }
      } else if (PyFloat_Check(val)) {
        double d = PyFloat_AS_DOUBLE(val);
        uint64_t bits;
        std::memcpy(&bits, &d, 8);
        out.push_back(2);
        put_u64le(out, bits);
      } else if (PyUnicode_Check(val)) {
        out.push_back(3);
        ok = put_lp_str(out, val, "val");
        if (!ok) out.pop_back();
      } else if (PyBytes_Check(val) || PyByteArray_Check(val) ||
                 PyMemoryView_Check(val)) {
        out.push_back(4);
        ok = put_lp_buffer(out, val, "val");
        if (!ok) out.pop_back();
      } else {
        PyErr_Format(PyExc_TypeError, "unsupported SqliteValue: %R",
                     reinterpret_cast<PyObject *>(Py_TYPE(val)));
        ok = false;
      }
    }
    if (ok) ok = put_i64_attr(out, c, g_attrs.col_version);
    if (ok) ok = put_u64_attr(out, c, g_attrs.db_version);
    if (ok) ok = put_u64_attr(out, c, g_attrs.seq);
    if (ok) {
      if (!PyBytes_Check(site) || PyBytes_GET_SIZE(site) != 16) {
        PyErr_SetString(PyExc_ValueError, "site_id must be 16 bytes");
        ok = false;
      } else {
        out.append(PyBytes_AS_STRING(site), 16);
      }
    }
    if (ok) ok = put_i64_attr(out, c, g_attrs.cl);
    Py_XDECREF(table);
    Py_XDECREF(pk);
    Py_XDECREF(cid);
    Py_XDECREF(val);
    Py_XDECREF(site);
    if (!ok) {
      Py_DECREF(seq);
      return nullptr;
    }
  }
  Py_DECREF(seq);
  return PyBytes_FromStringAndSize(out.data(),
                                   static_cast<Py_ssize_t>(out.size()));
}

#define NEED(k)                                                     \
  if (pos + static_cast<Py_ssize_t>(k) > n) {                       \
    PyErr_SetString(PyExc_ValueError, "truncated change array");    \
    goto fail;                                                      \
  }

PyObject *speedy_decode_changes(PyObject *, PyObject *args) {
  Py_buffer buf;
  Py_ssize_t offset = 0;
  long long count = 0;
  if (!PyArg_ParseTuple(args, "y*nL", &buf, &offset, &count)) return nullptr;
  const uint8_t *p = static_cast<const uint8_t *>(buf.buf);
  Py_ssize_t n = buf.len;
  if (offset < 0 || offset > n || count < 0) {
    PyErr_SetString(PyExc_ValueError, "offset/count out of range");
    PyBuffer_Release(&buf);
    return nullptr;
  }
  Py_ssize_t pos = offset;
  PyObject *out = PyList_New(0);
  if (!out) {
    PyBuffer_Release(&buf);
    return nullptr;
  }
  for (long long i = 0; i < count; i++) {
    PyObject *tup = nullptr;
    PyObject *table = nullptr, *pk = nullptr, *cid = nullptr,
             *val = nullptr, *site = nullptr;
    uint32_t len;
    uint64_t col_version, db_version, seqno, cl;
    uint8_t tag;
    /* table */
    NEED(4); len = get_u32le(p + pos); pos += 4;
    NEED(len);
    table = PyUnicode_DecodeUTF8(
        reinterpret_cast<const char *>(p + pos), len, nullptr);
    pos += len;
    if (!table) goto fail;
    /* pk */
    NEED(4); len = get_u32le(p + pos); pos += 4;
    NEED(len);
    pk = PyBytes_FromStringAndSize(
        reinterpret_cast<const char *>(p + pos), len);
    pos += len;
    if (!pk) goto fail;
    /* cid */
    NEED(4); len = get_u32le(p + pos); pos += 4;
    NEED(len);
    cid = PyUnicode_DecodeUTF8(
        reinterpret_cast<const char *>(p + pos), len, nullptr);
    pos += len;
    if (!cid) goto fail;
    /* val */
    NEED(1); tag = p[pos]; pos += 1;
    if (tag == 0) {
      val = Py_NewRef(Py_None);
    } else if (tag == 1) {
      NEED(8);
      val = PyLong_FromLongLong(
          static_cast<long long>(get_u64le(p + pos)));
      pos += 8;
    } else if (tag == 2) {
      NEED(8);
      uint64_t bits = get_u64le(p + pos);
      pos += 8;
      double d;
      std::memcpy(&d, &bits, 8);
      val = PyFloat_FromDouble(d);
    } else if (tag == 3 || tag == 4) {
      NEED(4); len = get_u32le(p + pos); pos += 4;
      NEED(len);
      val = (tag == 3)
                ? PyUnicode_DecodeUTF8(
                      reinterpret_cast<const char *>(p + pos), len, nullptr)
                : PyBytes_FromStringAndSize(
                      reinterpret_cast<const char *>(p + pos), len);
      pos += len;
    } else {
      PyErr_Format(PyExc_ValueError, "unknown SqliteValue variant %d", tag);
      goto fail;
    }
    if (!val) goto fail;
    /* fixed tail */
    NEED(8 + 8 + 8 + 16 + 8);
    col_version = get_u64le(p + pos); pos += 8;
    db_version = get_u64le(p + pos); pos += 8;
    seqno = get_u64le(p + pos); pos += 8;
    site = PyBytes_FromStringAndSize(
        reinterpret_cast<const char *>(p + pos), 16);
    pos += 16;
    if (!site) goto fail;
    cl = get_u64le(p + pos); pos += 8;
    tup = Py_BuildValue(
        "(NNNNLKKNL)", table, pk, cid, val,
        static_cast<long long>(col_version), db_version, seqno, site,
        static_cast<long long>(cl));
    if (!tup) {
      /* Py_BuildValue with N consumed the refs */
      Py_DECREF(out);
      PyBuffer_Release(&buf);
      return nullptr;
    }
    table = pk = cid = val = site = nullptr;
    if (PyList_Append(out, tup) != 0) {
      Py_DECREF(tup);
      Py_DECREF(out);
      PyBuffer_Release(&buf);
      return nullptr;
    }
    Py_DECREF(tup);
    continue;
  fail:
    Py_XDECREF(table);
    Py_XDECREF(pk);
    Py_XDECREF(cid);
    Py_XDECREF(val);
    Py_XDECREF(site);
    Py_DECREF(out);
    PyBuffer_Release(&buf);
    return nullptr;
  }
  PyBuffer_Release(&buf);
  PyObject *res = Py_BuildValue("(Nn)", out, pos);
  if (!res) Py_DECREF(out);
  return res;
}

#undef NEED

PyMethodDef methods[] = {
    {"pack_values", pack_values, METH_O,
     "Pack a sequence of SQL values into one self-describing blob."},
    {"unpack_values", unpack_values, METH_O,
     "Inverse of pack_values."},
    {"value_cmp", value_cmp, METH_VARARGS,
     "cr-sqlite merge tie-break comparison (-1/0/1)."},
    {"deframe", deframe, METH_VARARGS,
     "Split complete u32-BE length-delimited frames off the front."},
    {"speedy_encode_changes", speedy_encode_changes, METH_O,
     "Encode a sequence of Change rows in the speedy wire layout."},
    {"speedy_decode_changes", speedy_decode_changes, METH_VARARGS,
     "(buf, offset, count) -> (list of field tuples, end offset)."},
    {nullptr, nullptr, 0, nullptr},
};

PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_corrosion_native",
    "Native hot-path kernels (packed-pk codec, merge compare, framing).",
    -1, methods,
};

}  // namespace

PyMODINIT_FUNC PyInit__corrosion_native(void) {
  if (!g_attrs.init()) return nullptr;
  return PyModule_Create(&moduledef);
}
