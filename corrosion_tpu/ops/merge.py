"""CRDT merge kernels.

The merge of two replicas' cell states is an elementwise ``max`` over packed
keys (see :mod:`corrosion_tpu.ops.keys`); message delivery into a replica
array is a scatter-max.  Both shapes let XLA fuse the merge into surrounding
elementwise work and keep everything HBM-resident — this is the pjit'd
per-row reduction that replaces cr-sqlite's C merge
(``crates/corro-types/src/sqlite.rs:103-121`` loads the extension;
``doc/crdts.md:13-16`` defines the rule).
"""

from __future__ import annotations

import jax.numpy as jnp


def merge_keys(a, b):
    """Merge two equally-shaped packed-key arrays (commutative, idempotent,
    associative — the CRDT join)."""
    return jnp.maximum(a, b)


def merge_cells(states):
    """Merge replica states along the leading axis: [R, ...] -> [...]."""
    return jnp.max(states, axis=0)


def pallas_merge_cells(states, block_rows: int = 256, interpret=None):
    """Pallas twin of :func:`merge_cells`: the R-replica LWW join as a
    tiled TPU kernel (SURVEY §7.1's "pallas kernel for the hot merge";
    the jnp path stays the semantic reference and the fallback).

    states: [R, N, C] int32 packed keys.  The grid walks row blocks;
    each step loads all R replicas' [block, C] tiles into VMEM and
    reduces them on the VPU.  ``interpret=None`` auto-selects the
    interpreter off-TPU so the kernel is testable anywhere.
    """
    import jax
    from jax.experimental import pallas as pl

    r, n, c = states.shape
    if interpret is None:
        # interpreter only where pallas has no native lowering (CPU);
        # TPU and GPU both lower natively
        interpret = jax.default_backend() == "cpu"

    pad = (-n) % block_rows
    if pad:
        # padded rows merge to the pad value and are sliced off
        states = jnp.pad(states, ((0, 0), (0, pad), (0, 0)))
    n_pad = n + pad

    def kernel(in_ref, out_ref):
        acc = in_ref[0]
        for i in range(1, r):  # r is static: unrolled on the VPU
            acc = jnp.maximum(acc, in_ref[i])
        out_ref[:] = acc

    result = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((n_pad, c), states.dtype),
        grid=(n_pad // block_rows,),
        in_specs=[
            pl.BlockSpec((r, block_rows, c), lambda i: (0, i, 0)),
        ],
        out_specs=pl.BlockSpec((block_rows, c), lambda i: (i, 0)),
        interpret=interpret,
    )(states)
    return result[:n] if pad else result


def scatter_merge(state, targets, msg_keys):
    """Deliver messages into a replica-indexed state via scatter-max.

    state:    [N, ...cells] packed keys, one row per replica.
    targets:  [M] int replica indices (may repeat; duplicates merge).
    msg_keys: [M, ...cells] packed keys carried by each message.

    Returns the updated state.  Out-of-range targets must be pre-clamped or
    masked by pointing them at a dead row; ``mode="drop"`` makes XLA discard
    them, which the sim uses for loss/partition masking.
    """
    return state.at[targets].max(msg_keys, mode="drop")
