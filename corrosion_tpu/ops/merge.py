"""CRDT merge kernels.

The merge of two replicas' cell states is an elementwise ``max`` over packed
keys (see :mod:`corrosion_tpu.ops.keys`); message delivery into a replica
array is a scatter-max.  Both shapes let XLA fuse the merge into surrounding
elementwise work and keep everything HBM-resident — this is the pjit'd
per-row reduction that replaces cr-sqlite's C merge
(``crates/corro-types/src/sqlite.rs:103-121`` loads the extension;
``doc/crdts.md:13-16`` defines the rule).

The second half of this module is the COLUMNAR BATCHED-APPLY kernel
(docs/crdts.md "Columnar merge kernel"): the live agent's batched change
application and the simulator's representation-independence check both
resolve causal-length / LWW winners through ONE winner-selection core,
``select_winners``, instead of re-deriving the merge rule in per-change
Python.  A batch of changes encodes to flat arrays (interned pk/cid
ordinals, causal lengths, packed ``(cl, col_version, value_rank)`` LWW
keys); winners resolve via segmented prefix-max scans + segment-max
reductions.  Two backends produce bit-identical integer results:

* a pure-NumPy twin (the no-JAX fallback and the CPU-host default), and
* a jit-compiled JAX path, shape-bucketed to powers of two like
  ``exact_seed_batch``'s HBM policy so a stream of varying batch sizes
  compiles O(log) kernels, not O(batches).

The per-change dict loop in ``agent/storage.py`` stays verbatim as the
parity oracle (PR 3–5 discipline); ``tests/test_apply_batched.py`` pins
three-way equivalence and ``tests/test_merge_columnar.py`` pins the
numpy/jax twins against each other.
"""

from __future__ import annotations

import sys
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


def _jnp():
    """jax.numpy, imported on first jax-backed call — the live agent's
    NumPy-twin path must never trigger (or require) the JAX import."""
    import jax.numpy as jnp

    return jnp


def merge_keys(a, b):
    """Merge two equally-shaped packed-key arrays (commutative, idempotent,
    associative — the CRDT join)."""
    return _jnp().maximum(a, b)


def merge_cells(states):
    """Merge replica states along the leading axis: [R, ...] -> [...]."""
    return _jnp().max(states, axis=0)


def scatter_merge(state, targets, msg_keys):
    """Deliver messages into a replica-indexed state via scatter-max.

    state:    [N, ...cells] packed keys, one row per replica.
    targets:  [M] int replica indices (may repeat; duplicates merge).
    msg_keys: [M, ...cells] packed keys carried by each message.

    Returns the updated state.  Out-of-range targets must be pre-clamped or
    masked by pointing them at a dead row; ``mode="drop"`` makes XLA discard
    them, which the sim uses for loss/partition masking.
    """
    return state.at[targets].max(msg_keys, mode="drop")


# ---------------------------------------------------------------------------
# Columnar batched-apply winner selection
# ---------------------------------------------------------------------------

#: "no value" for packed LWW keys and segment seeds — far below any
#: packable key (which are non-negative) yet safe to add/compare in int64
NEG_KEY = -(1 << 62)
_BIG = 1 << 62

#: dense per-(pk, cid) seed matrices beyond this many cells fall back to
#: the dict oracle rather than allocating a hostile-batch-shaped array
MAX_SEED_CELLS = 4_000_000


@dataclass(frozen=True)
class MergePlan:
    """One table batch, encoded to flat arrays in stream order.

    ``pk``/``cid`` are first-appearance-interned ordinals (cid ``-1`` =
    row-level sentinel change); ``key`` packs ``(cl, col_version,
    value_rank)`` so that int64 order == the merge rule's lexicographic
    order (``NEG_KEY`` on sentinels).  ``seed_cl``/``seed_key`` carry the
    database's pre-batch view: the row causal length per pk (``-1`` = no
    row entry) and the packed clock/value per (pk, cid) cell (``NEG_KEY``
    = no clock row).
    """

    n: int
    n_pk: int
    n_cid: int
    pk: np.ndarray
    cid: np.ndarray
    sent: np.ndarray
    cl: np.ndarray
    key: np.ndarray
    seed_cl: np.ndarray
    seed_key: np.ndarray  # flat [n_pk * n_cid]
    pk_values: List
    cid_values: List
    # the extracted per-change value / col_version columns, stream
    # order — decoders index these instead of re-walking the changes
    vals: Tuple = ()
    vers: Tuple = ()


@dataclass(frozen=True)
class MergeDecision:
    """``select_winners`` output, mirroring the dict loop's per-pk state.

    Per pk ordinal: ``final_cl`` (max causal length incl. the seed),
    ``gen`` (the row generation changed), ``alive`` (final cl odd),
    ``ensure`` (an equal-generation live cell change touched the row),
    ``sent_flag`` (some generation raise was a sentinel change) and
    ``clrow_idx`` (stream index of the change whose ``(db_version, seq,
    site)`` stamp the row-CL record takes; ``-1`` = no raise).  Per
    (pk, cid) cell: ``winner_idx`` — stream index of the surviving LWW
    winner (``-1`` = none: beaten by the DB view, or wiped by a later
    generation).  ``impacted`` counts accept events exactly like the
    sequential replay (rows-impacted parity).
    """

    final_cl: np.ndarray
    gen: np.ndarray
    alive: np.ndarray
    ensure: np.ndarray
    sent_flag: np.ndarray
    clrow_idx: np.ndarray
    winner_idx: np.ndarray  # flat [n_pk * n_cid]
    impacted: int


_TYPE_BUCKET = {type(None): 0, bytes: 1, str: 2, float: 3, int: 4,
                bool: 4}


def value_ranks(values: Sequence) -> np.ndarray:
    """Dense ranks (position -> rank) under the cr-sqlite value order
    (:func:`corrosion_tpu.agent.pack.value_cmp`): type-enum bucket
    first -- ``NULL < BLOB < TEXT < REAL < INTEGER`` -- then the
    in-type order (str order == UTF-8 byte order; bool binds as
    INTEGER).  Equal-comparing values share a rank, bigger values get
    bigger ranks.  Bucketed so each type sorts with native C compares;
    no per-value comparator calls.  Raises TypeError on unsupported
    types, ValueError on NaN (value_cmp "ties" NaN against everything,
    which is not a total order) -- callers fall back to the per-change
    oracle."""
    n = len(values)
    ranks = np.zeros(n, np.int64)
    if not n:
        return ranks
    buckets = list(map(_TYPE_BUCKET.get, map(type, values)))
    if None in buckets:
        # exotic types: normalize bytes-likes / int subclasses, reject
        # the rest (rare path -- wire decode produces exact types)
        values = list(values)
        for i, v in enumerate(values):
            if buckets[i] is not None:
                continue
            if isinstance(v, (bytearray, memoryview)):
                values[i] = bytes(v)
                buckets[i] = 1
            elif isinstance(v, bool):
                values[i] = bool(v)
                buckets[i] = 4
            elif isinstance(v, int):
                values[i] = int(v)
                buckets[i] = 4
            elif isinstance(v, float):
                values[i] = float(v)
                buckets[i] = 3
            elif isinstance(v, str):
                values[i] = str(v)
                buckets[i] = 2
            else:
                raise TypeError(f"unsupported SQL value: {type(v)!r}")
    b0 = buckets[0]
    if b0 is not None and b0 != 0 and buckets.count(b0) == n:
        # homogeneous batch (the common wire shape): no bucket gather
        if b0 == 3 and any(v != v for v in values):
            raise ValueError("NaN value")
        rank_of = {v: r for r, v in enumerate(sorted(set(values)))}
        return np.fromiter(
            map(rank_of.__getitem__, values), np.int64, count=n
        )
    barr = np.fromiter(buckets, np.int8, count=n)
    offset = 0
    for b in range(5):
        ix = np.flatnonzero(barr == b)
        if not len(ix):
            continue
        if b == 0:  # every NULL is one rank
            offset += 1
            continue
        vals = [values[i] for i in ix.tolist()]
        if b == 3 and any(v != v for v in vals):
            raise ValueError("NaN value")
        distinct = sorted(set(vals))
        rank_of = {v: r for r, v in enumerate(distinct)}
        ranks[ix] = np.fromiter(
            map(rank_of.__getitem__, vals), np.int64, count=len(vals)
        )
        ranks[ix] += offset
        offset += len(distinct)
    return ranks


def encode_changes(
    records: Sequence[Tuple],
    seed_cls: Optional[Dict] = None,
    seed_cells: Optional[Dict] = None,
) -> Optional[MergePlan]:
    """Encode one table batch for :func:`select_winners`.

    ``records``: stream-ordered ``(pk, cid_or_None, cl, col_version,
    value)`` tuples (cid ``None`` = row-level sentinel).  ``seed_cls``:
    pk -> pre-batch row causal length.  ``seed_cells``: (pk, cid) ->
    ``(col_version, current_value)`` pre-batch clock view.

    Returns ``None`` when the batch cannot be packed into 62-bit keys
    (hostile out-of-range fields) or the dense seed matrix would be
    unreasonably large -- callers fall back to the per-change oracle.
    """
    if not records:
        return None
    pk_raw, cid_raw, cl_raw, ver_raw, val_raw = zip(*records)
    seed_cols = None
    if seed_cells:
        s_pk, s_cid = zip(*seed_cells)
        s_ver, s_val = zip(*seed_cells.values())
        seed_cols = (s_pk, s_cid, s_ver, s_val)
    return _encode_cols(
        len(records), pk_raw, cid_raw, cl_raw, ver_raw, val_raw,
        None, seed_cls or {}, seed_cols,
    )


def encode_change_batch(
    changes: Sequence,
    sentinel_cid,
    seed_cls: Optional[Dict] = None,
    seed_cell_cols: Optional[Tuple] = None,
) -> Optional[MergePlan]:
    """:func:`encode_changes` straight off ``Change`` objects -- column
    extraction via C-level ``attrgetter`` maps, no per-change tuple
    build.  ``sentinel_cid`` is the row-level sentinel marker
    (``types.change.SENTINEL_CID``); ``seed_cell_cols`` carries the
    DB clock view as parallel ``(pks, cids, col_versions, values)``
    sequences."""
    import operator

    if not changes:
        return None
    return _encode_cols(
        len(changes),
        tuple(map(operator.attrgetter("pk"), changes)),
        tuple(map(operator.attrgetter("cid"), changes)),
        tuple(map(operator.attrgetter("cl"), changes)),
        tuple(map(operator.attrgetter("col_version"), changes)),
        tuple(map(operator.attrgetter("val"), changes)),
        sentinel_cid, seed_cls or {}, seed_cell_cols,
    )


def _encode_cols(
    n: int, pk_raw, cid_raw, cl_raw, ver_raw, val_raw,
    sentinel, seed_cls: Dict, seed_cell_cols: Optional[Tuple],
) -> Optional[MergePlan]:
    from itertools import repeat

    # version/causal-length fields must be real ints (the dict oracle
    # compares whatever arrives; the kernel only handles the conforming
    # stream and falls back otherwise) -- C-level isinstance map
    if not all(map(isinstance, cl_raw, repeat(int))):
        return None
    if not all(map(isinstance, ver_raw, repeat(int))):
        return None

    pk_ord: Dict = {}
    for pk in pk_raw:
        if pk not in pk_ord:
            pk_ord[pk] = len(pk_ord)
    cid_ord: Dict = {sentinel: -1}
    for c in cid_raw:
        if c not in cid_ord:
            cid_ord[c] = len(cid_ord) - 1
    try:
        pk_col = np.fromiter(
            map(pk_ord.__getitem__, pk_raw), np.int64, count=n)
        cid_col = np.fromiter(
            map(cid_ord.__getitem__, cid_raw), np.int64, count=n)
        cl_col = np.fromiter(cl_raw, np.int64, count=n)
        ver_col = np.fromiter(ver_raw, np.int64, count=n)
    except OverflowError:  # hostile out-of-int64 fields
        return None
    del cid_ord[sentinel]
    if int(cl_col.min()) < 0 or int(ver_col.min()) < 0:
        return None
    n_pk, n_cid = len(pk_ord), max(1, len(cid_ord))
    if n_pk * n_cid > MAX_SEED_CELLS:
        return None

    # the row-CL seeds first: per-pk pre-batch causal length (-1 = no
    # row entry), needed below to filter which clock seeds participate
    for v in seed_cls.values():
        if not isinstance(v, int) or not 0 <= v <= _BIG:
            return None
    seed_cl = np.full(n_pk, -1, np.int64)
    if seed_cls:
        for pk, cl in seed_cls.items():
            o = pk_ord.get(pk)
            if o is not None:
                seed_cl[o] = cl

    # pool the DB-view cell values with the batch values so one ranking
    # covers every comparison the LWW tie-break can make.  Seed cells
    # only matter for pks holding a row-CL entry (with no entry the
    # first cell change adopts a fresh generation and the clock view
    # never participates) and for cids the batch references.
    sp = sc = sv = None
    if seed_cell_cols is not None:
        s_pk_raw, s_cid_raw, s_ver_raw, s_val_raw = seed_cell_cols
        if not (all(map(pk_ord.__contains__, s_pk_raw))
                and all(map(cid_ord.__contains__, s_cid_raw))):
            f = ([], [], [], [])
            for pk, cid, sver, sval in zip(
                s_pk_raw, s_cid_raw, s_ver_raw, s_val_raw
            ):
                if pk in pk_ord and cid in cid_ord:
                    f[0].append(pk)
                    f[1].append(cid)
                    f[2].append(sver)
                    f[3].append(sval)
            s_pk_raw, s_cid_raw, s_ver_raw, s_val_raw = f
        m = len(s_pk_raw)
        if m:
            if not all(map(isinstance, s_ver_raw, repeat(int))):
                return None
            try:
                sp = np.fromiter(
                    map(pk_ord.__getitem__, s_pk_raw), np.int64,
                    count=m)
                sc = np.fromiter(
                    map(cid_ord.__getitem__, s_cid_raw), np.int64,
                    count=m)
                sv = np.fromiter(s_ver_raw, np.int64, count=m)
            except OverflowError:
                return None
            if int(sv.min()) < 0:
                return None
            keep = np.flatnonzero(seed_cl[sp] >= 0)
            if len(keep) < m:
                sp, sc, sv = sp[keep], sc[keep], sv[keep]
                s_val_raw = [s_val_raw[i] for i in keep.tolist()]
    # A VALUE is only ever compared on an exact (pk, cid, cl,
    # col_version) tie -- between two batch candidates for the same
    # cell, or a candidate and the cell's DB clock seed.  Everything
    # else is decided by the (cl, ver) bits alone, so only
    # tie-implicated values get ranked (rank 0 otherwise): the common
    # backfill batch skips value ranking entirely, exactly like the
    # dict replay's lazily-called value_cmp.  Tag-hash membership is
    # conservative under collisions (a collision only ranks a value
    # needlessly).
    sent_col = cid_col < 0
    M = np.int64(1_000_003)
    tags = ((pk_col * M + cid_col) * M + cl_col) * M + ver_col
    cells_pos = np.flatnonzero(~sent_col)
    ctags = tags[cells_pos]
    if len(ctags) > 1:
        ss = np.sort(ctags)
        dup_tags = np.unique(ss[1:][ss[1:] == ss[:-1]])
    else:
        dup_tags = np.empty(0, np.int64)
    seed_rank = None
    six = None
    if sp is not None and len(sp):
        seed_tags = ((sp * M + sc) * M + seed_cl[sp]) * M + sv
        seed_tied = np.isin(seed_tags, ctags)
        six = np.flatnonzero(seed_tied)
        tie_tags = np.union1d(dup_tags, seed_tags[six])
        seed_rank = np.zeros(len(sp), np.int64)
    else:
        tie_tags = dup_tags
    rank_col = np.zeros(n, np.int64)
    max_rank = 0
    if len(tie_tags):
        cix = cells_pos[np.isin(ctags, tie_tags)]
        pool = [val_raw[i] for i in cix.tolist()]
        n_cell_pool = len(pool)
        if six is not None and len(six):
            pool.extend(s_val_raw[i] for i in six.tolist())
        try:
            ranks = value_ranks(pool)
        except (TypeError, ValueError):
            return None
        rank_col[cix] = ranks[:n_cell_pool]
        if six is not None and len(six):
            seed_rank[six] = ranks[n_cell_pool:]
        if len(ranks):
            max_rank = int(ranks.max())

    max_cl = int(cl_col.max())
    if seed_cls:
        max_cl = max(max_cl, max(seed_cls.values()))
    max_ver = int(ver_col.max())
    if sv is not None and len(sv):
        max_ver = max(max_ver, int(sv.max()))
    cl_bits = max(1, max_cl.bit_length())
    ver_bits = max(1, max_ver.bit_length())
    val_bits = max(1, max_rank.bit_length())
    if cl_bits + ver_bits + val_bits > 62:
        return None
    cl_shift = ver_bits + val_bits

    key_col = np.where(
        sent_col, NEG_KEY,
        (cl_col << cl_shift) | (ver_col << val_bits) | rank_col,
    )

    seed_key = np.full(n_pk * n_cid, NEG_KEY, np.int64)
    if sp is not None and len(sp):
        seed_key[sp * n_cid + sc] = (
            (seed_cl[sp] << cl_shift) | (sv << val_bits) | seed_rank
        )

    return MergePlan(
        n=n, n_pk=n_pk, n_cid=n_cid,
        pk=pk_col, cid=cid_col, sent=sent_col, cl=cl_col, key=key_col,
        seed_cl=seed_cl, seed_key=seed_key,
        pk_values=list(pk_ord), cid_values=list(cid_ord),
        vals=val_raw, vers=ver_raw,
    )


def _seg_cummax_np(x: np.ndarray, seg: np.ndarray) -> np.ndarray:
    """Segmented inclusive prefix max over contiguous segments
    (Hillis–Steele doubling: O(n log n) vector passes, no Python loop
    over segments)."""
    out = x.copy()
    shift = 1
    n = len(x)
    while shift < n:
        same = seg[shift:] == seg[:-shift]
        np.maximum(
            out[shift:], np.where(same, out[:-shift], NEG_KEY),
            out=out[shift:],
        )
        shift <<= 1
    return out


def _winners_np(plan: MergePlan) -> MergeDecision:
    """The NumPy twin of the winner-selection core.

    Reduction semantics (mirrors the sequential replay exactly):

    1. per pk, a segmented prefix max of causal length (seeded with the
       DB row CL) classifies every change as stale (cl < running max),
       equal-generation (cl == running max) or a generation RAISE
       (cl > running max);
    2. per (pk, cid), live-generation cell changes compete through a
       segmented prefix max over packed ``(cl, col_version,
       value_rank)`` keys seeded with the DB clock view — a strict
       improvement is an accept event (rows-impacted parity), and the
       last accept is the surviving winner;
    3. winners from generations below the pk's final causal length are
       discarded (a later raise wiped them), matching the dict loop's
       cell reset.
    """
    n, n_pk, n_cid = plan.n, plan.n_pk, plan.n_cid
    idx = np.arange(n, dtype=np.int64)
    pk, cid, sent, cl, key = plan.pk, plan.cid, plan.sent, plan.cl, plan.key

    # -- domain A: stream order within each pk ------------------------
    # real batches usually arrive pk-grouped (collect_changes emits
    # (db_version, seq) order, cells of one row adjacent): a sorted
    # input makes the stable sort the identity permutation, so skip it
    if np.all(pk[1:] >= pk[:-1]):
        oA = idx
        pkA, clA, sentA = pk, cl, sent
    else:
        oA = np.argsort(pk, kind="stable")
        pkA, clA, sentA = pk[oA], cl[oA], sent[oA]
    startsA = np.empty(n, bool)
    startsA[0] = True
    startsA[1:] = pkA[1:] != pkA[:-1]
    segA = np.cumsum(startsA) - 1
    cmaxA = _seg_cummax_np(clA, segA)
    prevA = np.empty(n, np.int64)
    prevA[0] = NEG_KEY
    prevA[1:] = cmaxA[:-1]
    seedA = plan.seed_cl[pkA]
    beforeA = np.where(startsA, seedA, np.maximum(seedA, prevA))
    raiseA = clA > beforeA
    oddA = (clA & 1) == 1
    cellA = ~sentA

    final_cl = plan.seed_cl.copy()
    np.maximum.at(final_cl, pk, cl)
    gen = final_cl > plan.seed_cl
    alive = (final_cl & 1) == 1
    sent_flag = np.zeros(n_pk, bool)
    np.logical_or.at(sent_flag, pkA, raiseA & sentA)
    ensure = np.zeros(n_pk, bool)
    np.logical_or.at(ensure, pkA, cellA & oddA & (clA == beforeA))

    # the row-CL stamp comes from the FIRST change attaining the final
    # causal length (the last raise of the sequential replay)
    cand = np.where(cl == final_cl[pk], idx, _BIG)
    clrow = np.full(n_pk, _BIG, np.int64)
    np.minimum.at(clrow, pk, cand)
    clrow_idx = np.where(gen, clrow, -1)

    n_sent_raise = int(np.count_nonzero(raiseA & sentA))
    n_even_raise = int(np.count_nonzero(raiseA & cellA & ~oddA))

    # LWW participants: live-generation cell changes only
    partA = cellA & oddA & (clA >= beforeA)
    part = np.zeros(n, bool)
    part[oA] = partA

    # -- domain B: stream order within each (pk, cid) cell ------------
    compB = pk * (n_cid + 2) + (cid + 1)
    if np.all(compB[1:] >= compB[:-1]):
        oB = idx
        pkB, cidB = pk, cid
        partB = part
        keyB = np.where(partB, key, NEG_KEY)
    else:
        oB = np.lexsort((idx, cid, pk))
        pkB, cidB = pk[oB], cid[oB]
        partB = part[oB]
        keyB = np.where(partB, key[oB], NEG_KEY)
    startsB = np.empty(n, bool)
    startsB[0] = True
    startsB[1:] = (pkB[1:] != pkB[:-1]) | (cidB[1:] != cidB[:-1])
    segB = np.cumsum(startsB) - 1
    cmaxB = _seg_cummax_np(keyB, segB)
    prevB = np.empty(n, np.int64)
    prevB[0] = NEG_KEY
    prevB[1:] = cmaxB[:-1]
    cell_ix = pkB * n_cid + np.maximum(cidB, 0)
    seedB = np.where(cidB >= 0, plan.seed_key[cell_ix], NEG_KEY)
    beforeB = np.where(startsB, seedB, np.maximum(seedB, prevB))
    acceptB = partB & (keyB > beforeB)
    n_accept = int(np.count_nonzero(acceptB))

    winner = np.full(n_pk * n_cid, -1, np.int64)
    np.maximum.at(winner, cell_ix[acceptB], oB[acceptB])
    wcl = np.where(winner >= 0, cl[np.maximum(winner, 0)], -1)
    wpk = np.arange(n_pk * n_cid, dtype=np.int64) // n_cid
    winner = np.where(
        (winner >= 0) & (wcl == final_cl[wpk]), winner, -1
    )

    return MergeDecision(
        final_cl=final_cl, gen=gen, alive=alive, ensure=ensure,
        sent_flag=sent_flag, clrow_idx=clrow_idx, winner_idx=winner,
        impacted=n_sent_raise + n_even_raise + n_accept,
    )


# -- JAX twin ----------------------------------------------------------

#: smallest jitted bucket; batches pad up to the next power of two so a
#: stream of varying sizes compiles O(log) kernel shapes (the
#: exact_seed_batch bucketing discipline)
MIN_BUCKET = 256
#: below this many changes the jit dispatch overhead dwarfs the scan;
#: ``backend="auto"`` keeps such batches on the NumPy twin
JAX_AUTO_MIN = 65536


def _bucket(n: int) -> int:
    b = MIN_BUCKET
    while b < n:
        b <<= 1
    return b


#: public alias: the device-resident clock cache (ops/devcache.py)
#: buckets its scatter/gather shapes with the same discipline so both
#: layers share one set of compiled kernel shapes
bucket_pow2 = _bucket


def _seg_cummax_jnp(x, seg, n: int):
    jnp = _jnp()
    shift = 1
    while shift < n:
        same = seg[shift:] == seg[:-shift]
        x = x.at[shift:].max(jnp.where(same, x[:-shift], NEG_KEY))
        shift <<= 1
    return x


def _winners_jax_core(pk, cid, sent, cl, key, seed_cl, seed_key,
                      n_cid: int, n: int):
    """Shape-static core (n = padded bucket size; pads carry pk ==
    n_pk, sent True, cl -1, key NEG_KEY so they never raise, never
    participate and never win)."""
    jnp = _jnp()
    idx = jnp.arange(n, dtype=jnp.int64)
    n_pk1 = seed_cl.shape[0]  # n_pk + 1 (pad segment)

    oA = jnp.lexsort((idx, pk))
    pkA, clA, sentA = pk[oA], cl[oA], sent[oA]
    startsA = jnp.concatenate(
        [jnp.ones(1, bool), pkA[1:] != pkA[:-1]]
    )
    segA = jnp.cumsum(startsA) - 1
    cmaxA = _seg_cummax_jnp(clA, segA, n)
    prevA = jnp.concatenate(
        [jnp.full(1, NEG_KEY, jnp.int64), cmaxA[:-1]]
    )
    seedA = seed_cl[pkA]
    beforeA = jnp.where(startsA, seedA, jnp.maximum(seedA, prevA))
    raiseA = clA > beforeA
    oddA = (clA & 1) == 1
    cellA = ~sentA

    final_cl = seed_cl.at[pk].max(cl)
    gen = final_cl > seed_cl
    alive = (final_cl & 1) == 1
    sent_flag = (
        jnp.zeros(n_pk1, jnp.int32).at[pkA].max(
            (raiseA & sentA).astype(jnp.int32)
        ) > 0
    )
    ensure = (
        jnp.zeros(n_pk1, jnp.int32).at[pkA].max(
            (cellA & oddA & (clA == beforeA)).astype(jnp.int32)
        ) > 0
    )
    cand = jnp.where(cl == final_cl[pk], idx, _BIG)
    clrow = jnp.full(n_pk1, _BIG, jnp.int64).at[pk].min(cand)
    clrow_idx = jnp.where(gen, clrow, -1)

    n_sent_raise = jnp.sum(raiseA & sentA)
    n_even_raise = jnp.sum(raiseA & cellA & ~oddA)

    partA = cellA & oddA & (clA >= beforeA)
    part = jnp.zeros(n, bool).at[oA].set(partA)

    oB = jnp.lexsort((idx, cid, pk))
    pkB, cidB = pk[oB], cid[oB]
    partB = part[oB]
    keyB = jnp.where(partB, key[oB], NEG_KEY)
    startsB = jnp.concatenate([
        jnp.ones(1, bool),
        (pkB[1:] != pkB[:-1]) | (cidB[1:] != cidB[:-1]),
    ])
    segB = jnp.cumsum(startsB) - 1
    cmaxB = _seg_cummax_jnp(keyB, segB, n)
    prevB = jnp.concatenate(
        [jnp.full(1, NEG_KEY, jnp.int64), cmaxB[:-1]]
    )
    cell_ix = pkB * n_cid + jnp.maximum(cidB, 0)
    seedB = jnp.where(cidB >= 0, seed_key[cell_ix], NEG_KEY)
    beforeB = jnp.where(startsB, seedB, jnp.maximum(seedB, prevB))
    acceptB = partB & (keyB > beforeB)
    n_accept = jnp.sum(acceptB)

    winner = jnp.full(n_pk1 * n_cid, -1, jnp.int64).at[
        jnp.where(acceptB, cell_ix, n_pk1 * n_cid - 1)
    ].max(jnp.where(acceptB, oB, -1))
    wcl = jnp.where(winner >= 0, cl[jnp.maximum(winner, 0)], -1)
    wpk = jnp.arange(n_pk1 * n_cid, dtype=jnp.int64) // n_cid
    winner = jnp.where(
        (winner >= 0) & (wcl == final_cl[wpk]), winner, -1
    )
    return (final_cl, gen, alive, ensure, sent_flag, clrow_idx, winner,
            n_sent_raise + n_even_raise + n_accept)


_JAX_CORE_CACHE: Dict[Tuple[int, int], object] = {}


def _winners_jax(plan: MergePlan) -> MergeDecision:
    import jax

    if not jax.config.jax_enable_x64:
        # 62-bit packed keys need int64 lanes; without x64 the numpy
        # twin is the correct backend
        raise RuntimeError("columnar merge on jax needs jax_enable_x64")
    n = _bucket(plan.n)
    pad = n - plan.n
    n_pk1 = plan.n_pk + 1
    pk = np.concatenate([plan.pk, np.full(pad, plan.n_pk, np.int64)])
    cid = np.concatenate([plan.cid, np.full(pad, -1, np.int64)])
    sent = np.concatenate([plan.sent, np.ones(pad, bool)])
    cl = np.concatenate([plan.cl, np.full(pad, -1, np.int64)])
    key = np.concatenate([plan.key, np.full(pad, NEG_KEY, np.int64)])
    seed_cl = np.concatenate([plan.seed_cl, np.full(1, -1, np.int64)])
    # one pad row of cells; the pad winner slot (last cell) absorbs
    # masked scatter writes
    seed_key = np.concatenate([
        plan.seed_key, np.full(plan.n_cid, NEG_KEY, np.int64)
    ])

    core = _JAX_CORE_CACHE.get((n, plan.n_cid))
    if core is None:
        core = jax.jit(
            _winners_jax_core, static_argnames=("n_cid", "n")
        )
        _JAX_CORE_CACHE[(n, plan.n_cid)] = core
    out = core(pk, cid, sent, cl, key, seed_cl, seed_key,
               n_cid=plan.n_cid, n=n)
    (final_cl, gen, alive, ensure, sent_flag, clrow_idx, winner,
     impacted) = (np.asarray(x) for x in out)
    np_pk = plan.n_pk
    return MergeDecision(
        final_cl=final_cl[:np_pk], gen=gen[:np_pk], alive=alive[:np_pk],
        ensure=ensure[:np_pk], sent_flag=sent_flag[:np_pk],
        clrow_idx=clrow_idx[:np_pk],
        winner_idx=winner[: np_pk * plan.n_cid],
        impacted=int(impacted),
    )


def select_winners(plan: MergePlan, backend: str = "auto") -> MergeDecision:
    """Resolve one encoded table batch to its net merge decision.

    ``backend``: ``"numpy"`` (the twin), ``"jax"`` (jit, bucketed), or
    ``"auto"`` — jax only when it is importable, x64 is live and the
    batch is big enough to amortize dispatch (``JAX_AUTO_MIN``).  Both
    backends return bit-identical decisions (pinned by
    tests/test_merge_columnar.py)."""
    if backend == "numpy":
        return _winners_np(plan)
    if backend == "jax":
        return _winners_jax(plan)
    if "jax" in sys.modules and plan.n >= JAX_AUTO_MIN:
        try:
            return _winners_jax(plan)
        except Exception:
            return _winners_np(plan)
    return _winners_np(plan)
