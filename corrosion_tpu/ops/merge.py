"""CRDT merge kernels.

The merge of two replicas' cell states is an elementwise ``max`` over packed
keys (see :mod:`corrosion_tpu.ops.keys`); message delivery into a replica
array is a scatter-max.  Both shapes let XLA fuse the merge into surrounding
elementwise work and keep everything HBM-resident — this is the pjit'd
per-row reduction that replaces cr-sqlite's C merge
(``crates/corro-types/src/sqlite.rs:103-121`` loads the extension;
``doc/crdts.md:13-16`` defines the rule).
"""

from __future__ import annotations

import jax.numpy as jnp


def merge_keys(a, b):
    """Merge two equally-shaped packed-key arrays (commutative, idempotent,
    associative — the CRDT join)."""
    return jnp.maximum(a, b)


def merge_cells(states):
    """Merge replica states along the leading axis: [R, ...] -> [...]."""
    return jnp.max(states, axis=0)


def scatter_merge(state, targets, msg_keys):
    """Deliver messages into a replica-indexed state via scatter-max.

    state:    [N, ...cells] packed keys, one row per replica.
    targets:  [M] int replica indices (may repeat; duplicates merge).
    msg_keys: [M, ...cells] packed keys carried by each message.

    Returns the updated state.  Out-of-range targets must be pre-clamped or
    masked by pointing them at a dead row; ``mode="drop"`` makes XLA discard
    them, which the sim uses for loss/partition masking.
    """
    return state.at[targets].max(msg_keys, mode="drop")
