"""Array kernels: the TPU-native forms of the CRDT merge and set algebra.

These are the hot ops behind the simulator — pure, shape-static, fusible
jnp/lax code (pallas variants can slot in underneath without changing the
API).

Exports resolve lazily (PEP 562): the live agent imports
:mod:`corrosion_tpu.ops.merge` for the columnar batched-apply kernel's
NumPy twin, and must not pay the JAX import (hundreds of ms, inside an
apply transaction) — or require JAX at all — unless a jax-backed kernel
is actually dispatched.
"""

_KEYS = ("KeyCodec", "DEFAULT_CODEC")
_MERGE = ("merge_keys", "scatter_merge", "merge_cells")
_DEVCACHE = ("DeviceClockCache", "NumpyStore", "JaxStore",
             "default_enabled", "DEFAULT_SLOTS")

__all__ = list(_KEYS + _MERGE + _DEVCACHE)


def __getattr__(name):
    if name in _KEYS:
        from corrosion_tpu.ops import keys

        return getattr(keys, name)
    if name in _MERGE:
        from corrosion_tpu.ops import merge

        return getattr(merge, name)
    if name in _DEVCACHE:
        from corrosion_tpu.ops import devcache

        return getattr(devcache, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
