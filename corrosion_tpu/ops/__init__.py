"""Array kernels: the TPU-native forms of the CRDT merge and set algebra.

These are the hot ops behind the simulator — pure, shape-static, fusible
jnp/lax code (pallas variants can slot in underneath without changing the
API).
"""

from corrosion_tpu.ops.keys import KeyCodec, DEFAULT_CODEC
from corrosion_tpu.ops.merge import (
    merge_cells,
    merge_keys,
    scatter_merge,
)

__all__ = [
    "KeyCodec",
    "DEFAULT_CODEC",
    "merge_keys",
    "scatter_merge",
    "merge_cells",
]
