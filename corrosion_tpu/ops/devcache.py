"""Device-resident clock cache for the batched CRDT apply path.

The columnar apply kernel (:mod:`corrosion_tpu.ops.merge`) made winner
selection array-shaped, but every batch still re-seeds its DB view from
three SQLite prefetches and throws the merged clocks away after the
flush.  This module keeps that hot state resident across batches: per
CRR table, an open-addressed packed-key index maps ``(pk, cid)`` cells
to slots in shape-bucketed int64 arrays that live on the configured
backend — plain ndarrays on the NumPy store, donated jnp device arrays
on the JAX store — so a steady stream of batches for the same rows
merges with **zero** SQLite prefetches and one device scatter per
commit.

Correctness contract (docs/crdts.md "Device-resident apply"):

* the cache is a *view*, never the truth — SQLite stays the durable
  sink behind :class:`corrosion_tpu.agent.storage` write-behind flush;
* all knowledge is full-row: a pk is served only when its causal
  length, row presence, and every requested cell (version *and* value)
  are known, else the whole pk misses and the caller re-prefetches;
* uncommitted state lives in a per-transaction shadow overlay
  (:meth:`DeviceClockCache.install` / :meth:`~DeviceClockCache.stage_states`
  write shadow-only) promoted into the main arrays at commit and
  discarded on rollback, so a rolled-back apply can never poison the
  cache;
* slots are monotonic and never reused — invalidation retires a pk's
  slot, orphaning its packed cell keys, instead of tombstoning the
  index; capacity pressure clears the whole table (counted as
  evictions) and the next batch re-seeds from SQLite.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

DEFAULT_SLOTS = 262144

# Fibonacci-multiplier hash over packed keys; scalar (python int) and
# vector (uint64 ndarray) forms below agree bit for bit.
_HASH_MULT = 0x9E3779B97F4A7C15
_MASK64 = (1 << 64) - 1

# Cell version sentinel on the *output* side: "known, and no clock row
# exists".  Versions are >= 1, so -1 is unreachable.
ABSENT = -1


class _ValUnknown:
    """Clock version cached without its value (the install's selected
    columns didn't cover this cid) — forces a pk miss when requested."""

    __slots__ = ()

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return "<val-unknown>"


VAL_UNKNOWN = _ValUnknown()


def default_enabled() -> bool:
    """Auto-default for ``AgentConfig.device_cache=None``: on only when
    JAX is *already imported* (never pay the import inside agent
    construction) and the default backend is a real accelerator —
    CPU-only hosts keep the prefetch path (ISSUE 18)."""
    import sys

    jax = sys.modules.get("jax")
    if jax is None:
        return False
    try:
        return jax.default_backend() != "cpu"
    except Exception:  # pragma: no cover - broken jax install
        return False


def _pow2_ceil(n: int) -> int:
    m = 1
    while m < n:
        m <<= 1
    return m


class NumpyStore:
    """Host twin of the device store — same API over plain ndarrays.
    The bit-equality suite pins JaxStore against this."""

    backend = "numpy"

    def full(self, n: int, fill: int):
        return np.full(n, fill, dtype=np.int64)

    def set(self, arr, idx: np.ndarray, vals: np.ndarray):
        arr[idx] = vals
        return arr

    def gather(self, arr, idx: np.ndarray) -> np.ndarray:
        return arr[idx]

    def to_host(self, arr) -> np.ndarray:
        return arr

    def from_host(self, arr) -> np.ndarray:
        return np.ascontiguousarray(arr, dtype=np.int64)


class JaxStore:
    """Clock/cl arrays live on the default JAX device; scatters run
    through a jitted, shape-bucketed index update that donates its
    operand off-CPU (the pjit donation pattern — the old array's buffer
    is reused, no host round-trip), gathers come back through one
    bucketed take."""

    backend = "jax"

    def __init__(self):
        import jax
        import jax.numpy as jnp

        if not jax.config.jax_enable_x64:
            raise RuntimeError(
                "devcache JaxStore requires jax_enable_x64 "
                "(causal lengths / col_versions are int64)"
            )
        from corrosion_tpu.ops.merge import bucket_pow2

        self._jnp = jnp
        self._bucket = bucket_pow2
        # donation is a no-op-with-warning on CPU backends; only donate
        # when the buffer actually lives off-host
        donate = (0,) if jax.default_backend() != "cpu" else ()
        self._set = jax.jit(
            lambda a, i, v: a.at[i].set(v), donate_argnums=donate
        )
        self._take = jax.jit(lambda a, i: a[i])

    def full(self, n: int, fill: int):
        return self._jnp.full(n, fill, dtype=self._jnp.int64)

    def set(self, arr, idx: np.ndarray, vals: np.ndarray):
        n = len(idx)
        m = self._bucket(n)
        if m > n:  # pad with a repeat: duplicate .set of an equal value
            idx = np.concatenate([idx, np.full(m - n, idx[-1], np.int64)])
            vals = np.concatenate(
                [vals, np.full(m - n, vals[-1], np.int64)]
            )
        return self._set(
            arr, self._jnp.asarray(idx), self._jnp.asarray(vals)
        )

    def gather(self, arr, idx: np.ndarray) -> np.ndarray:
        n = len(idx)
        m = self._bucket(n)
        if m > n:
            idx = np.concatenate([idx, np.full(m - n, idx[-1], np.int64)])
        return np.asarray(self._take(arr, self._jnp.asarray(idx)))[:n]

    def to_host(self, arr) -> np.ndarray:
        return np.asarray(arr)

    def from_host(self, arr):
        return self._jnp.asarray(
            np.ascontiguousarray(arr, dtype=np.int64)
        )


def make_store(backend: str = "auto"):
    if backend == "auto":
        backend = "jax" if default_enabled() else "numpy"
    if backend == "jax":
        return JaxStore()
    if backend == "numpy":
        return NumpyStore()
    raise ValueError(f"unknown devcache backend: {backend!r}")


class _TableShadow:
    """Per-transaction overlay for one table.

    ``rows[pk] = [cl, present, full]``:

    * ``cl``: causal length of the pk's ``__corro_cl`` row, or ``None``
      meaning *known to have no cl row* (always known once shadowed);
    * ``present``: data-row existence; ``None`` = inherit from the main
      cache (partial stage over a main-cache hit);
    * ``full``: the cells dict is exhaustive — a missing cid means *no
      clock row* (set by install, which sees every clock row for the
      pk, and by generation stages, which delete them all).

    ``cells[pk] = {cid: cell}`` with ``cell[0] = value`` (may be
    :data:`VAL_UNKNOWN`) and ``cell[1] = col_version`` — the layout of
    the merge's net-state cell tuples, so staging can BORROW the merge
    output dicts wholesale instead of re-keying every cell (tuples may
    carry trailing fields; only [0]/[1] are read here, and borrowed
    dicts are never mutated — an in-tx re-stage of the same pk builds
    a fresh merged dict).

    ``columnar`` memoizes the merge's own flat winner arrays when this
    shadow holds EXACTLY one staged batch with no generation rows and
    no prefetch install — the steady-state hot path — so the commit
    promote can scatter them straight into the device arrays instead
    of re-walking the dicts.  Anything that complicates the overlay
    (a second stage, an install, a targeted invalidation) clears it
    and the dict promote takes over.

    ``staged`` holds stage_states batches not yet folded into the
    dicts: the hot commit path promotes from ``columnar`` and never
    pays the dict build at all, so staging is LAZY — any reader of
    ``rows``/``cells`` must run ``_materialize`` first.
    """

    __slots__ = ("rows", "cells", "staged", "columnar")

    def __init__(self):
        self.rows: Dict[bytes, list] = {}
        self.cells: Dict[bytes, Dict[str, tuple]] = {}
        # deferred (info, states, cl_by_pk, vals_by_pk) stage batches
        self.staged: list = []
        # (plan, decision, cl_by_pk, vals_by_pk) or None
        self.columnar: Optional[tuple] = None


class _TableCache:
    """Main (committed) cache for one CRR table."""

    __slots__ = (
        "name", "n_cid", "cid_ord", "store", "max_rows", "max_cells",
        "pk_slot", "next_slot", "cap_rows", "row_cl", "row_known",
        "row_present", "cap_cells", "capbits", "cell_keys", "cell_ver",
        "cell_val", "cells_used",
    )

    def __init__(self, info, store, max_rows: int, max_cells: int):
        self.name = info.name
        self.n_cid = max(1, len(info.data_cols))
        self.cid_ord = {c: i for i, c in enumerate(info.data_cols)}
        self.store = store
        self.max_rows = max(64, int(max_rows))
        self.max_cells = max(64, int(max_cells))
        self._reset()

    def _reset(self) -> None:
        self.pk_slot: Dict[bytes, int] = {}
        self.next_slot = 0
        self.cap_rows = _pow2_ceil(min(1024, self.max_rows))
        self.row_cl = self.store.full(self.cap_rows, ABSENT)
        self.row_known = np.zeros(self.cap_rows, dtype=bool)
        self.row_present = np.zeros(self.cap_rows, dtype=bool)
        self.cap_cells = _pow2_ceil(min(4096, self.max_cells * 2))
        self.capbits = self.cap_cells.bit_length() - 1
        self.cell_keys = np.zeros(self.cap_cells, dtype=np.int64)
        self.cell_ver = self.store.full(self.cap_cells, 0)
        self.cell_val: List[object] = [None] * self.cap_cells
        self.cells_used = 0

    def live_entries(self) -> int:
        """Entries lost if this table were cleared (eviction accounting):
        live pks plus their reachable cells."""
        return len(self.pk_slot) + self.cells_used

    # -- keys ---------------------------------------------------------

    def _key(self, slot: int, cid: str) -> Optional[int]:
        o = self.cid_ord.get(cid)
        if o is None:
            return None
        return slot * self.n_cid + o + 1

    def _hash_scalar(self, key: int) -> int:
        return ((key * _HASH_MULT) & _MASK64) >> (64 - self.capbits)

    def _hash_vec(self, keys: np.ndarray) -> np.ndarray:
        prod = keys.astype(np.uint64) * np.uint64(_HASH_MULT)
        return (prod >> np.uint64(64 - self.capbits)).astype(np.int64)

    # -- rows ---------------------------------------------------------

    def room_for_rows(self, n: int) -> bool:
        return self.next_slot + n <= self.max_rows

    def ensure_row_capacity(self, n: int) -> None:
        """Grow the row arrays to hold ``n`` more slots; caller has
        already checked :meth:`room_for_rows`."""
        need = self.next_slot + n
        if need <= self.cap_rows:
            return
        cap = self.cap_rows
        while cap < need:
            cap <<= 1
        host = self.store.to_host(self.row_cl)
        new = np.full(cap, ABSENT, dtype=np.int64)
        new[: self.cap_rows] = host
        self.row_cl = self.store.from_host(new)
        nk = np.zeros(cap, dtype=bool)
        nk[: self.cap_rows] = self.row_known
        self.row_known = nk
        npr = np.zeros(cap, dtype=bool)
        npr[: self.cap_rows] = self.row_present
        self.row_present = npr
        self.cap_rows = cap

    def alloc_slot(self, pk: bytes) -> int:
        slot = self.next_slot
        self.next_slot = slot + 1
        self.pk_slot[pk] = slot
        return slot

    def retire(self, pk: bytes) -> bool:
        """Forget a pk: drop its slot (never reused) — its packed cell
        keys become unreachable garbage, reclaimed at the next clear."""
        slot = self.pk_slot.pop(pk, None)
        if slot is None:
            return False
        self.row_known[slot] = False
        return True

    # -- cells --------------------------------------------------------

    def room_for_cells(self, n: int) -> bool:
        return self.cells_used + n <= self.max_cells

    def ensure_cell_capacity(self, n: int) -> None:
        """Keep the open-addressed index under ~0.65 load after adding
        up to ``n`` entries; caller checked :meth:`room_for_cells`."""
        need = self.cells_used + n
        if need * 16 <= self.cap_cells * 10:  # load <= 0.625
            return
        cap = self.cap_cells
        while need * 16 > cap * 10:
            cap <<= 1
        old_keys = self.cell_keys
        live = np.nonzero(old_keys)[0]
        vers = self.store.gather(self.cell_ver, live) if len(live) \
            else np.zeros(0, dtype=np.int64)
        vals = [self.cell_val[int(i)] for i in live]
        self.cap_cells = cap
        self.capbits = cap.bit_length() - 1
        self.cell_keys = np.zeros(cap, dtype=np.int64)
        new_ver = np.zeros(cap, dtype=np.int64)
        self.cell_val = [None] * cap
        self.cells_used = 0
        mask = cap - 1
        for j, li in enumerate(live):
            key = int(old_keys[int(li)])
            i = self._hash_scalar(key)
            while int(self.cell_keys[i]) != 0:
                i = (i + 1) & mask
            self.cell_keys[i] = key
            new_ver[i] = int(vers[j])
            self.cell_val[i] = vals[j]
            self.cells_used += 1
        self.cell_ver = self.store.from_host(new_ver)

    def cell_put_batch(self, entries: List[Tuple[int, int, object]]) -> None:
        """Insert/update packed cells: ``(key, ver, val)`` triples
        (keys unique — shadow cells are per-pk dicts and slots are
        never shared).  One vectorized probe finds the already-present
        keys (the ENTIRE batch, in steady state); only genuinely new
        keys take the scalar insert walk.  Then ONE store scatter for
        the versions (the single device dispatch per commit).  Caller
        ensured capacity."""
        if not entries:
            return
        keys = np.fromiter(
            (e[0] for e in entries), np.int64, len(entries)
        )
        pos = self.cell_find(keys)
        missing = pos < 0
        if missing.any():
            mask = self.cap_cells - 1
            keys_arr = self.cell_keys
            for j in np.nonzero(missing)[0].tolist():
                key = entries[j][0]
                i = self._hash_scalar(key)
                while True:
                    k = int(keys_arr[i])
                    if k == key:
                        break
                    if k == 0:
                        keys_arr[i] = key
                        self.cells_used += 1
                        break
                    i = (i + 1) & mask
                pos[j] = i
        pos_l = pos.tolist()
        cell_val = self.cell_val
        vers = np.fromiter(
            (e[1] for e in entries), np.int64, len(entries)
        )
        for j, e in enumerate(entries):
            cell_val[pos_l[j]] = e[2]
        self.cell_ver = self.store.set(self.cell_ver, pos, vers)

    def cell_find(self, keys: np.ndarray) -> np.ndarray:
        """Vectorized probe: index position per key, -1 if absent."""
        n = len(keys)
        out = np.full(n, -1, dtype=np.int64)
        if n == 0:
            return out
        mask = self.cap_cells - 1
        idx = self._hash_vec(keys)
        pending = np.arange(n)
        table = self.cell_keys
        for _ in range(self.cap_cells):
            cur = table[idx[pending]]
            hit = cur == keys[pending]
            out[pending[hit]] = idx[pending[hit]]
            cont = ~(hit | (cur == 0))
            pending = pending[cont]
            if len(pending) == 0:
                break
            idx[pending] = (idx[pending] + 1) & mask
        return out


class DeviceClockCache:
    """Cross-batch (pk, cid) clock cache with a transactional shadow.

    All methods take the internal RLock; callers additionally hold the
    storage write lock for every mutating path (documented contract —
    the cache orders itself relative to SQLite through that lock)."""

    def __init__(self, slots: int = DEFAULT_SLOTS, backend: str = "auto"):
        self.store = make_store(backend)
        self.backend = self.store.backend
        self.slots = max(64, int(slots))
        self._lock = threading.RLock()
        self._tables: Dict[str, _TableCache] = {}
        self._shadow: Dict[str, _TableShadow] = {}
        # monotonic counters; the agent emits metric deltas off these
        self.counters: Dict[str, float] = {
            "hits": 0.0, "misses": 0.0, "evictions": 0.0,
        }
        self.invalidations: Dict[str, float] = {}

    # -- plumbing -----------------------------------------------------

    def _table(self, info) -> _TableCache:
        tc = self._tables.get(info.name)
        if tc is None:
            max_rows = max(64, self.slots // 4)
            tc = self._tables[info.name] = _TableCache(
                info, self.store, max_rows, self.slots
            )
        return tc

    def _shadow_for(self, name: str) -> _TableShadow:
        sh = self._shadow.get(name)
        if sh is None:
            sh = self._shadow[name] = _TableShadow()
        return sh

    def _evict_table(self, tc: _TableCache) -> None:
        self.counters["evictions"] += tc.live_entries()
        tc._reset()

    def stats(self) -> Dict[str, float]:
        with self._lock:
            out = dict(self.counters)
            out["invalidations"] = sum(self.invalidations.values())
            return out

    # -- read side ----------------------------------------------------

    def lookup(self, info, pks: List[bytes], ref_cids) -> Tuple[
        List[bytes], Dict[bytes, int], Dict[Tuple[bytes, str], int],
        Dict[bytes, dict],
    ]:
        """Resolve the merge seed view for a batch.

        Returns ``(miss_pks, cl_by_pk, clock_by_cell, vals_by_pk)``
        where the three dicts cover exactly the *hit* pks (shapes
        identical to the SQLite prefetches in
        ``storage._apply_table_batched``).  A pk hits only when its cl,
        row presence, and every requested cell — version *and* value —
        are known; anything less is a miss and the caller re-prefetches
        + installs.  A ref cid outside the table's schema poisons the
        whole batch to misses (junk cids are never cached)."""
        cl_by_pk: Dict[bytes, int] = {}
        clock_by_cell: Dict[Tuple[bytes, str], int] = {}
        vals_by_pk: Dict[bytes, dict] = {}
        with self._lock:
            tc = self._table(info)
            refs = [c for c in ref_cids]
            if any(c not in tc.cid_ord for c in refs):
                self.counters["misses"] += len(pks)
                return list(pks), cl_by_pk, clock_by_cell, vals_by_pk
            sh = self._shadow.get(info.name)
            if sh is None or (
                not sh.rows and not sh.cells and not sh.staged
            ):
                # steady state: no staged overlay for this table (the
                # common case — the shadow clears at every commit), so
                # the whole batch resolves against the main arrays in
                # a handful of vectorized ops
                return self._lookup_fast(tc, pks, refs)
            self._materialize(sh)
            miss: List[bytes] = []
            # phase 1: shadow + slot resolution; collect main-cache
            # row/cell queries for one vectorized probe each
            row_q_pks: List[bytes] = []
            row_q_slots: List[int] = []
            cell_q: List[Tuple[bytes, str, int]] = []  # (pk, cid, key)
            # per-pk assembly notes: list of (pk, shadow_row|None)
            plan: List[Tuple[bytes, Optional[list], list]] = []
            for pk in pks:
                srow = sh.rows.get(pk) if sh is not None else None
                scells = sh.cells.get(pk, {}) if sh is not None else {}
                slot = tc.pk_slot.get(pk)
                known = slot is not None and bool(tc.row_known[slot])
                need_main = []
                if srow is not None:
                    full = srow[2]
                    bad = False
                    for c in refs:
                        e = scells.get(c)
                        if e is None:
                            if not full:
                                need_main.append(c)
                        elif e[0] is VAL_UNKNOWN:
                            bad = True
                            break
                    if bad or (
                        (srow[1] is None or need_main) and not known
                    ):
                        miss.append(pk)
                        continue
                else:
                    if not known:
                        miss.append(pk)
                        continue
                    need_main = refs
                if srow is None or srow[1] is None or need_main:
                    row_q_pks.append(pk)
                    row_q_slots.append(slot)  # type: ignore[arg-type]
                for c in need_main:
                    cell_q.append((pk, c, tc._key(slot, c)))  # type: ignore[arg-type]
                plan.append((pk, srow, need_main))
            # phase 2: one probe + gathers against the main arrays
            row_cl_h: Dict[bytes, int] = {}
            row_pr_h: Dict[bytes, bool] = {}
            if row_q_slots:
                slots_arr = np.asarray(row_q_slots, dtype=np.int64)
                cls = tc.store.gather(tc.row_cl, slots_arr)
                prs = tc.row_present[slots_arr]
                for i, pk in enumerate(row_q_pks):
                    row_cl_h[pk] = int(cls[i])
                    row_pr_h[pk] = bool(prs[i])
            cell_h: Dict[Tuple[bytes, str], Tuple[int, object]] = {}
            bad_pks: set = set()
            if cell_q:
                keys = np.asarray([k for _, _, k in cell_q], np.int64)
                pos = tc.cell_find(keys)
                found = pos >= 0
                vers = tc.store.gather(
                    tc.cell_ver, pos[found]
                ) if found.any() else np.zeros(0, np.int64)
                vi = 0
                for j, (pk, c, _k) in enumerate(cell_q):
                    if found[j]:
                        p = int(pos[j])
                        val = tc.cell_val[p]
                        if val is VAL_UNKNOWN:
                            bad_pks.add(pk)
                        else:
                            cell_h[(pk, c)] = (int(vers[vi]), val)
                        vi += 1
                    else:
                        # row fully known: absent from index == no
                        # clock row for this cell
                        cell_h[(pk, c)] = (ABSENT, None)
            # phase 3: assemble outputs; demote val-unknown pks to miss
            hits = 0
            for pk, srow, need_main in plan:
                if pk in bad_pks:
                    miss.append(pk)
                    continue
                if srow is not None:
                    cl = srow[0]
                    present = srow[1]
                    if present is None:
                        present = row_pr_h[pk]
                else:
                    cl = row_cl_h[pk]
                    cl = None if cl == ABSENT else cl
                    present = row_pr_h[pk]
                if cl is not None:
                    cl_by_pk[pk] = cl
                row_vals: dict = {}
                sh_cells = (
                    self._shadow[info.name].cells.get(pk, {})
                    if srow is not None else {}
                )
                for c in refs:
                    if c in need_main:
                        ver, val = cell_h[(pk, c)]
                        if ver == ABSENT:
                            continue
                    else:
                        e = sh_cells.get(c)
                        if e is None:
                            continue  # full shadow: known absent
                        val, ver = e[0], e[1]
                    clock_by_cell[(pk, c)] = ver
                    row_vals[c] = val
                if present:
                    vals_by_pk[pk] = row_vals
                hits += 1
            self.counters["hits"] += hits
            self.counters["misses"] += len(miss)
            return miss, cl_by_pk, clock_by_cell, vals_by_pk

    def _lookup_fast(self, tc: _TableCache, pks: List[bytes],
                     refs: List[str]) -> Tuple[
        List[bytes], Dict[bytes, int], Dict[Tuple[bytes, str], int],
        Dict[bytes, dict],
    ]:
        """Shadow-free lookup: slot map, one row gather, one cell probe
        + gather, then a single assembly pass.  Semantically identical
        to the general path with an empty shadow (caller holds the
        lock and has validated ``refs`` against the schema)."""
        get = tc.pk_slot.get
        slots_arr = np.fromiter(
            (get(pk, -1) for pk in pks), np.int64, len(pks)
        )
        known = (slots_arr >= 0)
        if known.any():
            known &= tc.row_known[np.maximum(slots_arr, 0)]
        known_l = known.tolist()
        miss = [pk for pk, k in zip(pks, known_l) if not k]
        cl_by_pk: Dict[bytes, int] = {}
        clock_by_cell: Dict[Tuple[bytes, str], int] = {}
        vals_by_pk: Dict[bytes, dict] = {}
        if len(miss) == len(pks):
            self.counters["misses"] += len(miss)
            return miss, cl_by_pk, clock_by_cell, vals_by_pk
        hit_pks = [pk for pk, k in zip(pks, known_l) if k]
        hit_slots = slots_arr[known]
        cls_l = tc.store.gather(tc.row_cl, hit_slots).tolist()
        prs_l = tc.row_present[hit_slots].tolist()
        cl_by_pk.update(
            (pk, c) for pk, c in zip(hit_pks, cls_l) if c != ABSENT
        )
        bad: set = set()
        if refs:
            ords = np.fromiter(
                (tc.cid_ord[c] + 1 for c in refs), np.int64, len(refs)
            )
            keys = (
                hit_slots[:, None] * np.int64(tc.n_cid) + ords[None, :]
            ).ravel()
            pos = tc.cell_find(keys)
            found = pos >= 0
            vers = np.full(len(keys), ABSENT, dtype=np.int64)
            if found.any():
                vers[found] = tc.store.gather(tc.cell_ver, pos[found])
            pos_l = pos.tolist()
            vers_l = vers.tolist()
            cell_val = tc.cell_val
            k = 0
            for j, pk in enumerate(hit_pks):
                row_vals: dict = {}
                for c in refs:
                    p = pos_l[k]
                    if p >= 0:
                        val = cell_val[p]
                        if val is VAL_UNKNOWN:
                            bad.add(pk)
                        else:
                            clock_by_cell[(pk, c)] = vers_l[k]
                            row_vals[c] = val
                    k += 1
                if prs_l[j]:
                    vals_by_pk[pk] = row_vals
        else:
            vals_by_pk.update(
                (pk, {}) for pk, pr in zip(hit_pks, prs_l) if pr
            )
        if bad:
            # a requested value is cached version-only (non-selected
            # column at install time): demote those pks to misses
            for pk in bad:
                miss.append(pk)
                cl_by_pk.pop(pk, None)
                vals_by_pk.pop(pk, None)
                for c in refs:
                    clock_by_cell.pop((pk, c), None)
        self.counters["hits"] += len(hit_pks) - len(bad)
        self.counters["misses"] += len(miss)
        return miss, cl_by_pk, clock_by_cell, vals_by_pk

    def lookup_seed(self, info, pks: List[bytes], ref_cids) -> Optional[
        Tuple[List[bytes], Dict[bytes, int], tuple, set]
    ]:
        """Hot-path lookup returning the seed view in the columnar
        encoder's NATIVE form — parallel ``(pks, cids, col_versions,
        values)`` sequences plus a row-presence set — skipping the
        per-cell dict assembly :meth:`lookup` pays.  Returns ``None``
        when the table carries a live transaction overlay (same-tx
        restage: the caller must take the dict route), and the same
        miss/demotion decisions as :meth:`lookup` otherwise."""
        with self._lock:
            tc = self._table(info)
            refs = [c for c in ref_cids]
            if any(c not in tc.cid_ord for c in refs):
                self.counters["misses"] += len(pks)
                return list(pks), {}, ([], [], [], []), set()
            sh = self._shadow.get(info.name)
            if sh is not None and (sh.rows or sh.cells or sh.staged):
                return None
            n = len(pks)
            get = tc.pk_slot.get
            slots_arr = np.fromiter(
                (get(pk, -1) for pk in pks), np.int64, n
            )
            known = (slots_arr >= 0)
            if known.any():
                known &= tc.row_known[np.maximum(slots_arr, 0)]
            known_l = known.tolist()
            miss = [pk for pk, k in zip(pks, known_l) if not k]
            cl_by_pk: Dict[bytes, int] = {}
            s_pks: list = []
            s_cids: list = []
            s_vers: list = []
            s_vals: list = []
            present: set = set()
            if len(miss) == n:
                self.counters["misses"] += len(miss)
                return miss, cl_by_pk, (
                    s_pks, s_cids, s_vers, s_vals,
                ), present
            hit_pks = [pk for pk, k in zip(pks, known_l) if k]
            hit_slots = slots_arr[known]
            cls_l = tc.store.gather(tc.row_cl, hit_slots).tolist()
            prs_l = tc.row_present[hit_slots].tolist()
            cl_by_pk.update(
                (pk, c) for pk, c in zip(hit_pks, cls_l) if c != ABSENT
            )
            bad: set = set()
            if refs:
                ords = np.fromiter(
                    (tc.cid_ord[c] + 1 for c in refs), np.int64,
                    len(refs),
                )
                keys = (
                    hit_slots[:, None] * np.int64(tc.n_cid)
                    + ords[None, :]
                ).ravel()
                pos = tc.cell_find(keys)
                found = pos >= 0
                vers = np.full(len(keys), ABSENT, dtype=np.int64)
                if found.any():
                    vers[found] = tc.store.gather(
                        tc.cell_ver, pos[found]
                    )
                pos_l = pos.tolist()
                vers_l = vers.tolist()
                cell_val = tc.cell_val
                if found.all() and all(prs_l):
                    # bulk path for the steady-state shape (every cell
                    # cached, every row present): C-level repeats and
                    # one gather comprehension instead of the per-cell
                    # conditional loop
                    vals = [cell_val[p] for p in pos_l]
                    if not any(v is VAL_UNKNOWN for v in vals):
                        s_pks = [pk for pk in hit_pks for _ in refs]
                        s_cids = refs * len(hit_pks)
                        s_vers = vers_l
                        s_vals = vals
                        present = set(hit_pks)
                        self.counters["hits"] += len(hit_pks)
                        self.counters["misses"] += len(miss)
                        return miss, cl_by_pk, (
                            s_pks, s_cids, s_vers, s_vals,
                        ), present
                k = 0
                for j, pk in enumerate(hit_pks):
                    pr = prs_l[j]
                    if pr:
                        present.add(pk)
                    for c in refs:
                        p = pos_l[k]
                        if p >= 0:
                            val = cell_val[p]
                            if val is VAL_UNKNOWN:
                                bad.add(pk)
                            else:
                                s_pks.append(pk)
                                s_cids.append(c)
                                s_vers.append(vers_l[k])
                                # a non-present row's values never
                                # reach the merge (lookup() binds vals
                                # only for present rows)
                                s_vals.append(val if pr else None)
                        k += 1
            else:
                present.update(
                    pk for pk, pr in zip(hit_pks, prs_l) if pr
                )
            if bad:
                for pk in bad:
                    miss.append(pk)
                    cl_by_pk.pop(pk, None)
                    present.discard(pk)
                keep = [
                    i for i, pk in enumerate(s_pks) if pk not in bad
                ]
                if len(keep) != len(s_pks):
                    s_pks = [s_pks[i] for i in keep]
                    s_cids = [s_cids[i] for i in keep]
                    s_vers = [s_vers[i] for i in keep]
                    s_vals = [s_vals[i] for i in keep]
            self.counters["hits"] += len(hit_pks) - len(bad)
            self.counters["misses"] += len(miss)
            return miss, cl_by_pk, (
                s_pks, s_cids, s_vers, s_vals,
            ), present

    # -- write side (shadow only; promoted at commit) -----------------

    def install(self, info, miss_pks: List[bytes],
                cl_by_pk: Dict[bytes, int],
                clock_by_cell: Dict[Tuple[bytes, str], int],
                vals_by_pk: Dict[bytes, dict], ref_cids) -> None:
        """Seed the shadow from a SQLite prefetch of ``miss_pks``.  The
        clock prefetch covers every cid of those pks, so the installed
        rows are *full*; values outside the selected columns are
        :data:`VAL_UNKNOWN` (a later request for them re-misses)."""
        with self._lock:
            tc = self._table(info)
            sel = {c for c in info.data_cols if c in ref_cids}
            sh = self._shadow_for(info.name)
            self._materialize(sh)
            by_pk: Dict[bytes, Dict[str, tuple]] = {}
            for (pk, cid), ver in clock_by_cell.items():
                if cid not in tc.cid_ord:
                    continue  # junk cid in the DB: never cached
                if cid in sel:
                    val = vals_by_pk.get(pk, {}).get(cid)
                else:
                    val = VAL_UNKNOWN
                by_pk.setdefault(pk, {})[cid] = (val, ver)
            for pk in miss_pks:
                sh.rows[pk] = [
                    cl_by_pk.get(pk), pk in vals_by_pk, True,
                ]
                sh.cells[pk] = by_pk.get(pk, {})
            sh.columnar = None

    def stage_states(self, info, states: Dict[bytes, list],
                     cl_by_pk: Dict[bytes, int],
                     vals_by_pk: Dict[bytes, dict],
                     columnar: Optional[tuple] = None) -> None:
        """Overlay the post-flush net state of a merged batch (the
        ``states`` structure ``storage._flush_table_states`` consumes)
        onto the shadow.  ``cl_by_pk`` / ``vals_by_pk`` are the *seed
        views the merge ran against* (cache hits + prefetch overlay) —
        they resolve carried-over cl and row presence.  ``columnar``
        is the merge kernel's ``(plan, decision)`` when it ran — kept
        on the shadow for the vectorized commit promote when this
        stays the only overlay of the transaction.

        Staging is LAZY: the batch is queued on the shadow and only
        folded into the overlay dicts when something actually reads
        them (a same-tx lookup, install or invalidation, or the dict
        promote) — the steady-state commit promotes straight from the
        columnar arrays and never materializes."""
        with self._lock:
            sh = self._shadow_for(info.name)
            fresh = not sh.rows and not sh.cells and not sh.staged
            sh.staged.append((info, states, cl_by_pk, vals_by_pk))
            if (
                fresh and columnar is not None
                and not bool(columnar[1].gen.any())
            ):
                sh.columnar = (
                    columnar[0], columnar[1], cl_by_pk, vals_by_pk,
                )
            else:
                sh.columnar = None

    def _materialize(self, sh: _TableShadow) -> None:
        """Fold queued stage batches into the overlay dicts, in stage
        order.  Caller holds the lock."""
        if not sh.staged:
            return
        staged, sh.staged = sh.staged, []
        for info, states, cl_by_pk, vals_by_pk in staged:
            self._stage_into(sh, info, states, cl_by_pk, vals_by_pk)

    def _stage_into(self, sh: _TableShadow, info,
                    states: Dict[bytes, list],
                    cl_by_pk: Dict[bytes, int], vals_by_pk) -> None:
        CL, CLROW, GEN, ALIVE, ENSURE, CELLS = range(6)
        for pk, st in states.items():
            clrow = st[CLROW]
            if clrow is not None:
                cl = clrow[1]
            elif st[CL] is not None:
                cl = st[CL]
            else:
                cl = cl_by_pk.get(pk)  # None == no cl row
            # shadow cells share the merge cell layout, so the net
            # state's dict is borrowed as-is (never mutated here)
            cells = st[CELLS]
            if st[GEN]:
                # generation: row + every clock row replaced
                present = bool(st[ALIVE]) and bool(info.data_cols)
                sh.rows[pk] = [cl, present, True]
                sh.cells[pk] = cells
                continue
            prev = sh.rows.get(pk)
            if prev is not None:
                prev[0] = cl
                if st[ENSURE] and info.data_cols:
                    prev[1] = True
                prev_cells = sh.cells.get(pk)
                if prev_cells:
                    # fresh dict: the borrowed net-state dict is
                    # also queued for the write-behind flush
                    sh.cells[pk] = {**prev_cells, **cells}
                else:
                    sh.cells[pk] = cells
            else:
                # pk was a main-cache hit: partial overlay; row
                # presence inherits unless this batch ensured it
                present: Optional[bool]
                if pk in vals_by_pk or st[ENSURE]:
                    present = bool(info.data_cols)
                else:
                    present = None
                sh.rows[pk] = [cl, present, False]
                sh.cells[pk] = cells

    # -- transaction boundary -----------------------------------------

    def abort_tx(self) -> None:
        with self._lock:
            self._shadow = {}

    def commit_tx(self) -> None:
        """Promote the shadow into the main arrays: retire + reallocate
        slots for full rows, update cells in place for partial ones.
        Capacity pressure clears the table (evictions) and retries the
        promote once against the fresh arrays."""
        with self._lock:
            shadow, self._shadow = self._shadow, {}
            for name, sh in shadow.items():
                tc = self._tables.get(name)
                if tc is None:
                    continue
                self._promote_table(tc, sh)

    def _promote_table(self, tc: _TableCache, sh: _TableShadow) -> None:
        if sh.columnar is not None and self._promote_columnar(tc, sh):
            return
        self._materialize(sh)
        for attempt in (0, 1):
            n_rows = len(sh.rows)
            n_cells = sum(len(c) for c in sh.cells.values())
            if not tc.room_for_rows(n_rows) or \
                    not tc.room_for_cells(n_cells):
                if attempt:
                    return  # shadow alone exceeds capacity: skip cache
                self._evict_table(tc)
                continue
            break
        tc.ensure_row_capacity(len(sh.rows))
        tc.ensure_cell_capacity(
            sum(len(c) for c in sh.cells.values())
        )
        row_slots: List[int] = []
        row_cls: List[int] = []
        pres_slots: List[int] = []
        pres_vals: List[bool] = []
        known_slots: List[int] = []
        cell_entries: List[Tuple[int, int, object]] = []
        pk_slot_get = tc.pk_slot.get
        row_known = tc.row_known
        cid_ord = tc.cid_ord
        n_cid = tc.n_cid
        sh_cells_get = sh.cells.get
        for pk, (cl, present, full) in sh.rows.items():
            slot = pk_slot_get(pk)
            if full:
                # full knowledge replaces the row wholesale: a fresh
                # slot orphans any stale cells keyed to the old one
                if slot is not None:
                    tc.retire(pk)
                if not tc.room_for_rows(1):
                    return  # capacity raced the retire loop: give up
                slot = tc.alloc_slot(pk)
                pres_slots.append(slot)
                pres_vals.append(bool(present))
                known_slots.append(slot)
            else:
                if slot is None or not row_known[slot]:
                    continue  # partial overlay with no base: uncacheable
                if present is not None:
                    pres_slots.append(slot)
                    pres_vals.append(bool(present))
            row_slots.append(slot)
            row_cls.append(ABSENT if cl is None else int(cl))
            cells = sh_cells_get(pk)
            if cells:
                base = slot * n_cid + 1
                for cid, cell in cells.items():
                    o = cid_ord.get(cid)
                    if o is not None:
                        cell_entries.append(
                            (base + o, int(cell[1]), cell[0])
                        )
        # scalar boolean writes batched into two fancy-index stores
        if known_slots:
            tc.row_known[np.asarray(known_slots, dtype=np.int64)] = True
        if pres_slots:
            tc.row_present[np.asarray(pres_slots, dtype=np.int64)] = \
                np.asarray(pres_vals, dtype=bool)
        if row_slots:
            tc.row_cl = tc.store.set(
                tc.row_cl,
                np.asarray(row_slots, dtype=np.int64),
                np.asarray(row_cls, dtype=np.int64),
            )
        tc.cell_put_batch(cell_entries)

    def _promote_columnar(self, tc: _TableCache,
                          sh: _TableShadow) -> bool:
        """Steady-state promote: scatter the merge kernel's winner
        arrays straight into the device arrays.  Valid only for the
        shape ``stage_states`` vetted — one no-generation batch whose
        every pk was a main-cache hit — so every row is a partial
        in-place update of a known slot.  Returns False (no mutation
        done) to hand anything else to the dict promote."""
        plan, dec, cl_by_pk, vals_by_pk = sh.columnar  # type: ignore
        pk_values = plan.pk_values
        n = len(pk_values)
        if n == 0:
            return True
        get = tc.pk_slot.get
        slots = np.fromiter(
            (get(pk, -1) for pk in pk_values), np.int64, n
        )
        if (slots < 0).any() or not tc.row_known[slots].all():
            return False  # a pk missed the cache after all
        cids = plan.cid_values
        ord_map = np.fromiter(
            (tc.cid_ord.get(c, -1) for c in cids), np.int64, len(cids)
        )
        if (ord_map < 0).any():
            return False  # cid outside the cached ordinal space
        # n_cid pads to >= 1; phantom pad columns never hold winners,
        # so restrict the scatter to the real cid columns
        win = np.asarray(dec.winner_idx).reshape(
            n, plan.n_cid
        )[:, :len(cids)]
        wmask = win >= 0
        keys = (
            slots[:, None] * tc.n_cid + ord_map[None, :] + 1
        )[wmask]
        widx = win[wmask]
        pos = tc.cell_find(keys)
        n_new = int((pos < 0).sum())
        if n_new:
            if not tc.room_for_cells(n_new):
                return False  # capacity pressure: dict path evicts
            tc.ensure_cell_capacity(n_new)
            pos = tc.cell_find(keys)  # capacity growth rehashes
            mask = tc.cap_cells - 1
            keys_arr = tc.cell_keys
            for j in np.nonzero(pos < 0)[0].tolist():
                key = int(keys[j])
                i = tc._hash_scalar(key)
                while True:
                    k = int(keys_arr[i])
                    if k == key:
                        break
                    if k == 0:
                        keys_arr[i] = key
                        tc.cells_used += 1
                        break
                    i = (i + 1) & mask
                pos[j] = i
        if len(pos):
            tc.cell_ver = tc.store.set(
                tc.cell_ver, pos,
                np.asarray(plan.vers, dtype=np.int64)[widx],
            )
            vals = plan.vals
            cell_val = tc.cell_val
            for p, w in zip(pos.tolist(), widx.tolist()):
                cell_val[p] = vals[w]
        # rows: cl carries over as ABSENT unless the seed view had one
        # (mirrors stage_states' cl fallback for no-generation rows)
        has_cl = np.fromiter(
            (pk in cl_by_pk for pk in pk_values), bool, n
        )
        tc.row_cl = tc.store.set(
            tc.row_cl, slots,
            np.where(has_cl, np.asarray(dec.final_cl), ABSENT),
        )
        pres = np.asarray(dec.ensure, dtype=bool) | np.fromiter(
            (pk in vals_by_pk for pk in pk_values), bool, n
        )
        if pres.any():
            tc.row_present[slots[pres]] = True
        return True

    # -- invalidation -------------------------------------------------

    def _count_invalidation(self, reason: str, n: int) -> None:
        if n:
            self.invalidations[reason] = \
                self.invalidations.get(reason, 0.0) + n

    def invalidate_pks(self, table: str, pks, reason: str = "local_write") -> None:
        """Forget specific rows (small-path applies, targeted local
        writes).  Always safe: forgetting only forces a re-prefetch."""
        with self._lock:
            n = 0
            tc = self._tables.get(table)
            sh = self._shadow.get(table)
            if sh is not None:
                self._materialize(sh)
            for pk in pks:
                if tc is not None and tc.retire(pk):
                    n += 1
                if sh is not None:
                    if sh.rows.pop(pk, None) is not None:
                        n += 1
                    sh.cells.pop(pk, None)
            if sh is not None:
                sh.columnar = None
            self._count_invalidation(reason, n)

    def invalidate_table(self, table: str, reason: str = "schema") -> None:
        """Drop one table's cache wholesale (schema migration via
        ``as_crr`` changes the cid ordinal space)."""
        with self._lock:
            tc = self._tables.pop(table, None)
            sh = self._shadow.pop(table, None)
            n = tc.live_entries() if tc is not None else 0
            if sh is not None:
                self._materialize(sh)
                n += len(sh.rows)
            self._count_invalidation(reason, n)

    def invalidate_all(self, reason: str) -> None:
        """Snapshot install / compaction floor / local write commit:
        anything that rewrites CRR state outside the staged apply path.
        Caller holds the storage write lock (ordering contract)."""
        with self._lock:
            for sh in self._shadow.values():
                self._materialize(sh)
            n = sum(len(sh.rows) for sh in self._shadow.values())
            for tc in self._tables.values():
                n += tc.live_entries()
            self._tables = {}
            self._shadow = {}
            self._count_invalidation(reason, n)
