"""Packed CRDT cell keys.

The cr-sqlite merge rule (doc/crdts.md:13-16; LWW with causal length) is a
lexicographic max over ``(cl, col_version, value)`` per (row, column) cell:

1. larger causal length wins (row delete/resurrect dominates cell history;
   even cl = deleted, odd = live),
2. then larger ``col_version`` (per-cell lamport clock),
3. then the larger value ("biggest value wins" tie-break).

A lexicographic max is not expressible as independent per-field scatter-max,
so the three fields are packed into ONE integer word whose numeric order
equals the lexicographic order.  Then every merge — pairwise, segment, or
scatter — is a plain ``max``, which XLA turns into a combiner on the VPU and
into scatter-max for message delivery.

The default codec packs into int32 (TPU-native lane width); an int64 codec
is available when a simulation needs deeper version/value spaces.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class KeyCodec:
    """Bit layout for packed (cl, col_version, value_rank) keys.

    value_rank must be a non-negative int that preserves the desired value
    order; host code maps real SQLite values to ranks (the sim uses small
    ints directly).
    """

    cl_bits: int = 4
    ver_bits: int = 13
    val_bits: int = 14

    def __post_init__(self):
        total = self.cl_bits + self.ver_bits + self.val_bits
        if total > 62:
            raise ValueError(f"key layout needs {total} bits; max is 62")

    @property
    def total_bits(self) -> int:
        return self.cl_bits + self.ver_bits + self.val_bits

    @property
    def dtype(self):
        return jnp.int32 if self.total_bits <= 31 else jnp.int64

    @property
    def max_cl(self) -> int:
        return (1 << self.cl_bits) - 1

    @property
    def max_ver(self) -> int:
        return (1 << self.ver_bits) - 1

    @property
    def max_val(self) -> int:
        return (1 << self.val_bits) - 1

    def _check_dtype(self):
        if self.dtype == jnp.int64 and not jax.config.jax_enable_x64:
            raise RuntimeError(
                f"KeyCodec with {self.total_bits} bits needs int64 keys: "
                "enable jax_enable_x64 (or use jax.experimental.enable_x64)"
            )

    def pack(self, cl, col_version, value_rank):
        """Pack field arrays into one key array (fields must be in range)."""
        self._check_dtype()
        cl = jnp.asarray(cl, self.dtype)
        ver = jnp.asarray(col_version, self.dtype)
        val = jnp.asarray(value_rank, self.dtype)
        return (
            (cl << (self.ver_bits + self.val_bits))
            | (ver << self.val_bits)
            | val
        )

    def unpack(self, key):
        self._check_dtype()
        key = jnp.asarray(key, self.dtype)
        val = key & self.max_val
        ver = (key >> self.val_bits) & self.max_ver
        cl = (key >> (self.val_bits + self.ver_bits)) & self.max_cl
        return cl, ver, val

    def is_live(self, key):
        """Row live iff causal length is odd (doc/crdts.md: cl parity)."""
        cl, _, _ = self.unpack(key)
        return (cl & 1) == 1


DEFAULT_CODEC = KeyCodec()

# Deeper spaces: 16-bit cl, 24-bit versions, 22-bit values.
WIDE_CODEC = KeyCodec(cl_bits=16, ver_bits=24, val_bits=22)
