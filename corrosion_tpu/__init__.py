"""corrosion_tpu — a TPU-native re-design of Corrosion (gossip-based,
eventually-consistent distributed SQLite).

The framework has two halves:

* **The TPU simulator** (``corrosion_tpu.sim``, ``corrosion_tpu.models``,
  ``corrosion_tpu.ops``): SWIM membership, epidemic broadcast fanout and
  anti-entropy sync re-expressed as vmapped / pjit'd graph-propagation
  kernels over a sharded node dimension, with cr-sqlite's LWW /
  causal-length CRDT merges as per-row packed-key max reductions.  This is
  the path behind the north-star metric (p99 convergence time + msgs/node
  vs cluster size N; see BASELINE.md).

* **The host agent** (``corrosion_tpu.agent``): a real, runnable
  distributed-SQLite agent — our own implementation of the cr-sqlite CRDT
  semantics over stock sqlite3, SWIM membership, broadcast + sync over
  loopback/UDP, HTTP API, reactive subscriptions, CLI and devcluster
  tooling — mirroring the reference's serving surface
  (see SURVEY.md §1 layer map).

Reference parity notes cite files in the upstream Rust implementation as
``crates/...:line``.
"""

__version__ = "0.1.0"
