"""Python client for the corrosion_tpu HTTP API.

Parity: ``crates/corro-client`` — ``CorrosionApiClient`` (typed queries,
execute/transactions, schema migration) and ``sub.rs``'s
``SubscriptionStream`` (NDJSON event stream with observed-change-id gap
detection and automatic re-attach via ``from=``).
"""

from __future__ import annotations

import http.client
import json
import threading
import time
import urllib.error
import urllib.request
from typing import Iterable, Iterator, List, Optional, Sequence, Tuple


class ClientError(Exception):
    def __init__(self, status: int, message: str):
        super().__init__(f"HTTP {status}: {message}")
        self.status = status


class _ConnPool:
    """Keep-alive HTTP/1.1 connection pool (the stand-in for the
    reference's pooled hyper client, ``corro-client/src/lib.rs:51-98``).
    Pool reuse is for idempotent (GET/HEAD) request/response calls
    ONLY — table_stats/members and other metadata GETs reuse a warm
    TCP connection instead of a fresh handshake per call.  Everything
    else bypasses it: non-idempotent calls (transactions, migrations)
    must not risk an idle-closed keep-alive — they are never
    replayed — so they go over ``fresh()`` connections, and the
    streaming endpoints (queries, subscriptions, updates) hold their
    connection open via ``_request_stream``."""

    def __init__(self, host: str, port: int, timeout: float,
                 size: int = 4):
        self.host, self.port, self.timeout = host, port, timeout
        self.size = size
        self._free: List[http.client.HTTPConnection] = []
        self._lock = threading.Lock()

    def acquire(self) -> Tuple[http.client.HTTPConnection, bool]:
        """(connection, was_pooled) — was_pooled means a stale
        keep-alive is possible and the caller should retry once on a
        transport error."""
        with self._lock:
            if self._free:
                return self._free.pop(), True
        return self.fresh(), False

    def fresh(self) -> http.client.HTTPConnection:
        """A brand-new connection, never from the pool: the transport
        for non-idempotent requests, where an idle-closed keep-alive
        would fail a request that must not be replayed."""
        return http.client.HTTPConnection(
            self.host, self.port, timeout=self.timeout
        )

    def release(self, conn: http.client.HTTPConnection,
                reusable: bool) -> None:
        if reusable:
            with self._lock:
                if len(self._free) < self.size:
                    self._free.append(conn)
                    return
        try:
            conn.close()
        except Exception:
            pass

    def close(self) -> None:
        with self._lock:
            conns, self._free = self._free, []
        for c in conns:
            try:
                c.close()
            except Exception:
                pass


class SubscriptionStream:
    """Iterate subscription events; transparently re-attaches on drop.

    Gap detection: every ``change`` event carries a change id; if the
    stream drops, we re-attach with ``from=<last observed>`` so no event
    is lost or duplicated (``corro-client/src/sub.rs`` behavior).
    """

    def __init__(self, client: "CorrosionApiClient", query_id: str,
                 initial_resp, max_retries: int = 10):
        self.client = client
        self.id = query_id
        self._resp = initial_resp
        self.last_change_id: Optional[int] = None
        self.max_retries = max_retries

    def __iter__(self) -> Iterator[dict]:
        retries = 0
        while True:
            try:
                for raw in self._resp:
                    event = json.loads(raw)
                    if "change" in event:
                        cid = event["change"][3]
                        if (
                            self.last_change_id is not None
                            and cid > self.last_change_id + 1
                        ):
                            # missed events: force a re-attach from the
                            # last id we actually observed
                            raise ConnectionResetError("change id gap")
                        self.last_change_id = cid
                    retries = 0
                    yield event
                return
            except (ConnectionError, TimeoutError, OSError):
                retries += 1
                if retries > self.max_retries:
                    raise
                try:
                    self._resp.close()  # don't leak the dropped connection
                except Exception:
                    pass
                time.sleep(min(0.1 * 2**retries, 5.0))
                self._resp = self.client._subscribe_raw(
                    sub_id=self.id, from_change_id=self.last_change_id
                )


class CorrosionApiClient:
    def __init__(self, addr: Tuple[str, int], token: Optional[str] = None,
                 timeout: float = 30.0):
        self.addr = tuple(addr)
        self.base = f"http://{addr[0]}:{addr[1]}"
        self.token = token
        self.timeout = timeout
        self._pool = _ConnPool(addr[0], int(addr[1]), timeout)

    def close(self) -> None:
        self._pool.close()

    # -- plumbing --------------------------------------------------------

    def _headers(self) -> dict:
        h = {"Content-Type": "application/json"}
        if self.token:
            h["Authorization"] = f"Bearer {self.token}"
        return h

    def _request(self, path: str, body=None, method: Optional[str] = None,
                 stream: bool = False):
        if stream:
            return self._request_stream(path, body, method)
        data = json.dumps(body).encode() if body is not None else None
        meth = method or ("POST" if body is not None else "GET")
        # the pool serves IDEMPOTENT requests only: a pooled keep-alive
        # connection the server closed while idle fails at request
        # time, and a GET/HEAD simply retries once on a fresh socket.
        # A POST (e.g. /v1/transactions) is NEVER re-sent — the request
        # may have been applied before the connection died and a retry
        # would double-apply (the same rule _with_failover documents) —
        # so non-idempotent methods BYPASS the pool entirely: a fresh
        # connection both ways (no stale-socket first attempt, no
        # release back for reuse)
        idempotent = meth in ("GET", "HEAD")
        for attempt in (0, 1):
            if idempotent and attempt == 0:
                conn, was_pooled = self._pool.acquire()
            else:
                # non-idempotent methods always; idempotent RETRIES
                # too — re-acquiring could pop a second stale pooled
                # keep-alive and fail a healthy server twice
                conn, was_pooled = self._pool.fresh(), False
            try:
                conn.request(meth, path, body=data,
                             headers=self._headers())
                resp = conn.getresponse()
                payload = resp.read()
                reusable = idempotent and not resp.will_close
            except (http.client.HTTPException, OSError) as e:
                self._pool.release(conn, reusable=False)
                if was_pooled and attempt == 0:
                    continue  # stale keep-alive: one fresh retry
                raise ClientError(
                    0, f"cannot reach {self.base}: {e}"
                ) from None
            self._pool.release(conn, reusable)
            if resp.status >= 400:
                detail = payload.decode(errors="replace")
                try:
                    detail = json.loads(detail).get("error", detail)
                except (ValueError, AttributeError):
                    pass
                raise ClientError(resp.status, detail)
            return json.loads(payload or b"null")

    def _request_stream(self, path: str, body=None,
                        method: Optional[str] = None):
        req = urllib.request.Request(
            self.base + path,
            data=json.dumps(body).encode() if body is not None else None,
            method=method or ("POST" if body is not None else "GET"),
        )
        for k, v in self._headers().items():
            req.add_header(k, v)
        try:
            return urllib.request.urlopen(req, timeout=self.timeout)
        except urllib.error.HTTPError as e:
            detail = e.read().decode(errors="replace")
            try:
                detail = json.loads(detail).get("error", detail)
            except (ValueError, AttributeError):
                pass
            raise ClientError(e.code, detail) from None
        except urllib.error.URLError as e:
            raise ClientError(0, f"cannot reach {self.base}: {e.reason}") from None

    # -- API -------------------------------------------------------------

    def execute(self, statements: Sequence) -> dict:
        """POST /v1/transactions."""
        return self._request("/v1/transactions", list(statements))

    def query(self, statement) -> Tuple[List[str], List[list]]:
        """POST /v1/queries -> (columns, rows)."""
        resp = self._request("/v1/queries", statement, stream=True)
        cols: List[str] = []
        rows: List[list] = []
        with resp:
            for raw in resp:
                ev = json.loads(raw)
                if "columns" in ev:
                    cols = ev["columns"]
                elif "row" in ev:
                    rows.append(ev["row"][1])
                elif "error" in ev:
                    raise ClientError(500, ev["error"])
        return cols, rows

    def migrate(self, schema_sql) -> dict:
        """POST /v1/migrations."""
        body = schema_sql if isinstance(schema_sql, list) else [schema_sql]
        return self._request("/v1/migrations", body)

    def schema_from_paths(self, paths: Iterable[str]) -> dict:
        sqls = []
        for p in paths:
            with open(p) as f:
                sqls.append(f.read())
        return self.migrate(sqls)

    def table_stats(self) -> dict:
        return self._request("/v1/table_stats")

    def members(self) -> dict:
        return self._request("/v1/members")

    def subscribe(self, statement) -> SubscriptionStream:
        """POST /v1/subscriptions -> resumable event stream."""
        resp = self._request("/v1/subscriptions", statement, stream=True)
        query_id = resp.headers.get("x-corro-query-id", "")
        return SubscriptionStream(self, query_id, resp)

    def subscription(self, sub_id: str,
                     from_change_id: Optional[int] = None) -> SubscriptionStream:
        """GET /v1/subscriptions/:id — re-attach to an existing sub."""
        resp = self._subscribe_raw(sub_id, from_change_id)
        stream = SubscriptionStream(self, sub_id, resp)
        stream.last_change_id = from_change_id
        return stream

    def _subscribe_raw(self, sub_id: str, from_change_id: Optional[int]):
        path = f"/v1/subscriptions/{sub_id}"
        if from_change_id is not None:
            path += f"?from={from_change_id}"
        return self._request(path, stream=True)

    def updates(self, table: str) -> Iterator[dict]:
        """GET /v1/updates/:table — raw per-table change stream."""
        resp = self._request(f"/v1/updates/{table}", stream=True)
        with resp:
            for raw in resp:
                yield json.loads(raw)


class PooledApiClient:
    """DNS-resolving, failover-aware API client.

    Parity with ``CorrosionPooledClient`` (corro-client/src/lib.rs, the
    hickory-resolving pooled client): a hostname is resolved to its full
    address set, requests go to the current pick, a connection-level
    failure rotates to the next address and marks the bad one, and the
    name is re-resolved once `ttl` expires or every address has failed.
    """

    def __init__(self, host: str, port: int, token: Optional[str] = None,
                 timeout: float = 30.0, ttl: float = 30.0,
                 resolver=None):
        self.host, self.port = host, port
        self.token, self.timeout, self.ttl = token, timeout, ttl
        self._resolve = resolver or self._dns_resolve
        self._addrs: List[str] = []
        self._bad: set = set()
        self._pick = 0
        self._resolved_at = 0.0
        # addr -> cached client (keep-alive pools survive across calls)
        self._clients: dict = {}

    def _dns_resolve(self, host: str) -> List[str]:
        import socket

        infos = socket.getaddrinfo(host, self.port, type=socket.SOCK_STREAM)
        # stable order so rotation is deterministic across re-resolves
        return sorted({i[4][0] for i in infos})

    def _addresses(self) -> List[str]:
        now = time.time()
        stale = now - self._resolved_at > self.ttl
        if not self._addrs or stale or self._bad >= set(self._addrs):
            self._addrs = list(self._resolve(self.host))
            self._resolved_at = now
            self._bad.clear()
            if not self._addrs:
                raise ClientError(0, f"no addresses for {self.host}")
        return self._addrs

    def client(self) -> CorrosionApiClient:
        """The client for the currently-picked healthy address.
        ``_addresses()`` re-resolves (and clears the bad set) whenever
        every known address has been marked bad, so the scan below
        always finds a usable one.  Clients are CACHED per address so
        their keep-alive pools actually get reused across calls (a
        fresh client per call would open a fresh connection every
        time)."""
        addrs = self._addresses()
        for _ in range(len(addrs)):
            addr = addrs[self._pick % len(addrs)]
            if addr not in self._bad:
                cached = self._clients.get(addr)
                if cached is None:
                    cached = CorrosionApiClient(
                        (addr, self.port), token=self.token,
                        timeout=self.timeout,
                    )
                    self._clients[addr] = cached
                    if len(self._clients) > 16:
                        # evict the oldest cached client (FIFO)
                        old = next(iter(self._clients))
                        if old != addr:
                            self._clients.pop(old).close()
                return cached
            self._pick += 1
        raise AssertionError("unreachable: _addresses() clears full bad sets")

    # connection-level failures that mark an address bad and rotate;
    # mid-stream deaths surface as raw socket/http errors, not ClientError
    _FAILOVER_ERRORS = (ClientError, OSError, TimeoutError,
                        http.client.HTTPException)

    def _with_failover(self, fn, retry: bool = True):
        """Run ``fn`` against the picked address.

        Connection-level failures always mark the address bad and rotate
        the pick; with ``retry=False`` the error is then surfaced to the
        caller instead of re-running ``fn`` elsewhere. Non-idempotent
        calls (execute) must use ``retry=False``: a TimeoutError/OSError
        can fire *after* the server received and applied the transaction,
        and re-sending would apply it twice. The reference pooled client
        never retries either — it only rotates for the next call
        (corro-client/src/lib.rs handle_error).
        """
        last: Optional[Exception] = None
        for _ in range(max(2, len(self._addresses()) + 1)):
            c = self.client()
            try:
                return fn(c)
            except self._FAILOVER_ERRORS as e:
                if isinstance(e, ClientError) and e.status != 0:
                    raise  # an HTTP answer: the node is up
                host = c.addr[0]
                self._bad.add(host)
                self._pick += 1
                last = e
                if not retry:
                    raise
        raise last  # type: ignore[misc]

    def execute(self, statements: Sequence) -> dict:
        # Not idempotent: never auto-retried (see _with_failover).
        return self._with_failover(lambda c: c.execute(statements), retry=False)

    def query(self, statement) -> Tuple[List[str], List[list]]:
        return self._with_failover(lambda c: c.query(statement))

    def table_stats(self) -> dict:
        return self._with_failover(lambda c: c.table_stats())

    def members(self) -> dict:
        return self._with_failover(lambda c: c.members())
