"""Explicit-collective gossip fabric: broadcast delivery under shard_map.

The reference moves changesets between nodes over QUIC; the TPU-native
fabric is the ICI/DCN mesh.  Where ``__graft_entry__``'s dryrun lets
XLA infer collectives from `NamedSharding` annotations, this module
spells the fabric out: node state lives sharded over the mesh's
``nodes`` axis and one gossip tick is

  1. every shard draws the SAME per-column inverse permutations from
     the shared tick key (replicated compute — cheap integers), so all
     shards agree on each receiver's sender;
  2. an ``all_gather`` over ``nodes`` moves every shard's sender rows
     and activity mask across the fabric (the ICI stand-in for the
     reference's QUIC uni-streams);
  3. each shard's receivers gather from their column senders out of the
     gathered global state (delivery is local after the gather — no
     scatter anywhere, mirroring the permutation-fanout kernel).

The result is bitwise identical to the unsharded
:func:`corrosion_tpu.models.broadcast.broadcast_step` for the same key
(pinned by tests/test_sharding.py on the virtual 8-device CPU mesh), so
the sharded fabric can replace the single-chip kernel without touching
protocol semantics.  Two fabrics share that contract:

* :func:`sharded_broadcast_step` — one ``all_gather`` per tick,
  O(N·R) per shard: the right first fabric (early epidemic ticks
  genuinely are all-to-all dissemination);
* :func:`sharded_broadcast_step_ring` — the destination-sorted
  fabric: each shard ships each destination only the ACTIVE sender
  rows that destination's receivers drew this tick, over one
  ``all_to_all`` (XLA's ICI ring schedule).  Sparse/late ticks move
  almost nothing; a static slot cap bounds volume at O(D·cap·R) with
  an exact overflow count when demand exceeds it.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from corrosion_tpu.models.broadcast import BroadcastParams
from corrosion_tpu.ops.merge import merge_keys

def gather_nodes(x_l, axis: int = 0):
    """Reassemble a node-sharded leaf: tiled ``all_gather`` over the
    mesh's ``nodes`` axis, concatenating the shard blocks back along
    ``axis`` in device order (the inverse of the P(..., "nodes", ...)
    row split).  Shared by the broadcast fabrics here and the sharded
    exact rejection sampler (sim/calibrate.py)."""
    return jax.lax.all_gather(x_l, "nodes", axis=axis, tiled=True)


def _shard_map(f, mesh, in_specs, out_specs):
    """shard_map across jax versions: the promoted jax.shard_map (>=0.8,
    check_vma kwarg) or the experimental one (check_rep kwarg).  Checks
    are off either way: the body uses axis_index, so outputs are
    legitimately device-varying."""
    try:
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False,
        )
    except AttributeError:  # pragma: no cover - older jax
        from jax.experimental.shard_map import shard_map

        return shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=False,
        )


def sharded_broadcast_step(mesh, params: BroadcastParams):
    """Build a jitted per-shard gossip tick over ``mesh``'s ``nodes``
    axis.  Returns ``step(rows, tx, msgs, key) -> (rows', tx', msgs')``
    operating on GLOBAL arrays sharded [nodes] on their leading node
    axis (rows: [N, R]; tx/msgs: [N])."""
    n, k = params.n_nodes, params.fanout
    d_shards = mesh.shape["nodes"]
    if n % d_shards != 0:
        raise ValueError(f"n_nodes {n} must divide over {d_shards} shards")
    n_local = n // d_shards

    from corrosion_tpu.models.broadcast import _perm_senders

    u = params.universe or n

    def local_step(rows_l, tx_l, msgs_l, key):
        # (1) replicated permutation draw — same key everywhere, so
        # every shard agrees on each receiver's sender this tick
        # (mirrors _deliver_perm's column structure bitwise)
        key_t, key_l = jax.random.split(key)

        # (2) the fabric: move sender rows + activity across ICI
        rows_all = gather_nodes(rows_l)
        active_all = gather_nodes(tx_l > 0)

        if params.loss > 0.0:
            drop = jax.random.uniform(key_l, (n, k)) < params.loss

        # (3) local delivery: each of MY receivers gathers from its
        # column sender out of the gathered global state
        shard = jax.lax.axis_index("nodes")
        lo = shard * n_local
        my_idx = lo + jnp.arange(n_local, dtype=jnp.int32)
        new_rows_l = rows_l
        for j in range(k):
            sender_all = _perm_senders(
                key_t, j, n, u, j < params.fanout_ring0, params.ring0_size
            )  # [N] receiver->sender, identical on every shard
            sender = sender_all[my_idx]  # my receivers' senders
            valid = active_all[sender]
            if params.loss > 0.0:
                valid &= ~drop[my_idx, j]
            new_rows_l = merge_keys(
                new_rows_l,
                jnp.where(valid[:, None], rows_all[sender], rows_l),
            )

        # bookkeeping is local: decay my senders, refresh my learners
        learned_l = jnp.any(new_rows_l != rows_l, axis=1)
        active_l = tx_l > 0
        new_tx_l = jnp.where(active_l, tx_l - 1, tx_l)
        new_tx_l = jnp.where(learned_l, params.max_transmissions, new_tx_l)
        new_msgs_l = msgs_l + jnp.where(active_l, k, 0).astype(msgs_l.dtype)
        return new_rows_l, new_tx_l, new_msgs_l

    node_sharded = P("nodes")
    return jax.jit(
        _shard_map(
            local_step,
            mesh=mesh,
            in_specs=(node_sharded, node_sharded, node_sharded, P()),
            out_specs=(node_sharded, node_sharded, node_sharded),
        )
    )


def sharded_broadcast_step_ring(mesh, params: BroadcastParams,
                                slot_cap: int | None = None):
    """The destination-sorted fabric the all_gather docstring promised:
    instead of moving EVERY shard's full state every tick (O(N·R) per
    shard), each shard sends each destination shard only the sender
    rows that destination's receivers actually need this tick —
    deduplicated, and only for ACTIVE senders, so late-epidemic ticks
    (most senders quiescent under backoff/decay) move almost nothing.
    Routing is one ``all_to_all`` over the ``nodes`` axis, which XLA
    schedules as the ICI ring (the ppermute-ring realization of this
    plan); volume is O(D·cap·R) per shard per tick.

    ``slot_cap``: static per-destination slot budget.  Default
    ``n_local`` makes the fabric provably lossless (a destination can
    never need more distinct rows of mine than I have) and BITWISE
    equal to :func:`sharded_broadcast_step` / the single-chip kernel
    (pinned by tests/test_sharding.py).  A smaller cap trades fabric
    volume for possible drops on dense ticks — the returned
    ``overflow`` count (global, per tick) says exactly how many needed
    rows didn't fit; a dropped row is a lost delivery, the same fault
    class the protocol already heals via retransmission + anti-entropy.
    Sizing guide: expected demand per destination is ~``k·n_local/D``
    distinct rows on a fully-active tick, so ``cap = 4·k·n_local/D``
    gives ~4x headroom and cuts steady-state fabric volume by ~``D/4k``
    vs all_gather at large D.

    Returns ``step(rows, tx, msgs, key) -> (rows', tx', msgs',
    overflow)`` on GLOBAL arrays sharded [nodes] on their leading axis.
    """
    n, k = params.n_nodes, params.fanout
    d_shards = mesh.shape["nodes"]
    if n % d_shards != 0:
        raise ValueError(f"n_nodes {n} must divide over {d_shards} shards")
    n_local = n // d_shards
    cap = n_local if slot_cap is None else min(slot_cap, n_local)

    from corrosion_tpu.models.broadcast import _perm_senders

    u = params.universe or n

    def local_step(rows_l, tx_l, msgs_l, key):
        r_width = rows_l.shape[-1]
        key_t, key_l = jax.random.split(key)
        shard = jax.lax.axis_index("nodes")
        my_base = shard * n_local
        my_idx = my_base + jnp.arange(n_local, dtype=jnp.int32)
        active_l = tx_l > 0

        # (1) replicated sender maps (identical on every shard)
        senders = [
            _perm_senders(
                key_t, j, n, u, j < params.fanout_ring0, params.ring0_size
            )
            for j in range(k)
        ]

        # (2) destination-sorted demand: needed[d, i] = does shard d
        # need MY local row i this tick (some receiver of d draws it)?
        dest_of = (
            jnp.arange(n, dtype=jnp.int32) // n_local
        )  # receiver -> shard
        needed = jnp.zeros((d_shards, n_local), bool)
        for s_all in senders:
            mine = s_all // n_local == shard
            slocal = jnp.where(mine, s_all % n_local, n_local)
            needed = needed.at[dest_of, slocal].max(mine, mode="drop")
        needed &= active_l[None, :]  # inactive senders deliver nothing

        # (3) pack per destination: the first `cap` needed rows, their
        # global ids alongside (-1 pads); count what didn't fit
        scores = jnp.where(
            needed, jnp.arange(n_local, dtype=jnp.int32)[None, :],
            jnp.int32(n_local),
        )
        picked = jnp.sort(scores, axis=1)[:, :cap]  # [D, cap]
        valid = picked < n_local
        overflow_l = (
            jnp.sum(needed, axis=1) - jnp.sum(valid, axis=1)
        ).sum()
        safe = jnp.where(valid, picked, 0)
        send_ids = jnp.where(valid, my_base + safe, -1)  # [D, cap]
        send_rows = jnp.where(
            valid[:, :, None], rows_l[safe], 0
        )  # [D, cap, R]

        # (4) the fabric: one all_to_all (XLA's ICI ring schedule)
        recv_ids = jax.lax.all_to_all(
            send_ids, "nodes", split_axis=0, concat_axis=0
        ).reshape(-1)  # [D*cap]
        recv_rows = jax.lax.all_to_all(
            send_rows, "nodes", split_axis=0, concat_axis=0
        ).reshape(-1, r_width)

        # (5) local delivery: global sender id -> received slot
        slot_of = (
            jnp.full((n,), -1, jnp.int32)
            .at[jnp.where(recv_ids >= 0, recv_ids, n)]
            .set(jnp.arange(recv_ids.shape[0], dtype=jnp.int32),
                 mode="drop")
        )
        if params.loss > 0.0:
            drop = jax.random.uniform(key_l, (n, k)) < params.loss
        new_rows_l = rows_l
        for j, s_all in enumerate(senders):
            s = s_all[my_idx]
            slot = slot_of[s]
            ok = slot >= 0
            if params.loss > 0.0:
                ok &= ~drop[my_idx, j]
            new_rows_l = merge_keys(
                new_rows_l,
                jnp.where(
                    ok[:, None], recv_rows[jnp.maximum(slot, 0)], rows_l
                ),
            )

        learned_l = jnp.any(new_rows_l != rows_l, axis=1)
        new_tx_l = jnp.where(active_l, tx_l - 1, tx_l)
        new_tx_l = jnp.where(learned_l, params.max_transmissions, new_tx_l)
        new_msgs_l = msgs_l + jnp.where(active_l, k, 0).astype(msgs_l.dtype)
        overflow = jax.lax.psum(overflow_l, "nodes")
        return new_rows_l, new_tx_l, new_msgs_l, overflow

    node_sharded = P("nodes")
    return jax.jit(
        _shard_map(
            local_step,
            mesh=mesh,
            in_specs=(node_sharded, node_sharded, node_sharded, P()),
            out_specs=(node_sharded, node_sharded, node_sharded, P()),
        )
    )


def sharded_seq_sync_step(mesh, params):
    """Sequence-reassembly anti-entropy over the device mesh — the
    framework's "sequence parallelism": one changeset's seq bitmap is
    the long-sequence analogue (SURVEY §5), and its reconciliation
    shards over the ``nodes`` axis.

    Returns ``step(bits, msgs, key) -> (bits', msgs')`` on GLOBAL
    arrays sharded [nodes] on their leading axis.  The fabric is one
    ``all_gather`` of the seq bitmaps; the needs/served/arrival algebra
    then runs replicated and each shard commits its own receivers'
    rows and message charges.  Bitwise identical to the unsharded
    :func:`corrosion_tpu.models.sync.seq_sync_step` for the same key
    (pinned by tests/test_sharding.py).
    """
    from corrosion_tpu.models.sync import seq_sync_step

    n = params.n_nodes
    d_shards = mesh.shape["nodes"]
    if n % d_shards != 0:
        raise ValueError(f"n_nodes {n} must divide over {d_shards} shards")
    n_local = n // d_shards

    def local_step(bits_l, msgs_l, key):
        # (1) fabric: one all_gather moves every shard's bitmaps
        bits_all = gather_nodes(bits_l)
        msgs_all = gather_nodes(msgs_l)
        # (2) replicated algebra on the gathered state — same RNG as
        # the unsharded kernel, so every shard agrees on every session
        new_bits, new_msgs = seq_sync_step(bits_all, msgs_all, key, params)
        # (3) commit my rows
        shard = jax.lax.axis_index("nodes")
        lo = shard * n_local
        return (
            jax.lax.dynamic_slice_in_dim(new_bits, lo, n_local, 0),
            jax.lax.dynamic_slice_in_dim(new_msgs, lo, n_local, 0),
        )

    node_sharded = P("nodes")
    return jax.jit(
        _shard_map(
            local_step,
            mesh=mesh,
            in_specs=(node_sharded, node_sharded, P()),
            out_specs=(node_sharded, node_sharded),
        )
    )
