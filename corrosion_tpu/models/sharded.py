"""Explicit-collective gossip fabric: broadcast delivery under shard_map.

The reference moves changesets between nodes over QUIC; the TPU-native
fabric is the ICI/DCN mesh.  Where ``__graft_entry__``'s dryrun lets
XLA infer collectives from `NamedSharding` annotations, this module
spells the fabric out: node state lives sharded over the mesh's
``nodes`` axis and one gossip tick is

  1. every shard draws the SAME per-column inverse permutations from
     the shared tick key (replicated compute — cheap integers), so all
     shards agree on each receiver's sender;
  2. an ``all_gather`` over ``nodes`` moves every shard's sender rows
     and activity mask across the fabric (the ICI stand-in for the
     reference's QUIC uni-streams);
  3. each shard's receivers gather from their column senders out of the
     gathered global state (delivery is local after the gather — no
     scatter anywhere, mirroring the permutation-fanout kernel).

The result is bitwise identical to the unsharded
:func:`corrosion_tpu.models.broadcast.broadcast_step` for the same key
(pinned by tests/test_sharding.py on the virtual 8-device CPU mesh), so
the sharded fabric can replace the single-chip kernel without touching
protocol semantics.  Scaling note: all_gather volume is O(N·R) per tick
— the right first fabric (broadcasts genuinely are all-to-all
dissemination); a destination-sorted ppermute ring would cut it to
O(N·R/D) for sparse ticks and slots in behind the same interface.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from corrosion_tpu.models.broadcast import BroadcastParams

def _shard_map(f, mesh, in_specs, out_specs):
    """shard_map across jax versions: the promoted jax.shard_map (>=0.8,
    check_vma kwarg) or the experimental one (check_rep kwarg).  Checks
    are off either way: the body uses axis_index, so outputs are
    legitimately device-varying."""
    try:
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False,
        )
    except AttributeError:  # pragma: no cover - older jax
        from jax.experimental.shard_map import shard_map

        return shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=False,
        )


def sharded_broadcast_step(mesh, params: BroadcastParams):
    """Build a jitted per-shard gossip tick over ``mesh``'s ``nodes``
    axis.  Returns ``step(rows, tx, msgs, key) -> (rows', tx', msgs')``
    operating on GLOBAL arrays sharded [nodes] on their leading node
    axis (rows: [N, R]; tx/msgs: [N])."""
    n, k = params.n_nodes, params.fanout
    d_shards = mesh.shape["nodes"]
    if n % d_shards != 0:
        raise ValueError(f"n_nodes {n} must divide over {d_shards} shards")
    n_local = n // d_shards

    from corrosion_tpu.models.broadcast import _perm_senders

    u = params.universe or n

    def local_step(rows_l, tx_l, msgs_l, key):
        # (1) replicated permutation draw — same key everywhere, so
        # every shard agrees on each receiver's sender this tick
        # (mirrors _deliver_perm's column structure bitwise)
        key_t, key_l = jax.random.split(key)

        # (2) the fabric: move sender rows + activity across ICI
        rows_all = jax.lax.all_gather(
            rows_l, "nodes"
        ).reshape(n, rows_l.shape[-1])
        active_all = jax.lax.all_gather(tx_l > 0, "nodes").reshape(n)

        if params.loss > 0.0:
            drop = jax.random.uniform(key_l, (n, k)) < params.loss

        # (3) local delivery: each of MY receivers gathers from its
        # column sender out of the gathered global state
        shard = jax.lax.axis_index("nodes")
        lo = shard * n_local
        my_idx = lo + jnp.arange(n_local, dtype=jnp.int32)
        new_rows_l = rows_l
        for j in range(k):
            sender_all = _perm_senders(
                key_t, j, n, u, j < params.fanout_ring0, params.ring0_size
            )  # [N] receiver->sender, identical on every shard
            sender = sender_all[my_idx]  # my receivers' senders
            valid = active_all[sender]
            if params.loss > 0.0:
                valid &= ~drop[my_idx, j]
            new_rows_l = jnp.maximum(
                new_rows_l,
                jnp.where(valid[:, None], rows_all[sender], rows_l),
            )

        # bookkeeping is local: decay my senders, refresh my learners
        learned_l = jnp.any(new_rows_l != rows_l, axis=1)
        active_l = tx_l > 0
        new_tx_l = jnp.where(active_l, tx_l - 1, tx_l)
        new_tx_l = jnp.where(learned_l, params.max_transmissions, new_tx_l)
        new_msgs_l = msgs_l + jnp.where(active_l, k, 0).astype(msgs_l.dtype)
        return new_rows_l, new_tx_l, new_msgs_l

    node_sharded = P("nodes")
    return jax.jit(
        _shard_map(
            local_step,
            mesh=mesh,
            in_specs=(node_sharded, node_sharded, node_sharded, P()),
            out_specs=(node_sharded, node_sharded, node_sharded),
        )
    )


def sharded_seq_sync_step(mesh, params):
    """Sequence-reassembly anti-entropy over the device mesh — the
    framework's "sequence parallelism": one changeset's seq bitmap is
    the long-sequence analogue (SURVEY §5), and its reconciliation
    shards over the ``nodes`` axis.

    Returns ``step(bits, msgs, key) -> (bits', msgs')`` on GLOBAL
    arrays sharded [nodes] on their leading axis.  The fabric is one
    ``all_gather`` of the seq bitmaps; the needs/served/arrival algebra
    then runs replicated and each shard commits its own receivers'
    rows and message charges.  Bitwise identical to the unsharded
    :func:`corrosion_tpu.models.sync.seq_sync_step` for the same key
    (pinned by tests/test_sharding.py).
    """
    from corrosion_tpu.models.sync import seq_sync_step

    n = params.n_nodes
    d_shards = mesh.shape["nodes"]
    if n % d_shards != 0:
        raise ValueError(f"n_nodes {n} must divide over {d_shards} shards")
    n_local = n // d_shards

    def local_step(bits_l, msgs_l, key):
        # (1) fabric: one all_gather moves every shard's bitmaps
        bits_all = jax.lax.all_gather(
            bits_l, "nodes"
        ).reshape(n, bits_l.shape[-1])
        msgs_all = jax.lax.all_gather(msgs_l, "nodes").reshape(n)
        # (2) replicated algebra on the gathered state — same RNG as
        # the unsharded kernel, so every shard agrees on every session
        new_bits, new_msgs = seq_sync_step(bits_all, msgs_all, key, params)
        # (3) commit my rows
        shard = jax.lax.axis_index("nodes")
        lo = shard * n_local
        return (
            jax.lax.dynamic_slice_in_dim(new_bits, lo, n_local, 0),
            jax.lax.dynamic_slice_in_dim(new_msgs, lo, n_local, 0),
        )

    node_sharded = P("nodes")
    return jax.jit(
        _shard_map(
            local_step,
            mesh=mesh,
            in_specs=(node_sharded, node_sharded, P()),
            out_specs=(node_sharded, node_sharded),
        )
    )
