"""Explicit-collective gossip fabric: broadcast delivery under shard_map.

The reference moves changesets between nodes over QUIC; the TPU-native
fabric is the ICI/DCN mesh.  Where ``__graft_entry__``'s dryrun lets
XLA infer collectives from `NamedSharding` annotations, this module
spells the fabric out: node state lives sharded over the mesh's
``nodes`` axis and one gossip tick is

  1. every shard draws the SAME per-column inverse permutations from
     the shared tick key (replicated compute — cheap integers), so all
     shards agree on each receiver's sender;
  2. an ``all_gather`` over ``nodes`` moves every shard's sender rows
     and activity mask across the fabric (the ICI stand-in for the
     reference's QUIC uni-streams);
  3. each shard's receivers gather from their column senders out of the
     gathered global state (delivery is local after the gather — no
     scatter anywhere, mirroring the permutation-fanout kernel).

The result is bitwise identical to the unsharded
:func:`corrosion_tpu.models.broadcast.broadcast_step` for the same key
(pinned by tests/test_sharding.py on the virtual 8-device CPU mesh), so
the sharded fabric can replace the single-chip kernel without touching
protocol semantics.  Two fabrics share that contract:

* :func:`sharded_broadcast_step` — one ``all_gather`` per tick,
  O(N·R) per shard: the right first fabric (early epidemic ticks
  genuinely are all-to-all dissemination);
* :func:`sharded_broadcast_step_ring` — the destination-sorted
  fabric: each shard ships each destination only the ACTIVE sender
  rows that destination's receivers drew this tick, over one
  ``all_to_all`` (XLA's ICI ring schedule).  Sparse/late ticks move
  almost nothing; a static slot cap bounds volume at O(D·cap·R) with
  an exact overflow count when demand exceeds it.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from corrosion_tpu.models.broadcast import BroadcastParams
from corrosion_tpu.ops.merge import merge_keys

def gather_nodes(x_l, axis: int = 0, axis_name: str = "nodes"):
    """Reassemble a node-sharded leaf: tiled ``all_gather`` over the
    mesh's ``nodes`` axis (or another named axis, e.g. the multi-host
    kernel's ``hosts``), concatenating the shard blocks back along
    ``axis`` in device order (the inverse of the P(..., "nodes", ...)
    row split).  Shared by the broadcast fabrics here and the sharded
    exact rejection sampler (sim/calibrate.py)."""
    return jax.lax.all_gather(x_l, axis_name, axis=axis, tiled=True)


def _pack_bits(mask):
    """Bitpack a [..., M] bool mask (M % 8 == 0) into [..., M//8]
    uint8 wire bytes, LSB-first within each byte — the encoding the
    multi-host frontier kernel puts on the fabric so a validity delta
    costs one BIT per node-row instead of one bool byte."""
    m = mask.shape[-1]
    lanes = mask.reshape(mask.shape[:-1] + (m // 8, 8)).astype(jnp.uint8)
    shifts = jnp.arange(8, dtype=jnp.uint8)
    return jnp.sum(lanes << shifts, axis=-1, dtype=jnp.uint8)


def _unpack_bits(wire, m: int):
    """Inverse of ``_pack_bits``: [..., M//8] uint8 -> [..., M] bool."""
    shifts = jnp.arange(8, dtype=jnp.uint8)
    bits = (wire[..., None] >> shifts) & jnp.uint8(1)
    return bits.reshape(wire.shape[:-1] + (m,)).astype(bool)


def _shard_map(f, mesh, in_specs, out_specs):
    """shard_map across jax versions: the promoted jax.shard_map (>=0.8,
    check_vma kwarg) or the experimental one (check_rep kwarg).  Checks
    are off either way: the body uses axis_index, so outputs are
    legitimately device-varying."""
    try:
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False,
        )
    except AttributeError:  # pragma: no cover - older jax
        from jax.experimental.shard_map import shard_map

        return shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=False,
        )


def sharded_broadcast_step(mesh, params: BroadcastParams):
    """Build a jitted per-shard gossip tick over ``mesh``'s ``nodes``
    axis.  Returns ``step(rows, tx, msgs, key) -> (rows', tx', msgs')``
    operating on GLOBAL arrays sharded [nodes] on their leading node
    axis (rows: [N, R]; tx/msgs: [N])."""
    n, k = params.n_nodes, params.fanout
    d_shards = mesh.shape["nodes"]
    if n % d_shards != 0:
        raise ValueError(f"n_nodes {n} must divide over {d_shards} shards")
    n_local = n // d_shards

    from corrosion_tpu.models.broadcast import _perm_senders

    u = params.universe or n

    def local_step(rows_l, tx_l, msgs_l, key):
        # (1) replicated permutation draw — same key everywhere, so
        # every shard agrees on each receiver's sender this tick
        # (mirrors _deliver_perm's column structure bitwise)
        key_t, key_l = jax.random.split(key)

        # (2) the fabric: move sender rows + activity across ICI
        rows_all = gather_nodes(rows_l)
        active_all = gather_nodes(tx_l > 0)

        if params.loss > 0.0:
            drop = jax.random.uniform(key_l, (n, k)) < params.loss

        # (3) local delivery: each of MY receivers gathers from its
        # column sender out of the gathered global state
        shard = jax.lax.axis_index("nodes")
        lo = shard * n_local
        my_idx = lo + jnp.arange(n_local, dtype=jnp.int32)
        new_rows_l = rows_l
        for j in range(k):
            sender_all = _perm_senders(
                key_t, j, n, u, j < params.fanout_ring0, params.ring0_size
            )  # [N] receiver->sender, identical on every shard
            sender = sender_all[my_idx]  # my receivers' senders
            valid = active_all[sender]
            if params.loss > 0.0:
                valid &= ~drop[my_idx, j]
            new_rows_l = merge_keys(
                new_rows_l,
                jnp.where(valid[:, None], rows_all[sender], rows_l),
            )

        # bookkeeping is local: decay my senders, refresh my learners
        learned_l = jnp.any(new_rows_l != rows_l, axis=1)
        active_l = tx_l > 0
        new_tx_l = jnp.where(active_l, tx_l - 1, tx_l)
        new_tx_l = jnp.where(learned_l, params.max_transmissions, new_tx_l)
        new_msgs_l = msgs_l + jnp.where(active_l, k, 0).astype(msgs_l.dtype)
        return new_rows_l, new_tx_l, new_msgs_l

    node_sharded = P("nodes")
    return jax.jit(
        _shard_map(
            local_step,
            mesh=mesh,
            in_specs=(node_sharded, node_sharded, node_sharded, P()),
            out_specs=(node_sharded, node_sharded, node_sharded),
        )
    )


def sharded_broadcast_step_ring(mesh, params: BroadcastParams,
                                slot_cap: int | None = None):
    """The destination-sorted fabric the all_gather docstring promised:
    instead of moving EVERY shard's full state every tick (O(N·R) per
    shard), each shard sends each destination shard only the sender
    rows that destination's receivers actually need this tick —
    deduplicated, and only for ACTIVE senders, so late-epidemic ticks
    (most senders quiescent under backoff/decay) move almost nothing.
    Routing is one ``all_to_all`` over the ``nodes`` axis, which XLA
    schedules as the ICI ring (the ppermute-ring realization of this
    plan); volume is O(D·cap·R) per shard per tick.

    ``slot_cap``: static per-destination slot budget.  Default
    ``n_local`` makes the fabric provably lossless (a destination can
    never need more distinct rows of mine than I have) and BITWISE
    equal to :func:`sharded_broadcast_step` / the single-chip kernel
    (pinned by tests/test_sharding.py).  A smaller cap trades fabric
    volume for possible drops on dense ticks — the returned
    ``overflow`` count (global, per tick) says exactly how many needed
    rows didn't fit; a dropped row is a lost delivery, the same fault
    class the protocol already heals via retransmission + anti-entropy.
    Sizing guide: expected demand per destination is ~``k·n_local/D``
    distinct rows on a fully-active tick, so ``cap = 4·k·n_local/D``
    gives ~4x headroom and cuts steady-state fabric volume by ~``D/4k``
    vs all_gather at large D.

    Returns ``step(rows, tx, msgs, key) -> (rows', tx', msgs',
    overflow)`` on GLOBAL arrays sharded [nodes] on their leading axis.
    """
    n, k = params.n_nodes, params.fanout
    d_shards = mesh.shape["nodes"]
    if n % d_shards != 0:
        raise ValueError(f"n_nodes {n} must divide over {d_shards} shards")
    n_local = n // d_shards
    cap = n_local if slot_cap is None else min(slot_cap, n_local)

    from corrosion_tpu.models.broadcast import _perm_senders

    u = params.universe or n

    def local_step(rows_l, tx_l, msgs_l, key):
        r_width = rows_l.shape[-1]
        key_t, key_l = jax.random.split(key)
        shard = jax.lax.axis_index("nodes")
        my_base = shard * n_local
        my_idx = my_base + jnp.arange(n_local, dtype=jnp.int32)
        active_l = tx_l > 0

        # (1) replicated sender maps (identical on every shard)
        senders = [
            _perm_senders(
                key_t, j, n, u, j < params.fanout_ring0, params.ring0_size
            )
            for j in range(k)
        ]

        # (2) destination-sorted demand: needed[d, i] = does shard d
        # need MY local row i this tick (some receiver of d draws it)?
        dest_of = (
            jnp.arange(n, dtype=jnp.int32) // n_local
        )  # receiver -> shard
        needed = jnp.zeros((d_shards, n_local), bool)
        for s_all in senders:
            mine = s_all // n_local == shard
            slocal = jnp.where(mine, s_all % n_local, n_local)
            needed = needed.at[dest_of, slocal].max(mine, mode="drop")
        needed &= active_l[None, :]  # inactive senders deliver nothing

        # (3) pack per destination: the first `cap` needed rows, their
        # global ids alongside (-1 pads); count what didn't fit
        scores = jnp.where(
            needed, jnp.arange(n_local, dtype=jnp.int32)[None, :],
            jnp.int32(n_local),
        )
        picked = jnp.sort(scores, axis=1)[:, :cap]  # [D, cap]
        valid = picked < n_local
        overflow_l = (
            jnp.sum(needed, axis=1) - jnp.sum(valid, axis=1)
        ).sum()
        safe = jnp.where(valid, picked, 0)
        send_ids = jnp.where(valid, my_base + safe, -1)  # [D, cap]
        send_rows = jnp.where(
            valid[:, :, None], rows_l[safe], 0
        )  # [D, cap, R]

        # (4) the fabric: one all_to_all (XLA's ICI ring schedule)
        recv_ids = jax.lax.all_to_all(
            send_ids, "nodes", split_axis=0, concat_axis=0
        ).reshape(-1)  # [D*cap]
        recv_rows = jax.lax.all_to_all(
            send_rows, "nodes", split_axis=0, concat_axis=0
        ).reshape(-1, r_width)

        # (5) local delivery: global sender id -> received slot
        slot_of = (
            jnp.full((n,), -1, jnp.int32)
            .at[jnp.where(recv_ids >= 0, recv_ids, n)]
            .set(jnp.arange(recv_ids.shape[0], dtype=jnp.int32),
                 mode="drop")
        )
        if params.loss > 0.0:
            drop = jax.random.uniform(key_l, (n, k)) < params.loss
        new_rows_l = rows_l
        for j, s_all in enumerate(senders):
            s = s_all[my_idx]
            slot = slot_of[s]
            ok = slot >= 0
            if params.loss > 0.0:
                ok &= ~drop[my_idx, j]
            new_rows_l = merge_keys(
                new_rows_l,
                jnp.where(
                    ok[:, None], recv_rows[jnp.maximum(slot, 0)], rows_l
                ),
            )

        learned_l = jnp.any(new_rows_l != rows_l, axis=1)
        new_tx_l = jnp.where(active_l, tx_l - 1, tx_l)
        new_tx_l = jnp.where(learned_l, params.max_transmissions, new_tx_l)
        new_msgs_l = msgs_l + jnp.where(active_l, k, 0).astype(msgs_l.dtype)
        overflow = jax.lax.psum(overflow_l, "nodes")
        return new_rows_l, new_tx_l, new_msgs_l, overflow

    node_sharded = P("nodes")
    return jax.jit(
        _shard_map(
            local_step,
            mesh=mesh,
            in_specs=(node_sharded, node_sharded, node_sharded, P()),
            out_specs=(node_sharded, node_sharded, node_sharded, P()),
        )
    )


@lru_cache(maxsize=8)
def sharded_frontier_exact_step(mesh, cfg):
    """Mesh-native frontier-sparse exact tick (the sparse twin of
    ``sim/calibrate.py``'s ``sharded_packed_exact_step``): ``step(state,
    keys) -> state`` on GLOBAL seed-batched FrontierExactState arrays
    laid out per ``frontier_shardings``.

    The layout inverts the dense kernel's exchange pattern into the
    delta style the frontier representation affords:

    * the RING — the only O(N·cap) leaf — row-shards over ``nodes``
      (every use of row *i* is sender-local: the validity test reads
      sender *i*'s own ring, marking writes it);
    * every [S, N] dense leaf (infected/tx/next_send/msgs) is
      REPLICATED and each shard runs the full cheap bookkeeping
      itself — so the ``active``/``infected`` masks the dense fabric
      all_gathers every tick (and again for every sync round) never
      cross this fabric at all;
    * the ONLY per-tick exchange is the rejection loop's validity
      delta: each round, one tiled ``all_gather`` of the [S, n_local]
      still-bad bits for the rows each shard owns.  Ticks with an
      empty frontier skip the whole phase (no exchange, no draws).

    Bitwise identical per seed to the single-chip
    ``frontier_exact_tick`` — and through it to ``packed_exact_tick``
    (tests/test_sharding.py pins it with a negative control)."""
    import jax.numpy as jnp

    from corrosion_tpu.sim.calibrate import (
        FrontierExactState,
        _frontier_state_specs,
    )

    if cfg.n_nodes % mesh.shape["nodes"] != 0:
        raise ValueError(
            f"n_nodes {cfg.n_nodes} must divide over "
            f"{mesh.shape['nodes']} node shards"
        )
    specs = _frontier_state_specs()

    def local(state, keys):
        out = _sharded_frontier_tick_local(*state, keys, cfg)
        return FrontierExactState(*out)

    return jax.jit(
        _shard_map(
            local, mesh,
            in_specs=(specs, P()),
            out_specs=specs,
        )
    )


def _sharded_frontier_tick_local(infected, tx, next_send, ring_l, msgs,
                                 ticks, pending, keys, cfg,
                                 writer: int = 0):
    """One frontier tick on ONE shard for a seed batch.

    Shapes: infected/tx/next_send/msgs/pending [S, N] REPLICATED
    (identical on every shard); ring_l [S, n_local, cap] my shard's
    ring rows; ticks [S] lockstep; keys [S, 2] per-seed tick keys.
    Consumes the RNG stream in exactly ``packed_exact_tick``'s order
    (replicated integer draws, the fabric idiom above)."""
    from corrosion_tpu.sim.calibrate import (
        _backoff_next_send,
        _frontier_invalid,
        _latency_promote,
        _latency_region_of,
        _latency_split,
        _partition_of,
        _sync_pull,
        _wan_filter,
    )

    n, k = cfg.n_nodes, cfg.fanout
    S = infected.shape[0]
    n_local = ring_l.shape[1]
    cap = ring_l.shape[2]
    shard = jax.lax.axis_index("nodes")
    my_lo = shard * n_local
    idx_l = my_lo + jnp.arange(n_local, dtype=jnp.int32)
    idx = jnp.arange(n, dtype=jnp.int32)
    s_rows = jnp.arange(S, dtype=jnp.int32)

    def slice_l(x):  # [S, n] -> my [S, n_local] block
        return jax.lax.dynamic_slice_in_dim(x, my_lo, n_local, axis=1)

    # WAN queue promotion — fully replicated, like every dense leaf here
    if _latency_region_of(cfg) is not None:
        infected, tx, next_send, pending = _latency_promote(
            infected, tx, next_send, pending, ticks[:, None], cfg
        )
    active = infected & (tx > 0) & (next_send <= ticks[:, None])  # [S, N]
    part = _partition_of(cfg)
    part_active = ticks < cfg.heal_tick  # [S]

    ks = jax.vmap(lambda kk: jax.random.split(kk, 3))(keys)
    k_draw, k_loss, k_sync = ks[:, 0], ks[:, 1], ks[:, 2]

    def do_broadcast(args):
        infected, tx, next_send, ring_l, msgs, pending = args

        def draw(r):
            return jax.vmap(
                lambda kd: jax.random.randint(
                    jax.random.fold_in(kd, r), (n, k), 0, n
                )
            )(k_draw)  # [S, n, k] replicated

        def invalid_local(cand):
            """[S, n_local]: my rows' invalid bits — the per-round
            validity DELTA, the only thing that crosses the fabric."""
            cand_l = jax.lax.dynamic_slice_in_dim(cand, my_lo, n_local, 1)
            return _frontier_invalid(cfg, ring_l, idx_l, cand_l, writer)

        cand = draw(0)
        bad = gather_nodes(
            invalid_local(cand) & slice_l(active), axis=1
        )  # [S, n]

        def cond(carry):
            _, bad, _ = carry
            return jnp.any(bad)

        def body(carry):
            cand, bad, r = carry
            cand = jnp.where(bad[:, :, None], draw(r), cand)
            bad_l = invalid_local(cand) & slice_l(bad)
            return cand, gather_nodes(bad_l, axis=1), r + 1

        cand, _, _ = jax.lax.while_loop(
            cond, body, (cand, bad, jnp.int32(1))
        )

        delivered = jnp.broadcast_to(active[:, :, None], (S, n, k))
        if cfg.loss > 0.0:
            keep = jax.vmap(
                lambda kl: jax.random.uniform(kl, (n, k))
            )(k_loss) >= cfg.loss
            delivered &= keep
        if part is not None:
            delivered &= ~(
                (part[None, :, None] != part[cand])
                & part_active[:, None, None]
            )
        delivered = _wan_filter(delivered, cand, k_loss, cfg)
        delivered, queued = _latency_split(delivered, cand, ticks, cfg)
        if queued is not None:
            pending = jnp.minimum(pending, queued)

        # delivery is replicated: every shard commits the same scatter
        tgt = jnp.where(delivered, cand, n).reshape(S, n * k)
        new_infected = (
            infected.at[s_rows[:, None], tgt].set(True, mode="drop")
        )

        # mark on send — sender-local: my rows' targets into MY ring
        # rows at slots [sends_made*k, sends_made*k + k)
        cand_l = jax.lax.dynamic_slice_in_dim(cand, my_lo, n_local, 1)
        active_l = slice_l(active)
        send_base = (cfg.max_transmissions - slice_l(tx)) * k
        slot = send_base[:, :, None] + jnp.arange(k, dtype=jnp.int32)
        slot = jnp.where(active_l[:, :, None], slot, cap)
        new_ring_l = ring_l.at[
            s_rows[:, None, None],
            jnp.arange(n_local, dtype=jnp.int32)[None, :, None],
            slot,
        ].set(cand_l, mode="drop")
        msgs = msgs + jnp.where(active, k, 0)

        tx = jnp.where(active, tx - 1, tx)
        learned = new_infected & ~infected
        next_send = _backoff_next_send(
            active, learned, tx, next_send, ticks[:, None], cfg
        )
        tx = jnp.where(learned, cfg.max_transmissions, tx)
        return new_infected, tx, next_send, new_ring_l, msgs, pending

    infected, tx, next_send, ring_l, msgs, pending = jax.lax.cond(
        jnp.any(active), do_broadcast, lambda args: args,
        (infected, tx, next_send, ring_l, msgs, pending),
    )

    if cfg.sync_interval > 0:
        # fully replicated — the dense fabric needed an infected
        # all_gather here; the replicated layout needs nothing
        def do_sync(args):
            infected, msgs = args
            p = cfg.sync_peers
            peers = jax.vmap(
                lambda kk: jax.random.randint(kk, (n, p), 0, n)
            )(k_sync)  # [S, n, p] replicated
            reachable = jnp.ones((S, n, p), bool)
            if part is not None:
                reachable &= ~(
                    (part[None, :, None] != part[peers])
                    & part_active[:, None, None]
                )
            healed, pay = _sync_pull(infected, peers, reachable, cfg)
            return infected | healed, msgs + pay

        infected, msgs = jax.lax.cond(
            ticks[0] % cfg.sync_interval == cfg.sync_interval - 1,
            do_sync,
            lambda args: args,
            (infected, msgs),
        )

    return infected, tx, next_send, ring_l, msgs, ticks + 1, pending


@lru_cache(maxsize=8)
def make_sharded_frontier_chunk(mesh, cfg):
    """Jitted mesh-native frontier scan chunk: ``chunk(state,
    seed_keys) -> (state', (conv [C, S], msgs_mean [C, S], msgs_p99
    [C, S]))`` — the sparse twin of ``make_sharded_exact_chunk``
    (donated state; stats come straight off the REPLICATED leaves, no
    gather; cached by (mesh, cfg) so warm and measured runs share one
    compiled executable)."""
    import jax.numpy as jnp

    from corrosion_tpu.sim.calibrate import (
        FrontierExactState,
        _frontier_state_specs,
    )

    if cfg.n_nodes % mesh.shape["nodes"] != 0:
        raise ValueError(
            f"n_nodes {cfg.n_nodes} must divide over "
            f"{mesh.shape['nodes']} node shards"
        )
    specs = _frontier_state_specs()

    def local_chunk(state, seed_keys):
        def body(carry, _):
            keys_t = jax.vmap(jax.random.fold_in)(seed_keys, carry[5])
            nxt = _sharded_frontier_tick_local(*carry, keys_t, cfg)
            msgs_f = nxt[4].astype(jnp.float32)
            return nxt, (
                jnp.all(nxt[0], axis=1),
                jnp.mean(msgs_f, axis=1),
                jnp.percentile(msgs_f, 99, axis=1),
            )

        carry, stats = jax.lax.scan(
            body, tuple(state), xs=None, length=cfg.chunk_ticks,
        )
        return FrontierExactState(*carry), stats

    return jax.jit(
        _shard_map(
            local_chunk, mesh,
            in_specs=(specs, P()),
            out_specs=(specs, (P(), P(), P())),
        ),
        donate_argnums=(0,),
    )


def _check_host_mesh(mesh, cfg):
    h = mesh.shape["hosts"]
    if cfg.n_nodes % (8 * h) != 0:
        raise ValueError(
            f"n_nodes {cfg.n_nodes} must divide over {h} hosts into "
            "byte-aligned rows (n_nodes % (8 * n_hosts) == 0) for the "
            "bitpacked delta exchange"
        )
    return h


def _sharded_frontier_host_tick_local(infected, tx_l, next_send_l,
                                      ring_l, msgs_l, ticks, pending,
                                      keys, cfg, writer: int = 0):
    """One frontier tick on ONE HOST of the multi-host mesh for a seed
    batch — the TeraAgent-style delta-only exchange layer.

    Layout (``_frontier_host_specs``): tx_l/next_send_l/msgs_l
    [S, n_local] and ring_l [S, n_local, cap] are MY HOST'S row shard;
    infected/pending [S, N] are REPLICATED BY CONSTRUCTION — every
    host derives the identical full-width delivery commit, queue
    update and sync heal from the replicated candidate tuples and
    draws, so they never cross the fabric.

    The ONLY cross-host traffic per tick is the rejection loop's
    bitpacked validity deltas (one bit per owned row, 8 rows/byte):

    * round 0 — each host's ``active`` frontier bits (which of its
      rows draw a tuple this tick; this is also the emptiness signal
      that gates the whole phase);
    * round r — each host's still-bad bits (which of its rows'
      replicated tuples failed its LOCAL ring test).

    No ring rows, no infected masks, and NOTHING on sync rounds ever
    crosses.  Bitwise identical per seed to the single-host
    ``frontier_exact_tick`` (tests/test_sharding.py pins it across the
    headline shape and both measured topology families, with a
    seeded-corruption negative control)."""
    from corrosion_tpu.sim.calibrate import (
        LATENCY_NONE,
        _backoff_next_send,
        _frontier_invalid,
        _latency_region_of,
        _latency_split,
        _partition_of,
        _rtt_tier_of,
        _sync_pull,
        _wan_filter,
    )

    n, k = cfg.n_nodes, cfg.fanout
    S = infected.shape[0]
    n_local = ring_l.shape[1]
    cap = ring_l.shape[2]
    host = jax.lax.axis_index("hosts")
    my_lo = host * n_local
    idx_l = my_lo + jnp.arange(n_local, dtype=jnp.int32)
    s_rows = jnp.arange(S, dtype=jnp.int32)

    def slice_l(x):  # [S, n] -> my [S, n_local] block
        return jax.lax.dynamic_slice_in_dim(x, my_lo, n_local, axis=1)

    def exchange(mask_l):
        """[S, n_local] bool -> [S, n] bool — the ONLY cross-host op:
        one tiled all_gather of bitpacked delta bytes."""
        wire = gather_nodes(
            _pack_bits(mask_l), axis=1, axis_name="hosts"
        )
        return _unpack_bits(wire, n)

    # WAN queue promotion: due/arrived derive from the REPLICATED
    # infected+pending, so every host computes them identically and
    # applies the slice to its own sharded rows — zero exchange
    if _latency_region_of(cfg) is not None:
        due = pending <= ticks[:, None]
        arrived = due & ~infected
        tier = _rtt_tier_of(cfg)
        first_l = 1 if tier is None else tier[idx_l]
        arrived_l = slice_l(arrived)
        tx_l = jnp.where(arrived_l, cfg.max_transmissions, tx_l)
        next_send_l = jnp.where(
            arrived_l, ticks[:, None] + first_l, next_send_l
        )
        infected = infected | arrived
        pending = jnp.where(due, LATENCY_NONE, pending)

    active_l = (
        slice_l(infected) & (tx_l > 0) & (next_send_l <= ticks[:, None])
    )
    active = exchange(active_l)  # round-0 delta: my frontier bits
    part = _partition_of(cfg)
    part_active = ticks < cfg.heal_tick  # [S]

    ks = jax.vmap(lambda kk: jax.random.split(kk, 3))(keys)
    k_draw, k_loss, k_sync = ks[:, 0], ks[:, 1], ks[:, 2]

    def do_broadcast(args):
        infected, tx_l, next_send_l, ring_l, msgs_l, pending = args

        def draw(r):
            return jax.vmap(
                lambda kd: jax.random.randint(
                    jax.random.fold_in(kd, r), (n, k), 0, n
                )
            )(k_draw)  # [S, n, k] replicated

        def invalid_local(cand):
            """[S, n_local]: my rows' invalid bits — the per-round
            validity DELTA, bitpacked onto the fabric by
            ``exchange``."""
            cand_l = jax.lax.dynamic_slice_in_dim(
                cand, my_lo, n_local, 1
            )
            return _frontier_invalid(cfg, ring_l, idx_l, cand_l, writer)

        cand = draw(0)
        bad = exchange(invalid_local(cand) & active_l)  # [S, n]

        def cond(carry):
            _, bad, _ = carry
            return jnp.any(bad)

        def body(carry):
            cand, bad, r = carry
            cand = jnp.where(bad[:, :, None], draw(r), cand)
            bad_l = invalid_local(cand) & slice_l(bad)
            return cand, exchange(bad_l), r + 1

        cand, _, _ = jax.lax.while_loop(
            cond, body, (cand, bad, jnp.int32(1))
        )

        delivered = jnp.broadcast_to(active[:, :, None], (S, n, k))
        if cfg.loss > 0.0:
            keep = jax.vmap(
                lambda kl: jax.random.uniform(kl, (n, k))
            )(k_loss) >= cfg.loss
            delivered &= keep
        if part is not None:
            delivered &= ~(
                (part[None, :, None] != part[cand])
                & part_active[:, None, None]
            )
        delivered = _wan_filter(delivered, cand, k_loss, cfg)
        delivered, queued = _latency_split(delivered, cand, ticks, cfg)
        if queued is not None:
            pending = jnp.minimum(pending, queued)

        # delivery commit is replicated arithmetic on replicated
        # operands — every host runs the same scatter, zero exchange
        tgt = jnp.where(delivered, cand, n).reshape(S, n * k)
        new_infected = (
            infected.at[s_rows[:, None], tgt].set(True, mode="drop")
        )

        # mark on send — sender-local rows into MY ring shard
        cand_l = jax.lax.dynamic_slice_in_dim(cand, my_lo, n_local, 1)
        send_base = (cfg.max_transmissions - tx_l) * k
        slot = send_base[:, :, None] + jnp.arange(k, dtype=jnp.int32)
        slot = jnp.where(active_l[:, :, None], slot, cap)
        new_ring_l = ring_l.at[
            s_rows[:, None, None],
            jnp.arange(n_local, dtype=jnp.int32)[None, :, None],
            slot,
        ].set(cand_l, mode="drop")
        msgs_l = msgs_l + jnp.where(active_l, k, 0)

        tx_l = jnp.where(active_l, tx_l - 1, tx_l)
        learned_l = slice_l(new_infected & ~infected)
        next_send_l = _backoff_next_send(
            active_l, learned_l, tx_l, next_send_l, ticks[:, None],
            cfg, idx=idx_l,
        )
        tx_l = jnp.where(learned_l, cfg.max_transmissions, tx_l)
        return (new_infected, tx_l, next_send_l, new_ring_l, msgs_l,
                pending)

    infected, tx_l, next_send_l, ring_l, msgs_l, pending = jax.lax.cond(
        jnp.any(active), do_broadcast, lambda args: args,
        (infected, tx_l, next_send_l, ring_l, msgs_l, pending),
    )

    if cfg.sync_interval > 0:
        # sync rounds are EXCHANGE-FREE: infected is already replicated
        # (the dense fabric all_gathered it here; the host layer never
        # moves it), peers are replicated draws, and each host keeps
        # only its own rows of the session pay
        def do_sync(args):
            infected, msgs_l = args
            p = cfg.sync_peers
            peers = jax.vmap(
                lambda kk: jax.random.randint(kk, (n, p), 0, n)
            )(k_sync)  # [S, n, p] replicated
            reachable = jnp.ones((S, n, p), bool)
            if part is not None:
                reachable &= ~(
                    (part[None, :, None] != part[peers])
                    & part_active[:, None, None]
                )
            healed, pay = _sync_pull(infected, peers, reachable, cfg)
            return infected | healed, msgs_l + slice_l(pay)

        infected, msgs_l = jax.lax.cond(
            ticks[0] % cfg.sync_interval == cfg.sync_interval - 1,
            do_sync,
            lambda args: args,
            (infected, msgs_l),
        )

    return (infected, tx_l, next_send_l, ring_l, msgs_l, ticks + 1,
            pending)


@lru_cache(maxsize=8)
def sharded_frontier_host_step(mesh, cfg):
    """Jitted multi-host frontier tick: ``step(state, keys) -> state``
    on GLOBAL seed-batched FrontierExactState arrays laid out per
    ``frontier_host_shardings`` (``mesh`` carries a ``hosts`` axis).
    Cross-host traffic per tick is ONLY the rejection loop's bitpacked
    validity deltas — see ``_sharded_frontier_host_tick_local``."""
    from corrosion_tpu.sim.calibrate import (
        FrontierExactState,
        _frontier_host_specs,
    )

    _check_host_mesh(mesh, cfg)
    specs = _frontier_host_specs()

    def local(state, keys):
        out = _sharded_frontier_host_tick_local(*state, keys, cfg)
        return FrontierExactState(*out)

    return jax.jit(
        _shard_map(
            local, mesh,
            in_specs=(specs, P()),
            out_specs=specs,
        )
    )


@lru_cache(maxsize=8)
def make_sharded_frontier_host_chunk(mesh, cfg):
    """Jitted multi-host frontier scan chunk: ``chunk(state,
    seed_keys) -> (state', (conv [C, S], msgs_mean [C, S], msgs_p99
    [C, S]))`` — the host-axis twin of ``make_sharded_frontier_chunk``
    (donated state for in-place pipelining; cached by (mesh, cfg)).

    Convergence flags come free from the replicated ``infected``.  The
    per-tick msgs stats DO gather the sharded [S, n_local] msgs leaf —
    that is MEASUREMENT-plane instrumentation, not protocol exchange
    (the protocol contract stays delta-only; stats run on the gathered
    full array so the float reductions are bitwise the single-host
    oracle's)."""
    from corrosion_tpu.sim.calibrate import (
        FrontierExactState,
        _frontier_host_specs,
    )

    _check_host_mesh(mesh, cfg)
    specs = _frontier_host_specs()

    def local_chunk(state, seed_keys):
        def body(carry, _):
            keys_t = jax.vmap(jax.random.fold_in)(seed_keys, carry[5])
            nxt = _sharded_frontier_host_tick_local(*carry, keys_t, cfg)
            msgs_f = gather_nodes(
                nxt[4], axis=1, axis_name="hosts"
            ).astype(jnp.float32)
            return nxt, (
                jnp.all(nxt[0], axis=1),
                jnp.mean(msgs_f, axis=1),
                jnp.percentile(msgs_f, 99, axis=1),
            )

        carry, stats = jax.lax.scan(
            body, tuple(state), xs=None, length=cfg.chunk_ticks,
        )
        return FrontierExactState(*carry), stats

    return jax.jit(
        _shard_map(
            local_chunk, mesh,
            in_specs=(specs, P()),
            out_specs=(specs, (P(), P(), P())),
        ),
        donate_argnums=(0,),
    )


def sharded_seq_sync_step(mesh, params):
    """Sequence-reassembly anti-entropy over the device mesh — the
    framework's "sequence parallelism": one changeset's seq bitmap is
    the long-sequence analogue (SURVEY §5), and its reconciliation
    shards over the ``nodes`` axis.

    Returns ``step(bits, msgs, key) -> (bits', msgs')`` on GLOBAL
    arrays sharded [nodes] on their leading axis.  The fabric is one
    ``all_gather`` of the seq bitmaps; the needs/served/arrival algebra
    then runs replicated and each shard commits its own receivers'
    rows and message charges.  Bitwise identical to the unsharded
    :func:`corrosion_tpu.models.sync.seq_sync_step` for the same key
    (pinned by tests/test_sharding.py).
    """
    from corrosion_tpu.models.sync import seq_sync_step

    n = params.n_nodes
    d_shards = mesh.shape["nodes"]
    if n % d_shards != 0:
        raise ValueError(f"n_nodes {n} must divide over {d_shards} shards")
    n_local = n // d_shards

    def local_step(bits_l, msgs_l, key):
        # (1) fabric: one all_gather moves every shard's bitmaps
        bits_all = gather_nodes(bits_l)
        msgs_all = gather_nodes(msgs_l)
        # (2) replicated algebra on the gathered state — same RNG as
        # the unsharded kernel, so every shard agrees on every session
        new_bits, new_msgs = seq_sync_step(bits_all, msgs_all, key, params)
        # (3) commit my rows
        shard = jax.lax.axis_index("nodes")
        lo = shard * n_local
        return (
            jax.lax.dynamic_slice_in_dim(new_bits, lo, n_local, 0),
            jax.lax.dynamic_slice_in_dim(new_msgs, lo, n_local, 0),
        )

    node_sharded = P("nodes")
    return jax.jit(
        _shard_map(
            local_step,
            mesh=mesh,
            in_specs=(node_sharded, node_sharded, P()),
            out_specs=(node_sharded, node_sharded),
        )
    )
