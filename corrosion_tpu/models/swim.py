"""SWIM membership as a vmapped state machine over an [N, N] view matrix.

Reference behavior (the foca runtime the agent drives at
``crates/corro-agent/src/broadcast/mod.rs:122-381``; identity renewal at
``corro-types/src/actor.rs:199-210``):

* each protocol period a member **pings** one random peer; no ack →
  **ping-req** through ``num_indirect_probes`` helpers; still nothing →
  the peer is locally **suspected**;
* a suspicion that isn't refuted within the suspicion timeout becomes
  **down** and is disseminated;
* a member that learns it is suspected **refutes** by re-announcing
  itself with a bumped incarnation; a member declared down rejoins by
  renewing its identity (modeled here as an incarnation bump past the
  down record, the array analogue of ``Actor::renew``).

State is dense: ``view[i, j]`` is node i's knowledge of node j packed as
``incarnation * 4 + state_rank`` (alive=0 < suspect=1 < down=2), so SWIM's
override rules — suspect@inc beats alive@inc, alive@inc+1 refutes
suspect@inc, down@inc beats both, renewal beats down — are all one
numeric ``max``.  Probes, indirect probes, suspicion timeouts, gossip
dissemination and refutation are each one vectorized pass; the whole tick
is a single jitted function over [N] and [N, N] arrays.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from corrosion_tpu.models.common import rand_peers

ALIVE, SUSPECT, DOWN = 0, 1, 2
_NEVER = jnp.iinfo(jnp.int32).max


@dataclass(frozen=True)
class SwimParams:
    n_nodes: int
    num_indirect_probes: int = 3  # ping-req helpers after a failed ping
    suspect_timeout: int = 6  # ticks before suspect -> down
    gossip_targets: int = 3  # peers gossiped to per tick
    gossip_entries: int = 6  # view entries piggybacked per gossip msg
    loss: float = 0.0  # per-leg message drop probability
    # foca's update backlog decay: an entry rides at most this many
    # gossip rounds after it last changed, then leaves circulation
    # (scale with cluster size via utils/swimscale.py)
    update_tx_limit: int = 8

    @classmethod
    def scaled(cls, n_nodes: int, probe_ticks: int = 1, **overrides):
        """Cluster-size-scaled parameters (foca Config::new_wan via
        make_foca_config, broadcast/mod.rs:937-946): suspicion deadline
        and update retransmission limit grow with ceil(log10(n+1))."""
        from corrosion_tpu.utils.swimscale import (
            scaled_suspect_timeout,
            scaled_update_retransmissions,
        )

        defaults = dict(
            suspect_timeout=int(
                scaled_suspect_timeout(0, probe_ticks, n_nodes)
            ),
            update_tx_limit=scaled_update_retransmissions(n_nodes),
        )
        defaults.update(overrides)
        return cls(n_nodes=n_nodes, **defaults)


class SwimState(NamedTuple):
    view: jnp.ndarray  # [N, N] int32 packed (inc*4 + state)
    suspect_since: jnp.ndarray  # [N, N] int32 tick, _NEVER when not suspect
    incarnation: jnp.ndarray  # [N] int32 own incarnation
    msgs: jnp.ndarray  # [N] int32 messages sent
    # [N, N] gossip rounds entry (i, j) rode since it last changed
    # (freshness-prioritized piggyback + decay, foca's update backlog)
    update_tx: jnp.ndarray


def member_key(inc, state):
    return inc * 4 + state


def key_state(key):
    return key % 4


def key_inc(key):
    return key // 4


def swim_init(n_nodes: int) -> SwimState:
    """Everyone starts knowing everyone alive at incarnation 0."""
    return SwimState(
        view=jnp.zeros((n_nodes, n_nodes), jnp.int32),
        suspect_since=jnp.full((n_nodes, n_nodes), _NEVER, jnp.int32),
        incarnation=jnp.zeros(n_nodes, jnp.int32),
        msgs=jnp.zeros(n_nodes, jnp.int32),
        update_tx=jnp.zeros((n_nodes, n_nodes), jnp.int32),
    )


@partial(jax.jit, static_argnames=("params",))
def swim_step(state: SwimState, key, tick, params: SwimParams, alive,
              revived=None):
    """One protocol period for all N nodes at once.

    alive: [N] bool ground truth (the churn schedule); dead nodes never
    ack, send, or gossip.  revived: optional [N] bool — nodes coming
    back THIS tick, which run the rejoin announce below.  Returns the
    next SwimState.
    """
    n = params.n_nodes
    (k_probe, k_loss1, k_loss2, k_help, k_hloss, k_gt, k_ge, k_gloss,
     k_tu, k_ann, k_aloss) = jax.random.split(key, 11)
    view, suspect_since, inc, msgs, update_tx = state
    view_in = view  # for end-of-tick change detection (backlog reset)

    def lossy(k, shape):
        if params.loss > 0.0:
            return jax.random.uniform(k, shape) >= params.loss
        return jnp.ones(shape, dtype=bool)

    # --- rejoin announce (host boot parity) -------------------------------
    # A reviving node does NOT wait to discover its own DOWN record via
    # gossip/TurnUndead: it bumps its incarnation past its own last
    # record and ANNOUNCES to one random seed member, whose merged
    # record becomes top-freshness gossip next tick — the model twin of
    # launch-with-bootstrap -> announce/announce_ack (swim_foca
    # _swim_announce).  Without this path the model's rejoin ran ~1.6x
    # the host's (CHURNDIFF r4 rejoin ratio 0.62).
    if revived is not None:
        rows0 = jnp.arange(n)
        seed = rand_peers(k_ann, n, (n,))
        inc = jnp.where(
            revived,
            jnp.maximum(inc, key_inc(view[rows0, rows0])) + 1,
            inc,
        )
        rec = member_key(inc, ALIVE)
        view = view.at[rows0, rows0].set(
            jnp.where(revived, rec, view[rows0, rows0])
        )
        ann_ok = (
            revived & alive & alive[seed]
            & lossy(k_aloss, (n, 2)).all(axis=1)  # announce + ack legs
        )
        view = view.at[seed, rows0].max(jnp.where(ann_ok, rec, 0))
        # msgs: the announce (if the node is up) + the ack coming back
        msgs = msgs + revived.astype(jnp.int32)
        msgs = msgs.at[seed].add(ann_ok.astype(jnp.int32))

    # --- direct probe -----------------------------------------------------
    target = rand_peers(k_probe, n, (n,))  # [N]
    ping_ok = alive & lossy(k_loss1, (n,)) & alive[target]
    ack_ok = ping_ok & lossy(k_loss2, (n,))
    # msgs: ping (if sender alive) + ack (if it came back)
    msgs = msgs + alive.astype(jnp.int32) + jnp.zeros_like(msgs).at[target].add(
        ping_ok.astype(jnp.int32)
    )

    # --- indirect probes on direct failure --------------------------------
    h = params.num_indirect_probes
    helpers = rand_peers(k_help, n, (n, h))  # [N, H]
    legs = lossy(k_hloss, (n, h, 4))  # req, ping, ack, relay-ack
    indirect_ok = (
        (~ack_ok[:, None])
        & alive[:, None]
        & alive[helpers]
        & alive[target][:, None]
        & legs.all(axis=2)
    )  # [N, H]
    # msgs: ping-req per helper + helper's ping + acks riding back
    tried = (~ack_ok[:, None]) & alive[:, None]  # [N, H] requests sent
    msgs = msgs + tried.sum(axis=1, dtype=jnp.int32)
    msgs = msgs.at[helpers.reshape(-1)].add(
        (tried & alive[helpers]).reshape(-1).astype(jnp.int32)
    )
    msgs = msgs.at[target].add(indirect_ok.sum(axis=1, dtype=jnp.int32))

    probe_ok = ack_ok | indirect_ok.any(axis=1)  # [N]

    # --- apply probe outcome ---------------------------------------------
    rows = jnp.arange(n)
    alive_key_t = member_key(inc[target], ALIVE)
    cur = view[rows, target]
    # success: learn the target is alive at its current incarnation
    upd = jnp.where(probe_ok & alive, jnp.maximum(cur, alive_key_t), cur)
    # failure: suspect at the incarnation we currently know
    fail = (~probe_ok) & alive
    suspected = member_key(key_inc(cur), SUSPECT)
    upd = jnp.where(fail & (key_state(cur) == ALIVE), jnp.maximum(cur, suspected), upd)
    view = view.at[rows, target].set(upd)

    # --- suspicion timeout: suspect -> down -------------------------------
    is_suspect = key_state(view) == SUSPECT
    expired = is_suspect & (tick - suspect_since >= params.suspect_timeout)
    view = jnp.where(expired, member_key(key_inc(view), DOWN), view)

    # --- gossip dissemination ---------------------------------------------
    # freshness-prioritized piggyback (foca's update backlog): each node
    # gossips its LEAST-retransmitted entries, random tie-break; entries
    # past the retransmission limit decay out of circulation entirely
    g = params.gossip_targets
    m = min(params.gossip_entries, n)  # top_k cap on tiny clusters
    gt = rand_peers(k_gt, n, (n, g))  # [N, G] gossip targets
    tie = jax.random.uniform(k_ge, (n, n))
    scores = update_tx.astype(jnp.float32) + tie
    scores = jnp.where(
        update_tx >= params.update_tx_limit, jnp.inf, scores
    )
    _, ge = jax.lax.top_k(-scores, m)  # [N, M] freshest entries
    sendable = (
        jnp.take_along_axis(update_tx, ge, axis=1) < params.update_tx_limit
    )  # [N, M]
    ok = (
        alive[:, None, None]
        & lossy(k_gloss, (n, g, m))
        & alive[gt][:, :, None]
        & sendable[:, None, :]
    )
    payload = view[jnp.arange(n)[:, None], ge]  # [N, M] sender's entries
    payload = jnp.broadcast_to(payload[:, None, :], (n, g, m))
    members = jnp.broadcast_to(ge[:, None, :], (n, g, m))
    flat_idx = jnp.where(
        ok, gt[:, :, None] * n + members, n * n
    ).reshape(-1)
    view = (
        view.reshape(-1).at[flat_idx].max(payload.reshape(-1), mode="drop")
    ).reshape(n, n)
    msgs = msgs + (alive * g).astype(jnp.int32)
    # charge one backlog round per selected sendable entry
    sent_round = sendable & alive[:, None]
    update_tx = update_tx.at[
        jnp.arange(n)[:, None], ge
    ].add(sent_round.astype(jnp.int32))

    # --- probe/ack piggyback dissemination (host parity) ------------------
    # every ping datagram carries the prober's freshest entries and
    # every ack carries the target's (swim_foca _piggyback rides on
    # probe/ack exchanges); same backlog selection, same decay charges,
    # no extra messages (the ping/ack msgs are already counted above)
    rows2 = jnp.arange(n)
    # ping direction: prober i -> target[i], delivered iff the ping was
    pb_flat = jnp.where(
        ping_ok[:, None] & sendable,
        target[:, None] * n + ge, n * n,
    ).reshape(-1)
    pb_payload = view[rows2[:, None], ge]
    view = (
        view.reshape(-1).at[pb_flat].max(
            pb_payload.reshape(-1), mode="drop")
    ).reshape(n, n)
    update_tx = update_tx.at[rows2[:, None], ge].add(
        (sendable & alive[:, None]).astype(jnp.int32)
    )
    # ack direction: target[i] -> prober i, delivered iff the ack was
    ge_t = ge[target]  # [N, M] the target's freshest entries
    sendable_t = sendable[target]
    ack_flat = jnp.where(
        ack_ok[:, None] & sendable_t,
        rows2[:, None] * n + ge_t, n * n,
    ).reshape(-1)
    ack_payload = view[target[:, None], ge_t]
    view = (
        view.reshape(-1).at[ack_flat].max(
            ack_payload.reshape(-1), mode="drop")
    ).reshape(n, n)
    update_tx = update_tx.at[target[:, None], ge_t].add(
        (ping_ok[:, None] & sendable_t).astype(jnp.int32)
    )

    # --- refutation / renewal --------------------------------------------
    # a live node that sees itself non-alive in its own merged row bumps
    # its incarnation past the offending record and re-announces.  A
    # DOWN record that already decayed out of the gossip backlog can't
    # reach the victim that way — the TurnUndead path covers it: the
    # probed peer holds a DOWN record of its prober and tells it
    # directly (foca notify_down_members / TurnUndead, mirrored by the
    # host's swim_foca handler)
    self_key = view[rows, rows]
    peer_rec = view[target, rows]  # [N] probed peer's record of ME
    # TurnUndead is a real exchange: our contact must reach the peer and
    # its reply must come back — same loss model as every other leg
    told_undead = (
        alive & alive[target] & (key_state(peer_rec) == DOWN)
        & lossy(k_tu, (n, 2)).all(axis=1)
    )
    offending = jnp.maximum(
        self_key, jnp.where(told_undead, peer_rec, 0)
    )
    offended = alive & ((key_state(self_key) != ALIVE) | told_undead)
    new_inc = jnp.where(
        offended, key_inc(offending) + 1,
        jnp.maximum(inc, key_inc(self_key)),
    )
    inc = jnp.maximum(inc, new_inc)
    view = view.at[rows, rows].set(
        jnp.where(alive, member_key(inc, ALIVE), self_key)
    )

    # --- suspect_since maintenance ---------------------------------------
    now_suspect = key_state(view) == SUSPECT
    suspect_since = jnp.where(
        now_suspect & (suspect_since == _NEVER), tick, suspect_since
    )
    suspect_since = jnp.where(now_suspect, suspect_since, _NEVER)

    # --- backlog reset: a changed record is fresh news again --------------
    update_tx = jnp.where(view != view_in, 0, update_tx)

    return SwimState(view, suspect_since, inc, msgs, update_tx)
