"""Protocol state machines as array programs.

Each module re-expresses one of the reference's distributed protocols as a
pure, vmappable step function over dense node-indexed state:

* :mod:`corrosion_tpu.models.broadcast` — epidemic broadcast fanout with
  ring0 tiering, retransmit decay, loss/partition masks
  (reference: ``crates/corro-agent/src/broadcast/mod.rs:405-1028``).
* :mod:`corrosion_tpu.models.sync` — anti-entropy set reconciliation
  (reference: ``crates/corro-agent/src/api/peer.rs:344-1719``, needs
  algebra ``sync.rs:127-248``).
* :mod:`corrosion_tpu.models.swim` — SWIM probe/suspect/down membership
  with incarnation refutation (reference: foca runtime loop,
  ``crates/corro-agent/src/broadcast/mod.rs:122-381``).
"""

from corrosion_tpu.models.broadcast import BroadcastParams, broadcast_step
from corrosion_tpu.models.sync import SyncParams, sync_step, bitmap_needs
from corrosion_tpu.models.swim import SwimParams, SwimState, swim_init, swim_step

__all__ = [
    "BroadcastParams",
    "broadcast_step",
    "SyncParams",
    "sync_step",
    "bitmap_needs",
    "SwimParams",
    "SwimState",
    "swim_init",
    "swim_step",
]
