"""Anti-entropy sync as dense set reconciliation.

Reference behavior (``crates/corro-agent/src/api/peer.rs``, scheduler
``agent/util.rs:349-393``): on a decorrelated-jitter interval each node
picks a handful of peers, exchanges ``SyncStateV1`` handshakes, computes
what it's missing that each peer can serve (``sync.rs:127-248``), and the
peers stream the missing changes back in ≤8 KiB chunks.

TPU design: knowledge is dense —

* the **row model** (used by the convergence sims): a peer's full CRDT
  state is its [R] packed-key row vector; a pull-merge from peer ``p``
  is ``max(rows[i], rows[p])`` and the served volume is the count of
  cells where the peer was strictly ahead (that count ÷ cells/chunk =
  chunk messages, the unit the north-star metric counts);
* the **bitmap model** (mirrors the exact host algebra in
  :func:`corrosion_tpu.types.payload.SyncStateV1.compute_available_needs`):
  per-node version bitmaps where ``needs = theirs & ~ours`` — exposed as
  :func:`bitmap_needs` and cross-checked against the host implementation
  in tests.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from corrosion_tpu.models.common import partition_ok, rand_peers


@dataclass(frozen=True)
class SyncParams:
    n_nodes: int
    peers_per_round: int = 1  # concurrent sync partners (ref: 3..10)
    cells_per_chunk: int = 64  # cells that fit one 8 KiB chunk message
    handshake_msgs: int = 2  # SyncStart + State exchange per session
    # seed-flattening (models/common.py): peer draws stay inside the
    # sender's own universe of this width when set
    universe: Optional[int] = None
    # one-way partitions: a sync session needs BOTH directions up (the
    # dial is client→server, the served chunks server→client), so any
    # listed severed direction between the pair kills the session.
    # NOTE deliberately NO wan_cross_loss here: the wan_two_region
    # topology (models/broadcast.py) drops cross-region GOSSIP only —
    # anti-entropy sessions ride QUIC streams with retries, so a
    # session that forms either completes or (under a partition) never
    # forms at all.  Cross-region healing therefore flows through sync,
    # which is what makes the WAN family converge.
    oneway_blocks: Optional[tuple] = None


def bitmap_needs(ours, theirs):
    """Dense needs algebra: versions the peer has that we don't.

    ours/theirs: [..., V] bool knowledge bitmaps over a version universe.
    Mirrors ``compute_available_needs`` restricted to Full needs (the
    bitmap is gap-complete, so head/need/partial distinctions collapse).
    """
    return theirs & ~ours


def session_msgs(msgs_sent, peers, chunks, handshake_msgs, reachable=None):
    """Charge sync-session messages (shared by both sync kernels).

    The client pays half the handshake per session; each serving peer
    pays the other half plus its chunk stream (like the reference's
    server-side send loop).  peers/chunks: [N, P]; chunks are the chunk
    messages each session actually sent.
    """
    if reachable is None:
        reachable = jnp.ones(peers.shape, dtype=bool)
    sessions = jnp.sum(reachable, axis=1)  # [N] sessions as client
    client_msgs = sessions * (handshake_msgs // 2)
    per_server = (
        (handshake_msgs - handshake_msgs // 2) + chunks
    ) * reachable
    server_msgs = (
        jnp.zeros_like(msgs_sent)
        .at[peers.reshape(-1)]
        .add(per_server.reshape(-1).astype(msgs_sent.dtype))
    )
    return msgs_sent + client_msgs.astype(msgs_sent.dtype) + server_msgs


@partial(jax.jit, static_argnames=("params",))
def sync_step(rows, msgs_sent, key, params: SyncParams,
              partition_id=None, partition_active=False):
    """One anti-entropy round: every node pulls from random peers.

    rows:      [N, R] packed CRDT keys
    msgs_sent: [N] int32 cumulative message counter
    Returns (rows', msgs_sent').

    Message accounting per session: ``handshake_msgs`` split between the
    two parties, plus one message per served chunk (charged to the
    server, like the reference's server-side send loop).
    """
    n, p = params.n_nodes, params.peers_per_round
    peers = rand_peers(key, n, (n, p), universe=params.universe)  # [N, P]

    reachable = jnp.ones((n, p), dtype=bool)
    reachable &= partition_ok(
        partition_id, peers, partition_active,
        oneway=params.oneway_blocks, bidirectional=True,
    )

    # pull-merge: what each peer would give us
    peer_rows = rows[peers]  # [N, P, R]
    served_cells = jnp.sum(
        (peer_rows > rows[:, None, :]) & reachable[:, :, None], axis=2
    )  # [N, P] cells each peer is ahead on
    from corrosion_tpu.ops.merge import merge_cells, merge_keys

    merged = merge_cells(
        jnp.where(
            reachable[:, :, None], peer_rows, rows[:, None, :]
        ).swapaxes(0, 1)
    )
    new_rows = merge_keys(rows, merged)

    chunks = -(-served_cells // params.cells_per_chunk)  # [N, P] ceil div
    msgs = session_msgs(
        msgs_sent, peers, chunks, params.handshake_msgs, reachable
    )
    return new_rows, msgs


# -- sequence-chunked reassembly ---------------------------------------
#
# The host protocol never transfers a version atomically: a changeset is
# split into ≤8 KiB chunks of contiguous seq spans
# (``crates/corro-types/src/change.rs`` ChunkedChanges; partial
# buffering/promotion in ``agent/bookkeeping.py``), chunks arrive out of
# order, and the gaps left by lost chunks are recomputed as needs the
# next sync round.  This models that reassembly as a first-class
# vectorized structure: a dense [N, S] seq bitmap per node, with the gap
# algebra (``utils/ranges.py`` RangeSet) collapsing to bitwise ops.


@dataclass(frozen=True)
class SeqSyncParams:
    n_nodes: int
    n_seqs: int  # seqs in the changeset under reassembly
    peers_per_round: int = 1  # subset peer selection
    seqs_per_chunk: int = 8  # contiguous seqs per chunk message
    chunk_budget: int = 4  # chunks a server sends per session
    loss: float = 0.0  # per-CHUNK drop probability
    handshake_msgs: int = 2
    # seed-flattening (models/common.py)
    universe: Optional[int] = None


def bitmap_gaps(bits):
    """Missing-seq bitmap — the dense twin of ``RangeSet.gaps``.

    bits: [..., S] bool (seqs held).  The host agent keeps the same fact
    as sparse spans; tests cross-check the two representations.
    """
    return ~bits


@partial(jax.jit, static_argnames=("params",))
def seq_sync_step(bits, msgs_sent, key, params: SeqSyncParams):
    """One anti-entropy round over partially-reassembled changesets.

    bits:      [N, S] bool — seqs each node holds (buffered partials)
    msgs_sent: [N] int32 cumulative message counter
    Returns (bits', msgs_sent').

    Each node pulls from ``peers_per_round`` random peers.  A serving
    peer walks the client's needs (``peer & ~mine`` — exactly the
    RangeSet gap algebra, dense) in ascending seq order and sends up to
    ``chunk_budget`` chunks of ``seqs_per_chunk`` seqs.  Each chunk is
    dropped i.i.d. with ``loss`` — a lost chunk while later chunks of
    the same session land is precisely out-of-order arrival, and the
    hole it leaves is healed by a later round recomputing needs from the
    bitmap.  Partial holders serve their partials (complementary-partial
    serving, ``runtime.py`` _serve_need parity).
    """
    n, p = params.n_nodes, params.peers_per_round
    spc, budget = params.seqs_per_chunk, params.chunk_budget
    k_peers, k_drop = jax.random.split(key)

    peers = rand_peers(k_peers, n, (n, p), universe=params.universe)  # [N, P]
    peer_bits = bits[peers]  # [N, P, S]
    needs = peer_bits & ~bits[:, None, :]  # [N, P, S] gap algebra

    # serve in ascending seq order, capped at the session budget
    order = jnp.cumsum(needs.astype(jnp.int32), axis=2)  # 1-based rank
    served = needs & (order <= budget * spc)
    # chunk index of each served seq within its session
    chunk_of = jnp.clip((order - 1) // spc, 0, budget - 1)  # [N, P, S]
    dropped = (
        jax.random.uniform(k_drop, (n, p, budget)) < params.loss
    )  # [N, P, B]
    # expand each seq's chunk fate by a static select per chunk slot:
    # take_along_axis lowers to a serialized per-element gather on TPU
    # (measured ~20x the whole rest of the round); budget is tiny and
    # static, so B elementwise selects replace it
    drop_of = jnp.zeros_like(served)
    for b in range(budget):
        drop_of |= (chunk_of == b) & dropped[:, :, b][:, :, None]
    arrived = served & ~drop_of
    new_bits = bits | jnp.any(arrived, axis=1)

    chunks = -(-jnp.sum(served, axis=2) // spc)  # [N, P] ceil
    msgs = session_msgs(msgs_sent, peers, chunks, params.handshake_msgs)
    return new_bits, msgs
