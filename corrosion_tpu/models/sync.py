"""Anti-entropy sync as dense set reconciliation.

Reference behavior (``crates/corro-agent/src/api/peer.rs``, scheduler
``agent/util.rs:349-393``): on a decorrelated-jitter interval each node
picks a handful of peers, exchanges ``SyncStateV1`` handshakes, computes
what it's missing that each peer can serve (``sync.rs:127-248``), and the
peers stream the missing changes back in ≤8 KiB chunks.

TPU design: knowledge is dense —

* the **row model** (used by the convergence sims): a peer's full CRDT
  state is its [R] packed-key row vector; a pull-merge from peer ``p``
  is ``max(rows[i], rows[p])`` and the served volume is the count of
  cells where the peer was strictly ahead (that count ÷ cells/chunk =
  chunk messages, the unit the north-star metric counts);
* the **bitmap model** (mirrors the exact host algebra in
  :func:`corrosion_tpu.types.payload.SyncStateV1.compute_available_needs`):
  per-node version bitmaps where ``needs = theirs & ~ours`` — exposed as
  :func:`bitmap_needs` and cross-checked against the host implementation
  in tests.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp

from corrosion_tpu.models.common import partition_ok, rand_peers


@dataclass(frozen=True)
class SyncParams:
    n_nodes: int
    peers_per_round: int = 1  # concurrent sync partners (ref: 3..10)
    cells_per_chunk: int = 64  # cells that fit one 8 KiB chunk message
    handshake_msgs: int = 2  # SyncStart + State exchange per session


def bitmap_needs(ours, theirs):
    """Dense needs algebra: versions the peer has that we don't.

    ours/theirs: [..., V] bool knowledge bitmaps over a version universe.
    Mirrors ``compute_available_needs`` restricted to Full needs (the
    bitmap is gap-complete, so head/need/partial distinctions collapse).
    """
    return theirs & ~ours


@partial(jax.jit, static_argnames=("params",))
def sync_step(rows, msgs_sent, key, params: SyncParams,
              partition_id=None, partition_active=False):
    """One anti-entropy round: every node pulls from random peers.

    rows:      [N, R] packed CRDT keys
    msgs_sent: [N] int32 cumulative message counter
    Returns (rows', msgs_sent').

    Message accounting per session: ``handshake_msgs`` split between the
    two parties, plus one message per served chunk (charged to the
    server, like the reference's server-side send loop).
    """
    n, p = params.n_nodes, params.peers_per_round
    peers = rand_peers(key, n, (n, p))  # [N, P], never self

    reachable = jnp.ones((n, p), dtype=bool)
    reachable &= partition_ok(partition_id, peers, partition_active)

    # pull-merge: what each peer would give us
    peer_rows = rows[peers]  # [N, P, R]
    served_cells = jnp.sum(
        (peer_rows > rows[:, None, :]) & reachable[:, :, None], axis=2
    )  # [N, P] cells each peer is ahead on
    merged = jnp.max(
        jnp.where(reachable[:, :, None], peer_rows, rows[:, None, :]), axis=1
    )
    new_rows = jnp.maximum(rows, merged)

    # accounting: the client pays half the handshake per session; each
    # serving peer pays the other half plus its chunk stream
    sessions = jnp.sum(reachable, axis=1)  # [N] sessions as client
    chunks = -(-served_cells // params.cells_per_chunk)  # [N, P] ceil div
    client_msgs = sessions * (params.handshake_msgs // 2)
    per_server = (
        (params.handshake_msgs - params.handshake_msgs // 2) + chunks
    ) * reachable
    server_msgs = (
        jnp.zeros_like(msgs_sent)
        .at[peers.reshape(-1)]
        .add(per_server.reshape(-1).astype(msgs_sent.dtype))
    )
    msgs = msgs_sent + client_msgs.astype(msgs_sent.dtype) + server_msgs
    return new_rows, msgs
