"""Epidemic broadcast fanout as a masked scatter kernel.

Reference behavior (``crates/corro-agent/src/broadcast/mod.rs``):

* a node holding a changeset transmits it to a random sample of peers,
  preferring its **ring0** (lowest-RTT) tier first, then a global random
  sample (``:586-702``);
* each payload is retransmitted on subsequent rounds until its
  ``send_count`` reaches ``max_transmissions`` (``:745-765``);
* nodes that *receive* a broadcast-sourced changeset rebroadcast it with
  their own transmission budget (``handlers.rs:939-949``).

TPU design: delivery is formulated RECEIVER-side as permutation-fanout
(see :func:`_deliver_perm`): each fanout column is a random within-block
permutation, so every receiver gathers from the unique sender that
picked it — one batched argsort + one gather per column, no scatter.
Scatter on TPU serializes over colliding updates and measured ~13x
slower than the equivalent gathers at N=100k; the exact sender-side
sampler (with per-payload ``sent_to`` exclusion) is retained for
calibration scale via ``track_sent``.  Ring0 is modeled as a contiguous
index block of ~``ring0_size`` peers (the sim's stand-in for the
RTT<6ms tier); the rest of the fanout permutes over the whole universe.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from corrosion_tpu.models.common import partition_ok, severance_matrix
from corrosion_tpu.ops.merge import merge_keys, scatter_merge


@dataclass(frozen=True)
class BroadcastParams:
    n_nodes: int
    fanout_ring0: int = 2  # sends/tick into the ring0 block
    fanout_global: int = 2  # sends/tick into the whole cluster
    ring0_size: int = 256  # ring0 block width (RTT<6ms tier stand-in)
    max_transmissions: int = 8  # retransmit decay budget per payload
    loss: float = 0.0  # per-message drop probability
    # retransmission backoff in ticks: the nth retransmission waits
    # backoff_ticks*n after the previous send (the reference requeues
    # with 100ms*send_count, broadcast/mod.rs:745-765, while FRESH
    # payloads forward within one flush interval — so infection trees
    # run deeper than synchronous-round models predict).  0 = send
    # every tick (legacy synchronous-rounds behavior).
    backoff_ticks: float = 0.0
    # seed-flattening (models/common.py): when set, n_nodes is S
    # side-by-side universes of this width and peer draws stay inside
    # the sender's own universe — so one UNBATCHED scatter serves all
    # universes (batched scatter serializes on TPU, ~70x slower)
    universe: Optional[int] = None
    # one-way partitions (FaultPlan.oneway_blocks): exactly these
    # directed (src_block, dst_block) pairs sever while the partition
    # is active; None = symmetric (the original behavior)
    oneway_blocks: Optional[tuple] = None
    # scenario families beyond uniform fanout (EpidemicConfig mirrors):
    # - het_ring: node i (universe-local) sits on RTT tier
    #   1 + i*rtt_tiers//u of a ring by id; its retransmit gap and its
    #   first post-learn forward scale with the tier;
    # - wan_two_region: node i lives in region i*wan_blocks//u; gossip
    #   crossing regions suffers an EXTRA i.i.d. wan_cross_loss drop on
    #   top of ``loss`` (long-RTT datagram timeouts).  Anti-entropy
    #   sessions cross unharmed (QUIC streams with retries) — see
    #   models/sync.py.  ``uniform`` executes the pre-topology path.
    # - measured_ring: het_ring with a DATA-DRIVEN tier map — node
    #   tiers follow ``rtt_tier_weights``, the per-tier node-count
    #   weights of a measured Members RTT-ring distribution
    #   (``corro admin rtt dump`` / ``capture_rtt_topology``).
    topology: str = "uniform"
    rtt_tiers: int = 4
    wan_blocks: int = 2
    wan_cross_loss: float = 0.25
    # measured_ring only; a tuple so the params stay hashable
    rtt_tier_weights: Optional[tuple] = None

    @property
    def fanout(self) -> int:
        return self.fanout_ring0 + self.fanout_global


def measured_tier_map(n: int, weights) -> jnp.ndarray:
    """[n] int32 tier map (1..len(weights)) from measured per-tier
    node-count weights: tier t covers the next ``round(n *
    weights[t-1] / sum)`` ids of the ring.  Plain numpy cumsum/
    searchsorted over STATIC inputs, so under jit it constant-folds —
    the shared tier-map core of the perm kernel's ``measured_ring``
    and the exact kernels' (sim/calibrate.py ``_rtt_tier_of``)."""
    import numpy as np

    w = np.asarray(weights, np.float64)
    if w.ndim != 1 or w.size < 1 or (w < 0).any() or w.sum() <= 0:
        raise ValueError(
            "measured tier weights must be a non-empty 1-D sequence "
            "of non-negative values with a positive sum"
        )
    bounds = np.ceil(np.cumsum(w) / w.sum() * n).astype(np.int64)
    bounds[-1] = n  # guard the float tail: the last tier always closes
    tiers = 1 + np.searchsorted(bounds, np.arange(n), side="right")
    return jnp.asarray(tiers, jnp.int32)


def _rtt_tier(params: "BroadcastParams"):
    """[N] int32 RTT tier of the het_ring (synthetic 1..rtt_tiers
    ramp) or measured_ring (data-driven weights) topology,
    universe-local, or None on other topologies — static arithmetic,
    constant-folds."""
    if params.topology == "measured_ring":
        u = params.universe or params.n_nodes
        per_u = measured_tier_map(u, params.rtt_tier_weights)
        reps = -(-params.n_nodes // u)
        return jnp.tile(per_u, reps)[: params.n_nodes]
    if params.topology != "het_ring":
        return None
    u = params.universe or params.n_nodes
    local = jnp.arange(params.n_nodes, dtype=jnp.int32) % u
    return 1 + (local * params.rtt_tiers) // u


def _wan_region(params: "BroadcastParams"):
    """[N] int32 wan_two_region region id (universe-local), else None."""
    if params.topology != "wan_two_region" or params.wan_cross_loss <= 0.0:
        return None
    u = params.universe or params.n_nodes
    local = jnp.arange(params.n_nodes, dtype=jnp.int32) % u
    return (local * params.wan_blocks) // u


# sentinel hop depth for "not yet infected" (far above any real depth)
HOP_UNSET = jnp.int32(2**30)


class BroadcastStep(NamedTuple):
    """One-shape result for every broadcast_step variant; optional
    outputs are None when the corresponding input wasn't supplied."""

    rows: jnp.ndarray
    tx_remaining: jnp.ndarray
    msgs_sent: jnp.ndarray
    hops: Optional[jnp.ndarray] = None
    next_send: Optional[jnp.ndarray] = None
    sent: Optional[jnp.ndarray] = None


@partial(jax.jit, static_argnames=("params",))
def broadcast_step(rows, tx_remaining, msgs_sent, key, params: BroadcastParams,
                   partition_id=None, partition_active=False, hops=None,
                   tick=None, next_send=None, sent=None) -> BroadcastStep:
    """One gossip tick for every node at once.

    rows:         [N, R] packed CRDT keys (the node's table state)
    tx_remaining: [N] int32 remaining transmissions for the node's
                  current knowledge (0 = quiescent)
    msgs_sent:    [N] int32 cumulative sent-message counter
    key:          PRNG key for this tick
    partition_id: [N] int32 block id; messages crossing blocks are dropped
                  while ``partition_active`` (pass a traced bool)
    hops:         optional [N] int32 infection-tree depth (HOP_UNSET =
                  not infected); maintained by scatter-min of
                  sender_hop+1 over delivering messages — directly
                  comparable to the live agent's debug_hops counter
    sent:         optional [N, N] bool per-payload transmission memory —
                  the agent's ``sent_to`` set: a sender never re-picks a
                  peer it already transmitted this payload to
                  (broadcast/mod.rs member sampling).  Quadratic state:
                  calibration-scale only.  Draws become uniform
                  without-replacement over the not-yet-sent peers
                  (ring0/global split is ignored in this mode, matching
                  the ring0_enabled=False calibration harness).

    Returns a :class:`BroadcastStep` (hops'/next_send'/sent' are None
    when the corresponding input wasn't supplied).
    """
    n, k = params.n_nodes, params.fanout
    key_t, key_l = jax.random.split(key)

    active = tx_remaining > 0  # [N]
    if next_send is not None:
        if tick is None:
            raise ValueError("next_send requires tick")
        active &= next_send <= tick

    if sent is not None:
        if params.universe is not None:
            raise ValueError(
                "sent-tracking ([N, N] memory) is calibration-scale "
                "only and incompatible with seed-flattened universes"
            )
        # uniform sample WITHOUT replacement over peers not yet sent to:
        # random scores, exclusions pushed to +inf, take the k smallest
        scores = jax.random.uniform(key_t, (n, n))
        excluded = sent | jnp.eye(n, dtype=bool)
        scores = jnp.where(excluded, jnp.inf, scores)
        order = jnp.argsort(scores, axis=1)
        targets = order[:, :k]  # [N, K]
        avail = jnp.take_along_axis(scores, targets, axis=1) < jnp.inf
        # message viability: sender active, not lost, not across a partition
        ok = jnp.broadcast_to(active[:, None], (n, k)) & avail
        if params.loss > 0.0:
            ok &= jax.random.uniform(key_l, (n, k)) >= params.loss
        ok &= partition_ok(partition_id, targets, partition_active,
                           oneway=params.oneway_blocks)
        region = _wan_region(params)
        if region is not None:
            # the extra draw only exists on the wan topology, so every
            # other config's RNG stream is byte-identical
            wan_drop = jax.random.uniform(
                jax.random.fold_in(key_l, 1), (n, k)
            ) < params.wan_cross_loss
            ok &= ~((region[:, None] != region[targets]) & wan_drop)

        # masked delivery: dead messages point past the end and get
        # dropped.  Scatter-max is associative, so K column scatters
        # equal the combined [N*K] scatter without materializing the
        # [N*K, R] repeat of every payload
        masked = jnp.where(ok, targets, n)  # [N, K]
        new_rows = rows
        for j in range(k):
            # delivery IS the CRDT join: scatter-max of the senders'
            # packed keys into the receivers' rows (ops/merge.py)
            new_rows = scatter_merge(new_rows, masked[:, j], rows)
        learned = jnp.any(new_rows != rows, axis=1)
        cand = None
        if hops is not None:
            # first-infection depth: min over this tick's delivering
            # senders (same per-column structure as delivery)
            sender_hops = jnp.minimum(hops, HOP_UNSET) + 1  # [N]
            cand = jnp.full((n + 1,), HOP_UNSET, jnp.int32)
            for j in range(k):
                cand = cand.at[masked[:, j]].min(sender_hops)
            cand = cand[:n]
    else:
        new_rows, learned, cand = _deliver_perm(
            rows, active, hops, key_t, key_l, params,
            partition_id, partition_active,
        )

    # retransmit decay for senders; fresh budget for nodes that learned
    # something new (rebroadcast semantics)
    tx = jnp.where(active, tx_remaining - 1, tx_remaining)
    tx = jnp.where(learned, params.max_transmissions, tx)

    new_sent = None
    if sent is not None:
        # sent_to marks on SEND (before loss/partition: the sender can't
        # know the message died), and the charge is per actual send —
        # a sender with fewer than k fresh peers transmits fewer
        marks = jnp.broadcast_to(active[:, None], (n, k)) & avail
        senders = jnp.repeat(jnp.arange(n), k)
        mark_cols = jnp.where(marks, targets, n).reshape(-1)
        new_sent = sent.at[senders, mark_cols].set(True, mode="drop")
        msgs = msgs_sent + jnp.sum(marks, axis=1).astype(msgs_sent.dtype)
    else:
        msgs = msgs_sent + jnp.where(active, k, 0).astype(msgs_sent.dtype)
    nxt = None
    if next_send is not None:
        # nth retransmission waits backoff*n ticks; a fresh payload
        # (learner) forwards on the very next tick — both scaled by the
        # node's RTT tier on the het_ring topology
        send_count = params.max_transmissions - tx  # nth send just made
        gap = jnp.maximum(
            1,
            jnp.round(params.backoff_ticks * send_count).astype(jnp.int32),
        )
        tier = _rtt_tier(params)
        first = 1
        if tier is not None:
            gap = gap * tier
            first = tier
        nxt = jnp.where(active, tick + gap, next_send)
        nxt = jnp.where(learned, tick + first, nxt)
    new_hops = None
    if hops is not None:
        new_hops = jnp.where(learned, jnp.minimum(hops, cand), hops)
    return BroadcastStep(new_rows, tx, msgs, new_hops, nxt, new_sent)


def _largest_divisor_upto(n: int, cap: int) -> int:
    """Largest divisor of n that is <= cap (static Python helper)."""
    cap = max(1, min(cap, n))
    for d in range(cap, 0, -1):
        if n % d == 0:
            return d
    return 1


def _perm_senders(key_t, j: int, n: int, u: int, ring0: bool,
                  ring0_size: int):
    """[N] receiver->sender map for fanout column ``j`` (shared by the
    single-chip kernel and the sharded fabric — any change here must
    keep both bitwise identical; tests/test_sharding.py pins it).

    Global columns: inverse of a uniform random permutation within each
    width-``u`` universe (one batched argsort — the inverse of a uniform
    permutation is itself uniform).

    Ring0 columns: permutation within aligned blocks of b0 | u nodes,
    b0 the largest divisor of u <= ring0_size.  When u has no useful
    divisor (e.g. prime u: b0 == 1 would make the column pure
    self-sends), fall back to a receiver-side sliding-window draw —
    sender = t - off, off in [1, min(ring0_size, u-1)] — which keeps
    in-degree exactly 1 per column for every u at the cost of
    Binomial out-degree for ring0 sends.
    """
    kj = jax.random.fold_in(key_t, j)
    idx = jnp.arange(n, dtype=jnp.int32)
    if ring0:
        b0 = _largest_divisor_upto(u, ring0_size)
        if b0 < 2 or b0 < min(ring0_size, u - 1) // 4:
            hi = min(ring0_size, u - 1) if u > 1 else 1
            offs = jax.random.randint(kj, (n,), 1, hi + 1)
            local = idx % u
            return idx - local + (local - offs) % u
        block = b0
    else:
        block = u
    scores = jax.random.uniform(kj, (n // block, block))
    inv = jnp.argsort(scores, axis=1).reshape(-1).astype(jnp.int32)
    return idx - idx % block + inv


def _deliver_perm(rows, active, hops, key_t, key_l, params: BroadcastParams,
                  partition_id, partition_active):
    """Permutation-fanout delivery: the TPU-fast path.

    Scatter on TPU serializes over colliding updates (measured ~190 ms
    per 3.2M-update scatter on v5e vs ~15 ms for the same-volume
    gather), so delivery is reformulated receiver-side: each fanout
    column is a random within-block permutation pi, sender i transmits
    to pi(i), and every receiver t hears from the unique sender
    pi^-1(t) — one GATHER per column, no scatter anywhere.  The inverse
    of a uniform random permutation is itself uniform, so one batched
    argsort per column draws pi^-1 directly.

    Parity notes vs the reference sampler (broadcast/mod.rs:586-702):
    out-degree is exactly K per active sender (same as the reference's
    k distinct picks); in-degree is exactly K per column instead of
    Binomial(~K) — collision-free fanout reaches fresh peers with
    fewer redundant messages (measured msgs-at-convergence ~0.65x the
    exact sent_to-excluding sampler at N=256/fanout 3, ~0.75x the
    independent-draw scatter model at N=100k), so large-N msgs/node
    reads as a lower bound on the exact protocol's; the exact sampler
    stays the calibration reference (track_sent + simdiff).  pi(i)=i
    (probability 1/block) is a self-send: a no-op merge, matching a
    message to an already-infected peer.  The ring0
    tier is a permutation within aligned blocks of ~ring0_size
    neighbors (the contiguous-block RTT<6ms stand-in, same as the
    scatter path's offset draw).  The exact sampler (per-payload
    sent_to exclusion) remains available via track_sent at
    calibration scale.
    """
    n, k = params.n_nodes, params.fanout
    r_width = rows.shape[1]
    u = params.universe or n

    # pack everything delivery needs from the sender into ONE gatherable
    # array: [rows | sender_hop_or_inactive | partition_id] — separate
    # [N]-wide gathers cost almost as much as the [N, R] row gather, so
    # one packed gather per column replaces four.  The hop value doubles
    # as the activity flag, so an ACTIVE sender's hop is clamped below
    # the sentinel: a sender granted tx budget while never infected via
    # broadcast (hops == HOP_UNSET, e.g. healed by sync) must still
    # deliver — its receivers record depth HOP_UNSET-1 ("unknown")
    if hops is not None:
        shops = jnp.where(
            active, jnp.minimum(hops, HOP_UNSET - 2) + 1, HOP_UNSET
        )
    else:
        shops = jnp.where(active, 0, HOP_UNSET)
    cols = [rows, shops[:, None]]
    if partition_id is not None:
        cols.append(partition_id.astype(jnp.int32)[:, None])
    packed = jnp.concatenate(cols, axis=1)

    if params.loss > 0.0:
        drop = jax.random.uniform(key_l, (n, k)) < params.loss
    region = _wan_region(params)
    if region is not None:
        # wan-only extra draw: other configs' streams stay byte-equal
        wan_drop = jax.random.uniform(
            jax.random.fold_in(key_l, 1), (n, k)
        ) < params.wan_cross_loss

    new_rows = rows
    cand = jnp.full((n,), HOP_UNSET, jnp.int32)
    for j in range(k):
        sender = _perm_senders(
            key_t, j, n, u, j < params.fanout_ring0, params.ring0_size
        )
        g = packed[sender]  # [N, R+1(+1)]
        sh = g[:, r_width]
        valid = sh < HOP_UNSET  # sender was actively transmitting
        if params.loss > 0.0:
            valid &= ~drop[:, j]
        if region is not None:
            valid &= ~((region[sender] != region) & wan_drop[:, j])
        if partition_id is not None:
            # direction of flow is sender → receiver: the gathered
            # column carries the SENDER's block id
            spid = g[:, r_width + 1].astype(jnp.int32)
            rpid = partition_id.astype(jnp.int32)
            if params.oneway_blocks:
                sev = severance_matrix(params.oneway_blocks)
                b = sev.shape[0]
                cross = sev[
                    jnp.minimum(spid, b - 1), jnp.minimum(rpid, b - 1)
                ]
            else:
                cross = rpid != spid
            valid &= ~(cross & partition_active)
        new_rows = merge_keys(
            new_rows, jnp.where(valid[:, None], g[:, :r_width], rows)
        )
        cand = jnp.minimum(cand, jnp.where(valid, sh, HOP_UNSET))
    learned = jnp.any(new_rows != rows, axis=1)
    return new_rows, learned, (cand if hops is not None else None)
