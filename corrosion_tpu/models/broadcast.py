"""Epidemic broadcast fanout as a masked scatter kernel.

Reference behavior (``crates/corro-agent/src/broadcast/mod.rs``):

* a node holding a changeset transmits it to a random sample of peers,
  preferring its **ring0** (lowest-RTT) tier first, then a global random
  sample (``:586-702``);
* each payload is retransmitted on subsequent rounds until its
  ``send_count`` reaches ``max_transmissions`` (``:745-765``);
* nodes that *receive* a broadcast-sourced changeset rebroadcast it with
  their own transmission budget (``handlers.rs:939-949``).

TPU design: all N nodes' sends in one tick are a single [N, K] target
draw; delivery is one scatter-max of packed CRDT keys with loss and
partition masks folded in by pointing masked messages at an out-of-range
row (``mode="drop"``).  Ring0 is modeled as a contiguous index block of
``ring0_size`` peers around the sender (the sim's stand-in for the RTT<6ms
tier); the rest of the fanout is a uniform global draw.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from corrosion_tpu.models.common import block_peers, partition_ok, rand_peers


@dataclass(frozen=True)
class BroadcastParams:
    n_nodes: int
    fanout_ring0: int = 2  # sends/tick into the ring0 block
    fanout_global: int = 2  # sends/tick into the whole cluster
    ring0_size: int = 256  # ring0 block width (RTT<6ms tier stand-in)
    max_transmissions: int = 8  # retransmit decay budget per payload
    loss: float = 0.0  # per-message drop probability
    # retransmission backoff in ticks: the nth retransmission waits
    # backoff_ticks*n after the previous send (the reference requeues
    # with 100ms*send_count, broadcast/mod.rs:745-765, while FRESH
    # payloads forward within one flush interval — so infection trees
    # run deeper than synchronous-round models predict).  0 = send
    # every tick (legacy synchronous-rounds behavior).
    backoff_ticks: float = 0.0

    @property
    def fanout(self) -> int:
        return self.fanout_ring0 + self.fanout_global


def _draw_targets(key, params: BroadcastParams):
    """[N, K] target draw: ring0 block neighbors first, then global."""
    n = params.n_nodes
    key_r, key_g = jax.random.split(key)
    ring0_targets = block_peers(
        key_r, n, (n, params.fanout_ring0), params.ring0_size
    )
    global_targets = rand_peers(key_g, n, (n, params.fanout_global))
    return jnp.concatenate([ring0_targets, global_targets], axis=1)


# sentinel hop depth for "not yet infected" (far above any real depth)
HOP_UNSET = jnp.int32(2**30)


class BroadcastStep(NamedTuple):
    """One-shape result for every broadcast_step variant; optional
    outputs are None when the corresponding input wasn't supplied."""

    rows: jnp.ndarray
    tx_remaining: jnp.ndarray
    msgs_sent: jnp.ndarray
    hops: Optional[jnp.ndarray] = None
    next_send: Optional[jnp.ndarray] = None
    sent: Optional[jnp.ndarray] = None


@partial(jax.jit, static_argnames=("params",))
def broadcast_step(rows, tx_remaining, msgs_sent, key, params: BroadcastParams,
                   partition_id=None, partition_active=False, hops=None,
                   tick=None, next_send=None, sent=None) -> BroadcastStep:
    """One gossip tick for every node at once.

    rows:         [N, R] packed CRDT keys (the node's table state)
    tx_remaining: [N] int32 remaining transmissions for the node's
                  current knowledge (0 = quiescent)
    msgs_sent:    [N] int32 cumulative sent-message counter
    key:          PRNG key for this tick
    partition_id: [N] int32 block id; messages crossing blocks are dropped
                  while ``partition_active`` (pass a traced bool)
    hops:         optional [N] int32 infection-tree depth (HOP_UNSET =
                  not infected); maintained by scatter-min of
                  sender_hop+1 over delivering messages — directly
                  comparable to the live agent's debug_hops counter
    sent:         optional [N, N] bool per-payload transmission memory —
                  the agent's ``sent_to`` set: a sender never re-picks a
                  peer it already transmitted this payload to
                  (broadcast/mod.rs member sampling).  Quadratic state:
                  calibration-scale only.  Draws become uniform
                  without-replacement over the not-yet-sent peers
                  (ring0/global split is ignored in this mode, matching
                  the ring0_enabled=False calibration harness).

    Returns a :class:`BroadcastStep` (hops'/next_send'/sent' are None
    when the corresponding input wasn't supplied).
    """
    n, k = params.n_nodes, params.fanout
    key_t, key_l = jax.random.split(key)

    active = tx_remaining > 0  # [N]
    if next_send is not None:
        if tick is None:
            raise ValueError("next_send requires tick")
        active &= next_send <= tick

    if sent is not None:
        # uniform sample WITHOUT replacement over peers not yet sent to:
        # random scores, exclusions pushed to +inf, take the k smallest
        scores = jax.random.uniform(key_t, (n, n))
        excluded = sent | jnp.eye(n, dtype=bool)
        scores = jnp.where(excluded, jnp.inf, scores)
        order = jnp.argsort(scores, axis=1)
        targets = order[:, :k]  # [N, K]
        avail = jnp.take_along_axis(scores, targets, axis=1) < jnp.inf
    else:
        targets = _draw_targets(key_t, params)  # [N, K]
        avail = None

    # message viability: sender active, not lost, not across a partition
    ok = jnp.broadcast_to(active[:, None], (n, k))
    if avail is not None:
        ok &= avail
    if params.loss > 0.0:
        ok &= jax.random.uniform(key_l, (n, k)) >= params.loss
    ok &= partition_ok(partition_id, targets, partition_active)

    # masked delivery: dead messages point past the end and get dropped.
    # One scatter per fanout column, each carrying the senders' rows
    # directly — scatter-max is associative, so K column scatters equal
    # the combined [N*K] scatter, WITHOUT materializing the [N*K, R]
    # jnp.repeat of every payload (~20% of the 100k-node tick's wall)
    masked = jnp.where(ok, targets, n)  # [N, K]
    new_rows = rows
    for j in range(k):
        new_rows = new_rows.at[masked[:, j]].max(rows, mode="drop")

    # retransmit decay for senders; fresh budget for nodes that learned
    # something new (rebroadcast semantics)
    learned = jnp.any(new_rows != rows, axis=1)
    tx = jnp.where(active, tx_remaining - 1, tx_remaining)
    tx = jnp.where(learned, params.max_transmissions, tx)

    new_sent = None
    if sent is not None:
        # sent_to marks on SEND (before loss/partition: the sender can't
        # know the message died), and the charge is per actual send —
        # a sender with fewer than k fresh peers transmits fewer
        marks = jnp.broadcast_to(active[:, None], (n, k)) & avail
        senders = jnp.repeat(jnp.arange(n), k)
        mark_cols = jnp.where(marks, targets, n).reshape(-1)
        new_sent = sent.at[senders, mark_cols].set(True, mode="drop")
        msgs = msgs_sent + jnp.sum(marks, axis=1).astype(msgs_sent.dtype)
    else:
        msgs = msgs_sent + jnp.where(active, k, 0).astype(msgs_sent.dtype)
    nxt = None
    if next_send is not None:
        # nth retransmission waits backoff*n ticks; a fresh payload
        # (learner) forwards on the very next tick
        send_count = params.max_transmissions - tx  # nth send just made
        gap = jnp.maximum(
            1,
            jnp.round(params.backoff_ticks * send_count).astype(jnp.int32),
        )
        nxt = jnp.where(active, tick + gap, next_send)
        nxt = jnp.where(learned, tick + 1, nxt)
    new_hops = None
    if hops is not None:
        # first-infection depth: min over this tick's delivering senders
        # (same per-column structure as delivery; scatter-min associates)
        sender_hops = jnp.minimum(hops, HOP_UNSET) + 1  # [N]
        cand = jnp.full((n + 1,), HOP_UNSET, jnp.int32)
        for j in range(k):
            cand = cand.at[masked[:, j]].min(sender_hops)
        cand = cand[:n]
        new_hops = jnp.where(learned, jnp.minimum(hops, cand), hops)
    return BroadcastStep(new_rows, tx, msgs, new_hops, nxt, new_sent)
