"""Shared topology primitives for the protocol models.

Seed-flattening (the TPU batching strategy): batched (vmapped) scatter
serializes over the batch dimension on TPU — measured ~70x slower than
the same scatter unbatched — so multi-universe simulations place their
S independent universes side by side in ONE flat index space of
``S * n`` nodes instead of vmapping.  ``universe`` below is the
universe (block) width: peer draws stay inside the caller's own
universe, which keeps the universes statistically independent while
every scatter/gather in the tick runs unbatched at full width.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def _local_base(n: int, shape, universe: Optional[int]):
    """(local index, block base) for block-local modular arithmetic."""
    rows = jnp.arange(n, dtype=jnp.int32).reshape(
        (n,) + (1,) * (len(shape) - 1)
    )
    if universe is None:
        return rows, 0, n
    if n % universe:
        # a partial trailing block would draw out-of-range peers that
        # gather-clamping silently folds back onto self
        raise ValueError(f"universe {universe} must divide n_nodes {n}")
    return rows % universe, rows - rows % universe, universe


def rand_peers(key, n: int, shape, universe: Optional[int] = None):
    """Uniform random peers, never self.

    shape's leading dim must be n (one row per node); each entry is drawn
    as ``(local + offset) % u`` with offset in 1..u-1, where ``u`` is the
    universe width (defaults to the whole cluster).  With ``universe``
    set, draws never leave the caller's own block of ``u`` nodes.
    """
    local, base, u = _local_base(n, shape, universe)
    offs = jax.random.randint(key, shape, 1, max(u, 2))
    return base + (local + offs) % u


def partition_ok(partition_id, senders_axis_targets, active):
    """True where a message does NOT cross an active partition boundary.

    partition_id: [N] block ids or None (no partition).
    senders_axis_targets: [N, ...] target indices (row i = sender i).
    active: traced bool (partition currently in force).
    """
    if partition_id is None:
        return True
    cross = (
        partition_id.reshape((-1,) + (1,) * (senders_axis_targets.ndim - 1))
        != partition_id[senders_axis_targets]
    )
    return ~(cross & active)
