"""Shared topology primitives for the protocol models."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rand_peers(key, n: int, shape):
    """Uniform random peers, never self.

    shape's leading dim must be n (one row per node); each entry is drawn
    as ``(row + offset) % n`` with offset in 1..n-1.
    """
    offs = jax.random.randint(key, shape, 1, max(n, 2))
    rows = jnp.arange(n, dtype=jnp.int32).reshape((n,) + (1,) * (len(shape) - 1))
    return (rows + offs) % n


def block_peers(key, n: int, shape, block: int):
    """Random peers within a contiguous index block of ``block`` neighbors
    (offsets 1..block inclusive, capped at n-1), never self."""
    hi = min(block, n - 1) if n > 1 else 1
    offs = jax.random.randint(key, shape, 1, hi + 1)
    rows = jnp.arange(n, dtype=jnp.int32).reshape((n,) + (1,) * (len(shape) - 1))
    return (rows + offs) % n


def partition_ok(partition_id, senders_axis_targets, active):
    """True where a message does NOT cross an active partition boundary.

    partition_id: [N] block ids or None (no partition).
    senders_axis_targets: [N, ...] target indices (row i = sender i).
    active: traced bool (partition currently in force).
    """
    if partition_id is None:
        return True
    cross = (
        partition_id.reshape((-1,) + (1,) * (senders_axis_targets.ndim - 1))
        != partition_id[senders_axis_targets]
    )
    return ~(cross & active)
