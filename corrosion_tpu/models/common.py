"""Shared topology primitives for the protocol models.

Seed-flattening (the TPU batching strategy): batched (vmapped) scatter
serializes over the batch dimension on TPU — measured ~70x slower than
the same scatter unbatched — so multi-universe simulations place their
S independent universes side by side in ONE flat index space of
``S * n`` nodes instead of vmapping.  ``universe`` below is the
universe (block) width: peer draws stay inside the caller's own
universe, which keeps the universes statistically independent while
every scatter/gather in the tick runs unbatched at full width.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def _local_base(n: int, shape, universe: Optional[int]):
    """(local index, block base) for block-local modular arithmetic."""
    rows = jnp.arange(n, dtype=jnp.int32).reshape(
        (n,) + (1,) * (len(shape) - 1)
    )
    if universe is None:
        return rows, 0, n
    if n % universe:
        # a partial trailing block would draw out-of-range peers that
        # gather-clamping silently folds back onto self
        raise ValueError(f"universe {universe} must divide n_nodes {n}")
    return rows % universe, rows - rows % universe, universe


def rand_peers(key, n: int, shape, universe: Optional[int] = None):
    """Uniform random peers, never self.

    shape's leading dim must be n (one row per node); each entry is drawn
    as ``(local + offset) % u`` with offset in 1..u-1, where ``u`` is the
    universe width (defaults to the whole cluster).  With ``universe``
    set, draws never leave the caller's own block of ``u`` nodes.
    """
    local, base, u = _local_base(n, shape, universe)
    offs = jax.random.randint(key, shape, 1, max(u, 2))
    return base + (local + offs) % u


def severance_matrix(oneway) -> jnp.ndarray:
    """Static directed-severance lookup for one-way partitions:
    ``[B, B]`` bool where ``m[s, d]`` = traffic from block ``s`` to
    block ``d`` is cut.  Sized one past the largest listed block so
    clamped ids (blocks never named by a pair) land on an all-False
    pad row/column — unlisted directions always flow, matching
    ``FaultPlan.blocks_severed``.  Built from a static config tuple,
    so under jit it constant-folds into the compiled tick."""
    import numpy as np

    b = max(max(s, d) for s, d in oneway) + 2
    m = np.zeros((b, b), dtype=bool)
    for s, d in oneway:
        m[s][d] = True
    return jnp.asarray(m)


def partition_ok(partition_id, senders_axis_targets, active,
                 oneway=None, bidirectional: bool = False):
    """True where a message does NOT cross an active partition boundary.

    partition_id: [N] block ids or None (no partition).
    senders_axis_targets: [N, ...] target indices (row i = sender i).
    active: traced bool (partition currently in force).
    oneway: static tuple of directed ``(src_block, dst_block)`` pairs —
            exactly those directions sever (``FaultPlan.oneway_blocks``);
            None/empty = symmetric (every cross-block pair, both ways).
    bidirectional: the link needs BOTH directions up (a sync session's
            bi-stream: the dial runs src→dst, the served chunks flow
            dst→src) — only distinguishable from one-way plans; a
            symmetric partition already cuts both ways.
    """
    if partition_id is None:
        return True
    src = partition_id.reshape(
        (-1,) + (1,) * (senders_axis_targets.ndim - 1)
    )
    dst = partition_id[senders_axis_targets]
    if oneway:
        sev = severance_matrix(oneway)
        b = sev.shape[0]
        s = jnp.minimum(src.astype(jnp.int32), b - 1)
        d = jnp.minimum(dst.astype(jnp.int32), b - 1)
        cross = sev[s, d]
        if bidirectional:
            cross = cross | sev[d, s]
    else:
        cross = src != dst
    return ~(cross & active)
