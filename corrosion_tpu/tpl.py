"""Template engine: render config files from live queries.

Parity: ``crates/corro-tpl`` — the reference embeds Rhai with a ``sql()``
function streaming query rows, ``hostname()``, ``to_json``/``to_csv``
helpers, and re-renders the template whenever a subscribed query's state
changes.  Ours is a small built-in template dialect (Rhai isn't a thing
in Python):

* ``{{ expr }}`` — evaluate and substitute
* ``{% for x in expr %} ... {% endfor %}`` — iterate (nestable)
* ``{% if expr %} ... {% else %} ... {% endif %}``

The expression namespace provides ``sql(query)`` (rows with attribute and
index access), ``hostname()``, ``to_json(v)``, ``to_csv(rows)`` and
``env(name, default)``.  ``render_loop`` re-renders whenever any
``sql()`` query used by the template changes, via the subscriptions API.
"""

from __future__ import annotations

import json
import os
import re
import socket
import threading
import time
from typing import Callable, List, Optional, Sequence, Tuple

_TOKEN = re.compile(r"(\{\{.*?\}\}|\{%.*?%\})", re.S)


class Row:
    """A query row with attribute, index and iteration access."""

    def __init__(self, columns: Sequence[str], cells: Sequence):
        self.__dict__["_cols"] = list(columns)
        self.__dict__["_cells"] = list(cells)

    def __getattr__(self, name):
        try:
            return self._cells[self._cols.index(name)]
        except ValueError:
            raise AttributeError(name) from None

    def __getitem__(self, i):
        if isinstance(i, str):
            return getattr(self, i)
        return self._cells[i]

    def __iter__(self):
        return iter(self._cells)

    def __repr__(self):
        return f"Row({dict(zip(self._cols, self._cells))})"


class TemplateError(Exception):
    pass


def _parse(src: str) -> List:
    """Parse into a tree of ('text', s) | ('expr', code) |
    ('for', var, iter_code, body) | ('if', cond, body, else_body)."""
    tokens = _TOKEN.split(src)
    pos = 0

    def block(terminators):
        nonlocal pos
        nodes = []
        while pos < len(tokens):
            tok = tokens[pos]
            pos += 1
            if not tok:
                continue
            if tok.startswith("{{"):
                nodes.append(("expr", tok[2:-2].strip()))
            elif tok.startswith("{%"):
                stmt = tok[2:-2].strip()
                word = stmt.split(None, 1)[0] if stmt else ""
                if word in terminators:
                    return nodes, word
                if word == "for":
                    m = re.match(r"for\s+(\w+)\s+in\s+(.+)", stmt, re.S)
                    if not m:
                        raise TemplateError(f"bad for: {stmt}")
                    body, _ = block({"endfor"})
                    nodes.append(("for", m.group(1), m.group(2), body))
                elif word == "if":
                    cond = stmt[2:].strip()
                    body, term = block({"else", "endif"})
                    else_body = []
                    if term == "else":
                        else_body, _ = block({"endif"})
                    nodes.append(("if", cond, body, else_body))
                else:
                    raise TemplateError(f"unknown directive: {stmt}")
            else:
                nodes.append(("text", tok))
        if terminators:
            raise TemplateError(f"missing {terminators}")
        return nodes, None

    nodes, _ = block(set())
    return nodes


def _to_csv(rows) -> str:
    import csv
    import io

    buf = io.StringIO()
    w = csv.writer(buf)
    for r in rows:
        w.writerow(list(r))
    return buf.getvalue()


class Template:
    def __init__(self, source: str):
        self.nodes = _parse(source)

    def render(self, sql: Callable[[str], List[Row]], extra: Optional[dict] = None
               ) -> Tuple[str, List[str]]:
        """Render; returns (output, list of sql queries used)."""
        queries: List[str] = []

        def tracked_sql(q: str) -> List[Row]:
            queries.append(q)
            return sql(q)

        ns = {
            "sql": tracked_sql,
            "hostname": socket.gethostname,
            "to_json": lambda v: json.dumps(
                list(v) if isinstance(v, Row) else v, default=str
            ),
            "to_csv": _to_csv,
            "env": lambda name, default="": os.environ.get(name, default),
        }
        if extra:
            ns.update(extra)
        out: List[str] = []

        def walk(nodes, scope):
            for node in nodes:
                kind = node[0]
                if kind == "text":
                    out.append(node[1])
                elif kind == "expr":
                    val = eval(node[1], {"__builtins__": {}}, {**ns, **scope})  # noqa: S307
                    out.append("" if val is None else str(val))
                elif kind == "for":
                    _, var, it, body = node
                    for item in eval(it, {"__builtins__": {}}, {**ns, **scope}):  # noqa: S307
                        walk(body, {**scope, var: item})
                elif kind == "if":
                    _, cond, body, else_body = node
                    if eval(cond, {"__builtins__": {}}, {**ns, **scope}):  # noqa: S307
                        walk(body, scope)
                    else:
                        walk(else_body, scope)

        walk(self.nodes, {})
        return "".join(out), queries


def _client_sql(client) -> Callable[[str], List[Row]]:
    def sql(q: str) -> List[Row]:
        cols, rows = client.query(q)
        return [Row(cols, r) for r in rows]

    return sql


def render_once(api_addr, template_path: str, out_path: str,
                token: Optional[str] = None) -> List[str]:
    """Render a template once; returns the queries it used."""
    from corrosion_tpu.client import CorrosionApiClient

    client = CorrosionApiClient(api_addr, token=token)
    with open(template_path) as f:
        tpl = Template(f.read())
    output, queries = tpl.render(_client_sql(client))
    _write_atomic(out_path, output)
    return queries


def render_loop(api_addr, template_path: str, out_path: str,
                token: Optional[str] = None,
                stop: Optional[threading.Event] = None,
                on_render: Optional[Callable[[str], None]] = None) -> None:
    """Render, then re-render whenever any used query's results change."""
    from corrosion_tpu.client import CorrosionApiClient

    client = CorrosionApiClient(api_addr, token=token)
    with open(template_path) as f:
        tpl = Template(f.read())
    stop = stop or threading.Event()
    wake = threading.Event()

    output, queries = tpl.render(_client_sql(client))
    _write_atomic(out_path, output)
    if on_render:
        on_render(output)

    def watch(query: str) -> None:
        while not stop.is_set():
            try:
                for ev in client.subscribe(query):
                    if "change" in ev:
                        wake.set()
                    elif "eoq" in ev:
                        # snapshot complete: a write landing between our
                        # one-shot render queries and this subscription's
                        # creation is absorbed into the snapshot and
                        # never emits a change event — re-render once so
                        # that gap can't leave the file stale forever
                        wake.set()
                    if stop.is_set():
                        return
            except Exception:
                time.sleep(0.5)

    for q in set(queries):
        threading.Thread(target=watch, args=(q,), daemon=True).start()

    while not stop.is_set():
        wake.wait(timeout=0.5)
        if not wake.is_set():
            continue
        wake.clear()
        new_out, _ = tpl.render(_client_sql(client))
        if new_out != output:
            output = new_out
            _write_atomic(out_path, output)
            if on_render:
                on_render(output)


def _write_atomic(path: str, content: str) -> None:
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(content)
    os.replace(tmp, path)
