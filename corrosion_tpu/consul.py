"""Consul service/check sync bridge.

Parity: ``crates/consul-client`` (minimal Consul HTTP client) +
``corrosion consul sync`` (``corrosion/src/command/consul/sync.rs``): on
an interval, pull the local Consul agent's services and checks, diff
against hashes remembered in node-local ``__corro_consul_*`` tables, and
upsert/delete the differences into the gossiped ``consul_services`` /
``consul_checks`` CRR tables so the whole cluster sees them.
"""

from __future__ import annotations

import hashlib
import json
import time
import urllib.request
from typing import Callable, Dict, Optional, Tuple

CONSUL_SCHEMA = """
CREATE TABLE IF NOT EXISTS consul_services (
  node TEXT NOT NULL,
  id TEXT NOT NULL,
  name TEXT NOT NULL DEFAULT '',
  tags TEXT NOT NULL DEFAULT '[]',
  meta TEXT NOT NULL DEFAULT '{}',
  port INTEGER NOT NULL DEFAULT 0,
  address TEXT NOT NULL DEFAULT '',
  updated_at INTEGER NOT NULL DEFAULT 0,
  PRIMARY KEY (node, id)
);
CREATE TABLE IF NOT EXISTS consul_checks (
  node TEXT NOT NULL,
  id TEXT NOT NULL,
  service_id TEXT NOT NULL DEFAULT '',
  service_name TEXT NOT NULL DEFAULT '',
  name TEXT NOT NULL DEFAULT '',
  status TEXT NOT NULL DEFAULT '',
  output TEXT NOT NULL DEFAULT '',
  updated_at INTEGER NOT NULL DEFAULT 0,
  PRIMARY KEY (node, id)
);
"""


class ConsulClient:
    """Minimal Consul agent HTTP client (/v1/agent/services, /checks)."""

    def __init__(self, addr: str = "127.0.0.1:8500", timeout: float = 5.0):
        self.base = f"http://{addr}"
        self.timeout = timeout

    def _get(self, path: str):
        with urllib.request.urlopen(self.base + path, timeout=self.timeout) as r:
            return json.loads(r.read())

    def services(self) -> Dict[str, dict]:
        return self._get("/v1/agent/services")

    def checks(self) -> Dict[str, dict]:
        return self._get("/v1/agent/checks")


def _hash(obj) -> str:
    return hashlib.blake2s(
        json.dumps(obj, sort_keys=True).encode(), digest_size=16
    ).hexdigest()


def sync_once(
    client,
    node: str,
    services: Dict[str, dict],
    checks: Dict[str, dict],
    state: Dict[str, Dict[str, str]],
) -> Tuple[int, int]:
    """Diff services/checks against remembered hashes and push changes
    through the API ``client``.  ``state`` holds {"services": {id: hash},
    "checks": {id: hash}} and is mutated in place.  Returns
    (n_upserts, n_deletes)."""
    now = int(time.time())
    stmts = []
    # hash-state mutations are deferred until the push succeeds: a failed
    # execute must NOT mark changes as synced
    effects = []
    upserts = deletes = 0

    def diff(kind: str, current: Dict[str, dict], make_upsert, table: str):
        nonlocal upserts, deletes
        seen = state.setdefault(kind, {})
        for sid, svc in current.items():
            h = _hash(svc)
            if seen.get(sid) == h:
                continue
            stmts.append(make_upsert(sid, svc))
            effects.append((seen, sid, h))
            upserts += 1
        for sid in list(seen):
            if sid not in current:
                stmts.append(
                    [f"DELETE FROM {table} WHERE node = ? AND id = ?", [node, sid]]
                )
                effects.append((seen, sid, None))
                deletes += 1

    diff(
        "services",
        services,
        lambda sid, svc: [
            "INSERT INTO consul_services (node, id, name, tags, meta, port,"
            " address, updated_at) VALUES (?, ?, ?, ?, ?, ?, ?, ?)"
            " ON CONFLICT (node, id) DO UPDATE SET name=excluded.name,"
            " tags=excluded.tags, meta=excluded.meta, port=excluded.port,"
            " address=excluded.address, updated_at=excluded.updated_at",
            [
                node,
                sid,
                svc.get("Service", svc.get("Name", "")),
                json.dumps(svc.get("Tags") or []),
                json.dumps(svc.get("Meta") or {}),
                svc.get("Port") or 0,
                svc.get("Address") or "",
                now,
            ],
        ],
        "consul_services",
    )
    diff(
        "checks",
        checks,
        lambda cid, chk: [
            "INSERT INTO consul_checks (node, id, service_id, service_name,"
            " name, status, output, updated_at) VALUES (?, ?, ?, ?, ?, ?, ?, ?)"
            " ON CONFLICT (node, id) DO UPDATE SET service_id=excluded.service_id,"
            " service_name=excluded.service_name, name=excluded.name,"
            " status=excluded.status, output=excluded.output,"
            " updated_at=excluded.updated_at",
            [
                node,
                cid,
                chk.get("ServiceID", ""),
                chk.get("ServiceName", ""),
                chk.get("Name", ""),
                chk.get("Status", ""),
                chk.get("Output", ""),
                now,
            ],
        ],
        "consul_checks",
    )
    if stmts:
        client.execute(stmts)
        for seen, sid, h in effects:
            if h is None:
                seen.pop(sid, None)
            else:
                seen[sid] = h
    return upserts, deletes


def sync_loop(
    api_addr,
    consul_addr: str = "127.0.0.1:8500",
    node: Optional[str] = None,
    token: Optional[str] = None,
    interval: float = 1.0,
    once: bool = False,
    fetch: Optional[Callable[[], Tuple[Dict, Dict]]] = None,
) -> None:
    """Pull-from-consul push-to-corrosion loop (1 s cadence like the
    reference)."""
    import socket

    from corrosion_tpu.client import CorrosionApiClient

    api = CorrosionApiClient(api_addr, token=token)
    api.migrate(CONSUL_SCHEMA)
    consul = ConsulClient(consul_addr)
    node = node or socket.gethostname()
    state: Dict[str, Dict[str, str]] = {}
    from corrosion_tpu.client import ClientError

    while True:
        try:
            services, checks = (
                fetch() if fetch else (consul.services(), consul.checks())
            )
            sync_once(api, node, services, checks, state)
        except (OSError, ValueError, ClientError):
            pass  # transient: retried next interval
        if once:
            return
        time.sleep(interval)
