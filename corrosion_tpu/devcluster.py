"""Dev cluster runner: boot N agents from a topology file.

Parity: ``crates/corro-devcluster`` — parse a topology file of
``A -> B`` edges (B bootstraps from A), assign ports, generate configs,
run the agents, tear down on exit (``corro-devcluster/src/main.rs``).

Two runtimes:

* ``run_inprocess`` — N agents as asyncio tasks in this process (what the
  sim's bit-match harness and tests use);
* ``main`` — CLI entry spawning one ``corrosion-tpu agent`` subprocess
  per node with generated TOML configs.
"""

from __future__ import annotations

import asyncio
import os
import tempfile
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple


@dataclass
class Topology:
    nodes: List[str] = field(default_factory=list)
    edges: List[Tuple[str, str]] = field(default_factory=list)  # (a, b): b boots from a

    @classmethod
    def parse(cls, text: str) -> "Topology":
        topo = cls()
        seen = set()
        for line in text.splitlines():
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            if "->" in line:
                a, b = (s.strip() for s in line.split("->", 1))
                for n in (a, b):
                    if n not in seen:
                        seen.add(n)
                        topo.nodes.append(n)
                topo.edges.append((a, b))
            else:
                if line not in seen:
                    seen.add(line)
                    topo.nodes.append(line)
        return topo

    def bootstraps_for(self, node: str) -> List[str]:
        return [a for a, b in self.edges if b == node]


async def run_inprocess(
    topo: Topology,
    schema: Optional[str] = None,
    base_dir: Optional[str] = None,
    faults: Optional["object"] = None,
    **agent_overrides,
) -> Dict[str, "object"]:
    """Boot all agents; returns {name: Agent}.  Caller stops them.

    ``faults`` takes a :class:`corrosion_tpu.faults.FaultController`:
    every node registers with it (topology order — the deterministic
    index order the partition blocks key off), gets its injection hook
    installed on the transport/SWIM send paths, and the plan's
    crash/restart schedule becomes executable via
    :func:`run_crash_schedule` (restarts relaunch from the SAME node
    directory, so the reborn agent resumes its identity and catches up
    through anti-entropy)."""
    from corrosion_tpu.agent.testing import launch_test_agent

    base = base_dir or tempfile.mkdtemp(prefix="corro-devcluster-")
    agents: Dict[str, object] = {}

    async def spawn(name: str) -> "object":
        boots = []
        for up in topo.bootstraps_for(name):
            a = agents.get(up)
            if a is not None and getattr(a, "_udp", None) is not None:
                boots.append(f"{a.gossip_addr[0]}:{a.gossip_addr[1]}")
        d = os.path.join(base, name)
        os.makedirs(d, exist_ok=True)
        kwargs = dict(bootstrap=boots, tmpdir=d)
        if schema is not None:
            kwargs["schema"] = schema
        if faults is not None:
            # installed pre-start (launch_test_agent) so even the boot
            # window — bootstrap announces on a RESPAWN into an active
            # partition — is subject to the plan
            kwargs["fault_filter"] = faults.hook_for(name)
            # per-node HLC skew, derived (not stored) from the plan so
            # a respawn re-acquires its identical bad oscillator
            offset_ns, drift = faults.clock_for(name)
            if offset_ns or drift:
                kwargs["clock_skew_ns"] = offset_ns
                kwargs["clock_drift"] = drift
        agent = await launch_test_agent(**kwargs, **agent_overrides)
        if faults is not None:
            faults.register(name, tuple(agent.gossip_addr))
            agent.faults = faults
            # slow-disk hook at the storage write/collect seams; after
            # launch so schema-apply boot writes aren't charged seeded
            # draws (the fault model covers steady-state IO)
            agent.storage.io_fault = faults.io_hook_for(name)
        return agent

    for name in topo.nodes:
        agents[name] = await spawn(name)
        if faults is not None:
            faults.respawn[name] = spawn
    if faults is not None:
        faults.agents = agents
        faults.start()
    return agents


class ClusterObserver:
    """Telemetry-derived cluster view: the live cluster measuring its
    OWN convergence (docs/telemetry.md, convergence observability
    plane).

    Every per-node read goes THROUGH the Prometheus text exposition
    (``Metrics.render`` + the strict parser) — the same bytes a real
    scraper would see, so an exposition regression fails the observer,
    not just a lint.  Two in-process-only extras ride alongside:

    * exact cross-node convergence percentiles from the raw
      ``corro_change_lag_seconds`` sample rings (exposition carries
      only per-node quantiles — a p99 of p99s is not a p99);
    * cross-node trace assembly from the (process-shared) span ring —
      the multi-process equivalent is ``corrosion-tpu trace spans
      --trace <id>`` against each node's admin socket.
    """

    def __init__(self, agents: Dict[str, "object"],
                 faults: Optional["object"] = None):
        self.agents = dict(agents)
        self._base_msgs = 0.0
        # the FaultController, when the cluster runs under one: the
        # timeline merge pulls its flight_orphans (crashed
        # incarnations' rings, kept by run_crash_schedule) so a death
        # doesn't erase the history that led up to it
        self.faults = faults
        # extra orphaned rings a harness attaches manually
        self.extra_rings: List[Tuple[str, list]] = []

    # -- scrape --------------------------------------------------------

    def scrape(self) -> Dict[str, dict]:
        """Parse every node's rendered /metrics text, strictly."""
        from corrosion_tpu.agent.metrics import parse_prometheus_text

        out = {}
        for name, a in self.agents.items():
            text = a.metrics.render(a.metric_gauges())
            out[name] = parse_prometheus_text(text)
        return out

    @staticmethod
    def _family_sum(parsed: dict, family: str) -> float:
        fam = parsed.get(family)
        if fam is None:
            return 0.0
        return sum(v for _n, _l, v in fam["samples"])

    def msgs_total(self, scrape: Optional[Dict[str, dict]] = None) -> float:
        """Cluster-wide dissemination message count (the north-star
        msgs/node numerator), from the scraped exposition."""
        scrape = scrape or self.scrape()
        return sum(
            self._family_sum(p, "corro_broadcast_sent_total")
            + self._family_sum(p, "corro_sync_served_total")
            for p in scrape.values()
        )

    def mark(self) -> None:
        """Zero the msgs/node baseline at the measurement start."""
        self._base_msgs = self.msgs_total()

    def msgs_per_node(self, scrape: Optional[Dict[str, dict]] = None) -> float:
        return (self.msgs_total(scrape) - self._base_msgs) / max(
            1, len(self.agents)
        )

    # -- convergence ---------------------------------------------------

    def convergence_lag(self) -> dict:
        """The cluster's self-measured convergence: every node's raw
        first-arrival lag samples pooled, exact percentiles computed
        over the pool, per-path counts from the cumulative stats."""
        samples = []
        paths: Dict[str, int] = {}
        for a in self.agents.values():
            for key, ring in a.metrics.histogram_samples(
                "corro_change_lag_seconds"
            ).items():
                samples.extend(ring)
                path = dict(key).get("path", "?")
                count, _total = a.metrics.histogram_stats(
                    "corro_change_lag_seconds", path=path
                )
                paths[path] = paths.get(path, 0) + count
        if not samples:
            return {"count": 0, "paths": paths}
        from corrosion_tpu.agent.metrics import percentile_sorted

        s = sorted(samples)
        return {
            "count": len(s),
            "paths": paths,
            "p50_s": percentile_sorted(s, 0.5),
            "p99_s": percentile_sorted(s, 0.99),
            "max_s": s[-1],
            "mean_s": sum(s) / len(s),
        }

    def staleness(self, scrape: Optional[Dict[str, dict]] = None
                  ) -> Dict[str, float]:
        """Worst per-origin staleness across the cluster, from the
        scraped gauge."""
        worst: Dict[str, float] = {}
        for parsed in (scrape or self.scrape()).values():
            fam = parsed.get("corro_change_staleness_seconds")
            if fam is None:
                continue
            for _n, labels, v in fam["samples"]:
                actor = labels.get("actor_id", "?")
                worst[actor] = max(worst.get(actor, 0.0), v)
        return worst

    def loop_health(self, scrape: Optional[Dict[str, dict]] = None) -> dict:
        """Max loop stall across nodes + total attributed slow
        callbacks (the always-on stall probe, agent/health.py)."""
        worst = 0.0
        slow = 0.0
        for parsed in (scrape or self.scrape()).values():
            fam = parsed.get("corro_loop_stall_max_ms")
            if fam is not None:
                worst = max(
                    (v for _n, _l, v in fam["samples"]), default=worst
                )
            slow += self._family_sum(
                parsed, "corro_loop_slow_callbacks_total"
            )
        return {"max_stall_ms": worst, "slow_callbacks": slow}

    # -- no-divergence invariant (docs/faults.md, scenario matrix) -----

    def no_divergence(self) -> dict:
        """The cross-node NO-DIVERGENCE invariant the scenario matrix
        gates every cell on:

        1. **bytewise-equal table state** — every CRR table's full,
           order-normalized contents hash identically on every node;
        2. **consistent bookkeeping ledgers** — per origin actor, every
           node holds the same CONTAINED version set (max version, no
           differing gaps, same unresolved partials).  The
           applied-vs-cleared split is a per-node compaction detail
           and deliberately not compared;
        3. **one content per (actor, version)** — the accepted-content
           digests pooled across nodes never show two digests for one
           version (the equivocation invariant, checked cross-node
           where a single agent cannot see it);
        4. **representation independence** — the columnar merge kernel
           (:func:`corrosion_tpu.ops.merge.select_winners`, the SAME
           winner-selection core the live batched apply dispatches to)
           re-derives every table's data-row state from the clock-table
           representation (:meth:`kernel_state_check`), so "all nodes
           bytewise equal" can never silently mean "all nodes equally
           wrong about the merge rule".

        Returns ``{"ok": bool, "violations": [...]}`` with enough
        detail to name the diverging nodes."""
        import hashlib

        violations = []
        names = sorted(self.agents)

        table_digests: Dict[str, str] = {}
        for name in names:
            a = self.agents[name]
            h = hashlib.blake2b(digest_size=16)
            for t in sorted(a.storage.tables):
                q = t.replace('"', '""')
                cols, rows = a.storage.read_query(
                    f'SELECT * FROM "{q}"'
                )
                h.update(repr(
                    (t, cols, sorted(rows, key=repr))
                ).encode())
            table_digests[name] = h.hexdigest()
        if len(set(table_digests.values())) > 1:
            violations.append({
                "kind": "table_state",
                "digests": table_digests,
            })

        ledgers: Dict[str, dict] = {}
        for name in names:
            a = self.agents[name]
            with a.storage._lock:
                led = {}
                for actor, bv in a.bookie.actors().items():
                    if (bv.max_version == 0 and not bv.needed.spans()
                            and not bv.partials):
                        continue  # lazily-created empty entry, not state
                    led[actor.hex()] = (
                        bv.max_version,
                        tuple(bv.needed.spans()),
                        tuple(sorted(
                            v for v, p in bv.partials.items()
                            if not p.is_complete()
                        )),
                    )
            ledgers[name] = led
        actors = set()
        for led in ledgers.values():
            actors.update(led)
        for actor in sorted(actors):
            per_node = {
                name: ledgers[name].get(actor) for name in names
            }
            if len({repr(v) for v in per_node.values()}) > 1:
                violations.append({
                    "kind": "ledger",
                    "actor": actor,
                    "per_node": {
                        k: repr(v) for k, v in per_node.items()
                    },
                })

        accepted: Dict[tuple, tuple] = {}
        for name in names:
            a = self.agents[name]
            with a._equiv_lock:
                items = list(a._equiv_digests.items())
            for (actor, v), d in items:
                prev = accepted.get((actor, v))
                if prev is None:
                    accepted[(actor, v)] = (name, d)
                elif prev[1] != d:
                    violations.append({
                        "kind": "conflicting_contents",
                        "actor": actor.hex(),
                        "version": v,
                        "nodes": [prev[0], name],
                    })

        kern = self.kernel_state_check()
        violations.extend(kern["violations"])

        return {"ok": not violations, "violations": violations}

    def kernel_state_check(self) -> dict:
        """Representation-independence gate: re-derive data-row state
        from the clock representation through the SHARED columnar merge
        kernel and compare against the stored rows.

        One node's net change streams (``collect_changes`` for every
        interned origin — the same representation anti-entropy serves)
        run through :func:`corrosion_tpu.ops.merge.select_winners` with
        empty seeds; the decision must reproduce EVERY node's data
        tables: row liveness from causal-length parity, cell values
        from the surviving LWW winners.  Liveness and structure are
        independently derivable on the stream's own node (clock tables
        vs data rows); cell VALUES reconstruct from the data row at
        collect time, so value tampering on the streaming node is only
        visible against the other nodes' rows — which is why the
        prediction is compared cluster-wide, not just locally.  This is
        the sim-side graft of the live apply path's kernel ("CRDT
        Emulation, Simulation, and Representation Independence"): one
        merge implementation serves both worlds, and
        ``tests/test_merge_columnar.py`` proves the checker bites on
        seeded corruption."""
        from corrosion_tpu.ops import merge as mergeops
        from corrosion_tpu.types.change import SENTINEL_CID

        violations: list = []
        names = sorted(self.agents)
        if not names:
            return {"ok": True, "violations": violations}
        st = self.agents[names[0]].storage
        with st._lock:
            sites = [
                bytes(r[0]) for r in st.conn.execute(
                    "SELECT site_id FROM __corro_sites ORDER BY ordinal"
                )
            ]
        by_table: Dict[str, list] = {}
        for site in sites:
            for ch in st.collect_changes(
                (1, 1 << 60),
                None if site == st.site_id else site,
            ):
                by_table.setdefault(ch.table, []).append(ch)
        for t, info in sorted(st.tables.items()):
            t_changes = by_table.get(t, [])
            if not t_changes:
                continue
            plan = mergeops.encode_change_batch(t_changes, SENTINEL_CID)
            if plan is None:
                violations.append({"kind": "kernel_encode", "table": t})
                continue
            dec = mergeops.select_winners(plan)
            predicted: Dict[bytes, dict] = {}
            for p, pk in enumerate(plan.pk_values):
                if not bool(dec.alive[p]):
                    continue
                cells = {}
                base = p * plan.n_cid
                for c, cid in enumerate(plan.cid_values):
                    w = int(dec.winner_idx[base + c])
                    if w >= 0:
                        cells[cid] = plan.vals[w]
                predicted[pk] = cells
            pk_expr = "corro_pack(" + ", ".join(
                f'"{p}"' for p in info.pk_cols
            ) + ")"
            sel = "".join(f', "{c}"' for c in info.data_cols)
            # columns with NO predicted winner were wiped by the last
            # generation change (or never written): they must hold the
            # column DEFAULT.  Checkable when that default is NULL —
            # default-bearing columns are skipped (parsing arbitrary
            # DEFAULT expressions is not worth the coverage).
            q = t.replace('"', '""')
            _, ti_rows = st.read_query(f'PRAGMA table_info("{q}")')
            null_default = {
                r[1] for r in ti_rows if not r[5] and r[4] is None
            }
            for name in names:
                node_st = self.agents[name].storage
                if t not in node_st.tables:
                    continue
                _, rows = node_st.read_query(
                    f'SELECT {pk_expr}{sel} FROM "{t}"'
                )
                actual = {
                    bytes(r[0]): dict(zip(info.data_cols, r[1:]))
                    for r in rows
                }
                if set(actual) != set(predicted):
                    violations.append({
                        "kind": "kernel_liveness",
                        "table": t,
                        "node": name,
                        "extra_rows": len(
                            set(actual) - set(predicted)
                        ),
                        "missing_rows": len(
                            set(predicted) - set(actual)
                        ),
                    })
                    continue
                bad_cells = 0
                bad_residual = 0
                for pk, cells in predicted.items():
                    row = actual[pk]
                    for cid, val in cells.items():
                        if row.get(cid) != val:
                            bad_cells += 1
                    for cid in null_default:
                        if cid not in cells and row.get(cid) is not None:
                            bad_residual += 1
                if bad_cells:
                    violations.append({
                        "kind": "kernel_cells",
                        "table": t,
                        "node": name,
                        "cells": bad_cells,
                    })
                if bad_residual:
                    violations.append({
                        "kind": "kernel_residual",
                        "table": t,
                        "node": name,
                        "cells": bad_residual,
                    })
        return {"ok": not violations, "violations": violations}

    def equivocations(self, scrape: Optional[Dict[str, dict]] = None
                      ) -> Dict[str, float]:
        """Cluster-wide ``corro_sync_equivocations_total`` by kind,
        from the scraped exposition."""
        out: Dict[str, float] = {}
        for parsed in (scrape or self.scrape()).values():
            fam = parsed.get("corro_sync_equivocations_total")
            if fam is None:
                continue
            for _n, labels, v in fam["samples"]:
                kind = labels.get("kind", "?")
                out[kind] = out.get(kind, 0.0) + v
        return out

    # -- flight timeline (docs/telemetry.md, flight recorder) ----------

    def flight_timeline(self, limit: int = 0,
                        kind: Optional[str] = None) -> List[dict]:
        """ONE cluster timeline: every node's flight ring (snapshots +
        typed events) merged on the HLC axis.  The HLC is the merge
        key — it advances on every message receipt, so two nodes'
        records interleave in causal order even when the clock-skew
        fault family has pulled their wall clocks hundreds of ms apart.
        Wall time breaks HLC ties; ``kind`` ("snap"/"event") filters
        before the trailing ``limit``."""
        entries: List[dict] = []
        sources: List[Tuple[str, list]] = [
            (name, a.flight.entries(kind=kind))
            for name, a in self.agents.items()
            if getattr(a, "flight", None) is not None
        ]
        orphans = list(self.extra_rings)
        if self.faults is not None:
            orphans.extend(getattr(self.faults, "flight_orphans", ()))
        for node, ring in orphans:
            if kind is not None:
                ring = [e for e in ring if e["t"] == kind]
            sources.append((node, ring))
        for node, ring in sources:
            for e in ring:
                entries.append(dict(e, node=node))
        entries.sort(key=lambda e: (e["hlc"], e["wall"], e["node"]))
        if limit > 0:
            entries = entries[-limit:]
        return entries

    def flight_events(self, limit: int = 0) -> List[dict]:
        """The merged typed-event journal alone (the timeline minus
        the metric snapshots)."""
        return self.flight_timeline(limit=limit, kind="event")

    def coverage_curve(self, tracked: List[tuple]) -> dict:
        """The time-resolved coverage curve of tracked
        ``(actor_bytes, version)`` waves, from the provenance
        first-seen stamps: for each wave, t0 is the ORIGIN's own HLC
        commit ts (the changeset timestamp bookkeeping recorded) and
        each remote node contributes its first-arrival HLC stamp, so
        the whole curve lives on the HLC axis.  Coverage at offset t =
        fraction of (node, wave) pairs holding the wave within t of
        its commit (the origin counts at t=0).  Returns the pooled
        sorted offsets plus threshold crossing times — the trajectory
        the timeline bench gates against the epidemic kernel's
        per-tick prediction."""
        from corrosion_tpu.types import Timestamp

        n = len(self.agents)
        first_seen = {
            name: a.provenance_first_seen()
            for name, a in self.agents.items()
        }
        dts: List[float] = []
        missing = 0
        waves = 0
        for actor, version in tracked:
            version = int(version)
            origin = next(
                (a for a in self.agents.values()
                 if a.actor_id == actor), None,
            )
            if origin is None:
                continue
            ts0 = origin.bookie.version_ts(actor, version)
            if ts0 is None:
                continue
            waves += 1
            t0 = Timestamp(ts0).wall_seconds()
            dts.append(0.0)  # the origin holds its wave at commit
            for name, a in self.agents.items():
                if a.actor_id == actor:
                    continue
                stamp = first_seen[name].get((actor, version))
                if stamp is None:
                    # no provenance record (e.g. a pre-provenance
                    # arrival path): counted, never invented — the
                    # curve plateaus below 1.0 instead of lying
                    missing += 1
                    continue
                _wall, hlc = stamp
                dts.append(
                    max(0.0, Timestamp(hlc).wall_seconds() - t0)
                )
        dts.sort()
        expected = n * waves
        thresholds = (0.5, 0.75, 0.9, 0.99, 1.0)
        t_at = {}
        for c in thresholds:
            need = int(-(-c * expected // 1))  # ceil
            t_at[str(c)] = (
                round(dts[need - 1], 4)
                if 0 < need <= len(dts) else None
            )
        return {
            "n_nodes": n,
            "waves": waves,
            "expected": expected,
            "samples": len(dts),
            "missing": missing,
            "offsets_s": [round(d, 4) for d in dts],
            "t_at_coverage": t_at,
        }

    # -- traces --------------------------------------------------------

    def assemble_trace(self, trace_id: str):
        """All spans of one trace, oldest first (in-process: the span
        ring is process-shared; multi-process: ask each node's admin
        socket with ``trace spans --trace``)."""
        from corrosion_tpu.agent import tracing

        spans = tracing.recent_spans(
            tracing.RECENT_MAX, trace_id=trace_id
        )
        return sorted(spans, key=lambda s: s.start)

    def latest_write_trace(self):
        """Trace id of the most recent write.group span, if any — the
        root of a broadcast-path trace."""
        from corrosion_tpu.agent import tracing

        for s in reversed(tracing.recent_spans(tracing.RECENT_MAX)):
            if s.name == "write.group":
                return s.trace_id
        return None

    def snapshot(self) -> dict:
        """One observer record: the cluster's own convergence numbers
        next to its health surface."""
        scrape = self.scrape()
        return {
            "n_nodes": len(self.agents),
            "convergence_lag": self.convergence_lag(),
            "msgs_per_node": self.msgs_per_node(scrape),
            "loop_health": self.loop_health(scrape),
            "staleness_worst_s": (
                max(self.staleness(scrape).values(), default=0.0)
            ),
        }


async def run_stall_schedule(faults: "object") -> None:
    """Execute the plan's loop-stall schedule: at each event, block the
    event loop with a real ``time.sleep`` for the event's duration —
    the stalled-event-loop fault family.  In-process clusters share one
    loop, so a stall freezes every agent at once (the worst case); the
    agents' own ``LoopHealthProbe`` must observe and attribute it.
    Event times are seconds relative to the controller's clock, like
    crashes."""
    import time as _time

    loop = asyncio.get_running_loop()
    for ev in sorted(faults.plan.loop_stalls, key=lambda e: e.at):
        delay = ev.at - faults.elapsed()
        if delay > 0:
            await asyncio.sleep(delay)
        loop.call_soon(_time.sleep, ev.duration_ms / 1e3)
        # yield so the stall actually executes before bookkeeping
        await asyncio.sleep(0)
        faults.injected["stall"] += 1
        faults.stall_log.append(
            (faults.elapsed(), ev.node, ev.duration_ms)
        )


async def run_crash_schedule(faults: "object") -> None:
    """Execute the controller's crash/restart schedule against the
    cluster booted by :func:`run_inprocess` (pass the same controller).

    Crashes are non-graceful stops (peers see genuine connect failures
    and run the suspicion pipeline); restarts relaunch from the same
    node directory — resume, not re-seed — updating the controller's
    ``agents`` dict in place.  Event times are seconds relative to the
    controller's start()."""
    events = []
    for ev in faults.plan.crashes:
        events.append((ev.at, "crash", ev.node))
        if ev.restart_at is not None:
            events.append((ev.restart_at, "restart", ev.node))
    events.sort()
    if not hasattr(faults, "flight_orphans"):
        # a crashed incarnation's flight ring would die with it: keep
        # it so ClusterObserver.flight_timeline (extra_rings) can still
        # assemble the history that led up to the death
        faults.flight_orphans = []
    for at, kind, node in events:
        delay = at - faults.elapsed()
        if delay > 0:
            await asyncio.sleep(delay)
        if kind == "crash":
            agent = faults.agents.get(node)
            if agent is not None:
                if agent.flight is not None:
                    # the crash MARKER rides the dying ring — the
                    # timeline's record of when and why history stops
                    agent.flight.event("crash", node=node)
                    faults.flight_orphans.append(
                        (node, agent.flight.entries())
                    )
                await agent.stop(graceful=False)
        else:
            agent = await faults.respawn[node](node)
            faults.agents[node] = agent
            if agent.flight is not None:
                agent.flight.event("restart", node=node)
        faults.crash_log.append((faults.elapsed(), kind, node))


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    import signal
    import subprocess
    import sys

    ap = argparse.ArgumentParser(prog="corro-devcluster")
    ap.add_argument("topology", nargs="?", default=None,
                    help="file of 'A -> B' edges (process runtime)")
    ap.add_argument("--schema", default=None, help="schema .sql file")
    ap.add_argument("--base-dir", default=None)
    ap.add_argument("--runtime", choices=["process", "tpu-sim"],
                    default="process",
                    help="process: spawn agent subprocesses; tpu-sim: run "
                         "the JAX simulator vs an in-process agent cluster "
                         "and record the trace diff")
    ap.add_argument("-n", "--nodes", type=int, default=64,
                    help="cluster size for --runtime tpu-sim")
    ap.add_argument("--out", default=None,
                    help="tpu-sim: write the diff JSON here "
                         "(default SIMDIFF_N{n}.json)")
    ap.add_argument("--port-base", type=int, default=42000,
                    help="first gossip port for --runtime process")
    args = ap.parse_args(argv)

    if args.runtime == "tpu-sim":
        import asyncio as aio
        import json

        from corrosion_tpu.sim.simdiff import run_simdiff

        if args.schema:
            ap.error("--schema is not supported with --runtime tpu-sim "
                     "(the diff uses the fixed test schema on both sides)")
        out = args.out or f"SIMDIFF_N{args.nodes}.json"
        result = aio.run(
            run_simdiff(n=args.nodes, out_path=out, base_dir=args.base_dir)
        )
        print(json.dumps(result))
        return 0

    if args.topology is None:
        ap.error("topology file required for --runtime process")

    with open(args.topology) as f:
        topo = Topology.parse(f.read())
    base = args.base_dir or tempfile.mkdtemp(prefix="corro-devcluster-")

    procs: List[subprocess.Popen] = []
    port = args.port_base
    addrs: Dict[str, str] = {}
    try:
        for name in topo.nodes:
            d = os.path.join(base, name)
            os.makedirs(d, exist_ok=True)
            gossip = f"127.0.0.1:{port}"
            api = f"127.0.0.1:{port + 1}"
            port += 2
            addrs[name] = gossip
            boots = [addrs[a] for a in topo.bootstraps_for(name) if a in addrs]
            cfg = os.path.join(d, "config.toml")
            with open(cfg, "w") as f:
                f.write(f'[db]\npath = "{d}/corrosion.db"\n')
                if args.schema:
                    f.write(f'schema_paths = ["{os.path.abspath(args.schema)}"]\n')
                f.write(f'\n[gossip]\naddr = "{gossip}"\n')
                f.write("bootstrap = [" + ", ".join(f'"{b}"' for b in boots) + "]\n")
                f.write(f'\n[api]\naddr = "{api}"\n')
            procs.append(
                subprocess.Popen(
                    [sys.executable, "-m", "corrosion_tpu.cli", "agent",
                     "--config", cfg],
                )
            )
            print(f"{name}: gossip={gossip} api={api} dir={d}",
                  flush=True)
        print("devcluster up; ctrl-c to stop", flush=True)
        # block the signals BEFORE sigwait: unblocked, delivery takes
        # the default action (terminate) and the finally-block teardown
        # of the agent subprocesses never runs
        signal.pthread_sigmask(
            signal.SIG_BLOCK, {signal.SIGINT, signal.SIGTERM}
        )
        signal.sigwait({signal.SIGINT, signal.SIGTERM})
        return 0
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()


if __name__ == "__main__":
    raise SystemExit(main())
