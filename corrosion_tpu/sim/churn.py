"""SWIM membership churn simulation (BASELINE.md config #2).

A cluster runs the SWIM model while the ground-truth liveness schedule
kills and revives nodes; the measured quantities are failure-detection
latency (ticks from death until every live node marks the victim down)
and rejoin propagation (ticks until every live node sees the revived
node alive again), plus msgs/node — the SWIM slice of the north-star
metric.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from corrosion_tpu.models.swim import (
    ALIVE,
    DOWN,
    SwimParams,
    key_state,
    swim_init,
    swim_step,
)


@dataclass(frozen=True)
class ChurnConfig:
    n_nodes: int = 64
    params: SwimParams = None  # type: ignore[assignment]
    kill_tick: int = 4  # when the victim dies (offset within a cycle)
    revive_tick: int = 40  # when it comes back (offset within a cycle)
    victim: int = 1
    max_ticks: int = 128
    # repeated join/suspect/leave cycles (BASELINE config #2): cycle c
    # kills victim (victim + c) % n at c*cycle_period + kill_tick and
    # revives it at + revive_tick.  cycles=1 is the legacy single cycle.
    cycles: int = 1
    cycle_period: int = 64
    # bigger chunks = fewer host sync points: each chunk call pays a
    # fixed dispatch cost that dwarfs the N=64 compute, and per-tick
    # flags keep the reported latencies exact either way
    chunk_ticks: int = 32

    def __post_init__(self):
        if self.params is None:
            # cluster-size-scaled SWIM parameters (make_foca_config /
            # Config::new_wan parity): at N=64 the suspicion deadline is
            # 4 * ceil(log10(65)) = 8 probe ticks and updates ride at
            # most 8 gossip rounds
            object.__setattr__(
                self, "params", SwimParams.scaled(self.n_nodes)
            )


@partial(jax.jit, static_argnames=("cfg",))
def _scan_chunk(state, seed_key, start_tick, cfg: ChurnConfig):
    p = cfg.params
    n = cfg.n_nodes

    def schedule(t):
        """(alive [N], revived [N], victim scalar) at tick t."""
        if cfg.cycles <= 1:
            victim = jnp.int32(cfg.victim)
            off = t
        else:
            cyc = jnp.minimum(t // cfg.cycle_period, cfg.cycles - 1)
            off = t - cyc * cfg.cycle_period
            victim = (cfg.victim + cyc) % n
        dead = (off >= cfg.kill_tick) & (off < cfg.revive_tick)
        alive = jnp.ones((n,), dtype=bool).at[victim].set(~dead)
        revived = jnp.zeros((n,), dtype=bool).at[victim].set(
            off == cfg.revive_tick
        )
        return alive, revived, victim

    def body(st, i):
        t = start_tick + i
        key = jax.random.fold_in(seed_key, t)
        alive, revived, victim = schedule(t)
        nxt = swim_step(st, key, t, p, alive, revived=revived)
        others = jnp.arange(n) != victim
        col = key_state(nxt.view[:, victim])
        detected = jnp.all(jnp.where(others, col == DOWN, True))
        rejoined = jnp.all(jnp.where(others, col == ALIVE, True))
        return nxt, (detected, rejoined)

    return jax.lax.scan(body, state, jnp.arange(cfg.chunk_ticks))


def run_churn_cycles(cfg: ChurnConfig, seed: int = 0):
    """Repeated join/suspect/leave cycles (BASELINE config #2): returns
    per-cycle detection/rejoin latencies plus aggregates.  Latencies
    are in ticks (= probe periods), offsets from each cycle's own
    kill/revive tick."""
    assert cfg.cycles >= 1
    assert cfg.revive_tick < cfg.cycle_period
    state = swim_init(cfg.n_nodes)
    seed_key = jax.random.PRNGKey(seed)
    total = cfg.cycles * cfg.cycle_period + cfg.cycle_period // 2
    total = -(-total // cfg.chunk_ticks) * cfg.chunk_ticks

    t0 = time.perf_counter()
    det_flags, rej_flags = [], []
    ticks = 0
    while ticks < total:
        state, (det, rej) = _scan_chunk(state, seed_key, ticks, cfg)
        det_flags.append(np.asarray(det))
        rej_flags.append(np.asarray(rej))
        ticks += cfg.chunk_ticks
    wall = time.perf_counter() - t0
    det = np.concatenate(det_flags)
    rej = np.concatenate(rej_flags)

    def first_true(flags, start, end):
        w = flags[start:end]
        return int(w.argmax()) if w.any() else None

    per_cycle = []
    for c in range(cfg.cycles):
        lo = c * cfg.cycle_period
        hi = (c + 1) * cfg.cycle_period if c < cfg.cycles - 1 else ticks
        d = first_true(det, lo + cfg.kill_tick, hi)
        r = first_true(rej, lo + cfg.revive_tick, hi)
        per_cycle.append({
            "victim": (cfg.victim + c) % cfg.n_nodes,
            "detect_latency": d,
            "rejoin_latency": r,
        })
    msgs = np.asarray(state.msgs)
    dets = [c["detect_latency"] for c in per_cycle
            if c["detect_latency"] is not None]
    rejs = [c["rejoin_latency"] for c in per_cycle
            if c["rejoin_latency"] is not None]
    return {
        "n_nodes": cfg.n_nodes,
        "cycles": cfg.cycles,
        "per_cycle": per_cycle,
        "detect_latency_mean": (
            float(np.mean(dets)) if dets else None
        ),
        "rejoin_latency_mean": (
            float(np.mean(rejs)) if rejs else None
        ),
        "msgs_per_node_per_tick": float(msgs.mean()) / max(ticks, 1),
        "wall_s": wall,
        "ticks_run": ticks,
    }


def run_churn(cfg: ChurnConfig, seed: int = 0):
    """Returns detection/rejoin latency stats for one churn cycle."""
    state = swim_init(cfg.n_nodes)
    seed_key = jax.random.PRNGKey(seed)

    t0 = time.perf_counter()
    det_flags, rej_flags = [], []
    ticks = 0
    while ticks < cfg.max_ticks:
        state, (det, rej) = _scan_chunk(state, seed_key, ticks, cfg)
        det_flags.append(np.asarray(det))
        rej_flags.append(np.asarray(rej))
        ticks += cfg.chunk_ticks
        if ticks > cfg.revive_tick and rej_flags[-1][-1]:
            break
    wall = time.perf_counter() - t0

    det = np.concatenate(det_flags)
    rej = np.concatenate(rej_flags)
    detect_tick = int(det.argmax()) if det.any() else None
    # rejoin counts only after the revive tick
    rej[: cfg.revive_tick] = False
    rejoin_tick = int(rej.argmax()) if rej.any() else None
    msgs = np.asarray(state.msgs)
    return {
        "n_nodes": cfg.n_nodes,
        "detect_latency": (
            None if detect_tick is None else detect_tick - cfg.kill_tick
        ),
        "rejoin_latency": (
            None if rejoin_tick is None else rejoin_tick - cfg.revive_tick
        ),
        "msgs_per_node_mean": float(msgs.mean()),
        # run-length-independent rate: the total depends on where the
        # chunk grid stops the run, the per-tick rate does not
        "msgs_per_node_per_tick": float(msgs.mean()) / max(ticks, 1),
        "wall_s": wall,
        "ticks_run": ticks,
    }
