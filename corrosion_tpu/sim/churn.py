"""SWIM membership churn simulation (BASELINE.md config #2).

A cluster runs the SWIM model while the ground-truth liveness schedule
kills and revives nodes; the measured quantities are failure-detection
latency (ticks from death until every live node marks the victim down)
and rejoin propagation (ticks until every live node sees the revived
node alive again), plus msgs/node — the SWIM slice of the north-star
metric.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from corrosion_tpu.models.swim import (
    ALIVE,
    DOWN,
    SwimParams,
    key_state,
    swim_init,
    swim_step,
)


@dataclass(frozen=True)
class ChurnConfig:
    n_nodes: int = 64
    params: SwimParams = None  # type: ignore[assignment]
    kill_tick: int = 4  # when the victim dies
    revive_tick: int = 40  # when it comes back
    victim: int = 1
    max_ticks: int = 128
    # bigger chunks = fewer host sync points: each chunk call pays a
    # fixed dispatch cost that dwarfs the N=64 compute, and per-tick
    # flags keep the reported latencies exact either way
    chunk_ticks: int = 32

    def __post_init__(self):
        if self.params is None:
            # cluster-size-scaled SWIM parameters (make_foca_config /
            # Config::new_wan parity): at N=64 the suspicion deadline is
            # 4 * ceil(log10(65)) = 8 probe ticks and updates ride at
            # most 8 gossip rounds
            object.__setattr__(
                self, "params", SwimParams.scaled(self.n_nodes)
            )


@partial(jax.jit, static_argnames=("cfg",))
def _scan_chunk(state, seed_key, start_tick, cfg: ChurnConfig):
    p = cfg.params

    def alive_at(t):
        a = jnp.ones((cfg.n_nodes,), dtype=bool)
        dead = (t >= cfg.kill_tick) & (t < cfg.revive_tick)
        return a.at[cfg.victim].set(~dead)

    def body(st, i):
        t = start_tick + i
        key = jax.random.fold_in(seed_key, t)
        nxt = swim_step(st, key, t, p, alive_at(t))
        others = jnp.arange(cfg.n_nodes) != cfg.victim
        col = key_state(nxt.view[:, cfg.victim])
        detected = jnp.all(jnp.where(others, col == DOWN, True))
        rejoined = jnp.all(jnp.where(others, col == ALIVE, True))
        return nxt, (detected, rejoined)

    return jax.lax.scan(body, state, jnp.arange(cfg.chunk_ticks))


def run_churn(cfg: ChurnConfig, seed: int = 0):
    """Returns detection/rejoin latency stats for one churn cycle."""
    state = swim_init(cfg.n_nodes)
    seed_key = jax.random.PRNGKey(seed)

    t0 = time.perf_counter()
    det_flags, rej_flags = [], []
    ticks = 0
    while ticks < cfg.max_ticks:
        state, (det, rej) = _scan_chunk(state, seed_key, ticks, cfg)
        det_flags.append(np.asarray(det))
        rej_flags.append(np.asarray(rej))
        ticks += cfg.chunk_ticks
        if ticks > cfg.revive_tick and rej_flags[-1][-1]:
            break
    wall = time.perf_counter() - t0

    det = np.concatenate(det_flags)
    rej = np.concatenate(rej_flags)
    detect_tick = int(det.argmax()) if det.any() else None
    # rejoin counts only after the revive tick
    rej[: cfg.revive_tick] = False
    rejoin_tick = int(rej.argmax()) if rej.any() else None
    msgs = np.asarray(state.msgs)
    return {
        "n_nodes": cfg.n_nodes,
        "detect_latency": (
            None if detect_tick is None else detect_tick - cfg.kill_tick
        ),
        "rejoin_latency": (
            None if rejoin_tick is None else rejoin_tick - cfg.revive_tick
        ),
        "msgs_per_node_mean": float(msgs.mean()),
        # run-length-independent rate: the total depends on where the
        # chunk grid stops the run, the per-tick rate does not
        "msgs_per_node_per_tick": float(msgs.mean()) / max(ticks, 1),
        "wall_s": wall,
        "ticks_run": ticks,
    }
