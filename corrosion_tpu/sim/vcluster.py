"""Virtual-time cluster: hundreds of REAL agents on one event heap.

Every prior live artifact (CHAOS/OBS/SCENARIOS/TIMELINE) tops out at
N=32 because agents burn wall-clock in sleeps — SWIM timers, broadcast
flush intervals, sync backoff, breaker cooldowns, partition heal
delays.  This module is the unlock the ROADMAP names: with every agent
time source behind the injectable :class:`~corrosion_tpu.clock.Clock`
(PR: virtual-time cluster), a :class:`~corrosion_tpu.clock.VirtualClock`
plus a discrete-event scheduler drives N=512–1024 in-process agents
through the full fault-campaign stack in *seconds* of wall time
(LiveStack, PAPERS.md: cluster-scale full-stack simulation by putting
unmodified node software on virtual time; "Simulating BFT Protocol
Implementations at Scale", PAPERS.md: the hostile-fraction sweeps that
only become possible at that scale).

What is REAL here (extending ``agent/det.py``'s tick substrate to a
continuous virtual timeline + the seeded ``FaultPlan`` seams):

* full ``Agent`` objects — real SQLite storage with CRR triggers, real
  bookkeeping, real speedy wire bytes (``encode_broadcast_frame`` /
  ``decode_uni_frame_meta``), real ``handle_change`` ingest with dedup,
  equivocation defense (quarantine windows age on the virtual clock),
  rebroadcast-on-learn, real ``Members`` suspicion state, real
  ``generate_sync``/``_serve_need`` anti-entropy down to the frames;
* real ``FaultController`` decisions — per-link drop/delay/partition
  (one-way included), seeded slow-IO draws, crash/restart schedules,
  per-node HLC skew — with ``now=clock.monotonic`` so heal windows
  and schedule times elapse virtually;
* real per-peer ``CircuitBreaker`` objects (cooldowns on the virtual
  clock) driving the real ``Members`` quarantine path.

What the scheduler replaces is exactly the *timing and socket layer*:
timer fires, fault-plan delays, crash/restart schedules and SWIM probe
rounds all advance by event-queue pops instead of sleeps, and frames
hand off in-memory with per-link virtual latency instead of TCP.

Determinism: single-threaded, seeded per-agent PRNG streams
(``det_seed_for``), seeded site ids, a FIXED virtual wall epoch, and
heap ties broken by insertion order — two runs with one
``(seed, FaultPlan, campaign)`` produce byte-identical flight-recorder
event journals and identical end-state checksums
(``tests/test_vtime.py``).  The batched serve path and its thread
pools are therefore OFF by default here (``sync_batched_serve=False``:
the per-version oracle is thread-free; it also avoids 2×N serve
threads at N=1024).
"""

from __future__ import annotations

import asyncio
import hashlib
import json
import random
from typing import Callable, Dict, List, Optional, Tuple

from corrosion_tpu.clock import VirtualClock
from corrosion_tpu.faults import FaultController, FaultPlan


class _TransportStub:
    """The slice of ``Transport`` an unstarted agent's peers of code
    touch: the breaker registry (``_breaker_open`` / ``metric_gauges``)
    and per-peer stats."""

    def __init__(self):
        self.breakers: Dict[tuple, object] = {}
        self.stats: Dict[tuple, object] = {}


class _Pending:
    """One queued broadcast payload on one agent — the virtual form of
    the live loop's ``pending`` tuples (and det.py's ``_Entry``)."""

    __slots__ = ("cv", "frame", "remaining", "next_due", "sent_to")

    def __init__(self, cv, frame: bytes, remaining: int, next_due: float):
        self.cv = cv
        self.frame = frame
        self.remaining = remaining
        self.next_due = next_due
        self.sent_to: set = set()


#: default per-link one-way latency (seconds) — loopback-scale, like
#: the live in-process cluster; FaultPlan delay/jitter adds on top
LINK_RTT_S = 0.002

#: virtual agents mirror launch_test_agent's fast-timer posture, plus
#: the virtual-mode specifics documented in the module docstring
VIRTUAL_DEFAULTS = dict(
    probe_interval=0.25,
    probe_timeout=0.15,
    suspect_timeout=10.0,
    rebroadcast_delay=0.05,
    sync_interval_min=0.15,
    sync_interval_max=0.4,
    bcast_flush_interval=0.02,
    flight_interval_s=0.25,
    breaker_cooldown=0.5,
    subs_enabled=False,
    api_port=None,
    ring0_enabled=False,
    stall_probe_interval=0.0,  # the scheduler's stall beat replaces it
    sync_batched_serve=False,  # thread-free determinism (module doc)
)

#: the scheduler's stall-beat cadence — the virtual analogue of
#: ``AgentConfig.stall_probe_interval`` (a beat that fires late because
#: a jump passed it measures the stall, exactly like the live probe's
#: late wakeup)
STALL_BEAT_S = 0.05


def vsite_id(seed: int, index: int) -> bytes:
    """Seeded site (actor) id — a pure function of (seed, index) so a
    campaign's actor ids are replay-stable."""
    return hashlib.blake2b(
        f"vsite:{seed}:{index}".encode(), digest_size=16
    ).digest()


def vsig_keypair(seed: int, index: int):
    """Seeded Ed25519 keypair for node ``index`` of a SIGNED campaign
    (``types/crypto.py seed_keypair``).  The KDF input includes the
    campaign seed, which the harness holds privately — deriving the
    secret needs more than the public actor id, so a tampering relay
    inside the campaign cannot re-sign what it altered (the property
    the framing_relay cell proves)."""
    from corrosion_tpu.types.crypto import seed_keypair

    return seed_keypair(f"vsig:{seed}:{index}".encode())


class VirtualCluster:
    """N real agents under the virtual-time discrete-event scheduler."""

    def __init__(
        self,
        n: int,
        seed: int = 0,
        plan: Optional[FaultPlan] = None,
        base_dir: Optional[str] = None,
        clock: Optional[VirtualClock] = None,
        link_rtt_s: float = LINK_RTT_S,
        link_rtt_fn=None,
        sign: bool = False,
        defer_crashes: bool = False,
        **agent_overrides,
    ):
        import os
        import tempfile

        from corrosion_tpu.agent.runtime import AgentConfig

        self.n = n
        self.seed = seed
        self.clock = clock or VirtualClock()
        self.link_rtt_s = link_rtt_s
        # optional per-pair RTT: ``link_rtt_fn(i, j) -> seconds`` for
        # node indices i -> j (None = uniform ``link_rtt_s``).  Drives
        # both delivery delay and the probe RTT samples the Members
        # rings record — a heterogeneous fn gives a deterministic
        # multi-tier distribution for ``capture_rtt_topology``
        self.link_rtt_fn = link_rtt_fn
        self.plan = plan or FaultPlan(seed=seed)
        self.ctrl = FaultController(self.plan, now=self.clock.monotonic)
        # signed changeset attribution (docs/faults.md): every node
        # gets a seeded Ed25519 keypair and ONE shared trust directory
        # (the agents hold a live reference, so register_pubkey
        # extends it after boot — e.g. for a keyed hostile actor)
        self.sign = sign
        self._sig_secrets: List[Optional[bytes]] = [None] * n
        self.sig_directory: Dict[bytes, bytes] = {}
        if sign:
            for i in range(n):
                sec, pub = vsig_keypair(seed, i)
                self._sig_secrets[i] = sec
                self.sig_directory[vsite_id(seed, i)] = pub
        # Byzantine sync servers (faults.ByzantineSyncServer): node
        # name -> hostile server double; a client sync round choosing
        # one runs the hostile session instead of the real serve
        self.byz_servers: Dict[str, object] = {}
        # Byzantine snapshot servers (faults.ByzantineSnapshotServer):
        # node name -> double serving tampered snapshot streams; the
        # client's own install gates (digest/size verify) must contain
        # them — never this harness
        self.snap_byz: Dict[str, object] = {}
        self._own_dir = base_dir is None
        self.base_dir = base_dir or tempfile.mkdtemp(prefix="corro-vt-")
        os.makedirs(self.base_dir, exist_ok=True)
        self._overrides = dict(VIRTUAL_DEFAULTS)
        self._overrides.update(agent_overrides)
        self.names = [f"n{i}" for i in range(n)]
        self._idx: Dict[str, int] = {nm: i for i, nm in enumerate(self.names)}
        self.agents: Dict[str, object] = {}
        self._addr_idx: Dict[tuple, int] = {}
        self._crashed: set = set()
        self._entries: List[Dict[tuple, _Pending]] = [{} for _ in range(n)]
        self._flush_armed: List[Optional[object]] = [None] * n
        # recurring-chain handles (probe/sync/snapshot), cancelled on
        # crash: a chain event already queued past restart_at would
        # otherwise survive the death and run a DUPLICATE chain next
        # to the one _restart arms
        self._chain_events: List[List[object]] = [[] for _ in range(n)]
        self._sync_backoff: List[Optional[object]] = [None] * n
        self._busy_until: List[float] = [0.0] * n
        self._incarnations: List[int] = [0] * n
        # per-agent lifetime stall max (the live LoopHealthProbe keeps
        # ITS OWN max; a reborn node starts from zero)
        self._stall_max_by_agent: Dict[str, float] = {}
        self._configs: List[AgentConfig] = []
        # one private loop reused for every serve coroutine: a fresh
        # asyncio.run per sync session costs more than the session at
        # N=512 scale
        self._serve_loop = asyncio.new_event_loop()

        # template DB: one node's schema+trigger DDL, file-copied to
        # the other N-1 with the site row rewritten — the DDL is ~2/3
        # of a 512-agent boot and identical across nodes
        self._template = os.path.join(self.base_dir, "_template.db")
        self._make_template()
        for i, name in enumerate(self.names):
            d = os.path.join(self.base_dir, name)
            os.makedirs(d, exist_ok=True)
            self.ctrl.register(name, ("virt", i))
            self._addr_idx[("virt", i)] = i
            self._configs.append(self._make_config(i, d))
            self.agents[name] = self._spawn(i)
        # full static membership in index order (the det.py contract:
        # Members.sample's population ordering is ascending node index)
        self._seed_membership()
        self.ctrl.agents = self.agents
        self.ctrl.flight_orphans = []
        self.ctrl.start()

        # recurring duties, deterministically staggered per agent
        for i in range(n):
            self._arm_agent_loops(i)
        self.clock.schedule(STALL_BEAT_S, self._stall_beat)
        for ev in self.plan.loop_stalls:
            self.clock.schedule_at(ev.at, self._make_stall(ev))
        if not defer_crashes:
            self.schedule_plan_crashes(0.0)

    def schedule_plan_crashes(self, offset: float) -> None:
        """Schedule the plan's crash/restart events at ``offset +
        ev.at`` / ``offset + ev.restart_at``.  Runs at boot with
        offset 0 unless ``defer_crashes=True`` — the snapshot cells
        (docs/sync.md) defer so their setup phase (history
        convergence + floor compaction, variable virtual duration)
        completes BEFORE the storm's victims start dying."""
        for ev in self.plan.crashes:
            self.clock.schedule_at(
                offset + ev.at, lambda _d, nm=ev.node: self._crash(nm)
            )
            if ev.restart_at is not None:
                self.clock.schedule_at(
                    offset + ev.restart_at,
                    lambda _d, nm=ev.node: self._restart(nm),
                )

    # -- construction ---------------------------------------------------

    def _make_config(self, i: int, node_dir: str):
        from corrosion_tpu.agent.runtime import AgentConfig
        from corrosion_tpu.agent.testing import TEST_SCHEMA

        offset_ns, drift = self.ctrl.clock_for(self.names[i])
        sig_kwargs = {}
        if self.sign:
            sig_kwargs = dict(
                sig_secret=self._sig_secrets[i],
                # the SHARED directory object: late registrations
                # (hostile keys, respawns) are visible to every agent
                sig_pubkeys=self.sig_directory,
            )
        return AgentConfig(
            db_path=f"{node_dir}/corrosion.db",
            schema_sql=TEST_SCHEMA,
            clock=self.clock,
            site_id=vsite_id(self.seed, i),
            clock_skew_ns=offset_ns,
            clock_drift=drift,
            **sig_kwargs,
            **self._overrides,
        )

    def register_pubkey(self, actor_id: bytes, pub: bytes) -> None:
        """Extend the shared trust directory (e.g. a keyed hostile
        actor whose signed conflicts the campaign must prove)."""
        self.sig_directory[bytes(actor_id)] = bytes(pub)

    def _make_template(self) -> None:
        """Build the one template database every fresh node copies:
        full schema + CRR triggers applied once, WAL folded in so the
        copy is a single file."""
        import sqlite3

        from corrosion_tpu.agent.schema import apply_schema
        from corrosion_tpu.agent.storage import CrConn
        from corrosion_tpu.agent.testing import TEST_SCHEMA

        st = CrConn(self._template, site_id=b"\x00" * 16)
        apply_schema(st, TEST_SCHEMA)
        st.conn.execute("PRAGMA wal_checkpoint(TRUNCATE)")
        st.close()
        con = sqlite3.connect(self._template)
        con.execute("PRAGMA journal_mode=DELETE")
        con.close()

    def _instantiate_db(self, i: int) -> None:
        """Fresh node from the template: copy + rewrite the self-site
        row to the node's seeded id (a RESTART skips this — the
        existing directory is the node's durable identity)."""
        import os
        import shutil
        import sqlite3

        path = self._configs[i].db_path
        if os.path.exists(path):
            return
        shutil.copyfile(self._template, path)
        con = sqlite3.connect(path)
        # durability is not a property under test at instantiation
        # time (the campaign's crash model closes storage cleanly):
        # skip the per-node fsync — at N=512 the commits alone cost
        # ~1 s of boot
        con.execute("PRAGMA synchronous=OFF")
        con.execute(
            "UPDATE __corro_sites SET site_id = ? WHERE ordinal = 1",
            (self._configs[i].site_id,),
        )
        con.commit()
        con.close()

    def _seed_membership(self) -> None:
        """Full static ALIVE membership, written directly (the upsert
        path costs ~2.5s of a 512-node boot for N² records whose merge
        rules are all trivially 'new')."""
        from corrosion_tpu.agent.members import Member

        now = self.clock.monotonic()
        infos = [
            (a.actor_id, ("virt", j))
            for j, a in enumerate(self.agents.values())
        ]
        for a in self.agents.values():
            with a.members._lock:
                mm = a.members._members
                for actor, addr in infos:
                    if actor != a.actor_id and actor not in mm:
                        mm[actor] = Member(
                            actor_id=actor, addr=addr, last_seen=now
                        )
                a.members._alive_cache = None

    def _spawn(self, i: int):
        from corrosion_tpu.agent.det import _SyncLoop, det_seed_for
        from corrosion_tpu.agent.runtime import Agent

        self._instantiate_db(i)
        a = Agent(self._configs[i])
        # per-node deterministic PRNG stream; a respawn moves to a
        # derived stream (pure in (seed, i, incarnation)) so the reborn
        # node doesn't replay its previous life's draws
        a._rng = random.Random(
            det_seed_for(self.seed, i) ^ (self._incarnations[i] * 0x9E3779B9)
        )
        a._loop = _SyncLoop()  # queue-or-defer paths run inline
        a.transport = _TransportStub()
        a.faults = self.ctrl
        a.gossip_addr = ("virt", i)
        # slow-disk seam: the seeded decision is consulted (counted +
        # logged) but the delay is charged to VIRTUAL busy time — a
        # real sleep would burn wall clock without moving the heap
        inner = self.ctrl.io_hook_for(self.names[i])

        def io_hook(op: str, _i=i, _inner=inner) -> float:
            d = _inner(op)
            if d > 0:
                now = self.clock.monotonic()
                self._busy_until[_i] = max(self._busy_until[_i], now) + d
            return 0.0

        a.storage.io_fault = io_hook
        return a

    def _chain(self, i: int, at: float, fn) -> None:
        """Schedule one link of a per-agent recurring chain, keeping
        the handle so :meth:`_crash` can sever the whole chain."""
        self._chain_events[i].append(self.clock.schedule_at(at, fn))

    def _arm_agent_loops(self, i: int) -> None:
        from corrosion_tpu.utils.backoff import Backoff

        a = self.agents[self.names[i]]
        cfg = a.config
        now = self.clock.monotonic()
        stagger = ((i * 0.6180339887) % 1.0)
        self._chain(
            i, now + cfg.probe_interval * (1.0 + stagger),
            lambda due, _i=i: self._probe_round(_i, due),
        )
        self._sync_backoff[i] = iter(
            Backoff(base=cfg.sync_interval_min, cap=cfg.sync_interval_max,
                    rng=a._rng)
        )
        self._chain(
            i, now + next(self._sync_backoff[i]) * (1.0 + stagger),
            lambda due, _i=i: self._sync_round(_i, due),
        )
        if a.flight is not None and cfg.flight_interval_s > 0:
            self._chain(
                i, now + cfg.flight_interval_s * (1.0 + stagger),
                lambda due, _i=i: self._snapshot(_i, due),
            )

    # -- workload -------------------------------------------------------

    def write(self, origin: int, sql: str, args: tuple = ()) -> int:
        """One local write on ``origin``; broadcast collection runs
        inline (``_SyncLoop``) and the payload flushes at the next
        armed flush event."""
        res = self.agents[self.names[origin]].execute_transaction(
            [(sql, args)]
        )
        self._arm_flush(origin)
        return res["version"]

    def inject(self, targets: List[int], cv, source,
               delay: float = 0.0, rebroadcast: bool = True,
               sig: Optional[bytes] = None, peer=None) -> None:
        """Schedule a crafted changeset (e.g. an ``EquivocatingPeer``
        payload) into each target's REAL ingest path at ``now+delay`` —
        the virtual form of the live harness's ``_deliver``.

        ``rebroadcast=False`` delivers point-to-point without relay
        amplification: with the payload already injected at EVERY
        node, re-gossiping it adds only duplicate traffic — at N=512
        with 32 hostiles that is ~10^5 redundant decodes per wave.
        The single-equivocator matrix family keeps relay on, so the
        rebroadcast-path defense coverage is not lost.

        ``sig`` rides the delivery as the origin's claimed Ed25519
        signature; ``peer`` attributes the delivery to a transport
        address (the framing_relay cell's tampering relay) — together
        the signed-attribution meta the live envelope would carry."""
        for j in targets:
            self.clock.schedule(
                delay, lambda _d, _j=j, _cv=cv: self._ingest_injected(
                    _j, _cv, source, rebroadcast, sig=sig, peer=peer
                )
            )

    def _ingest_injected(self, j: int, cv, source,
                         rebroadcast: bool = True,
                         sig: Optional[bytes] = None, peer=None) -> None:
        if j in self._crashed_idx():
            return
        a = self.agents[self.names[j]]
        a.handle_change(cv, source, rebroadcast=rebroadcast,
                        meta=(None, 0, sig, peer))
        if rebroadcast:
            self._arm_flush(j)

    # -- the scheduler's duties ----------------------------------------

    def _crashed_idx(self) -> set:
        return {self._idx[nm] for nm in self._crashed}

    def _arm_flush(self, i: int, at: Optional[float] = None) -> None:
        """Ensure a flush event is armed for agent ``i`` no later than
        ``at`` (default: one flush interval out — the live loop's
        fresh-payload latency)."""
        if self.names[i] in self._crashed:
            return
        a = self.agents[self.names[i]]
        now = self.clock.monotonic()
        at = max(
            at if at is not None else now + a.config.bcast_flush_interval,
            self._busy_until[i],
        )
        armed = self._flush_armed[i]
        if armed is not None and not armed.cancelled and armed.due <= at:
            return
        if armed is not None:
            self.clock.cancel(armed)
        self._flush_armed[i] = self.clock.schedule_at(
            at, lambda due, _i=i: self._flush(_i, due)
        )

    def _flush(self, i: int, _due: float) -> None:
        """One broadcast flush for agent ``i``: drain the queue, send
        due payloads through the fault plan, requeue retransmissions —
        the live ``_broadcast_loop`` body on the virtual heap."""
        self._flush_armed[i] = None
        name = self.names[i]
        if name in self._crashed:
            return
        a = self.agents[name]
        cfg = a.config
        now = self.clock.monotonic()
        entries = self._entries[i]
        while not a._bcast_queue.empty():
            cv, remaining, hop, tp, sig = a._bcast_queue.get_nowait()
            key = a._seen_key(cv)
            if key in entries:
                continue
            entries[key] = _Pending(
                cv, a.encode_broadcast_frame(cv, hop, tp, sig),
                remaining, now,
            )
        crashed = self._crashed_idx()
        sends = 0
        for key in list(entries):
            e = entries[key]
            if e.next_due > now or e.remaining < 1:
                continue
            local = e.cv.actor_id.bytes == a.actor_id
            targets = a.members.sample(
                cfg.fanout, a._rng,
                ring0_first=(cfg.ring0_enabled and local and not e.sent_to),
                exclude=e.sent_to,
            )
            if not targets:
                del entries[key]  # coverage exhausted
                continue
            for m in targets:
                addr = tuple(m.addr)
                j = self._addr_idx.get(addr)
                if j is None:
                    # a member record with no cluster node behind it
                    # (e.g. a registered-then-hostile actor): the live
                    # transport fails to connect — breaker evidence
                    self._breaker_failure(a, addr)
                    continue
                if j in crashed:
                    # a dead peer is a genuine send failure: breaker
                    # evidence, no sent_to mark (stays eligible)
                    self._breaker_failure(a, addr)
                    continue
                # in-flight fault semantics (faults.py): drops and
                # partitions are sender-invisible — the send "succeeds"
                e.sent_to.add(m.actor_id)
                self._breaker_success(a, addr)
                sends += 1
                act = self.ctrl.filter(name, self.names[j], "uni")
                if act.drop:
                    continue
                self.clock.schedule(
                    self._pair_rtt_s(i, j) + act.delay,
                    lambda _d, _j=j, _f=e.frame, _i=i: self._deliver(
                        _j, _f, src=_i
                    ),
                )
            e.remaining -= 1
            if e.remaining < 1:
                del entries[key]
            else:
                send_count = cfg.max_transmissions - e.remaining
                e.next_due = now + cfg.rebroadcast_delay * send_count
        if sends:
            a.metrics.counter("corro_broadcast_sent_total", sends)
            a.metrics.counter("corro_broadcast_flushes_total")
        # re-arm: retransmissions wake at their due time; fresh queue
        # items (raced in during this event) at the flush interval
        nxt = min((e.next_due for e in entries.values()), default=None)
        if not a._bcast_queue.empty():
            self._arm_flush(i)
        elif nxt is not None:
            self._arm_flush(i, at=max(nxt, now + 1e-4))

    def _deliver(self, j: int, frame: bytes,
                 src: Optional[int] = None) -> None:
        """Delivery phase: the real wire + ingest path (det.py's
        contract), then re-arm the receiver's flush for any
        rebroadcast-on-learn it queued inline.  ``src`` is the sending
        node index — the delivering-transport identity a failed origin
        signature blames (``runtime._blame_relay``)."""
        from corrosion_tpu.bridge import speedy
        from corrosion_tpu.types import ChangeSource

        if j in self._crashed_idx():
            return
        a = self.agents[self.names[j]]
        peer = ("virt", src) if src is not None else None
        for payload in speedy.FrameReader().feed(frame):
            decoded = a.decode_uni_frame_meta(payload)
            if decoded is not None:
                cv, tp, hop, sig = decoded
                a.handle_change(cv, ChangeSource.BROADCAST,
                                meta=(tp, hop, sig, peer))
        if not a._bcast_queue.empty():
            self._arm_flush(j)

    def _pair_rtt_s(self, i: int, j) -> float:
        """One-way link latency node i -> node j in seconds."""
        if self.link_rtt_fn is not None and j is not None:
            return float(self.link_rtt_fn(i, j))
        return self.link_rtt_s

    # -- SWIM probes on the heap ---------------------------------------

    def _udp_leg_ok(self, src: str, dst: str) -> bool:
        act = self.ctrl.filter(src, dst, "udp")
        return not act.drop

    def _probe_round(self, i: int, due: float) -> None:
        name = self.names[i]
        if name in self._crashed:
            return
        a = self.agents[name]
        self._chain_events[i] = [
            e for e in self._chain_events[i] if not e.cancelled
            and e.due > self.clock.monotonic()
        ]
        self._chain(
            i, max(due + a.config.probe_interval, self._busy_until[i]),
            lambda d, _i=i: self._probe_round(_i, d),
        )
        alive = a.members.alive()
        if alive:
            m = a._rng.choice(alive)
            tj = self._addr_idx.get(tuple(m.addr))
            target = self.names[tj] if tj is not None else None
            t_up = target is not None and target not in self._crashed
            ok = (
                t_up
                and self._udp_leg_ok(name, target)
                and self._udp_leg_ok(target, name)
            )
            if not ok and target is not None:
                # indirect probe via helpers (consumes the same rng
                # draw the live loop's helper sample does)
                helpers = [
                    h for h in alive if h.actor_id != m.actor_id
                ]
                if helpers:
                    helpers = a._rng.sample(
                        helpers,
                        min(a.config.num_indirect_probes, len(helpers)),
                    )
                    for h in helpers:
                        hj = self._addr_idx.get(tuple(h.addr))
                        if hj is None:
                            continue  # no node behind the record
                        hname = self.names[hj]
                        if hname in self._crashed:
                            continue
                        if (
                            self._udp_leg_ok(name, hname)
                            and self._udp_leg_ok(hname, target)
                            and t_up
                            and self._udp_leg_ok(target, hname)
                            and self._udp_leg_ok(hname, name)
                        ):
                            ok = True
                            break
            if ok:
                a.members.record_rtt(
                    m.actor_id, self._pair_rtt_s(i, tj) * 2e3
                )
                a._suspects.pop(m.actor_id, None)
                a.members.revive(m.actor_id)
            else:
                a._mark_suspect(m)
        a._reap_suspects()

    # -- anti-entropy on the heap --------------------------------------

    def _breaker(self, a, addr: tuple):
        from corrosion_tpu.agent.transport import CircuitBreaker

        b = a.transport.breakers.get(addr)
        if b is None:
            b = a.transport.breakers[addr] = CircuitBreaker(
                a.config.breaker_threshold, a.config.breaker_cooldown,
                now=self.clock.monotonic,
            )
        return b

    def _breaker_failure(self, a, addr: tuple) -> None:
        if self._breaker(a, addr).record_failure():
            a.metrics.counter("corro_transport_breaker_opens_total")
            a._on_breaker(addr, True)

    def _breaker_success(self, a, addr: tuple) -> None:
        if self._breaker(a, addr).record_success():
            a.metrics.counter("corro_transport_breaker_closes_total")
            a._on_breaker(addr, False)

    def _sync_round(self, i: int, due: float) -> None:
        """One client sync round for agent ``i`` — det.py's
        ``_det_sync_round`` extended with fault/breaker/journal
        semantics: REAL ``generate_sync`` / ``_choose_sync_peers`` /
        ``_allocate_needs`` / ``_serve_need`` down to the frame bytes;
        the scheduler replaces the socket/timing layer, and a severed
        direction (either way — the bi-stream needs both) or a crashed
        peer is a session failure feeding the breaker."""
        name = self.names[i]
        if name in self._crashed:
            return
        a = self.agents[name]
        self._chain(
            i, max(due + next(self._sync_backoff[i]),
                   self._busy_until[i]),
            lambda d, _i=i: self._sync_round(_i, d),
        )
        ours = a.generate_sync()
        chosen = a._choose_sync_peers(ours)
        if not chosen:
            return
        sessions = []
        for m in chosen:
            addr = tuple(m.addr)
            j = self._addr_idx.get(addr)
            if j is None:
                self._breaker_failure(a, addr)
                continue
            peer = self.names[j]
            if not self._breaker(a, addr).allow():
                continue
            act = self.ctrl.filter(name, peer, "bi")
            if (
                peer in self._crashed
                or act.drop
                or self.ctrl._partitioned(peer, name)
            ):
                self._breaker_failure(a, addr)
                continue
            byz = self.byz_servers.get(peer)
            if byz is not None:
                # hostile serve: the client-side defenses (state
                # screen, need cap, frame budget, session deadline)
                # must contain it — never this harness
                self._byz_session(a, m, byz)
                continue
            sbyz = self.snap_byz.get(peer)
            if sbyz is not None:
                # hostile SNAPSHOT serve: the install gates (digest +
                # size verification over the staged bytes) must
                # contain it — never this harness
                self._vsnap_byz(a, m, sbyz, j)
                continue
            self._breaker_success(a, addr)
            sessions.append({
                "member": m,
                "theirs": self.agents[peer].generate_sync(),
                "j": j,
            })
        if not sessions:
            return
        # snapshot-or-changes dispatch: the REAL agent selection policy
        # (runtime._pick_snapshot_session) — at most one session per
        # round installs; the rest allocate needs as usual
        snap_sess, sessions = a._pick_snapshot_session(sessions, ours)
        a._allocate_needs(sessions, ours)
        if snap_sess is not None:
            self._vsnap_session(i, a, snap_sess)
            if name in self._crashed:
                # a SnapFault killed the client mid-install: the rest
                # of its round dies with it
                return
        for s in sessions:
            self._sync_session(a, s)

    def _sync_session(self, a, s: dict) -> None:
        from corrosion_tpu.agent.det import _CollectWriter
        from corrosion_tpu.bridge import speedy
        from corrosion_tpu.types import ChangeSource, Timestamp

        m = s["member"]
        server = self.agents[self.names[s["j"]]]
        batches = list(a._request_batches(s["needs"]))
        needs_total = sum(len(v) for v in s["needs"].values())
        peer_hex = m.actor_id.hex()
        live = a._sync_session_begin("client", peer_hex, needs_total)
        a._flight_event(
            "sync_client_start", peer=peer_hex, needs=needs_total
        )
        srv_live = server._sync_session_begin(
            "server", a.actor_id.hex(), needs_total
        )
        server._flight_event(
            "sync_server_start", peer=a.actor_id.hex()
        )
        served: List = []
        w = _CollectWriter()
        if batches:
            sess = {"chunk": server.SYNC_CHUNK_MAX, "live": srv_live}

            async def serve_all():
                for batch in batches:
                    for actor, needs in batch:
                        for need in needs:
                            await server._serve_need(
                                w, actor.bytes, need, sess
                            )
                            srv_live["needs_done"] += 1

            self._serve_loop.run_until_complete(serve_all())
            reader = speedy.FrameReader()
            for payload in reader.feed(b"".join(w.chunks)):
                served.append(speedy.decode_sync_message(payload))
        count = 0
        for msg in served:
            if isinstance(msg, Timestamp):
                try:
                    a.clock.update_with_timestamp(msg)
                except Exception:
                    pass
            elif hasattr(msg, "actor_id"):  # ChangeV1
                a.handle_change(msg, ChangeSource.SYNC)
                count += 1
        live["changes"] = count
        live["bytes"] = sum(len(c) for c in w.chunks)
        a.members.update_sync_ts(m.actor_id, self.clock.wall())
        a.metrics.counter("corro_sync_client_rounds_total")
        a._sync_session_end(live, "client", "received")
        a._flight_event(
            "sync_client_end", peer=peer_hex,
            changes=count, bytes=live["bytes"], complete=True,
        )
        server._sync_session_end(srv_live, "server", "served")
        server._flight_event(
            "sync_server_end", peer=a.actor_id.hex(),
            needs=srv_live["needs_done"], bytes=srv_live["bytes"],
        )

    def _byz_session(self, a, m, byz) -> None:
        """One client session against a Byzantine sync server
        (``faults.ByzantineSyncServer``): the hostile advert/serve is
        produced by the double, and containment comes exclusively from
        the agent's OWN client-side defenses — the advertised-state
        screen, the per-session need cap (inside ``_allocate_needs``),
        the frame-validation budget, and the session deadline."""
        from corrosion_tpu.bridge import speedy
        from corrosion_tpu.types.changeset import ChangeSource, ChangeV1

        addr = tuple(m.addr)
        theirs = byz.advertised_state()
        reason = a._screen_sync_state(theirs)
        if reason is not None:
            a._sync_client_reject(reason, addr, trip=True)
            return
        sessions = [{"member": m, "theirs": theirs}]
        a._allocate_needs(sessions, a.generate_sync())
        deadline = a.config.sync_session_deadline_s
        if deadline > 0 and byz.serve_duration() > deadline:
            # slow trickle: the virtual serve would outlive the
            # session deadline — the client aborts at the budget
            a._sync_client_reject("deadline", addr)
            self._breaker_failure(a, addr)
            return
        try:
            payloads = speedy.FrameReader().feed(
                byz.serve_frames(sessions[0]["needs"])
            )
        except speedy.SpeedyError:
            # oversized/corrupt framing kills the whole stream
            a._sync_client_reject("frame_garbage", addr, trip=True)
            return
        frame_errs = 0
        for payload in payloads:
            try:
                msg = speedy.decode_sync_message(payload)
            except speedy.SpeedyError:
                frame_errs += 1
                a._sync_client_reject("frame_garbage")
                if frame_errs > a.SYNC_CLIENT_FRAME_BUDGET:
                    a._trip_breaker(addr)
                    return
                continue
            if isinstance(msg, ChangeV1):
                # conflicting re-serves of held versions land in the
                # version-ledger dedup; fresh hostile data is gated by
                # what the advert could legitimately offer
                a.handle_change(msg, ChangeSource.SYNC,
                                rebroadcast=False)

    def _vsnap_session(self, i: int, a, s: dict) -> None:
        """One snapshot install session on the virtual heap: the live
        wire replaced by in-memory chunk handoff, every install gate
        REAL — whole-snapshot digest verify, identity rewrite on the
        staged sidecar, journal marker, atomic swap, in-place state
        reload — plus the ``SnapFault`` crash stages, which kill the
        client exactly where the knob says and prove the boot-time
        recovery contract (``snapshot.recover_pending_install``)."""
        from corrosion_tpu.agent.snapshot import SnapshotCrash

        name = self.names[i]
        m = s["member"]
        server = self.agents[self.names[s["j"]]]
        fault = self.ctrl.snap_decision(name)
        crash_at = None
        if fault is not None and fault.mode in (
            "crash_installing", "crash_swapped"
        ):
            crash_at = fault.mode[len("crash_"):]
        path, digest, size = server._snapshot_build()
        with open(path, "rb") as f:
            blob = f.read()
        server._snapshot_serve_record(a.actor_id.hex(), len(blob))
        st = a._snapshot_stage_begin(
            m.actor_id.hex(), digest, size, s["theirs"].heads,
            crash_at=crash_at,
        )
        cb = max(1, a.config.snapshot_chunk_bytes)
        chunks = [blob[k : k + cb] for k in range(0, len(blob), cb)]
        try:
            for idx, chunk in enumerate(chunks):
                if fault is not None and fault.mode == "crash_staging" \
                        and idx == len(chunks) // 2:
                    raise SnapshotCrash("staging")
                a._snapshot_stage_feed(st, chunk)
            ok = a._snapshot_install_staged(st, addr=tuple(m.addr))
        except SnapshotCrash:
            # leave the sidecar/marker exactly as the crash found them
            # (a real death flushes nothing further); the reborn node's
            # boot recovery classifies the window
            f = st.pop("f", None)
            if f is not None:
                try:
                    f.close()
                except OSError:
                    pass
            self._crash(name)
            if fault is not None:
                self.clock.schedule(
                    fault.restart_delay,
                    lambda _d, nm=name: self._restart(nm),
                )
            return
        if ok:
            a.members.update_sync_ts(m.actor_id, self.clock.wall())

    def _vsnap_byz(self, a, m, byz, j: int) -> None:
        """One client session against a Byzantine snapshot server
        (``faults.ByzantineSnapshotServer``): the hostile advert +
        tampered stream come from the double, and containment comes
        exclusively from the client's OWN install gates — the offer
        screen and the whole-snapshot digest/size verification.  A
        contained serve trips the hostile peer's breaker, so the
        client's next rounds fall back to change-by-change via honest
        peers."""
        server = self.agents[self.names[j]]
        theirs = byz.advertised_state(server)
        ours = a.generate_sync()
        if not a._snapshot_wanted(ours, theirs):
            return
        addr = tuple(m.addr)
        digest, size, chunks = byz.tampered_serve(
            server, a.config.snapshot_chunk_bytes
        )
        st = a._snapshot_stage_begin(
            m.actor_id.hex(), digest, size, theirs.heads
        )
        try:
            for chunk in chunks:
                a._snapshot_stage_feed(st, chunk)
        except Exception:
            a._snapshot_abort(st, "snap_stream", addr, trip=True)
            return
        # truncated/corrupted/divergent bytes all die on the digest
        # gate inside the install (reason=snap_digest, breaker trip)
        a._snapshot_install_staged(st, addr=addr)

    def schedule_wipe(self, name: str, at: float) -> None:
        """Schedule deletion of ``name``'s database (+ snapshot
        sidecars) — between a crash and its restart this turns the
        reborn node into a FRESH bootstrap (the long-dead/new-node
        shape whose catch-up the snapshot path exists for)."""
        import os

        path = self._configs[self._idx[name]].db_path

        def wipe(_due: float) -> None:
            for p in (
                path, path + "-wal", path + "-shm",
                path + ".snap-staged", path + ".snap-state",
                path + ".snap-serve",
            ):
                if os.path.exists(p):
                    os.unlink(p)

        self.clock.schedule_at(at, wipe)

    # -- recorder snapshots / stall beats ------------------------------

    def _snapshot(self, i: int, due: float) -> None:
        name = self.names[i]
        if name in self._crashed:
            return
        a = self.agents[name]
        self._chain(
            i, max(due + a.config.flight_interval_s,
                   self._busy_until[i]),
            lambda d, _i=i: self._snapshot(_i, d),
        )
        a.flight.snapshot_once()

    def _stall_beat(self, due: float) -> None:
        """The virtual LoopHealthProbe: a beat that fires late (a
        ``jump`` passed it) measures the stall for EVERY agent — the
        in-process cluster shares one loop, so a stall freezes them
        all at once (the live ``run_stall_schedule`` semantics)."""
        self.clock.schedule(STALL_BEAT_S, self._stall_beat)
        late_ms = (self.clock.monotonic() - due) * 1e3
        if late_ms < 0.5:
            return
        crashed = self._crashed
        for name, a in self.agents.items():
            if name in crashed:
                continue
            a.metrics.histogram("corro_loop_stall_ms", late_ms)
            # per-AGENT lifetime max, like the live probe's: a reborn
            # node's fresh registry starts from zero and must not be
            # gated on some other incarnation's cluster-wide record
            if late_ms > self._stall_max_by_agent.get(name, 0.0):
                self._stall_max_by_agent[name] = late_ms
                a.metrics.gauge("corro_loop_stall_max_ms", late_ms)

    def _make_stall(self, ev) -> Callable[[float], None]:
        def fire(_due: float) -> None:
            self.clock.jump(ev.duration_ms / 1e3)
            self.ctrl.injected["stall"] += 1
            self.ctrl.stall_log.append(
                (self.ctrl.elapsed(), ev.node, ev.duration_ms)
            )

        return fire

    # -- crash / restart -----------------------------------------------

    def _crash(self, name: str) -> None:
        if name in self._crashed:
            return
        agent = self.agents[name]
        if agent.flight is not None:
            agent.flight.event("crash", node=name)
            self.ctrl.flight_orphans.append(
                (name, agent.flight.entries())
            )
        try:
            agent.storage.close()
        except Exception:
            pass
        i = self._idx[name]
        self._entries[i].clear()
        armed = self._flush_armed[i]
        if armed is not None:
            self.clock.cancel(armed)
            self._flush_armed[i] = None
        for ev in self._chain_events[i]:
            self.clock.cancel(ev)
        self._chain_events[i] = []
        self._crashed.add(name)
        self.ctrl.crash_log.append((self.ctrl.elapsed(), "crash", name))

    def _restart(self, name: str) -> None:
        """Respawn from the SAME node directory — resume, not re-seed:
        the reborn agent reloads its persisted site id, incarnation,
        bookkeeping and equivocation digests, re-derives its (identical)
        bad oscillator from the plan, and catches up through
        anti-entropy."""
        if name not in self._crashed:
            return
        i = self._idx[name]
        self._incarnations[i] += 1
        self._crashed.discard(name)
        self._stall_max_by_agent.pop(name, None)
        agent = self._spawn(i)
        self.agents[name] = agent
        self.ctrl.agents = self.agents
        if agent.flight is not None:
            agent.flight.event("restart", node=name)
        # membership: the reborn node announces (virtual form of the
        # announce/gossip round) — peers refresh its record with the
        # bumped incarnation; it re-learns every live peer
        for j, peer in enumerate(self.agents.values()):
            if peer is agent or self.names[j] in self._crashed:
                continue
            peer.members.upsert(
                agent.actor_id, ("virt", i), incarnation=agent.incarnation
            )
            peer._suspects.pop(agent.actor_id, None)
            agent.members.upsert(peer.actor_id, ("virt", j))
        self._arm_agent_loops(i)
        self.ctrl.crash_log.append(
            (self.ctrl.elapsed(), "restart", name)
        )

    # -- driving --------------------------------------------------------

    def run_for(self, dt: float) -> int:
        return self.clock.run_until(self.clock.monotonic() + dt)

    def run_until_true(self, pred: Callable[[], bool],
                       timeout: float, step: float = 0.25) -> bool:
        """Advance virtual time in ``step`` slices until ``pred()``
        holds (checked between slices) or ``timeout`` virtual seconds
        pass.  The virtual ``wait_for``."""
        deadline = self.clock.monotonic() + timeout
        while True:
            if pred():
                return True
            if self.clock.monotonic() >= deadline:
                return False
            self.run_for(min(step, deadline - self.clock.monotonic()))

    # -- measurement ----------------------------------------------------

    def observer(self):
        from corrosion_tpu.devcluster import ClusterObserver

        live = {
            nm: a for nm, a in self.agents.items()
            if nm not in self._crashed
        }
        return ClusterObserver(live, faults=self.ctrl)

    def converged(self, versions: List[Tuple[bytes, int]]) -> bool:
        """Every live node holds every tracked (actor, version)."""
        for nm, a in self.agents.items():
            if nm in self._crashed:
                continue
            for actor, v in versions:
                if a.actor_id != actor and not a.bookie.for_actor(
                    actor
                ).contains_version(v):
                    return False
        return True

    def journal_bytes(self) -> bytes:
        """The merged typed-event journal, canonically serialized —
        the byte-determinism surface: two runs with one (seed, plan,
        campaign) must produce EQUAL bytes."""
        events = self.observer().flight_events()
        return json.dumps(events, sort_keys=True).encode()

    def state_checksum(self) -> str:
        """End-state checksum over every live node's CRR table bytes
        and bookkeeping ledgers — the determinism test's second half
        (and a compact no-divergence witness: all-equal per-node
        digests ⇒ bytewise-equal table state)."""
        h = hashlib.blake2b(digest_size=16)
        for nm in self.names:
            if nm in self._crashed:
                continue
            a = self.agents[nm]
            h.update(nm.encode())
            for t in sorted(a.storage.tables):
                q = t.replace('"', '""')
                cols, rows = a.storage.read_query(f'SELECT * FROM "{q}"')
                h.update(repr((t, cols, sorted(rows, key=repr))).encode())
            with a.storage._lock:
                for actor, bv in sorted(
                    a.bookie.actors().items(), key=lambda kv: kv[0]
                ):
                    h.update(repr((
                        actor, bv.max_version, tuple(bv.needed.spans()),
                        tuple(sorted(bv.partials)),
                    )).encode())
        return h.hexdigest()

    def close(self) -> None:
        import shutil

        for nm, a in self.agents.items():
            if nm in self._crashed:
                continue
            try:
                a.storage.close()
            except Exception:
                pass
        self._serve_loop.close()
        if self._own_dir:
            shutil.rmtree(self.base_dir, ignore_errors=True)


def capture_rtt_topology(cluster: "VirtualCluster", edges=None) -> dict:
    """Aggregate every live node's Members RTT-ring view into one
    measured-topology JSON dict (``topology: measured_ring``).

    This is the deterministic campaign-side twin of the agent admin
    ``rtt dump`` command: instead of querying one node over the UDS,
    it merges the per-node tier distributions that SWIM probe rounds
    recorded (``VirtualCluster(link_rtt_fn=...)`` makes those
    heterogeneous and reproducible).  The resulting ``weights`` vector
    feeds ``bench.py --frontier --topology measured_ring`` /
    ``HeadlineExactConfig(rtt_tier_weights=...)`` directly.
    """
    from corrosion_tpu.agent.members import (
        DEFAULT_RTT_TIER_EDGES_MS,
        rtt_topology,
    )

    if edges is None:
        edges = DEFAULT_RTT_TIER_EDGES_MS
    n_tiers = len(edges) + 1
    weights = [0] * n_tiers
    sampled = unsampled = 0
    per_node = []
    for nm in cluster.names:
        if nm in cluster._crashed:
            continue
        topo = rtt_topology(cluster.agents[nm].members, edges)
        w = topo["weights"]
        for t, c in enumerate(w):
            weights[t] += c
        sampled += topo["members_sampled"]
        unsampled += topo["members_unsampled"]
        per_node.append({"node": nm, "weights": w})
    while len(weights) > 1 and weights[-1] == 0:
        weights.pop()
    return {
        "topology": "measured_ring",
        "tier_edges_ms": list(edges),
        "rtt_tiers": len(weights),
        "weights": weights,
        "members_sampled": sampled,
        "members_unsampled": unsampled,
        "nodes": per_node,
    }
