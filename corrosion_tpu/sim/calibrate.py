"""Exact-sampler msgs/node calibration at large N.

The production epidemic kernel delivers via permutation fanout
(``models/broadcast.py``): collision-free in-degree makes its
msgs-at-convergence a known ~0.65-0.75× lower bound of the exact
``sent_to``-excluding sampler the agents run.  The exact sampler's old
home (``broadcast_step(sent=...)``) holds [N, N] *scores* per tick and
vmaps seeds, capping calibration at N≈512.  This module runs the exact
protocol at N=1k-16k:

* one seed at a time (no vmapped [S, N, N] state);
* ``sent`` as one [N, N] bool (256 MB at 16k — fits HBM);
* per-tick scores generated in sender CHUNKS of [C, N] with
  ``lax.top_k`` selection, so the 1 GB full scores matrix never
  materializes;
* single-payload state ([N] infected/budget/backoff), the same
  semantics the deterministic bit-match pins against the live agents
  (``sim/bitmatch.py``): retire on exhausted coverage, rebroadcast with
  fresh budget on learn, nth retransmission after
  ``max(1, round(backoff * n))`` ticks.

``run_msgs_calibration`` measures msgs/node at convergence for the
exact sampler vs the matched perm-fanout config and emits the ratio per
N — the correction factor ``bench.py`` applies to annotate its sweep
(``CALIB_MSGS.json``).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from functools import partial
from typing import Dict, List, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np


@dataclass(frozen=True)
class ExactConfig:
    n_nodes: int
    fanout: int = 4
    max_transmissions: int = 8
    backoff_ticks: float = 0.0
    max_ticks: int = 192
    sender_chunk: int = 2048


class ExactState(NamedTuple):
    infected: jnp.ndarray  # [N] bool
    tx: jnp.ndarray  # [N] int32 remaining transmissions
    next_send: jnp.ndarray  # [N] int32
    sent: jnp.ndarray  # [N, N] bool per-payload sent_to
    msgs: jnp.ndarray  # [N] int32
    tick: jnp.ndarray  # scalar int32


def exact_init(cfg: ExactConfig, writer: int = 0) -> ExactState:
    n = cfg.n_nodes
    return ExactState(
        infected=jnp.zeros((n,), bool).at[writer].set(True),
        tx=jnp.zeros((n,), jnp.int32).at[writer].set(cfg.max_transmissions),
        next_send=jnp.zeros((n,), jnp.int32),
        sent=jnp.zeros((n, n), bool),
        msgs=jnp.zeros((n,), jnp.int32),
        tick=jnp.zeros((), jnp.int32),
    )


@partial(jax.jit, static_argnames=("cfg",))
def exact_tick(state: ExactState, key, cfg: ExactConfig) -> ExactState:
    n, k = cfg.n_nodes, cfg.fanout
    c = min(cfg.sender_chunk, n)
    infected, tx, next_send, sent, msgs, tick = state
    active = infected & (tx > 0) & (next_send <= tick)

    new_infected = infected
    new_sent = sent
    sent_counts = jnp.zeros((n,), jnp.int32)
    idx = jnp.arange(n, dtype=jnp.int32)
    for start in range(0, n, c):
        ci = min(c, n - start)  # final chunk may be short
        rows = idx[start:start + ci]  # static slice
        scores = jax.random.uniform(
            jax.random.fold_in(key, start), (ci, n)
        )
        excluded = sent[start:start + ci] | (rows[:, None] == idx[None, :])
        scores = jnp.where(excluded, jnp.inf, scores)
        neg_top, targets = jax.lax.top_k(-scores, k)  # [Ci, k]
        avail = neg_top > -jnp.inf
        ok = avail & active[start:start + ci, None]
        masked = jnp.where(ok, targets, n)  # dead -> dropped
        new_infected = new_infected.at[masked.reshape(-1)].set(
            True, mode="drop"
        )
        chunk_rows = jnp.repeat(rows, k)
        new_sent = new_sent.at[chunk_rows, masked.reshape(-1)].set(
            True, mode="drop"
        )
        sent_counts = sent_counts.at[start:start + ci].set(
            ok.sum(axis=1).astype(jnp.int32)
        )

    msgs = msgs + sent_counts
    # budget/backoff — the det-sim/agent semantics: a send decrements,
    # exhausted coverage retires, learners get a fresh budget and first
    # forward next tick
    sent_now = active & (sent_counts > 0)
    exhausted = active & (sent_counts == 0)
    tx = jnp.where(sent_now, tx - 1, tx)
    tx = jnp.where(exhausted, 0, tx)
    send_count = cfg.max_transmissions - tx
    gap = jnp.maximum(
        1, jnp.round(cfg.backoff_ticks * send_count).astype(jnp.int32)
    )
    next_send = jnp.where(sent_now, tick + gap, next_send)
    learned = new_infected & ~infected
    tx = jnp.where(learned, cfg.max_transmissions, tx)
    next_send = jnp.where(learned, tick + 1, next_send)
    return ExactState(new_infected, tx, next_send, new_sent, msgs, tick + 1)


def run_exact(cfg: ExactConfig, seed: int = 0) -> Dict:
    """One exact-sampler epidemic; msgs/node measured at convergence."""
    state = exact_init(cfg)
    key = jax.random.PRNGKey(seed)
    t0 = time.perf_counter()
    converged_tick: Optional[int] = None
    for t in range(cfg.max_ticks):
        state = exact_tick(state, jax.random.fold_in(key, t), cfg)
        # cheap host check: one bool + one int
        if converged_tick is None and bool(state.infected.all()):
            converged_tick = t + 1
            break
    msgs = np.asarray(state.msgs)
    return {
        "n_nodes": cfg.n_nodes,
        "converged_tick": converged_tick,
        "msgs_per_node_mean": float(msgs.mean()),
        "wall_s": time.perf_counter() - t0,
    }


def run_msgs_calibration(
    ns: List[int] = (1000, 4000, 16000),
    seeds: int = 3,
    fanout: int = 4,
    max_transmissions: int = 8,
    out_path: Optional[str] = None,
) -> Dict:
    """Exact vs perm-fanout msgs/node under matched conditions (uniform
    sampling, no loss, no sync, no partitions) — the measured correction
    factor for the sweep's perm-fanout lower bound."""
    import json

    from corrosion_tpu.sim.epidemic import EpidemicConfig, run_epidemic_seeds

    points = []
    for n in ns:
        ecfg = ExactConfig(
            n_nodes=n, fanout=fanout, max_transmissions=max_transmissions
        )
        exact_msgs = []
        conv = []
        for s in range(seeds):
            r = run_exact(ecfg, seed=s)
            exact_msgs.append(r["msgs_per_node_mean"])
            conv.append(r["converged_tick"])
        pcfg = EpidemicConfig(
            n_nodes=n, n_rows=4,
            fanout_ring0=0, fanout_global=fanout, ring0_size=1,
            max_transmissions=max_transmissions, loss=0.0,
            sync_interval=0, track_hops=False,
            max_ticks=ecfg.max_ticks, chunk_ticks=8,
        )
        run_epidemic_seeds(pcfg, n_seeds=seeds, seed=1)  # warm compile
        perm = run_epidemic_seeds(pcfg, n_seeds=seeds, seed=0)
        exact_mean = float(np.mean(exact_msgs))
        points.append({
            "n": n,
            "msgs_exact": round(exact_mean, 2),
            "msgs_perm": round(perm["msgs_per_node_mean"], 2),
            "exact_over_perm": round(
                exact_mean / max(perm["msgs_per_node_mean"], 1e-9), 3
            ),
            "exact_converged_ticks": conv,
            "perm_ticks_p50": perm["ticks_p50"],
            "seeds": seeds,
        })
    out = {
        "metric": "exact_vs_perm_msgs_calibration",
        "fanout": fanout,
        "max_transmissions": max_transmissions,
        "conditions": "uniform sampling, no loss/sync/partition",
        "points": points,
    }
    if out_path:
        with open(out_path, "w") as f:
            json.dump(out, f, indent=1)
    return out


def ratio_for(calib: Dict, n: int) -> Optional[float]:
    """exact/perm correction factor at the calibrated N nearest to n."""
    pts = calib.get("points") or []
    if not pts:
        return None
    best = min(pts, key=lambda p: abs(p["n"] - n))
    return best["exact_over_perm"]
