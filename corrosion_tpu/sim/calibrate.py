"""Exact-sampler msgs/node calibration at large N.

The production epidemic kernel delivers via permutation fanout
(``models/broadcast.py``): collision-free in-degree makes its
msgs-at-convergence a known ~0.65-0.75× lower bound of the exact
``sent_to``-excluding sampler the agents run.  The exact sampler's old
home (``broadcast_step(sent=...)``) holds [N, N] *scores* per tick and
vmaps seeds, capping calibration at N≈512.  This module runs the exact
protocol at N=1k-16k:

* one seed at a time (no vmapped [S, N, N] state);
* ``sent`` as one [N, N] bool (256 MB at 16k — fits HBM);
* per-tick scores generated in sender CHUNKS of [C, N] with
  ``lax.top_k`` selection, so the 1 GB full scores matrix never
  materializes;
* single-payload state ([N] infected/budget/backoff), the same
  semantics the deterministic bit-match pins against the live agents
  (``sim/bitmatch.py``): retire on exhausted coverage, rebroadcast with
  fresh budget on learn, nth retransmission after
  ``max(1, round(backoff * n))`` ticks.

``run_msgs_calibration`` measures msgs/node at convergence for the
exact sampler vs the matched perm-fanout config and emits the ratio per
N — the correction factor ``bench.py`` applies to annotate its sweep
(``CALIB_MSGS.json``).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from functools import partial
from typing import Dict, List, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np


@dataclass(frozen=True)
class ExactConfig:
    n_nodes: int
    fanout: int = 4
    max_transmissions: int = 8
    backoff_ticks: float = 0.0
    max_ticks: int = 192
    sender_chunk: int = 2048


class ExactState(NamedTuple):
    infected: jnp.ndarray  # [N] bool
    tx: jnp.ndarray  # [N] int32 remaining transmissions
    next_send: jnp.ndarray  # [N] int32
    sent: jnp.ndarray  # [N, N] bool per-payload sent_to
    msgs: jnp.ndarray  # [N] int32
    tick: jnp.ndarray  # scalar int32


def exact_init(cfg: ExactConfig, writer: int = 0) -> ExactState:
    n = cfg.n_nodes
    return ExactState(
        infected=jnp.zeros((n,), bool).at[writer].set(True),
        tx=jnp.zeros((n,), jnp.int32).at[writer].set(cfg.max_transmissions),
        next_send=jnp.zeros((n,), jnp.int32),
        sent=jnp.zeros((n, n), bool),
        msgs=jnp.zeros((n,), jnp.int32),
        tick=jnp.zeros((), jnp.int32),
    )


@partial(jax.jit, static_argnames=("cfg",))
def exact_tick(state: ExactState, key, cfg: ExactConfig) -> ExactState:
    n, k = cfg.n_nodes, cfg.fanout
    c = min(cfg.sender_chunk, n)
    infected, tx, next_send, sent, msgs, tick = state
    active = infected & (tx > 0) & (next_send <= tick)

    new_infected = infected
    new_sent = sent
    sent_counts = jnp.zeros((n,), jnp.int32)
    idx = jnp.arange(n, dtype=jnp.int32)
    for start in range(0, n, c):
        ci = min(c, n - start)  # final chunk may be short
        rows = idx[start:start + ci]  # static slice
        scores = jax.random.uniform(
            jax.random.fold_in(key, start), (ci, n)
        )
        excluded = sent[start:start + ci] | (rows[:, None] == idx[None, :])
        scores = jnp.where(excluded, jnp.inf, scores)
        neg_top, targets = jax.lax.top_k(-scores, k)  # [Ci, k]
        avail = neg_top > -jnp.inf
        ok = avail & active[start:start + ci, None]
        masked = jnp.where(ok, targets, n)  # dead -> dropped
        new_infected = new_infected.at[masked.reshape(-1)].set(
            True, mode="drop"
        )
        chunk_rows = jnp.repeat(rows, k)
        new_sent = new_sent.at[chunk_rows, masked.reshape(-1)].set(
            True, mode="drop"
        )
        sent_counts = sent_counts.at[start:start + ci].set(
            ok.sum(axis=1).astype(jnp.int32)
        )

    msgs = msgs + sent_counts
    # budget/backoff — the det-sim/agent semantics: a send decrements,
    # exhausted coverage retires, learners get a fresh budget and first
    # forward next tick
    sent_now = active & (sent_counts > 0)
    exhausted = active & (sent_counts == 0)
    tx = jnp.where(sent_now, tx - 1, tx)
    tx = jnp.where(exhausted, 0, tx)
    send_count = cfg.max_transmissions - tx
    gap = jnp.maximum(
        1, jnp.round(cfg.backoff_ticks * send_count).astype(jnp.int32)
    )
    next_send = jnp.where(sent_now, tick + gap, next_send)
    learned = new_infected & ~infected
    tx = jnp.where(learned, cfg.max_transmissions, tx)
    next_send = jnp.where(learned, tick + 1, next_send)
    return ExactState(new_infected, tx, next_send, new_sent, msgs, tick + 1)


def run_exact(cfg: ExactConfig, seed: int = 0) -> Dict:
    """One exact-sampler epidemic; msgs/node measured at convergence."""
    state = exact_init(cfg)
    key = jax.random.PRNGKey(seed)
    t0 = time.perf_counter()
    converged_tick: Optional[int] = None
    for t in range(cfg.max_ticks):
        state = exact_tick(state, jax.random.fold_in(key, t), cfg)
        # cheap host check: one bool + one int
        if converged_tick is None and bool(state.infected.all()):
            converged_tick = t + 1
            break
    msgs = np.asarray(state.msgs)
    return {
        "n_nodes": cfg.n_nodes,
        "converged_tick": converged_tick,
        "msgs_per_node_mean": float(msgs.mean()),
        "wall_s": time.perf_counter() - t0,
    }


def run_msgs_calibration(
    ns: List[int] = (1000, 4000, 16000),
    seeds: int = 3,
    fanout: int = 4,
    max_transmissions: int = 8,
    out_path: Optional[str] = None,
) -> Dict:
    """Exact vs perm-fanout msgs/node under matched conditions (uniform
    sampling, no loss, no sync, no partitions) — the measured correction
    factor for the sweep's perm-fanout lower bound."""
    import json

    from corrosion_tpu.sim.epidemic import EpidemicConfig, run_epidemic_seeds

    points = []
    for n in ns:
        ecfg = ExactConfig(
            n_nodes=n, fanout=fanout, max_transmissions=max_transmissions
        )
        exact_msgs = []
        conv = []
        for s in range(seeds):
            r = run_exact(ecfg, seed=s)
            exact_msgs.append(r["msgs_per_node_mean"])
            conv.append(r["converged_tick"])
        pcfg = EpidemicConfig(
            n_nodes=n, n_rows=4,
            fanout_ring0=0, fanout_global=fanout, ring0_size=1,
            max_transmissions=max_transmissions, loss=0.0,
            sync_interval=0, track_hops=False,
            max_ticks=ecfg.max_ticks, chunk_ticks=8,
        )
        run_epidemic_seeds(pcfg, n_seeds=seeds, seed=1)  # warm compile
        perm = run_epidemic_seeds(pcfg, n_seeds=seeds, seed=0)
        exact_mean = float(np.mean(exact_msgs))
        points.append({
            "n": n,
            "msgs_exact": round(exact_mean, 2),
            "msgs_perm": round(perm["msgs_per_node_mean"], 2),
            "exact_over_perm": round(
                exact_mean / max(perm["msgs_per_node_mean"], 1e-9), 3
            ),
            "exact_converged_ticks": conv,
            "perm_ticks_p50": perm["ticks_p50"],
            "seeds": seeds,
        })
    out = {
        "metric": "exact_vs_perm_msgs_calibration",
        "fanout": fanout,
        "max_transmissions": max_transmissions,
        "conditions": "uniform sampling, no loss/sync/partition",
        "points": points,
    }
    if out_path:
        with open(out_path, "w") as f:
            json.dump(out, f, indent=1)
    return out


def ratio_for(calib: Dict, n: int) -> Optional[float]:
    """exact/perm correction factor at the calibrated N nearest to n."""
    pts = calib.get("points") or []
    if not pts:
        return None
    best = min(pts, key=lambda p: abs(p["n"] - n))
    return best["exact_over_perm"]


# ---------------------------------------------------------------------------
# Bitpacked exact sampler at headline scale (N = 64k-100k)
# ---------------------------------------------------------------------------
#
# The scores-based kernel above draws an [C, N] uniform matrix per sender
# chunk — O(N^2) PRNG draws per tick, ~10^10 at N=100k: unusable.  The
# headline protocol caps every sender's per-payload ``sent_to`` at
# ``max_transmissions * fanout`` (+ the origin's ring0 block) entries,
# a vanishing fraction of N, so exact uniform WITHOUT-replacement
# sampling is cheap by FULL-TUPLE REJECTION: draw k iid uniforms per
# sender, accept only if all k are distinct, not self, and not in
# ``sent_to``; redraw whole tuples until every active sender accepts
# (a lax.while_loop; acceptance is ~1 - k*excl/N ≈ 99.9% at 100k, so
# it settles in 1-2 rounds).  Conditioning iid tuples on validity makes
# accepted tuples exactly uniform over ordered distinct allowed
# k-tuples — the distribution of the agents' ``Members.sample`` /
# ``random.sample`` (uniformity exact up to jax.random.randint's
# ~2^-32 modulo bias on non-power-of-2 N).
#
# ``sent_to`` is BITPACKED: [N, ceil(N/8)] uint8 — 1.25 GB at 100k,
# well inside one chip's HBM.  Membership tests are gathers of one byte
# per candidate; marking is a scatter-add of the bit value (each bit is
# set at most once per payload — a previously-sent target is never
# re-drawn — so add == or).
#
# The rest of the tick is the HEADLINE protocol of ``sim/epidemic.py``
# reduced to single-payload state (one writer, so [N]-bool infection is
# equivalent to the [N, R] row state): per-message loss, partition
# blocks until heal_tick, periodic anti-entropy pulls with the same
# session message accounting, retransmit budget with backoff, and the
# agents' ring0 semantics (the origin's FIRST transmission reaches its
# whole <6ms tier; reference ``broadcast/mod.rs:586-702``) seeded at
# init.  This is the measurement VERDICT r4 asked for: the exact
# sampler's msgs/node AT 100k, not a ratio extrapolated from 16k.


@dataclass(frozen=True)
class HeadlineExactConfig:
    n_nodes: int
    fanout: int = 4
    ring0_size: int = 256  # origin first-transmission tier (0 = off)
    max_transmissions: int = 8
    backoff_ticks: float = 0.0
    loss: float = 0.0
    partition_blocks: int = 1
    heal_tick: int = 0
    sync_interval: int = 0
    sync_peers: int = 1
    handshake_msgs: int = 2  # sync session accounting (models/sync.py)
    max_ticks: int = 192
    chunk_ticks: int = 16

    def __post_init__(self):
        # rejection sampling needs the excluded set to stay far below N
        # (it also guarantees coverage never exhausts, so the retire
        # path of the small-N kernels cannot trigger)
        # worst case: the origin (budget*k sends + its ring0 tier); at
        # 2x headroom the full-tuple acceptance is still >=25%/round
        excl = self.max_transmissions * self.fanout + self.ring0_size + 1
        if self.n_nodes < 2 * excl:
            raise ValueError(
                f"n_nodes={self.n_nodes} too small for rejection "
                f"sampling (excluded set can reach {excl}); use the "
                "scores-based ExactConfig kernel below N≈1k"
            )


class PackedExactState(NamedTuple):
    infected: jnp.ndarray  # [N] bool
    tx: jnp.ndarray  # [N] int32 remaining transmissions
    next_send: jnp.ndarray  # [N] int32
    sent: jnp.ndarray  # [N, ceil(N/8)] uint8 bitpacked sent_to
    msgs: jnp.ndarray  # [N] int32 (broadcast + sync session msgs)
    tick: jnp.ndarray  # scalar int32


def packed_exact_init(
    cfg: HeadlineExactConfig, key, writer: int = 0
) -> PackedExactState:
    n = cfg.n_nodes
    nb = -(-n // 8)
    infected = jnp.zeros((n,), bool).at[writer].set(True)
    tx = jnp.zeros((n,), jnp.int32).at[writer].set(cfg.max_transmissions)
    next_send = jnp.zeros((n,), jnp.int32)
    sent = jnp.zeros((n, nb), jnp.uint8)
    msgs = jnp.zeros((n,), jnp.int32)
    if cfg.ring0_size > 1:
        # the origin's first flush goes to its ENTIRE ring0 tier plus k
        # global picks (agents: Members.sample ring0_first).  Seed the
        # tier here: mark sent_to, charge msgs, deliver per-peer under
        # loss; tick 0's normal send then draws the k global picks
        # (ring0 excluded via sent_to) and consumes the budget once —
        # together they are exactly the det-mode first transmission.
        idx = jnp.arange(n, dtype=jnp.int32)
        block = jnp.minimum(cfg.ring0_size, n)
        in_tier = (idx // block == writer // block) & (idx != writer)
        delivered = in_tier
        if cfg.loss > 0.0:
            keep = jax.random.uniform(key, (n,)) >= cfg.loss
            delivered = in_tier & keep
        infected = infected | delivered
        tx = jnp.where(delivered, cfg.max_transmissions, tx)
        next_send = jnp.where(delivered, 1, next_send)
        # writer's sent bits for the whole tier (marked on send)
        byte = idx // 8
        bit = (jnp.uint8(1) << (idx % 8).astype(jnp.uint8))
        row = jnp.zeros((nb,), jnp.uint8).at[
            jnp.where(in_tier, byte, nb)
        ].add(jnp.where(in_tier, bit, jnp.uint8(0)), mode="drop")
        sent = sent.at[writer].set(row)
        msgs = msgs.at[writer].add(in_tier.sum().astype(jnp.int32))
    return PackedExactState(
        infected, tx, next_send, sent, msgs, jnp.zeros((), jnp.int32)
    )


def _partition_of(cfg: HeadlineExactConfig):
    if cfg.partition_blocks <= 1:
        return None
    idx = jnp.arange(cfg.n_nodes, dtype=jnp.int32)
    return idx * cfg.partition_blocks // cfg.n_nodes


def _sent_bit(sent, rows, targets):
    """Broadcasted bool: is ``targets``'s bit set in ``rows``' packed
    sent_to rows?"""
    byte = sent[rows, targets // 8]
    return ((byte >> (targets % 8).astype(jnp.uint8)) & 1).astype(bool)


def packed_exact_tick(
    state: PackedExactState, key, cfg: HeadlineExactConfig
) -> PackedExactState:
    n, k = cfg.n_nodes, cfg.fanout
    nb = state.sent.shape[1]
    infected, tx, next_send, sent, msgs, tick = state
    idx = jnp.arange(n, dtype=jnp.int32)
    active = infected & (tx > 0) & (next_send <= tick)
    part = _partition_of(cfg)
    part_active = tick < cfg.heal_tick

    k_draw, k_loss, k_sync = jax.random.split(key, 3)

    def invalid_rows(cand):
        """[N] bool: row's k-tuple has a self/sent/duplicate hit."""
        self_hit = cand == idx[:, None]
        sent_hit = _sent_bit(sent, idx[:, None], cand)
        dup = jnp.zeros((n,), bool)
        for a in range(k):
            for b in range(a + 1, k):
                dup |= cand[:, a] == cand[:, b]
        return jnp.any(self_hit | sent_hit, axis=1) | dup

    cand = jax.random.randint(jax.random.fold_in(k_draw, 0), (n, k), 0, n)
    bad = invalid_rows(cand) & active

    def cond(carry):
        _, bad, _ = carry
        return jnp.any(bad)

    def body(carry):
        cand, bad, r = carry
        fresh = jax.random.randint(
            jax.random.fold_in(k_draw, r), (n, k), 0, n
        )
        cand = jnp.where(bad[:, None], fresh, cand)
        return cand, invalid_rows(cand) & bad, r + 1

    cand, _, _ = jax.lax.while_loop(
        cond, body, (cand, bad, jnp.int32(1))
    )

    delivered = jnp.broadcast_to(active[:, None], (n, k))
    if cfg.loss > 0.0:
        delivered &= jax.random.uniform(k_loss, (n, k)) >= cfg.loss
    if part is not None:
        delivered &= ~((part[:, None] != part[cand]) & part_active)

    new_infected = infected.at[
        jnp.where(delivered, cand, n).reshape(-1)
    ].set(True, mode="drop")

    # mark on send (loss/partition invisible to the sender): one bit per
    # (sender, target); each target is fresh, so add == or
    mark_cols = jnp.where(active[:, None], cand // 8, nb).reshape(-1)
    mark_rows = jnp.repeat(idx, k)
    mark_bits = (jnp.uint8(1) << (cand % 8).astype(jnp.uint8)).reshape(-1)
    new_sent = sent.at[mark_rows, mark_cols].add(mark_bits, mode="drop")
    msgs = msgs + jnp.where(active, k, 0)

    # budget/backoff — det/agent semantics (coverage never exhausts at
    # rejection scale, so the retire path does not exist here)
    tx = jnp.where(active, tx - 1, tx)
    send_count = cfg.max_transmissions - tx
    gap = jnp.maximum(
        1, jnp.round(cfg.backoff_ticks * send_count).astype(jnp.int32)
    )
    next_send = jnp.where(active, tick + gap, next_send)
    learned = new_infected & ~infected
    tx = jnp.where(learned, cfg.max_transmissions, tx)
    next_send = jnp.where(learned, tick + 1, next_send)

    # anti-entropy pull on the kernel cadence (models/sync.py sync_step
    # reduced to single-payload: a reachable infected peer heals the
    # client; session accounting = handshake split + one chunk per
    # serving session)
    if cfg.sync_interval > 0:
        def do_sync(args):
            infected, msgs = args
            p = cfg.sync_peers
            peers = jax.random.randint(k_sync, (n, p), 0, n)
            reachable = jnp.ones((n, p), bool)
            if part is not None:
                reachable &= ~((part[:, None] != part[peers]) & part_active)
            ahead = infected[peers] & ~infected[:, None] & reachable
            healed = jnp.any(ahead, axis=1)
            client_pay = (
                jnp.sum(reachable, axis=1) * (cfg.handshake_msgs // 2)
            ).astype(jnp.int32)
            per_server = (
                (cfg.handshake_msgs - cfg.handshake_msgs // 2)
                * reachable + ahead
            ).astype(jnp.int32)
            server_pay = (
                jnp.zeros((n,), jnp.int32)
                .at[peers.reshape(-1)]
                .add(per_server.reshape(-1))
            )
            return infected | healed, msgs + client_pay + server_pay

        new_infected, msgs = jax.lax.cond(
            tick % cfg.sync_interval == cfg.sync_interval - 1,
            do_sync,
            lambda args: args,
            (new_infected, msgs),
        )

    return PackedExactState(
        new_infected, tx, next_send, new_sent, msgs, tick + 1
    )


@partial(jax.jit, static_argnames=("cfg",))
def _packed_scan_chunk(state: PackedExactState, seed_key,
                       cfg: HeadlineExactConfig):
    """cfg.chunk_ticks rounds per dispatch; per-tick (converged,
    msgs_mean, msgs_p99) so each seed's stats are read at its OWN
    convergence tick."""

    def body(st, _):
        nxt = packed_exact_tick(
            st, jax.random.fold_in(seed_key, st.tick), cfg
        )
        msgs_f = nxt.msgs.astype(jnp.float32)
        return nxt, (
            jnp.all(nxt.infected),
            jnp.mean(msgs_f),
            jnp.percentile(msgs_f, 99),
        )

    return jax.lax.scan(body, state, xs=None, length=cfg.chunk_ticks)


def run_exact_headline(
    cfg: HeadlineExactConfig, n_seeds: int = 4, seed: int = 0
) -> Dict:
    """Sequential-seed exact-sampler epidemics at headline scale.

    Returns the same stat keys as ``run_epidemic_seeds`` (msgs/ticks at
    each seed's own convergence tick) with ``delivery_model: exact``.
    Seeds run sequentially — the [N, N/8] ``sent_to`` bitmap is per-run
    state and seed-flattening would multiply it by S.
    """
    t0 = time.perf_counter()
    firsts: List[float] = []
    means: List[float] = []
    p99s: List[float] = []
    converged = 0
    for s in range(n_seeds):
        key = jax.random.PRNGKey(seed * 10_007 + s)
        state = packed_exact_init(cfg, jax.random.fold_in(key, 2**20))
        flags: List[np.ndarray] = []
        mm: List[np.ndarray] = []
        mp: List[np.ndarray] = []
        ticks_done = 0
        while ticks_done < cfg.max_ticks:
            state, (conv, m_mean, m_p99) = _packed_scan_chunk(
                state, key, cfg
            )
            flags.append(np.asarray(conv))
            mm.append(np.asarray(m_mean))
            mp.append(np.asarray(m_p99))
            ticks_done += cfg.chunk_ticks
            if flags[-1][-1]:
                break
        allflags = np.concatenate(flags)
        allmm = np.concatenate(mm)
        allmp = np.concatenate(mp)
        if allflags.any():
            fi = int(allflags.argmax())
            converged += 1
            firsts.append(fi + 1)
        else:
            fi = len(allflags) - 1
            firsts.append(float("inf"))
        means.append(float(allmm[fi]))
        p99s.append(float(allmp[fi]))
    return {
        "n_nodes": cfg.n_nodes,
        "n_seeds": n_seeds,
        "delivery_model": "exact",
        "converged_frac": converged / n_seeds,
        "ticks_p50": float(np.percentile(firsts, 50)),
        "ticks_p99": float(np.percentile(firsts, 99)),
        "msgs_per_node_mean": float(np.mean(means)),
        "msgs_per_node_p99": float(np.mean(p99s)),
        "wall_s": time.perf_counter() - t0,
    }
