"""Exact-sampler msgs/node calibration at large N.

The production epidemic kernel delivers via permutation fanout
(``models/broadcast.py``): collision-free in-degree makes its
msgs-at-convergence a known ~0.65-0.75× lower bound of the exact
``sent_to``-excluding sampler the agents run.  The exact sampler's old
home (``broadcast_step(sent=...)``) holds [N, N] *scores* per tick and
vmaps seeds, capping calibration at N≈512.  This module runs the exact
protocol at N=1k-16k:

* one seed at a time (no vmapped [S, N, N] state);
* ``sent`` as one [N, N] bool (256 MB at 16k — fits HBM);
* per-tick scores generated in sender CHUNKS of [C, N] with
  ``lax.top_k`` selection, so the 1 GB full scores matrix never
  materializes;
* single-payload state ([N] infected/budget/backoff), the same
  semantics the deterministic bit-match pins against the live agents
  (``sim/bitmatch.py``): retire on exhausted coverage, rebroadcast with
  fresh budget on learn, nth retransmission after
  ``max(1, round(backoff * n))`` ticks.

``run_msgs_calibration`` measures msgs/node at convergence for the
exact sampler vs the matched perm-fanout config and emits the ratio per
N — the correction factor ``bench.py`` applies to annotate its sweep
(``CALIB_MSGS.json``).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from functools import lru_cache, partial
from typing import Dict, List, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np


@dataclass(frozen=True)
class ExactConfig:
    n_nodes: int
    fanout: int = 4
    max_transmissions: int = 8
    backoff_ticks: float = 0.0
    max_ticks: int = 192
    sender_chunk: int = 2048


class ExactState(NamedTuple):
    infected: jnp.ndarray  # [N] bool
    tx: jnp.ndarray  # [N] int32 remaining transmissions
    next_send: jnp.ndarray  # [N] int32
    sent: jnp.ndarray  # [N, N] bool per-payload sent_to
    msgs: jnp.ndarray  # [N] int32
    tick: jnp.ndarray  # scalar int32


def exact_init(cfg: ExactConfig, writer: int = 0) -> ExactState:
    n = cfg.n_nodes
    return ExactState(
        infected=jnp.zeros((n,), bool).at[writer].set(True),
        tx=jnp.zeros((n,), jnp.int32).at[writer].set(cfg.max_transmissions),
        next_send=jnp.zeros((n,), jnp.int32),
        sent=jnp.zeros((n, n), bool),
        msgs=jnp.zeros((n,), jnp.int32),
        tick=jnp.zeros((), jnp.int32),
    )


@partial(jax.jit, static_argnames=("cfg",))
def exact_tick(state: ExactState, key, cfg: ExactConfig) -> ExactState:
    n, k = cfg.n_nodes, cfg.fanout
    c = min(cfg.sender_chunk, n)
    infected, tx, next_send, sent, msgs, tick = state
    active = infected & (tx > 0) & (next_send <= tick)

    new_infected = infected
    new_sent = sent
    sent_counts = jnp.zeros((n,), jnp.int32)
    idx = jnp.arange(n, dtype=jnp.int32)
    for start in range(0, n, c):
        ci = min(c, n - start)  # final chunk may be short
        rows = idx[start:start + ci]  # static slice
        scores = jax.random.uniform(
            jax.random.fold_in(key, start), (ci, n)
        )
        excluded = sent[start:start + ci] | (rows[:, None] == idx[None, :])
        scores = jnp.where(excluded, jnp.inf, scores)
        neg_top, targets = jax.lax.top_k(-scores, k)  # [Ci, k]
        avail = neg_top > -jnp.inf
        ok = avail & active[start:start + ci, None]
        masked = jnp.where(ok, targets, n)  # dead -> dropped
        new_infected = new_infected.at[masked.reshape(-1)].set(
            True, mode="drop"
        )
        chunk_rows = jnp.repeat(rows, k)
        new_sent = new_sent.at[chunk_rows, masked.reshape(-1)].set(
            True, mode="drop"
        )
        sent_counts = sent_counts.at[start:start + ci].set(
            ok.sum(axis=1).astype(jnp.int32)
        )

    msgs = msgs + sent_counts
    # budget/backoff — the det-sim/agent semantics: a send decrements,
    # exhausted coverage retires, learners get a fresh budget and first
    # forward next tick
    sent_now = active & (sent_counts > 0)
    exhausted = active & (sent_counts == 0)
    tx = jnp.where(sent_now, tx - 1, tx)
    tx = jnp.where(exhausted, 0, tx)
    send_count = cfg.max_transmissions - tx
    gap = jnp.maximum(
        1, jnp.round(cfg.backoff_ticks * send_count).astype(jnp.int32)
    )
    next_send = jnp.where(sent_now, tick + gap, next_send)
    learned = new_infected & ~infected
    tx = jnp.where(learned, cfg.max_transmissions, tx)
    next_send = jnp.where(learned, tick + 1, next_send)
    return ExactState(new_infected, tx, next_send, new_sent, msgs, tick + 1)


def run_exact(cfg: ExactConfig, seed: int = 0) -> Dict:
    """One exact-sampler epidemic; msgs/node measured at convergence."""
    state = exact_init(cfg)
    key = jax.random.PRNGKey(seed)
    t0 = time.perf_counter()
    converged_tick: Optional[int] = None
    for t in range(cfg.max_ticks):
        state = exact_tick(state, jax.random.fold_in(key, t), cfg)
        # cheap host check: one bool + one int
        if converged_tick is None and bool(state.infected.all()):
            converged_tick = t + 1
            break
    msgs = np.asarray(state.msgs)
    return {
        "n_nodes": cfg.n_nodes,
        "converged_tick": converged_tick,
        "msgs_per_node_mean": float(msgs.mean()),
        "wall_s": time.perf_counter() - t0,
    }


def run_msgs_calibration(
    ns: List[int] = (1000, 4000, 16000),
    seeds: int = 3,
    fanout: int = 4,
    max_transmissions: int = 8,
    out_path: Optional[str] = None,
) -> Dict:
    """Exact vs perm-fanout msgs/node under matched conditions (uniform
    sampling, no loss, no sync, no partitions) — the measured correction
    factor for the sweep's perm-fanout lower bound."""
    import json

    from corrosion_tpu.sim.epidemic import EpidemicConfig, run_epidemic_seeds

    points = []
    for n in ns:
        ecfg = ExactConfig(
            n_nodes=n, fanout=fanout, max_transmissions=max_transmissions
        )
        exact_msgs = []
        conv = []
        for s in range(seeds):
            r = run_exact(ecfg, seed=s)
            exact_msgs.append(r["msgs_per_node_mean"])
            conv.append(r["converged_tick"])
        pcfg = EpidemicConfig(
            n_nodes=n, n_rows=4,
            fanout_ring0=0, fanout_global=fanout, ring0_size=1,
            max_transmissions=max_transmissions, loss=0.0,
            sync_interval=0, track_hops=False,
            max_ticks=ecfg.max_ticks, chunk_ticks=8,
        )
        run_epidemic_seeds(pcfg, n_seeds=seeds, seed=1)  # warm compile
        perm = run_epidemic_seeds(pcfg, n_seeds=seeds, seed=0)
        exact_mean = float(np.mean(exact_msgs))
        points.append({
            "n": n,
            "msgs_exact": round(exact_mean, 2),
            "msgs_perm": round(perm["msgs_per_node_mean"], 2),
            "exact_over_perm": round(
                exact_mean / max(perm["msgs_per_node_mean"], 1e-9), 3
            ),
            "exact_converged_ticks": conv,
            "perm_ticks_p50": perm["ticks_p50"],
            "seeds": seeds,
        })
    out = {
        "metric": "exact_vs_perm_msgs_calibration",
        "fanout": fanout,
        "max_transmissions": max_transmissions,
        "conditions": "uniform sampling, no loss/sync/partition",
        "points": points,
    }
    if out_path:
        with open(out_path, "w") as f:
            json.dump(out, f, indent=1)
    return out


def ratio_for(calib: Dict, n: int) -> Optional[float]:
    """exact/perm correction factor at the calibrated N nearest to n."""
    pts = calib.get("points") or []
    if not pts:
        return None
    best = min(pts, key=lambda p: abs(p["n"] - n))
    return best["exact_over_perm"]


# ---------------------------------------------------------------------------
# Bitpacked exact sampler at headline scale (N = 64k-100k)
# ---------------------------------------------------------------------------
#
# The scores-based kernel above draws an [C, N] uniform matrix per sender
# chunk — O(N^2) PRNG draws per tick, ~10^10 at N=100k: unusable.  The
# headline protocol caps every sender's per-payload ``sent_to`` at
# ``max_transmissions * fanout`` (+ the origin's ring0 block) entries,
# a vanishing fraction of N, so exact uniform WITHOUT-replacement
# sampling is cheap by FULL-TUPLE REJECTION: draw k iid uniforms per
# sender, accept only if all k are distinct, not self, and not in
# ``sent_to``; redraw whole tuples until every active sender accepts
# (a lax.while_loop; acceptance is ~1 - k*excl/N ≈ 99.9% at 100k, so
# it settles in 1-2 rounds).  Conditioning iid tuples on validity makes
# accepted tuples exactly uniform over ordered distinct allowed
# k-tuples — the distribution of the agents' ``Members.sample`` /
# ``random.sample`` (uniformity exact up to jax.random.randint's
# ~2^-32 modulo bias on non-power-of-2 N).
#
# ``sent_to`` is BITPACKED: [N, ceil(N/8)] uint8 — 1.25 GB at 100k,
# well inside one chip's HBM.  Membership tests are gathers of one byte
# per candidate; marking is a scatter-add of the bit value (each bit is
# set at most once per payload — a previously-sent target is never
# re-drawn — so add == or).
#
# The rest of the tick is the HEADLINE protocol of ``sim/epidemic.py``
# reduced to single-payload state (one writer, so [N]-bool infection is
# equivalent to the [N, R] row state): per-message loss, partition
# blocks until heal_tick, periodic anti-entropy pulls with the same
# session message accounting, retransmit budget with backoff, and the
# agents' ring0 semantics (the origin's FIRST transmission reaches its
# whole <6ms tier; reference ``broadcast/mod.rs:586-702``) seeded at
# init.  This is the measurement VERDICT r4 asked for: the exact
# sampler's msgs/node AT 100k, not a ratio extrapolated from 16k.


@dataclass(frozen=True)
class HeadlineExactConfig:
    n_nodes: int
    fanout: int = 4
    ring0_size: int = 256  # origin first-transmission tier (0 = off)
    max_transmissions: int = 8
    backoff_ticks: float = 0.0
    loss: float = 0.0
    partition_blocks: int = 1
    heal_tick: int = 0
    sync_interval: int = 0
    sync_peers: int = 1
    handshake_msgs: int = 2  # sync session accounting (models/sync.py)
    max_ticks: int = 192
    chunk_ticks: int = 16
    # scenario families beyond uniform fanout (mirrors EpidemicConfig):
    # - ``het_ring``: node i sits on RTT tier 1 + i*rtt_tiers//n of a
    #   ring by id — its retransmit gap (and its first forward after
    #   learning) scales with the tier, so the convergence tail is
    #   driven by the slow arc of the ring;
    # - ``wan_two_region``: node i lives in region i*wan_blocks//n;
    #   gossip sends crossing regions suffer an EXTRA i.i.d. drop of
    #   ``wan_cross_loss`` on top of ``loss`` (long-RTT datagram
    #   timeouts), while anti-entropy sessions cross unharmed (the
    #   reference syncs over QUIC streams with retries).
    # - ``measured_ring``: het_ring with a DATA-DRIVEN tier map — node
    #   tiers follow the node-count weights of a measured ``Members``
    #   RTT-ring distribution (``corro admin rtt dump`` /
    #   ``capture_rtt_topology``) instead of the synthetic linear ramp.
    # ``uniform`` executes exactly the pre-topology code path.
    topology: str = "uniform"
    rtt_tiers: int = 4
    wan_blocks: int = 2
    wan_cross_loss: float = 0.25
    # measured_ring only: per-tier node-count weights (tier t gets
    # weights[t-1]/sum of the id ring).  A tuple so the config stays
    # hashable (static jit arg / lru_cache key).
    rtt_tier_weights: Optional[tuple] = None
    # wan_two_region only: cross-region sends that survive loss are
    # DELAYED this many ticks (tick-quantized WAN latency queue) instead
    # of committing immediately.  0 = immediate delivery, bitwise the
    # pre-latency kernel.
    wan_latency_ticks: int = 0

    def __post_init__(self):
        if self.topology not in (
            "uniform", "het_ring", "wan_two_region", "measured_ring"
        ):
            raise ValueError(f"unknown topology {self.topology!r}")
        if self.topology == "het_ring" and self.rtt_tiers < 1:
            raise ValueError("het_ring needs rtt_tiers >= 1")
        if self.topology == "wan_two_region" and self.wan_blocks < 2:
            raise ValueError("wan_two_region needs wan_blocks >= 2")
        if self.topology == "measured_ring":
            w = self.rtt_tier_weights
            if not w or any(x < 0 for x in w) or sum(w) <= 0:
                raise ValueError(
                    "measured_ring needs rtt_tier_weights: a non-empty "
                    "tuple of non-negative per-tier node weights with a "
                    "positive sum (corro admin rtt dump emits one)"
                )
        if self.wan_latency_ticks < 0:
            raise ValueError("wan_latency_ticks must be >= 0")
        if self.wan_latency_ticks > 0 and self.topology != "wan_two_region":
            raise ValueError(
                "wan_latency_ticks needs the wan_two_region topology "
                "(latency is a property of the cross-region links)"
            )
        # rejection sampling needs the excluded set to stay far below N
        # (it also guarantees coverage never exhausts, so the retire
        # path of the small-N kernels cannot trigger)
        # worst case: the origin (budget*k sends + its ring0 tier); at
        # 2x headroom the full-tuple acceptance is still >=25%/round
        excl = self.max_transmissions * self.fanout + self.ring0_size + 1
        if self.n_nodes < 2 * excl:
            raise ValueError(
                f"n_nodes={self.n_nodes} too small for rejection "
                f"sampling (excluded set can reach {excl}); use the "
                "scores-based ExactConfig kernel below N≈1k"
            )


# int32 sentinel for the WAN latency queue: "no delivery in flight".
# Strictly above any reachable tick, strictly below int32 overflow
# headroom (tick + wan_latency_ticks never wraps).
LATENCY_NONE = (1 << 30) - 1


class PackedExactState(NamedTuple):
    infected: jnp.ndarray  # [N] bool
    tx: jnp.ndarray  # [N] int32 remaining transmissions
    next_send: jnp.ndarray  # [N] int32
    sent: jnp.ndarray  # [N, ceil(N/8)] uint8 bitpacked sent_to
    msgs: jnp.ndarray  # [N] int32 (broadcast + sync session msgs)
    tick: jnp.ndarray  # scalar int32
    # [N] int32 WAN latency queue: earliest tick a queued cross-region
    # delivery for this node lands (LATENCY_NONE = nothing in flight).
    # Appended LAST so the positional leaf order the chunk builders
    # index (tick at [5]) is unchanged.
    pending: jnp.ndarray


def packed_exact_init(
    cfg: HeadlineExactConfig, key, writer: int = 0
) -> PackedExactState:
    n = cfg.n_nodes
    nb = -(-n // 8)
    infected = jnp.zeros((n,), bool).at[writer].set(True)
    tx = jnp.zeros((n,), jnp.int32).at[writer].set(cfg.max_transmissions)
    next_send = jnp.zeros((n,), jnp.int32)
    sent = jnp.zeros((n, nb), jnp.uint8)
    msgs = jnp.zeros((n,), jnp.int32)
    if cfg.ring0_size > 1:
        # the origin's first flush goes to its ENTIRE ring0 tier plus k
        # global picks (agents: Members.sample ring0_first).  Seed the
        # tier here: mark sent_to, charge msgs, deliver per-peer under
        # loss; tick 0's normal send then draws the k global picks
        # (ring0 excluded via sent_to) and consumes the budget once —
        # together they are exactly the det-mode first transmission.
        idx = jnp.arange(n, dtype=jnp.int32)
        block = jnp.minimum(cfg.ring0_size, n)
        in_tier = (idx // block == writer // block) & (idx != writer)
        delivered = in_tier
        if cfg.loss > 0.0:
            keep = jax.random.uniform(key, (n,)) >= cfg.loss
            delivered = in_tier & keep
        infected = infected | delivered
        tx = jnp.where(delivered, cfg.max_transmissions, tx)
        next_send = jnp.where(delivered, 1, next_send)
        # writer's sent bits for the whole tier (marked on send)
        byte = idx // 8
        bit = (jnp.uint8(1) << (idx % 8).astype(jnp.uint8))
        row = jnp.zeros((nb,), jnp.uint8).at[
            jnp.where(in_tier, byte, nb)
        ].add(jnp.where(in_tier, bit, jnp.uint8(0)), mode="drop")
        sent = sent.at[writer].set(row)
        msgs = msgs.at[writer].add(in_tier.sum().astype(jnp.int32))
    return PackedExactState(
        infected, tx, next_send, sent, msgs, jnp.zeros((), jnp.int32),
        jnp.full((n,), LATENCY_NONE, jnp.int32),
    )


def _partition_of(cfg: HeadlineExactConfig):
    if cfg.partition_blocks <= 1:
        return None
    idx = jnp.arange(cfg.n_nodes, dtype=jnp.int32)
    return idx * cfg.partition_blocks // cfg.n_nodes


def _rtt_tier_of(cfg: HeadlineExactConfig):
    """[N] int32 RTT tier of the het_ring (synthetic linear ramp,
    1..rtt_tiers) or measured_ring (data-driven node-count weights)
    topology, or None on other topologies.  Static arithmetic, so under
    jit it constant-folds into the compiled tick."""
    if cfg.topology == "measured_ring":
        from corrosion_tpu.models.broadcast import measured_tier_map

        return measured_tier_map(cfg.n_nodes, cfg.rtt_tier_weights)
    if cfg.topology != "het_ring":
        return None
    idx = jnp.arange(cfg.n_nodes, dtype=jnp.int32)
    return 1 + (idx * cfg.rtt_tiers) // cfg.n_nodes


def _region_of(cfg: HeadlineExactConfig):
    """[N] int32 WAN region of the wan_two_region topology, else None."""
    if cfg.topology != "wan_two_region" or cfg.wan_cross_loss <= 0.0:
        return None
    idx = jnp.arange(cfg.n_nodes, dtype=jnp.int32)
    return (idx * cfg.wan_blocks) // cfg.n_nodes


def _latency_region_of(cfg: HeadlineExactConfig):
    """[N] int32 region map for the WAN LATENCY queue, else None.
    Distinct from ``_region_of`` (the extra cross-region LOSS filter,
    gated on ``wan_cross_loss``) so the latency family runs with
    cross-region loss at zero — and so that at ``wan_latency_ticks=0``
    every queue op compiles out and the kernels are bitwise the
    pre-latency code (tests/test_frontier.py pins it)."""
    if cfg.topology != "wan_two_region" or cfg.wan_latency_ticks <= 0:
        return None
    idx = jnp.arange(cfg.n_nodes, dtype=jnp.int32)
    return (idx * cfg.wan_blocks) // cfg.n_nodes


def _latency_promote(infected, tx, next_send, pending, tick,
                     cfg: HeadlineExactConfig, idx=None):
    """Commit due WAN-queue arrivals at the START of a tick, before the
    active set is computed (shared by every exact kernel).  An arrival
    behaves exactly like a learner: fresh budget, first forward after
    its tier's worth of ticks; an arrival at an already-infected node
    is a duplicate and only clears the queue slot.  ``idx`` slices the
    tier to the caller's rows when its leaves are row-sharded (same
    contract as ``_backoff_next_send``).  Returns ``(infected, tx,
    next_send, pending)``."""
    due = pending <= tick
    arrived = due & ~infected
    tier = _rtt_tier_of(cfg)
    first = 1 if tier is None else (tier if idx is None else tier[idx])
    infected = infected | arrived
    tx = jnp.where(arrived, cfg.max_transmissions, tx)
    next_send = jnp.where(arrived, tick + first, next_send)
    pending = jnp.where(due, LATENCY_NONE, pending)
    return infected, tx, next_send, pending


def _latency_split(delivered, cand, tick, cfg: HeadlineExactConfig):
    """Split a post-loss [..., N, K] delivered mask into immediate
    commits and WAN-queued arrivals.  Returns ``(delivered_now,
    queued)`` where ``queued`` is a [..., N] int32 per-target earliest
    arrival tick (``tick + wan_latency_ticks``; LATENCY_NONE where
    nothing was queued this tick) for the caller to fold in with
    ``jnp.minimum(pending, queued)`` — a scatter-MIN, so no in-flight
    delivery is ever dropped, later duplicates just collapse onto the
    earliest arrival.  ``queued`` is None when the latency family is
    off (the zero-latency identity: no queue op exists to disturb the
    trajectory)."""
    region = _latency_region_of(cfg)
    if region is None:
        return delivered, None
    n = cfg.n_nodes
    src = region.reshape((1,) * (cand.ndim - 2) + (n, 1))
    delayed = delivered & (src != region[cand])
    batch = cand.shape[:-2]
    B = 1
    for d in batch:
        B *= d
    # column n is the dump slot for non-delayed lanes
    tgt = jnp.where(delayed, cand, n).reshape(B, -1)
    arrival = (
        jnp.asarray(tick, jnp.int32).reshape(B, 1)
        + cfg.wan_latency_ticks
    )
    queued = (
        jnp.full((B, n + 1), LATENCY_NONE, jnp.int32)
        .at[jnp.arange(B, dtype=jnp.int32)[:, None], tgt]
        .min(jnp.broadcast_to(arrival, tgt.shape))
    )[:, :n].reshape(batch + (n,))
    return delivered & ~delayed, queued


def _wan_filter(delivered, cand, k_loss, cfg: HeadlineExactConfig):
    """Apply the WAN extra cross-region drop to a [..., N, K] delivered
    mask (shared by the packed oracle, the frontier kernel, and both
    mesh kernels).  ``k_loss`` is one key, or a [S, 2] stack of them
    for the seed-batched shard kernels — the draw vmaps to stay
    replicated-identical to the oracle's per-seed stream.  The extra
    uniform draw only exists on the wan topology, so every other
    config's RNG stream is byte-identical to the pre-topology
    kernel."""
    region = _region_of(cfg)
    if region is None:
        return delivered
    n, k = cfg.n_nodes, cfg.fanout

    def draw(kl):
        return jax.random.uniform(jax.random.fold_in(kl, 1), (n, k))

    wan_drop = (
        jax.vmap(draw)(k_loss) if k_loss.ndim == 2 else draw(k_loss)
    ) < cfg.wan_cross_loss
    src = region.reshape((1,) * (cand.ndim - 2) + (n, 1))
    cross = src != region[cand]
    return delivered & ~(cross & wan_drop)


def _sync_pull(infected, peers, reachable, cfg: HeadlineExactConfig):
    """The anti-entropy pull algebra shared by every exact kernel
    (packed oracle, frontier, and both mesh kernels): ``infected``
    [..., N], ``peers``/``reachable`` [..., N, P] — returns
    ``(healed [..., N], pay [..., N])``, the nodes a reachable
    infected peer heals this round and the per-node session message
    charges (handshake split + one chunk per serving session —
    ``models/sync.py session_msgs`` reduced to single-payload).
    Callers apply them to their own (possibly row-sliced) leaves."""
    shape = infected.shape
    n = shape[-1]
    p = peers.shape[-1]
    B = 1
    for d in shape[:-1]:
        B *= d
    inf_f = infected.reshape(B, n)
    peers_f = peers.reshape(B, n * p)
    reach_f = reachable.reshape(B, n, p)
    inf_peers = jnp.take_along_axis(inf_f, peers_f, axis=1).reshape(
        B, n, p
    )
    ahead = inf_peers & ~inf_f[:, :, None] & reach_f
    healed = jnp.any(ahead, axis=2)
    client_pay = (
        jnp.sum(reach_f, axis=2) * (cfg.handshake_msgs // 2)
    ).astype(jnp.int32)
    per_server = (
        (cfg.handshake_msgs - cfg.handshake_msgs // 2) * reach_f + ahead
    ).astype(jnp.int32)
    b_rows = jnp.arange(B, dtype=jnp.int32)
    server_pay = (
        jnp.zeros((B, n), jnp.int32)
        .at[b_rows[:, None], peers_f]
        .add(per_server.reshape(B, n * p))
    )
    return (
        healed.reshape(shape),
        (client_pay + server_pay).reshape(shape),
    )


def _backoff_next_send(active, learned, tx, next_send, tick,
                       cfg: HeadlineExactConfig, idx=None):
    """Shared budget/backoff arithmetic (post-decrement ``tx``): the nth
    retransmission waits ``max(1, round(backoff*n))`` ticks, scaled by
    the node's RTT tier on the het_ring topology; a fresh learner
    forwards after one tick (its tier's worth on het_ring).  ``idx``
    slices the tier to the caller's rows when its leaves are sharded
    (the dense mesh kernel) rather than full-width/replicated."""
    send_count = cfg.max_transmissions - tx
    gap = jnp.maximum(
        1, jnp.round(cfg.backoff_ticks * send_count).astype(jnp.int32)
    )
    tier = _rtt_tier_of(cfg)
    first = 1
    if tier is not None:
        if idx is not None:
            tier = tier[idx]
        gap = gap * tier
        first = tier
    nxt = jnp.where(active, tick + gap, next_send)
    return jnp.where(learned, tick + first, nxt)


def _sent_bit(sent, rows, targets):
    """Broadcasted bool: is ``targets``'s bit set in ``rows``' packed
    sent_to rows?"""
    byte = sent[rows, targets // 8]
    return ((byte >> (targets % 8).astype(jnp.uint8)) & 1).astype(bool)


def packed_exact_tick(
    state: PackedExactState, key, cfg: HeadlineExactConfig
) -> PackedExactState:
    n, k = cfg.n_nodes, cfg.fanout
    nb = state.sent.shape[1]
    infected, tx, next_send, sent, msgs, tick, pending = state
    idx = jnp.arange(n, dtype=jnp.int32)
    if _latency_region_of(cfg) is not None:
        infected, tx, next_send, pending = _latency_promote(
            infected, tx, next_send, pending, tick, cfg
        )
    active = infected & (tx > 0) & (next_send <= tick)
    part = _partition_of(cfg)
    part_active = tick < cfg.heal_tick

    k_draw, k_loss, k_sync = jax.random.split(key, 3)

    def invalid_rows(cand):
        """[N] bool: row's k-tuple has a self/sent/duplicate hit."""
        self_hit = cand == idx[:, None]
        sent_hit = _sent_bit(sent, idx[:, None], cand)
        dup = jnp.zeros((n,), bool)
        for a in range(k):
            for b in range(a + 1, k):
                dup |= cand[:, a] == cand[:, b]
        return jnp.any(self_hit | sent_hit, axis=1) | dup

    cand = jax.random.randint(jax.random.fold_in(k_draw, 0), (n, k), 0, n)
    bad = invalid_rows(cand) & active

    def cond(carry):
        _, bad, _ = carry
        return jnp.any(bad)

    def body(carry):
        cand, bad, r = carry
        fresh = jax.random.randint(
            jax.random.fold_in(k_draw, r), (n, k), 0, n
        )
        cand = jnp.where(bad[:, None], fresh, cand)
        return cand, invalid_rows(cand) & bad, r + 1

    cand, _, _ = jax.lax.while_loop(
        cond, body, (cand, bad, jnp.int32(1))
    )

    delivered = jnp.broadcast_to(active[:, None], (n, k))
    if cfg.loss > 0.0:
        delivered &= jax.random.uniform(k_loss, (n, k)) >= cfg.loss
    if part is not None:
        delivered &= ~((part[:, None] != part[cand]) & part_active)
    delivered = _wan_filter(delivered, cand, k_loss, cfg)
    delivered, queued = _latency_split(delivered, cand, tick, cfg)
    if queued is not None:
        pending = jnp.minimum(pending, queued)

    new_infected = infected.at[
        jnp.where(delivered, cand, n).reshape(-1)
    ].set(True, mode="drop")

    # mark on send (loss/partition invisible to the sender): one bit per
    # (sender, target); each target is fresh, so add == or
    mark_cols = jnp.where(active[:, None], cand // 8, nb).reshape(-1)
    mark_rows = jnp.repeat(idx, k)
    mark_bits = (jnp.uint8(1) << (cand % 8).astype(jnp.uint8)).reshape(-1)
    new_sent = sent.at[mark_rows, mark_cols].add(mark_bits, mode="drop")
    msgs = msgs + jnp.where(active, k, 0)

    # budget/backoff — det/agent semantics (coverage never exhausts at
    # rejection scale, so the retire path does not exist here)
    tx = jnp.where(active, tx - 1, tx)
    learned = new_infected & ~infected
    next_send = _backoff_next_send(active, learned, tx, next_send, tick,
                                   cfg)
    tx = jnp.where(learned, cfg.max_transmissions, tx)

    # anti-entropy pull on the kernel cadence (models/sync.py sync_step
    # reduced to single-payload: a reachable infected peer heals the
    # client; session accounting = handshake split + one chunk per
    # serving session)
    if cfg.sync_interval > 0:
        def do_sync(args):
            infected, msgs = args
            p = cfg.sync_peers
            peers = jax.random.randint(k_sync, (n, p), 0, n)
            reachable = jnp.ones((n, p), bool)
            if part is not None:
                reachable &= ~((part[:, None] != part[peers]) & part_active)
            healed, pay = _sync_pull(infected, peers, reachable, cfg)
            return infected | healed, msgs + pay

        new_infected, msgs = jax.lax.cond(
            tick % cfg.sync_interval == cfg.sync_interval - 1,
            do_sync,
            lambda args: args,
            (new_infected, msgs),
        )

    return PackedExactState(
        new_infected, tx, next_send, new_sent, msgs, tick + 1, pending
    )


# ---------------------------------------------------------------------------
# Seed-parallel + mesh-native exact sampler
# ---------------------------------------------------------------------------
#
# The kernel above is one seed on one chip; the [N, ceil(N/8)] bitmap
# (1.25 GB at 100k, 8.2 GB at 256k) is the only state that doesn't
# batch or shard for free.  Two independent axes fix that:
#
# * SEEDS: ``packed_exact_tick`` vmaps cleanly (the rejection
#   while_loop batches to "loop while any seed still has an invalid
#   tuple", which freezes finished seeds — per-seed trajectories stay
#   bitwise identical to sequential runs), so S seeds per dispatch cost
#   S bitmaps of HBM and one kernel launch.  ``exact_seed_batch`` picks
#   S from the HBM budget; batches beyond it run pipelined with the
#   scan-chunk state DONATED, so sequential batches reuse the bitmap
#   buffers in place instead of doubling peak HBM.
#
# * NODES: the bitmap row-shards over the mesh's ``nodes`` axis
#   (models/sharded.py fabric idiom) because every use of row i is
#   sender-local: the validity test reads sender i's OWN packed row and
#   bit-marking writes it.  The only values that must cross the fabric
#   are [S, N]-bool masks — per-round candidate VALIDITY bits for the
#   rejection loop and the active/infected masks — each one tiled
#   all_gather (``gather_nodes``); candidate draws are replicated
#   integer PRNG (the sharded broadcast fabric's trick), so every shard
#   agrees on every tuple without moving it.  Per-chip HBM for the
#   bitmap drops D-fold: N=256k on a v5e-8 is 8.2 GB / 8 ≈ 1 GB per
#   chip per seed.  The sharded tick is BITWISE the single-chip
#   ``packed_exact_tick`` for the same per-seed keys
#   (tests/test_sharding.py pins it on the virtual 8-device mesh).


# per-device HBM headroom granted to sent_to bitmaps (v5e = 16 GB HBM;
# leave the other half for XLA temps, stats and the small state)
DEFAULT_EXACT_HBM_BUDGET = 8 << 30


def exact_seed_batch(cfg: HeadlineExactConfig, n_seeds: int,
                     n_shards: int = 1,
                     hbm_budget_bytes: Optional[int] = None) -> int:
    """Seed-batching policy: how many seed universes fit side by side
    once the [N, ceil(N/8)] ``sent_to`` bitmap is row-sharded over
    ``n_shards`` devices.  The 2x factor covers the tick's out-of-place
    bitmap update (scatter-add reads old + writes new before donation
    can reuse the buffer)."""
    nb = -(-cfg.n_nodes // 8)
    per_seed = (cfg.n_nodes // max(1, n_shards)) * nb
    budget = (DEFAULT_EXACT_HBM_BUDGET if hbm_budget_bytes is None
              else hbm_budget_bytes)
    fit = max(1, int(budget // max(1, 2 * per_seed)))
    return max(1, min(n_seeds, fit, 32))


@partial(jax.jit, static_argnames=("cfg",), donate_argnums=(0,))
def _packed_scan_chunk_batch(state: PackedExactState, seed_keys,
                             cfg: HeadlineExactConfig):
    """Single-chip seed-batched chunk: ``state`` leaves carry a leading
    [S] seed axis (tick is [S]); ``seed_keys`` is [S, 2].  Per-tick
    stats come back [C, S].  The carried state is donated so sequential
    chunk dispatches update the S bitmaps in place."""

    def body(st, _):
        keys_t = jax.vmap(jax.random.fold_in)(seed_keys, st.tick)
        nxt = jax.vmap(
            lambda s, kk: packed_exact_tick(s, kk, cfg)
        )(st, keys_t)
        msgs_f = nxt.msgs.astype(jnp.float32)
        return nxt, (
            jnp.all(nxt.infected, axis=1),
            jnp.mean(msgs_f, axis=1),
            jnp.percentile(msgs_f, 99, axis=1),
        )

    return jax.lax.scan(body, state, xs=None, length=cfg.chunk_ticks)


def _sharded_tick_local(infected_l, tx_l, next_send_l, sent_l, msgs_l,
                        ticks, pending_l, keys,
                        cfg: HeadlineExactConfig):
    """One exact-sampler tick on ONE shard's rows for a seed batch.

    Shapes (S = seed batch, n_local = N / D shards):
    infected_l/tx_l/next_send_l/msgs_l [S, n_local]; sent_l
    [S, n_local, nb]; ticks [S] (lockstep, all equal); keys [S, 2]
    per-seed tick keys (already tick-folded, same contract as
    ``packed_exact_tick``).

    Candidate draws, loss draws and sync peer draws are REPLICATED
    (same per-seed key on every shard — cheap integers, the
    models/sharded.py fabric idiom); sent-bit tests and marks are
    sender-local; validity/active/infected masks cross the fabric as
    tiled all_gathers.  Bitwise identical per seed to
    ``packed_exact_tick`` for the same keys.
    """
    from corrosion_tpu.models.sharded import gather_nodes

    n, k = cfg.n_nodes, cfg.fanout
    S, n_local = infected_l.shape
    nb = sent_l.shape[2]
    shard = jax.lax.axis_index("nodes")
    my_lo = shard * n_local
    idx_l = my_lo + jnp.arange(n_local, dtype=jnp.int32)
    s_rows = jnp.arange(S, dtype=jnp.int32)

    def slice_l(x):  # [S, n] -> my [S, n_local] block
        return jax.lax.dynamic_slice_in_dim(x, my_lo, n_local, axis=1)

    if _latency_region_of(cfg) is not None:
        infected_l, tx_l, next_send_l, pending_l = _latency_promote(
            infected_l, tx_l, next_send_l, pending_l, ticks[:, None],
            cfg, idx=idx_l,
        )
    active_l = infected_l & (tx_l > 0) & (next_send_l <= ticks[:, None])
    active = gather_nodes(active_l, axis=1)  # [S, n]
    part = _partition_of(cfg)
    part_active = ticks < cfg.heal_tick  # [S]

    ks = jax.vmap(lambda kk: jax.random.split(kk, 3))(keys)
    k_draw, k_loss, k_sync = ks[:, 0], ks[:, 1], ks[:, 2]

    def draw(r):
        return jax.vmap(
            lambda kd: jax.random.randint(
                jax.random.fold_in(kd, r), (n, k), 0, n
            )
        )(k_draw)  # [S, n, k] replicated

    def invalid_local(cand):
        """[S, n_local] bool: my rows' k-tuples with a
        self/sent/duplicate hit — the sent test is a LOCAL byte gather
        of the sender's own packed row."""
        cand_l = jax.lax.dynamic_slice_in_dim(cand, my_lo, n_local, 1)
        self_hit = cand_l == idx_l[None, :, None]
        byte = jnp.take_along_axis(sent_l, cand_l // 8, axis=2)
        sent_hit = (
            (byte >> (cand_l % 8).astype(jnp.uint8)) & 1
        ).astype(bool)
        dup = jnp.zeros((S, n_local), bool)
        for a in range(k):
            for b in range(a + 1, k):
                dup |= cand_l[..., a] == cand_l[..., b]
        return jnp.any(self_hit | sent_hit, axis=2) | dup

    cand = draw(0)
    bad = gather_nodes(invalid_local(cand) & active_l, axis=1)  # [S, n]

    def cond(carry):
        _, bad, _ = carry
        return jnp.any(bad)

    def body(carry):
        cand, bad, r = carry
        cand = jnp.where(bad[:, :, None], draw(r), cand)
        bad_l = invalid_local(cand) & slice_l(bad)
        return cand, gather_nodes(bad_l, axis=1), r + 1

    cand, _, _ = jax.lax.while_loop(cond, body, (cand, bad, jnp.int32(1)))

    delivered = jnp.broadcast_to(active[:, :, None], (S, n, k))
    if cfg.loss > 0.0:
        keep = jax.vmap(
            lambda kl: jax.random.uniform(kl, (n, k))
        )(k_loss) >= cfg.loss
        delivered &= keep
    if part is not None:
        delivered &= ~(
            (part[None, :, None] != part[cand])
            & part_active[:, None, None]
        )
    delivered = _wan_filter(delivered, cand, k_loss, cfg)
    delivered, queued = _latency_split(delivered, cand, ticks, cfg)
    if queued is not None:
        # full-width queue min is replicated arithmetic; fold my rows
        pending_l = jnp.minimum(pending_l, slice_l(queued))

    # delivery: every shard knows every (replicated) tuple, so each
    # commits its own rows from one full-width scatter then slices
    tgt = jnp.where(delivered, cand, n).reshape(S, n * k)
    hit = jnp.zeros((S, n), bool).at[s_rows[:, None], tgt].set(
        True, mode="drop"
    )
    new_infected_l = infected_l | slice_l(hit)

    # mark on send — sender-local: my rows' bits in MY bitmap shard
    cand_l = jax.lax.dynamic_slice_in_dim(cand, my_lo, n_local, 1)
    mark_cols = jnp.where(active_l[:, :, None], cand_l // 8, nb)
    mark_bits = (jnp.uint8(1) << (cand_l % 8).astype(jnp.uint8))
    new_sent_l = sent_l.at[
        s_rows[:, None, None],
        jnp.arange(n_local, dtype=jnp.int32)[None, :, None],
        mark_cols,
    ].add(mark_bits, mode="drop")
    new_msgs_l = msgs_l + jnp.where(active_l, k, 0)

    new_tx_l = jnp.where(active_l, tx_l - 1, tx_l)
    learned_l = new_infected_l & ~infected_l
    new_next_send_l = _backoff_next_send(
        active_l, learned_l, new_tx_l, next_send_l, ticks[:, None],
        cfg, idx=idx_l,
    )
    new_tx_l = jnp.where(learned_l, cfg.max_transmissions, new_tx_l)

    if cfg.sync_interval > 0:
        # gather OUTSIDE the cond so both branches stay collective-free
        infected_all = gather_nodes(new_infected_l, axis=1)  # [S, n]

        def do_sync(args):
            infected_l, msgs_l = args
            p = cfg.sync_peers
            peers = jax.vmap(
                lambda kk: jax.random.randint(kk, (n, p), 0, n)
            )(k_sync)  # [S, n, p] replicated
            reachable = jnp.ones((S, n, p), bool)
            if part is not None:
                reachable &= ~(
                    (part[None, :, None] != part[peers])
                    & part_active[:, None, None]
                )
            healed, pay = _sync_pull(infected_all, peers, reachable, cfg)
            return infected_l | slice_l(healed), msgs_l + slice_l(pay)

        new_infected_l, new_msgs_l = jax.lax.cond(
            ticks[0] % cfg.sync_interval == cfg.sync_interval - 1,
            do_sync,
            lambda args: args,
            (new_infected_l, new_msgs_l),
        )

    return (new_infected_l, new_tx_l, new_next_send_l, new_sent_l,
            new_msgs_l, ticks + 1, pending_l)


def _exact_state_specs():
    """(in/out) PartitionSpecs for a seed-batched PackedExactState:
    node axes sharded over ``nodes``, seed axis replicated."""
    from jax.sharding import PartitionSpec as P

    return PackedExactState(
        infected=P(None, "nodes"),
        tx=P(None, "nodes"),
        next_send=P(None, "nodes"),
        sent=P(None, "nodes", None),
        msgs=P(None, "nodes"),
        tick=P(),
        pending=P(None, "nodes"),
    )


def exact_shardings(mesh) -> PackedExactState:
    """NamedShardings for a SEED-BATCHED PackedExactState (leading [S]
    axis on every leaf, tick [S]) — one NamedSharding per field,
    derived from the SAME spec table the shard_map wrappers use
    (``_exact_state_specs``), so the layout has a single source of
    truth."""
    from jax.sharding import NamedSharding

    return jax.tree.map(
        lambda p: NamedSharding(mesh, p), _exact_state_specs()
    )


@lru_cache(maxsize=8)
def sharded_packed_exact_step(mesh, cfg: HeadlineExactConfig):
    """Build the jitted mesh-native exact tick: ``step(state, keys) ->
    state`` on GLOBAL seed-batched PackedExactState arrays node-sharded
    per ``exact_shardings``; ``keys`` [S, 2] are per-seed tick keys
    (caller folds tick, same contract as ``packed_exact_tick``).

    Cached by (mesh, cfg): a fresh ``jax.jit`` wrapper per call would
    discard its compile cache, making warm runs useless."""
    from corrosion_tpu.models.sharded import _shard_map

    if cfg.n_nodes % mesh.shape["nodes"] != 0:
        raise ValueError(
            f"n_nodes {cfg.n_nodes} must divide over "
            f"{mesh.shape['nodes']} node shards"
        )
    from jax.sharding import PartitionSpec as P

    specs = _exact_state_specs()

    def local(state: PackedExactState, keys):
        out = _sharded_tick_local(*state, keys, cfg)
        return PackedExactState(*out)

    return jax.jit(
        _shard_map(
            local, mesh,
            in_specs=(specs, P()),
            out_specs=specs,
        )
    )


@lru_cache(maxsize=8)
def make_sharded_exact_chunk(mesh, cfg: HeadlineExactConfig):
    """Build the jitted mesh-native scan chunk: ``chunk(state,
    seed_keys) -> (state', (conv [C, S], msgs_mean [C, S], msgs_p99
    [C, S]))`` — the sharded twin of ``_packed_scan_chunk_batch``
    (state donated for in-place pipelining, per-tick keys folded from
    [S, 2] seed keys, stats computed from gathered global arrays so
    they are replicated).

    Cached by (mesh, cfg) so ``run_exact_headline``'s warm call and
    measured call share one compiled executable — a fresh ``jax.jit``
    wrapper per call would recompile and charge it to ``wall_s``."""
    from corrosion_tpu.models.sharded import _shard_map, gather_nodes

    if cfg.n_nodes % mesh.shape["nodes"] != 0:
        raise ValueError(
            f"n_nodes {cfg.n_nodes} must divide over "
            f"{mesh.shape['nodes']} node shards"
        )
    from jax.sharding import PartitionSpec as P

    specs = _exact_state_specs()

    def local_chunk(state: PackedExactState, seed_keys):
        def body(carry, _):
            keys_t = jax.vmap(jax.random.fold_in)(seed_keys, carry[5])
            nxt = _sharded_tick_local(*carry, keys_t, cfg)
            msgs_all = gather_nodes(nxt[4], axis=1).astype(jnp.float32)
            conv = jnp.all(gather_nodes(nxt[0], axis=1), axis=1)
            return nxt, (
                conv,
                jnp.mean(msgs_all, axis=1),
                jnp.percentile(msgs_all, 99, axis=1),
            )

        carry, stats = jax.lax.scan(
            body, tuple(state), xs=None, length=cfg.chunk_ticks,
        )
        return PackedExactState(*carry), stats

    return jax.jit(
        _shard_map(
            local_chunk, mesh,
            in_specs=(specs, P()),
            out_specs=(specs, (P(), P(), P())),
        ),
        donate_argnums=(0,),
    )


# ---------------------------------------------------------------------------
# Frontier-sparse exact sampler (N = 256k-1M+)
# ---------------------------------------------------------------------------
#
# The bitpacked kernel's [N, ceil(N/8)] ``sent_to`` bitmap is O(N^2/8)
# bytes — 1.25 GB at 100k, 8.2 GB at 256k, ~125 GB at 1M: past the 256k
# stretch point the next order of magnitude is a REPRESENTATION problem
# (TeraAgent, PAPERS.md, distributes half a trillion agents on exactly
# this move: sparse, delta-encoded state exchange over shards).  The
# protocol itself is frontier-sparse: a node transmits at most
# ``max_transmissions * fanout`` targets per payload, so its entire
# exclusion set fits a CAPPED RECENT-TARGET RING of that many slots —
# O(N * budget * fanout) bytes total (128 MB at 1M vs 125 GB dense),
# and the per-tick ring test is a ``cap``-wide compare instead of a
# byte gather from a cache-hostile gigabyte bitmap.
#
# Exactness is preserved structurally, not statistically:
#
# * each active send appends its k fresh targets at ring slots
#   ``sends_made * k + j`` — slots never collide and never overflow,
#   because ``tx`` decrements once per active tick and a node learns
#   (gets a fresh budget) at most once;
# * the ORIGIN's ring0 tier (seeded at init, up to ring0_size-1
#   targets) is the one exclusion that would not fit the ring — but the
#   tier is a contiguous index block, so membership is ARITHMETIC
#   (``_ring0_tier_hit``), not stored;
# * the RNG stream (candidate rounds, loss, sync peers) is consumed in
#   exactly the bitpacked kernel's order, so for the same per-seed keys
#   the trajectory — infected set, per-node msgs, tx, next_send, and
#   the ring DECODED back to a bitmap — is BITWISE ``packed_exact_tick``
#   (tests/test_frontier.py pins it at N<=256 with a seeded-corruption
#   negative control; tests/test_sharding.py pins the mesh twin).
#
# Per-tick work is frontier-gated: ticks with an EMPTY frontier (no
# node has anything left to send — the long sync-only tail after the
# broadcast wave dies) skip the entire draw/test/mark phase via
# ``lax.cond``, and the rejection loop's extra rounds only run while
# some frontier row still holds an invalid tuple.


def frontier_ring_cap(cfg: HeadlineExactConfig) -> int:
    """Ring slots per node: the protocol's own bound on distinct
    targets a non-origin node can ever send this payload to."""
    return cfg.max_transmissions * cfg.fanout


class FrontierExactState(NamedTuple):
    infected: jnp.ndarray  # [N] bool
    tx: jnp.ndarray  # [N] int32 remaining transmissions
    next_send: jnp.ndarray  # [N] int32
    ring: jnp.ndarray  # [N, cap] int32 sent-target ring (N = empty slot)
    msgs: jnp.ndarray  # [N] int32 (broadcast + sync session msgs)
    tick: jnp.ndarray  # scalar int32
    # [N] int32 WAN latency queue (LATENCY_NONE = nothing in flight);
    # appended LAST so tick stays at leaf index [5] for the chunk
    # builders' positional indexing
    pending: jnp.ndarray


def frontier_exact_init(
    cfg: HeadlineExactConfig, key, writer: int = 0
) -> FrontierExactState:
    """Bitwise ``packed_exact_init`` on every dense leaf (same tier
    loss draw from ``key``); the origin's ring0 tier is NOT stored —
    its membership test is arithmetic (``_ring0_tier_hit``)."""
    n = cfg.n_nodes
    cap = frontier_ring_cap(cfg)
    infected = jnp.zeros((n,), bool).at[writer].set(True)
    tx = jnp.zeros((n,), jnp.int32).at[writer].set(cfg.max_transmissions)
    next_send = jnp.zeros((n,), jnp.int32)
    ring = jnp.full((n, cap), n, jnp.int32)
    msgs = jnp.zeros((n,), jnp.int32)
    if cfg.ring0_size > 1:
        idx = jnp.arange(n, dtype=jnp.int32)
        block = jnp.minimum(cfg.ring0_size, n)
        in_tier = (idx // block == writer // block) & (idx != writer)
        delivered = in_tier
        if cfg.loss > 0.0:
            keep = jax.random.uniform(key, (n,)) >= cfg.loss
            delivered = in_tier & keep
        infected = infected | delivered
        tx = jnp.where(delivered, cfg.max_transmissions, tx)
        next_send = jnp.where(delivered, 1, next_send)
        msgs = msgs.at[writer].add(in_tier.sum().astype(jnp.int32))
    return FrontierExactState(
        infected, tx, next_send, ring, msgs, jnp.zeros((), jnp.int32),
        jnp.full((n,), LATENCY_NONE, jnp.int32),
    )


def _ring0_tier_hit(cfg: HeadlineExactConfig, rows_idx, cand,
                    writer: int = 0):
    """Arithmetic replacement for the origin's seeded tier bits:
    ``cand`` targets that ``packed_exact_init`` marked in the writer's
    ``sent_to`` row.  rows_idx: [..., rows]; cand: [..., rows, K]."""
    if cfg.ring0_size <= 1:
        return jnp.zeros(cand.shape, bool)
    block = min(cfg.ring0_size, cfg.n_nodes)
    in_tier = (cand // block == writer // block) & (cand != writer)
    return (rows_idx[..., None] == writer) & in_tier


def _frontier_invalid(cfg: HeadlineExactConfig, ring, rows_idx, cand,
                      writer: int = 0):
    """[..., rows] bool: rows whose k-tuple has a self/sent/duplicate
    hit — the sent test is a cap-wide compare against the row's OWN
    ring plus the origin's arithmetic tier (``packed_exact_tick``'s
    ``invalid_rows`` over the sparse representation).
    ring: [..., rows, cap]; rows_idx: [rows]; cand: [..., rows, K]."""
    k = cfg.fanout
    self_hit = cand == rows_idx[..., None]
    ring_hit = jnp.any(
        ring[..., None, :] == cand[..., None], axis=-1
    )
    tier_hit = _ring0_tier_hit(cfg, rows_idx, cand, writer)
    dup = jnp.zeros(cand.shape[:-1], bool)
    for a in range(k):
        for b in range(a + 1, k):
            dup |= cand[..., a] == cand[..., b]
    return jnp.any(self_hit | ring_hit | tier_hit, axis=-1) | dup


@partial(jax.jit, static_argnames=("cfg", "writer"))
def frontier_exact_tick(
    state: FrontierExactState, key, cfg: HeadlineExactConfig,
    writer: int = 0,
) -> FrontierExactState:
    """One exact-sampler tick over the frontier-sparse representation.
    Consumes the RNG stream in exactly ``packed_exact_tick``'s order;
    ``writer`` must match the init's (the arithmetic ring0 tier)."""
    n, k = cfg.n_nodes, cfg.fanout
    cap = state.ring.shape[-1]
    infected, tx, next_send, ring, msgs, tick, pending = state
    idx = jnp.arange(n, dtype=jnp.int32)
    # queue arrivals promote OUTSIDE the frontier gate: an in-flight WAN
    # delivery can revive an EMPTY frontier (everything local already
    # spent its budget while the cross-region copy is still in the air)
    if _latency_region_of(cfg) is not None:
        infected, tx, next_send, pending = _latency_promote(
            infected, tx, next_send, pending, tick, cfg
        )
    active = infected & (tx > 0) & (next_send <= tick)
    part = _partition_of(cfg)
    part_active = tick < cfg.heal_tick

    k_draw, k_loss, k_sync = jax.random.split(key, 3)

    def do_broadcast(args):
        infected, tx, next_send, ring, msgs, pending = args

        def invalid_rows(cand):
            return _frontier_invalid(cfg, ring, idx, cand, writer)

        cand = jax.random.randint(
            jax.random.fold_in(k_draw, 0), (n, k), 0, n
        )
        bad = invalid_rows(cand) & active

        def cond(carry):
            _, bad, _ = carry
            return jnp.any(bad)

        def body(carry):
            cand, bad, r = carry
            fresh = jax.random.randint(
                jax.random.fold_in(k_draw, r), (n, k), 0, n
            )
            cand = jnp.where(bad[:, None], fresh, cand)
            return cand, invalid_rows(cand) & bad, r + 1

        cand, _, _ = jax.lax.while_loop(
            cond, body, (cand, bad, jnp.int32(1))
        )

        delivered = jnp.broadcast_to(active[:, None], (n, k))
        if cfg.loss > 0.0:
            delivered &= jax.random.uniform(k_loss, (n, k)) >= cfg.loss
        if part is not None:
            delivered &= ~((part[:, None] != part[cand]) & part_active)
        delivered = _wan_filter(delivered, cand, k_loss, cfg)
        delivered, queued = _latency_split(delivered, cand, tick, cfg)
        if queued is not None:
            pending = jnp.minimum(pending, queued)

        new_infected = infected.at[
            jnp.where(delivered, cand, n).reshape(-1)
        ].set(True, mode="drop")

        # mark on send: the nth active tick appends its k fresh targets
        # at slots [n*k, n*k+k) — tx decrements once per active tick and
        # a node learns at most once, so slots never collide/overflow
        send_base = (cfg.max_transmissions - tx) * k
        slot = send_base[:, None] + jnp.arange(k, dtype=jnp.int32)
        slot = jnp.where(active[:, None], slot, cap)
        new_ring = ring.at[idx[:, None], slot].set(cand, mode="drop")
        msgs = msgs + jnp.where(active, k, 0)

        tx = jnp.where(active, tx - 1, tx)
        learned = new_infected & ~infected
        next_send = _backoff_next_send(
            active, learned, tx, next_send, tick, cfg
        )
        tx = jnp.where(learned, cfg.max_transmissions, tx)
        return new_infected, tx, next_send, new_ring, msgs, pending

    # empty frontier => the whole draw/test/mark phase is a no-op in
    # the bitpacked kernel too (no draws are ever consumed: per-tick
    # keys are re-derived, not carried) — skip it
    infected, tx, next_send, ring, msgs, pending = jax.lax.cond(
        jnp.any(active), do_broadcast, lambda args: args,
        (infected, tx, next_send, ring, msgs, pending),
    )

    if cfg.sync_interval > 0:
        def do_sync(args):
            infected, msgs = args
            p = cfg.sync_peers
            peers = jax.random.randint(k_sync, (n, p), 0, n)
            reachable = jnp.ones((n, p), bool)
            if part is not None:
                reachable &= ~((part[:, None] != part[peers]) & part_active)
            healed, pay = _sync_pull(infected, peers, reachable, cfg)
            return infected | healed, msgs + pay

        infected, msgs = jax.lax.cond(
            tick % cfg.sync_interval == cfg.sync_interval - 1,
            do_sync,
            lambda args: args,
            (infected, msgs),
        )

    return FrontierExactState(
        infected, tx, next_send, ring, msgs, tick + 1, pending
    )


def frontier_sent_bitmap(state: FrontierExactState,
                         cfg: HeadlineExactConfig,
                         writer: int = 0) -> np.ndarray:
    """Decode the ring (+ the arithmetic ring0 tier) back to the dense
    [N, ceil(N/8)] bitmap — the parity operand the bit-match suite
    compares against ``packed_exact_tick``'s ``sent``."""
    n = cfg.n_nodes
    nb = -(-n // 8)
    bitmap = np.zeros((n, nb), np.uint8)
    ring = np.asarray(state.ring)
    cap = ring.shape[1]
    rows = np.repeat(np.arange(n), cap)
    tgt = ring.reshape(-1)
    live = tgt < n
    np.bitwise_or.at(
        bitmap, (rows[live], tgt[live] // 8),
        (np.uint8(1) << (tgt[live] % 8).astype(np.uint8)),
    )
    if cfg.ring0_size > 1:
        idx = np.arange(n)
        block = min(cfg.ring0_size, n)
        in_tier = (idx // block == writer // block) & (idx != writer)
        t = idx[in_tier]
        np.bitwise_or.at(
            bitmap, (np.full(t.shape, writer), t // 8),
            (np.uint8(1) << (t % 8).astype(np.uint8)),
        )
    return bitmap


@partial(jax.jit, static_argnames=("cfg",), donate_argnums=(0,))
def _frontier_scan_chunk_batch(state: FrontierExactState, seed_keys,
                               cfg: HeadlineExactConfig):
    """Seed-batched frontier chunk — the sparse twin of
    ``_packed_scan_chunk_batch`` (leading [S] axis, donated state,
    [C, S] stats)."""

    def body(st, _):
        keys_t = jax.vmap(jax.random.fold_in)(seed_keys, st.tick)
        nxt = jax.vmap(
            lambda s, kk: frontier_exact_tick(s, kk, cfg)
        )(st, keys_t)
        msgs_f = nxt.msgs.astype(jnp.float32)
        return nxt, (
            jnp.all(nxt.infected, axis=1),
            jnp.mean(msgs_f, axis=1),
            jnp.percentile(msgs_f, 99, axis=1),
        )

    return jax.lax.scan(body, state, xs=None, length=cfg.chunk_ticks)


def _frontier_state_specs():
    """PartitionSpecs for a seed-batched FrontierExactState on a
    ``nodes`` mesh: the ring (the only O(N * cap) leaf) row-shards;
    every [S, N] dense leaf is REPLICATED — each shard runs the full
    cheap bookkeeping itself, so no active/infected mask ever crosses
    the fabric (the delta-exchange layout; see models/sharded.py)."""
    from jax.sharding import PartitionSpec as P

    return FrontierExactState(
        infected=P(),
        tx=P(),
        next_send=P(),
        ring=P(None, "nodes", None),
        msgs=P(),
        tick=P(),
        pending=P(),
    )


def frontier_shardings(mesh) -> FrontierExactState:
    """NamedShardings for a seed-batched FrontierExactState (one
    source of truth with the shard_map specs, like
    ``exact_shardings``)."""
    from jax.sharding import NamedSharding

    return jax.tree.map(
        lambda p: NamedSharding(mesh, p), _frontier_state_specs()
    )


def _frontier_host_specs():
    """PartitionSpecs for a seed-batched FrontierExactState on a
    ``hosts`` mesh — the MULTI-HOST layout.  Every O(N) int32 leaf
    (tx/next_send/msgs) row-shards alongside the ring: at N=10M the
    dense per-node state is no longer small enough to replicate per
    host.  ``infected`` and ``pending`` stay REPLICATED — but BY
    CONSTRUCTION, not by exchange: every host derives the identical
    full-width commit from the replicated candidate tuples and draws,
    so they never cross the fabric.  The only cross-host traffic per
    tick is the rejection loop's bitpacked validity deltas
    (models/sharded.py ``_sharded_frontier_host_tick_local``)."""
    from jax.sharding import PartitionSpec as P

    return FrontierExactState(
        infected=P(),
        tx=P(None, "hosts"),
        next_send=P(None, "hosts"),
        ring=P(None, "hosts", None),
        msgs=P(None, "hosts"),
        tick=P(),
        pending=P(),
    )


def frontier_host_shardings(mesh) -> FrontierExactState:
    """NamedShardings for the multi-host frontier layout (one source
    of truth with ``_frontier_host_specs``)."""
    from jax.sharding import NamedSharding

    return jax.tree.map(
        lambda p: NamedSharding(mesh, p), _frontier_host_specs()
    )


def host_memory_budget_bytes(
    n_hosts: int = 1, default: Optional[int] = None
) -> Optional[int]:
    """Per-host state budget derived from the machine's available RAM
    (``/proc/meminfo`` MemAvailable), the way ``_device_bitmap_budget``
    derives per-device HBM: half of what's available, split across the
    ``n_hosts`` emulated on this machine (virtual hosts SHARE the one
    RAM).  Returns ``default`` (None) when /proc/meminfo is unreadable
    — callers fall back to their own constant."""
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemAvailable:"):
                    kib = int(line.split()[1])
                    return (kib * 1024) // (2 * max(1, n_hosts))
    except (OSError, ValueError, IndexError):
        pass
    return default


def frontier_seed_batch(cfg: HeadlineExactConfig, n_seeds: int,
                        n_shards: int = 1,
                        hbm_budget_bytes: Optional[int] = None,
                        host_sharded: bool = False) -> int:
    """Seed-batching policy for the frontier kernel: the ring is the
    governing state at O(N * cap * 4) bytes per seed (vs the dense
    kernel's O(N^2/8) bitmap), so far more seeds fit the same budget.

    Single-host mesh layout: only the ring shards; the [S, N] dense
    leaves (20 B/node: tx/next_send/msgs/pending int32 + infected
    bool) are REPLICATED on every device (``_frontier_state_specs``),
    so their term never divides by the shard count.

    ``host_sharded`` switches to the multi-host layout
    (``_frontier_host_specs``): tx/next_send/msgs shard with the ring
    (12 B/node over ``n_shards`` hosts) and only infected+pending
    (5 B/node) replicate — and the default budget comes from HOST RAM
    (``host_memory_budget_bytes``) the way ``_device_bitmap_budget``
    derives HBM, because the sharded leaves now live in host memory on
    every emulated host."""
    cap = frontier_ring_cap(cfg)
    shards = max(1, n_shards)
    if host_sharded:
        per_seed = (
            (cfg.n_nodes // shards) * (cap * 4 + 12) + cfg.n_nodes * 5
        )
    else:
        per_seed = (cfg.n_nodes // shards) * cap * 4 + cfg.n_nodes * 20
    budget = hbm_budget_bytes
    if budget is None and host_sharded:
        budget = host_memory_budget_bytes(shards)
    if budget is None:
        budget = DEFAULT_EXACT_HBM_BUDGET
    fit = max(1, int(budget // max(1, 2 * per_seed)))
    return max(1, min(n_seeds, fit, 32))


def run_exact_headline(
    cfg: HeadlineExactConfig, n_seeds: int = 4, seed: int = 0,
    mesh=None, seed_batch: Optional[int] = None,
    warm_chunks: Optional[int] = None,
    hbm_budget_bytes: Optional[int] = None,
    kernel: str = "dense",
    host_sharded: bool = False,
) -> Dict:
    """Seed-parallel exact-sampler epidemics at headline scale.

    Seeds run in vmapped batches sized by ``exact_seed_batch`` (the
    [N, N/8] ``sent_to`` bitmap is the HBM governor); batches beyond
    the budget pipeline sequentially with donated buffers.  With
    ``mesh`` (a Mesh carrying a ``nodes`` axis) the bitmap and node
    state row-shard over the fabric, dropping per-chip HBM D-fold —
    per-seed trajectories are bitwise identical either way.
    ``warm_chunks`` stops after that many scan chunks (compile warming
    without paying a full run).

    ``kernel`` selects the representation: ``"dense"`` (the bitpacked
    [N, N/8] ``sent_to`` kernel) or ``"sparse"`` (the frontier kernel:
    capped recent-target rings, O(N * budget * fanout) state — the only
    representation that reaches N=1M).  Per-seed trajectories are
    bitwise identical across kernels AND across sharding, so the choice
    never moves the published numbers (pinned by tests/test_frontier.py
    and tests/test_sharding.py); the result records which one ran under
    ``"kernel"`` (``sharded-`` prefixed when a mesh was used).

    ``host_sharded`` (sparse kernel only, ``mesh`` must carry a
    ``hosts`` axis) selects the MULTI-HOST frontier layout: every
    O(N) int32 leaf row-shards over the host axis and the only
    cross-host traffic per tick is the rejection loop's bitpacked
    validity deltas.  The kernel tag becomes ``host-sparse`` and the
    result records ``n_hosts``.

    Returns the same stat keys as ``run_epidemic_seeds`` (msgs/ticks at
    each seed's own convergence tick) with ``delivery_model: exact``.
    """
    from corrosion_tpu.sim.epidemic import stats_at_convergence

    if kernel not in ("dense", "sparse"):
        raise ValueError(f"unknown kernel {kernel!r}")
    sparse = kernel == "sparse"
    if host_sharded and (not sparse or mesh is None):
        raise ValueError(
            "host_sharded needs kernel='sparse' and a mesh with a "
            "'hosts' axis"
        )
    t0 = time.perf_counter()
    mesh_axis = "hosts" if host_sharded else "nodes"
    n_shards = int(mesh.shape[mesh_axis]) if mesh is not None else 1
    if sparse:
        sb = seed_batch or frontier_seed_batch(
            cfg, n_seeds, n_shards, hbm_budget_bytes,
            host_sharded=host_sharded,
        )
    else:
        sb = seed_batch or exact_seed_batch(
            cfg, n_seeds, n_shards, hbm_budget_bytes
        )
    init_fn = frontier_exact_init if sparse else packed_exact_init
    chunk_fn = None
    if mesh is not None:
        if host_sharded:
            from corrosion_tpu.models.sharded import (
                make_sharded_frontier_host_chunk,
            )

            chunk_fn = make_sharded_frontier_host_chunk(mesh, cfg)
        elif sparse:
            from corrosion_tpu.models.sharded import (
                make_sharded_frontier_chunk,
            )

            chunk_fn = make_sharded_frontier_chunk(mesh, cfg)
        else:
            chunk_fn = make_sharded_exact_chunk(mesh, cfg)
    firsts: List[float] = []
    means: List[float] = []
    p99s: List[float] = []
    converged = 0
    warmed_shapes: set = set()
    for lo in range(0, n_seeds, sb):
        S = min(sb, n_seeds - lo)
        if warm_chunks is not None:
            # a warm call only needs each DISTINCT batch shape once
            # (compile is per-S); re-running identical batches would be
            # pure dead work
            if S in warmed_shapes:
                continue
            warmed_shapes.add(S)
        base_keys = jnp.stack([
            jax.random.PRNGKey(seed * 10_007 + s)
            for s in range(lo, lo + S)
        ])
        state = jax.vmap(
            lambda kk: init_fn(cfg, jax.random.fold_in(kk, 2**20))
        )(base_keys)
        if mesh is not None:
            if host_sharded:
                shardings = frontier_host_shardings(mesh)
            elif sparse:
                shardings = frontier_shardings(mesh)
            else:
                shardings = exact_shardings(mesh)
            state = jax.device_put(state, shardings)
        flags: List[np.ndarray] = []
        mm: List[np.ndarray] = []
        mp: List[np.ndarray] = []
        ticks_done = 0
        chunks = 0
        while ticks_done < cfg.max_ticks:
            if mesh is not None:
                state, (conv, m_mean, m_p99) = chunk_fn(state, base_keys)
            elif sparse:
                state, (conv, m_mean, m_p99) = _frontier_scan_chunk_batch(
                    state, base_keys, cfg
                )
            else:
                state, (conv, m_mean, m_p99) = _packed_scan_chunk_batch(
                    state, base_keys, cfg
                )
            flags.append(np.asarray(conv).T)  # scan stacks [C, S]
            mm.append(np.asarray(m_mean).T)
            mp.append(np.asarray(m_p99).T)
            ticks_done += cfg.chunk_ticks
            chunks += 1
            if flags[-1][:, -1].all():
                break
            if warm_chunks is not None and chunks >= warm_chunks:
                break
        conv_mask, first, (m_at, p_at) = stats_at_convergence(
            np.concatenate(flags, axis=1),
            np.concatenate(mm, axis=1),
            np.concatenate(mp, axis=1),
        )
        converged += int(conv_mask.sum())
        firsts.extend(float(x) for x in first)
        means.extend(float(x) for x in m_at)
        p99s.extend(float(x) for x in p_at)
    if host_sharded:
        kernel_tag = "host-sparse"
    else:
        kernel_tag = ("sharded-" if mesh is not None else "") + kernel
    return {
        "n_nodes": cfg.n_nodes,
        "n_seeds": n_seeds,
        "delivery_model": "exact",
        "kernel": kernel_tag,
        "n_hosts": n_shards if host_sharded else 1,
        "converged_frac": converged / n_seeds,
        "ticks_p50": float(np.percentile(firsts, 50)),
        "ticks_p99": float(np.percentile(firsts, 99)),
        "msgs_per_node_mean": float(np.mean(means)),
        "msgs_per_node_p99": float(np.mean(p99s)),
        "seed_batch": sb,
        "n_shards": n_shards,
        "wall_s": time.perf_counter() - t0,
    }
