"""Sim-vs-agent trace diff: calibrate the TPU simulator against the real
in-process agent cluster.

The north-star metric path (BASELINE.json: "bit-match corro-devcluster at
N≤256") needs a recorded comparison between the JAX epidemic simulator
and a real cluster of our agents running the actual gossip protocol over
loopback UDP/TCP.  This module runs both under matched parameters
(fanout, max_transmissions, no loss) and diffs the convergence traces:

* ``msgs_per_node`` — broadcast messages sent per node until the cluster
  converged (sim counts scatter deliveries; agents count real UDP sends
  via the ``corro_broadcast_sent_total`` metric);
* ``ticks_to_converge`` — sim protocol rounds vs the agent cluster's
  wall-clock divided by the rebroadcast delay (one "hop" ≈ one round);
* ``converged_frac`` — both must reach 1.0.

Used by ``corro-devcluster --runtime tpu-sim`` (one recorded diff JSON)
and by tests at small N.

Parity anchor: the reference measures the same path with
``configurable_stress_test`` (corro-agent/src/agent/tests.rs:284-302)
booting N real agents in-process; our sim side replaces the cluster with
the vmapped kernel, which is the whole point of the TPU build.
"""

from __future__ import annotations

import asyncio
import json
import math
import time
from typing import Dict, Optional


def sim_trace(
    n: int,
    fanout: int = 3,
    max_transmissions: int = 5,
    seeds: int = 8,
    sync: bool = True,
) -> Dict:
    """Run the JAX epidemic sim at matched parameters; return trace stats."""
    from corrosion_tpu.sim.epidemic import EpidemicConfig, run_epidemic_seeds

    cfg = EpidemicConfig(
        n_nodes=n,
        n_rows=4,
        fanout_ring0=0,
        fanout_global=fanout,
        ring0_size=1,  # agents sample uniformly: no ring0 tier
        max_transmissions=max_transmissions,
        loss=0.0,
        sync_interval=8 if sync else 0,
        sync_peers=1,
        max_ticks=256,
        chunk_ticks=8,
    )
    stats = run_epidemic_seeds(cfg, n_seeds=seeds, seed=0)
    return {
        "runtime": "tpu-sim",
        "n_nodes": n,
        "converged_frac": stats["converged_frac"],
        "ticks_to_converge_p50": _finite(stats["ticks_p50"]),
        "ticks_to_converge_p99": _finite(stats["ticks_p99"]),
        "msgs_per_node": stats["msgs_per_node_mean"],
        "wall_s": stats["wall_s"],
    }


def _finite(v: Optional[float]) -> Optional[float]:
    """inf/nan (a seed never converged) → None so the JSON stays strict."""
    if v is None or not math.isfinite(v):
        return None
    return v


async def agent_trace(
    n: int,
    fanout: int = 3,
    max_transmissions: int = 5,
    rebroadcast_delay: float = 0.05,
    timeout: float = 60.0,
    base_dir: Optional[str] = None,
) -> Dict:
    """Boot n real agents on loopback, gossip one write to convergence.

    Bootstrap is a star onto node 0; full membership is awaited before
    the write so the epidemic runs over a complete member view (matching
    the sim's uniform sampling over N nodes).
    """
    from corrosion_tpu.agent.testing import launch_test_agent, wait_for

    agents = []
    try:
        first = await launch_test_agent(
            tmpdir=None if base_dir is None else f"{base_dir}/n0",
            fanout=fanout,
            max_transmissions=max_transmissions,
            rebroadcast_delay=rebroadcast_delay,
        )
        agents.append(first)
        boot = [f"{first.gossip_addr[0]}:{first.gossip_addr[1]}"]
        for i in range(1, n):
            agents.append(
                await launch_test_agent(
                    bootstrap=boot,
                    tmpdir=None if base_dir is None else f"{base_dir}/n{i}",
                    fanout=fanout,
                    max_transmissions=max_transmissions,
                    rebroadcast_delay=rebroadcast_delay,
                )
            )

        # full membership (SWIM dissemination), so fanout sampling sees N-1
        await wait_for(
            lambda: all(
                len(a.members.alive()) >= n - 1 for a in agents
            ),
            timeout=timeout,
        )

        def sent_total() -> int:
            return sum(
                int(a.metrics.get_counter("corro_broadcast_sent_total") or 0)
                for a in agents
            )

        base_sent = sent_total()
        t0 = time.perf_counter()
        agents[0].execute_transaction(
            [("INSERT INTO tests (id, text) VALUES (?, ?)",
              (4242, "simdiff"))]
        )

        def converged() -> bool:
            for a in agents:
                _, rows = a.storage.read_query(
                    "SELECT text FROM tests WHERE id = 4242"
                )
                if not rows or rows[0][0] != "simdiff":
                    return False
            return True

        await wait_for(converged, timeout=timeout, interval=0.02)
        wall = time.perf_counter() - t0
        msgs = sent_total() - base_sent
        return {
            "runtime": "agents",
            "n_nodes": n,
            "converged_frac": 1.0,
            "wall_to_converge_s": round(wall, 4),
            "ticks_to_converge_est": round(wall / rebroadcast_delay, 1),
            "msgs_per_node": round(msgs / n, 2),
        }
    finally:
        await asyncio.gather(*(a.stop() for a in agents), return_exceptions=True)


def diff_traces(sim: Dict, agents: Dict) -> Dict:
    """Join the two traces into one recorded diff."""
    sim_ticks = sim["ticks_to_converge_p50"]
    return {
        "n_nodes": sim["n_nodes"],
        "sim": sim,
        "agents": agents,
        "diff": {
            "msgs_per_node_ratio": round(
                sim["msgs_per_node"] / max(agents["msgs_per_node"], 1e-9), 3
            ),
            "ticks_ratio": (
                None if sim_ticks is None else round(
                    sim_ticks / max(agents["ticks_to_converge_est"], 1e-9), 3
                )
            ),
            "both_converged": (
                sim["converged_frac"] == 1.0
                and agents["converged_frac"] == 1.0
            ),
        },
    }


async def run_simdiff(
    n: int = 64,
    fanout: int = 3,
    max_transmissions: int = 5,
    out_path: Optional[str] = None,
    base_dir: Optional[str] = None,
) -> Dict:
    sim = sim_trace(n, fanout=fanout, max_transmissions=max_transmissions)
    ag = await agent_trace(
        n, fanout=fanout, max_transmissions=max_transmissions,
        base_dir=base_dir,
    )
    result = diff_traces(sim, ag)
    if out_path:
        with open(out_path, "w") as f:
            json.dump(result, f, indent=1, allow_nan=False)
    return result
