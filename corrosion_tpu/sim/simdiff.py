"""Sim-vs-agent trace diff: calibrate the TPU simulator against the real
in-process agent cluster.

The north-star metric path (BASELINE.json: "bit-match corro-devcluster at
N≤256") needs a recorded comparison between the JAX epidemic simulator
and a real cluster of our agents running the actual gossip protocol over
loopback (speedy wire bytes end to end).  Both sides run under matched
parameters — uniform k-fanout, same ``max_transmissions``, no loss, no
anti-entropy — and the diff compares MEASURED quantities on both sides:

* ``msgs_per_node`` — broadcast messages sent per node until the cluster
  converged (sim counts scatter deliveries; agents count successful uni
  sends via ``corro_broadcast_sent_total``);
* ``hops_p50`` / ``hops_p99`` — infection-tree depth per node.  The sim
  maintains it as a scatter-min kernel (``models/broadcast.py``); the
  agents carry a real per-payload hop counter on the wire
  (``AgentConfig.debug_hops``) — a measurement, not a wall-clock/delay
  estimate.

Matched-condition notes (recorded in the JSON):

* agents run with ``ring0_enabled=False`` — on loopback every peer is in
  the RTT<6ms ring0 tier, so the reference's "all of ring0 first" local
  fanout would make every dissemination 1 hop deep; uniform sampling is
  the condition the simulator models (and what a real WAN cluster does);
* membership is pre-seeded and SWIM probing quiesced: the epidemic under
  measurement is the broadcast; membership dissemination is measured
  separately (BASELINE config #2);
* the sim models the agents' per-payload ``sent_to`` exclusion exactly
  (``track_sent``, broadcast/mod.rs:683-690 semantics) — hop depths
  match 1:1; the known residual is time quantization: the sim's
  tick-grid flush/backoff rounding fits slightly more redundant
  retransmissions before the convergence cutoff than the agents'
  wall-clock schedule does, so msgs/node reads a little high.

Parity anchor: the reference measures the same path with
``configurable_stress_test`` (corro-agent/src/agent/tests.rs:284-302)
booting N real agents in-process; our sim side replaces the cluster with
the vmapped kernel, which is the whole point of the TPU build.
"""

from __future__ import annotations

import asyncio
import json
import math
import time
from typing import Dict, List, Optional


def sim_trace(
    n: int,
    fanout: int = 3,
    max_transmissions: int = 5,
    seeds: int = 8,
    sync: bool = False,
    backoff_ticks: float = 2.5,
) -> Dict:
    """Run the JAX epidemic sim at matched parameters; return trace stats.

    One tick = one agent flush interval (the fastest forward latency for
    a FRESH payload); the nth retransmission waits ``backoff_ticks*n``
    more, matching the agents' rebroadcast_delay/flush_interval ratio
    (0.05/0.02 = 2.5 by default) and the reference's 100ms*send_count
    requeue backoff."""
    from corrosion_tpu.sim.epidemic import EpidemicConfig, run_epidemic_seeds

    cfg = EpidemicConfig(
        n_nodes=n,
        n_rows=4,
        fanout_ring0=0,
        fanout_global=fanout,
        ring0_size=1,  # agents sample uniformly: no ring0 tier
        max_transmissions=max_transmissions,
        loss=0.0,
        backoff_ticks=backoff_ticks,
        # model the agents' per-payload sent_to exclusion exactly (the
        # calibration N is small enough for the [N, N] memory)
        track_sent=True,
        sync_interval=8 if sync else 0,
        sync_peers=1,
        max_ticks=256,
        chunk_ticks=8,
    )
    stats = run_epidemic_seeds(cfg, n_seeds=seeds, seed=0)
    return {
        "runtime": "tpu-sim",
        "n_nodes": n,
        "converged_frac": stats["converged_frac"],
        "ticks_to_converge_p50": _finite(stats["ticks_p50"]),
        "ticks_to_converge_p99": _finite(stats["ticks_p99"]),
        "msgs_per_node": stats["msgs_per_node_mean"],
        "hops_p50": stats["hops_p50"],
        "hops_p99": stats["hops_p99"],
        "wall_s": stats["wall_s"],
    }


def _finite(v: Optional[float]) -> Optional[float]:
    """inf/nan (a seed never converged) → None so the JSON stays strict."""
    if v is None or not math.isfinite(v):
        return None
    return v


def _percentile(vals: List[float], q: float) -> float:
    if not vals:
        return 0.0
    import numpy as np

    return float(np.percentile(vals, q, method="nearest"))


async def agent_trace(
    n: int,
    fanout: int = 3,
    max_transmissions: int = 5,
    rebroadcast_delay: float = 0.05,
    writes: int = 4,
    timeout: float = 60.0,
    base_dir: Optional[str] = None,
) -> Dict:
    """Boot n real agents on loopback and measure ``writes`` epidemics.

    Each write originates at a different node; per-node infection depth
    comes from the on-wire hop counter (``debug_hops``), msgs/node from
    the successful-send metric.  Membership is pre-seeded (see module
    docstring) and anti-entropy/SWIM are quiesced so the broadcast path
    alone is measured.
    """
    from corrosion_tpu.agent.testing import (
        launch_test_agent,
        seed_full_membership,
        wait_for,
    )

    agents = []
    try:
        common = dict(
            fanout=fanout,
            max_transmissions=max_transmissions,
            rebroadcast_delay=rebroadcast_delay,
            bcast_flush_interval=0.02,
            debug_hops=True,
            ring0_enabled=False,
            # quiesce everything that is not the broadcast path
            sync_interval_min=3600.0,
            sync_interval_max=7200.0,
            probe_interval=3600.0,
            maintenance_interval=3600.0,
            max_concurrent_applies=1,
            subs_enabled=False,
            api_port=None,
            uni_cache_size=12,  # fd budget: N agents share one process,
        )
        for i in range(n):
            agents.append(
                await launch_test_agent(
                    bootstrap=[],
                    tmpdir=None if base_dir is None else f"{base_dir}/n{i}",
                    **common,
                )
            )
        seed_full_membership(agents)

        def sent_total() -> int:
            return sum(
                int(a.metrics.get_counter("corro_broadcast_sent_total") or 0)
                for a in agents
            )

        all_hops: List[int] = []
        msgs_per_write: List[float] = []
        wall_per_write: List[float] = []
        for w in range(writes):
            origin = agents[(w * (n // max(writes, 1))) % n]
            base_sent = sent_total()
            t0 = time.perf_counter()
            res = origin.execute_transaction(
                [("INSERT INTO tests (id, text) VALUES (?, ?)",
                  (10_000 + w, f"simdiff-{w}"))]
            )
            version = res["version"]

            def converged() -> bool:
                return all(
                    a is origin
                    or a.bookie.for_actor(origin.actor_id).contains_version(
                        version
                    )
                    for a in agents
                )

            await wait_for(converged, timeout=timeout, interval=0.01)
            wall_per_write.append(time.perf_counter() - t0)
            msgs_per_write.append((sent_total() - base_sent) / n)
            # drain the retransmission tail (sends continue past
            # convergence by design) so the next write's delta measures
            # only its own epidemic; the quiet window must exceed the
            # LONGEST inter-send gap, delay * max_transmissions
            max_gap = rebroadcast_delay * max_transmissions + 0.1
            stable = sent_total()
            quiet = 0.0
            while quiet < max_gap:
                await asyncio.sleep(0.1)
                now_total = sent_total()
                quiet = quiet + 0.1 if now_total == stable else 0.0
                stable = now_total
            for a in agents:
                if a is origin:
                    continue
                hops = [
                    h
                    for key, h in a._recv_hops.items()
                    if key[0] == origin.actor_id and key[1] == version
                ]
                if hops:
                    all_hops.append(min(hops) + 1)
            # the sim's percentile population includes the writer at
            # depth 0 — match it so both sides measure the same quantity
            all_hops.append(0)

        return {
            "runtime": "agents",
            "n_nodes": n,
            "writes": writes,
            "converged_frac": 1.0,
            "wall_to_converge_s": round(
                sum(wall_per_write) / len(wall_per_write), 4
            ),
            "msgs_per_node": round(
                sum(msgs_per_write) / len(msgs_per_write), 2
            ),
            "hops_measured": len(all_hops),
            "hops_p50": _percentile(all_hops, 50),
            "hops_p99": _percentile(all_hops, 99),
            "conditions": {
                "ring0_enabled": False,
                "membership": "pre-seeded, SWIM quiesced",
                "anti_entropy": "disabled",
                "wire": "speedy (reference bytes) + 1-byte hop prefix",
            },
        }
    finally:
        await asyncio.gather(*(a.stop() for a in agents), return_exceptions=True)


def diff_traces(sim: Dict, agents: Dict) -> Dict:
    """Join the two traces into one recorded diff."""
    def ratio(a, b):
        # a hop percentile can be None (measured coverage below the
        # percentile rank — sim/epidemic.py hop_stat); no ratio then
        if a is None or b is None:
            return None
        return round(a / max(b, 1e-9), 3)

    return {
        "n_nodes": sim["n_nodes"],
        "sim": sim,
        "agents": agents,
        "diff": {
            "msgs_per_node_ratio": ratio(
                sim["msgs_per_node"], agents["msgs_per_node"]
            ),
            "hops_p50_ratio": ratio(sim["hops_p50"], agents["hops_p50"]),
            "hops_p99_ratio": ratio(sim["hops_p99"], agents["hops_p99"]),
            "both_converged": (
                sim["converged_frac"] == 1.0
                and agents["converged_frac"] == 1.0
            ),
            "residual_note": (
                "sim models the agents' sent_to exclusion (hop depths "
                "match); the residual msgs/node gap is time "
                "quantization — the tick grid and the agents' "
                "wall-clock retransmit schedule fit slightly different "
                "numbers of redundant retransmissions before their "
                "respective convergence cutoffs, so the ratio lands "
                "near 1 on either side"
            ),
        },
    }


async def run_simdiff(
    n: int = 256,
    fanout: int = 3,
    max_transmissions: int = 5,
    writes: int = 4,
    out_path: Optional[str] = None,
    base_dir: Optional[str] = None,
) -> Dict:
    sim = sim_trace(n, fanout=fanout, max_transmissions=max_transmissions)
    ag = await agent_trace(
        n, fanout=fanout, max_transmissions=max_transmissions,
        writes=writes, base_dir=base_dir,
    )
    result = diff_traces(sim, ag)
    if out_path:
        with open(out_path, "w") as f:
            json.dump(result, f, indent=1, allow_nan=False)
    return result
