"""Observability soak: the live cluster measures its OWN convergence,
gated against harness ground truth, next to the kernel's prediction.

The north-star metric (p99 convergence + msgs/node) was, until this
plane existed, measured only by the external bench harness — the
system could not say how converged it was.  This soak closes the loop
"Simulating BFT Protocol Implementations at Scale" runs for protocol
validation (measured propagation vs model prediction), but with the
measurement coming from *inside* the agents:

* **telemetry** — every node records origin-commit→first-arrival lag
  (``corro_change_lag_seconds``) from the changeset's own HLC
  timestamp; :class:`~corrosion_tpu.devcluster.ClusterObserver` pools
  the raw samples into exact cluster percentiles and takes msgs/node
  from the scraped exposition;
* **ground truth** — the harness stamps each write before submission
  and each node's first ``on_change`` arrival out-of-band, the same
  instants the telemetry claims to measure;
* **prediction** — the epidemic kernel's fault-free convergence depth
  at the same (n, fanout, max_transmissions), on the simdiff tick
  base.

``bench.py --obs`` writes the three side by side to ``OBS_N32.json``
and asserts |telemetry_p99 / ground_truth_p99 − 1| ≤ tolerance: if the
plane drifts from reality, the artifact says so.
"""

from __future__ import annotations

import asyncio
import json
import time
from typing import Dict, Optional

# the simdiff/chaos time base: one kernel tick ≈ the agents' broadcast
# flush interval (launch_test_agent pins bcast_flush_interval=0.02)
TICK_S = 0.02


def sim_obs_trace(
    n: int,
    fanout: int = 3,
    max_transmissions: int = 5,
    seeds: int = 8,
) -> Dict:
    """Fault-free epidemic-kernel prediction at obs scale: convergence
    depth in ticks under uniform sampling (the agents run with ring0
    disabled for comparability, like the chaos soak)."""
    import math

    from corrosion_tpu.sim.epidemic import EpidemicConfig, run_epidemic_seeds

    cfg = EpidemicConfig(
        n_nodes=n,
        n_rows=4,
        fanout_ring0=0,
        fanout_global=fanout,
        ring0_size=1,
        max_transmissions=max_transmissions,
        loss=0.0,
        backoff_ticks=2.5,
        track_sent=True,
        sync_interval=8,
        sync_peers=1,
        max_ticks=256,
        chunk_ticks=16,
    )
    stats = run_epidemic_seeds(cfg, n_seeds=seeds, seed=0)

    def fin(v):
        return None if v is None or not math.isfinite(v) else v

    p50, p99 = fin(stats["ticks_p50"]), fin(stats["ticks_p99"])
    return {
        "runtime": "tpu-sim",
        "n_nodes": n,
        "converged_frac": stats["converged_frac"],
        "ticks_p50": p50,
        "ticks_p99": p99,
        "predicted_wall_p50_s": p50 * TICK_S if p50 is not None else None,
        "predicted_wall_p99_s": p99 * TICK_S if p99 is not None else None,
        "msgs_per_node": stats["msgs_per_node_mean"],
        "tick_seconds": TICK_S,
        "wall_s": stats["wall_s"],
    }


async def agent_obs_trace(
    n: int,
    writes: int = 40,
    writer_stride: int = 3,
    write_gap: float = 0.03,
    fanout: int = 3,
    max_transmissions: int = 5,
    timeout: float = 90.0,
    base_dir: Optional[str] = None,
) -> Dict:
    """Boot n real agents, run a spread write workload, and measure
    convergence THREE ways at once: the cluster's own telemetry
    (ClusterObserver), harness ground truth (write stamps + on_change
    arrival stamps), and the assembled broadcast-path trace of one
    write."""
    from corrosion_tpu.agent.testing import seed_full_membership, wait_for
    from corrosion_tpu.devcluster import (
        ClusterObserver,
        Topology,
        run_inprocess,
    )

    topo = Topology.parse("\n".join(f"n0 -> n{i}" for i in range(1, n)))
    agents = await run_inprocess(
        topo,
        base_dir=base_dir,
        fanout=fanout,
        max_transmissions=max_transmissions,
        ring0_enabled=False,  # uniform sampling: the kernel's model
        subs_enabled=False,
        api_port=None,
        uni_cache_size=16,  # n agents share one process's fd budget
        # a slow host must not down-mark members mid-measurement
        # (failure detection is not the quantity under test)
        suspect_timeout=10.0,
    )
    try:
        # bootstrap contact is the HARD precondition (every node must
        # have joined); FULL organic formation is best-effort — the
        # measured condition is full membership, and seeding below
        # installs the complete view (actor + addr) either way.  32
        # agents gossiping on one event loop can need minutes to form
        # organically on a constrained host, which is SWIM's metric,
        # not this soak's.
        await wait_for(
            lambda: all(a.members.alive() for a in agents.values()),
            timeout=max(60.0, 3.0 * n),
        )
        try:
            await wait_for(
                lambda: all(
                    len(a.members.alive()) == n - 1
                    for a in agents.values()
                ),
                timeout=30,
            )
        except TimeoutError:
            pass  # seeded below
        # full membership so the epidemic (not SWIM dissemination) is
        # the measured quantity — the simdiff matched condition
        seed_full_membership(list(agents.values()))

        obs = ClusterObserver(agents)
        obs.mark()

        # ground truth, out of band: first on_change arrival per
        # (node, origin actor, version), wall clock (CPython dict
        # setdefault is atomic; hooks fire on worker threads)
        arrivals: Dict[str, Dict[tuple, float]] = {
            name: {} for name in agents
        }

        def hook_for(name):
            seen = arrivals[name]

            def hook(cv):
                cs = cv.changeset
                if cs.is_full:
                    seen.setdefault(
                        (cv.actor_id.bytes, int(cs.version)), time.time()
                    )

            return hook

        for name, a in agents.items():
            a.on_change = hook_for(name)

        # spread write workload: every writer_stride-th node writes in
        # turn, stamped BEFORE submission (the HLC commit ts lands a
        # hair later — both sides of the comparison measure the same
        # instant to well under the flush-interval granularity)
        writers = [
            agents[f"n{i}"] for i in range(0, n, max(1, writer_stride))
        ]
        t_write: Dict[tuple, float] = {}
        for w in range(writes):
            origin = writers[w % len(writers)]
            t0 = time.time()
            # sync-blocking: run off-loop, or every write freezes the
            # SHARED loop all n in-process agents (and their stall
            # probes) run on — inflating the very lag/stall series the
            # soak is measuring
            res = await asyncio.to_thread(
                origin.execute_transaction,
                [("INSERT INTO tests (id, text) VALUES (?, ?)",
                  (7000 + w, f"obs-{w}"))],
            )
            t_write[(origin.actor_id, res["version"])] = t0
            await asyncio.sleep(write_gap)

        def converged() -> bool:
            for a in agents.values():
                for (actor, v) in t_write:
                    if a.actor_id != actor and not a.bookie.for_actor(
                        actor
                    ).contains_version(v):
                        return False
            return True

        t0 = time.perf_counter()
        await wait_for(converged, timeout=timeout, interval=0.02)
        wall = time.perf_counter() - t0

        # harness ground truth: per-(node, version) first-arrival lag
        ground = []
        missing = 0
        for name, a in agents.items():
            seen = arrivals[name]
            for (actor, v), t_w in t_write.items():
                if a.actor_id == actor:
                    continue
                t_a = seen.get((actor, v))
                if t_a is None:
                    # arrived via a path that skips on_change news
                    # (e.g. emptyset clearing) — count, don't invent
                    missing += 1
                    continue
                ground.append(max(0.0, t_a - t_w))
        ground.sort()

        from corrosion_tpu.agent.metrics import percentile_sorted

        def pct(s, q):
            return percentile_sorted(s, q) if s else None

        telemetry = obs.convergence_lag()
        scrape = obs.scrape()  # strict-parsed: a render regression raises

        # one write's assembled broadcast-path trace: the write-group
        # span, the collect span, and remote first-arrival applies all
        # share a trace id
        trace_names = []
        trace_id = obs.latest_write_trace()
        if trace_id is not None:
            trace_names = sorted(
                {s.name for s in obs.assemble_trace(trace_id)}
            )

        return {
            "runtime": "agents",
            "n_nodes": n,
            "writes": writes,
            "converged_frac": 1.0,
            "wall_after_last_write_s": round(wall, 3),
            "ground_truth": {
                "samples": len(ground),
                "missing_arrivals": missing,
                "p50_s": pct(ground, 0.50),
                "p99_s": pct(ground, 0.99),
                "max_s": ground[-1] if ground else None,
            },
            "telemetry": {
                "lag": telemetry,
                "msgs_per_node": obs.msgs_per_node(scrape),
                "loop_health": obs.loop_health(scrape),
                "staleness_worst_s": max(
                    obs.staleness(scrape).values(), default=0.0
                ),
            },
            "trace": {
                "trace_id": trace_id,
                "span_names": trace_names,
            },
            "conditions": {
                "ring0_enabled": False,
                "membership": "pre-seeded after formation",
                "writers": len(writers),
                "write_gap_s": write_gap,
            },
        }
    finally:
        for a in list(agents.values()):
            try:
                await a.stop()
            except Exception:
                pass


async def run_obs(
    n: int = 32,
    writes: int = 40,
    seeds: int = 8,
    tolerance: float = 0.15,
    out_path: Optional[str] = None,
    base_dir: Optional[str] = None,
    sim: bool = True,
) -> Dict:
    """The observability soak: telemetry vs ground truth vs kernel
    prediction, one JSON artifact, the tolerance asserted in-record."""
    prediction = (
        sim_obs_trace(n, seeds=seeds) if sim else None
    )
    ag = await agent_obs_trace(n, writes=writes, base_dir=base_dir)

    tel_p99 = (ag["telemetry"]["lag"] or {}).get("p99_s")
    gt_p99 = ag["ground_truth"]["p99_s"]
    ratio = (
        tel_p99 / gt_p99 if tel_p99 is not None and gt_p99 else None
    )
    within = ratio is not None and abs(ratio - 1.0) <= tolerance
    result = {
        "n_nodes": n,
        "metric": "telemetry_vs_ground_truth_p99_convergence_lag",
        "value": round(ratio, 4) if ratio is not None else None,
        "unit": "ratio",
        "tolerance": tolerance,
        "within_tolerance": within,
        "agents": ag,
        "sim": prediction,
        "diff": {
            "telemetry_p99_s": tel_p99,
            "ground_truth_p99_s": gt_p99,
            "kernel_predicted_wall_p99_s": (
                prediction["predicted_wall_p99_s"] if prediction else None
            ),
            "msgs_per_node_telemetry": ag["telemetry"]["msgs_per_node"],
            "msgs_per_node_kernel": (
                prediction["msgs_per_node"] if prediction else None
            ),
            "note": (
                "telemetry = the agents' own corro_change_lag_seconds "
                "samples (origin HLC ts -> first-arrival wall); ground "
                "truth = harness write stamps vs on_change arrival "
                "stamps; the kernel predicts full-cluster convergence "
                "depth for the loss-free uniform-fanout family on the "
                "simdiff tick base"
            ),
        },
    }
    if not within:
        result["error"] = (
            "telemetry-derived p99 convergence lag diverges from "
            f"harness ground truth beyond ±{tolerance:.0%}"
        )
    if out_path:
        with open(out_path, "w") as f:
            json.dump(result, f, indent=1, allow_nan=False)
            f.write("\n")
    return result
