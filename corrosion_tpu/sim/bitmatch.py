"""Bit-match of the simulator against the real agents (the exactness
half of the north star).

``agent/det.py`` runs N real agents — real CRR storage, real speedy
bytes, real ingest — under a discrete-event tick scheduler with seeded
PRNG streams.  This module is the **simulator side**: a deterministic
replay of the same protocol model the JAX epidemic kernel implements
(per-payload ``sent_to`` exclusion, retransmit-decay budget,
backoff-scheduled retransmissions, rebroadcast-on-learn — the
``track_sent`` semantics of ``models/broadcast.py``), drawing fanout
targets from the *same* per-node PRNG streams.

The two sides share exactly two pure functions — ``det_seed_for`` (the
per-node stream seed) and ``det_backoff_gap`` (tick backoff) — plus the
sampling *convention* (``Members.sample``: population in ascending node
index, exclusion filtered before the draw, the whole population
returned without consuming the stream when it fits the fanout).
Everything else — who is infected, who may send, what each ``sent_to``
contains, when budgets exhaust, every message count — is computed
independently: the agents through their storage/bookkeeping/wire
pipeline, the sim through this array state machine.  One diverging
decision desynchronizes the PRNG streams and every later tick, so
per-tick equality of infected sets and per-node message counts is a
sharp equivalence test of the protocol semantics, not a replay of
recorded outputs.

``run_bitmatch`` produces the ``BITMATCH_N{64,256}.json`` artifacts
(wired into ``bench.py``): per-write per-tick equality plus the first
mismatching tick, if any.

Reference anchors: sent_to sampling ``broadcast/mod.rs:586-702``,
retransmit requeue ``:745-765``, rebroadcast-on-learn
``handlers.rs:939-949``.
"""

from __future__ import annotations

import json
import random
from typing import Dict, List, Optional, Set

from corrosion_tpu.agent.det import (
    DetCluster,
    DetParams,
    det_backoff_gap,
    det_seed_for,
    run_det_epidemic,
)


def det_sim_epidemic(params: DetParams, origin: int) -> Dict:
    """Deterministic replay: the simulator's protocol state machine on
    the shared PRNG streams.  Same trace shape as ``run_det_epidemic``.
    """
    n = params.n_nodes
    rngs = [random.Random(det_seed_for(params.seed, i)) for i in range(n)]
    return _det_sim_epidemic_with_rngs(params, origin, rngs)


def diff_det_traces(sim: Dict, agents: Dict) -> Dict:
    """Tick-for-tick equality of infected sets and per-node msgs."""
    s_ticks, a_ticks = sim["ticks"], agents["ticks"]
    first_mismatch: Optional[int] = None
    detail: Optional[str] = None
    for t in range(max(len(s_ticks), len(a_ticks))):
        if t >= len(s_ticks) or t >= len(a_ticks):
            first_mismatch = t
            detail = (
                f"trace lengths differ: sim {len(s_ticks)} vs "
                f"agents {len(a_ticks)}"
            )
            break
        if s_ticks[t]["infected"] != a_ticks[t]["infected"]:
            first_mismatch, detail = t, "infected sets differ"
            break
        if s_ticks[t]["msgs"] != a_ticks[t]["msgs"]:
            first_mismatch, detail = t, "per-node msg counts differ"
            break
    return {
        "match": first_mismatch is None,
        "ticks_compared": len(s_ticks),
        "converged_tick_sim": sim["converged_tick"],
        "converged_tick_agents": agents["converged_tick"],
        "first_mismatch_tick": first_mismatch,
        "mismatch_detail": detail,
    }


def run_bitmatch(
    n: int,
    writes: int = 2,
    seed: int = 0,
    fanout: int = 3,
    max_transmissions: int = 5,
    backoff_ticks: float = 2.5,
    out_path: Optional[str] = None,
    base_dir: Optional[str] = None,
) -> Dict:
    """Run ``writes`` sequential epidemics on both sides and diff them.

    Each write starts from a different origin on the SAME deterministic
    cluster (state carries over, as it does in a real cluster); the sim
    side replays each epidemic with fresh single-payload state but the
    continuing PRNG streams — exactly what the agents' scheduler does,
    since a quiesced payload leaves no queue state behind.
    """
    params = DetParams(
        n_nodes=n, fanout=fanout, max_transmissions=max_transmissions,
        backoff_ticks=backoff_ticks, seed=seed,
    )
    cluster = DetCluster(params, base_dir=base_dir)
    sim_rng_state: Optional[List] = None
    per_write = []
    try:
        for w in range(writes):
            origin = (w * (n // max(writes, 1))) % n
            agents_trace = run_det_epidemic(cluster, origin, write_id=w)
            assert cluster.quiescent(), "epidemic did not quiesce"
            sim_trace = _sim_with_continued_streams(
                params, origin, sim_rng_state
            )
            sim_rng_state = sim_trace.pop("_rng_state")
            d = diff_det_traces(sim_trace, agents_trace)
            per_write.append({
                "origin": origin,
                **d,
                "msgs_total": (
                    sum(agents_trace["ticks"][-1]["msgs"])
                    if agents_trace["ticks"] else 0
                ),
            })
    finally:
        cluster.close()

    result = {
        "metric": "bitmatch_sim_vs_agents",
        "n_nodes": n,
        "writes": writes,
        "seed": seed,
        "fanout": fanout,
        "max_transmissions": max_transmissions,
        "backoff_ticks": backoff_ticks,
        "bitmatch": all(p["match"] for p in per_write),
        "per_write": per_write,
        "conditions": {
            "agents": (
                "real Agent objects (CRR storage, speedy wire bytes, "
                "seen-cache ingest) under the discrete-event scheduler"
            ),
            "sim": "deterministic replay of the track_sent model",
            "shared": "per-node PRNG streams + tick-backoff mapping",
        },
    }
    if out_path:
        with open(out_path, "w") as f:
            json.dump(result, f, indent=1)
    return result


def _sim_with_continued_streams(
    params: DetParams, origin: int, rng_state: Optional[List]
) -> Dict:
    """Replay one epidemic, carrying PRNG stream state across writes the
    same way the agents' persistent ``_rng`` objects do."""
    n = params.n_nodes
    rngs = [random.Random(det_seed_for(params.seed, i)) for i in range(n)]
    if rng_state is not None:
        for r, st in zip(rngs, rng_state):
            r.setstate(st)
    out = _det_sim_epidemic_with_rngs(params, origin, rngs)
    out["_rng_state"] = [r.getstate() for r in rngs]
    return out


def _det_sim_epidemic_with_rngs(
    params: DetParams, origin: int, rngs: List[random.Random]
) -> Dict:
    """Core replay loop parameterized by live PRNG objects."""
    n, k, max_tx = params.n_nodes, params.fanout, params.max_transmissions
    infected = [False] * n
    infected[origin] = True
    remaining = [0] * n
    remaining[origin] = max_tx
    next_due = [0] * n
    sent_to: List[Set[int]] = [set() for _ in range(n)]
    active = [False] * n
    active[origin] = True
    msgs = [0] * n

    trace: List[Dict] = []
    converged_tick: Optional[int] = None
    for t in range(params.max_ticks):
        deliveries: List[int] = []
        for i in range(n):
            if not active[i] or next_due[i] > t or remaining[i] < 1:
                continue
            pop = [j for j in range(n) if j != i and j not in sent_to[i]]
            if len(pop) <= k:
                targets = pop
            else:
                targets = rngs[i].sample(pop, k)
            if not targets:
                active[i] = False
                continue
            sent_to[i].update(targets)
            msgs[i] += len(targets)
            deliveries.extend(targets)
            remaining[i] -= 1
            if remaining[i] < 1:
                active[i] = False
            else:
                send_count = max_tx - remaining[i]
                next_due[i] = t + det_backoff_gap(
                    params.backoff_ticks, send_count
                )
        for j in deliveries:
            if not infected[j]:
                infected[j] = True
                active[j] = True
                remaining[j] = max_tx
                next_due[j] = t + 1
        trace.append({
            "infected": [i for i in range(n) if infected[i]],
            "msgs": list(msgs),
        })
        if converged_tick is None and all(infected):
            converged_tick = t
        if not any(active):
            break
    return {
        "origin": origin,
        "ticks": trace,
        "converged_tick": converged_tick,
    }
