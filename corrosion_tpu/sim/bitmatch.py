"""Bit-match of the simulator against the real agents (the exactness
half of the north star).

``agent/det.py`` runs N real agents — real CRR storage, real speedy
bytes, real ingest, real sync-need allocation and serving — under a
discrete-event tick scheduler with seeded PRNG streams.  This module is
the **simulator side**: a deterministic replay of the same protocol
model the JAX epidemic kernel implements (per-payload ``sent_to``
exclusion, retransmit-decay budget, backoff-scheduled retransmissions,
rebroadcast-on-learn, ring0-first fanout, per-message loss, periodic
anti-entropy — the headline shape of ``sim/epidemic.py``), drawing
every random decision from the *same* per-node PRNG streams.

The two sides share exactly two pure functions — ``det_seed_for`` (the
per-node stream seed) and ``det_backoff_gap`` (tick backoff) — plus the
sampling *conventions* (``Members.sample``: population in ascending node
index, exclusion filtered before the split, ring0 tier uncapped first;
``_choose_sync_peers``: 2x candidate sample, stable sort by (need,
last-sync, rtt)).  Everything else — who is infected, who may send,
what each ``sent_to`` contains, when budgets exhaust, which server a
sync need is allocated to, every broadcast and sync message count — is
computed independently: the agents through their
storage/bookkeeping/wire/sync pipeline, the sim through this array
state machine.  One diverging decision desynchronizes the PRNG streams
and every later tick, so per-tick equality of infected sets and
per-node message counts is a sharp equivalence test of the protocol
semantics, not a replay of recorded outputs.

``run_bitmatch`` produces the ``BITMATCH_N{64,256}.json`` artifacts
(wired into ``bench.py``), now in the HEADLINE protocol shape: ring0
on, loss on, anti-entropy sync every 8 ticks — the same parameter
family as the benchmarked 100k-node epidemic, not a simplified
fanout-only protocol.

Reference anchors: sent_to sampling + ring0 tier
``broadcast/mod.rs:586-702``, retransmit requeue ``:745-765``,
rebroadcast-on-learn ``handlers.rs:939-949``, sync client round + need
allocation ``peer.rs:1039-1466``.
"""

from __future__ import annotations

import json
import random
from typing import Dict, List, Optional, Set, Tuple

from corrosion_tpu.agent.det import (
    FAR_RTT_MS,
    RING0_RTT_MS,
    DetCluster,
    DetParams,
    det_backoff_gap,
    det_seed_for,
    run_det_epidemic,
)


def det_sim_epidemic(params: DetParams, origin: int) -> Dict:
    """Deterministic replay: the simulator's protocol state machine on
    the shared PRNG streams.  Same trace shape as ``run_det_epidemic``.
    """
    n = params.n_nodes
    rngs = [random.Random(det_seed_for(params.seed, i)) for i in range(n)]
    return _det_sim_epidemic_with_rngs(params, origin, rngs, {}, 0)


def diff_det_traces(sim: Dict, agents: Dict) -> Dict:
    """Tick-for-tick equality of infected sets and per-node broadcast
    AND sync message counts."""
    s_ticks, a_ticks = sim["ticks"], agents["ticks"]
    first_mismatch: Optional[int] = None
    detail: Optional[str] = None
    for t in range(max(len(s_ticks), len(a_ticks))):
        if t >= len(s_ticks) or t >= len(a_ticks):
            first_mismatch = t
            detail = (
                f"trace lengths differ: sim {len(s_ticks)} vs "
                f"agents {len(a_ticks)}"
            )
            break
        if s_ticks[t]["infected"] != a_ticks[t]["infected"]:
            first_mismatch, detail = t, "infected sets differ"
            break
        if s_ticks[t]["msgs"] != a_ticks[t]["msgs"]:
            first_mismatch, detail = t, "per-node msg counts differ"
            break
        if s_ticks[t].get("sync_msgs") != a_ticks[t].get("sync_msgs"):
            first_mismatch, detail = t, "per-node sync msg counts differ"
            break
    return {
        "match": first_mismatch is None,
        "ticks_compared": len(s_ticks),
        "converged_tick_sim": sim["converged_tick"],
        "converged_tick_agents": agents["converged_tick"],
        "first_mismatch_tick": first_mismatch,
        "mismatch_detail": detail,
    }


def run_bitmatch(
    n: int,
    writes: int = 2,
    seed: int = 0,
    fanout: int = 3,
    max_transmissions: int = 5,
    backoff_ticks: float = 2.5,
    loss: float = 0.0,
    ring0_size: int = 0,
    sync_interval: int = 0,
    sync_peers: int = 3,
    out_path: Optional[str] = None,
    base_dir: Optional[str] = None,
) -> Dict:
    """Run ``writes`` sequential epidemics on both sides and diff them.

    Each write starts from a different origin on the SAME deterministic
    cluster (state carries over, as it does in a real cluster); the sim
    side replays each epidemic with fresh single-payload state but the
    continuing PRNG streams, last-sync ordering, and tick offset —
    exactly what the agents' scheduler does, since a quiesced payload
    leaves no queue state behind but the members' sync bookkeeping and
    the absolute tick (which gates the sync cadence) persist.
    """
    params = DetParams(
        n_nodes=n, fanout=fanout, max_transmissions=max_transmissions,
        backoff_ticks=backoff_ticks, seed=seed, loss=loss,
        ring0_size=ring0_size, sync_interval=sync_interval,
        sync_peers=sync_peers,
    )
    cluster = DetCluster(params, base_dir=base_dir)
    rngs = [random.Random(det_seed_for(seed, i)) for i in range(n)]
    last_sync: Dict[Tuple[int, int], float] = {}
    per_write = []
    try:
        for w in range(writes):
            origin = (w * (n // max(writes, 1))) % n
            tick0 = cluster.tick_no
            agents_trace = run_det_epidemic(cluster, origin, write_id=w)
            assert cluster.quiescent(), "epidemic did not quiesce"
            if sync_interval > 0:
                # the sim's cross-write sync model assumes previous
                # epidemics fully converged (everyone holds every prior
                # actor's head, so prior actors generate no needs)
                assert agents_trace["converged_tick"] is not None, (
                    "epidemic did not converge within max_ticks"
                )
            sim_trace = _det_sim_epidemic_with_rngs(
                params, origin, rngs, last_sync, tick0
            )
            d = diff_det_traces(sim_trace, agents_trace)
            per_write.append({
                "origin": origin,
                **d,
                "msgs_total": (
                    sum(agents_trace["ticks"][-1]["msgs"])
                    if agents_trace["ticks"] else 0
                ),
                "sync_msgs_total": (
                    sum(agents_trace["ticks"][-1].get("sync_msgs", []))
                    if agents_trace["ticks"] else 0
                ),
            })
    finally:
        cluster.close()

    result = {
        "metric": "bitmatch_sim_vs_agents",
        "n_nodes": n,
        "writes": writes,
        "seed": seed,
        "fanout": fanout,
        "max_transmissions": max_transmissions,
        "backoff_ticks": backoff_ticks,
        "loss": loss,
        "ring0_size": ring0_size,
        "sync_interval": sync_interval,
        "sync_peers": sync_peers,
        "bitmatch": all(p["match"] for p in per_write),
        "per_write": per_write,
        "conditions": {
            "agents": (
                "real Agent objects (CRR storage, speedy wire bytes, "
                "seen-cache ingest, real sync-need allocation/serving) "
                "under the discrete-event scheduler"
            ),
            "sim": (
                "deterministic replay of the headline protocol model "
                "(ring0-first fanout, per-message loss, track_sent "
                "exclusion, periodic anti-entropy)"
            ),
            "shared": "per-node PRNG streams + tick-backoff mapping",
        },
    }
    if out_path:
        with open(out_path, "w") as f:
            json.dump(result, f, indent=1)
    return result


def _det_sim_epidemic_with_rngs(
    params: DetParams,
    origin: int,
    rngs: List[random.Random],
    last_sync: Dict[Tuple[int, int], float],
    tick0: int,
) -> Dict:
    """Core replay loop parameterized by live PRNG objects, the
    carried-over last-sync ordering state, and the cluster's absolute
    tick offset (the sync cadence runs on absolute ticks)."""
    n, k, max_tx = params.n_nodes, params.fanout, params.max_transmissions
    r0 = params.ring0_size
    infected = [False] * n
    infected[origin] = True
    remaining = [0] * n
    remaining[origin] = max_tx
    next_due = [tick0] * n
    sent_to: List[Set[int]] = [set() for _ in range(n)]
    active = [False] * n
    active[origin] = True
    msgs = [0] * n
    sync_msgs = [0] * n

    def same_block(i: int, j: int) -> bool:
        return r0 > 0 and i // r0 == j // r0

    def rtt(i: int, j: int) -> float:
        if r0 <= 0:
            return float("inf")  # no samples recorded -> rtt None
        return RING0_RTT_MS if same_block(i, j) else FAR_RTT_MS

    trace: List[Dict] = []
    converged_tick: Optional[int] = None
    for lt in range(params.max_ticks):
        t = tick0 + lt  # absolute cluster tick
        # -- send phase (ascending index, one PRNG stream per node) ---
        deliveries: List[int] = []
        for i in range(n):
            if not active[i] or next_due[i] > t or remaining[i] < 1:
                continue
            pop = [j for j in range(n) if j != i and j not in sent_to[i]]
            # ring0-first exactly when the agent does: a LOCAL payload's
            # first transmission (Members.sample ring0_first branch:
            # ALL ring0 peers uncapped + k sampled from the rest; the
            # rest-sample consumes the stream even when it fits)
            if r0 > 0 and i == origin and not sent_to[i]:
                ring0 = [j for j in pop if same_block(i, j)]
                rest = [j for j in pop if not same_block(i, j)]
                targets = ring0 + rngs[i].sample(rest, min(len(rest), k))
            elif len(pop) <= k:
                targets = pop
            else:
                targets = rngs[i].sample(pop, k)
            if not targets:
                active[i] = False
                continue
            for j in targets:
                sent_to[i].add(j)
                # one loss draw per target, in sample order, from the
                # sender's stream — mirrors DetCluster.tick exactly
                if params.loss > 0.0 and rngs[i].random() < params.loss:
                    continue
                deliveries.append(j)
            msgs[i] += len(targets)
            remaining[i] -= 1
            if remaining[i] < 1:
                active[i] = False
            else:
                send_count = max_tx - remaining[i]
                next_due[i] = t + det_backoff_gap(
                    params.backoff_ticks, send_count
                )
        # -- delivery phase (end of tick; learners first send next tick)
        for j in deliveries:
            if not infected[j]:
                infected[j] = True
                active[j] = True
                remaining[j] = max_tx
                next_due[j] = t + 1
        # -- anti-entropy phase (kernel cadence, absolute ticks) -------
        if (
            params.sync_interval > 0
            and t % params.sync_interval == params.sync_interval - 1
        ):
            for i in range(n):
                _sim_sync_round(
                    params, i, t, rngs, infected, sync_msgs, last_sync,
                    rtt, origin,
                )
        trace.append({
            "infected": [i for i in range(n) if infected[i]],
            "msgs": list(msgs),
            "sync_msgs": list(sync_msgs),
        })
        if converged_tick is None and all(infected):
            converged_tick = lt
        if not any(active) and (
            params.sync_interval <= 0 or converged_tick is not None
        ):
            break
    return {
        "origin": origin,
        "ticks": trace,
        "converged_tick": converged_tick,
    }


def _sim_sync_round(
    params: DetParams,
    i: int,
    t: int,
    rngs: List[random.Random],
    infected: List[bool],
    sync_msgs: List[int],
    last_sync: Dict[Tuple[int, int], float],
    rtt,
    origin: int,
) -> None:
    """The replay of one client sync round — mirrors
    ``DetCluster._det_sync_round`` decision for decision.

    Knowledge model: the current epidemic's payload is all a sync can
    move (prior writes fully converged — asserted by ``run_bitmatch`` —
    so prior actors' heads are equal everywhere and generate no needs;
    ``need_len_for_actor`` is 0 for every peer because single-version
    histories have no recorded gaps)."""
    n = params.n_nodes
    peers = [j for j in range(n) if j != i]
    desired = max(min(len(peers) // 100, 10), min(3, len(peers)))
    desired = min(desired, params.sync_peers)
    cands = rngs[i].sample(peers, min(desired * 2, len(peers)))
    cands.sort(key=lambda j: (0, last_sync.get((i, j), 0.0), rtt(i, j)))
    chosen = cands[:desired]
    if not chosen:
        return
    for j in chosen:
        sync_msgs[i] += 2  # BiPayload + Clock
        sync_msgs[j] += 2  # State + Clock
    if not infected[i]:
        # the single need (current actor, full head range) is allocated
        # to the FIRST session whose server advertises it; one Request
        # frame from the client, one served changeset frame back
        for j in chosen:
            if infected[j]:
                sync_msgs[i] += 1
                sync_msgs[j] += 1
                infected[i] = True
                break
    for j in chosen:
        last_sync[(i, j)] = float(t)
