"""Anti-entropy reassembly simulation (BASELINE.md config #4).

10k nodes, periodic sync with subset peer selection, broadcast disabled:
one writer holds a chunked changeset and every other node reassembles it
purely through sync rounds — chunk-budgeted sessions, per-chunk loss,
out-of-order arrival, gap healing — using the vectorized seq-bitmap
kernel (:func:`corrosion_tpu.models.sync.seq_sync_step`).

Reference behavior: ``crates/corro-agent/src/api/peer.rs`` (chunked
serving, partial buffering) + ``agent/handlers.rs`` sync scheduling.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from corrosion_tpu.models.sync import SeqSyncParams, seq_sync_step
from corrosion_tpu.sim.epidemic import seed_convergence


@dataclass(frozen=True)
class AntiEntropyConfig:
    n_nodes: int = 10_000
    n_seqs: int = 64  # seqs in the disseminating changeset
    peers_per_round: int = 1
    seqs_per_chunk: int = 8
    chunk_budget: int = 4
    loss: float = 0.02  # per-chunk drop (exercises gap healing)
    max_ticks: int = 96
    chunk_ticks: int = 8
    # seed-flattening (models/common.py): S universes side by side so
    # every gather/scatter in the round runs unbatched
    n_universes: Optional[int] = None

    @property
    def flat_nodes(self) -> int:
        return self.n_nodes * (self.n_universes or 1)

    @property
    def params(self) -> SeqSyncParams:
        return SeqSyncParams(
            n_nodes=self.flat_nodes,
            n_seqs=self.n_seqs,
            peers_per_round=self.peers_per_round,
            seqs_per_chunk=self.seqs_per_chunk,
            chunk_budget=self.chunk_budget,
            loss=self.loss,
            universe=self.n_nodes if self.n_universes else None,
        )


def anti_entropy_init(cfg: AntiEntropyConfig, writer: int = 0):
    writers = (
        writer
        + jnp.arange(cfg.n_universes or 1, dtype=jnp.int32) * cfg.n_nodes
    )
    bits = jnp.zeros((cfg.flat_nodes, cfg.n_seqs), bool).at[writers].set(True)
    msgs = jnp.zeros((cfg.flat_nodes,), jnp.int32)
    return bits, msgs


@partial(jax.jit, static_argnames=("cfg",))
def _scan_chunk(carry, seed_key, start_tick, cfg: AntiEntropyConfig):
    S = cfg.n_universes or 1

    def body(c, i):
        bits, msgs = c
        key = jax.random.fold_in(seed_key, start_tick + i)
        bits, msgs = seq_sync_step(bits, msgs, key, cfg.params)
        converged = jnp.all(
            bits.reshape(S, cfg.n_nodes, cfg.n_seqs), axis=(1, 2)
        )
        m_mean = jnp.mean(
            msgs.astype(jnp.float32).reshape(S, cfg.n_nodes), axis=1
        )
        return (bits, msgs), (converged, m_mean)

    return jax.lax.scan(body, carry, jnp.arange(cfg.chunk_ticks))


def run_anti_entropy_seeds(cfg: AntiEntropyConfig, n_seeds: int = 16,
                           seed: int = 0):
    """Multi-universe run (seed-flattened); convergence stats."""
    from dataclasses import replace

    flat_cfg = replace(cfg, n_universes=n_seeds)
    key = jax.random.PRNGKey(seed)
    carry = anti_entropy_init(flat_cfg)

    t0 = time.perf_counter()
    flags, means = [], []
    ticks_done = 0
    while ticks_done < cfg.max_ticks:
        carry, (conv, m_mean) = _scan_chunk(carry, key, ticks_done, flat_cfg)
        conv = np.asarray(conv).T  # scan stacks [C, S] -> [S, C]
        flags.append(conv)
        means.append(np.asarray(m_mean).T)
        ticks_done += cfg.chunk_ticks
        if conv[:, -1].all():
            break
    wall = time.perf_counter() - t0

    allflags = np.concatenate(flags, axis=1)  # [S, T]
    allmeans = np.concatenate(means, axis=1)
    converged, first_idx, first = seed_convergence(allflags)
    rows = np.arange(n_seeds)
    msgs_at_conv = allmeans[rows, first_idx]
    return {
        "n_nodes": cfg.n_nodes,
        "n_seeds": n_seeds,
        "converged_frac": float(converged.mean()),
        "ticks_p50": float(np.percentile(first, 50)),
        "ticks_p99": float(np.percentile(first, 99)),
        "msgs_per_node_mean": float(msgs_at_conv.mean()),
        "wall_s": wall,
        "ticks_run": ticks_done,
    }
