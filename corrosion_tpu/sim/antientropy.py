"""Anti-entropy reassembly simulation (BASELINE.md config #4).

10k nodes, periodic sync with subset peer selection, broadcast disabled:
one writer holds a chunked changeset and every other node reassembles it
purely through sync rounds — chunk-budgeted sessions, per-chunk loss,
out-of-order arrival, gap healing — using the vectorized seq-bitmap
kernel (:func:`corrosion_tpu.models.sync.seq_sync_step`).

Reference behavior: ``crates/corro-agent/src/api/peer.rs`` (chunked
serving, partial buffering) + ``agent/handlers.rs`` sync scheduling.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from corrosion_tpu.models.sync import SeqSyncParams, seq_sync_step
from corrosion_tpu.sim.epidemic import seed_convergence


@dataclass(frozen=True)
class AntiEntropyConfig:
    n_nodes: int = 10_000
    n_seqs: int = 64  # seqs in the disseminating changeset
    peers_per_round: int = 1
    seqs_per_chunk: int = 8
    chunk_budget: int = 4
    loss: float = 0.02  # per-chunk drop (exercises gap healing)
    max_ticks: int = 96
    chunk_ticks: int = 8

    @property
    def params(self) -> SeqSyncParams:
        return SeqSyncParams(
            n_nodes=self.n_nodes,
            n_seqs=self.n_seqs,
            peers_per_round=self.peers_per_round,
            seqs_per_chunk=self.seqs_per_chunk,
            chunk_budget=self.chunk_budget,
            loss=self.loss,
        )


def anti_entropy_init(cfg: AntiEntropyConfig, writer: int = 0):
    bits = jnp.zeros((cfg.n_nodes, cfg.n_seqs), bool).at[writer].set(True)
    msgs = jnp.zeros((cfg.n_nodes,), jnp.int32)
    return bits, msgs


@partial(jax.jit, static_argnames=("cfg",))
def _scan_chunk(carry, seed_key, start_tick, cfg: AntiEntropyConfig):
    def body(c, i):
        bits, msgs = c
        key = jax.random.fold_in(seed_key, start_tick + i)
        bits, msgs = seq_sync_step(bits, msgs, key, cfg.params)
        converged = jnp.all(bits)
        return (bits, msgs), (converged, jnp.mean(msgs.astype(jnp.float32)))

    return jax.lax.scan(body, carry, jnp.arange(cfg.chunk_ticks))


def run_anti_entropy_seeds(cfg: AntiEntropyConfig, n_seeds: int = 16,
                           seed: int = 0):
    """Vmapped multi-universe run; convergence distribution stats."""
    keys = jax.random.split(jax.random.PRNGKey(seed), n_seeds)
    bits, msgs = anti_entropy_init(cfg)
    carry = (
        jnp.broadcast_to(bits, (n_seeds,) + bits.shape),
        jnp.broadcast_to(msgs, (n_seeds,) + msgs.shape),
    )
    chunk = jax.vmap(
        lambda c, k, t: _scan_chunk(c, k, t, cfg), in_axes=(0, 0, None)
    )

    t0 = time.perf_counter()
    flags, means = [], []
    ticks_done = 0
    while ticks_done < cfg.max_ticks:
        carry, (conv, m_mean) = chunk(carry, keys, ticks_done)
        conv = np.asarray(conv)  # [S, C]
        flags.append(conv)
        means.append(np.asarray(m_mean))
        ticks_done += cfg.chunk_ticks
        if conv[:, -1].all():
            break
    wall = time.perf_counter() - t0

    allflags = np.concatenate(flags, axis=1)  # [S, T]
    allmeans = np.concatenate(means, axis=1)
    converged, first_idx, first = seed_convergence(allflags)
    rows = np.arange(n_seeds)
    msgs_at_conv = allmeans[rows, first_idx]
    return {
        "n_nodes": cfg.n_nodes,
        "n_seeds": n_seeds,
        "converged_frac": float(converged.mean()),
        "ticks_p50": float(np.percentile(first, 50)),
        "ticks_p99": float(np.percentile(first, 99)),
        "msgs_per_node_mean": float(msgs_at_conv.mean()),
        "wall_s": wall,
        "ticks_run": ticks_done,
    }
