"""SWIM churn calibration: the model's failure-detection latency vs
REAL agents.

Round-3 review: the churn bench's detection latency "has no reference
anchor to validate against".  This harness supplies the anchor: boot N
real agents with ACTIVE SWIM probing (binary foca datagrams on
loopback), crash one, and measure the wall time until every survivor
holds a DOWN record; then relaunch it from the same data dir and
measure rejoin propagation.  The sim side runs the vmapped SWIM model
(``models/swim.py``) under the SAME cluster-size-scaled parameters
(``utils/swimscale.py``), and both sides are compared in PROBE-PERIOD
units — the model's tick is one probe interval by construction.

What matches by design: the suspicion deadline (both sides scale it as
``suspicion_mult * ceil(log10(n+1))`` probe periods — the host's
configured floor is set to 0 here so the scaled term governs) and the
dissemination mechanics (freshness-prioritized piggyback with decay on
both sides).  The residual is the host's timer jitter and the fact
that a host probe round-trip is wall-asynchronous where the model's is
tick-synchronous.
"""

from __future__ import annotations

import asyncio
import json
import time
from typing import Dict, Optional


async def host_churn_trace(
    n: int = 64,
    probe_interval: float = 0.15,
    timeout: float = 60.0,
    base_dir: Optional[str] = None,
    cycles: int = 1,
) -> Dict:
    """Repeated crash + rejoin cycles on N real agents (BASELINE
    config #2's join/suspect/leave cycles — a different victim each
    cycle); latencies in probe-period units (directly comparable to
    model ticks)."""
    from corrosion_tpu.agent.members import MemberState
    from corrosion_tpu.agent.testing import (
        launch_test_agent,
        seed_full_membership,
        wait_for,
    )

    agents = []
    common = dict(
        probe_interval=probe_interval,
        probe_timeout=probe_interval * 0.8,
        # one gossip round (3 targets) per probe period mirrors the
        # model's per-tick gossip exactly; pinning it PROPORTIONALLY
        # keeps the anchor invariant to the probe_interval argument
        gossip_interval=probe_interval,
        suspect_timeout=0.0,  # floor off: the scaled deadline governs
        # quiesce everything that is not membership
        sync_interval_min=3600.0,
        sync_interval_max=7200.0,
        maintenance_interval=3600.0,
        subs_enabled=False,
        api_port=None,
        uni_cache_size=8,
    )
    try:
        for i in range(n):
            agents.append(await launch_test_agent(
                bootstrap=[],
                tmpdir=None if base_dir is None else f"{base_dir}/n{i}",
                **common,
            ))
        seed_full_membership(agents)
        # let a few probe rounds pass so the cluster is steady
        await asyncio.sleep(probe_interval * 4)

        per_cycle = []
        for c in range(cycles):
            vi = n - 1 - c  # a different victim each cycle
            victim = agents[vi]
            victim_actor = victim.actor_id
            victim_dir = victim.config.db_path.rsplit("/", 1)[0]
            survivors = [a for j, a in enumerate(agents) if j != vi]

            t0 = time.perf_counter()
            await victim.stop(graceful=False)  # crash

            def down_everywhere():
                for a in survivors:
                    m = a.members.get(victim_actor)
                    if m is None or m.state is not MemberState.DOWN:
                        return False
                return True

            await wait_for(down_everywhere, timeout=timeout, interval=0.02)
            detect_wall = time.perf_counter() - t0

            # rejoin: same data dir = same identity, renewed generation
            t1 = time.perf_counter()
            reborn = await launch_test_agent(
                tmpdir=victim_dir,
                bootstrap=[
                    f"{survivors[0].gossip_addr[0]}:"
                    f"{survivors[0].gossip_addr[1]}"
                ],
                **common,
            )
            agents[vi] = reborn
            assert reborn.actor_id == victim_actor

            def alive_everywhere():
                for a in survivors:
                    m = a.members.get(victim_actor)
                    if m is None or m.state is not MemberState.ALIVE:
                        return False
                return True

            await wait_for(alive_everywhere, timeout=timeout, interval=0.02)
            rejoin_wall = time.perf_counter() - t1
            per_cycle.append({
                "detect_wall_s": round(detect_wall, 3),
                "rejoin_wall_s": round(rejoin_wall, 3),
                "detect_probe_periods": round(
                    detect_wall / probe_interval, 1),
                "rejoin_probe_periods": round(
                    rejoin_wall / probe_interval, 1),
            })
            # settle before the next cycle
            await asyncio.sleep(probe_interval * 2)

        mean_d = sum(c["detect_probe_periods"] for c in per_cycle) / len(
            per_cycle
        )
        mean_r = sum(c["rejoin_probe_periods"] for c in per_cycle) / len(
            per_cycle
        )
        return {
            "runtime": "agents",
            "n_nodes": n,
            "cycles": cycles,
            "probe_interval_s": probe_interval,
            "per_cycle": per_cycle,
            "detect_probe_periods": round(mean_d, 1),
            "rejoin_probe_periods": round(mean_r, 1),
            "conditions": {
                "wire": "binary foca datagrams over UDP loopback",
                "suspicion": "scaled deadline only (floor 0)",
                "membership": "pre-seeded; sync/maintenance quiesced",
            },
        }
    finally:
        await asyncio.gather(
            *(a.stop() for a in agents), return_exceptions=True
        )


def model_churn_trace(n: int = 64, cycles: int = 1) -> Dict:
    """The SWIM model's churn cycles under the same scaled parameters;
    latencies already in ticks (= probe periods)."""
    from corrosion_tpu.sim.churn import (
        ChurnConfig,
        run_churn,
        run_churn_cycles,
    )

    if cycles <= 1:
        stats = run_churn(ChurnConfig(n_nodes=n))
        return {
            "runtime": "tpu-sim",
            "n_nodes": n,
            "detect_ticks": stats["detect_latency"],
            "rejoin_ticks": stats["rejoin_latency"],
            "msgs_per_node_per_tick": round(
                stats["msgs_per_node_per_tick"], 2),
        }
    # cycle windows sized to the scaled suspicion deadline so a cold
    # first cycle still resolves inside its own window
    period = 48 if n <= 64 else 72
    stats = run_churn_cycles(ChurnConfig(
        n_nodes=n, cycles=cycles, cycle_period=period,
        kill_tick=4, revive_tick=period - 16,
    ))
    return {
        "runtime": "tpu-sim",
        "n_nodes": n,
        "cycles": cycles,
        "per_cycle": stats["per_cycle"],
        "detect_ticks": stats["detect_latency_mean"],
        "rejoin_ticks": stats["rejoin_latency_mean"],
        "msgs_per_node_per_tick": round(
            stats["msgs_per_node_per_tick"], 2),
    }


async def run_churndiff(
    n: int = 64,
    probe_interval: float = 0.15,
    out_path: Optional[str] = None,
    base_dir: Optional[str] = None,
    cycles: int = 1,
    timeout: float = 60.0,
) -> Dict:
    host = await host_churn_trace(
        n, probe_interval=probe_interval, base_dir=base_dir,
        cycles=cycles, timeout=timeout,
    )
    model = model_churn_trace(n, cycles=cycles)

    def ratio(a, b):
        if a is None or b is None or not b:
            return None
        return round(a / b, 2)

    # steady-state = cycles after the first: BOTH sides show a colder,
    # slower first cycle (the update backlog is saturated with the
    # initial membership records, crowding the victim's suspicion out
    # of gossip selection), so cycle 0 measures backlog decay, not the
    # detection pipeline
    steady = {}
    if cycles > 1 and "per_cycle" in model:
        hd = [c["detect_probe_periods"] for c in host["per_cycle"][1:]]
        hr = [c["rejoin_probe_periods"] for c in host["per_cycle"][1:]]
        md = [c["detect_latency"] for c in model["per_cycle"][1:]
              if c["detect_latency"] is not None]
        mr = [c["rejoin_latency"] for c in model["per_cycle"][1:]
              if c["rejoin_latency"] is not None]
        if hd and md:
            steady["steady_detect_ratio"] = ratio(
                sum(hd) / len(hd), sum(md) / len(md))
        if hr and mr:
            steady["steady_rejoin_ratio"] = ratio(
                sum(hr) / len(hr), sum(mr) / len(mr))

    result = {
        "n_nodes": n,
        "cycles": cycles,
        "host": host,
        "model": model,
        "diff": {
            "detect_ratio_host_over_model": ratio(
                host["detect_probe_periods"], model["detect_ticks"]
            ),
            "rejoin_ratio_host_over_model": ratio(
                host["rejoin_probe_periods"], model["rejoin_ticks"]
            ),
            **steady,
            "residual_note": (
                "with per-node suspicion timers and the periodic "
                "gossip loop (foca periodic_gossip parity, pinned at "
                "one 3-target round per probe period to mirror the "
                "model's per-tick gossip) the host detect latency "
                "lands within a few percent of the model's tick count "
                "(ratio ~1.0): the host's real probe-timeout chain "
                "is roughly offset by the model's synchronous-round "
                "pessimism.  The round-4 rejoin residual (0.62, a "
                "MISSING model path) is closed structurally: the "
                "model now runs the host's announce-to-seed on "
                "revival and the probe/ack piggyback channels "
                "(models/swim.py), lifting the ratio to ~0.75-0.85.  "
                "What remains is tick granularity, not protocol: the "
                "model is round-synchronous (a record received this "
                "tick forwards NEXT tick), while the host forwards "
                "within the same probe period — about one tick of "
                "store-and-forward lag on a 2-3 tick propagation.  "
                "Building this anchor caught three real host gaps, "
                "all fixed: ts=0 piggybacked records dropped as stale "
                "generations, gossip-learned suspicions never arming "
                "the local suspicion timer, and dissemination riding "
                "only on probe/ack piggyback with no dedicated gossip "
                "cadence"
            ),
        },
    }
    if out_path:
        with open(out_path, "w") as f:
            json.dump(result, f, indent=1, allow_nan=False)
    return result


def main() -> None:  # pragma: no cover - artifact generator
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--n", type=int, default=64)
    ap.add_argument("--cycles", type=int, default=3)
    ap.add_argument("--probe-interval", type=float, default=0.15)
    ap.add_argument("--timeout", type=float, default=60.0)
    ap.add_argument("--out", type=str, default=None)
    args = ap.parse_args()
    out = args.out or f"CHURNDIFF_N{args.n}.json"
    r = asyncio.run(run_churndiff(
        args.n, probe_interval=args.probe_interval, out_path=out,
        cycles=args.cycles, timeout=args.timeout,
    ))
    print(json.dumps(r["diff"], indent=1))


if __name__ == "__main__":  # pragma: no cover
    main()
