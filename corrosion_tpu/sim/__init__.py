"""The tpu-sim runtime: whole-cluster simulation as one array program.

This is the north-star path (BASELINE.json): instead of spawning N tokio
agents over loopback QUIC like ``corro-devcluster``, the cluster IS the
tensor — node state lives in HBM, every gossip/sync/SWIM tick is one jitted
step over the node axis, and independent seeds ("parallel universes") are
vmapped to get p99 convergence distributions from a single scan.
"""

from corrosion_tpu.sim.epidemic import (
    EpidemicConfig,
    EpidemicState,
    epidemic_init,
    epidemic_tick,
    run_epidemic,
    run_epidemic_seeds,
)
from corrosion_tpu.sim.churn import ChurnConfig, run_churn
from corrosion_tpu.sim.chaos import run_chaos
from corrosion_tpu.sim.antientropy import (
    AntiEntropyConfig,
    run_anti_entropy_seeds,
)

__all__ = [
    "AntiEntropyConfig",
    "run_anti_entropy_seeds",
    "EpidemicConfig",
    "EpidemicState",
    "epidemic_init",
    "epidemic_tick",
    "run_epidemic",
    "run_epidemic_seeds",
    "ChurnConfig",
    "run_churn",
    "run_chaos",
]
