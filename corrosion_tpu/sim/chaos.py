"""Chaos soak: the live cluster under the sim's fault family, diffed
against the sim's degraded-mode prediction.

SIMDIFF calibrates the fault-free broadcast path; this is its
faulted-regime analogue.  The same (loss, partition, churn) parameter
family that drives the epidemic kernel's headline config (5% loss +
partition heal, ``sim/epidemic.py``) is mapped onto a
:class:`~corrosion_tpu.faults.FaultPlan` and injected into a real
N-node in-process cluster (``devcluster.run_inprocess``), and both
sides report the north-star quantities — convergence time and
msgs/node — side by side in one JSON artifact (``CHAOS_N32.json``).

Mapping (recorded in the artifact):

* ``loss``             → ``FaultPlan.drop`` on uni + udp channels
  (in-flight loss: the sender believes it sent);
* ``partition_blocks`` → ``FaultPlan.partition_blocks`` (same
  index→block function as the sim's ``_partition_ids``);
* ``heal_tick``        → ``FaultPlan.heal_after = heal_tick * tick_s``
  where one tick ≈ the agents' flush interval (the simdiff time base);
* churn                → ``FaultPlan.crashes``: a node crashes
  mid-epidemic and restarts, catching up through anti-entropy.  The
  epidemic kernel does not model data-plane node death (that lives in
  the SWIM churn kernel), so the crash leg is agent-side-only and the
  sim prediction covers the loss+partition legs — noted in the diff.

Convergence here is *through* the faults: writes land on both sides of
the split before it heals, so only anti-entropy + rebroadcast can
reach the union — exactly the degraded mode the hardening (bounded
redials, circuit breaker, partial-round sync retry) exists for.
"""

from __future__ import annotations

import asyncio
import json
import time
from typing import Dict, Optional


def sim_chaos_trace(
    n: int,
    loss: float = 0.05,
    partition_blocks: int = 2,
    heal_tick: int = 32,
    fanout: int = 3,
    max_transmissions: int = 5,
    seeds: int = 8,
    oneway_blocks=None,
    track_sent: bool = None,
) -> Dict:
    """Epidemic-kernel prediction for the faulted regime: loss +
    partition-heal with anti-entropy enabled (the headline family at
    chaos scale)."""
    from corrosion_tpu.sim.epidemic import EpidemicConfig, run_epidemic_seeds

    cfg = EpidemicConfig(
        n_nodes=n,
        n_rows=4,
        fanout_ring0=0,
        fanout_global=fanout,
        ring0_size=1,  # agents sample uniformly under quarantine too
        max_transmissions=max_transmissions,
        loss=loss,
        partition_blocks=partition_blocks,
        heal_tick=heal_tick,
        oneway_blocks=(
            tuple(tuple(p) for p in oneway_blocks)
            if oneway_blocks else None
        ),
        backoff_ticks=2.5,  # the agents' rebroadcast_delay/flush ratio
        # the exact sent_to-excluding sampler carries [N, N] memory and
        # the slow vmap path: calibration-scale only.  Past ~128 nodes
        # (the virtual campaigns' N=512 predictions) the flat
        # perm-fanout path predicts the same coverage dynamics with
        # msgs as a documented lower bound (models/broadcast.py)
        track_sent=(n <= 128) if track_sent is None else track_sent,
        sync_interval=8,  # anti-entropy must heal what faults dropped
        sync_peers=1,
        max_ticks=512,
        chunk_ticks=16,
    )
    stats = run_epidemic_seeds(cfg, n_seeds=seeds, seed=0)
    import math

    def fin(v):
        return None if v is None or not math.isfinite(v) else v

    return {
        "runtime": "tpu-sim",
        "n_nodes": n,
        "loss": loss,
        "partition_blocks": partition_blocks,
        "heal_tick": heal_tick,
        "oneway_blocks": (
            [list(p) for p in oneway_blocks] if oneway_blocks else None
        ),
        "converged_frac": stats["converged_frac"],
        "ticks_to_converge_p50": fin(stats["ticks_p50"]),
        "ticks_to_converge_p99": fin(stats["ticks_p99"]),
        "msgs_per_node": stats["msgs_per_node_mean"],
        "wall_s": stats["wall_s"],
    }


async def agent_chaos_trace(
    n: int,
    loss: float = 0.05,
    partition_blocks: int = 2,
    heal_after: float = 0.64,
    crash_at: float = 0.2,
    restart_at: float = 1.2,
    fanout: int = 3,
    max_transmissions: int = 5,
    seed: int = 0,
    timeout: float = 90.0,
    base_dir: Optional[str] = None,
) -> Dict:
    """Boot n real agents, subject them to the FaultPlan, and measure
    convergence of a split-brain write pair through the fault regime."""
    from corrosion_tpu.agent.testing import seed_full_membership, wait_for
    from corrosion_tpu.devcluster import (
        Topology,
        run_crash_schedule,
        run_inprocess,
    )
    from corrosion_tpu.faults import CrashEvent, FaultController, FaultPlan

    victim = f"n{n - 1}"  # last node: never a writer, crashes mid-run
    plan = FaultPlan(
        seed=seed,
        drop=loss,
        partition_blocks=partition_blocks,
        heal_after=heal_after,
        crashes=(CrashEvent(victim, at=crash_at, restart_at=restart_at),),
    )
    ctrl = FaultController(plan)
    crash_task = None
    topo = Topology.parse(
        "\n".join(f"n0 -> n{i}" for i in range(1, n))
    )
    agents = await run_inprocess(
        topo,
        base_dir=base_dir,
        faults=ctrl,
        fanout=fanout,
        max_transmissions=max_transmissions,
        ring0_enabled=False,  # uniform sampling: the sim's model
        # faults must not down-mark the whole cluster mid-measurement;
        # failure detection is exercised by the crash leg only
        suspect_timeout=10.0,
        breaker_cooldown=0.5,  # post-heal recovery inside the budget
        subs_enabled=False,
        api_port=None,
        uni_cache_size=16,  # n agents share one process's fd budget
    )
    try:
        await wait_for(
            lambda: all(
                len(a.members.alive()) == n - 1 for a in agents.values()
            ),
            timeout=30,
        )
        # full membership so the epidemic (not SWIM dissemination) is
        # the measured quantity — the simdiff matched condition
        seed_full_membership(list(agents.values()))

        def msgs_total() -> int:
            return sum(
                int(a.metrics.get_counter("corro_broadcast_sent_total")
                    or 0)
                + int(a.metrics.get_counter("corro_sync_served_total")
                      or 0)
                for a in agents.values()
            )

        base_msgs = msgs_total()
        ctrl.restart_clock()
        ctrl.split()
        crash_task = asyncio.ensure_future(run_crash_schedule(ctrl))
        t0 = time.perf_counter()
        # one write on each side of the split: only the fault-tolerant
        # machinery (rebroadcast + anti-entropy after heal, restart
        # catch-up) can reach the union
        left = agents["n0"]
        right_name = f"n{(n // partition_blocks)}" if partition_blocks > 1 \
            else "n1"
        right = agents[right_name]
        versions = []
        for writer, text in ((left, "chaos-left"), (right, "chaos-right")):
            res = writer.execute_transaction(
                [("INSERT INTO tests (id, text) VALUES (?, ?)",
                  (9000 + len(versions), text))]
            )
            versions.append((writer.actor_id, res["version"]))

        def converged() -> bool:
            for a in agents.values():
                for actor, v in versions:
                    if a.actor_id != actor and not a.bookie.for_actor(
                        actor
                    ).contains_version(v):
                        return False
            return True

        await wait_for(converged, timeout=timeout, interval=0.02)
        wall = time.perf_counter() - t0
        await asyncio.wait_for(crash_task, timeout=timeout)

        stats = {"faults_dropped": 0, "redials": 0, "breaker_opens": 0,
                 "failures": 0}
        for a in agents.values():
            for st in a.transport.stats.values():
                for k in stats:
                    stats[k] += getattr(st, k)
        return {
            "runtime": "agents",
            "n_nodes": n,
            "converged_frac": 1.0,
            "wall_to_converge_s": round(wall, 3),
            "msgs_per_node": round((msgs_total() - base_msgs) / n, 2),
            "injected": dict(ctrl.injected),
            "crash_log": [
                {"t": round(t, 3), "event": ev, "node": node}
                for t, ev, node in ctrl.crash_log
            ],
            "transport": stats,
            "conditions": {
                "ring0_enabled": False,
                "membership": "pre-seeded after formation",
                "writes": "one per partition side, pre-heal",
                "victim": victim,
            },
        }
    finally:
        # a convergence timeout must not leave the crash scheduler
        # alive: it would respawn the victim AFTER the loop below has
        # stopped everything, leaking a fully started agent
        if crash_task is not None and not crash_task.done():
            crash_task.cancel()
            try:
                await crash_task
            except (asyncio.CancelledError, Exception):
                pass
        for a in list(agents.values()):
            try:
                await a.stop()
            except Exception:
                pass


async def run_chaos(
    n: int = 32,
    loss: float = 0.05,
    partition_blocks: int = 2,
    heal_tick: int = 32,
    tick_s: float = 0.02,
    seeds: int = 8,
    out_path: Optional[str] = None,
    base_dir: Optional[str] = None,
) -> Dict:
    """The chaos soak: sim prediction + live faulted cluster, one JSON."""
    sim = sim_chaos_trace(
        n, loss=loss, partition_blocks=partition_blocks,
        heal_tick=heal_tick, seeds=seeds,
    )
    heal_after = heal_tick * tick_s
    ag = await agent_chaos_trace(
        n, loss=loss, partition_blocks=partition_blocks,
        heal_after=heal_after,
        crash_at=heal_after * 0.3,
        restart_at=heal_after + 0.6,
        base_dir=base_dir,
    )
    sim_wall = (
        sim["ticks_to_converge_p50"] * tick_s
        if sim["ticks_to_converge_p50"] is not None else None
    )
    result = {
        "n_nodes": n,
        "fault_family": {
            "loss": loss,
            "partition_blocks": partition_blocks,
            "heal_tick": heal_tick,
            "tick_seconds": tick_s,
            "heal_after_s": heal_after,
            "churn": "one crash+restart (agent side only; the epidemic "
                     "kernel models loss+partition — node death lives "
                     "in the SWIM churn kernel)",
        },
        "sim": sim,
        "agents": ag,
        "diff": {
            "sim_predicted_wall_s_p50": (
                round(sim_wall, 3) if sim_wall is not None else None
            ),
            "agents_wall_s": ag["wall_to_converge_s"],
            "msgs_per_node_ratio": (
                round(sim["msgs_per_node"]
                      / max(ag["msgs_per_node"], 1e-9), 3)
                if ag["msgs_per_node"] else None
            ),
            "both_converged": (
                sim["converged_frac"] == 1.0
                and ag["converged_frac"] == 1.0
            ),
            "residual_note": (
                "the agent side additionally carries a crash/restart "
                "(catch-up via anti-entropy) and real breaker/backoff "
                "dynamics the tick-grid kernel does not model, so its "
                "wall clock reads above the pure loss+partition "
                "prediction; msgs/node compares the same quantities "
                "as SIMDIFF"
            ),
        },
    }
    if out_path:
        with open(out_path, "w") as f:
            json.dump(result, f, indent=1, allow_nan=False)
    return result
