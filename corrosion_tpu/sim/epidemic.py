"""Epidemic broadcast + anti-entropy convergence simulation.

Covers BASELINE.md configs #3 (1k-node fanout + LWW convergence), #4
(10k-node anti-entropy) and #5 (100k-node epidemic, 5% loss + partition
heal).  A writer commits one changeset; gossip fanout with retransmit
decay spreads it; periodic anti-entropy heals what loss/partitions
dropped; the run converges when every node's CRDT row state equals the
join of all writes.

The measured quantities are the north-star metrics: ticks (protocol
rounds) to convergence and messages per node.

TPU design notes:

* one tick = one fused jitted function (fanout draw + scatter-max + decay
  + masked sync) over [N]- and [N, R]-shaped arrays;
* ``lax.scan`` over a chunk of ticks keeps the host out of the loop; the
  host only checks the per-chunk convergence flags (cheap bool transfer)
  and stops scanning — a fixed-shape alternative to ``while_loop`` that
  still lets XLA pipeline across ticks;
* independent seeds are ``vmap``-ed into parallel universes, so a p99
  over 64 cluster runs costs one scan instead of 64 devcluster boots.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, replace
from functools import partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from corrosion_tpu.models.broadcast import (
    HOP_UNSET,
    BroadcastParams,
    broadcast_step,
)
from corrosion_tpu.models.sync import SyncParams, sync_step
from corrosion_tpu.ops.keys import DEFAULT_CODEC


@dataclass(frozen=True)
class EpidemicConfig:
    n_nodes: int
    n_rows: int = 8  # CRDT cells carried by the changeset
    fanout_ring0: int = 2
    fanout_global: int = 2
    ring0_size: int = 256
    max_transmissions: int = 8
    loss: float = 0.0
    # partition: nodes are split into `partition_blocks` blocks whose
    # cross-traffic is dropped until `heal_tick`
    partition_blocks: int = 1
    heal_tick: int = 0
    # one-way partitions (the asym_partition scenario family): exactly
    # these directed (src_block, dst_block) pairs sever while the
    # partition is active; None = symmetric.  Gossip severs per listed
    # direction; anti-entropy sessions need both directions up
    oneway_blocks: Optional[tuple] = None
    # nth retransmission waits backoff_ticks*n (reference 100ms*n);
    # 0 = send every tick (synchronous rounds)
    backoff_ticks: float = 0.0
    # model the agents' per-payload sent_to exclusion exactly ([N, N]
    # memory — calibration-scale only; see broadcast_step's sent arg)
    track_sent: bool = False
    # infection-depth (hop) tracking: needed by the sim-vs-agent
    # calibration (simdiff) but not by the convergence metrics; the
    # scatter-min it needs lowers to a slow serialized path on TPU at
    # 100k nodes (~80% of the headline tick), so large-N configs whose
    # outputs don't include hops turn it off
    track_hops: bool = True
    # anti-entropy cadence (0 = disabled)
    sync_interval: int = 8
    sync_peers: int = 1
    cells_per_chunk: int = 64
    max_ticks: int = 256
    chunk_ticks: int = 16  # scan chunk between host convergence checks

    # seed-flattening (models/common.py): S universes of n_nodes laid
    # side by side in one flat index space; None = single universe
    n_universes: Optional[int] = None

    # scenario families beyond uniform fanout (models/broadcast.py and
    # the exact kernels' HeadlineExactConfig carry the same fields):
    # - ``het_ring``: node i sits on RTT tier 1 + i*rtt_tiers//n of a
    #   ring by id; its retransmit cadence (and first post-learn
    #   forward) scales with the tier — the convergence tail is driven
    #   by the slow arc of the ring;
    # - ``wan_two_region``: node i lives in region i*wan_blocks//n;
    #   gossip crossing regions suffers an EXTRA i.i.d. drop of
    #   ``wan_cross_loss`` on top of ``loss``, while anti-entropy
    #   sessions cross unharmed (QUIC streams with retries).
    # - ``measured_ring``: het_ring with a data-driven tier map from a
    #   measured Members RTT-ring distribution (``rtt_tier_weights`` =
    #   per-tier node-count weights; ``corro admin rtt dump`` emits
    #   them).
    topology: str = "uniform"
    rtt_tiers: int = 4
    wan_blocks: int = 2
    wan_cross_loss: float = 0.25
    rtt_tier_weights: Optional[tuple] = None

    def __post_init__(self):
        if self.topology not in (
            "uniform", "het_ring", "wan_two_region", "measured_ring"
        ):
            raise ValueError(f"unknown topology {self.topology!r}")
        if self.topology == "het_ring" and self.rtt_tiers < 1:
            raise ValueError("het_ring needs rtt_tiers >= 1")
        if self.topology == "wan_two_region" and self.wan_blocks < 2:
            raise ValueError("wan_two_region needs wan_blocks >= 2")
        if self.topology == "measured_ring":
            w = self.rtt_tier_weights
            if not w or any(x < 0 for x in w) or sum(w) <= 0:
                raise ValueError(
                    "measured_ring needs rtt_tier_weights: non-empty, "
                    "non-negative, positive sum (corro admin rtt dump)"
                )

    @property
    def flat_nodes(self) -> int:
        return self.n_nodes * (self.n_universes or 1)

    @property
    def _universe(self) -> Optional[int]:
        return self.n_nodes if self.n_universes else None

    @property
    def broadcast_params(self) -> BroadcastParams:
        return BroadcastParams(
            n_nodes=self.flat_nodes,
            fanout_ring0=self.fanout_ring0,
            fanout_global=self.fanout_global,
            ring0_size=min(self.ring0_size, self.n_nodes),
            max_transmissions=self.max_transmissions,
            loss=self.loss,
            backoff_ticks=self.backoff_ticks,
            universe=self._universe,
            oneway_blocks=self.oneway_blocks,
            topology=self.topology,
            rtt_tiers=self.rtt_tiers,
            wan_blocks=self.wan_blocks,
            wan_cross_loss=self.wan_cross_loss,
            rtt_tier_weights=self.rtt_tier_weights,
        )

    @property
    def sync_params(self) -> SyncParams:
        return SyncParams(
            n_nodes=self.flat_nodes,
            peers_per_round=self.sync_peers,
            cells_per_chunk=self.cells_per_chunk,
            universe=self._universe,
            oneway_blocks=self.oneway_blocks,
        )


class EpidemicState(NamedTuple):
    rows: jnp.ndarray  # [N, R] packed CRDT keys
    tx_remaining: jnp.ndarray  # [N] int32
    msgs: jnp.ndarray  # [N] int32
    tick: jnp.ndarray  # scalar int32
    # [N] int32 infection depth (HOP_UNSET = not yet); None when
    # cfg.track_hops is off
    hops: Optional[jnp.ndarray]
    next_send: jnp.ndarray  # [N] int32 earliest tick of the next send
    # [N, N] bool when cfg.track_sent, else None (a jnp default here
    # would initialize the JAX backend at import time)
    sent: Optional[jnp.ndarray] = None


def epidemic_init(cfg: EpidemicConfig, writer: int = 0) -> EpidemicState:
    """All nodes at the base state; each universe's writer holds one
    committed changeset (col_version 2) ready to broadcast."""
    codec = DEFAULT_CODEC
    n, r = cfg.flat_nodes, cfg.n_rows
    base = codec.pack(
        jnp.ones((n, r), jnp.int32),
        jnp.ones((n, r), jnp.int32),
        jnp.zeros((n, r), jnp.int32),
    )
    news = codec.pack(
        jnp.ones((r,), jnp.int32),
        jnp.full((r,), 2, jnp.int32),
        jnp.ones((r,), jnp.int32),
    )
    # one writer per universe at the same local offset
    writers = (
        writer
        + jnp.arange(cfg.n_universes or 1, dtype=jnp.int32) * cfg.n_nodes
    )
    rows = base.at[writers].set(news)
    tx = jnp.zeros((n,), jnp.int32).at[writers].set(cfg.max_transmissions)
    return EpidemicState(
        rows=rows,
        tx_remaining=tx,
        msgs=jnp.zeros((n,), jnp.int32),
        tick=jnp.zeros((), jnp.int32),
        hops=(
            jnp.full((n,), HOP_UNSET, jnp.int32).at[writers].set(0)
            if cfg.track_hops else None
        ),
        next_send=jnp.zeros((n,), jnp.int32),
        sent=jnp.zeros((n, n), bool) if cfg.track_sent else None,
    )


def _partition_ids(cfg: EpidemicConfig):
    if cfg.partition_blocks <= 1:
        return None
    local = jnp.arange(cfg.flat_nodes, dtype=jnp.int32) % cfg.n_nodes
    return local * cfg.partition_blocks // cfg.n_nodes


def epidemic_tick(state: EpidemicState, key, cfg: EpidemicConfig) -> EpidemicState:
    """One protocol round: gossip fanout, then (on cadence) anti-entropy."""
    part = _partition_ids(cfg)
    part_active = state.tick < cfg.heal_tick
    k_b, k_s = jax.random.split(key)

    rows, tx, msgs, hops, next_send, sent = broadcast_step(
        state.rows,
        state.tx_remaining,
        state.msgs,
        k_b,
        cfg.broadcast_params,
        partition_id=part,
        partition_active=part_active,
        hops=state.hops,
        tick=state.tick,
        next_send=state.next_send,
        sent=state.sent if cfg.track_sent else None,
    )
    if sent is None:
        sent = state.sent

    if cfg.sync_interval > 0:
        def do_sync(args):
            rows, msgs = args
            return sync_step(
                rows, msgs, k_s, cfg.sync_params,
                partition_id=part, partition_active=part_active,
            )

        rows, msgs = jax.lax.cond(
            state.tick % cfg.sync_interval == cfg.sync_interval - 1,
            do_sync,
            lambda args: args,
            (rows, msgs),
        )

    return EpidemicState(rows, tx, msgs, state.tick + 1, hops, next_send,
                         sent)


@partial(jax.jit, static_argnames=("cfg",))
def _scan_chunk(state: EpidemicState, seed_key, target_row, cfg: EpidemicConfig):
    """Run cfg.chunk_ticks rounds; record per-tick convergence flags.

    In flat (seed-flattened) mode every per-tick statistic comes back
    per-universe with shape [S]; in single-universe mode they are
    scalars (the legacy vmap path)."""
    S = cfg.n_universes

    def per_universe(x):
        """[flat_nodes]-shaped stat -> [S, n_nodes] (or [1, n] unflat)."""
        return x.reshape((S or 1), cfg.n_nodes)

    def body(st, _):
        key = jax.random.fold_in(seed_key, st.tick)
        nxt = epidemic_tick(st, key, cfg)
        conv = jnp.all(
            nxt.rows.reshape((S or 1), cfg.n_nodes, cfg.n_rows)
            == target_row[None, None, :],
            axis=(1, 2),
        )
        # per-tick message aggregates so per-seed stats can be read at the
        # seed's OWN convergence tick, not at global loop stop
        msgs_f = per_universe(nxt.msgs.astype(jnp.float32))
        if nxt.hops is not None:
            # infection depth over broadcast-infected nodes ONLY: a node
            # healed by sync (or delivered by a sender of unknown depth,
            # the >= HOP_UNSET-1 clamp) has no defined depth and becomes
            # NaN — percentiles are taken over real depths and reported
            # alongside the coverage fraction, never a sentinel value
            hops_f = per_universe(jnp.where(
                nxt.hops >= HOP_UNSET - 1, jnp.nan,
                nxt.hops.astype(jnp.float32),
            ))
            h50 = jnp.nanpercentile(hops_f, 50, axis=1)
            h99 = jnp.nanpercentile(hops_f, 99, axis=1)
            hcov = jnp.mean(~jnp.isnan(hops_f), axis=1)
        else:  # hops untracked: no measurement at all
            h50 = h99 = jnp.full(((S or 1),), jnp.nan, jnp.float32)
            hcov = jnp.zeros(((S or 1),), jnp.float32)
        stats = (
            conv,
            jnp.mean(msgs_f, axis=1),
            jnp.percentile(msgs_f, 99, axis=1),
            h50,
            h99,
            hcov,
        )
        if S is None:  # legacy scalar outputs for the vmap path
            stats = tuple(x[0] for x in stats)
        return nxt, stats

    return jax.lax.scan(body, state, xs=None, length=cfg.chunk_ticks)


def seed_convergence(allflags):
    """Per-seed convergence extraction shared by the sim runners.

    allflags: [S, T] bool per-tick convergence.  Returns (converged
    mask, index of each seed's OWN convergence tick — last tick run if
    it never converged — and 1-based first tick, inf if never)."""
    converged = allflags.any(axis=1)
    first_idx = np.where(
        converged, allflags.argmax(axis=1), allflags.shape[1] - 1
    )
    first = np.where(converged, first_idx + 1, np.inf)
    return converged, first_idx, first


def stats_at_convergence(allflags, *series):
    """Shared per-seed stat extraction (epidemic + exact-sampler
    runners): each [S, T] per-tick series is read at that seed's OWN
    convergence tick, never at global loop stop.

    Returns (converged mask [S], 1-based first tick [S] (inf if
    never), and one [S] value array per input series)."""
    converged, first_idx, first = seed_convergence(allflags)
    rows = np.arange(allflags.shape[0])
    return converged, first, [s[rows, first_idx] for s in series]


@partial(jax.jit, static_argnames=("cfg",))
def _scan_chunk_coverage(state: EpidemicState, seed_key, target_row,
                         cfg: EpidemicConfig):
    """Run ``cfg.chunk_ticks`` rounds recording the PER-TICK coverage
    fraction — the share of nodes whose rows equal the target — per
    universe.  The time-resolved sibling of ``_scan_chunk``'s all-or-
    nothing convergence flags: the flight-recorder timeline gates the
    live cluster's coverage TRAJECTORY against this curve, not just its
    endpoint."""
    S = cfg.n_universes

    def body(st, _):
        key = jax.random.fold_in(seed_key, st.tick)
        nxt = epidemic_tick(st, key, cfg)
        holds = jnp.all(
            nxt.rows.reshape((S or 1), cfg.n_nodes, cfg.n_rows)
            == target_row[None, None, :],
            axis=2,
        )
        return nxt, jnp.mean(holds.astype(jnp.float32), axis=1)

    return jax.lax.scan(body, state, xs=None, length=cfg.chunk_ticks)


def run_epidemic_coverage(cfg: EpidemicConfig, n_seeds: int = 8,
                          seed: int = 0):
    """Per-tick predicted coverage curve, seed-flattened (one scan for
    all universes; ``track_sent`` unsupported — the curve predictor
    runs the flat layout only).  Returns::

        {"coverage": [mean coverage at tick 1..T],
         "coverage_p10": ..., "coverage_p90": ...,  # seed spread
         "ticks_run": T, "converged_frac": ...}

    The scan stops once every universe holds coverage 1.0 (or
    ``max_ticks``)."""
    if cfg.track_sent:
        raise ValueError(
            "run_epidemic_coverage runs the seed-flattened layout only "
            "(track_sent needs the [N, N] vmap path)"
        )
    flat_cfg = replace(cfg, n_universes=n_seeds)
    key = jax.random.PRNGKey(seed)
    state = epidemic_init(flat_cfg)
    target = state.rows[0]
    chunks = []
    ticks_done = 0
    while ticks_done < cfg.max_ticks:
        state, cov = _scan_chunk_coverage(state, key, target, flat_cfg)
        cov = np.asarray(cov).T  # [C, S] -> [S, C]
        chunks.append(cov)
        ticks_done += cfg.chunk_ticks
        if (cov[:, -1] >= 1.0).all():
            break
    allcov = np.concatenate(chunks, axis=1)  # [S, T]
    return {
        "coverage": [float(v) for v in allcov.mean(axis=0)],
        "coverage_p10": [
            float(v) for v in np.percentile(allcov, 10, axis=0)
        ],
        "coverage_p90": [
            float(v) for v in np.percentile(allcov, 90, axis=0)
        ],
        "ticks_run": int(allcov.shape[1]),
        "converged_frac": float((allcov[:, -1] >= 1.0).mean()),
    }


def run_epidemic(cfg: EpidemicConfig, seed: int = 0):
    """Single-universe run.  Returns a stats dict (host values)."""
    stats = run_epidemic_seeds(cfg, n_seeds=1, seed=seed)
    stats["ticks_to_converge"] = stats.pop("ticks_p99")
    return stats


def run_epidemic_seeds(cfg: EpidemicConfig, n_seeds: int = 16, seed: int = 0):
    """Multi-seed run; returns convergence distribution stats.

    The scan advances all universes together in chunks; the host loop
    stops as soon as every universe has converged (or max_ticks hit).

    Seed-flattening: the S universes are laid side by side in one flat
    [S*N] index space (block-local peer draws) instead of being vmapped
    — batched scatter serializes on TPU, and the flat layout turns the
    tick's scatters into single unbatched ops (measured ~70x faster at
    N=100k).  Only ``track_sent`` (the [N, N] calibration mode) still
    uses the legacy vmap path.
    """
    if cfg.track_sent:
        return _run_epidemic_seeds_vmap(cfg, n_seeds, seed)
    flat_cfg = replace(cfg, n_universes=n_seeds)
    key = jax.random.PRNGKey(seed)
    init = epidemic_init(flat_cfg)
    # convergence target = the writer's committed state (the join of all
    # writes in this single-writer scenario)
    target = init.rows[0]

    t0 = time.perf_counter()
    flags, means, p99s = [], [], []  # each: list of [S, C] arrays
    h50s, h99s, hcovs = [], [], []
    ticks_done = 0
    state = init
    while ticks_done < cfg.max_ticks:
        state, (conv, m_mean, m_p99, h_p50, h_p99, h_cov) = _scan_chunk(
            state, key, target, flat_cfg
        )
        conv = np.asarray(conv).T  # scan stacks [C, S] -> [S, C]
        flags.append(conv)
        means.append(np.asarray(m_mean).T)
        p99s.append(np.asarray(m_p99).T)
        h50s.append(np.asarray(h_p50).T)
        h99s.append(np.asarray(h_p99).T)
        hcovs.append(np.asarray(h_cov).T)
        ticks_done += cfg.chunk_ticks
        if conv[:, -1].all():
            break
    wall = time.perf_counter() - t0
    return _epidemic_stats(
        cfg, n_seeds, flags, means, p99s, h50s, h99s, hcovs, wall,
        ticks_done,
    )


def _run_epidemic_seeds_vmap(cfg: EpidemicConfig, n_seeds: int, seed: int):
    """Legacy vmapped multi-seed path (required by track_sent's [N, N]
    per-universe memory; calibration-scale only)."""
    keys = jax.random.split(jax.random.PRNGKey(seed), n_seeds)
    init = epidemic_init(cfg)
    target = init.rows[0]
    states = jax.tree.map(
        lambda x: jnp.broadcast_to(x, (n_seeds,) + x.shape), init
    )

    chunk = jax.vmap(
        lambda st, k, tgt: _scan_chunk(st, k, tgt, cfg), in_axes=(0, 0, None)
    )

    t0 = time.perf_counter()
    flags, means, p99s = [], [], []  # each: list of [S, C] arrays
    h50s, h99s, hcovs = [], [], []
    ticks_done = 0
    while ticks_done < cfg.max_ticks:
        states, (conv, m_mean, m_p99, h_p50, h_p99, h_cov) = chunk(
            states, keys, target
        )
        conv = np.asarray(conv)  # [S, C] (vmap leads with the seed axis)
        flags.append(conv)
        means.append(np.asarray(m_mean))
        p99s.append(np.asarray(m_p99))
        h50s.append(np.asarray(h_p50))
        h99s.append(np.asarray(h_p99))
        hcovs.append(np.asarray(h_cov))
        ticks_done += cfg.chunk_ticks
        if conv[:, -1].all():
            break
    wall = time.perf_counter() - t0
    return _epidemic_stats(
        cfg, n_seeds, flags, means, p99s, h50s, h99s, hcovs, wall,
        ticks_done,
    )


def _epidemic_stats(cfg, n_seeds, flags, means, p99s, h50s, h99s, hcovs,
                    wall, ticks_done):
    """Fold per-chunk [S, C] stat arrays into the result dict.

    Hop percentiles are measured over broadcast-infected nodes only; a
    percentile whose rank exceeds the measured coverage (e.g. a p99
    when only 97% of nodes were infected via broadcast) is reported as
    None, never a sentinel.  ``hops_broadcast_frac`` carries the
    coverage so the reader can see why.
    """
    allflags = np.concatenate(flags, axis=1)  # [S, T]
    converged, first, (m_at, p_at, h50_at, h99_at, hcov_at) = (
        stats_at_convergence(
            allflags,
            np.concatenate(means, axis=1),
            np.concatenate(p99s, axis=1),
            np.concatenate(h50s, axis=1),
            np.concatenate(h99s, axis=1),
            np.concatenate(hcovs, axis=1),
        )
    )
    hcov = float(hcov_at.mean()) if cfg.track_hops else None

    def hop_stat(vals_at, needed_cov):
        if not cfg.track_hops or hcov is None or hcov < needed_cov:
            return None
        v = float(np.nanmean(vals_at))
        return None if np.isnan(v) else v

    return {
        "n_nodes": cfg.n_nodes,
        "n_seeds": n_seeds,
        "converged_frac": float(converged.mean()),
        "ticks_p50": float(np.percentile(first, 50)),
        "ticks_p99": float(np.percentile(first, 99)),
        "msgs_per_node_mean": float(m_at.mean()),
        "msgs_per_node_p99": float(p_at.mean()),
        "hops_p50": hop_stat(h50_at, 0.50),
        "hops_p99": hop_stat(h99_at, 0.99),
        "hops_broadcast_frac": hcov,
        "wall_s": wall,
        "ticks_run": ticks_done,
    }
