"""Adversarial scenario matrix: co-simulation campaigns over the four
hostile fault families, gated on convergence + NO-DIVERGENCE.

CHAOS validated the live cluster against the kernel under the sim's own
fault family (loss / partition / churn).  This module runs the matrix
the BFT-simulation literature demands for trustworthy headline numbers
(PAPERS.md: "Simulating BFT Protocol Implementations at Scale" runs
implementations against adversarial scenarios next to a model;
"CRDT Emulation, Simulation, and Representation Independence" motivates
the no-divergence property as the gate):

* ``clock_skew``     — per-node HLC offset + drift at the ``HLClock``
  seam (``types/hlc.py skewed_now_ns``), exercising the 300 ms
  max-delta gossip-clock rule and the provenance negative-lag clamp;
* ``asym_partition`` — a ONE-WAY partition (``FaultPlan.oneway_blocks``)
  healing by wall clock: the severed direction drops while the reverse
  keeps flowing — the TOCTOU-hardened ``open_bi`` recheck applies
  per direction;
* ``slow_io``        — seeded slow-disk delays at the storage
  write/collect seams plus a scheduled event-loop stall, observed by
  the agents' own ``LoopHealthProbe``;
* ``equivocation``   — a hostile origin re-claiming an accepted
  ``(actor, version)`` with conflicting contents, replaying duplicates,
  and shipping garbage seq spans; agents must detect
  (``corro_sync_equivocations_total``), quarantine (``Members`` path),
  and accept zero divergent rows;
* ``compound``       — loss + one-way partition + clock skew at once.

Every cell runs a live in-process cluster next to the epidemic kernel's
prediction (the CHAOS/OBS comparison), scraped through
``ClusterObserver``, and gates on:

1. full convergence of the cell's write workload;
2. ``ClusterObserver.no_divergence()`` — bytewise-equal table state,
   consistent bookkeeping ledgers, one accepted content per
   ``(actor, version)``;
3. family-specific assertions (skew applied, stall observed, hostile
   actor quarantined with zero divergent rows, ...).

``bench.py --scenarios`` writes the matrix to ``SCENARIOS_N32.json``;
``tests/test_scenarios.py`` runs one small cell per family in tier-1
and the full N=32 matrix under ``@slow``.
"""

from __future__ import annotations

import asyncio
import json
import time
from typing import Dict, List, Optional

from corrosion_tpu.faults import (
    EquivocatingPeer,
    FaultController,
    FaultPlan,
    LoopStall,
)

# the simdiff/chaos time base: one kernel tick ≈ the agents' broadcast
# flush interval (launch_test_agent pins bcast_flush_interval=0.02)
TICK_S = 0.02

FAMILIES = (
    "clock_skew",
    "asym_partition",
    "slow_io",
    "equivocation",
    "compound",
)


def build_plan(family: str, seed: int, heal_after: float,
               stall_ms: float) -> FaultPlan:
    """The seeded FaultPlan for one matrix cell.  Parameters sit at
    the aggressive end of what a WAN deployment sees: 200 ms skew
    straddles the 300 ms max-delta rule once drift accumulates, 2–6 ms
    per-IO delays are a saturated disk, a ~stall_ms loop stall is a GC
    pause / noisy neighbor."""
    if family == "clock_skew":
        return FaultPlan(
            seed=seed,
            clock_skew_max_ns=200_000_000,  # ±200 ms constant offset
            clock_drift_max_ppm=200.0,      # ±200 ppm linear drift
        )
    if family == "asym_partition":
        return FaultPlan(
            seed=seed,
            partition_blocks=2,
            oneway_blocks=((0, 1),),  # block0 → block1 severed only
            heal_after=heal_after,
        )
    if family == "slow_io":
        return FaultPlan(
            seed=seed,
            disk_write_delay=0.002,
            disk_write_jitter=0.004,
            disk_read_delay=0.002,
            disk_read_jitter=0.004,
            loop_stalls=(LoopStall("n0", at=0.05, duration_ms=stall_ms),),
        )
    if family == "equivocation":
        return FaultPlan(seed=seed)
    if family == "compound":
        return FaultPlan(
            seed=seed,
            drop=0.05,
            partition_blocks=2,
            oneway_blocks=((0, 1),),
            heal_after=heal_after,
            clock_skew_max_ns=150_000_000,
        )
    raise ValueError(f"unknown scenario family {family!r}")


def sim_prediction(family: str, n: int, heal_after: float,
                   seeds: int = 8) -> Dict:
    """The epidemic kernel's prediction for the cell, with any
    modeling residual named.  The kernel now models loss + partitions
    INCLUDING the directed (one-way) shape (``EpidemicConfig.
    oneway_blocks`` — gossip severs per listed direction, anti-entropy
    sessions need both directions up, exactly the live bi-stream
    semantics), so the asym_partition cell compares against the
    directed prediction with NO partition residual.  Skew / slow IO /
    equivocation alter timestamps, lock holds and screening — not the
    message dynamics — so those cells keep comparing against the
    fault-free prediction and keep their residual."""
    from corrosion_tpu.sim.chaos import sim_chaos_trace
    from corrosion_tpu.sim.obs import sim_obs_trace

    heal_tick = max(1, int(round(heal_after / TICK_S)))
    if family in ("asym_partition", "compound"):
        loss = 0.05 if family == "compound" else 0.0
        pred = sim_chaos_trace(
            n, loss=loss, partition_blocks=2, heal_tick=heal_tick,
            seeds=seeds, oneway_blocks=((0, 1),),
        )
        if family == "compound":
            pred["residual"] = (
                "the kernel models the cell's loss + one-way partition "
                "exactly; clock skew (the cell's third fault) alters "
                "timestamps, not message dynamics, and carries no "
                "kernel-side model"
            )
        return pred
    pred = sim_obs_trace(n, seeds=seeds)
    pred["residual"] = (
        "the kernel does not model clock skew / disk latency / hostile "
        "peers — they alter timestamps, lock holds and screening, not "
        "the message dynamics — so the cell compares against the "
        "fault-free prediction"
    )
    return pred


async def _deliver(agent, cv, source) -> None:
    """Feed one crafted changeset into an agent's REAL ingest pipeline
    (bounded queue → change loop → apply workers), loop-affine."""
    agent.enqueue_change(cv, source)


async def _run_hostile_attack(agents: Dict[str, "object"],
                              seed: int, wait_for) -> Dict:
    """The equivocating-peer script: bait → conflicting re-send (split
    across the broadcast and sync detection sites) → replayed
    duplicates → garbage spans (from a SECOND hostile actor, since the
    first is quarantined the moment its conflict is seen) →
    post-quarantine probe.  Returns what the harness knows
    ground-truth about, for the cell's gates."""
    from corrosion_tpu.types import ChangeSource

    peer = EquivocatingPeer(seed=seed)
    spanner = EquivocatingPeer(seed=seed + 1000)
    targets = list(agents.values())
    # the hostile peers "joined" the cluster before turning: make them
    # members everywhere so quarantine has a record to mark (and the
    # admin cluster_members output a row to show)
    for a in targets:
        a.members.upsert(peer.actor_id, ("127.0.0.1", 9))
        a.members.upsert(spanner.actor_id, ("127.0.0.1", 10))

    def all_contain(version: int):
        return all(
            a.bookie.for_actor(peer.actor_id).contains_version(version)
            for a in targets
        )

    # 1. bait: a well-formed version accepted everywhere
    bait = peer.honest(9100, "bait")
    for a in targets:
        await _deliver(a, bait, ChangeSource.BROADCAST)
    await wait_for(lambda: all_contain(1), timeout=20)

    # 2. conflicting contents for ONE version: content A accepted
    #    everywhere first, then content B re-claims it on the gossip
    #    path.  Detection is BROADCAST-scope by design: gossiped bytes
    #    are immutable per version, while sync re-serves legitimately
    #    reflect serve-time compaction (docs/faults.md)
    a_cv, b_cv = peer.conflicting_pair(9101)
    for a in targets:
        await _deliver(a, a_cv, ChangeSource.BROADCAST)
    await wait_for(lambda: all_contain(2), timeout=20)
    for a in targets:
        await _deliver(a, b_cv, ChangeSource.BROADCAST)
    # replayed duplicates of the ACCEPTED content: absorbed on both
    # paths, never counted as equivocation
    for i, a in enumerate(targets):
        src = ChangeSource.BROADCAST if i % 2 == 0 else ChangeSource.SYNC
        await _deliver(a, a_cv, src)

    # 3. garbage seq spans (screened before any buffering) — from the
    #    second hostile actor, which is not yet quarantined
    garbage = spanner.garbage_span(9102)
    wide = spanner.absurd_width(9103)
    for a in targets:
        await _deliver(a, garbage, ChangeSource.BROADCAST)
        await _deliver(a, wide, ChangeSource.SYNC)

    # 4. wait for every node to have detected + quarantined BOTH
    def all_quarantined():
        return all(
            peer.actor_id in a._equiv_quarantined
            and spanner.actor_id in a._equiv_quarantined
            for a in targets
        )

    await wait_for(all_quarantined, timeout=20)

    # 5. post-quarantine probe: a fresh well-formed version must DROP
    post = peer.honest(9104, "post-quarantine")
    for a in targets:
        await _deliver(a, post, ChangeSource.BROADCAST)

    return {
        "actor": peer.actor_id.hex(),
        "span_actor": spanner.actor_id.hex(),
        "accepted_versions": [1, 2],
        "post_quarantine_version": int(post.changeset.version),
    }


async def agent_scenario_cell(
    family: str,
    n: int = 9,
    seed: int = 0,
    writes: int = 6,
    heal_after: float = 0.8,
    stall_ms: float = 150.0,
    timeout: float = 60.0,
    base_dir: Optional[str] = None,
) -> Dict:
    """Run one matrix cell on a live cluster; returns the measurement
    record with its ``gates`` dict (every gate must be True)."""
    from corrosion_tpu.agent.testing import seed_full_membership, wait_for
    from corrosion_tpu.devcluster import (
        ClusterObserver,
        Topology,
        run_inprocess,
        run_stall_schedule,
    )

    plan = build_plan(family, seed, heal_after, stall_ms)
    ctrl = FaultController(plan)
    topo = Topology.parse("\n".join(f"n0 -> n{i}" for i in range(1, n)))
    agents = await run_inprocess(
        topo,
        base_dir=base_dir,
        faults=ctrl,
        ring0_enabled=False,   # uniform sampling: the kernel's model
        subs_enabled=False,
        api_port=None,
        uni_cache_size=16,
        suspect_timeout=10.0,  # faults must not down-mark the cluster
        breaker_cooldown=0.5,
        # fast flight snapshots: even a short tier-1 cell's timeline
        # attachment carries real metric history, not just events
        flight_interval_s=0.25,
    )
    stall_task = None
    try:
        await wait_for(
            lambda: all(
                len(a.members.alive()) == n - 1 for a in agents.values()
            ),
            timeout=max(30.0, 2.0 * n),
        )
        seed_full_membership(list(agents.values()))
        obs = ClusterObserver(agents, faults=ctrl)
        obs.mark()

        # stall-probe sample cursor per node: the boot of N in-process
        # agents stalls the shared loop too (synchronous schema DDL),
        # so the stall gate must look only at samples recorded AFTER
        # the schedule arms.  The cursor is the CUMULATIVE histogram
        # count (monotone, trim-immune) — the value ring itself trims
        # past ~1279 samples, so a stored index would drift
        def _stall_ring(a):
            rings = a.metrics.histogram_samples("corro_loop_stall_ms")
            return next(iter(rings.values()), [])

        def _stall_count(a):
            n, _s = a.metrics.histogram_stats("corro_loop_stall_ms")
            return n

        pre_stall_counts = {
            name: _stall_count(a) for name, a in agents.items()
        }

        def _new_stall_samples(name):
            a = agents[name]
            n_new = _stall_count(a) - pre_stall_counts[name]
            if n_new <= 0:
                return []
            return _stall_ring(a)[-n_new:]

        ctrl.restart_clock()
        if plan.partition_blocks > 1:
            ctrl.split()
        if plan.loop_stalls:
            stall_task = asyncio.ensure_future(run_stall_schedule(ctrl))

        hostile = None
        if family == "equivocation":
            hostile = await _run_hostile_attack(agents, seed, wait_for)

        # spread write workload; under a partition, one writer per
        # block so only post-heal machinery can reach the union.  The
        # second writer is the FIRST index whose block differs
        # (block_of is idx*blocks//n — ceil(n/blocks), not n//blocks)
        names = list(agents)
        if plan.partition_blocks > 1:
            other = next(
                i for i in range(n)
                if plan.block_of(i, n) != plan.block_of(0, n)
            )
            writers = [names[0], names[other]]
        else:
            writers = names[:: max(1, n // 3)]
        t0 = time.perf_counter()
        versions = []
        for w in range(writes):
            origin = agents[writers[w % len(writers)]]
            res = await asyncio.to_thread(
                origin.execute_transaction,
                [("INSERT INTO tests (id, text) VALUES (?, ?)",
                  (8000 + w, f"{family}-{w}"))],
            )
            versions.append((origin.actor_id, res["version"]))
            await asyncio.sleep(0.02)

        def converged() -> bool:
            for a in agents.values():
                for actor, v in versions:
                    if a.actor_id != actor and not a.bookie.for_actor(
                        actor
                    ).contains_version(v):
                        return False
            return True

        converged_ok = True
        try:
            await wait_for(converged, timeout=timeout, interval=0.02)
        except TimeoutError:
            # a non-converging cell is a RESULT, not a crash: record
            # the failed gate so the campaign artifact names it
            converged_ok = False
        wall = time.perf_counter() - t0
        if stall_task is not None:
            try:
                await asyncio.wait_for(stall_task, timeout=timeout)
            except asyncio.TimeoutError:
                stall_task.cancel()
            stall_task = None

        scrape = obs.scrape()
        lag = obs.convergence_lag()
        nodiv = obs.no_divergence()
        equiv = obs.equivocations(scrape)
        loop_health = obs.loop_health(scrape)

        # the cell's flight-recorder attachment: a red cell ships its
        # own post-mortem — the merged typed-event journal (bounded),
        # snapshot count, and the write waves' coverage trajectory
        events = obs.flight_events()
        kind_counts: Dict[str, int] = {}
        for e in events:
            kind_counts[e["kind"]] = kind_counts.get(e["kind"], 0) + 1
        timeline = {
            "snapshots": len(obs.flight_timeline(kind="snap")),
            "event_counts": kind_counts,
            "events": [
                {
                    "node": e["node"], "kind": e["kind"],
                    "hlc": e["hlc"], "wall": round(e["wall"], 3),
                    "attrs": e.get("attrs", {}),
                }
                for e in events[-200:]
            ],
            "coverage": obs.coverage_curve(versions),
        }

        gates = {
            "converged": converged_ok,
            "no_divergence": nodiv["ok"],
            # the provenance negative-lag clamp: a skewed-ahead origin
            # must clamp to 0, never record negative
            "lags_non_negative": all(
                s >= 0.0
                for a in agents.values()
                for ring in a.metrics.histogram_samples(
                    "corro_change_lag_seconds"
                ).values()
                for s in ring
            ),
        }
        detail: Dict = {}
        if family in ("clock_skew", "compound"):
            skews = {
                name: plan.node_clock(name)[0] for name in agents
            }
            gates["skew_applied"] = any(abs(v) > 0 for v in skews.values())
            detail["clock_skew_ns"] = skews
        if family == "asym_partition" or family == "compound":
            gates["partition_fired"] = ctrl.injected["partition"] > 0
        if family == "slow_io":
            gates["disk_delays_fired"] = ctrl.injected["disk"] > 0
            gates["stall_injected"] = ctrl.injected["stall"] >= len(
                plan.loop_stalls
            )
            # the agents' OWN probe must have seen the injected stall —
            # judged on post-boot samples only (the sample cursor)
            gates["stall_observed"] = any(
                max(_new_stall_samples(name), default=0.0)
                >= 0.5 * stall_ms
                for name in agents
            )
        if family == "equivocation":
            hostile_actors = [
                bytes.fromhex(hostile["actor"]),
                bytes.fromhex(hostile["span_actor"]),
            ]
            gates["content_detected"] = equiv.get("content", 0) >= 1
            gates["span_detected"] = equiv.get("span", 0) >= 1
            gates["hostile_quarantined_everywhere"] = all(
                actor in a._equiv_quarantined
                and (a.members.get(actor) is not None
                     and a.members.get(actor).quarantined
                     and a.members.get(actor).quarantine_reason
                     == "equivocation")
                for a in agents.values()
                for actor in hostile_actors
            )
            # zero divergent rows: no node ever applied the conflicting
            # re-send, the garbage spans, or post-quarantine traffic
            def _count_like(a, pat):
                _, rows = a.storage.read_query(
                    "SELECT COUNT(*) FROM tests WHERE text LIKE ?",
                    (pat,),
                )
                return rows[0][0]

            gates["zero_divergent_rows"] = all(
                _count_like(a, "equiv-b-%") == 0
                and _count_like(a, "garbage-%") == 0
                and _count_like(a, "wide-%") == 0
                and _count_like(a, "post-quarantine") == 0
                for a in agents.values()
            )
            detail["hostile"] = hostile
            detail["equivocations"] = equiv

        return {
            "family": family,
            "n_nodes": n,
            "seed": seed,
            "writes": writes,
            "wall_to_converge_s": round(wall, 3),
            "live_p99_s": lag.get("p99_s"),
            "live_p50_s": lag.get("p50_s"),
            "lag_samples": lag.get("count", 0),
            "msgs_per_node": round(obs.msgs_per_node(scrape), 2),
            "loop_health": loop_health,
            "injected": dict(ctrl.injected),
            "no_divergence": nodiv,
            "timeline": timeline,
            "gates": gates,
            "passed": all(gates.values()),
            "detail": detail,
        }
    finally:
        if stall_task is not None and not stall_task.done():
            stall_task.cancel()
            try:
                await stall_task
            except (asyncio.CancelledError, Exception):
                pass
        for a in list(agents.values()):
            try:
                await a.stop()
            except Exception:
                pass


async def run_scenarios(
    n: int = 32,
    seed: int = 0,
    families: Optional[List[str]] = None,
    sim_seeds: int = 8,
    heal_after: float = 0.64,
    out_path: Optional[str] = None,
    base_dir: Optional[str] = None,
    sim: bool = True,
) -> Dict:
    """The campaign: every family's cell on a live N-node cluster next
    to the kernel prediction, one JSON artifact, all gates asserted
    in-record."""
    import os

    families = list(families or FAMILIES)
    unknown = [f for f in families if f not in FAMILIES]
    if unknown:
        # validate UP FRONT: a typo must not surface mid-campaign
        # after earlier N=32 cells already burned their minutes
        raise ValueError(
            f"unknown scenario families {unknown}; valid: {FAMILIES}"
        )
    results = {}
    for family in families:
        # seed offset by the family's FIXED position in FAMILIES, not
        # its position in a --scenario-families subset: replaying one
        # failing cell must reproduce the matrix run's exact draws
        i = FAMILIES.index(family)
        cell_dir = (
            os.path.join(base_dir, family) if base_dir else None
        )
        prediction = (
            sim_prediction(family, n, heal_after, seeds=sim_seeds)
            if sim else None
        )
        try:
            cell = await agent_scenario_cell(
                family, n=n, seed=seed + i, heal_after=heal_after,
                base_dir=cell_dir,
                timeout=120.0,
            )
        except Exception as e:  # noqa: BLE001 - one cell crashing
            # must not discard the completed cells' results
            cell = {
                "family": family,
                "n_nodes": n,
                "seed": seed + i,
                "error": f"{type(e).__name__}: {e}",
                "live_p99_s": None,
                "msgs_per_node": None,
                "no_divergence": {"ok": False, "violations": []},
                "timeline": None,
                "gates": {"converged": False},
                "passed": False,
            }
        pred_p99 = None
        if prediction is not None:
            pred_p99 = prediction.get("predicted_wall_p99_s")
            if pred_p99 is None and prediction.get(
                "ticks_to_converge_p99"
            ) is not None:
                pred_p99 = prediction["ticks_to_converge_p99"] * TICK_S
        results[family] = {
            "agents": cell,
            "sim": prediction,
            "diff": {
                "live_p99_s": cell["live_p99_s"],
                "kernel_predicted_wall_p99_s": pred_p99,
                "msgs_per_node_live": cell["msgs_per_node"],
                "msgs_per_node_kernel": (
                    prediction.get("msgs_per_node")
                    if prediction else None
                ),
            },
        }

    all_passed = all(r["agents"]["passed"] for r in results.values())
    no_div = all(
        r["agents"]["no_divergence"]["ok"] for r in results.values()
    )
    out = {
        "n_nodes": n,
        "metric": "adversarial_scenario_matrix",
        "families": list(results),
        "all_cells_converged": all(
            r["agents"]["gates"].get("converged", False)
            for r in results.values()
        ),
        "no_divergence_all_cells": no_div,
        "all_gates_passed": all_passed,
        "tick_seconds": TICK_S,
        "cells": results,
    }
    if not all_passed:
        out["error"] = "one or more scenario gates failed"
    if out_path:
        with open(out_path, "w") as f:
            json.dump(out, f, indent=1, allow_nan=False)
            f.write("\n")
    return out


# ---------------------------------------------------------------------------
# virtual-time campaigns (sim/vcluster.py): the same matrix at N=512–1024
# in seconds of wall time, plus the cells only reachable at scale
# ---------------------------------------------------------------------------

#: scale-only fault families — restart storms, hostile-fraction sweeps
#: ("Simulating BFT Protocol Implementations at Scale", PAPERS.md),
#: compound cells composing matrix faults with crash schedules, and
#: the signed-attribution/Byzantine-sync-serve cells (docs/faults.md):
#: framing_relay is the headline NEGATIVE control — a tampering relay
#: is convicted while the framed honest origin is quarantined on zero
#: nodes — signed_equivocator proves the permanent (restart-surviving)
#: verdict, byz_sync_server proves the serve-path client defenses, and
#: hostile_sweep_32_signed re-runs the 32-hostile sweep with keyed
#: hostiles so every verdict lands as a signed proof
SCALE_FAMILIES = (
    "restart_storm",
    "hostile_sweep_8",
    "hostile_sweep_32",
    "equiv_during_heal",
    "skew_during_restart",
    "framing_relay",
    "signed_equivocator",
    "byz_sync_server",
    "hostile_sweep_32_signed",
    "restart_storm_snapshot",
    "byz_snapshot_server",
    "crash_mid_install",
)

#: snapshot-bootstrap cells (docs/sync.md): a compacted history makes
#: snapshot install the only below-floor catch-up path —
#: restart_storm_snapshot wipes a storm's victims so every reborn node
#: bootstraps via snapshot install + tail sync; byz_snapshot_server
#: proves the install gates contain a hostile snapshot server (digest
#: mismatch → breaker trip → change-by-change fallback via honest
#: peers, zero divergent rows); crash_mid_install kills installing
#: clients at every journal stage and proves the boot recovery
#: contract re-converges them
SNAP_FAMILIES = (
    "restart_storm_snapshot", "byz_snapshot_server", "crash_mid_install",
)

VIRTUAL_FAMILIES = FAMILIES + SCALE_FAMILIES

#: cells that run on a SIGNED cluster (per-node Ed25519 keypairs, one
#: shared trust directory, spot checks on)
SIGNED_FAMILIES = (
    "framing_relay", "signed_equivocator", "hostile_sweep_32_signed",
)


def _hostile_count(family: str) -> int:
    if family in ("equivocation", "equiv_during_heal",
                  "signed_equivocator"):
        return 1
    if family.startswith("hostile_sweep_"):
        return int(family.split("_")[2])
    return 0


def build_virtual_plan(family: str, seed: int, heal_after: float,
                       stall_ms: float, n: int) -> "FaultPlan":
    """The seeded FaultPlan for one virtual cell.  The five matrix
    families reuse :func:`build_plan` verbatim; the scale families add
    crash schedules (restart storms, skew-during-restart) on top of
    the matrix parameters."""
    from corrosion_tpu.faults import CrashEvent

    if family in FAMILIES:
        return build_plan(family, seed, heal_after, stall_ms)
    if family == "restart_storm":
        k = max(2, n // 16)
        stride = max(1, n // k)
        crashes = tuple(
            CrashEvent(
                f"n{(j * stride) % n}",
                at=0.3 + j * 0.02,
                restart_at=1.3 + j * 0.02,
            )
            for j in range(k)
        )
        return FaultPlan(seed=seed, crashes=crashes)
    if family in ("hostile_sweep_8", "hostile_sweep_32",
                  "hostile_sweep_32_signed", "framing_relay",
                  "byz_sync_server"):
        return FaultPlan(seed=seed)
    if family == "signed_equivocator":
        # the victim restart that proves the persisted proof re-arms:
        # timed well past the attack script (which runs at virtual
        # t≈0) and before the convergence check waits it out
        return FaultPlan(
            seed=seed,
            crashes=(CrashEvent("n3", at=0.6, restart_at=1.2),),
        )
    if family == "equiv_during_heal":
        return FaultPlan(
            seed=seed, partition_blocks=2, heal_after=heal_after
        )
    if family in ("restart_storm_snapshot", "byz_snapshot_server"):
        # victims crash early and restart later; the cell script wipes
        # their directories in between (VirtualCluster.schedule_wipe),
        # so the reborn nodes are FRESH bootstraps
        k = max(2, n // 16) if family == "restart_storm_snapshot" else 3
        stride = max(1, (n - 8) // k)
        crashes = tuple(
            CrashEvent(
                f"n{8 + (j * stride) % max(1, n - 8)}",
                at=0.3 + j * 0.02,
                restart_at=1.3 + j * 0.02,
            )
            for j in range(k)
        )
        return FaultPlan(seed=seed, crashes=crashes)
    if family == "crash_mid_install":
        from corrosion_tpu.faults import SnapFault

        # three wiped victims, one injected death per install stage;
        # each reborn node's retry must run clean (the faults are
        # one-shot) and re-converge the cluster
        victims = [f"n{8 + j * max(1, (n - 8) // 3)}" for j in range(3)]
        crashes = tuple(
            CrashEvent(v, at=0.3 + j * 0.02, restart_at=0.8 + j * 0.02)
            for j, v in enumerate(victims)
        )
        stages = ("crash_staging", "crash_installing", "crash_swapped")
        return FaultPlan(
            seed=seed,
            crashes=crashes,
            snap_faults=tuple(
                SnapFault(v, stage, restart_delay=0.4)
                for v, stage in zip(victims, stages)
            ),
        )
    if family == "skew_during_restart":
        k = max(2, n // 64)
        stride = max(1, n // k)
        crashes = tuple(
            CrashEvent(
                f"n{(j * stride) % n}", at=0.4, restart_at=1.6
            )
            for j in range(k)
        )
        return FaultPlan(
            seed=seed,
            clock_skew_max_ns=200_000_000,
            clock_drift_max_ppm=200.0,
            crashes=crashes,
        )
    raise ValueError(f"unknown virtual scenario family {family!r}")


def _virtual_hostile_attack(c, seed: int, k: int,
                            mid_heal: bool = False,
                            heal_after: float = 0.0,
                            signed: bool = False) -> Dict:
    """The equivocating-peer script on virtual time, for ``k``
    simultaneous hostiles (the hostile-fraction sweep): per hostile —
    bait → conflicting re-send → replayed duplicate; one extra
    span-garbage actor covers the structural screen.  ``mid_heal``
    delays the conflicting re-sends until just before the partition
    heals (the equivocation-during-partition-heal compound cell).

    ``signed=True`` gives every hostile a REGISTERED Ed25519 keypair
    (the insider-gone-rogue shape) and signs every crafted delivery:
    the conflicting pair then verifies as a signed-equivocation PROOF
    and the quarantine goes permanent
    (``quarantine_reason="signed_equivocation"``).  The span-garbage
    actor stays unkeyed either way, pinning the bounded-window verdict
    next to the permanent one in a single cell."""
    from corrosion_tpu.faults import EquivocatingPeer
    from corrosion_tpu.types import ChangeSource

    all_idx = list(range(c.n))
    # the sweep's question at scale is detection + quarantine fan-out,
    # not relay throughput: multi-hostile waves deliver point-to-point
    # (the matrix's single-equivocator family keeps relay on)
    relay = k == 1
    hostiles = []
    for h in range(k):
        sig_secret = None
        if signed:
            from corrosion_tpu.types.crypto import seed_keypair

            sig_secret, pub = seed_keypair(
                f"vhostile:{seed}:{h}".encode()
            )
        peer = EquivocatingPeer(
            seed=seed + 1 + h, now_ns=c.clock.wall_ns,
            sig_secret=sig_secret,
        )
        if signed:
            c.register_pubkey(peer.actor_id, pub)
        hostiles.append(peer)
    spanner = EquivocatingPeer(seed=seed + 5000, now_ns=c.clock.wall_ns)
    for a in c.agents.values():
        for h, peer in enumerate(hostiles):
            a.members.upsert(peer.actor_id, ("hostile", h))
        a.members.upsert(spanner.actor_id, ("hostile", 9999))

    def _inject(cv, peer, source):
        c.inject(all_idx, cv, source, rebroadcast=relay,
                 sig=peer.sign_changeset(cv))

    def all_contain(actor, version):
        return all(
            a.bookie.for_actor(actor).contains_version(version)
            for nm, a in c.agents.items() if nm not in c._crashed
        )

    # 1. bait: a well-formed version per hostile, accepted everywhere
    for peer in hostiles:
        _inject(peer.honest(9100, "bait"), peer, ChangeSource.BROADCAST)
    assert c.run_until_true(
        lambda: all(all_contain(p.actor_id, 1) for p in hostiles),
        timeout=20,
    ), "bait did not reach every node"

    # 2. conflicting contents: content A everywhere first, then B
    #    re-claims it on the gossip path (optionally timed to land
    #    around the partition heal)
    pairs = [p.conflicting_pair(9101) for p in hostiles]
    for (a_cv, _b), peer in zip(pairs, hostiles):
        _inject(a_cv, peer, ChangeSource.BROADCAST)
    assert c.run_until_true(
        lambda: all(all_contain(p.actor_id, 2) for p in hostiles),
        timeout=20,
    ), "accepted content did not reach every node"
    if mid_heal:
        # land the re-send as the heal opens the severed direction
        gap = heal_after - c.clock.monotonic() - 0.05
        if gap > 0:
            c.run_for(gap)
    for (_a, b_cv), peer in zip(pairs, hostiles):
        _inject(b_cv, peer, ChangeSource.BROADCAST)
    # replayed duplicates of the ACCEPTED content: absorbed, never
    # counted (split across both detection paths like the live cell)
    for i, ((a_cv, _b), peer) in enumerate(zip(pairs, hostiles)):
        src = (ChangeSource.BROADCAST if i % 2 == 0
               else ChangeSource.SYNC)
        _inject(a_cv, peer, src)

    # 3. garbage seq spans (screened before any buffering)
    c.inject(all_idx, spanner.garbage_span(9102), ChangeSource.BROADCAST, rebroadcast=relay)
    c.inject(all_idx, spanner.absurd_width(9103), ChangeSource.SYNC, rebroadcast=relay)

    # 4. every node must have detected + quarantined every hostile
    actors = [p.actor_id for p in hostiles] + [spanner.actor_id]

    def all_quarantined():
        return all(
            actor in a._equiv_quarantined
            for nm, a in c.agents.items() if nm not in c._crashed
            for actor in actors
        )

    quarantined_ok = c.run_until_true(all_quarantined, timeout=20)

    # 5. post-quarantine probe: fresh well-formed traffic must DROP
    posts = [p.honest(9104, "post-quarantine") for p in hostiles]
    for post, peer in zip(posts, hostiles):
        _inject(post, peer, ChangeSource.BROADCAST)
    c.run_for(0.2)
    return {
        "hostiles": [p.actor_id.hex() for p in hostiles],
        "hostile_peers": hostiles,
        "span_actor": spanner.actor_id.hex(),
        "hostile_actors": actors,
        "keyed_actors": [p.actor_id for p in hostiles] if signed else [],
        "quarantined_everywhere": quarantined_ok,
        "post_quarantine_version": int(
            posts[0].changeset.version
        ) if posts else None,
    }


def _virtual_framing_relay(c, seed: int, relay_idx: int = 1,
                           waves: int = 4) -> Dict:
    """The headline NEGATIVE control (docs/faults.md, signed
    attribution): an honest keyed origin's signed waves converge, then
    a tampering relay — a real cluster node's transport identity —
    re-delivers every wave with the contents rewritten but the
    ORIGINAL signature attached.  Every node's digest screen fires on
    the conflict, verification fails, and blame must land on the
    DELIVERING relay: the honest origin is quarantined on ZERO nodes."""
    from corrosion_tpu.faults import EquivocatingPeer
    from corrosion_tpu.types import ChangeSource
    from corrosion_tpu.types.crypto import seed_keypair

    sec, pub = seed_keypair(f"vframing-origin:{seed}".encode())
    origin = EquivocatingPeer(
        seed=seed + 7_000, now_ns=c.clock.wall_ns, sig_secret=sec,
    )
    c.register_pubkey(origin.actor_id, pub)
    for a in c.agents.values():
        a.members.upsert(origin.actor_id, ("honest", 7))
    all_idx = list(range(c.n))
    relay_addr = ("virt", relay_idx)
    # everyone except the relay receives the tampered re-delivery (a
    # node cannot be its own delivering transport)
    victims = [i for i in all_idx if i != relay_idx]

    def all_contain(version):
        return all(
            a.bookie.for_actor(origin.actor_id).contains_version(version)
            for nm, a in c.agents.items() if nm not in c._crashed
        )

    # 1. the honest signed waves, accepted everywhere
    cvs = []
    for w in range(waves):
        cv = origin.honest(9300 + w, f"honest-{w}")
        cvs.append(cv)
        c.inject(all_idx, cv, ChangeSource.BROADCAST,
                 rebroadcast=False, sig=origin.sign_changeset(cv))
    assert c.run_until_true(
        lambda: all(all_contain(int(cv.changeset.version))
                    for cv in cvs),
        timeout=20,
    ), "honest waves did not reach every node"

    # 2. the tampering relay: rewritten contents, original signature,
    #    delivery attributed to the relay node's transport address
    for w, cv in enumerate(cvs):
        tampered = origin.tampered_copy(cv, f"tampered-{w}")
        c.inject(victims, tampered, ChangeSource.BROADCAST,
                 rebroadcast=False, sig=origin.sign_changeset(cv),
                 peer=relay_addr)
    c.run_for(0.3)
    return {
        "origin": origin.actor_id.hex(),
        "origin_actor": origin.actor_id,
        "relay": f"n{relay_idx}",
        "relay_addr": relay_addr,
        "victims": victims,
        "waves": waves,
    }


#: Byzantine sync-server mode → the client-reject reason its defense
#: must produce (None = contained by dedup, no reject counter)
BYZ_MODE_REASONS = {
    "lying_ranges": "advertised_range",
    "absurd_needs": "advertised_range",
    "huge_head": "need_cap",
    "garbage_frames": "frame_garbage",
    "oversized_frame": "frame_garbage",
    "slow_trickle": "deadline",
    "conflicting_reserve": None,
}


def _virtual_byz_sync(c, seed: int) -> Dict:
    """The Byzantine sync-SERVER cell script: one hostile server per
    attack mode (real cluster nodes n1..n7 whose serve path is played
    by ``faults.ByzantineSyncServer``), plus a phantom honest wave the
    conflicting_reserve mode re-serves tampered.  Each mode is driven
    against three deterministic clients explicitly (organic sync
    rounds hit the hostile servers too, but the campaign must not
    depend on sampling luck), and containment comes entirely from the
    client-side defenses."""
    from corrosion_tpu.faults import ByzantineSyncServer, EquivocatingPeer
    from corrosion_tpu.types import ChangeSource

    # the phantom wave every client holds, for tampered re-serves
    source = EquivocatingPeer(seed=seed + 8_000, now_ns=c.clock.wall_ns)
    for a in c.agents.values():
        a.members.upsert(source.actor_id, ("honest", 8))
    all_idx = list(range(c.n))
    for w in range(2):
        c.inject(all_idx, source.honest(9400 + w, f"reserve-src-{w}"),
                 ChangeSource.BROADCAST, rebroadcast=False)
    assert c.run_until_true(
        lambda: all(
            a.bookie.for_actor(source.actor_id).contains_version(2)
            for nm, a in c.agents.items() if nm not in c._crashed
        ),
        timeout=20,
    ), "reserve-source wave did not reach every node"

    modes = list(ByzantineSyncServer.MODES)
    servers = {}
    for k, mode in enumerate(modes):
        name = f"n{k + 1}"
        servers[name] = ByzantineSyncServer(
            seed=seed, mode=mode, now_ns=c.clock.wall_ns,
            reserve_source=source,
        )
    c.byz_servers.update(servers)

    # deterministic coverage: three clients per mode run one hostile
    # session each, through the SAME seam organic rounds use
    for k, (name, byz) in enumerate(sorted(servers.items())):
        server_idx = int(name[1:])
        for j in range(3):
            client_idx = (len(modes) + 1 + 3 * k + j) % c.n
            if client_idx == server_idx:
                continue
            client = c.agents[f"n{client_idx}"]
            member = client.members.get(
                c.agents[name].actor_id
            )
            if member is not None:
                c._byz_session(client, member, byz)
    c.run_for(0.3)
    return {
        "servers": {nm: b.mode for nm, b in servers.items()},
        "reserve_actor": source.actor_id.hex(),
    }


def _virtual_snapshot_setup(c, family: str, seed: int) -> Dict:
    """The snapshot-cell pre-phase, run before the measured write
    workload: (1) a multi-writer HISTORY that converges everywhere;
    (2) maintenance-driven history compaction on every honest node
    (``_compaction_pass`` with the cell's retain-0 override), so every
    advertised floor covers the whole history and below-floor catch-up
    is snapshot-only; (3) wipes scheduled between each victim's crash
    and restart, turning the reborn nodes into FRESH bootstraps; and
    (4) for ``byz_snapshot_server``, the hostile doubles registered on
    real nodes n1..n3 plus deterministically-scheduled attack sessions
    against each reborn victim (organic rounds hit the hostiles too,
    but the campaign must not depend on sampling luck).

    The storm itself is DEFERRED (``VirtualCluster(defer_crashes=
    True)``): the plan's crash/restart times are offsets applied to
    the virtual clock AFTER this setup returns — the history must be
    converged and compacted below every floor before the first victim
    dies, and the setup's own convergence wait has no fixed duration.
    Writers stay on n0/n4/n6: victims are strided from n8 up and the
    byz doubles sit on n1..n3, so no writer is ever crashed, wiped,
    or hostile."""
    from corrosion_tpu.faults import ByzantineSnapshotServer

    writers = [0, min(4, c.n - 1), min(6, c.n - 1)]
    versions = []
    hist = 12
    for w in range(hist):
        origin = writers[w % len(writers)]
        v = c.write(
            origin,
            "INSERT INTO tests (id, text) VALUES (?, ?)",
            (7000 + w, f"storm-{w}"),
        )
        versions.append((c.agents[f"n{origin}"].actor_id, v))
        c.run_for(0.02)
    assert c.run_until_true(
        lambda: c.converged(versions), timeout=30
    ), "snapshot-cell history did not converge"

    servers = {}
    if family == "byz_snapshot_server":
        # honest nodes keep floor 0 here: containment must fall back
        # to CHANGE-BY-CHANGE via honest peers, so only the hostile
        # doubles advertise (fabricated) floors
        for k, mode in enumerate(ByzantineSnapshotServer.MODES):
            servers[f"n{k + 1}"] = ByzantineSnapshotServer(
                seed=seed, mode=mode
            )
        c.snap_byz.update(servers)
    else:
        for a in c.agents.values():
            a._compaction_pass()

    # the deferred storm: crash/restart offsets anchor at NOW (the
    # compacted, converged pre-state), wipes between each death and
    # rebirth turn the victims into fresh bootstraps
    t0 = c.clock.monotonic()
    c.schedule_plan_crashes(t0)
    for ev in c.plan.crashes:
        if ev.restart_at is not None:
            c.schedule_wipe(
                ev.node, t0 + (ev.at + ev.restart_at) / 2.0
            )

    if servers:
        # one scripted hostile session per (reborn victim, mode),
        # timed just after each rebirth while the victim is still
        # behind (dispatch otherwise declines: nothing to cover)
        ordered = sorted(servers.items())

        def _attack(victim: str, sname: str, double) -> None:
            if victim in c._crashed or sname in c._crashed:
                return
            client = c.agents[victim]
            hostile = c.agents[sname]
            member = client.members.get(hostile.actor_id)
            if member is not None:
                c._vsnap_byz(client, member, double, int(sname[1:]))

        for ev in c.plan.crashes:
            if ev.restart_at is None:
                continue
            for k, (sname, double) in enumerate(ordered):
                c.clock.schedule_at(
                    t0 + ev.restart_at + 0.05 + k * 0.01,
                    lambda _d, v=ev.node, s=sname, b=double:
                        _attack(v, s, b),
                )
    return {
        "history": hist,
        "history_versions": versions,
        "servers": {nm: b.mode for nm, b in servers.items()},
        "victims": [ev.node for ev in c.plan.crashes],
    }


def virtual_scenario_cell(
    family: str,
    n: int = 64,
    seed: int = 0,
    writes: int = 6,
    heal_after: float = 0.64,
    stall_ms: float = 150.0,
    timeout: float = 60.0,
    base_dir: Optional[str] = None,
    probe_interval: Optional[float] = None,
) -> Dict:
    """One matrix/scale cell on the virtual-time cluster; returns the
    same gated record shape as :func:`agent_scenario_cell` (plus
    ``runtime: "virtual"`` and the virtual/wall split), so the
    artifact lint and campaign assertions apply unchanged.
    ``timeout`` is VIRTUAL seconds — the wall cost is just the events.
    """
    import time as _time

    from corrosion_tpu.sim.vcluster import VirtualCluster

    plan = build_virtual_plan(family, seed, heal_after, stall_ms, n)
    overrides = {}
    if probe_interval is not None:
        overrides["probe_interval"] = probe_interval
    elif n >= 256:
        # probes are O(N) per event: at scale a coarser cadence keeps
        # the event count linear without touching the campaign's
        # dynamics (suspicion is neutralized by suspect_timeout=10
        # exactly like the live cells)
        overrides["probe_interval"] = 1.0
    signed = family in SIGNED_FAMILIES
    if signed:
        # signed cluster: per-node keypairs + spot checks live (the
        # spot-check interval bound keeps pure-Python verification off
        # the campaign's critical path)
        overrides["sig_spot_check_rate"] = 0.05
    if family in SNAP_FAMILIES:
        # retain-0: every contained version is compactable, so the
        # 12-version cell history sits entirely below the floors the
        # setup phase advances — dispatch genuinely chooses snapshot
        overrides["snapshot_retain_versions"] = 0
    wall0 = _time.perf_counter()
    c = VirtualCluster(
        n, seed=seed, plan=plan, base_dir=base_dir, sign=signed,
        defer_crashes=family in SNAP_FAMILIES,
        **overrides,
    )
    try:
        if plan.partition_blocks > 1:
            c.ctrl.split()

        hostile = None
        framing = None
        byz = None
        snap = None
        k_hostile = _hostile_count(family)
        if family == "framing_relay":
            framing = _virtual_framing_relay(c, seed)
        elif family == "byz_sync_server":
            byz = _virtual_byz_sync(c, seed)
        elif family in SNAP_FAMILIES:
            snap = _virtual_snapshot_setup(c, family, seed)
        elif k_hostile:
            hostile = _virtual_hostile_attack(
                c, seed, k_hostile,
                mid_heal=(family == "equiv_during_heal"),
                heal_after=heal_after,
                signed=signed,
            )

        # write workload: one writer per partition block, else strided
        if plan.partition_blocks > 1:
            other = next(
                i for i in range(n)
                if plan.block_of(i, n) != plan.block_of(0, n)
            )
            writers = [0, other]
        elif family in SNAP_FAMILIES:
            # setup deferred the storm to fire right after this
            # workload: keep the writers clear of the strided victims
            # (n8 up) and the byz doubles (n1..n3)
            writers = sorted({0, min(4, n - 1), min(6, n - 1)})
        else:
            writers = list(range(0, n, max(1, n // 3)))[:3] or [0]
        t0v = c.clock.monotonic()
        versions = []
        for w in range(writes):
            origin = writers[w % len(writers)]
            v = c.write(
                origin,
                "INSERT INTO tests (id, text) VALUES (?, ?)",
                (8000 + w, f"{family}-{w}"),
            )
            versions.append((c.agents[f"n{origin}"].actor_id, v))
            c.run_for(0.02)

        want_crash_events = len(plan.crashes) + sum(
            1 for ev in plan.crashes if ev.restart_at is not None
        )
        # every snapshot-install fault is one EXTRA death + rebirth on
        # top of the scheduled storm (faults.SnapFault is one-shot)
        want_crash_events += 2 * len(plan.snap_faults)

        def settled() -> bool:
            if plan.crashes:
                # the WHOLE schedule must have run (convergence before
                # the first crash is not the cell's question) and every
                # reborn node must be back AND caught up
                if len(c.ctrl.crash_log) < want_crash_events \
                        or c._crashed:
                    return False
            return c.converged(versions)

        converged_ok = c.run_until_true(settled, timeout=timeout)
        virt_s = c.clock.monotonic() - t0v
        # one more snapshot interval so the end state reaches the rings
        c.run_for(0.3)

        restart_probe_version = None
        if family == "signed_equivocator" and hostile is not None:
            # the permanent verdict must survive the victim restart
            # the plan injected: a fresh well-formed SIGNED version
            # from the proven equivocator still drops on every node —
            # including the reborn one, whose proof reloaded from
            # __corro_equiv_proofs at boot
            from corrosion_tpu.types import ChangeSource as _CS

            peer = hostile["hostile_peers"][0]
            probe_cv = peer.honest(9105, "post-restart")
            restart_probe_version = int(probe_cv.changeset.version)
            c.inject(list(range(n)), probe_cv, _CS.BROADCAST,
                     rebroadcast=False,
                     sig=peer.sign_changeset(probe_cv))
            c.run_for(0.2)

        obs = c.observer()
        scrape = obs.scrape()
        lag = obs.convergence_lag()
        nodiv = obs.no_divergence()
        equiv = obs.equivocations(scrape)
        loop_health = obs.loop_health(scrape)
        events = obs.flight_events()
        kind_counts: Dict[str, int] = {}
        for e in events:
            kind_counts[e["kind"]] = kind_counts.get(e["kind"], 0) + 1
        timeline = {
            "snapshots": len(obs.flight_timeline(kind="snap")),
            "event_counts": kind_counts,
            "events": [
                {
                    "node": e["node"], "kind": e["kind"],
                    "hlc": e["hlc"], "wall": round(e["wall"], 3),
                    "attrs": e.get("attrs", {}),
                }
                for e in events[-200:]
            ],
            "coverage": obs.coverage_curve(versions),
        }

        gates = {
            "converged": converged_ok,
            "no_divergence": nodiv["ok"],
            "lags_non_negative": all(
                s >= 0.0
                for nm, a in c.agents.items() if nm not in c._crashed
                for ring in a.metrics.histogram_samples(
                    "corro_change_lag_seconds"
                ).values()
                for s in ring
            ),
        }
        detail: Dict = {}
        live_agents = [
            a for nm, a in c.agents.items() if nm not in c._crashed
        ]
        if family in ("clock_skew", "compound", "skew_during_restart"):
            skews = {nm: plan.node_clock(nm)[0] for nm in c.agents}
            gates["skew_applied"] = any(
                abs(v) > 0 for v in skews.values()
            )
            detail["clock_skew_ns_nonzero"] = sum(
                1 for v in skews.values() if v
            )
        if plan.partition_blocks > 1:
            gates["partition_fired"] = c.ctrl.injected["partition"] > 0
        if family == "slow_io":
            gates["disk_delays_fired"] = c.ctrl.injected["disk"] > 0
            gates["stall_injected"] = (
                c.ctrl.injected["stall"] >= len(plan.loop_stalls)
            )
            gates["stall_observed"] = any(
                max(
                    (s for ring in a.metrics.histogram_samples(
                        "corro_loop_stall_ms"
                    ).values() for s in ring),
                    default=0.0,
                ) >= 0.5 * stall_ms
                for a in live_agents
            )
        if plan.crashes:
            gates["crash_schedule_ran"] = (
                len(c.ctrl.crash_log) == want_crash_events
                and not c._crashed
            )
            detail["crashes"] = len(plan.crashes)
        def _count_like(a, pat):
            _, rows = a.storage.read_query(
                "SELECT COUNT(*) FROM tests WHERE text LIKE ?",
                (pat,),
            )
            return rows[0][0]

        if k_hostile and hostile is not None:
            actors = hostile["hostile_actors"]
            keyed = set(hostile["keyed_actors"])
            reborn_names = {
                node for _t, ev, node in c.ctrl.crash_log
                if ev == "restart"
            }

            def _member_verdict_ok(nm, a, actor) -> bool:
                if actor not in keyed and nm in reborn_names:
                    # UNSIGNED verdicts are in-memory by design (a
                    # bounded window for forgeable attribution): a
                    # reborn victim legitimately starts clean and
                    # re-convicts on the next conflicting re-send.
                    # Only SIGNED proofs must survive the restart
                    return True
                expected = ("signed_equivocation" if actor in keyed
                            else "equivocation")
                m = a.members.get(actor)
                if m is None:
                    # a reborn node re-learns hostile records lazily;
                    # the verdict itself (reloaded from the proof
                    # store) is what must hold
                    return actor in a._equiv_quarantined
                return m.quarantined and m.quarantine_reason == expected

            gates["content_detected"] = (
                equiv.get("content", 0) >= k_hostile
            )
            gates["span_detected"] = equiv.get("span", 0) >= 1
            gates["hostile_quarantined_everywhere"] = (
                hostile["quarantined_everywhere"]
                and all(
                    _member_verdict_ok(nm, a, actor)
                    for nm, a in c.agents.items()
                    if nm not in c._crashed
                    for actor in actors
                )
            )
            if signed:
                # keyed hostiles were convicted by PROOF: permanent
                # verdicts (deadline = inf) on every live node
                gates["signed_verdict_permanent"] = all(
                    a._equiv_quarantined.get(actor) == float("inf")
                    for a in live_agents
                    for actor in keyed
                )
            if restart_probe_version is not None:
                reborn = [
                    c.agents[node]
                    for _t, ev, node in c.ctrl.crash_log
                    if ev == "restart" and node not in c._crashed
                ]
                gates["proof_survived_restart"] = bool(reborn) and all(
                    not a.bookie.for_actor(actor).contains_version(
                        restart_probe_version
                    )
                    for a in live_agents
                    for actor in keyed
                ) and all(
                    a._equiv_quarantined.get(actor) == float("inf")
                    for a in reborn
                    for actor in keyed
                )
                gates["zero_post_restart_rows"] = all(
                    _count_like(a, "post-restart") == 0
                    for a in live_agents
                )

            gates["zero_divergent_rows"] = all(
                _count_like(a, "equiv-b-%") == 0
                and _count_like(a, "garbage-%") == 0
                and _count_like(a, "wide-%") == 0
                and _count_like(a, "post-quarantine") == 0
                for a in live_agents
            )
            detail["hostiles"] = k_hostile
            detail["equivocations"] = equiv

        if framing is not None:
            origin_actor = framing["origin_actor"]
            # the headline negative control, in-record: the framed
            # honest origin is quarantined on ZERO nodes — neither the
            # verdict map nor the membership flag — while every victim
            # observed the signature failure and convicted the relay's
            # transport identity
            # "never quarantined" means never CONVICTED: no node may
            # hold an attribution-class verdict (equivocation /
            # signed_equivocation / sig_failure) against the origin.
            # Plain transport-breaker quarantine is excluded — the
            # harness-crafted origin has no real socket, so nodes that
            # sample it for fanout legitimately open its address
            # breaker (evidence about reachability, not authorship)
            _verdict_reasons = (
                "equivocation", "signed_equivocation", "sig_failure",
            )
            origin_quarantined = [
                nm for nm, a in c.agents.items()
                if origin_actor in a._equiv_quarantined
                or (a.members.get(origin_actor) is not None
                    and a.members.get(origin_actor).quarantined
                    and a.members.get(origin_actor).quarantine_reason
                    in _verdict_reasons)
            ]
            gates["origin_never_quarantined"] = not origin_quarantined

            def _victim_blamed(a) -> bool:
                # monotone evidence (breaker flags are transient by
                # design — half-open recovery is the point of the
                # bounded relay verdict): the node verified at least
                # one failing signature AND recorded the sig_failure
                # quarantine transition for the relay's transport
                return (
                    a.metrics.get_counter(
                        "corro_sig_verifications_total", result="fail"
                    ) >= 1
                    and a.metrics.get_counter(
                        "corro_members_quarantine_transitions_total",
                        state="sig_failure",
                    ) >= 1
                )

            gates["relay_blamed_everywhere"] = all(
                _victim_blamed(c.agents[f"n{i}"])
                for i in framing["victims"]
            )
            gates["zero_tampered_rows"] = all(
                _count_like(a, "tampered-%") == 0 for a in live_agents
            )
            detail["framing"] = {
                "origin": framing["origin"],
                "relay": framing["relay"],
                "origin_quarantined_nodes": len(origin_quarantined),
                "victims": len(framing["victims"]),
                "sig_fail_verifications": sum(
                    a.metrics.get_counter(
                        "corro_sig_verifications_total", result="fail"
                    )
                    for a in live_agents
                ),
            }

        if byz is not None:
            rejects: Dict[str, float] = {}
            for parsed in scrape.values():
                fam_ = parsed.get("corro_sync_client_rejects_total")
                if fam_ is None:
                    continue
                for _n2, labels, v in fam_["samples"]:
                    r = labels.get("reason", "?")
                    rejects[r] = rejects.get(r, 0.0) + v
            for reason in ("advertised_range", "need_cap",
                           "frame_garbage", "deadline"):
                gates[f"rejected_{reason}"] = rejects.get(reason, 0) >= 1
            gates["zero_reserve_rows"] = all(
                _count_like(a, "byz-reserve-%") == 0
                for a in live_agents
            )
            detail["byz"] = {
                "servers": byz["servers"],
                "client_rejects": rejects,
            }

        if snap is not None:
            reborn_nodes = sorted({
                node for _t, ev2, node in c.ctrl.crash_log
                if ev2 == "restart" and node not in c._crashed
            })
            installs_ok = {
                nm: c.agents[nm].metrics.get_counter(
                    "corro_snapshot_installs_total", result="ok"
                )
                for nm in reborn_nodes
            }
            serves = sum(
                a.metrics.get_counter("corro_snapshot_serves_total")
                for a in live_agents
            )
            snap_rejects = sum(
                a.metrics.get_counter(
                    "corro_sync_client_rejects_total",
                    reason="snap_digest",
                )
                for a in live_agents
            )
            recoveries = {}
            for a in live_agents:
                for stage in ("retry", "finalized"):
                    n_rec = a.metrics.get_counter(
                        "corro_snapshot_recoveries_total", stage=stage
                    )
                    if n_rec:
                        recoveries[stage] = (
                            recoveries.get(stage, 0) + n_rec
                        )
            # the pre-storm history must be contained everywhere too —
            # on reborn nodes it can ONLY have arrived via the
            # snapshot path (honest floors cover it) or the
            # change-by-change fallback (the byz cell's honest peers)
            gates["history_converged"] = c.converged(
                snap["history_versions"]
            )
            if family == "restart_storm_snapshot":
                gates["reborn_installed_via_snapshot"] = bool(
                    reborn_nodes
                ) and all(v >= 1 for v in installs_ok.values())
                gates["snapshots_served"] = serves >= len(reborn_nodes)
            if family == "byz_snapshot_server":
                # containment: every victim rejected hostile serves on
                # the digest gate, NOTHING installed cluster-wide (the
                # honest peers advertise no floors — fallback is
                # genuinely change-by-change), no tampered row exists
                gates["rejected_snap_digest"] = (
                    snap_rejects >= len(snap["victims"])
                )
                gates["hostile_never_installed"] = all(
                    v == 0 for v in installs_ok.values()
                ) and sum(
                    a.metrics.get_counter(
                        "corro_snapshot_installs_total", result="ok"
                    )
                    for a in live_agents
                ) == 0
                gates["zero_tampered_rows"] = all(
                    _count_like(a, "evil%") == 0 for a in live_agents
                )
            if family == "crash_mid_install":
                # every injected stage fired, both recovery outcomes
                # were exercised (mid-stage crashes → clean retry; a
                # post-swap crash → finalized boot), and the retries
                # completed real installs
                gates["snap_crashes_fired"] = (
                    c.ctrl.injected["snap_crash"]
                    == len(plan.snap_faults)
                )
                gates["recovery_retry_seen"] = (
                    recoveries.get("retry", 0) >= 2
                )
                gates["recovery_finalized_seen"] = (
                    recoveries.get("finalized", 0) >= 1
                )
                gates["retries_installed"] = sum(
                    installs_ok.values()
                ) >= len(plan.snap_faults) - 1
            detail["snapshot"] = {
                "history": snap["history"],
                "victims": snap["victims"],
                "servers": snap["servers"],
                "reborn": len(reborn_nodes),
                "installs_ok": sum(installs_ok.values()),
                "snapshots_served": serves,
                "snap_digest_rejects": snap_rejects,
                "recoveries": recoveries,
            }

        return {
            "runtime": "virtual",
            "family": family,
            "n_nodes": n,
            "seed": seed,
            "writes": writes,
            "virtual_to_converge_s": round(virt_s, 3),
            "wall_s": round(_time.perf_counter() - wall0, 3),
            "wall_to_converge_s": round(virt_s, 3),
            "live_p99_s": lag.get("p99_s"),
            "live_p50_s": lag.get("p50_s"),
            "lag_samples": lag.get("count", 0),
            "msgs_per_node": round(obs.msgs_per_node(scrape), 2),
            "loop_health": loop_health,
            "injected": dict(c.ctrl.injected),
            "no_divergence": nodiv,
            "state_checksum": c.state_checksum(),
            "timeline": timeline,
            "gates": gates,
            "passed": all(gates.values()),
            "detail": detail,
        }
    finally:
        c.close()


def run_virtual_scenarios(
    n: int = 512,
    seed: int = 0,
    families: Optional[List[str]] = None,
    sim_seeds: int = 8,
    heal_after: float = 0.64,
    out_path: Optional[str] = None,
    base_dir: Optional[str] = None,
    sim: bool = True,
) -> Dict:
    """The virtual-time campaign: every matrix family PLUS the
    scale-only cells at N=512–1024, each next to the kernel prediction
    where the kernel models the family, one JSON artifact, all gates
    asserted in-record — in seconds of wall time."""
    import os
    import time as _time

    families = list(families or VIRTUAL_FAMILIES)
    unknown = [f for f in families if f not in VIRTUAL_FAMILIES]
    if unknown:
        raise ValueError(
            f"unknown virtual families {unknown}; "
            f"valid: {VIRTUAL_FAMILIES}"
        )
    wall0 = _time.perf_counter()
    results = {}
    # the fault-free prediction is identical for every family the
    # kernel doesn't model (skew / slow IO / hostile peers): compute
    # it once — at N=512 each kernel run costs real seconds
    pred_cache: Dict[str, Dict] = {}
    for family in families:
        i = VIRTUAL_FAMILIES.index(family)
        cell_dir = (
            os.path.join(base_dir, family) if base_dir else None
        )
        prediction = None
        if sim and family in FAMILIES:
            pkey = (
                family if family in ("asym_partition", "compound")
                else "_fault_free"
            )
            prediction = pred_cache.get(pkey)
            if prediction is None:
                prediction = pred_cache[pkey] = sim_prediction(
                    family, n, heal_after, seeds=sim_seeds
                )
        try:
            cell = virtual_scenario_cell(
                family, n=n, seed=seed + i, heal_after=heal_after,
                base_dir=cell_dir, timeout=120.0,
            )
        except Exception as e:  # noqa: BLE001 - one cell crashing
            # must not discard the completed cells' results
            cell = {
                "runtime": "virtual",
                "family": family,
                "n_nodes": n,
                "seed": seed + i,
                "error": f"{type(e).__name__}: {e}",
                "live_p99_s": None,
                "msgs_per_node": None,
                "no_divergence": {"ok": False, "violations": []},
                "timeline": None,
                "gates": {"converged": False},
                "passed": False,
            }
        pred_p99 = None
        if prediction is not None:
            pred_p99 = prediction.get("predicted_wall_p99_s")
            if pred_p99 is None and prediction.get(
                "ticks_to_converge_p99"
            ) is not None:
                pred_p99 = prediction["ticks_to_converge_p99"] * TICK_S
        results[family] = {
            "agents": cell,
            "sim": prediction,
            "diff": {
                "live_p99_s": cell["live_p99_s"],
                "kernel_predicted_wall_p99_s": pred_p99,
                "msgs_per_node_live": cell["msgs_per_node"],
                "msgs_per_node_kernel": (
                    prediction.get("msgs_per_node")
                    if prediction else None
                ),
            },
        }

    all_passed = all(r["agents"]["passed"] for r in results.values())
    no_div = all(
        r["agents"]["no_divergence"]["ok"] for r in results.values()
    )
    wall_total = round(_time.perf_counter() - wall0, 3)
    # the acceptance budget's subject is the FIVE-FAMILY MATRIX (the
    # live campaign's shape re-run at scale); the scale-only cells ride
    # along in the same artifact with their own cost on top
    wall_matrix = round(
        sum(
            r["agents"].get("wall_s", 0.0)
            for f, r in results.items() if f in FAMILIES
        ),
        3,
    )
    out = {
        "n_nodes": n,
        "metric": "virtual_time_adversarial_scenario_matrix",
        "runtime": "virtual",
        "families": list(results),
        "all_cells_converged": all(
            r["agents"]["gates"].get("converged", False)
            for r in results.values()
        ),
        "no_divergence_all_cells": no_div,
        "all_gates_passed": all_passed,
        "tick_seconds": TICK_S,
        "wall_s_total": wall_total,
        "wall_s_matrix": wall_matrix,
        "cells": results,
    }
    if not all_passed:
        out["error"] = "one or more scenario gates failed"
    if out_path:
        with open(out_path, "w") as f:
            json.dump(out, f, indent=1, allow_nan=False)
            f.write("\n")
    return out
