"""Adversarial scenario matrix: co-simulation campaigns over the four
hostile fault families, gated on convergence + NO-DIVERGENCE.

CHAOS validated the live cluster against the kernel under the sim's own
fault family (loss / partition / churn).  This module runs the matrix
the BFT-simulation literature demands for trustworthy headline numbers
(PAPERS.md: "Simulating BFT Protocol Implementations at Scale" runs
implementations against adversarial scenarios next to a model;
"CRDT Emulation, Simulation, and Representation Independence" motivates
the no-divergence property as the gate):

* ``clock_skew``     — per-node HLC offset + drift at the ``HLClock``
  seam (``types/hlc.py skewed_now_ns``), exercising the 300 ms
  max-delta gossip-clock rule and the provenance negative-lag clamp;
* ``asym_partition`` — a ONE-WAY partition (``FaultPlan.oneway_blocks``)
  healing by wall clock: the severed direction drops while the reverse
  keeps flowing — the TOCTOU-hardened ``open_bi`` recheck applies
  per direction;
* ``slow_io``        — seeded slow-disk delays at the storage
  write/collect seams plus a scheduled event-loop stall, observed by
  the agents' own ``LoopHealthProbe``;
* ``equivocation``   — a hostile origin re-claiming an accepted
  ``(actor, version)`` with conflicting contents, replaying duplicates,
  and shipping garbage seq spans; agents must detect
  (``corro_sync_equivocations_total``), quarantine (``Members`` path),
  and accept zero divergent rows;
* ``compound``       — loss + one-way partition + clock skew at once.

Every cell runs a live in-process cluster next to the epidemic kernel's
prediction (the CHAOS/OBS comparison), scraped through
``ClusterObserver``, and gates on:

1. full convergence of the cell's write workload;
2. ``ClusterObserver.no_divergence()`` — bytewise-equal table state,
   consistent bookkeeping ledgers, one accepted content per
   ``(actor, version)``;
3. family-specific assertions (skew applied, stall observed, hostile
   actor quarantined with zero divergent rows, ...).

``bench.py --scenarios`` writes the matrix to ``SCENARIOS_N32.json``;
``tests/test_scenarios.py`` runs one small cell per family in tier-1
and the full N=32 matrix under ``@slow``.
"""

from __future__ import annotations

import asyncio
import json
import time
from typing import Dict, List, Optional

from corrosion_tpu.faults import (
    EquivocatingPeer,
    FaultController,
    FaultPlan,
    LoopStall,
)

# the simdiff/chaos time base: one kernel tick ≈ the agents' broadcast
# flush interval (launch_test_agent pins bcast_flush_interval=0.02)
TICK_S = 0.02

FAMILIES = (
    "clock_skew",
    "asym_partition",
    "slow_io",
    "equivocation",
    "compound",
)


def build_plan(family: str, seed: int, heal_after: float,
               stall_ms: float) -> FaultPlan:
    """The seeded FaultPlan for one matrix cell.  Parameters sit at
    the aggressive end of what a WAN deployment sees: 200 ms skew
    straddles the 300 ms max-delta rule once drift accumulates, 2–6 ms
    per-IO delays are a saturated disk, a ~stall_ms loop stall is a GC
    pause / noisy neighbor."""
    if family == "clock_skew":
        return FaultPlan(
            seed=seed,
            clock_skew_max_ns=200_000_000,  # ±200 ms constant offset
            clock_drift_max_ppm=200.0,      # ±200 ppm linear drift
        )
    if family == "asym_partition":
        return FaultPlan(
            seed=seed,
            partition_blocks=2,
            oneway_blocks=((0, 1),),  # block0 → block1 severed only
            heal_after=heal_after,
        )
    if family == "slow_io":
        return FaultPlan(
            seed=seed,
            disk_write_delay=0.002,
            disk_write_jitter=0.004,
            disk_read_delay=0.002,
            disk_read_jitter=0.004,
            loop_stalls=(LoopStall("n0", at=0.05, duration_ms=stall_ms),),
        )
    if family == "equivocation":
        return FaultPlan(seed=seed)
    if family == "compound":
        return FaultPlan(
            seed=seed,
            drop=0.05,
            partition_blocks=2,
            oneway_blocks=((0, 1),),
            heal_after=heal_after,
            clock_skew_max_ns=150_000_000,
        )
    raise ValueError(f"unknown scenario family {family!r}")


def sim_prediction(family: str, n: int, heal_after: float,
                   seeds: int = 8) -> Dict:
    """The epidemic kernel's prediction for the cell, with its
    modeling residual named.  The kernel models loss + SYMMETRIC
    partitions; skew / slow IO / equivocation do not change its
    message dynamics, so those cells compare against the fault-free
    (or loss-only) prediction and record the residual."""
    from corrosion_tpu.sim.chaos import sim_chaos_trace
    from corrosion_tpu.sim.obs import sim_obs_trace

    heal_tick = max(1, int(round(heal_after / TICK_S)))
    if family in ("asym_partition", "compound"):
        loss = 0.05 if family == "compound" else 0.0
        pred = sim_chaos_trace(
            n, loss=loss, partition_blocks=2, heal_tick=heal_tick,
            seeds=seeds,
        )
        pred["residual"] = (
            "kernel partitions are symmetric; the live cell severs one "
            "direction only, so its reachable direction keeps flowing "
            "and live convergence reads at or below this prediction"
        )
        return pred
    pred = sim_obs_trace(n, seeds=seeds)
    pred["residual"] = (
        "the kernel does not model clock skew / disk latency / hostile "
        "peers — they alter timestamps, lock holds and screening, not "
        "the message dynamics — so the cell compares against the "
        "fault-free prediction"
    )
    return pred


async def _deliver(agent, cv, source) -> None:
    """Feed one crafted changeset into an agent's REAL ingest pipeline
    (bounded queue → change loop → apply workers), loop-affine."""
    agent.enqueue_change(cv, source)


async def _run_hostile_attack(agents: Dict[str, "object"],
                              seed: int, wait_for) -> Dict:
    """The equivocating-peer script: bait → conflicting re-send (split
    across the broadcast and sync detection sites) → replayed
    duplicates → garbage spans (from a SECOND hostile actor, since the
    first is quarantined the moment its conflict is seen) →
    post-quarantine probe.  Returns what the harness knows
    ground-truth about, for the cell's gates."""
    from corrosion_tpu.types import ChangeSource

    peer = EquivocatingPeer(seed=seed)
    spanner = EquivocatingPeer(seed=seed + 1000)
    targets = list(agents.values())
    # the hostile peers "joined" the cluster before turning: make them
    # members everywhere so quarantine has a record to mark (and the
    # admin cluster_members output a row to show)
    for a in targets:
        a.members.upsert(peer.actor_id, ("127.0.0.1", 9))
        a.members.upsert(spanner.actor_id, ("127.0.0.1", 10))

    def all_contain(version: int):
        return all(
            a.bookie.for_actor(peer.actor_id).contains_version(version)
            for a in targets
        )

    # 1. bait: a well-formed version accepted everywhere
    bait = peer.honest(9100, "bait")
    for a in targets:
        await _deliver(a, bait, ChangeSource.BROADCAST)
    await wait_for(lambda: all_contain(1), timeout=20)

    # 2. conflicting contents for ONE version: content A accepted
    #    everywhere first, then content B re-claims it on the gossip
    #    path.  Detection is BROADCAST-scope by design: gossiped bytes
    #    are immutable per version, while sync re-serves legitimately
    #    reflect serve-time compaction (docs/faults.md)
    a_cv, b_cv = peer.conflicting_pair(9101)
    for a in targets:
        await _deliver(a, a_cv, ChangeSource.BROADCAST)
    await wait_for(lambda: all_contain(2), timeout=20)
    for a in targets:
        await _deliver(a, b_cv, ChangeSource.BROADCAST)
    # replayed duplicates of the ACCEPTED content: absorbed on both
    # paths, never counted as equivocation
    for i, a in enumerate(targets):
        src = ChangeSource.BROADCAST if i % 2 == 0 else ChangeSource.SYNC
        await _deliver(a, a_cv, src)

    # 3. garbage seq spans (screened before any buffering) — from the
    #    second hostile actor, which is not yet quarantined
    garbage = spanner.garbage_span(9102)
    wide = spanner.absurd_width(9103)
    for a in targets:
        await _deliver(a, garbage, ChangeSource.BROADCAST)
        await _deliver(a, wide, ChangeSource.SYNC)

    # 4. wait for every node to have detected + quarantined BOTH
    def all_quarantined():
        return all(
            peer.actor_id in a._equiv_quarantined
            and spanner.actor_id in a._equiv_quarantined
            for a in targets
        )

    await wait_for(all_quarantined, timeout=20)

    # 5. post-quarantine probe: a fresh well-formed version must DROP
    post = peer.honest(9104, "post-quarantine")
    for a in targets:
        await _deliver(a, post, ChangeSource.BROADCAST)

    return {
        "actor": peer.actor_id.hex(),
        "span_actor": spanner.actor_id.hex(),
        "accepted_versions": [1, 2],
        "post_quarantine_version": int(post.changeset.version),
    }


async def agent_scenario_cell(
    family: str,
    n: int = 9,
    seed: int = 0,
    writes: int = 6,
    heal_after: float = 0.8,
    stall_ms: float = 150.0,
    timeout: float = 60.0,
    base_dir: Optional[str] = None,
) -> Dict:
    """Run one matrix cell on a live cluster; returns the measurement
    record with its ``gates`` dict (every gate must be True)."""
    from corrosion_tpu.agent.testing import seed_full_membership, wait_for
    from corrosion_tpu.devcluster import (
        ClusterObserver,
        Topology,
        run_inprocess,
        run_stall_schedule,
    )

    plan = build_plan(family, seed, heal_after, stall_ms)
    ctrl = FaultController(plan)
    topo = Topology.parse("\n".join(f"n0 -> n{i}" for i in range(1, n)))
    agents = await run_inprocess(
        topo,
        base_dir=base_dir,
        faults=ctrl,
        ring0_enabled=False,   # uniform sampling: the kernel's model
        subs_enabled=False,
        api_port=None,
        uni_cache_size=16,
        suspect_timeout=10.0,  # faults must not down-mark the cluster
        breaker_cooldown=0.5,
        # fast flight snapshots: even a short tier-1 cell's timeline
        # attachment carries real metric history, not just events
        flight_interval_s=0.25,
    )
    stall_task = None
    try:
        await wait_for(
            lambda: all(
                len(a.members.alive()) == n - 1 for a in agents.values()
            ),
            timeout=max(30.0, 2.0 * n),
        )
        seed_full_membership(list(agents.values()))
        obs = ClusterObserver(agents, faults=ctrl)
        obs.mark()

        # stall-probe sample cursor per node: the boot of N in-process
        # agents stalls the shared loop too (synchronous schema DDL),
        # so the stall gate must look only at samples recorded AFTER
        # the schedule arms.  The cursor is the CUMULATIVE histogram
        # count (monotone, trim-immune) — the value ring itself trims
        # past ~1279 samples, so a stored index would drift
        def _stall_ring(a):
            rings = a.metrics.histogram_samples("corro_loop_stall_ms")
            return next(iter(rings.values()), [])

        def _stall_count(a):
            n, _s = a.metrics.histogram_stats("corro_loop_stall_ms")
            return n

        pre_stall_counts = {
            name: _stall_count(a) for name, a in agents.items()
        }

        def _new_stall_samples(name):
            a = agents[name]
            n_new = _stall_count(a) - pre_stall_counts[name]
            if n_new <= 0:
                return []
            return _stall_ring(a)[-n_new:]

        ctrl.restart_clock()
        if plan.partition_blocks > 1:
            ctrl.split()
        if plan.loop_stalls:
            stall_task = asyncio.ensure_future(run_stall_schedule(ctrl))

        hostile = None
        if family == "equivocation":
            hostile = await _run_hostile_attack(agents, seed, wait_for)

        # spread write workload; under a partition, one writer per
        # block so only post-heal machinery can reach the union.  The
        # second writer is the FIRST index whose block differs
        # (block_of is idx*blocks//n — ceil(n/blocks), not n//blocks)
        names = list(agents)
        if plan.partition_blocks > 1:
            other = next(
                i for i in range(n)
                if plan.block_of(i, n) != plan.block_of(0, n)
            )
            writers = [names[0], names[other]]
        else:
            writers = names[:: max(1, n // 3)]
        t0 = time.perf_counter()
        versions = []
        for w in range(writes):
            origin = agents[writers[w % len(writers)]]
            res = await asyncio.to_thread(
                origin.execute_transaction,
                [("INSERT INTO tests (id, text) VALUES (?, ?)",
                  (8000 + w, f"{family}-{w}"))],
            )
            versions.append((origin.actor_id, res["version"]))
            await asyncio.sleep(0.02)

        def converged() -> bool:
            for a in agents.values():
                for actor, v in versions:
                    if a.actor_id != actor and not a.bookie.for_actor(
                        actor
                    ).contains_version(v):
                        return False
            return True

        converged_ok = True
        try:
            await wait_for(converged, timeout=timeout, interval=0.02)
        except TimeoutError:
            # a non-converging cell is a RESULT, not a crash: record
            # the failed gate so the campaign artifact names it
            converged_ok = False
        wall = time.perf_counter() - t0
        if stall_task is not None:
            try:
                await asyncio.wait_for(stall_task, timeout=timeout)
            except asyncio.TimeoutError:
                stall_task.cancel()
            stall_task = None

        scrape = obs.scrape()
        lag = obs.convergence_lag()
        nodiv = obs.no_divergence()
        equiv = obs.equivocations(scrape)
        loop_health = obs.loop_health(scrape)

        # the cell's flight-recorder attachment: a red cell ships its
        # own post-mortem — the merged typed-event journal (bounded),
        # snapshot count, and the write waves' coverage trajectory
        events = obs.flight_events()
        kind_counts: Dict[str, int] = {}
        for e in events:
            kind_counts[e["kind"]] = kind_counts.get(e["kind"], 0) + 1
        timeline = {
            "snapshots": len(obs.flight_timeline(kind="snap")),
            "event_counts": kind_counts,
            "events": [
                {
                    "node": e["node"], "kind": e["kind"],
                    "hlc": e["hlc"], "wall": round(e["wall"], 3),
                    "attrs": e.get("attrs", {}),
                }
                for e in events[-200:]
            ],
            "coverage": obs.coverage_curve(versions),
        }

        gates = {
            "converged": converged_ok,
            "no_divergence": nodiv["ok"],
            # the provenance negative-lag clamp: a skewed-ahead origin
            # must clamp to 0, never record negative
            "lags_non_negative": all(
                s >= 0.0
                for a in agents.values()
                for ring in a.metrics.histogram_samples(
                    "corro_change_lag_seconds"
                ).values()
                for s in ring
            ),
        }
        detail: Dict = {}
        if family in ("clock_skew", "compound"):
            skews = {
                name: plan.node_clock(name)[0] for name in agents
            }
            gates["skew_applied"] = any(abs(v) > 0 for v in skews.values())
            detail["clock_skew_ns"] = skews
        if family == "asym_partition" or family == "compound":
            gates["partition_fired"] = ctrl.injected["partition"] > 0
        if family == "slow_io":
            gates["disk_delays_fired"] = ctrl.injected["disk"] > 0
            gates["stall_injected"] = ctrl.injected["stall"] >= len(
                plan.loop_stalls
            )
            # the agents' OWN probe must have seen the injected stall —
            # judged on post-boot samples only (the sample cursor)
            gates["stall_observed"] = any(
                max(_new_stall_samples(name), default=0.0)
                >= 0.5 * stall_ms
                for name in agents
            )
        if family == "equivocation":
            hostile_actors = [
                bytes.fromhex(hostile["actor"]),
                bytes.fromhex(hostile["span_actor"]),
            ]
            gates["content_detected"] = equiv.get("content", 0) >= 1
            gates["span_detected"] = equiv.get("span", 0) >= 1
            gates["hostile_quarantined_everywhere"] = all(
                actor in a._equiv_quarantined
                and (a.members.get(actor) is not None
                     and a.members.get(actor).quarantined
                     and a.members.get(actor).quarantine_reason
                     == "equivocation")
                for a in agents.values()
                for actor in hostile_actors
            )
            # zero divergent rows: no node ever applied the conflicting
            # re-send, the garbage spans, or post-quarantine traffic
            def _count_like(a, pat):
                _, rows = a.storage.read_query(
                    "SELECT COUNT(*) FROM tests WHERE text LIKE ?",
                    (pat,),
                )
                return rows[0][0]

            gates["zero_divergent_rows"] = all(
                _count_like(a, "equiv-b-%") == 0
                and _count_like(a, "garbage-%") == 0
                and _count_like(a, "wide-%") == 0
                and _count_like(a, "post-quarantine") == 0
                for a in agents.values()
            )
            detail["hostile"] = hostile
            detail["equivocations"] = equiv

        return {
            "family": family,
            "n_nodes": n,
            "seed": seed,
            "writes": writes,
            "wall_to_converge_s": round(wall, 3),
            "live_p99_s": lag.get("p99_s"),
            "live_p50_s": lag.get("p50_s"),
            "lag_samples": lag.get("count", 0),
            "msgs_per_node": round(obs.msgs_per_node(scrape), 2),
            "loop_health": loop_health,
            "injected": dict(ctrl.injected),
            "no_divergence": nodiv,
            "timeline": timeline,
            "gates": gates,
            "passed": all(gates.values()),
            "detail": detail,
        }
    finally:
        if stall_task is not None and not stall_task.done():
            stall_task.cancel()
            try:
                await stall_task
            except (asyncio.CancelledError, Exception):
                pass
        for a in list(agents.values()):
            try:
                await a.stop()
            except Exception:
                pass


async def run_scenarios(
    n: int = 32,
    seed: int = 0,
    families: Optional[List[str]] = None,
    sim_seeds: int = 8,
    heal_after: float = 0.64,
    out_path: Optional[str] = None,
    base_dir: Optional[str] = None,
    sim: bool = True,
) -> Dict:
    """The campaign: every family's cell on a live N-node cluster next
    to the kernel prediction, one JSON artifact, all gates asserted
    in-record."""
    import os

    families = list(families or FAMILIES)
    unknown = [f for f in families if f not in FAMILIES]
    if unknown:
        # validate UP FRONT: a typo must not surface mid-campaign
        # after earlier N=32 cells already burned their minutes
        raise ValueError(
            f"unknown scenario families {unknown}; valid: {FAMILIES}"
        )
    results = {}
    for family in families:
        # seed offset by the family's FIXED position in FAMILIES, not
        # its position in a --scenario-families subset: replaying one
        # failing cell must reproduce the matrix run's exact draws
        i = FAMILIES.index(family)
        cell_dir = (
            os.path.join(base_dir, family) if base_dir else None
        )
        prediction = (
            sim_prediction(family, n, heal_after, seeds=sim_seeds)
            if sim else None
        )
        try:
            cell = await agent_scenario_cell(
                family, n=n, seed=seed + i, heal_after=heal_after,
                base_dir=cell_dir,
                timeout=120.0,
            )
        except Exception as e:  # noqa: BLE001 - one cell crashing
            # must not discard the completed cells' results
            cell = {
                "family": family,
                "n_nodes": n,
                "seed": seed + i,
                "error": f"{type(e).__name__}: {e}",
                "live_p99_s": None,
                "msgs_per_node": None,
                "no_divergence": {"ok": False, "violations": []},
                "timeline": None,
                "gates": {"converged": False},
                "passed": False,
            }
        pred_p99 = None
        if prediction is not None:
            pred_p99 = prediction.get("predicted_wall_p99_s")
            if pred_p99 is None and prediction.get(
                "ticks_to_converge_p99"
            ) is not None:
                pred_p99 = prediction["ticks_to_converge_p99"] * TICK_S
        results[family] = {
            "agents": cell,
            "sim": prediction,
            "diff": {
                "live_p99_s": cell["live_p99_s"],
                "kernel_predicted_wall_p99_s": pred_p99,
                "msgs_per_node_live": cell["msgs_per_node"],
                "msgs_per_node_kernel": (
                    prediction.get("msgs_per_node")
                    if prediction else None
                ),
            },
        }

    all_passed = all(r["agents"]["passed"] for r in results.values())
    no_div = all(
        r["agents"]["no_divergence"]["ok"] for r in results.values()
    )
    out = {
        "n_nodes": n,
        "metric": "adversarial_scenario_matrix",
        "families": list(results),
        "all_cells_converged": all(
            r["agents"]["gates"].get("converged", False)
            for r in results.values()
        ),
        "no_divergence_all_cells": no_div,
        "all_gates_passed": all_passed,
        "tick_seconds": TICK_S,
        "cells": results,
    }
    if not all_passed:
        out["error"] = "one or more scenario gates failed"
    if out_path:
        with open(out_path, "w") as f:
            json.dump(out, f, indent=1, allow_nan=False)
            f.write("\n")
    return out
