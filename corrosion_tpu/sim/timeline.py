"""Timeline campaign: the live cluster's convergence TRAJECTORY gated
against the epidemic kernel's per-tick prediction.

CHAOS/OBS/SCENARIOS compare *endpoints* — converged or not, p99 lag vs
prediction.  This campaign compares the *shape of the run*: a
partition-heal cell writes on both sides of a symmetric 2-block split,
the flight recorder (``agent/recorder.py``) journals the run and the
provenance first-seen stamps give each ``(actor, version)`` wave's
time-resolved coverage curve (``ClusterObserver.coverage_curve``,
HLC-aligned), and the kernel predicts the same curve per tick
(``epidemic.run_epidemic_coverage``).  The gate asserts the live curve
has the predicted SHAPE, with every tolerance named in-record:

* **plateau** — just before the heal (the maximal guaranteed-pre-heal
  offset) both curves must sit at the severed-block fraction: live vs
  predicted coverage within ``PLATEAU_TOL`` absolute;
* **held** — neither curve may reach (near-)full coverage before the
  heal: the partition actually partitioned, in both worlds;
* **recovery** — post-heal the live curve must complete, and its full-
  coverage offset must land within ``RECOVERY_FACTOR`` × the kernel's
  (+ ``RECOVERY_SLACK_S``): the kernel's tick grid does not model TCP
  reconnects, breaker cooldowns, or the anti-entropy cadence, a
  residual CHAOS_N32 already documents at ≈3-4× wall — the factor
  bounds it instead of pretending it away.

``bench.py --timeline`` writes ``TIMELINE_N32.json`` with the curves,
the assembled cluster timeline (merged flight rings), and — computed by
the bench harness next to it — the recorder's own paired off/on A/B on
the WRITE_BENCH headline shape (<5%).
"""

from __future__ import annotations

import asyncio
import json
import time
from typing import Dict, List, Optional

from corrosion_tpu.faults import FaultController, FaultPlan

# the simdiff/chaos time base: one kernel tick ≈ the agents' broadcast
# flush interval (launch_test_agent pins bcast_flush_interval=0.02)
TICK_S = 0.02

# named trajectory tolerances (recorded in the artifact)
#
# The plateau is probed at the MAXIMAL guaranteed-pre-heal offset —
# (heal delay − the last write's offset from the split − a guard) — so
# in-block propagation has the whole partition window to complete: a
# loaded host propagates in-block in hundreds of ms, and probing at a
# fixed small fraction of the heal delay made the gate a host-speed
# lottery rather than a shape check.
PLATEAU_GUARD_S = 0.1      # keep the probe strictly before the heal
PLATEAU_PROBE_MIN_S = 0.1  # floor when writes ran long
PLATEAU_TOL = 0.20         # |live - predicted| plateau coverage, absolute
FULL_COV = 0.99            # "full coverage" threshold for gating
RECOVERY_FACTOR = 6.0      # live full-coverage offset vs kernel's
RECOVERY_SLACK_S = 2.0     # additive slack on top of the factor


def kernel_coverage_prediction(
    n: int,
    heal_tick: int,
    fanout: int = 3,
    max_transmissions: int = 5,
    seeds: int = 8,
) -> Dict:
    """The kernel's per-tick coverage curve for the partition-heal
    family (loss-free, symmetric 2-block split healing at
    ``heal_tick``) — the prediction the live trajectory gates against.
    Seed-flattened layout (no per-payload sent tracking: it needs the
    [N, N] vmap path; at loss 0 the exclusion shifts msgs, not the
    coverage dynamics)."""
    from corrosion_tpu.sim.epidemic import (
        EpidemicConfig,
        run_epidemic_coverage,
    )

    cfg = EpidemicConfig(
        n_nodes=n,
        n_rows=4,
        fanout_ring0=0,
        fanout_global=fanout,
        ring0_size=1,
        max_transmissions=max_transmissions,
        loss=0.0,
        partition_blocks=2,
        heal_tick=heal_tick,
        backoff_ticks=2.5,
        sync_interval=8,
        sync_peers=1,
        max_ticks=512,
        chunk_ticks=16,
    )
    cov = run_epidemic_coverage(cfg, n_seeds=seeds, seed=0)
    curve = cov["coverage"]
    times = [round((i + 1) * TICK_S, 4) for i in range(len(curve))]

    def t_at(c: float) -> Optional[float]:
        for t, v in zip(times, curve):
            if v >= c:
                return t
        return None

    return {
        "runtime": "tpu-sim",
        "n_nodes": n,
        "heal_tick": heal_tick,
        "heal_s": round(heal_tick * TICK_S, 4),
        "tick_seconds": TICK_S,
        "times_s": times,
        "coverage": [round(v, 4) for v in curve],
        "coverage_p10": [round(v, 4) for v in cov["coverage_p10"]],
        "coverage_p90": [round(v, 4) for v in cov["coverage_p90"]],
        "converged_frac": cov["converged_frac"],
        "t_at_coverage": {
            str(c): t_at(c) for c in (0.5, 0.75, 0.9, 0.99, 1.0)
        },
    }


def curve_value_at(times: List[float], coverage: List[float],
                   t: float) -> float:
    """Predicted coverage at offset ``t`` (step interpolation; 0 before
    the first tick)."""
    v = 0.0
    for tt, cc in zip(times, coverage):
        if tt > t:
            break
        v = cc
    return v


async def agent_timeline_cell(
    n: int = 32,
    writes: int = 6,
    heal_after: float = 1.28,
    seed: int = 0,
    timeout: float = 90.0,
    base_dir: Optional[str] = None,
    event_limit: int = 400,
) -> Dict:
    """The live partition-heal cell: writes land on BOTH sides of the
    split immediately after it arms (so every wave's commit sits well
    before the heal), the run converges through heal + anti-entropy,
    and the flight plane yields the assembled timeline + the coverage
    trajectory."""
    from corrosion_tpu.agent.testing import seed_full_membership, wait_for
    from corrosion_tpu.devcluster import (
        ClusterObserver,
        Topology,
        run_inprocess,
    )

    plan = FaultPlan(
        seed=seed, partition_blocks=2, heal_after=heal_after
    )
    ctrl = FaultController(plan)
    topo = Topology.parse("\n".join(f"n0 -> n{i}" for i in range(1, n)))
    agents = await run_inprocess(
        topo,
        base_dir=base_dir,
        faults=ctrl,
        ring0_enabled=False,   # uniform sampling: the kernel's model
        subs_enabled=False,
        api_port=None,
        uni_cache_size=16,
        suspect_timeout=10.0,  # the split must not down-mark members
        breaker_cooldown=0.5,
        # fast snapshots: a sub-5 s cell still gets a real timeline
        flight_interval_s=0.25,
    )
    try:
        await wait_for(
            lambda: all(
                len(a.members.alive()) == n - 1 for a in agents.values()
            ),
            timeout=max(30.0, 2.0 * n),
        )
        seed_full_membership(list(agents.values()))
        obs = ClusterObserver(agents, faults=ctrl)
        obs.mark()

        names = list(agents)
        other = next(
            i for i in range(n)
            if plan.block_of(i, n) != plan.block_of(0, n)
        )
        writers = [names[0], names[other]]

        ctrl.restart_clock()
        ctrl.split()
        split_wall = time.time()

        # the write burst, one origin per block, back to back: every
        # wave's commit lands within a fraction of the heal delay, so
        # the wave-relative plateau probe below stays mid-partition
        # for all of them
        versions: List[tuple] = []
        for w in range(writes):
            origin = agents[writers[w % 2]]
            res = await asyncio.to_thread(
                origin.execute_transaction,
                [("INSERT INTO tests (id, text) VALUES (?, ?)",
                  (9000 + w, f"timeline-{w}"))],
            )
            versions.append((origin.actor_id, res["version"]))
            await asyncio.sleep(0.01)
        last_write_off = time.time() - split_wall

        def converged() -> bool:
            for a in agents.values():
                for actor, v in versions:
                    if a.actor_id != actor and not a.bookie.for_actor(
                        actor
                    ).contains_version(v):
                        return False
            return True

        t0 = time.perf_counter()
        converged_ok = True
        try:
            await wait_for(converged, timeout=timeout, interval=0.02)
        except TimeoutError:
            converged_ok = False
        wall = time.perf_counter() - t0
        # one more snapshot round so the post-convergence state is in
        # every ring before assembly
        await asyncio.sleep(0.3)

        curve = obs.coverage_curve(versions)
        events = obs.flight_events()
        kind_counts: Dict[str, int] = {}
        for e in events:
            k = e["kind"]
            kind_counts[k] = kind_counts.get(k, 0) + 1
        snapshots = len(obs.flight_timeline(kind="snap"))
        lag = obs.convergence_lag()
        scrape = obs.scrape()

        return {
            "runtime": "agents",
            "n_nodes": n,
            "writes": writes,
            "heal_after_s": heal_after,
            "converged": converged_ok,
            "wall_to_converge_s": round(wall, 3),
            "last_write_offset_s": round(last_write_off, 3),
            "coverage": curve,
            "live_p99_s": lag.get("p99_s"),
            "msgs_per_node": round(obs.msgs_per_node(scrape), 2),
            "timeline": {
                "snapshots": snapshots,
                "event_counts": kind_counts,
                "events": [
                    {
                        "node": e["node"], "kind": e["kind"],
                        "hlc": e["hlc"],
                        "wall_off_s": round(e["wall"] - split_wall, 3),
                        "attrs": e.get("attrs", {}),
                    }
                    for e in events[-event_limit:]
                ],
            },
        }
    finally:
        for a in list(agents.values()):
            try:
                await a.stop()
            except Exception:
                pass


def trajectory_gates(live: Dict, pred: Dict,
                     heal_after: float) -> Dict:
    """The named-tolerance trajectory comparison: plateau / held /
    recovery, each gate a boolean next to its operands."""
    probe_t = max(
        PLATEAU_PROBE_MIN_S,
        heal_after - live.get("last_write_offset_s", 0.0)
        - PLATEAU_GUARD_S,
    )
    curve = live["coverage"]
    offsets = curve["offsets_s"]
    expected = max(1, curve["expected"])
    live_plateau = sum(1 for d in offsets if d <= probe_t) / expected
    pred_plateau = curve_value_at(
        pred["times_s"], pred["coverage"], probe_t
    )
    live_full = curve["t_at_coverage"].get(str(FULL_COV))
    pred_full = pred["t_at_coverage"].get(str(FULL_COV))
    recovery_budget = (
        None if pred_full is None
        else round(RECOVERY_FACTOR * pred_full + RECOVERY_SLACK_S, 3)
    )
    gates = {
        "converged": bool(live["converged"]),
        # mid-partition both worlds sit at the severed-block fraction
        "plateau_matches": abs(live_plateau - pred_plateau)
        <= PLATEAU_TOL,
        # the partition held: neither curve near-full before the heal
        "partition_held": live_plateau < FULL_COV
        and pred_plateau < FULL_COV,
        # post-heal the live wave completes within the named budget
        "recovery_within_budget": (
            live_full is not None
            and recovery_budget is not None
            and live_full <= recovery_budget
        ),
    }
    return {
        "gates": gates,
        "plateau_probe_s": round(probe_t, 4),
        "live_plateau_cov": round(live_plateau, 4),
        "predicted_plateau_cov": round(pred_plateau, 4),
        "plateau_tolerance": PLATEAU_TOL,
        "live_full_coverage_s": live_full,
        "predicted_full_coverage_s": pred_full,
        "recovery_budget_s": recovery_budget,
        "recovery_factor": RECOVERY_FACTOR,
        "recovery_slack_s": RECOVERY_SLACK_S,
        "residual": (
            "the kernel's tick grid does not model TCP reconnects, "
            "breaker cooldowns or the anti-entropy cadence; live "
            "recovery runs a documented ~3-4x slower than predicted "
            "(CHAOS_N32), bounded here by recovery_factor instead of "
            "hidden"
        ),
    }


async def run_timeline(
    n: int = 32,
    writes: int = 6,
    # heal_tick = 64: double the chaos family's 0.64 s so in-block
    # propagation reliably completes (plateaus) inside the partition
    # window even on a loaded host — the plateau gate checks shape,
    # not host speed
    heal_after: float = 1.28,
    seeds: int = 8,
    out_path: Optional[str] = None,
    base_dir: Optional[str] = None,
    sim: bool = True,
    overhead_gate: Optional[Dict] = None,
) -> Dict:
    """The timeline campaign: live partition-heal trajectory vs the
    kernel's per-tick curve, one JSON artifact, all gates asserted
    in-record.  ``overhead_gate`` (the recorder off/on A/B the bench
    harness measures) is embedded verbatim when provided."""
    heal_tick = max(1, int(round(heal_after / TICK_S)))
    prediction = (
        kernel_coverage_prediction(n, heal_tick, seeds=seeds)
        if sim else None
    )
    live = await agent_timeline_cell(
        n, writes=writes, heal_after=heal_after, base_dir=base_dir,
    )
    out: Dict = {
        "n_nodes": n,
        "metric": "partition_heal_trajectory_vs_kernel",
        "tick_seconds": TICK_S,
        "agents": live,
        "sim": prediction,
    }
    if prediction is not None:
        traj = trajectory_gates(live, prediction, heal_after)
        out["trajectory"] = traj
        out["all_gates_passed"] = all(traj["gates"].values())
        out["value"] = traj["live_full_coverage_s"]
        out["unit"] = "s_full_coverage_offset"
        if not out["all_gates_passed"]:
            out["error"] = (
                "live coverage trajectory diverged from the kernel "
                "prediction beyond the named tolerances"
            )
    if overhead_gate is not None:
        out["overhead_gate"] = overhead_gate
        if overhead_gate.get("pass") is False:
            out.setdefault(
                "error",
                "flight-recorder overhead gate failed: recorder-on "
                "write throughput regressed > 5% vs recorder-off",
            )
    if out_path:
        with open(out_path, "w") as f:
            json.dump(out, f, indent=1, allow_nan=False)
            f.write("\n")
    return out


# ---------------------------------------------------------------------------
# virtual-time trajectory campaign (sim/vcluster.py): the partition-heal
# cell at N=512–1024 in seconds of wall time, plus the N=32
# virtual-vs-real parity cell that keeps the virtual path honest
# ---------------------------------------------------------------------------

# named parity tolerances (virtual vs real, same seed & shape — the
# virtual scheduler models timers and link latency, not TCP dynamics,
# so the comparison is banded, not exact)
PARITY_PLATEAU_TOL = 0.25   # |virtual - live| plateau coverage
PARITY_MSGS_FACTOR = 6.0    # msgs/node ratio band (either direction)
PARITY_RECOVERY_FACTOR = 6.0  # full-coverage offset ratio band
PARITY_RECOVERY_SLACK_S = 2.0


def virtual_timeline_cell(
    n: int = 512,
    writes: int = 6,
    heal_after: float = 1.28,
    seed: int = 0,
    timeout: float = 60.0,
    base_dir: Optional[str] = None,
    probe_interval: Optional[float] = None,
) -> Dict:
    """The partition-heal trajectory cell on VIRTUAL time: same
    record shape as :func:`agent_timeline_cell` (the trajectory gates
    apply unchanged), ``timeout`` in virtual seconds.  The virtual
    flush interval equals ``TICK_S``, so the kernel's tick grid maps
    onto the virtual timeline exactly as it maps onto the live one."""
    import time as _time

    from corrosion_tpu.sim.vcluster import VirtualCluster

    plan = FaultPlan(
        seed=seed, partition_blocks=2, heal_after=heal_after
    )
    overrides = {}
    if probe_interval is not None:
        overrides["probe_interval"] = probe_interval
    elif n >= 256:
        overrides["probe_interval"] = 1.0
    wall0 = _time.perf_counter()
    c = VirtualCluster(
        n, seed=seed, plan=plan, base_dir=base_dir, **overrides
    )
    try:
        other = next(
            i for i in range(n)
            if plan.block_of(i, n) != plan.block_of(0, n)
        )
        writers = [0, other]
        c.ctrl.split()
        split_virt = c.clock.monotonic()
        split_wall = c.clock.wall()

        versions: List[tuple] = []
        for w in range(writes):
            origin = writers[w % 2]
            v = c.write(
                origin,
                "INSERT INTO tests (id, text) VALUES (?, ?)",
                (9000 + w, f"timeline-{w}"),
            )
            versions.append((c.agents[f"n{origin}"].actor_id, v))
            c.run_for(0.01)
        last_write_off = c.clock.monotonic() - split_virt

        converged_ok = c.run_until_true(
            lambda: c.converged(versions), timeout=timeout
        )
        virt_s = c.clock.monotonic() - split_virt
        # one more snapshot round before assembly
        c.run_for(0.3)

        obs = c.observer()
        curve = obs.coverage_curve(versions)
        events = obs.flight_events()
        kind_counts: Dict[str, int] = {}
        for e in events:
            k = e["kind"]
            kind_counts[k] = kind_counts.get(k, 0) + 1
        snapshots = len(obs.flight_timeline(kind="snap"))
        lag = obs.convergence_lag()
        scrape = obs.scrape()

        return {
            "runtime": "virtual-agents",
            "n_nodes": n,
            "writes": writes,
            "heal_after_s": heal_after,
            "converged": converged_ok,
            "wall_to_converge_s": round(virt_s, 3),
            "virtual_to_converge_s": round(virt_s, 3),
            "campaign_wall_s": round(_time.perf_counter() - wall0, 3),
            "last_write_offset_s": round(last_write_off, 3),
            "coverage": curve,
            "live_p99_s": lag.get("p99_s"),
            "msgs_per_node": round(obs.msgs_per_node(scrape), 2),
            "timeline": {
                "snapshots": snapshots,
                "event_counts": kind_counts,
                "events": [
                    {
                        "node": e["node"], "kind": e["kind"],
                        "hlc": e["hlc"],
                        "wall_off_s": round(e["wall"] - split_wall, 3),
                        "attrs": e.get("attrs", {}),
                    }
                    for e in events[-400:]
                ],
            },
        }
    finally:
        c.close()


def _plateau_cov(cell: Dict, probe_t: float) -> float:
    curve = cell["coverage"]
    expected = max(1, curve["expected"])
    return sum(1 for d in curve["offsets_s"] if d <= probe_t) / expected


def virtual_real_parity(
    n: int = 32,
    writes: int = 6,
    heal_after: float = 1.28,
    seed: int = 0,
    base_dir: Optional[str] = None,
) -> Dict:
    """The N=32 parity cell: the SAME partition-heal shape (same seed,
    same heal window, same writer layout) on the virtual scheduler and
    on the live socket cluster, compared within named tolerances —
    what keeps the virtual path honest against the system it stands in
    for.  Banded, not exact: the virtual scheduler models timers and
    per-link latency; the live run adds TCP connects, worker-thread
    scheduling and host noise on top."""
    import os

    live = asyncio.run(agent_timeline_cell(
        n, writes=writes, heal_after=heal_after, seed=seed,
        base_dir=os.path.join(base_dir, "live") if base_dir else None,
    ))
    virt = virtual_timeline_cell(
        n, writes=writes, heal_after=heal_after, seed=seed,
        base_dir=os.path.join(base_dir, "virtual") if base_dir else None,
    )
    probe_t = max(
        PLATEAU_PROBE_MIN_S,
        heal_after
        - max(live.get("last_write_offset_s", 0.0),
              virt.get("last_write_offset_s", 0.0))
        - PLATEAU_GUARD_S,
    )
    live_plateau = _plateau_cov(live, probe_t)
    virt_plateau = _plateau_cov(virt, probe_t)
    live_full = live["coverage"]["t_at_coverage"].get(str(FULL_COV))
    virt_full = virt["coverage"]["t_at_coverage"].get(str(FULL_COV))
    msgs_ratio = (
        virt["msgs_per_node"] / live["msgs_per_node"]
        if live["msgs_per_node"] else None
    )
    recovery_ok = (
        live_full is not None and virt_full is not None
        and virt_full
        <= PARITY_RECOVERY_FACTOR * live_full + PARITY_RECOVERY_SLACK_S
        and live_full
        <= PARITY_RECOVERY_FACTOR * virt_full + PARITY_RECOVERY_SLACK_S
    )
    gates = {
        "both_converged": bool(
            live["converged"] and virt["converged"]
        ),
        "plateau_close": abs(live_plateau - virt_plateau)
        <= PARITY_PLATEAU_TOL,
        "msgs_within_factor": (
            msgs_ratio is not None
            and 1.0 / PARITY_MSGS_FACTOR
            <= msgs_ratio <= PARITY_MSGS_FACTOR
        ),
        "recovery_within_factor": recovery_ok,
    }
    return {
        "n_nodes": n,
        "seed": seed,
        "heal_after_s": heal_after,
        "gates": gates,
        "passed": all(gates.values()),
        "plateau_probe_s": round(probe_t, 4),
        "live_plateau_cov": round(live_plateau, 4),
        "virtual_plateau_cov": round(virt_plateau, 4),
        "plateau_tolerance": PARITY_PLATEAU_TOL,
        "live_full_coverage_s": live_full,
        "virtual_full_coverage_s": virt_full,
        "recovery_factor": PARITY_RECOVERY_FACTOR,
        "recovery_slack_s": PARITY_RECOVERY_SLACK_S,
        "msgs_per_node_live": live["msgs_per_node"],
        "msgs_per_node_virtual": virt["msgs_per_node"],
        "msgs_factor": PARITY_MSGS_FACTOR,
        "live_wall_to_converge_s": live["wall_to_converge_s"],
        "virtual_campaign_wall_s": virt.get("campaign_wall_s"),
        "residual": (
            "the virtual scheduler models timers + per-link latency; "
            "the live cell adds TCP connects, thread scheduling and "
            "host noise — hence banded tolerances, not equality"
        ),
    }


def run_virtual_timeline(
    n: int = 512,
    writes: int = 6,
    heal_after: float = 1.28,
    seeds: int = 8,
    out_path: Optional[str] = None,
    base_dir: Optional[str] = None,
    sim: bool = True,
    parity_n: Optional[int] = 32,
) -> Dict:
    """The virtual-time timeline campaign: the N=512 partition-heal
    trajectory gated against the kernel's per-tick curve (same
    tolerances as the live campaign), plus the N=32 virtual-vs-real
    parity cell, one JSON artifact."""
    import os

    heal_tick = max(1, int(round(heal_after / TICK_S)))
    prediction = (
        kernel_coverage_prediction(n, heal_tick, seeds=seeds)
        if sim else None
    )
    live = virtual_timeline_cell(
        n, writes=writes, heal_after=heal_after,
        base_dir=os.path.join(base_dir, "cell") if base_dir else None,
    )
    out: Dict = {
        "n_nodes": n,
        "metric": "virtual_partition_heal_trajectory_vs_kernel",
        "runtime": "virtual",
        "tick_seconds": TICK_S,
        "agents": live,
        "sim": prediction,
    }
    if prediction is not None:
        traj = trajectory_gates(live, prediction, heal_after)
        out["trajectory"] = traj
        out["all_gates_passed"] = all(traj["gates"].values())
        out["value"] = traj["live_full_coverage_s"]
        out["unit"] = "s_full_coverage_offset"
        if not out["all_gates_passed"]:
            out["error"] = (
                "virtual coverage trajectory diverged from the kernel "
                "prediction beyond the named tolerances"
            )
    if parity_n:
        parity = virtual_real_parity(
            parity_n, writes=writes, heal_after=heal_after,
            base_dir=(
                os.path.join(base_dir, "parity") if base_dir else None
            ),
        )
        out["parity_n32"] = parity
        if not parity["passed"]:
            out.setdefault(
                "error",
                "virtual-vs-real parity cell failed its named "
                "tolerances",
            )
            out["all_gates_passed"] = False
    if out_path:
        with open(out_path, "w") as f:
            json.dump(out, f, indent=1, allow_nan=False)
            f.write("\n")
    return out
