"""foca SWIM wire codec: binary datagrams replacing the JSON envelope.

The reference relays foca's own messages verbatim as QUIC datagrams —
``Foca::with_custom_broadcast(actor, config, rng,
BincodeCodec(bincode::DefaultOptions::new()), NoCustomBroadcast)``
(``crates/corro-agent/src/broadcast/mod.rs:137-142``) — with the
``Actor`` identity of ``crates/corro-types/src/actor.rs:132-210``.
This module implements that datagram format so our SWIM layer speaks
binary foca messages instead of JSON.

Layout (bincode 1.3 DefaultOptions primitives, see ``bridge/bincode.py``):

``Actor`` (serde-derived field order, ``actor.rs:132-139``)::

    id          ActorId(#[serde(transparent)] Uuid)
                → uuid 1.x binary serde: serialize_bytes(16)
                → varint len 0x10 + 16 raw bytes
    addr        SocketAddr → serde binary impl: newtype variant
                (varint 0 = V4 / 1 = V6), then (ip_octets, port):
                4 (or 16) raw octet bytes + u16 varint port
    ts          Timestamp(#[serde(transparent)] NTP64) → u64 varint
    cluster_id  ClusterId(#[serde(transparent)] u16) → u16 varint

``Header``/``Message``/``Member`` follow foca 0.16's protocol types
(foca src/payload.rs, src/member.rs; ``Incarnation``/``ProbeNumber``
are u16)::

    Header  { src: Actor, src_incarnation: u16, dst: Actor,
              message: Message }
    Message enum (variant tag = u32 varint):
      0 Ping(ProbeNumber)              1 Ack(ProbeNumber)
      2 PingReq      { target, probe_number }
      3 IndirectPing { origin, probe_number }
      4 IndirectAck  { target, probe_number }
      5 ForwardedAck { origin, probe_number }
      6 Announce     7 Feed    8 Gossip    9 Broadcast   10 TurnUndead
    Member  { id: Actor, incarnation: u16, state: State }
    State enum: 0 Alive, 1 Suspect, 2 Down

A datagram is one encoded ``Header`` followed by zero or more ``Member``
records (cluster updates / Feed contents) back-to-back until the end of
the packet — foca's ``handle_data`` reads members while bytes remain.
Packets are capped at 1178 bytes (``broadcast/mod.rs:943``).

RECONSTRUCTION NOTE: foca's crate source is not present in this
offline tree, so the ``Header``/``Message``/``Member`` shapes above are
reconstructed from foca 0.16's public API/docs and the reference's
usage; the serde/bincode/uuid primitive rules are implemented from
their published specs.  ``tests/test_foca_wire.py`` pins this layout
with golden byte vectors and drives a live agent as a foreign peer
speaking only these bytes (join → probe → refutation).
"""

from __future__ import annotations

import ipaddress
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

from corrosion_tpu.bridge.bincode import BincodeError, BReader, BWriter

MAX_PACKET = 1178  # broadcast/mod.rs:943

# Message variant tags
PING, ACK, PING_REQ, INDIRECT_PING, INDIRECT_ACK, FORWARDED_ACK = range(6)
ANNOUNCE, FEED, GOSSIP, BROADCAST, TURN_UNDEAD = range(6, 11)

# Member states
STATE_ALIVE, STATE_SUSPECT, STATE_DOWN = range(3)

_NO_FIELD_TAGS = frozenset((ANNOUNCE, FEED, GOSSIP, BROADCAST, TURN_UNDEAD))
_PROBE_ONLY_TAGS = frozenset((PING, ACK))


class FocaError(ValueError):
    pass


@dataclass(frozen=True)
class FocaActor:
    """The foca identity: corro's Actor (actor.rs:132-139)."""

    id: bytes  # 16-byte uuid / crsql site_id
    addr: Tuple[str, int]
    ts: int = 0  # NTP64 (uhlc HLC)
    cluster_id: int = 0

    def same_prefix(self, other: "FocaActor") -> bool:
        """Identity::has_same_prefix (actor.rs:183-197): nil ids compare
        by gossip addr (a joining client doesn't know our id yet)."""
        nil = b"\x00" * 16
        if self.id == nil or other.id == nil:
            return self.addr == other.addr
        return self.id == other.id


@dataclass(frozen=True)
class FocaMessage:
    tag: int
    probe_number: int = 0
    peer: Optional[FocaActor] = None  # target/origin for tags 2-5


@dataclass(frozen=True)
class FocaMember:
    actor: FocaActor
    incarnation: int
    state: int  # STATE_*


@dataclass(frozen=True)
class FocaDatagram:
    src: FocaActor
    src_incarnation: int
    dst: FocaActor
    message: FocaMessage
    updates: List[FocaMember] = field(default_factory=list)


# -- Actor ------------------------------------------------------------


def _w_actor(w: BWriter, a: FocaActor) -> None:
    if len(a.id) != 16:
        raise FocaError(f"actor id must be 16 bytes, got {len(a.id)}")
    w.lp_bytes(a.id)
    ip = ipaddress.ip_address(a.addr[0])
    if ip.version == 4:
        w.varint(0).raw(ip.packed)
    else:
        w.varint(1).raw(ip.packed)
    w.varint(a.addr[1])
    w.varint(a.ts)
    w.varint(a.cluster_id)


def _r_actor(r: BReader) -> FocaActor:
    ident = r.lp_bytes()
    if len(ident) != 16:
        raise FocaError(f"actor id must be 16 bytes, got {len(ident)}")
    fam = r.varint()
    if fam == 0:
        host = str(ipaddress.IPv4Address(r.raw(4)))
    elif fam == 1:
        host = str(ipaddress.IPv6Address(r.raw(16)))
    else:
        raise FocaError(f"unknown address family {fam}")
    port = r.varint()
    ts = r.varint()
    cluster_id = r.varint()
    return FocaActor(id=bytes(ident), addr=(host, port), ts=ts,
                     cluster_id=cluster_id)


# -- Message ----------------------------------------------------------


def _w_message(w: BWriter, m: FocaMessage) -> None:
    w.varint(m.tag)
    if m.tag in _PROBE_ONLY_TAGS:
        w.varint(m.probe_number)
    elif m.tag in _NO_FIELD_TAGS:
        pass
    elif m.peer is not None:
        _w_actor(w, m.peer)
        w.varint(m.probe_number)
    else:
        raise FocaError(f"message tag {m.tag} requires a peer actor")


def _r_message(r: BReader) -> FocaMessage:
    tag = r.varint()
    if tag in _PROBE_ONLY_TAGS:
        return FocaMessage(tag=tag, probe_number=r.varint())
    if tag in _NO_FIELD_TAGS:
        return FocaMessage(tag=tag)
    if tag in (PING_REQ, INDIRECT_PING, INDIRECT_ACK, FORWARDED_ACK):
        peer = _r_actor(r)
        return FocaMessage(tag=tag, peer=peer, probe_number=r.varint())
    raise FocaError(f"unknown message tag {tag}")


# -- Member -----------------------------------------------------------


def _w_member(w: BWriter, m: FocaMember) -> None:
    _w_actor(w, m.actor)
    w.varint(m.incarnation)
    w.varint(m.state)


def _r_member(r: BReader) -> FocaMember:
    actor = _r_actor(r)
    incarnation = r.varint()
    state = r.varint()
    if state not in (STATE_ALIVE, STATE_SUSPECT, STATE_DOWN):
        raise FocaError(f"unknown member state {state}")
    return FocaMember(actor=actor, incarnation=incarnation, state=state)


# -- datagram ---------------------------------------------------------


def encode_datagram(d: FocaDatagram) -> bytes:
    """Header + as many updates as fit in MAX_PACKET (foca fills the
    remaining packet space with piggybacked cluster updates)."""
    w = BWriter()
    _w_actor(w, d.src)
    w.varint(d.src_incarnation)
    _w_actor(w, d.dst)
    _w_message(w, d.message)
    out = w.getvalue()
    if len(out) > MAX_PACKET:
        raise FocaError(f"header alone exceeds {MAX_PACKET} bytes")
    for m in d.updates:
        mw = BWriter()
        _w_member(mw, m)
        mb = mw.getvalue()
        if len(out) + len(mb) > MAX_PACKET:
            break
        out += mb
    return out


def decode_datagram(data: bytes) -> FocaDatagram:
    r = BReader(data)
    try:
        src = _r_actor(r)
        src_incarnation = r.varint()
        dst = _r_actor(r)
        message = _r_message(r)
        updates = []
        while r.remaining() > 0:
            updates.append(_r_member(r))
    except BincodeError as e:
        raise FocaError(str(e)) from e
    return FocaDatagram(
        src=src, src_incarnation=src_incarnation, dst=dst,
        message=message, updates=updates,
    )
