"""Interop bridge: golden cr-sqlite reference engine + speedy wire codec.

The north-star bit-match path (SURVEY §7.6): validate our CRDT merge
against the real cr-sqlite extension, and speak the reference agent's
speedy-encoded wire types so traces can be diffed against real agents.
"""

from corrosion_tpu.bridge.crsqlite_ref import (
    CrsqliteRef,
    crsqlite_available,
    find_crsqlite_so,
)

__all__ = [
    "CrsqliteRef",
    "crsqlite_available",
    "find_crsqlite_so",
]
