"""bincode 1.3 ``DefaultOptions`` primitives.

The reference's SWIM layer serializes foca protocol types with
``bincode::DefaultOptions::new()``
(``crates/corro-agent/src/broadcast/mod.rs:141``), i.e. bincode 1.3.3
(workspace ``Cargo.toml:15``) in its *varint* configuration:

* u8/i8: one raw byte;
* u16/u32/u64: varint — values ``0..=250`` as a single byte, then a
  marker byte ``251``/``252``/``253`` followed by the value as
  little-endian u16/u32/u64 (smallest width that fits);
* i16/i32/i64: zigzag-mapped to unsigned, then varint;
* enum discriminants: u32 varint;
* ``serialize_bytes``/Vec/String: u64-varint length + raw bytes;
* fixed arrays and tuples/structs: fields back-to-back, no framing;
* Option: one 0/1 byte, then the value.

This module implements exactly that spec; ``bridge/foca.py`` builds the
foca/Actor types on top.
"""

from __future__ import annotations

import struct
from typing import List


class BincodeError(ValueError):
    pass


class BWriter:
    def __init__(self):
        self._parts: List[bytes] = []

    def u8(self, v: int) -> "BWriter":
        if not 0 <= v <= 0xFF:
            raise BincodeError(f"u8 out of range: {v}")
        self._parts.append(bytes((v,)))
        return self

    def varint(self, v: int) -> "BWriter":
        """Unsigned varint (u16/u32/u64/usize/discriminant/length)."""
        if v < 0:
            raise BincodeError(f"negative unsigned: {v}")
        if v <= 250:
            self._parts.append(bytes((v,)))
        elif v <= 0xFFFF:
            self._parts.append(b"\xfb" + struct.pack("<H", v))
        elif v <= 0xFFFF_FFFF:
            self._parts.append(b"\xfc" + struct.pack("<I", v))
        elif v <= 0xFFFF_FFFF_FFFF_FFFF:
            self._parts.append(b"\xfd" + struct.pack("<Q", v))
        else:
            raise BincodeError(f"u64 out of range: {v}")
        return self

    def signed_varint(self, v: int) -> "BWriter":
        """Zigzag + varint (i16/i32/i64)."""
        return self.varint((v << 1) ^ (v >> 63) if v >= -(1 << 63)
                           else self._range_err(v))

    def _range_err(self, v):
        raise BincodeError(f"i64 out of range: {v}")

    def raw(self, b: bytes) -> "BWriter":
        self._parts.append(bytes(b))
        return self

    def lp_bytes(self, b: bytes) -> "BWriter":
        """serialize_bytes: u64-varint length + raw bytes."""
        return self.varint(len(b)).raw(b)

    def getvalue(self) -> bytes:
        return b"".join(self._parts)


class BReader:
    def __init__(self, data: bytes, pos: int = 0):
        self.data = bytes(data)
        self.pos = pos

    def remaining(self) -> int:
        return len(self.data) - self.pos

    def _take(self, n: int) -> bytes:
        if self.pos + n > len(self.data):
            raise BincodeError(
                f"unexpected EOF at {self.pos}+{n} of {len(self.data)}"
            )
        out = self.data[self.pos:self.pos + n]
        self.pos += n
        return out

    def u8(self) -> int:
        return self._take(1)[0]

    def varint(self) -> int:
        b = self.u8()
        if b <= 250:
            return b
        if b == 251:
            return struct.unpack("<H", self._take(2))[0]
        if b == 252:
            return struct.unpack("<I", self._take(4))[0]
        if b == 253:
            return struct.unpack("<Q", self._take(8))[0]
        raise BincodeError(f"unsupported varint marker {b} (u128?)")

    def signed_varint(self) -> int:
        u = self.varint()
        return (u >> 1) ^ -(u & 1)

    def raw(self, n: int) -> bytes:
        return self._take(n)

    def lp_bytes(self) -> bytes:
        return self._take(self.varint())
