"""Golden-reference bridge to the real cr-sqlite extension.

The reference agent gets its CRDT semantics from a vendored native
cr-sqlite build (loaded at ``crates/corro-types/src/sqlite.rs:103-121``).
Our engine (:mod:`corrosion_tpu.agent.storage`) re-implements those
semantics over stock sqlite3.  This bridge loads the *actual* vendored
``crsqlite-linux-x86_64.so`` into a Python ``sqlite3`` connection so
property tests can replay identical op sequences on both engines and
assert the final replicated states bit-match (SURVEY §7.1's golden test).

Only used by tests/tools; the agent never depends on the extension.
"""

from __future__ import annotations

import os
import sqlite3
from contextlib import contextmanager
from typing import List, Optional, Sequence, Tuple

# Candidate locations for the vendored extension (first hit wins); override
# with CRSQLITE_SO.  The reference checks in prebuilt blobs under
# crates/corro-types/ (SURVEY §2.1).
_SO_CANDIDATES = (
    os.environ.get("CRSQLITE_SO", ""),
    "/root/reference/crates/corro-types/crsqlite-linux-x86_64.so",
)

# Column list of the crsql_changes virtual table, in the order the reference
# reads and writes it (corro-agent/src/agent/util.rs:1314-1317).
CHANGES_COLS = (
    '"table"', "pk", "cid", "val", "col_version", "db_version",
    "site_id", "cl", "seq",
)
_SELECT_CHANGES = (
    f"SELECT {', '.join(CHANGES_COLS)} FROM crsql_changes"
)
_INSERT_CHANGES = (
    f"INSERT INTO crsql_changes ({', '.join(CHANGES_COLS)}) "
    "VALUES (?, ?, ?, ?, ?, ?, ?, ?, ?)"
)


def find_crsqlite_so() -> Optional[str]:
    for cand in _SO_CANDIDATES:
        if cand and os.path.exists(cand):
            return cand
    return None


def crsqlite_available() -> bool:
    if find_crsqlite_so() is None:
        return False
    # Broad catch: loading can fail with TypeError (< 3.12: no `entrypoint`
    # kwarg), AttributeError (no loadable-extension support), or
    # sqlite3.Error — all mean "skip the golden tests", not "crash".
    conn = None
    try:
        conn = _connect(":memory:")
        return True
    except Exception:
        return False
    finally:
        if conn is not None:
            conn.close()


def _connect(path: str) -> sqlite3.Connection:
    so = find_crsqlite_so()
    if so is None:
        raise FileNotFoundError("cr-sqlite extension not found (set CRSQLITE_SO)")
    conn = sqlite3.connect(path, check_same_thread=False)
    conn.isolation_level = None  # explicit transactions only
    conn.enable_load_extension(True)
    # The filename-derived entrypoint would be sqlite3_crsqlitelinuxx_init;
    # the real symbol is the canonical one.
    conn.load_extension(os.path.splitext(so)[0], entrypoint="sqlite3_crsqlite_init")
    conn.enable_load_extension(False)
    return conn


class CrsqliteRef:
    """A replica backed by the real cr-sqlite extension.

    Mirrors the surface of :class:`corrosion_tpu.agent.storage.CrConn`
    that the golden tests drive: schema setup, transactional writes,
    change collection, change application, and table reads.
    """

    def __init__(self, path: str = ":memory:"):
        self.conn = _connect(path)
        self.site_id: bytes = bytes(
            self.conn.execute("SELECT crsql_site_id()").fetchone()[0]
        )

    @contextmanager
    def tx(self):
        """One explicit transaction == one db_version (like CrConn.write_tx)."""
        self.conn.execute("BEGIN IMMEDIATE")
        try:
            yield self.conn
        except BaseException:
            self.conn.execute("ROLLBACK")
            raise
        self.conn.execute("COMMIT")

    def execute(self, sql: str, params: Sequence = ()):
        with self.tx() as conn:
            return conn.execute(sql, params)

    def as_crr(self, table: str) -> None:
        self.conn.execute("SELECT crsql_as_crr(?)", (table,))

    def db_version(self) -> int:
        return self.conn.execute("SELECT crsql_db_version()").fetchone()[0]

    def changes(self, since_db_version: int = 0) -> List[Tuple]:
        """All change rows this replica knows (any origin site), raw."""
        return self.conn.execute(
            _SELECT_CHANGES + " WHERE db_version > ? ORDER BY db_version, seq",
            (since_db_version,),
        ).fetchall()

    def apply(self, rows: Sequence[Tuple]) -> None:
        """Merge raw change rows (the INSERT side of crsql_changes)."""
        with self.tx() as conn:
            conn.executemany(_INSERT_CHANGES, rows)

    def data(self, table: str) -> List[Tuple]:
        """Full table contents in a canonical (rowid-independent) order."""
        cur = self.conn.execute(f'SELECT * FROM "{table}"')
        return sorted(cur.fetchall(), key=_sort_key)

    def close(self) -> None:
        try:
            self.conn.execute("SELECT crsql_finalize()")
        except sqlite3.Error:
            pass
        self.conn.close()


def _sort_key(row: Tuple):
    # total order across heterogenous sqlite values
    return tuple(
        (0, "") if v is None
        else (1, float(v)) if isinstance(v, (int, float))
        else (2, v) if isinstance(v, str)
        else (3, bytes(v).hex())
        for v in row
    )
