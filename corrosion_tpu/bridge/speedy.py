"""speedy-compatible binary codec for the reference agent's wire types.

The reference serializes every gossip/sync message with the Rust `speedy`
crate (corro-speedy 0.8.7 fork) and frames streams with tokio's
``LengthDelimitedCodec``.  This module re-implements that byte format in
Python so our agent/simulator can exchange and diff traces with real
corrosion agents (SURVEY §7.6; VERDICT round-1 item 2(b)).

Layout rules (speedy 0.8, little-endian context — the default used by the
reference's ``read_from_buffer``/``write_to_buffer`` call sites):

* fixed-width integers/floats: little-endian;
* ``Vec<T>`` / ``String`` / ``&str`` / ``SmallVec<u8>``: ``u32`` length
  prefix + elements;
* ``Option<T>``: ``u8`` 1/0 then the value;
* ``HashMap<K, V>``: ``u32`` length + key/value pairs;
* ``RangeInclusive<T>``: start value then end value;
* ``[u8; 16]`` / ``Uuid``: 16 raw bytes, no length;
* derived enums: ``u32`` variant index in declaration order;
* ``#[speedy(default_on_eof)]`` fields: omitted-at-EOF ⇒ default on read;
* newtypes (``Version``/``CrsqlDbVersion``/``CrsqlSeq`` = u64,
  ``ClusterId`` = u16, ``Timestamp`` = NTP64 u64): the inner value.

Type definitions mirrored (field order is the wire order):
``ChangeV1``/``Changeset`` (broadcast.rs:104-137), ``UniPayload``/
``BiPayload`` (broadcast.rs:37-67), ``Change`` (change.rs:19-29),
``SqliteValue`` (corro-api-types/src/lib.rs:421-428,614-679 — manual
impl: u8 tag), ``SyncMessage``/``SyncStateV1``/``SyncNeedV1``/
``SyncRejectionV1`` (sync.rs:18-263), ``SyncTraceContextV1``
(sync.rs:32-36), ``ActorId`` (actor.rs:91-119, raw uuid bytes),
``Timestamp`` (broadcast.rs:363-391, u64), ``TableName``/``ColumnName``
(corro-api-types:780-856, string).

Stream framing: ``LengthDelimitedCodec`` defaults — ``u32`` BIG-endian
length prefix (tokio_util), used for uni-stream broadcasts and sync
bi-streams.
"""

from __future__ import annotations

import struct
from typing import Dict, List, Optional, Tuple

from corrosion_tpu.types.actor import ActorId, ClusterId
from corrosion_tpu.types.base import CrsqlDbVersion, CrsqlSeq, Version
from corrosion_tpu.types.change import Change
from corrosion_tpu.types.changeset import Changeset, ChangesetKind, ChangeV1
from corrosion_tpu.types.hlc import Timestamp
from corrosion_tpu.types.payload import (
    BiPayload,
    BroadcastV1,
    SyncNeedV1,
    SyncStateV1,
    UniPayload,
)


class SpeedyError(ValueError):
    pass


def _load_native():
    from corrosion_tpu.native import load_or_none

    return load_or_none()


# the C extension, gated per feature so a stale build missing newer
# entry points falls back to the Python twin for just those paths
_native_mod = _load_native()
_native = (
    _native_mod
    if _native_mod is not None
    and hasattr(_native_mod, "speedy_encode_changes")
    and hasattr(_native_mod, "speedy_decode_changes")
    else None
)


# ---------------------------------------------------------------------------
# primitive writer/reader
# ---------------------------------------------------------------------------


class Writer:
    def __init__(self):
        self._parts: List[bytes] = []

    def _pack(self, fmt: str, v) -> "Writer":
        # error-type parity with the native path: out-of-range or
        # wrong-typed values raise SpeedyError on both encoders
        try:
            self._parts.append(struct.pack(fmt, v))
        except (struct.error, TypeError, OverflowError, ValueError) as e:
            raise SpeedyError(f"cannot encode {v!r} as {fmt}: {e}") from e
        return self

    def u8(self, v: int) -> "Writer":
        return self._pack("<B", v)

    def u16(self, v: int) -> "Writer":
        return self._pack("<H", v)

    def u32(self, v: int) -> "Writer":
        return self._pack("<I", v)

    def u64(self, v: int) -> "Writer":
        try:
            v = int(v)
        except (TypeError, ValueError) as e:
            raise SpeedyError(f"cannot encode {v!r} as u64: {e}") from e
        return self._pack("<Q", v)

    def i64(self, v: int) -> "Writer":
        try:
            v = int(v)
        except (TypeError, ValueError) as e:
            raise SpeedyError(f"cannot encode {v!r} as i64: {e}") from e
        return self._pack("<q", v)

    def f64(self, v: float) -> "Writer":
        return self._pack("<d", v)

    def raw(self, b: bytes) -> "Writer":
        self._parts.append(bytes(b))
        return self

    def lp_bytes(self, b: bytes) -> "Writer":
        """u32-length-prefixed bytes (Vec<u8>/String/str)."""
        self.u32(len(b))
        return self.raw(b)

    def s(self, text: str) -> "Writer":
        return self.lp_bytes(text.encode("utf-8"))

    def tag(self, index: int) -> "Writer":
        """Derived-enum variant tag."""
        return self.u32(index)

    def opt(self, v, write_fn) -> "Writer":
        if v is None:
            return self.u8(0)
        self.u8(1)
        write_fn(v)
        return self

    def getvalue(self) -> bytes:
        return b"".join(self._parts)


class Reader:
    def __init__(self, data: bytes, pos: int = 0):
        self.data = bytes(data)
        self.pos = pos

    def _take(self, n: int) -> bytes:
        if self.pos + n > len(self.data):
            raise SpeedyError(
                f"unexpected EOF at {self.pos}+{n} of {len(self.data)}"
            )
        b = self.data[self.pos : self.pos + n]
        self.pos += n
        return b

    @property
    def eof(self) -> bool:
        return self.pos >= len(self.data)

    def u8(self) -> int:
        return self._take(1)[0]

    def u16(self) -> int:
        return struct.unpack("<H", self._take(2))[0]

    def u32(self) -> int:
        return struct.unpack("<I", self._take(4))[0]

    def u64(self) -> int:
        return struct.unpack("<Q", self._take(8))[0]

    def i64(self) -> int:
        return struct.unpack("<q", self._take(8))[0]

    def f64(self) -> float:
        return struct.unpack("<d", self._take(8))[0]

    def raw(self, n: int) -> bytes:
        return self._take(n)

    def lp_bytes(self) -> bytes:
        return self._take(self.u32())

    def s(self) -> str:
        return self.lp_bytes().decode("utf-8")

    def tag(self) -> int:
        return self.u32()

    def opt(self, read_fn):
        return read_fn() if self.u8() else None

    def expect_end(self) -> None:
        if not self.eof:
            raise SpeedyError(f"{len(self.data) - self.pos} trailing bytes")


# ---------------------------------------------------------------------------
# leaf types
# ---------------------------------------------------------------------------


def _w_actor(w: Writer, a: ActorId) -> None:
    w.raw(a.bytes)


def _r_actor(r: Reader) -> ActorId:
    return ActorId(r.raw(16))


def _w_ts(w: Writer, ts: Timestamp) -> None:
    w.u64(int(ts))


def _r_ts(r: Reader) -> Timestamp:
    return Timestamp(r.u64())


def _w_value(w: Writer, v) -> None:
    """SqliteValue: u8 tag 0..4 (Null/Integer/Real/Text/Blob)."""
    if v is None:
        w.u8(0)
    elif isinstance(v, bool):
        w.u8(1).i64(int(v))
    elif isinstance(v, int):
        w.u8(1).i64(v)
    elif isinstance(v, float):
        w.u8(2).f64(v)
    elif isinstance(v, str):
        w.u8(3).s(v)
    elif isinstance(v, (bytes, bytearray, memoryview)):
        w.u8(4).lp_bytes(bytes(v))
    else:
        raise SpeedyError(f"unsupported SqliteValue: {type(v)!r}")


def _r_value(r: Reader):
    t = r.u8()
    if t == 0:
        return None
    if t == 1:
        return r.i64()
    if t == 2:
        return r.f64()
    if t == 3:
        return r.s()
    if t == 4:
        return r.lp_bytes()
    raise SpeedyError(f"unknown SqliteValue variant {t}")


def _w_change(w: Writer, c: Change) -> None:
    w.s(c.table)
    w.lp_bytes(c.pk)
    w.s(c.cid)
    _w_value(w, c.val)
    w.i64(c.col_version)
    w.u64(int(c.db_version))
    w.u64(int(c.seq))
    w.raw(c.site_id)
    w.i64(c.cl)


def _r_change(r: Reader) -> Change:
    return Change(
        table=r.s(),
        pk=r.lp_bytes(),
        cid=r.s(),
        val=_r_value(r),
        col_version=r.i64(),
        db_version=CrsqlDbVersion(r.u64()),
        seq=CrsqlSeq(r.u64()),
        site_id=r.raw(16),
        cl=r.i64(),
    )


def encode_change(c: Change) -> bytes:
    """One Change in the speedy layout (the partial-buffer blob body)."""
    w = Writer()
    _w_change(w, c)
    return w.getvalue()


def decode_change(data: bytes) -> Change:
    """Inverse of :func:`encode_change`; raises SpeedyError on junk or
    trailing bytes."""
    r = Reader(data)
    c = _r_change(r)
    r.expect_end()
    return c


# ---------------------------------------------------------------------------
# Changeset / ChangeV1 / UniPayload / BiPayload
# ---------------------------------------------------------------------------

_CS_EMPTY, _CS_FULL, _CS_EMPTY_SET = 0, 1, 2


def _w_changes(w: Writer, changes) -> None:
    """The change-array hot loop: native when available (the C
    extension packs the speedy layout directly), Python twin otherwise."""
    if _native is not None:
        try:
            w.raw(_native.speedy_encode_changes(changes))
        except (TypeError, OverflowError) as e:
            # error-type parity with the Python twin's SpeedyError
            raise SpeedyError(str(e)) from None
        return
    for c in changes:
        _w_change(w, c)


def _r_changes(r: Reader, count: int) -> List[Change]:
    if _native is not None:
        try:
            tups, end = _native.speedy_decode_changes(r.data, r.pos, count)
        except ValueError as e:
            raise SpeedyError(str(e)) from None
        r.pos = end
        return [
            Change(
                table=t, pk=pk, cid=cid, val=val, col_version=cv,
                db_version=CrsqlDbVersion(dv), seq=CrsqlSeq(sq),
                site_id=site, cl=cl,
            )
            for t, pk, cid, val, cv, dv, sq, site, cl in tups
        ]
    return [_r_change(r) for _ in range(count)]


def _w_changeset(w: Writer, cs: Changeset) -> None:
    if cs.kind is ChangesetKind.EMPTY:
        w.tag(_CS_EMPTY)
        w.u64(int(cs.versions[0])).u64(int(cs.versions[1]))
        w.opt(cs.ts, lambda ts: _w_ts(w, ts))
    elif cs.kind is ChangesetKind.FULL:
        w.tag(_CS_FULL)
        w.u64(int(cs.version))
        w.u32(len(cs.changes))
        _w_changes(w, cs.changes)
        w.u64(int(cs.seqs[0])).u64(int(cs.seqs[1]))
        w.u64(int(cs.last_seq))
        _w_ts(w, cs.ts)
    else:
        w.tag(_CS_EMPTY_SET)
        w.u32(len(cs.ranges))
        for s, e in cs.ranges:
            w.u64(int(s)).u64(int(e))
        _w_ts(w, cs.ts)


def _r_changeset(r: Reader) -> Changeset:
    t = r.tag()
    if t == _CS_EMPTY:
        versions = (Version(r.u64()), Version(r.u64()))
        # `ts` is #[speedy(default_on_eof)]
        ts = None if r.eof else r.opt(lambda: _r_ts(r))
        return Changeset.empty(versions, ts)
    if t == _CS_FULL:
        version = Version(r.u64())
        changes = _r_changes(r, r.u32())
        seqs = (CrsqlSeq(r.u64()), CrsqlSeq(r.u64()))
        last_seq = CrsqlSeq(r.u64())
        ts = _r_ts(r)
        return Changeset.full(version, changes, seqs, last_seq, ts)
    if t == _CS_EMPTY_SET:
        ranges = [
            (Version(r.u64()), Version(r.u64())) for _ in range(r.u32())
        ]
        ts = _r_ts(r)
        return Changeset.empty_set(ranges, ts)
    raise SpeedyError(f"unknown Changeset variant {t}")


def _w_change_v1(w: Writer, cv: ChangeV1) -> None:
    _w_actor(w, cv.actor_id)
    _w_changeset(w, cv.changeset)


def _r_change_v1(r: Reader) -> ChangeV1:
    return ChangeV1(actor_id=_r_actor(r), changeset=_r_changeset(r))


def encode_uni_payload(p: UniPayload) -> bytes:
    """UniPayload::V1 { data: UniPayloadV1::Broadcast(BroadcastV1::Change),
    cluster_id (default_on_eof) }."""
    w = Writer()
    w.tag(0)  # UniPayload::V1
    w.tag(0)  # UniPayloadV1::Broadcast
    w.tag(0)  # BroadcastV1::Change
    _w_change_v1(w, p.broadcast.change)
    w.u16(int(p.cluster_id))
    return w.getvalue()


def decode_uni_payload(data: bytes) -> UniPayload:
    r = Reader(data)
    if r.tag() != 0:
        raise SpeedyError("unknown UniPayload variant")
    if r.tag() != 0:
        raise SpeedyError("unknown UniPayloadV1 variant")
    if r.tag() != 0:
        raise SpeedyError("unknown BroadcastV1 variant")
    change = _r_change_v1(r)
    cluster_id = ClusterId(0) if r.eof else ClusterId(r.u16())
    r.expect_end()
    return UniPayload(broadcast=BroadcastV1(change=change), cluster_id=cluster_id)


# -- traced uni envelope (versioned extension) -------------------------
#
# Broadcast-path trace propagation (docs/telemetry.md): a 1-byte
# version prefix ahead of the classic UniPayload bytes, mirroring the
# partial-buffer blob versioning — the classic payload's first byte is
# 0x00 (the u32-LE UniPayload::V1 tag), so 0x01 unambiguously marks the
# extended format and OLD-FORMAT PAYLOADS DECODE UNCHANGED.  Body:
#
#   u8 version (=1) | u8 hop | Option<String> traceparent | UniPayload
#
# ``hop`` counts rebroadcast generations (0 = the origin's own
# transmission), letting receivers label provenance lag broadcast vs
# rebroadcast; ``traceparent`` re-parents the remote apply span on the
# origin's write-group trace.  Emission is gated by
# ``AgentConfig.bcast_trace_propagation`` — turn it off for
# reference-byte-exact wire output (receivers accept both regardless).

TRACED_UNI_VERSION = 1
# signed attribution envelope (docs/faults.md): the traced layout plus
# one more Option field — a raw 64-byte Ed25519 signature over the
# changeset's canonical identity (types/crypto.py; the signing message
# is built by agent/runtime.py sig_message).  Emitted only when the
# origin is configured with a signing key, so an unsigned deployment's
# wire stays byte-exact vs the v0/v1 formats.
SIGNED_UNI_VERSION = 2
SIG_BYTES = 64
# traceparent is 55 chars; anything longer is junk, reject before it
# can bloat frames or the span ring
MAX_TRACEPARENT_LEN = 64


def encode_traced_uni(payload: bytes, traceparent: Optional[str] = None,
                      hop: int = 0) -> bytes:
    """Wrap classic UniPayload bytes in the traced envelope."""
    w = Writer()
    w.u8(TRACED_UNI_VERSION)
    w.u8(min(max(int(hop), 0), 255))
    w.opt(traceparent, w.s)
    w.raw(payload)
    return w.getvalue()


def encode_signed_uni(payload: bytes, traceparent: Optional[str] = None,
                      hop: int = 0, sig: Optional[bytes] = None) -> bytes:
    """Wrap classic UniPayload bytes in the SIGNED envelope (v2):
    ``u8 2 | u8 hop | Option<traceparent> | Option<[u8;64] sig> |
    UniPayload``.  ``sig`` rides as 64 raw bytes (speedy ``[u8; N]``
    layout, no length prefix)."""
    if sig is not None and len(sig) != SIG_BYTES:
        raise SpeedyError(
            f"signature must be {SIG_BYTES} bytes, got {len(sig)}"
        )
    w = Writer()
    w.u8(SIGNED_UNI_VERSION)
    w.u8(min(max(int(hop), 0), 255))
    w.opt(traceparent, w.s)
    w.opt(sig, w.raw)
    w.raw(payload)
    return w.getvalue()


def _read_traceparent(r: Reader) -> Optional[str]:
    # strict Option tag, matching traced_uni_payload_start: the walker
    # and the decoder must accept the SAME byte set or the live path's
    # prelude screen and the det scheduler diverge on hostile frames
    flag = r.u8()
    if flag == 0:
        return None
    if flag != 1:
        raise SpeedyError(f"bad Option tag {flag}")
    # bound in BYTES (the u32 length prefix), exactly like
    # traced_uni_payload_start — bounding the decoded char count
    # instead would let a multi-byte-UTF-8 traceparent pass here
    # while the walker rejects the same frame, and live ingest
    # (which screens via the walker) would diverge from the det
    # scheduler on identical bytes
    raw = r.lp_bytes()
    if len(raw) > MAX_TRACEPARENT_LEN:
        raise SpeedyError("oversized traceparent")
    try:
        return raw.decode("utf-8")
    except UnicodeDecodeError as e:
        # keep the SpeedyError contract: a raw UnicodeDecodeError
        # would escape callers' `except SpeedyError` handling
        raise SpeedyError(f"invalid traceparent utf-8: {e}") from None


def decode_uni_envelope(
    data: bytes,
) -> Tuple[bytes, Optional[str], int, Optional[bytes]]:
    """``(classic_payload, traceparent, hop, sig)`` from any wire
    format: classic (0x00), traced (0x01) or signed (0x02).  Unknown
    envelope versions raise SpeedyError."""
    if not data:
        raise SpeedyError("empty uni payload")
    if data[0] == 0:
        return data, None, 0, None
    if data[0] not in (TRACED_UNI_VERSION, SIGNED_UNI_VERSION):
        raise SpeedyError(f"unknown traced-uni version {data[0]}")
    r = Reader(data, pos=1)
    hop = r.u8()
    tp = _read_traceparent(r)
    sig = None
    if data[0] == SIGNED_UNI_VERSION:
        flag = r.u8()
        if flag == 1:
            sig = r.raw(SIG_BYTES)
        elif flag != 0:
            raise SpeedyError(f"bad Option tag {flag}")
    return data[r.pos:], tp, hop, sig


def decode_traced_uni(data: bytes) -> Tuple[bytes, Optional[str], int]:
    """``(classic_payload, traceparent, hop)`` from any wire format —
    the pre-signing surface, kept for callers that don't carry the
    signature (the signature, if any, is dropped)."""
    payload, tp, hop, _sig = decode_uni_envelope(data)
    return payload, tp, hop


def traced_uni_payload_start(data: bytes, off: int = 0) -> int:
    """Offset of the classic UniPayload bytes inside ``data`` — the
    cheap event-loop-side check (no string decode, no change decode)
    that lets the ingest queue's 12-byte tag prelude screen work on
    every wire format (classic/traced/signed).  Raises SpeedyError on
    a malformed envelope."""
    if off >= len(data):
        raise SpeedyError("empty uni payload")
    version = data[off]
    if version == 0:
        return off
    if version not in (TRACED_UNI_VERSION, SIGNED_UNI_VERSION):
        raise SpeedyError(f"unknown traced-uni version {version}")
    pos = off + 2  # version + hop
    if pos >= len(data):
        raise SpeedyError("truncated traced-uni envelope")
    flag = data[pos]
    pos += 1
    if flag == 1:
        if pos + 4 > len(data):
            raise SpeedyError("truncated traceparent length")
        (n,) = struct.unpack_from("<I", data, pos)
        if n > MAX_TRACEPARENT_LEN:
            raise SpeedyError("oversized traceparent")
        pos += 4 + n
    elif flag != 0:
        raise SpeedyError(f"bad Option tag {flag}")
    if version == SIGNED_UNI_VERSION:
        if pos >= len(data):
            raise SpeedyError("truncated signed-uni envelope")
        flag = data[pos]
        pos += 1
        if flag == 1:
            if pos + SIG_BYTES > len(data):
                raise SpeedyError("truncated signature")
            pos += SIG_BYTES
        elif flag != 0:
            raise SpeedyError(f"bad Option tag {flag}")
    return pos


def encode_bi_payload(p: BiPayload, cluster_id: ClusterId = ClusterId(0)) -> bytes:
    """BiPayload::V1 { data: BiPayloadV1::SyncStart { actor_id, trace_ctx },
    cluster_id }."""
    w = Writer()
    w.tag(0)  # BiPayload::V1
    w.tag(0)  # BiPayloadV1::SyncStart
    _w_actor(w, p.actor_id)
    trace = p.trace_ctx or {}
    w.opt(trace.get("traceparent"), w.s)
    w.opt(trace.get("tracestate"), w.s)
    w.u16(int(cluster_id))
    return w.getvalue()


def decode_bi_payload(data: bytes) -> Tuple[BiPayload, ClusterId]:
    r = Reader(data)
    if r.tag() != 0:
        raise SpeedyError("unknown BiPayload variant")
    if r.tag() != 0:
        raise SpeedyError("unknown BiPayloadV1 variant")
    actor = _r_actor(r)
    # trace_ctx is default_on_eof as a whole struct
    trace: Optional[dict] = None
    if not r.eof:
        tp = r.opt(r.s)
        ts_ = r.opt(r.s)
        if tp or ts_:
            trace = {}
            if tp:
                trace["traceparent"] = tp
            if ts_:
                trace["tracestate"] = ts_
    cluster_id = ClusterId(0) if r.eof else ClusterId(r.u16())
    r.expect_end()
    return BiPayload(actor_id=actor, trace_ctx=trace), cluster_id


# ---------------------------------------------------------------------------
# Sync messages
# ---------------------------------------------------------------------------

_SN_FULL, _SN_PARTIAL, _SN_EMPTY = 0, 1, 2


def _w_need(w: Writer, n: SyncNeedV1) -> None:
    if n.kind == "full":
        w.tag(_SN_FULL)
        w.u64(n.versions[0]).u64(n.versions[1])
    elif n.kind == "partial":
        w.tag(_SN_PARTIAL)
        w.u64(int(n.version))
        w.u32(len(n.seqs))
        for s, e in n.seqs:
            w.u64(s).u64(e)
    else:
        w.tag(_SN_EMPTY)
        w.opt(n.ts, lambda ts: _w_ts(w, ts))


def _span(r: Reader) -> Tuple[int, int]:
    s, e = r.u64(), r.u64()
    if e < s:
        raise SpeedyError(f"inverted range {s}..={e}")
    return s, e


def _r_need(r: Reader) -> SyncNeedV1:
    t = r.tag()
    if t == _SN_FULL:
        return SyncNeedV1.full(*_span(r))
    if t == _SN_PARTIAL:
        version = r.u64()
        seqs = [_span(r) for _ in range(r.u32())]
        return SyncNeedV1.partial(version, seqs)
    if t == _SN_EMPTY:
        return SyncNeedV1.empty(r.opt(lambda: _r_ts(r)))
    raise SpeedyError(f"unknown SyncNeedV1 variant {t}")


def _w_sync_state(w: Writer, st: SyncStateV1) -> None:
    _w_actor(w, st.actor_id)
    w.u32(len(st.heads))
    for actor, head in st.heads.items():
        _w_actor(w, actor)
        w.u64(int(head))
    w.u32(len(st.need))
    for actor, spans in st.need.items():
        _w_actor(w, actor)
        w.u32(len(spans))
        for s, e in spans:
            w.u64(s).u64(e)
    w.u32(len(st.partial_need))
    for actor, partials in st.partial_need.items():
        _w_actor(w, actor)
        w.u32(len(partials))
        for version, spans in partials.items():
            w.u64(int(version))
            w.u32(len(spans))
            for s, e in spans:
                w.u64(s).u64(e)
    w.opt(st.last_cleared_ts, lambda ts: _w_ts(w, ts))
    # snapshot-serve extension (docs/sync.md): trailing floors map,
    # written ONLY when non-empty — a floor-less state emits the
    # pre-snapshot bytes exactly (same default_on_eof discipline as
    # last_cleared_ts before it)
    if st.snap_floors:
        w.u32(len(st.snap_floors))
        for actor, floor in st.snap_floors.items():
            _w_actor(w, actor)
            w.u64(int(floor))


def _r_sync_state(r: Reader) -> SyncStateV1:
    actor = _r_actor(r)
    heads = {}
    for _ in range(r.u32()):
        a = _r_actor(r)
        heads[a] = Version(r.u64())
    need: Dict[ActorId, List[Tuple[int, int]]] = {}
    for _ in range(r.u32()):
        a = _r_actor(r)
        need[a] = [_span(r) for _ in range(r.u32())]
    partial_need: Dict[ActorId, Dict[Version, List[Tuple[int, int]]]] = {}
    for _ in range(r.u32()):
        a = _r_actor(r)
        partials = {}
        for _ in range(r.u32()):
            v = Version(r.u64())
            partials[v] = [_span(r) for _ in range(r.u32())]
        partial_need[a] = partials
    last_cleared_ts = None if r.eof else r.opt(lambda: _r_ts(r))
    snap_floors: Dict[ActorId, int] = {}
    if not r.eof:
        for _ in range(r.u32()):
            a = _r_actor(r)
            snap_floors[a] = r.u64()
    return SyncStateV1(
        actor_id=actor,
        heads=heads,
        need=need,
        partial_need=partial_need,
        last_cleared_ts=last_cleared_ts,
        snap_floors=snap_floors,
    )


# SyncMessageV1 variant indices (sync.rs:23-30)
_SM_STATE, _SM_CHANGESET, _SM_CLOCK, _SM_REJECTION, _SM_REQUEST = range(5)

# snapshot-serve extension variants (docs/sync.md): a client whose
# needs fall below the server's advertised snapshot floors requests a
# whole-database snapshot instead of change-by-change serving.  The
# variants extend the enum PAST the reference's tags, so a session
# that never dispatches snapshot emits the reference's exact bytes.
_SM_SNAP_REQUEST, _SM_SNAP_OFFER, _SM_SNAP_CHUNK, _SM_SNAP_DONE = range(5, 9)

#: whole-snapshot content digest length (blake2b-32) carried by offers
SNAP_DIGEST_LEN = 32

# SyncRejectionV1 variant indices (sync.rs:251-257)
REJECTION_MAX_CONCURRENCY = 0
REJECTION_DIFFERENT_CLUSTER = 1

SyncRequest = List[Tuple[ActorId, List[SyncNeedV1]]]


def encode_sync_message(msg) -> bytes:
    """msg is one of: SyncStateV1 | ChangeV1 | Timestamp |
    ("rejection", int) | ("request", SyncRequest) |
    ("snap_request",) | ("snap_offer", digest32, size) |
    ("snap_chunk", bytes) | ("snap_done",)."""
    w = Writer()
    w.tag(0)  # SyncMessage::V1
    if isinstance(msg, SyncStateV1):
        w.tag(_SM_STATE)
        _w_sync_state(w, msg)
    elif isinstance(msg, ChangeV1):
        w.tag(_SM_CHANGESET)
        _w_change_v1(w, msg)
    elif isinstance(msg, Timestamp):
        w.tag(_SM_CLOCK)
        _w_ts(w, msg)
    elif isinstance(msg, tuple) and msg[0] == "rejection":
        w.tag(_SM_REJECTION)
        w.tag(msg[1])
    elif isinstance(msg, tuple) and msg[0] == "request":
        w.tag(_SM_REQUEST)
        w.u32(len(msg[1]))
        for actor, needs in msg[1]:
            _w_actor(w, actor)
            w.u32(len(needs))
            for n in needs:
                _w_need(w, n)
    elif isinstance(msg, tuple) and msg[0] == "snap_request":
        w.tag(_SM_SNAP_REQUEST)
    elif isinstance(msg, tuple) and msg[0] == "snap_offer":
        digest, size = msg[1], msg[2]
        if len(digest) != SNAP_DIGEST_LEN:
            raise SpeedyError(
                f"snapshot digest must be {SNAP_DIGEST_LEN} bytes"
            )
        w.tag(_SM_SNAP_OFFER)
        w.raw(bytes(digest))
        w.u64(int(size))
    elif isinstance(msg, tuple) and msg[0] == "snap_chunk":
        w.tag(_SM_SNAP_CHUNK)
        w.lp_bytes(bytes(msg[1]))
    elif isinstance(msg, tuple) and msg[0] == "snap_done":
        w.tag(_SM_SNAP_DONE)
    else:
        raise SpeedyError(f"cannot encode sync message {type(msg)!r}")
    return w.getvalue()


def decode_sync_message(data: bytes):
    r = Reader(data)
    if r.tag() != 0:
        raise SpeedyError("unknown SyncMessage variant")
    t = r.tag()
    if t == _SM_STATE:
        out = _r_sync_state(r)
    elif t == _SM_CHANGESET:
        out = _r_change_v1(r)
    elif t == _SM_CLOCK:
        out = _r_ts(r)
    elif t == _SM_REJECTION:
        out = ("rejection", r.tag())
    elif t == _SM_REQUEST:
        req: SyncRequest = []
        for _ in range(r.u32()):
            actor = _r_actor(r)
            req.append((actor, [_r_need(r) for _ in range(r.u32())]))
        out = ("request", req)
    elif t == _SM_SNAP_REQUEST:
        out = ("snap_request",)
    elif t == _SM_SNAP_OFFER:
        out = ("snap_offer", r.raw(SNAP_DIGEST_LEN), r.u64())
    elif t == _SM_SNAP_CHUNK:
        out = ("snap_chunk", r.lp_bytes())
    elif t == _SM_SNAP_DONE:
        out = ("snap_done",)
    else:
        raise SpeedyError(f"unknown SyncMessageV1 variant {t}")
    r.expect_end()
    return out


# ---------------------------------------------------------------------------
# LengthDelimitedCodec framing (u32 big-endian, tokio_util default)
# ---------------------------------------------------------------------------

MAX_FRAME_LEN = 8 * 1024 * 1024


def frame(payload: bytes) -> bytes:
    return struct.pack(">I", len(payload)) + payload


def _py_deframe(buf: bytes) -> Tuple[List[bytes], bytes]:
    """Split complete frames off the front; return (frames, remainder)."""
    frames = []
    pos = 0
    while pos + 4 <= len(buf):
        (n,) = struct.unpack_from(">I", buf, pos)
        if n > MAX_FRAME_LEN:
            raise SpeedyError(f"frame length {n} exceeds max {MAX_FRAME_LEN}")
        if pos + 4 + n > len(buf):
            break
        frames.append(buf[pos + 4 : pos + 4 + n])
        pos += 4 + n
    return frames, buf[pos:]


if _native_mod is not None and hasattr(_native_mod, "deframe"):
    def deframe(buf: bytes) -> Tuple[List[bytes], bytes]:
        """Native frame splitter (semantics pinned to :func:`_py_deframe`)."""
        try:
            return _native_mod.deframe(buf, MAX_FRAME_LEN)
        except ValueError as e:
            raise SpeedyError(str(e)) from None
else:
    deframe = _py_deframe


class FrameReader:
    """Incremental LengthDelimited deframer for stream transports:
    feed() raw bytes, get back complete frame payloads."""

    def __init__(self):
        self._buf = bytearray()

    def feed(self, data: bytes) -> List[bytes]:
        self._buf += data
        frames, rest = deframe(bytes(self._buf))
        self._buf = bytearray(rest)
        return frames
