{% for r in sql("SELECT hex(id) AS id, title, completed_at FROM todos ORDER BY title") %}
[{% if r.completed_at %}x{% else %} {% endif %}] {{ r.title }}
{% endfor %}
