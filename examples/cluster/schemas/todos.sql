-- Every CREATE TABLE in a schema file becomes a replicated CRR table.
-- Constraints follow the reference's rules: a primary key is required;
-- foreign keys, unique indexes, and NOT NULL without a default are
-- rejected (they cannot merge deterministically).
CREATE TABLE todos (
    id BLOB NOT NULL PRIMARY KEY,
    title TEXT NOT NULL DEFAULT '',
    completed_at INTEGER
);
