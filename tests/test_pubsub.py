"""Subscription matcher + table-update stream tests (over real agents)."""

import asyncio
import json
import threading
import urllib.request

import pytest

from corrosion_tpu.agent.testing import launch_test_agent, wait_for


@pytest.fixture
def run():
    def _run(coro):
        return asyncio.run(coro)

    return _run


def _collect_stream(url, events, body=None, n_target=64):
    """Read NDJSON events from an endpoint into `events` until closed."""

    def reader():
        req = urllib.request.Request(
            url, data=json.dumps(body).encode() if body is not None else None
        )
        try:
            with urllib.request.urlopen(req, timeout=30) as resp:
                events.append(("__headers__", dict(resp.headers)))
                for line in resp:
                    events.append(json.loads(line))
                    if len(events) > n_target:
                        return
        except Exception as e:  # noqa: BLE001 - surfaced via events
            events.append(("__error__", repr(e)))

    t = threading.Thread(target=reader, daemon=True)
    t.start()
    return t


def test_subscription_snapshot_then_live_changes(run):
    async def main():
        a = await launch_test_agent()
        try:
            a.execute_transaction(
                [["INSERT INTO tests (id, text) VALUES (1, 'one')"]]
            )
            handle = a.subs.subscribe("SELECT id, text FROM tests ORDER BY id")
            gen = handle.stream()
            assert next(gen) == {"columns": ["id", "text"]}
            assert next(gen)["row"][1] == [1, "one"]
            eoq = next(gen)
            assert "eoq" in eoq

            # live: insert, update, delete
            a.execute_transaction(
                [["INSERT INTO tests (id, text) VALUES (2, 'two')"]]
            )
            ev = await asyncio.to_thread(next, gen)
            assert ev["change"][0] == "insert" and ev["change"][2] == [2, "two"]

            a.execute_transaction(
                [["UPDATE tests SET text='TWO' WHERE id=2"]]
            )
            kinds = set()
            for _ in range(2):
                ev = await asyncio.to_thread(next, gen)
                kinds.add((ev["change"][0], tuple(ev["change"][2])))
            # an update appears as delete(old)+insert(new) in diff terms
            assert ("insert", (2, "TWO")) in kinds
            assert ("delete", (2, "two")) in kinds

            a.execute_transaction([["DELETE FROM tests WHERE id=1"]])
            ev = await asyncio.to_thread(next, gen)
            assert ev["change"][0] == "delete" and ev["change"][2] == [1, "one"]
        finally:
            await a.stop()

    run(main())


def test_same_sql_shares_subscription(run):
    async def main():
        a = await launch_test_agent()
        try:
            h1 = a.subs.subscribe("SELECT id FROM tests")
            h2 = a.subs.subscribe("  SELECT id FROM tests ; ")
            assert h1.id == h2.id
        finally:
            await a.stop()

    run(main())


def test_subscription_sees_remote_changes(run):
    async def main():
        a = await launch_test_agent()
        b = await launch_test_agent(
            bootstrap=[f"{a.gossip_addr[0]}:{a.gossip_addr[1]}"]
        )
        try:
            await wait_for(lambda: a.members.alive() and b.members.alive())
            handle = b.subs.subscribe("SELECT id, text FROM tests")
            gen = handle.stream()
            while "eoq" not in (ev := next(gen)):
                pass
            a.execute_transaction(
                [["INSERT INTO tests (id, text) VALUES (7, 'remote')"]]
            )
            ev = await asyncio.to_thread(next, gen)
            assert ev["change"][0] == "insert"
            assert ev["change"][2] == [7, "remote"]
        finally:
            await b.stop()
            await a.stop()

    run(main())


def test_catch_up_from_change_id(run):
    async def main():
        a = await launch_test_agent()
        try:
            handle = a.subs.subscribe("SELECT id FROM tests")
            a.execute_transaction([["INSERT INTO tests (id) VALUES (1)"]])
            await wait_for(lambda: handle.last_change_id >= 1)
            cid = handle.last_change_id
            a.execute_transaction([["INSERT INTO tests (id) VALUES (2)"]])
            await wait_for(lambda: handle.last_change_id >= cid + 1)
            # re-attach from the observed change id: only the delta arrives
            gen = handle.stream(from_change_id=cid)
            ev = next(gen)
            assert ev["change"][0] == "insert" and ev["change"][2] == [2]
        finally:
            await a.stop()

    run(main())


def test_subscription_http_roundtrip(run):
    async def main():
        a = await launch_test_agent()
        try:
            a.execute_transaction(
                [["INSERT INTO tests (id, text) VALUES (1, 'seed')"]]
            )
            events = []
            url = f"http://{a.api_addr[0]}:{a.api_addr[1]}/v1/subscriptions"
            _collect_stream(url, events, body="SELECT id, text FROM tests")
            await wait_for(
                lambda: any(isinstance(e, dict) and "eoq" in e for e in events)
            )
            headers = events[0][1]
            assert "x-corro-query-id" in headers
            a.execute_transaction(
                [["INSERT INTO tests (id, text) VALUES (2, 'live')"]]
            )
            await wait_for(
                lambda: any(
                    isinstance(e, dict) and e.get("change", [None])[0] == "insert"
                    and e["change"][2] == [2, "live"]
                    for e in events
                )
            )
        finally:
            await a.stop()

    run(main())


def test_table_updates_stream(run):
    async def main():
        a = await launch_test_agent()
        try:
            gen = a.subs.table_updates("tests")
            a.execute_transaction([["INSERT INTO tests (id) VALUES (5)"]])
            ev = await asyncio.to_thread(next, gen)
            assert ev["change"][0] == "upsert" and ev["change"][1] == [5]
            a.execute_transaction([["DELETE FROM tests WHERE id=5"]])
            ev = await asyncio.to_thread(next, gen)
            assert ev["change"][0] == "delete" and ev["change"][1] == [5]
        finally:
            await a.stop()

    run(main())


def test_subscription_restored_after_restart(run):
    async def main():
        import tempfile

        d = tempfile.mkdtemp(prefix="corro-subs-")
        a = await launch_test_agent(tmpdir=d)
        try:
            a.subs.subscribe("SELECT id, text FROM tests")
            a.execute_transaction([["INSERT INTO tests (id) VALUES (1)"]])
        finally:
            await a.stop()

        a2 = await launch_test_agent(tmpdir=d)
        try:
            subs = a2.subs.list()
            assert len(subs) == 1
            assert subs[0]["sql"] == "SELECT id, text FROM tests"
            h = a2.subs.get(subs[0]["id"])
            assert len(h.rows) == 1
        finally:
            await a2.stop()

    run(main())
