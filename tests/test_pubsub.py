"""Subscription matcher + table-update stream tests (over real agents)."""

import asyncio
import json
import sqlite3
import threading
import urllib.request

import pytest

from corrosion_tpu.agent.testing import launch_test_agent, wait_for


@pytest.fixture
def run():
    def _run(coro):
        return asyncio.run(coro)

    return _run


def _collect_stream(url, events, body=None, n_target=64):
    """Read NDJSON events from an endpoint into `events` until closed."""

    def reader():
        req = urllib.request.Request(
            url, data=json.dumps(body).encode() if body is not None else None
        )
        try:
            with urllib.request.urlopen(req, timeout=30) as resp:
                events.append(("__headers__", dict(resp.headers)))
                for line in resp:
                    events.append(json.loads(line))
                    if len(events) > n_target:
                        return
        except Exception as e:  # noqa: BLE001 - surfaced via events
            events.append(("__error__", repr(e)))

    t = threading.Thread(target=reader, daemon=True)
    t.start()
    return t


def test_subscription_snapshot_then_live_changes(run):
    async def main():
        a = await launch_test_agent()
        try:
            a.execute_transaction(
                [["INSERT INTO tests (id, text) VALUES (1, 'one')"]]
            )
            handle = a.subs.subscribe("SELECT id, text FROM tests ORDER BY id")
            gen = handle.stream()
            assert next(gen) == {"columns": ["id", "text"]}
            assert next(gen)["row"][1] == [1, "one"]
            eoq = next(gen)
            assert "eoq" in eoq

            # live: insert, update, delete
            a.execute_transaction(
                [["INSERT INTO tests (id, text) VALUES (2, 'two')"]]
            )
            ev = await asyncio.to_thread(next, gen)
            assert ev["change"][0] == "insert" and ev["change"][2] == [2, "two"]

            a.execute_transaction(
                [["UPDATE tests SET text='TWO' WHERE id=2"]]
            )
            # pk-keyed materialization: a changed row is an UPDATE event
            ev = await asyncio.to_thread(next, gen)
            assert (ev["change"][0], tuple(ev["change"][2])) == (
                "update", (2, "TWO")
            )

            a.execute_transaction([["DELETE FROM tests WHERE id=1"]])
            ev = await asyncio.to_thread(next, gen)
            assert ev["change"][0] == "delete" and ev["change"][2] == [1, "one"]
        finally:
            await a.stop()

    run(main())


def test_same_sql_shares_subscription(run):
    async def main():
        a = await launch_test_agent()
        try:
            h1 = a.subs.subscribe("SELECT id FROM tests")
            h2 = a.subs.subscribe("  SELECT id FROM tests ; ")
            assert h1.id == h2.id
        finally:
            await a.stop()

    run(main())


def test_subscription_sees_remote_changes(run):
    async def main():
        a = await launch_test_agent()
        b = await launch_test_agent(
            bootstrap=[f"{a.gossip_addr[0]}:{a.gossip_addr[1]}"]
        )
        try:
            await wait_for(lambda: a.members.alive() and b.members.alive())
            handle = b.subs.subscribe("SELECT id, text FROM tests")
            gen = handle.stream()
            while "eoq" not in (ev := next(gen)):
                pass
            a.execute_transaction(
                [["INSERT INTO tests (id, text) VALUES (7, 'remote')"]]
            )
            ev = await asyncio.to_thread(next, gen)
            assert ev["change"][0] == "insert"
            assert ev["change"][2] == [7, "remote"]
        finally:
            await b.stop()
            await a.stop()

    run(main())


def test_catch_up_from_change_id(run):
    async def main():
        a = await launch_test_agent()
        try:
            handle = a.subs.subscribe("SELECT id FROM tests")
            a.execute_transaction([["INSERT INTO tests (id) VALUES (1)"]])
            await wait_for(lambda: handle.last_change_id >= 1)
            cid = handle.last_change_id
            a.execute_transaction([["INSERT INTO tests (id) VALUES (2)"]])
            await wait_for(lambda: handle.last_change_id >= cid + 1)
            # re-attach from the observed change id: only the delta arrives
            gen = handle.stream(from_change_id=cid)
            ev = next(gen)
            assert ev["change"][0] == "insert" and ev["change"][2] == [2]
        finally:
            await a.stop()

    run(main())


def test_subscription_http_roundtrip(run):
    async def main():
        a = await launch_test_agent()
        try:
            a.execute_transaction(
                [["INSERT INTO tests (id, text) VALUES (1, 'seed')"]]
            )
            events = []
            url = f"http://{a.api_addr[0]}:{a.api_addr[1]}/v1/subscriptions"
            _collect_stream(url, events, body="SELECT id, text FROM tests")
            await wait_for(
                lambda: any(isinstance(e, dict) and "eoq" in e for e in events)
            )
            headers = events[0][1]
            assert "x-corro-query-id" in headers
            a.execute_transaction(
                [["INSERT INTO tests (id, text) VALUES (2, 'live')"]]
            )
            await wait_for(
                lambda: any(
                    isinstance(e, dict) and e.get("change", [None])[0] == "insert"
                    and e["change"][2] == [2, "live"]
                    for e in events
                )
            )
        finally:
            await a.stop()

    run(main())


def test_table_updates_stream(run):
    async def main():
        a = await launch_test_agent()
        try:
            gen = a.subs.table_updates("tests")
            a.execute_transaction([["INSERT INTO tests (id) VALUES (5)"]])
            ev = await asyncio.to_thread(next, gen)
            assert ev["change"][0] == "upsert" and ev["change"][1] == [5]
            a.execute_transaction([["DELETE FROM tests WHERE id=5"]])
            ev = await asyncio.to_thread(next, gen)
            assert ev["change"][0] == "delete" and ev["change"][1] == [5]
        finally:
            await a.stop()

    run(main())


def test_subscription_restored_after_restart(run):
    async def main():
        import tempfile

        d = tempfile.mkdtemp(prefix="corro-subs-")
        a = await launch_test_agent(tmpdir=d)
        try:
            a.subs.subscribe("SELECT id, text FROM tests")
            a.execute_transaction([["INSERT INTO tests (id) VALUES (1)"]])
        finally:
            await a.stop()

        a2 = await launch_test_agent(tmpdir=d)
        try:
            subs = a2.subs.list()
            assert len(subs) == 1
            assert subs[0]["sql"] == "SELECT id, text FROM tests"
            h = a2.subs.get(subs[0]["id"])
            assert len(h.rows) == 1
        finally:
            await a2.stop()

    run(main())


def test_incremental_delta_work_scales_with_change_not_table(run):
    """A 100k-row table with a live subscription processes a 10-row
    change batch with work proportional to the 10 rows: the pk-scoped
    delta query runs as an indexed SEARCH, and the sqlite VM executes
    orders of magnitude fewer instructions than a full re-evaluation."""
    async def main():
        a = await launch_test_agent()
        try:
            # bulk-load 100k rows in one statement (CRR triggers fire
            # per row, so this is also a trigger soak)
            a.execute_transaction([[
                "INSERT INTO tests (id, text) "
                "SELECT value, 'v' || value FROM ("
                "WITH RECURSIVE c(value) AS ("
                "SELECT 1 UNION ALL SELECT value+1 FROM c WHERE value<100000"
                ") SELECT value FROM c)"
            ]])
            sub = a.subs.subscribe(
                "SELECT id, text FROM tests WHERE id % 2 = 0"
            )
            assert sub.incremental, "query should qualify for delta eval"
            assert len(sub.rows) == 50_000
            # the bulk load's own broadcast chunks land as ~100k pending
            # candidate pks (handled by the full-refresh fallback).
            # Local on_change deliveries are FIFO on the event loop, so
            # a probe row inserted NOW reaches the worker only after the
            # whole backlog; then idle() confirms no refresh round is
            # still in flight (the sets go empty the moment a round is
            # claimed, long before its SQL finishes)
            a.execute_transaction([
                ["INSERT INTO tests (id, text) VALUES (199998, 'probe')"]
            ])
            await wait_for(
                lambda: any(
                    c[0] == 199998 for _, c in list(sub.rows.values())
                ),
                timeout=60,
            )
            await wait_for(a.subs.idle, timeout=60)

            # the delta query must be an indexed SEARCH, not a SCAN
            cols, plan = a.storage.read_query(
                "EXPLAIN QUERY PLAN SELECT * FROM "
                f"({sub.sql}) WHERE (\"id\") IN (VALUES (2))"
            )
            plan_text = " ".join(str(c) for row in plan for c in row)
            # the VALUES list shows as "SCAN CONSTANT ROW" — what matters
            # is that the TABLE is searched by index, never scanned
            # (older sqlite prints "SEARCH TABLE tests", >=3.36 drops
            # the TABLE keyword — accept both)
            from corrosion_tpu.agent.pubsub import plan_mentions
            assert plan_mentions(plan_text, "SEARCH", "tests"), plan_text
            assert not plan_mentions(plan_text, "SCAN", "tests"), plan_text

            # count sqlite VM progress ticks during the live delta
            ticks = [0]
            def _tick():
                ticks[0] += 1
                return 0
            a.storage._ro_conn.set_progress_handler(_tick, 1000)
            try:
                before = sub.last_change_id
                a.execute_transaction([
                    ["INSERT INTO tests (id, text) VALUES (?, ?)",
                     [200_000 + i, f"new{i}"]]
                    for i in range(10)
                ])
                await wait_for(lambda: sub.last_change_id >= before + 5)
            finally:
                a.storage._ro_conn.set_progress_handler(None, 0)
            # full re-evaluation walks 100k+ rows -> hundreds of ticks at
            # 1000 insns/tick; the pk-scoped delta touches ~10 rows
            fallbacks = a.metrics.get_counter(
                "corro_subs_delta_fallbacks_total"
            )
            assert ticks[0] < 50, (
                f"delta cost blew up: {ticks[0]} ticks "
                f"(delta fallbacks: {fallbacks})"
            )
        finally:
            await a.stop()

    run(main())


def test_join_subscription_incremental_delta(run):
    """A two-table inner-join subscription processes a 1-row change
    with O(1) statements — one pk-scoped delta SELECT, no full
    re-evaluation (the reference's per-table temp-pk scoping,
    pubsub.rs:602-737,1432-1707) — and emits correct events for
    inserts, join-key updates, and deletes on either side."""
    async def main():
        a = await launch_test_agent()
        try:
            a.execute_transaction([
                ["INSERT INTO tests (id, text) VALUES (1, 'l1')"],
                ["INSERT INTO tests (id, text) VALUES (2, 'l2')"],
                ["INSERT INTO tests2 (id, text) VALUES (1, 'r1')"],
            ])
            sub = a.subs.subscribe(
                "SELECT tests.text, tests2.text FROM tests"
                " JOIN tests2 ON tests.id = tests2.id"
            )
            assert sub.incremental
            assert sorted(c for _, c in sub.rows.values()) == [
                ["l1", "r1"]
            ]
            # the seed writes' change notifications land on the event
            # loop after subscribe — drain them so the counter below
            # sees only the probe write's round
            await asyncio.sleep(0.1)
            await wait_for(a.subs.idle, timeout=15)

            # count SELECT statements the delta path issues for one
            # 1-row change: exactly one scoped evaluation
            statements = []
            orig = a.storage.read_query

            def counting(sql, params=()):
                statements.append(sql)
                return orig(sql, params)

            a.storage.read_query = counting
            try:
                before = sub.last_change_id
                a.execute_transaction([
                    ["INSERT INTO tests2 (id, text) VALUES (2, 'r2')"]
                ])
                await wait_for(
                    lambda: sub.last_change_id > before, timeout=15
                )
                await wait_for(a.subs.idle, timeout=15)
            finally:
                a.storage.read_query = orig
            deltas = [s for s in statements if "__corro_pk_" in s]
            fulls = [
                s for s in statements
                if s.strip().upper().startswith("SELECT")
                and "__corro_pk_" not in s
                and "EXPLAIN" not in s.upper()
            ]
            assert len(deltas) == 1, statements
            assert not fulls, statements
            assert a.metrics.get_counter(
                "corro_subs_delta_fallbacks_total") in (0, None)
            assert sorted(c for _, c in sub.rows.values()) == [
                ["l1", "r1"], ["l2", "r2"]
            ]

            # update through the LEFT side
            before = sub.last_change_id
            a.execute_transaction([
                ["UPDATE tests SET text = 'l1b' WHERE id = 1"]
            ])
            await wait_for(
                lambda: ["l1b", "r1"] in [
                    c for _, c in list(sub.rows.values())
                ],
                timeout=15,
            )
            # delete through the RIGHT side removes the join row
            a.execute_transaction([["DELETE FROM tests2 WHERE id = 1"]])
            await wait_for(
                lambda: sorted(
                    c for _, c in list(sub.rows.values())
                ) == [["l2", "r2"]],
                timeout=15,
            )
        finally:
            await a.stop()

    run(main())


def test_self_join_subscription_incremental(run):
    """A self-join on indexed columns qualifies; a 1-row change
    re-evaluates each aliased occurrence with ONE scoped statement per
    occurrence — never a full re-query (occurrence-tagged aliases,
    pubsub.rs:602-737)."""
    async def main():
        a = await launch_test_agent()
        try:
            a.execute_transaction([
                ["INSERT INTO tests (id, text) VALUES (1, 'a')"],
                ["INSERT INTO tests (id, text) VALUES (2, 'b')"],
            ])
            sub = a.subs.subscribe(
                "SELECT l.id, r.text FROM tests l JOIN tests r"
                " ON l.id = r.id"
            )
            assert sub.incremental and not sub.full_refresh_aliases
            assert sorted(c for _, c in sub.rows.values()) == [
                [1, "a"], [2, "b"]
            ]
            await asyncio.sleep(0.1)
            await wait_for(a.subs.idle, timeout=15)

            statements = []
            orig = a.storage.read_query

            def counting(sql, params=()):
                statements.append(sql)
                return orig(sql, params)

            a.storage.read_query = counting
            try:
                before = sub.last_change_id
                a.execute_transaction([
                    ["INSERT INTO tests (id, text) VALUES (3, 'c')"]
                ])
                await wait_for(
                    lambda: sub.last_change_id > before, timeout=15
                )
                await wait_for(a.subs.idle, timeout=15)
            finally:
                a.storage.read_query = orig
            deltas = [s for s in statements if "__corro_pk_" in s]
            fulls = [
                s for s in statements
                if s.strip().upper().startswith("SELECT")
                and "__corro_pk_" not in s
                and "EXPLAIN" not in s.upper()
            ]
            # one scoped delta per occurrence (aliases l and r)
            assert len(deltas) == 2, statements
            assert not fulls, statements
            assert sorted(c for _, c in sub.rows.values()) == [
                [1, "a"], [2, "b"], [3, "c"]
            ]
            # delete removes the row through both occurrences
            a.execute_transaction([["DELETE FROM tests WHERE id = 2"]])
            await wait_for(
                lambda: sorted(
                    c for _, c in list(sub.rows.values())
                ) == [[1, "a"], [3, "c"]],
                timeout=15,
            )
        finally:
            await a.stop()

    run(main())


def test_left_join_subscription_incremental(run):
    """LEFT JOIN: a 1-row change on the NULLABLE side runs one anchor
    harvest + one anchor-scoped delta (never a full re-query), and
    NULL-extension transitions are emitted in both directions."""
    async def main():
        a = await launch_test_agent()
        try:
            a.execute_transaction([
                ["INSERT INTO tests (id, text) VALUES (1, 'a')"],
                ["INSERT INTO tests (id, text) VALUES (2, 'b')"],
                ["INSERT INTO tests2 (id, text) VALUES (1, 'x')"],
            ])
            sub = a.subs.subscribe(
                "SELECT tests.id, tests2.text FROM tests"
                " LEFT JOIN tests2 ON tests.id = tests2.id"
            )
            assert sub.incremental and not sub.full_refresh_aliases
            assert sorted(c for _, c in sub.rows.values()) == [
                [1, "x"], [2, None]
            ]
            await asyncio.sleep(0.1)
            await wait_for(a.subs.idle, timeout=15)

            statements = []
            orig = a.storage.read_query

            def counting(sql, params=()):
                statements.append(sql)
                return orig(sql, params)

            a.storage.read_query = counting
            try:
                before = sub.last_change_id
                # inner-side insert: row 2 transitions NULL -> matched
                a.execute_transaction([
                    ["INSERT INTO tests2 (id, text) VALUES (2, 'y')"]
                ])
                await wait_for(
                    lambda: sub.last_change_id > before, timeout=15
                )
                await wait_for(a.subs.idle, timeout=15)
            finally:
                a.storage.read_query = orig
            scoped = [s for s in statements if "__corro_pk_" in s]
            harvests = [
                s for s in statements
                if s.strip().upper().startswith("SELECT")
                and "__corro_pk_" not in s
                and "EXPLAIN" not in s.upper()
            ]
            # one harvest (affected anchors) + one anchor-scoped delta
            assert len(harvests) == 1, statements
            assert len(scoped) == 1, statements
            assert sorted(c for _, c in sub.rows.values()) == [
                [1, "x"], [2, "y"]
            ]
            # inner-side delete: matched -> NULL-extended again
            a.execute_transaction([["DELETE FROM tests2 WHERE id = 1"]])
            await wait_for(
                lambda: sorted(
                    c for _, c in list(sub.rows.values())
                ) == [[1, None], [2, "y"]],
                timeout=15,
            )
        finally:
            await a.stop()

    run(main())


def test_left_join_subscription_restore_after_restart(run):
    """LEFT-JOIN sub state (incl. NULL-extension identities) survives a
    restart, and a transition applied while down is caught up."""
    import tempfile

    d = tempfile.mkdtemp(prefix="corro-ljsub-")

    async def main():
        a = await launch_test_agent(tmpdir=d)
        try:
            a.execute_transaction([
                ["INSERT INTO tests (id, text) VALUES (1, 'a')"],
            ])
            h = a.subs.subscribe(
                "SELECT tests.id, tests2.text FROM tests"
                " LEFT JOIN tests2 ON tests.id = tests2.id"
            )
            assert h.incremental
            assert sorted(c for _, c in h.rows.values()) == [[1, None]]
        finally:
            await a.stop()

        a2 = await launch_test_agent(tmpdir=d)
        try:
            subs = a2.subs.list()
            h2 = a2.subs.get(subs[0]["id"])
            assert h2.incremental
            # the boot refresh catches up; the NULL-extension identity
            # restored from disk still transitions correctly
            before = h2.last_change_id
            a2.execute_transaction([
                ["INSERT INTO tests2 (id, text) VALUES (1, 'z')"]
            ])
            await wait_for(
                lambda: sorted(
                    c for _, c in list(h2.rows.values())) == [[1, "z"]],
                timeout=15,
            )
            assert h2.last_change_id > before
        finally:
            await a2.stop()

    run(main())


AGG_SCHEMA = """
CREATE TABLE emps (
  id INTEGER NOT NULL PRIMARY KEY,
  dept TEXT,
  salary INTEGER NOT NULL DEFAULT 0
);
CREATE INDEX emps_dept ON emps (dept);
"""


def test_aggregate_subscription_incremental(run):
    """Single-table GROUP BY: a 1-row change probes the changed pks'
    groups and re-aggregates ONLY those groups (one probe + one scoped
    re-agg, never a full re-query); count changes arrive as in-place
    updates of the group row; group moves retract/extend both groups;
    the NULL group works (IS-scoping, not IN)."""
    async def main():
        a = await launch_test_agent(schema=AGG_SCHEMA)
        try:
            a.execute_transaction([
                ["INSERT INTO emps (id, dept, salary) VALUES (1, 'eng', 10)"],
                ["INSERT INTO emps (id, dept, salary) VALUES (2, 'eng', 20)"],
                ["INSERT INTO emps (id, dept, salary) VALUES (3, 'ops', 5)"],
            ])
            sub = a.subs.subscribe(
                "SELECT dept, count(*), sum(salary) FROM emps"
                " GROUP BY dept"
            )
            assert sub.incremental and sub.agg
            assert sorted(c for _, c in sub.rows.values()) == [
                ["eng", 2, 30], ["ops", 1, 5]
            ]
            await asyncio.sleep(0.1)
            await wait_for(a.subs.idle, timeout=15)

            gen = sub.stream()
            while "eoq" not in next(gen):
                pass
            statements = []
            orig = a.storage.read_query

            def counting(sql, params=()):
                statements.append(sql)
                return orig(sql, params)

            a.storage.read_query = counting
            try:
                before = sub.last_change_id
                a.execute_transaction([
                    ["INSERT INTO emps (id, dept, salary)"
                     " VALUES (4, 'eng', 30)"]
                ])
                await wait_for(
                    lambda: sub.last_change_id > before, timeout=15
                )
                await wait_for(a.subs.idle, timeout=15)
            finally:
                a.storage.read_query = orig
            sels = [
                s for s in statements
                if s.strip().upper().startswith("SELECT")
                and "EXPLAIN" not in s.upper()
            ]
            probes = [s for s in sels if "VALUES" in s]
            scoped = [s for s in sels if "__corro_grp_" in s]
            fulls = [s for s in sels if s not in probes and s not in scoped]
            assert len(probes) == 1 and len(scoped) == 1, statements
            assert not fulls, statements
            # the count change is an in-place UPDATE of the group row
            ev = await asyncio.to_thread(next, gen)
            assert ev["change"][0] == "update"
            assert ev["change"][2] == ["eng", 3, 60]

            # group move: ops loses its only row -> delete; eng grows
            a.execute_transaction([
                ["UPDATE emps SET dept = 'eng' WHERE id = 3"]
            ])
            await wait_for(
                lambda: sorted(c for _, c in list(sub.rows.values()))
                == [["eng", 4, 65]],
                timeout=15,
            )
            # NULL group: IS-scoping finds it where IN could not
            a.execute_transaction([
                ["UPDATE emps SET dept = NULL WHERE id = 4"]
            ])
            await wait_for(
                lambda: sorted(
                    (c for _, c in list(sub.rows.values())),
                    key=str,
                ) == sorted([[None, 1, 30], ["eng", 3, 35]], key=str),
                timeout=15,
            )
        finally:
            await a.stop()

    run(main())


def test_aggregate_subscription_or_where_precedence(run):
    """A top-level OR in the user WHERE is parenthesized before the
    group-scope AND is appended — a change touching only an unrelated
    group must not leak other groups into the scoped re-aggregation
    (which would emit spurious inserts / partial aggregates)."""
    async def main():
        a = await launch_test_agent(schema=AGG_SCHEMA)
        try:
            a.execute_transaction([
                ["INSERT INTO emps (id, dept) VALUES (1, 'eng')"],
                ["INSERT INTO emps (id, dept) VALUES (2, 'ops')"],
                ["INSERT INTO emps (id, dept) VALUES (3, 'misc')"],
            ])
            h = a.subs.subscribe(
                "SELECT dept, count(*) FROM emps"
                " WHERE dept = 'eng' OR dept = 'ops' GROUP BY dept"
            )
            assert h.agg
            assert sorted(c for _, c in h.rows.values()) == [
                ["eng", 1], ["ops", 1]
            ]
            before = h.last_change_id
            a.execute_transaction([
                ["INSERT INTO emps (id, dept) VALUES (4, 'misc')"]
            ])
            await asyncio.sleep(0.3)
            await wait_for(a.subs.idle, timeout=15)
            assert h.last_change_id == before
            assert sorted(c for _, c in h.rows.values()) == [
                ["eng", 1], ["ops", 1]
            ]
            a.execute_transaction([
                ["INSERT INTO emps (id, dept) VALUES (5, 'ops')"]
            ])
            await wait_for(
                lambda: sorted(c for _, c in list(h.rows.values()))
                == [["eng", 1], ["ops", 2]],
                timeout=15,
            )
        finally:
            await a.stop()

    run(main())


def test_aggregate_subscription_having_and_restore(run):
    """HAVING rides inside the scoped re-aggregation (a group failing
    it disappears); aggregate sub state survives restart and catches
    up changes applied while down."""
    import tempfile

    d = tempfile.mkdtemp(prefix="corro-aggsub-")

    async def main():
        a = await launch_test_agent(tmpdir=d, schema=AGG_SCHEMA)
        try:
            h = a.subs.subscribe(
                "SELECT dept, count(*) FROM emps GROUP BY dept"
                " HAVING count(*) > 1"
            )
            assert h.incremental and h.agg
            a.execute_transaction([
                ["INSERT INTO emps (id, dept) VALUES (1, 'x')"],
                ["INSERT INTO emps (id, dept) VALUES (2, 'x')"],
                ["INSERT INTO emps (id, dept) VALUES (3, 'y')"],
            ])
            await wait_for(
                lambda: sorted(c for _, c in list(h.rows.values()))
                == [["x", 2]],
                timeout=15,
            )
            # dropping below the HAVING floor deletes the group row
            a.execute_transaction([["DELETE FROM emps WHERE id = 2"]])
            await wait_for(lambda: len(h.rows) == 0, timeout=15)
            a.execute_transaction([
                ["INSERT INTO emps (id, dept) VALUES (4, 'y')"]
            ])
            await wait_for(
                lambda: sorted(c for _, c in list(h.rows.values()))
                == [["y", 2]],
                timeout=15,
            )
        finally:
            await a.stop()

        a2 = await launch_test_agent(tmpdir=d, schema=AGG_SCHEMA)
        try:
            subs = a2.subs.list()
            h2 = a2.subs.get(subs[0]["id"])
            assert h2.incremental and h2.agg
            assert sorted(c for _, c in h2.rows.values()) == [["y", 2]]
            # deltas keep working post-restore (pk_groups map rebuilt)
            a2.execute_transaction([
                ["INSERT INTO emps (id, dept) VALUES (5, 'y')"]
            ])
            await wait_for(
                lambda: sorted(c for _, c in list(h2.rows.values()))
                == [["y", 3]],
                timeout=15,
            )
        finally:
            await a2.stop()

    run(main())


def test_aggregate_eligibility():
    """Which aggregate shapes qualify: indexed single-table GROUP BY
    yes; unindexed group column, global aggregates (no GROUP BY),
    DISTINCT and LIMIT no — they stay on the correct full-refresh
    path."""
    async def main():
        a = await launch_test_agent(schema=AGG_SCHEMA)
        try:
            def sub(sql):
                return a.subs.subscribe(sql)

            assert sub(
                "SELECT dept, count(*) FROM emps GROUP BY dept"
            ).agg
            # salary has no index -> scoped re-agg would scan
            assert not sub(
                "SELECT salary, count(*) FROM emps GROUP BY salary"
            ).incremental
            # COUNT(*)-only (no GROUP BY): maintained incrementally by
            # per-pk membership transitions since the sharded-matcher
            # round — the one global group never re-aggregates
            c = sub("SELECT count(*) FROM emps")
            assert c.incremental and c.count_only
            # any WHERE rides along: the membership probe is scoped to
            # the changed pks (always pk-indexed), the predicate only
            # re-evaluates on those rows
            cw = sub("SELECT count(*) FROM emps WHERE salary > 5")
            assert cw.incremental and cw.count_only
            # COUNT with GROUP BY is the aggregate path, not count-only
            assert not sub(
                "SELECT dept, count(*) FROM emps GROUP BY dept"
            ).count_only
            assert not sub(
                "SELECT DISTINCT dept, count(*) FROM emps GROUP BY dept"
            ).incremental
            assert not sub(
                "SELECT dept, count(*) FROM emps GROUP BY dept LIMIT 5"
            ).incremental
        finally:
            await a.stop()

    asyncio.run(main())


def test_join_subscription_restore_after_restart(run):
    """Join-sub state (multi-table pk index) survives restart; a change
    applied while down is caught up by the boot refresh."""
    import tempfile

    d = tempfile.mkdtemp(prefix="corro-joinsub-")

    async def main():
        a = await launch_test_agent(tmpdir=d)
        try:
            a.execute_transaction([
                ["INSERT INTO tests (id, text) VALUES (1, 'x')"],
                ["INSERT INTO tests2 (id, text) VALUES (1, 'y')"],
            ])
            h = a.subs.subscribe(
                "SELECT tests.id, tests2.text FROM tests"
                " JOIN tests2 ON tests.id = tests2.id"
            )
            assert h.incremental and len(h.rows) == 1
        finally:
            await a.stop()

        a2 = await launch_test_agent(tmpdir=d)
        try:
            subs = a2.subs.list()
            assert len(subs) == 1
            h2 = a2.subs.get(subs[0]["id"])
            assert h2.incremental and len(h2.rows) == 1
            # multi-table pk index rebuilt from the persisted rows
            assert len(h2.by_pk) == 2
            # deltas keep working post-restore
            before = h2.last_change_id
            a2.execute_transaction([
                ["INSERT INTO tests (id, text) VALUES (2, 'p')"],
                ["INSERT INTO tests2 (id, text) VALUES (2, 'q')"],
            ])
            await wait_for(
                lambda: h2.last_change_id > before and len(h2.rows) == 2,
                timeout=15,
            )
        finally:
            await a2.stop()

    run(main())


def test_incremental_eligibility(run):
    """Pin which queries qualify for pk-scoped delta evaluation and
    which fall back to the (correct) full re-evaluation path."""
    async def main():
        a = await launch_test_agent()
        try:
            # a plain local (non-replicated) lookup table for join cases
            a.storage.conn.execute(
                "CREATE TABLE lookup (k INTEGER PRIMARY KEY, v TEXT)"
            )
            a.storage.conn.execute(
                "INSERT INTO lookup VALUES (1, 'x'), (2, 'y')"
            )

            def sub(sql):
                return a.subs.subscribe(sql)

            assert sub("SELECT id, text FROM tests").incremental
            assert sub(
                "SELECT id, text FROM tests WHERE id % 2 = 0"
            ).incremental
            # pk not projected by the USER: the hidden __corro_pk_*
            # splice provides the identity now — eligible
            assert sub("SELECT text FROM tests").incremental
            # GROUP BY on an indexed column: scoped re-aggregation
            # qualifies since round 5 (test_aggregate_* pin behavior)
            assert sub(
                "SELECT id, count(*) FROM tests GROUP BY id"
            ).agg
            # subquery -> two SELECTs
            assert not sub(
                "SELECT id, text FROM tests "
                "WHERE id IN (SELECT id FROM tests2)"
            ).incremental
            # inner join of two replicated tables: eligible — each
            # changed table scopes its own delta (pubsub.rs:602-737)
            j = sub(
                "SELECT tests.id, tests2.text FROM tests "
                "JOIN tests2 ON tests.id = tests2.id"
            )
            assert j.incremental
            assert {t for t, _a, _n in j.pk_items} == {"tests", "tests2"}
            # LEFT JOIN on an indexed column: eligible since round 5 —
            # inner-side changes re-scope through the anchor
            lj = sub(
                "SELECT tests.id, tests2.text FROM tests "
                "LEFT JOIN tests2 ON tests.id = tests2.id"
            )
            assert lj.incremental
            assert [n for _t, _a, n in lj.pk_items] == [False, True]
            # RIGHT/FULL: the anchor property breaks — not eligible
            # (sqlite < 3.39 cannot even prepare a RIGHT JOIN, so the
            # subscribe fails outright there — also not incremental)
            if sqlite3.sqlite_version_info >= (3, 39):
                assert not sub(
                    "SELECT tests.id FROM tests "
                    "RIGHT JOIN tests2 ON tests.id = tests2.id"
                ).incremental
            else:
                with pytest.raises(sqlite3.OperationalError):
                    sub(
                        "SELECT tests.id FROM tests "
                        "RIGHT JOIN tests2 ON tests.id = tests2.id"
                    )
            # self-join: eligible since round 5 — each aliased
            # occurrence scopes its own delta
            sj = sub(
                "SELECT a.id FROM tests a JOIN tests b ON a.id = b.id"
            )
            assert sj.incremental
            assert sorted(sj.pk_idx) == ["a", "b"]
            # join on an UNINDEXED column: the sibling table's side of
            # the delta plan is a SCAN, so each changed row would cost
            # O(sibling) — must fall back to full refresh
            assert not sub(
                "SELECT tests.id FROM tests "
                "JOIN tests2 ON tests.text = tests2.text"
            ).incremental
            # comma join against a NON-replicated local table: several
            # result rows per pk in unguaranteed order — must not
            # qualify even though only one *replicated* table is read
            assert not sub(
                "SELECT id, v FROM tests, lookup"
            ).incremental
            # the ineligible comma join must still be CORRECT via the
            # fallback path
            h = sub("SELECT id, v FROM tests, lookup")
            a.execute_transaction(
                [["INSERT INTO tests (id, text) VALUES (50, 'a')"]]
            )
            await wait_for(lambda: len(h.rows) >= 2)
            assert sorted(c for _, c in h.rows.values()) == [
                [50, "x"], [50, "y"]
            ]
        finally:
            await a.stop()

    run(main())


def test_idle_subscription_gc(run, monkeypatch):
    """A subscription with no attached receivers is garbage-collected
    after SUB_GC_S and its state file removed; re-subscribing recreates
    it from a fresh snapshot (reference 120s zero-receiver GC)."""
    import os

    from corrosion_tpu.agent.pubsub import SubsManager

    monkeypatch.setattr(SubsManager, "SUB_GC_S", 0.1)

    async def main():
        a = await launch_test_agent()
        try:
            h = a.subs.subscribe("SELECT id FROM tests")
            path = h.db_path
            assert os.path.exists(path)
            # the GC sweep runs on the worker's 5s deadline.  NB: poll
            # the state file, not subs.get() — get() counts as receiver
            # activity and would keep the sub alive
            await wait_for(
                lambda: not os.path.exists(path), timeout=15
            )
            assert h.id not in a.subs._subs
            # an attached stream keeps a new sub alive past the horizon
            h2 = a.subs.subscribe("SELECT id FROM tests")
            gen = h2.stream()
            next(gen)  # attach (columns event)
            await asyncio.sleep(0.3)
            assert a.subs.get(h2.id) is not None
        finally:
            await a.stop()

    run(main())


def test_from_items_parser_envelope():
    """Pin the from-clause parser's reach (r4 weak #7: the envelope
    was untested): quoted identifiers and literals containing
    keywords must parse; genuinely out-of-scope shapes return None
    (costing only the optimization, never correctness)."""
    from corrosion_tpu.agent.pubsub import from_items, from_items_ex

    # plain / aliased / comma / inner / left variants
    assert from_items("SELECT * FROM t") == [("t", "t", False)]
    assert from_items("SELECT * FROM t AS a JOIN u b ON a.x = b.x") == [
        ("t", "a", False), ("u", "b", False)
    ]
    assert from_items("SELECT * FROM t, u") == [
        ("t", "t", False), ("u", "u", False)
    ]
    assert from_items(
        "SELECT * FROM t LEFT OUTER JOIN u ON t.x = u.x"
    ) == [("t", "t", False), ("u", "u", True)]
    # quoted identifiers parse (quotes stripped into the item name)
    assert from_items('SELECT * FROM "t" JOIN "u" ON "t".x = "u".x') == [
        ("t", "t", False), ("u", "u", False)
    ]
    # a string literal containing keywords must not derail the scan
    items = from_items(
        "SELECT * FROM t JOIN u ON u.tag = 'LEFT JOIN v ON' "
        "WHERE t.id = u.id"
    )
    assert items == [("t", "t", False), ("u", "u", False)]
    # connector spans point at the real connectors
    items, spans = from_items_ex(
        "SELECT * FROM t LEFT JOIN u ON t.x = u.x"
    )
    assert spans[0] is None
    s, e = spans[1]
    assert "LEFT JOIN" in "SELECT * FROM t LEFT JOIN u ON t.x = u.x"[s:e]
    # out-of-scope shapes: None, not garbage
    for sql in (
        "SELECT * FROM (SELECT 1)",
        "SELECT * FROM t NATURAL JOIN u",
        "SELECT * FROM t RIGHT JOIN u ON t.x = u.x",
        "SELECT 1",
    ):
        assert from_items(sql) is None, sql


def test_refresh_failure_counted_not_swallowed(run):
    """A full-refresh failure in the drain round is counted in
    corro_subs_refresh_failures_total (it used to vanish into a bare
    `except sqlite3.Error: pass`), and the worker survives it."""
    async def main():
        a = await launch_test_agent()
        try:
            a.execute_transaction(
                [["INSERT INTO tests (id, text) VALUES (1, 'one')"]]
            )
            handle = a.subs.subscribe("SELECT id, text FROM tests")
            await wait_for(a.subs.idle, timeout=10)
            import sqlite3 as _sqlite3

            orig = handle.refresh
            fails = {"n": 0}

            def boom():
                fails["n"] += 1
                raise _sqlite3.OperationalError("injected refresh failure")

            handle.refresh = boom
            try:
                a.subs._drain_round({handle.id}, {})
            finally:
                handle.refresh = orig
            assert fails["n"] == 1
            assert a.metrics.get_counter(
                "corro_subs_refresh_failures_total") == 1
            # the matcher still works after the failed round
            a.execute_transaction(
                [["INSERT INTO tests (id, text) VALUES (2, 'two')"]]
            )
            await wait_for(
                lambda: any(
                    c[0] == 2 for _, c in list(handle.rows.values())
                ),
                timeout=10,
            )
        finally:
            await a.stop()

    run(main())


# -- sharded matcher satellites (bounded buffers, narrowed refresh, ----
# -- widened shapes) ---------------------------------------------------


def test_fanout_bounded_drop_oldest(run):
    """A slow stream consumer loses its OLDEST buffered events (it must
    resubscribe from a snapshot once it notices the change-id gap), the
    intake path never blocks, and every drop is counted per sub."""
    import queue as queue_mod

    async def main():
        a = await launch_test_agent()
        try:
            h = a.subs.subscribe("SELECT id, text FROM tests")
            q = queue_mod.Queue(maxsize=2)
            with h._lock:
                h._streams.append(q)
            e1 = {"change": ["insert", 1, [1, "a"], 1]}
            e2 = {"change": ["insert", 2, [2, "b"], 2]}
            e3 = {"change": ["insert", 3, [3, "c"], 3]}
            h._fanout(e1)
            h._fanout(e2)
            h._fanout(e3)  # full -> e1 evicted, e3 admitted
            assert [q.get_nowait(), q.get_nowait()] == [e2, e3]
            assert a.metrics.get_counter(
                "corro_subs_events_dropped_total", sub_id=h.id
            ) == 1
        finally:
            await a.stop()

    run(main())


def test_table_updates_bounded_drop_oldest(run):
    """Same backpressure contract for the table-update notify streams:
    drop-oldest, counted per table, intake never stalls."""
    async def main():
        a = await launch_test_agent()
        try:
            stream = a.subs.table_updates("tests")
            q = stream._q
            # fill the bounded queue to the brim without consuming
            while True:
                try:
                    q.put_nowait({"change": ["upsert", [0]]})
                except Exception:
                    break
            depth = q.qsize()
            a.execute_transaction(
                [["INSERT INTO tests (id, text) VALUES (9, 'new')"]]
            )
            await wait_for(
                lambda: a.metrics.get_counter(
                    "corro_subs_updates_dropped_total", table="tests"
                ) >= 1
            )
            assert q.qsize() == depth  # bounded: evict-one, admit-one
            # the NEWEST event survived; an oldest filler was dropped
            events = []
            while q.qsize():
                events.append(q.get_nowait())
            assert events[-1] == {"change": ["upsert", [9]]}
            stream.close()
        finally:
            await a.stop()

    run(main())


NARROW_SCHEMA = """
CREATE TABLE lt (
  id INTEGER NOT NULL PRIMARY KEY,
  k INTEGER,
  v TEXT
);
CREATE TABLE rt (
  id INTEGER NOT NULL PRIMARY KEY,
  k INTEGER,
  w TEXT
);
CREATE INDEX rt_k ON rt (k);
"""


def test_degraded_alias_narrowed_refresh(run):
    """A degraded (unindexable) alias routes ONLY ITSELF through full
    refresh: sibling aliases keep their scoped deltas, so a change wave
    touching only the healthy anchor costs zero refreshes.  Parity with
    the old route-everything-through-refresh behavior: the final state
    is identical (a post-hoc refresh adds no events)."""
    async def main():
        a = await launch_test_agent(schema=NARROW_SCHEMA)
        try:
            h = a.subs.subscribe(
                "SELECT lt.id, rt.w FROM lt LEFT JOIN rt ON lt.k = rt.k"
            )
            # rt's harvest cannot reach an index on lt.k -> degraded;
            # the anchor stays cleanly pk-scoped
            assert h.incremental
            assert h.full_refresh_aliases == {"rt"}
            await wait_for(a.subs.idle)
            base = a.metrics.get_counter_sum("corro_subs_refresh_total")

            # anchor-only wave: scoped delta, NO full refresh
            a.execute_transaction(
                [["INSERT INTO lt (id, k, v) VALUES (1, 10, 'x')"]]
            )
            await wait_for(
                lambda: a.subs.idle() and len(h.rows) == 1
            )
            assert a.metrics.get_counter_sum(
                "corro_subs_refresh_total"
            ) == base
            assert sorted(c for _, c in h.rows.values()) == [[1, None]]

            # degraded-alias wave: one full refresh for the round
            a.execute_transaction(
                [["INSERT INTO rt (id, k, w) VALUES (1, 10, 'yes')"]]
            )
            await wait_for(
                lambda: a.subs.idle()
                and sorted(c for _, c in h.rows.values()) == [[1, "yes"]]
            )
            assert a.metrics.get_counter_sum(
                "corro_subs_refresh_total"
            ) == base + 1

            # mixed wave: the healthy alias's delta AND one refresh
            a.execute_transaction([
                ["INSERT INTO lt (id, k, v) VALUES (2, 20, 'y')"],
                ["INSERT INTO rt (id, k, w) VALUES (2, 20, 'z')"],
            ])
            await wait_for(
                lambda: a.subs.idle() and len(h.rows) == 2
            )
            _, truth = a.storage.read_query(h.sql)
            assert sorted(c for _, c in h.rows.values()) == sorted(
                [list(r) for r in truth]
            )
            # old-behavior parity: re-running the full refresh the old
            # code would have issued emits NOTHING new
            before = h.last_change_id
            h.refresh()
            assert h.last_change_id == before
        finally:
            await a.stop()

    run(main())


def test_bounded_order_limit_subscription(run):
    """ORDER BY + LIMIT over an indexed ordering: bounded re-evaluation
    (a delta-round-counted whole-query re-run capped at O(limit)), with
    top-N eviction and refill semantics."""
    async def main():
        a = await launch_test_agent()
        try:
            h = a.subs.subscribe(
                "SELECT id, text FROM tests ORDER BY id LIMIT 3"
            )
            assert h.incremental and h.bounded
            for i in (5, 6, 7, 8):
                a.execute_transaction([[
                    f"INSERT INTO tests (id, text) VALUES ({i}, 't{i}')"
                ]])
            await wait_for(
                lambda: a.subs.idle()
                and sorted(c[0] for _, c in h.rows.values()) == [5, 6, 7]
            )
            base = a.metrics.get_counter_sum("corro_subs_refresh_total")
            # a smaller id evicts the current tail
            a.execute_transaction(
                [["INSERT INTO tests (id, text) VALUES (1, 'head')"]]
            )
            await wait_for(
                lambda: a.subs.idle()
                and sorted(c[0] for _, c in h.rows.values()) == [1, 5, 6]
            )
            # a deletion refills from below the cut
            a.execute_transaction([["DELETE FROM tests WHERE id = 5"]])
            await wait_for(
                lambda: a.subs.idle()
                and sorted(c[0] for _, c in h.rows.values()) == [1, 6, 7]
            )
            # every wave was a bounded re-run, never a refresh
            assert a.metrics.get_counter_sum(
                "corro_subs_refresh_total"
            ) == base
            assert a.metrics.get_counter_sum(
                "corro_subs_bounded_refresh_total"
            ) >= 2
            # un-indexed ordering cannot bound the re-run: full refresh
            # (checked last — a full-refresh sub on the same table
            # would inflate the counters the asserts above pin)
            nb = a.subs.subscribe(
                "SELECT id, text FROM tests ORDER BY text LIMIT 3"
            )
            assert not nb.incremental and not nb.bounded
        finally:
            await a.stop()

    run(main())


MULTI_PK_SCHEMA = """
CREATE TABLE mc (
  a INTEGER NOT NULL,
  b TEXT NOT NULL,
  val TEXT,
  PRIMARY KEY (a, b)
);
"""


def test_multi_column_pk_in_list_columnar(run):
    """A multi-column pk IN-list predicate (any column order in the
    tuple) qualifies for the columnar matcher; rows outside the filter
    never reach the subscription."""
    async def main():
        a = await launch_test_agent(schema=MULTI_PK_SCHEMA)
        try:
            h = a.subs.subscribe(
                "SELECT val FROM mc WHERE (b, a) IN "
                "(VALUES ('x', 1), ('y', 2))"
            )
            assert h.incremental
            assert h.columnar_spec is not None
            assert len(h.columnar_spec.pk_filter) == 2
            gen = h.stream()
            while "eoq" not in next(gen):
                pass
            a.execute_transaction([
                ["INSERT INTO mc (a, b, val) VALUES (1, 'x', 'hit')"],
                ["INSERT INTO mc (a, b, val) VALUES (3, 'z', 'miss')"],
            ])
            ev = await asyncio.to_thread(next, gen)
            assert ev["change"][0] == "insert"
            assert ev["change"][2] == ["hit"]
            await wait_for(a.subs.idle)
            assert sorted(c for _, c in h.rows.values()) == [["hit"]]
            # affinity guard: quoted ints against an INTEGER pk column
            # cannot be packed-byte matched -> oracle path, not columnar
            mixed = a.subs.subscribe(
                "SELECT val FROM mc WHERE (b, a) IN (VALUES ('x', '1'))"
            )
            assert mixed.columnar_spec is None
        finally:
            await a.stop()

    run(main())
