"""CRDT storage engine tests: trigger bookkeeping, change collection,
merge application, and multi-replica convergence."""

import random

import pytest

from corrosion_tpu.agent.storage import CrConn
from corrosion_tpu.agent.pack import pack_values, unpack_values, value_cmp
from corrosion_tpu.types.change import SENTINEL_CID

SCHEMA = (
    "CREATE TABLE IF NOT EXISTS machines ("
    " id INTEGER PRIMARY KEY NOT NULL,"
    " name TEXT NOT NULL DEFAULT '',"
    " status TEXT NOT NULL DEFAULT 'broken')"
)


@pytest.fixture
def db(tmp_path):
    def mk(name):
        conn = CrConn(str(tmp_path / f"{name}.db"))
        conn.conn.execute(SCHEMA)
        conn.as_crr("machines")
        return conn

    return mk


def test_pack_roundtrip_and_order():
    vals = [None, -5, 3.5, "abc", b"\x00\xff", True]
    assert unpack_values(pack_values(vals)) == [None, -5, 3.5, "abc", b"\x00\xff", 1]
    # cr-sqlite tie-break order (pinned by tests/test_crsqlite_golden.py):
    # NULL < BLOB < TEXT < REAL < INTEGER; numeric/bytes within one type
    assert value_cmp(None, 0) < 0
    assert value_cmp(2, "a") > 0
    assert value_cmp("z", b"\x00") > 0
    assert value_cmp("b", "a") > 0
    assert value_cmp(2, 2.5) > 0
    assert value_cmp(2, 3) < 0
    assert value_cmp(2.5, 3.5) < 0


def test_local_write_creates_clock_rows(db):
    a = db("a")
    a.execute("INSERT INTO machines (id, name, status) VALUES (1, 'meow', 'created')")
    assert a.db_version() == 1
    changes = a.changes_for_version(1)
    cids = sorted(ch.cid for ch in changes)
    assert cids == ["name", "status"]
    assert all(int(ch.db_version) == 1 and ch.cl == 1 for ch in changes)
    seqs = sorted(int(ch.seq) for ch in changes)
    # fresh inserts number cells from 0 (cr-sqlite alignment: the row's
    # causal-length entry consumes no seq slot unless it ships as a
    # sentinel — see tests/test_crsqlite_golden.py)
    assert seqs == [0, 1]

    a.execute("INSERT INTO machines (id, name, status) VALUES (2, 'woof', 'created')")
    assert a.db_version() == 2


def test_transaction_is_one_version(db):
    a = db("a")
    with a.write_tx() as conn:
        for i in range(5):
            conn.execute(
                "INSERT INTO machines (id, name) VALUES (?, ?)", (i, f"m{i}")
            )
    assert a.db_version() == 1
    assert len(a.changes_for_version(1)) == 10  # 2 cols x 5 rows


def test_readonly_tx_consumes_no_version(db):
    a = db("a")
    with a.write_tx() as conn:
        conn.execute("SELECT * FROM machines").fetchall()
    assert a.db_version() == 0


def test_update_only_touches_changed_columns(db):
    a = db("a")
    a.execute("INSERT INTO machines (id, name, status) VALUES (1, 'meow', 'created')")
    a.execute("UPDATE machines SET status='started' WHERE id=1")
    changes = a.changes_for_version(2)
    assert [ch.cid for ch in changes] == ["status"]
    assert changes[0].col_version == 2
    assert changes[0].val == "started"


def test_changes_replicate(db):
    a, b = db("a"), db("b")
    a.execute("INSERT INTO machines (id, name, status) VALUES (1, 'meow', 'created')")
    applied = b.apply_changes(a.changes_for_version(1))
    assert applied > 0
    row = b.conn.execute("SELECT name, status FROM machines WHERE id=1").fetchone()
    assert row == ("meow", "created")


def test_lww_bigger_col_version_wins(db):
    a, b = db("a"), db("b")
    a.execute("INSERT INTO machines (id, status) VALUES (1, 'created')")
    b.apply_changes(a.changes_for_version(1))
    # b updates twice (col_version 3), a updates once (col_version 2)
    b.execute("UPDATE machines SET status='starting' WHERE id=1")
    b.execute("UPDATE machines SET status='started' WHERE id=1")
    a.execute("UPDATE machines SET status='destroyed' WHERE id=1")
    # cross-apply
    a.apply_changes(b.collect_changes((1, b.db_version()), b.site_id))
    b.apply_changes(a.collect_changes((1, a.db_version()), a.site_id))
    sa = a.conn.execute("SELECT status FROM machines WHERE id=1").fetchone()[0]
    sb = b.conn.execute("SELECT status FROM machines WHERE id=1").fetchone()[0]
    assert sa == sb == "started"  # col_version 3 beats 2


def test_lww_tie_biggest_value_wins(db):
    a, b = db("a"), db("b")
    a.execute("INSERT INTO machines (id) VALUES (1)")
    b.apply_changes(a.changes_for_version(1))
    a.execute("UPDATE machines SET status='apple' WHERE id=1")
    b.execute("UPDATE machines SET status='zebra' WHERE id=1")
    a.apply_changes(b.collect_changes((1, b.db_version()), b.site_id))
    b.apply_changes(a.collect_changes((1, a.db_version()), a.site_id))
    sa = a.conn.execute("SELECT status FROM machines WHERE id=1").fetchone()[0]
    sb = b.conn.execute("SELECT status FROM machines WHERE id=1").fetchone()[0]
    assert sa == sb == "zebra"


def test_delete_propagates_and_wins_over_update(db):
    a, b = db("a"), db("b")
    a.execute("INSERT INTO machines (id, name) VALUES (1, 'meow')")
    b.apply_changes(a.changes_for_version(1))
    # concurrent: a deletes, b updates
    a.execute("DELETE FROM machines WHERE id=1")
    b.execute("UPDATE machines SET name='woof' WHERE id=1")
    a.apply_changes(b.collect_changes((1, b.db_version()), b.site_id))
    b.apply_changes(a.collect_changes((1, a.db_version()), a.site_id))
    assert a.conn.execute("SELECT * FROM machines").fetchall() == []
    assert b.conn.execute("SELECT * FROM machines").fetchall() == []


def test_resurrect_after_delete(db):
    a, b = db("a"), db("b")
    a.execute("INSERT INTO machines (id, name) VALUES (1, 'meow')")
    a.execute("DELETE FROM machines WHERE id=1")
    a.execute("INSERT INTO machines (id, name) VALUES (1, 'reborn')")
    b.apply_changes(a.collect_changes((1, a.db_version()), a.site_id))
    row = b.conn.execute("SELECT name FROM machines WHERE id=1").fetchone()
    assert row == ("reborn",)
    # causal length is 3 (insert -> delete -> insert)
    changes = a.collect_changes((1, a.db_version()))
    assert max(ch.cl for ch in changes) == 3


def test_delete_has_sentinel_change(db):
    a = db("a")
    a.execute("INSERT INTO machines (id, name) VALUES (1, 'x')")
    a.execute("DELETE FROM machines WHERE id=1")
    changes = a.changes_for_version(2)
    assert len(changes) == 1
    assert changes[0].cid == SENTINEL_CID
    assert changes[0].cl == 2 and changes[0].is_delete()


def test_apply_is_idempotent(db):
    a, b = db("a"), db("b")
    a.execute("INSERT INTO machines (id, name, status) VALUES (1, 'm', 's')")
    chs = a.changes_for_version(1)
    b.apply_changes(chs)
    before = b.conn.execute("SELECT * FROM machines").fetchall()
    applied_again = b.apply_changes(chs)
    assert applied_again == 0
    assert b.conn.execute("SELECT * FROM machines").fetchall() == before


def test_three_replicas_converge_random_ops():
    """Property: any op interleaving + any delivery order converges."""
    import tempfile, os

    rng = random.Random(7)
    with tempfile.TemporaryDirectory() as d:
        nodes = []
        for name in "abc":
            c = CrConn(os.path.join(d, f"{name}.db"))
            c.conn.execute(SCHEMA)
            c.as_crr("machines")
            nodes.append(c)

        for step in range(60):
            n = rng.choice(nodes)
            op = rng.random()
            rid = rng.randint(1, 6)
            if op < 0.5:
                n.execute(
                    "INSERT INTO machines (id, name, status) VALUES (?, ?, ?) "
                    "ON CONFLICT(id) DO UPDATE SET name=excluded.name",
                    (rid, f"n{step}", rng.choice(["a", "b", "c"])),
                )
            elif op < 0.8:
                n.execute(
                    "UPDATE machines SET status=? WHERE id=?",
                    (rng.choice(["x", "y", "z"]), rid),
                )
            else:
                n.execute("DELETE FROM machines WHERE id=?", (rid,))

        # full exchange, arbitrary order, applied twice for idempotence
        for _ in range(2):
            order = nodes * 2
            rng.shuffle(order)
            for dst in order:
                for src in nodes:
                    if src is dst:
                        continue
                    chs = src.collect_changes((1, src.db_version()), src.site_id)
                    rng.shuffle(chs)  # delivery order must not matter
                    dst.apply_changes(chs)

        snaps = [
            n.conn.execute(
                "SELECT id, name, status FROM machines ORDER BY id"
            ).fetchall()
            for n in nodes
        ]
        assert snaps[0] == snaps[1] == snaps[2]
        assert len(snaps[0]) > 0
        for n in nodes:
            n.close()


def test_partial_new_generation_resets_stale_cells(db):
    """A cell change from a newer row generation must not leave previous-
    generation values in other columns (8KiB chunking can deliver a
    resurrected row's cells across messages)."""
    a, b = db("a"), db("b")
    a.execute("INSERT INTO machines (id, name, status) VALUES (1, 'meow', 'old')")
    b.apply_changes(a.collect_changes((1, 1), a.site_id))
    a.execute("DELETE FROM machines WHERE id=1")
    a.execute("INSERT INTO machines (id, name, status) VALUES (1, 'reborn', 'new')")
    gen3 = a.collect_changes((2, a.db_version()), a.site_id)
    # deliver ONLY the gen-3 'status' cell first
    status_only = [ch for ch in gen3 if ch.cid == "status"]
    b.apply_changes(status_only)
    row = b.conn.execute("SELECT name, status FROM machines WHERE id=1").fetchone()
    assert row == ("", "new"), f"stale previous-generation cell survived: {row}"
    # the rest arrives later; replicas converge
    b.apply_changes(gen3)
    row = b.conn.execute("SELECT name, status FROM machines WHERE id=1").fetchone()
    assert row == ("reborn", "new")


# -- split read/write pool ---------------------------------------------


def test_reader_pool_allows_concurrent_reads(db):
    """Two readers run at once: one thread holds a pooled reader while
    another completes a read_query (the old single-RO-conn design
    serialized them)."""
    import threading

    c = db("pool")
    c.execute("INSERT INTO machines (id, name) VALUES (1, 'a')")

    holding = threading.Event()
    release = threading.Event()
    done = []

    def hold_reader():
        with c.reader():
            holding.set()
            release.wait(timeout=10)

    t = threading.Thread(target=hold_reader, daemon=True)
    t.start()
    assert holding.wait(timeout=5)
    # a second read must not block on the held reader
    _, rows = c.read_query("SELECT count(*) FROM machines")
    done.append(rows)
    release.set()
    t.join(timeout=5)
    assert done == [[(1,)]]
    assert len(c._ro_all) >= 2  # the pool genuinely grew


def test_write_priority_high_beats_low(db):
    """With the connection contended, a HIGH (apply) waiter acquires
    before a LOW (maintenance) waiter that arrived first."""
    import threading
    import time

    from corrosion_tpu.agent.locks import PRIO_HIGH, PRIO_LOW

    c = db("prio")
    order = []
    low_waiting = threading.Event()
    high_waiting = threading.Event()

    c._lock.acquire()  # main thread owns the connection
    try:
        def low():
            low_waiting.set()
            with c._lock.prio(PRIO_LOW, "maintenance"):
                order.append("low")

        def high():
            high_waiting.set()
            with c._lock.prio(PRIO_HIGH, "apply"):
                order.append("high")

        tl = threading.Thread(target=low, daemon=True)
        tl.start()
        assert low_waiting.wait(timeout=5)
        time.sleep(0.05)  # low is parked in acquire()
        th = threading.Thread(target=high, daemon=True)
        th.start()
        assert high_waiting.wait(timeout=5)
        time.sleep(0.05)
    finally:
        c._lock.release()
    tl.join(timeout=5)
    th.join(timeout=5)
    assert order == ["high", "low"]


def test_interruptible_transaction_aborts_runaway(db):
    """A statement overrunning its budget is interrupted instead of
    holding the write connection (InterruptibleTransaction parity)."""
    import sqlite3

    c = db("intr")
    slow = (
        "WITH RECURSIVE c(x) AS (SELECT 1 UNION ALL SELECT x+1 FROM c "
        "WHERE x < 100000000) SELECT max(x) FROM c"
    )
    with pytest.raises(sqlite3.OperationalError, match="interrupt"):
        with c._lock, c.interruptible(0.1):
            c.conn.execute(slow).fetchone()
    # the connection remains usable afterwards
    c.execute("INSERT INTO machines (id, name) VALUES (9, 'alive')")
    assert c.read_query("SELECT name FROM machines WHERE id=9")[1] == [
        ("alive",)
    ]
