"""Local compaction: automatic bookkeeping clearing of overwritten
versions.

Port of the reference's ``test_automatic_bookkeeping_clearing``
(corro-agent/src/agent/tests.rs:2187) plus the O(1)-history property the
compaction exists for: after N overwrites of one row, bookkeeping holds
one cleared range + one concrete version, and a fresh node receives O(1)
versions' worth of changes via sync.
"""

from __future__ import annotations

import asyncio

from corrosion_tpu.agent.runtime import Agent, AgentConfig
from corrosion_tpu.agent.testing import TEST_SCHEMA, launch_test_agent, wait_for
from corrosion_tpu.types import ActorId, ChangeSource, ChangeV1, Changeset
from corrosion_tpu.types.base import CrsqlSeq, Version


def _bookkeeping(agent):
    return agent.storage.conn.execute(
        "SELECT start_version, end_version, db_version "
        "FROM __corro_bookkeeping WHERE actor_id=? ORDER BY start_version",
        (agent.actor_id,),
    ).fetchall()


def _full_changeset(agent, version: int, db_version: int) -> ChangeV1:
    changes = agent.storage.collect_changes((db_version, db_version))
    last_seq = len(changes) - 1
    return ChangeV1(
        actor_id=ActorId(agent.actor_id),
        changeset=Changeset.full(
            Version(version), changes,
            (CrsqlSeq(0), CrsqlSeq(last_seq)), CrsqlSeq(last_seq),
            agent.clock.new_timestamp(),
        ),
    )


def _offline_agent(tmp_path, name) -> Agent:
    return Agent(AgentConfig(
        db_path=str(tmp_path / f"{name}.db"), schema_sql=TEST_SCHEMA
    ))


def test_automatic_bookkeeping_clearing(tmp_path):
    """Named twin of corro-agent/src/agent/tests.rs:2187."""
    a1 = _offline_agent(tmp_path, "a1")
    a2 = _offline_agent(tmp_path, "a2")

    r = a1.execute_transaction(
        [("INSERT INTO tests (id, text) VALUES (?, ?)",
          (9001, "service-name"))]
    )
    assert r["version"] == 1
    # one concrete version
    assert _bookkeeping(a1) == [(1, None, 1)]

    cv1 = _full_changeset(a1, 1, 1)
    assert a2.handle_change(cv1, ChangeSource.BROADCAST)

    # overwrite the whole row -> version 1 is fully overwritten locally
    r = a1.execute_transaction(
        [("INSERT OR REPLACE INTO tests (id, text) VALUES (?, ?)",
          (9001, "service-name-overwrite"))]
    )
    assert r["version"] == 2
    # version 1 became a cleared range; version 2 is concrete (tests.rs
    # asserts exactly this bookkeeping shape)
    assert _bookkeeping(a1) == [(1, 1, None), (2, None, 2)]

    # the receiving node does NOT clear: only the originating node
    # compacts its own versions (impact triggers watch local rows only)
    cv2 = _full_changeset(a1, 2, 2)
    a1_rows_in_a2 = a2.bookie.for_actor(a1.actor_id)
    assert a2.handle_change(cv2, ChangeSource.BROADCAST)
    a2_bk = a2.storage.conn.execute(
        "SELECT start_version, end_version FROM __corro_bookkeeping "
        "WHERE actor_id=? ORDER BY start_version",
        (a1.actor_id,),
    ).fetchall()
    assert a2_bk == [(1, None), (2, None)]
    assert a1_rows_in_a2.contains_version(1)
    a1.storage.close()
    a2.storage.close()


def test_overwrites_collapse_to_one_cleared_range(tmp_path):
    a1 = _offline_agent(tmp_path, "a1")
    n = 20
    for i in range(n):
        a1.execute_transaction(
            [("INSERT OR REPLACE INTO tests (id, text) VALUES (1, ?)",
              (f"value-{i}",))]
        )
    # all overwritten versions merged into ONE cleared range + the live one
    assert _bookkeeping(a1) == [(1, n - 1, None), (n, None, n)]
    booked = a1.bookie.for_actor(a1.actor_id)
    assert booked.cleared.spans() == [(1, n - 1)]
    # cleared ranges still count as "contained" for dedupe/sync algebra
    assert booked.contains_version(5)
    a1.storage.close()


def test_empty_changeset_gossips_to_peers(tmp_path):
    """The originating node's cleared range reaches peers as a
    Changeset::Empty and clears their bookkeeping for that actor."""
    a1 = _offline_agent(tmp_path, "a1")
    a2 = _offline_agent(tmp_path, "a2")
    a1.execute_transaction(
        [("INSERT INTO tests (id, text) VALUES (1, 'v1')",)]
    )
    assert a2.handle_change(_full_changeset(a1, 1, 1), ChangeSource.BROADCAST)
    a1.execute_transaction(
        [("INSERT OR REPLACE INTO tests (id, text) VALUES (1, 'v2')",)]
    )
    # simulate gossip of the empty changeset a1 produced
    booked1 = a1.bookie.for_actor(a1.actor_id)
    assert booked1.cleared.spans() == [(1, 1)]
    empty = ChangeV1(
        actor_id=ActorId(a1.actor_id),
        changeset=Changeset.empty(
            (Version(1), Version(1)), a1.clock.new_timestamp()
        ),
    )
    assert a2.handle_change(empty, ChangeSource.BROADCAST)
    a2_view = a2.bookie.for_actor(a1.actor_id)
    assert a2_view.cleared.contains(1)
    a1.storage.close()
    a2.storage.close()


def test_cleared_watermark_heals_partial_broadcast(tmp_path):
    """A peer that saw only a SUBSET of the ranges stamped with one
    compaction ts must still learn the rest via the sync Empty-need
    exchange, and its watermark must then match the originator's so
    steady-state sync rounds stop re-serving cleared history."""
    async def main():
        (tmp_path / "n1").mkdir()
        (tmp_path / "n2").mkdir()
        a1 = await launch_test_agent(tmpdir=str(tmp_path / "n1"))
        # two separate compactions => two cleared groups w/ distinct ts
        for i in range(6):
            a1.execute_transaction(
                [("INSERT OR REPLACE INTO tests (id, text) VALUES (1, ?)",
                  (f"x{i}",))]
            )
        for i in range(6):
            a1.execute_transaction(
                [("INSERT OR REPLACE INTO tests (id, text) VALUES (2, ?)",
                  (f"y{i}",))]
            )
        booked1 = a1.bookie.for_actor(a1.actor_id)
        assert booked1.last_cleared_ts is not None
        a2 = await launch_test_agent(
            bootstrap=[f"{a1.gossip_addr[0]}:{a1.gossip_addr[1]}"],
            tmpdir=str(tmp_path / "n2"),
        )
        a2_view = lambda: a2.bookie.for_actor(a1.actor_id)
        await wait_for(
            lambda: a2_view().last_cleared_ts is not None
            and int(a2_view().last_cleared_ts)
            == int(booked1.last_cleared_ts),
            timeout=20,
        )
        # all cleared ranges present, not just the latest group
        assert a2_view().cleared.spans() == booked1.cleared.spans()
        # steady state: the server has nothing newer than a2's watermark
        assert a1.bookie.cleared_since(
            a1.actor_id, int(a2_view().last_cleared_ts)
        ) == []
        await a1.stop()
        await a2.stop()

    asyncio.run(main())


def test_fresh_node_sync_transfers_o1_versions(tmp_path):
    """End-to-end: after N overwrites, a freshly bootstrapped node
    converges having received only O(1) versions' changes via sync."""
    async def main():
        (tmp_path / "n1").mkdir()
        (tmp_path / "n2").mkdir()
        a1 = await launch_test_agent(tmpdir=str(tmp_path / "n1"))
        n = 30
        for i in range(n):
            a1.execute_transaction(
                [("INSERT OR REPLACE INTO tests (id, text) VALUES (1, ?)",
                  (f"v{i}",))]
            )
        assert _bookkeeping(a1) == [(1, n - 1, None), (n, None, n)]
        a2 = await launch_test_agent(
            bootstrap=[f"{a1.gossip_addr[0]}:{a1.gossip_addr[1]}"],
            tmpdir=str(tmp_path / "n2"),
        )

        def converged():
            _, rows = a2.storage.read_query(
                "SELECT text FROM tests WHERE id = 1"
            )
            return rows and rows[0][0] == f"v{n - 1}"

        await wait_for(converged, timeout=20)
        # a2 knows the cleared range (no gaps to request) and received
        # only the live version's changes.  The FULL cleared span is an
        # eventually-consistent property, not an instantaneous one: when
        # a2 boots into a1's broadcast retransmission tail it first
        # picks up a fragmented subset of the cleared ranges, and the
        # complete per-ts group arrives with the first anti-entropy
        # round's empty-need serve — so wait for it, don't snapshot it
        a2_view = a2.bookie.for_actor(a1.actor_id)
        await wait_for(
            lambda: a2_view.cleared.contains_span(1, n - 1), timeout=20
        )
        assert a2_view.needed_spans() == []
        received = a2.metrics.get_counter("corro_sync_changes_received_total")
        assert received <= 4, f"expected O(1) changes, got {received}"
        await a1.stop()
        await a2.stop()

    asyncio.run(main())
