import time

import pytest

from corrosion_tpu.types import (
    Actor,
    ActorId,
    Change,
    ChunkedChanges,
    ClusterId,
    CrsqlDbVersion,
    CrsqlSeq,
    HLClock,
    Timestamp,
    Version,
)
from corrosion_tpu.types.hlc import ClockDriftError


def test_u64_newtypes():
    v = Version(5)
    assert v.succ() == Version(6) and v.pred() == Version(4)
    assert isinstance(v + 1, Version)
    with pytest.raises(ValueError):
        Version(-1)
    with pytest.raises(ValueError):
        CrsqlSeq(1 << 64)


def test_actor_identity():
    a = ActorId.generate()
    assert len(a.bytes) == 16
    assert ActorId.from_hex(str(a)) == a
    act = Actor(id=a, addr="127.0.0.1:1234", ts=Timestamp(1), cluster_id=ClusterId(0))
    renewed = act.renew(Timestamp(99))
    assert renewed.has_same_prefix(act)
    assert renewed.ts == Timestamp(99) and act.ts == Timestamp(1)


def test_hlc_monotonic_and_merge():
    clock = HLClock()
    stamps = [clock.new_timestamp() for _ in range(100)]
    assert stamps == sorted(stamps)
    assert len(set(stamps)) == 100

    # merging a remote timestamp moves `last` forward
    remote = Timestamp(int(clock.last) + 1000)
    clock.update_with_timestamp(remote)
    assert int(clock.last) == int(remote)
    assert int(clock.new_timestamp()) > int(remote)

    # drift rejection
    far_future = Timestamp.pack(time.time_ns() + 10_000_000_000, 0)
    with pytest.raises(ClockDriftError):
        clock.update_with_timestamp(far_future)


def test_hlc_stalled_physical_clock_uses_logical():
    t = [1_000_000_000]
    clock = HLClock(now_ns=lambda: t[0])
    a = clock.new_timestamp()
    b = clock.new_timestamp()
    assert int(b) > int(a)
    assert b.physical_ns == a.physical_ns


def _mk_change(seq: int, size: int = 0) -> Change:
    return Change(
        table="t",
        pk=b"\x01",
        cid="c",
        val="x" * size,
        col_version=1,
        db_version=CrsqlDbVersion(1),
        seq=CrsqlSeq(seq),
        site_id=b"\x00" * 16,
        cl=1,
    )


def test_chunker_single_chunk():
    changes = [_mk_change(i) for i in range(3)]
    chunks = list(ChunkedChanges(changes, 0, 2))
    assert len(chunks) == 1
    got, (s, e) = chunks[0]
    assert len(got) == 3 and (int(s), int(e)) == (0, 2)


def test_chunker_splits_on_budget():
    changes = [_mk_change(i, size=600) for i in range(10)]
    chunks = list(ChunkedChanges(changes, 0, 9, max_buf_size=2000))
    # contiguous inclusive coverage of 0..=9
    assert chunks[0][1][0] == 0
    assert chunks[-1][1][1] == 9
    for (_, (_, e0)), (_, (s1, _)) in zip(chunks, chunks[1:]):
        assert int(s1) == int(e0) + 1
    assert sum(len(c) for c, _ in chunks) == 10
    assert len(chunks) > 1


def test_chunker_empty_iter_yields_full_range():
    chunks = list(ChunkedChanges([], 4, 7))
    assert chunks == [([], (CrsqlSeq(4), CrsqlSeq(7)))]


def test_chunker_last_chunk_extends_to_last_seq():
    # trailing seqs with no changes (e.g. elided rows) still covered
    changes = [_mk_change(0), _mk_change(1)]
    chunks = list(ChunkedChanges(changes, 0, 5))
    assert chunks[-1][1][1] == 5


def test_change_chunker_reference_scenarios():
    """Named port of ``change.rs`` ``test_change_chunker`` — every
    scenario, same expectations (empty iterator, budget splits, elided
    trailing rows, seq gaps riding the enclosing range)."""
    changes = [_mk_change(seq) for seq in range(100)]
    size = changes[0].estimated_byte_size()

    # empty iterator: one empty chunk covering the whole range
    assert list(ChunkedChanges([], 0, 100)) == [([], (0, 100))]

    # budget = 2 changes: [c0, c1] 0..=1 then [c2] 2..=100
    out = list(ChunkedChanges(changes[:3], 0, 100, max_buf_size=2 * size))
    assert out == [
        ([changes[0], changes[1]], (0, 1)),
        ([changes[2]], (2, 100)),
    ]

    # last_seq == 0 with a trailing change beyond it: only [c0] 0..=0
    out = list(ChunkedChanges(changes[:2], 0, 0, max_buf_size=size))
    assert out == [([changes[0]], (0, 0))]

    # seq gaps inside one budget: the range rides to last_seq
    out = list(ChunkedChanges([changes[0], changes[2]], 0, 100,
                              max_buf_size=2 * size))
    assert out == [([changes[0], changes[2]], (0, 100))]

    # all-gaps, huge budget: one chunk 0..=100
    out = list(ChunkedChanges(
        [changes[2], changes[4], changes[7], changes[8]], 0, 100,
        max_buf_size=100_000))
    assert out == [
        ([changes[2], changes[4], changes[7], changes[8]], (0, 100))
    ]

    # gaps split by budget: [c2, c4] 0..=4 then [c7, c8] 5..=10
    out = list(ChunkedChanges(
        [changes[2], changes[4], changes[7], changes[8]], 0, 10,
        max_buf_size=2 * size))
    assert out == [
        ([changes[2], changes[4]], (0, 4)),
        ([changes[7], changes[8]], (5, 10)),
    ]
