"""RFC 8032 conformance for the dependency-free Ed25519
(``types/crypto.py``) — the signed-changeset-attribution primitive.

Vectors are §7.1 of RFC 8032 (TEST 1-3 + the SHA(abc) vector), byte
for byte; plus negative cases (wrong message/key/signature, malformed
encodings), the derivation KDF, and the process-wide verification memo
the virtual campaigns lean on.
"""

from __future__ import annotations

import hashlib

import pytest

from corrosion_tpu.types import crypto

# (secret, public, message, signature) — RFC 8032 §7.1
RFC8032_VECTORS = [
    (  # TEST 1: empty message
        "9d61b19deffd5a60ba844af492ec2cc44449c5697b326919703bac031cae7f60",
        "d75a980182b10ab7d54bfed3c964073a0ee172f3daa62325af021a68f707511a",
        "",
        "e5564300c360ac729086e2cc806e828a84877f1eb8e5d974d873e06522490155"
        "5fb8821590a33bacc61e39701cf9b46bd25bf5f0595bbe24655141438e7a100b",
    ),
    (  # TEST 2: one byte
        "4ccd089b28ff96da9db6c346ec114e0f5b8a319f35aba624da8cf6ed4fb8a6fb",
        "3d4017c3e843895a92b70aa74d1b7ebc9c982ccf2ec4968cc0cd55f12af4660c",
        "72",
        "92a009a9f0d4cab8720e820b5f642540a2b27b5416503f8fb3762223ebdb69da"
        "085ac1e43e15996e458f3613d0f11d8c387b2eaeb4302aeeb00d291612bb0c00",
    ),
    (  # TEST 3: two bytes
        "c5aa8df43f9f837bedb7442f31dcb7b166d38535076f094b85ce3a2e0b4458f7",
        "fc51cd8e6218a1a38da47ed00230f0580816ed13ba3303ac5deb911548908025",
        "af82",
        "6291d657deec24024827e69c3abe01a30ce548a284743a445e3680d7db5ac3ac"
        "18ff9b538d16f290ae67f760984dc6594a7c15e9716ed28dc027beceea1ec40a",
    ),
    (  # TEST SHA(abc): ed25519 over a sha512 digest
        "833fe62409237b9d62ec77587520911e9a759cec1d19755b7da901b96dca3d42",
        "ec172b93ad5e563bf4932c70e1245034c35467ef2efd4d64ebf819683467e2bf",
        hashlib.sha512(b"abc").hexdigest(),
        "dc2a4459e7369633a52b1bf277839a00201009a3efbf3ecb69bea2186c26b589"
        "09351fc9ac90b3ecfdfbc7c66431e0303dca179c138ac17ad9bef1177331a704",
    ),
]


@pytest.mark.parametrize("sk,pk,msg,sig", RFC8032_VECTORS)
def test_rfc8032_vectors(sk, pk, msg, sig):
    sk, pk = bytes.fromhex(sk), bytes.fromhex(pk)
    msg, sig = bytes.fromhex(msg), bytes.fromhex(sig)
    assert crypto.public_key(sk) == pk
    assert crypto.sign(sk, msg) == sig
    assert crypto.verify(pk, msg, sig)


def test_verify_rejects_wrong_message_key_and_signature():
    sk, pk, _msg, _sig = (bytes.fromhex(v) for v in RFC8032_VECTORS[0])
    sig = crypto.sign(sk, b"genuine")
    assert crypto.verify(pk, b"genuine", sig)
    assert not crypto.verify(pk, b"tampered", sig)
    # flipped signature bits
    for i in (0, 31, 32, 63):
        bad = bytearray(sig)
        bad[i] ^= 0x01
        assert not crypto.verify(pk, b"genuine", bytes(bad))
    # wrong key
    sk2, pk2 = crypto.seed_keypair(b"someone else")
    assert not crypto.verify(pk2, b"genuine", sig)
    # a signature by the other key over the same message
    assert not crypto.verify(pk, b"genuine", crypto.sign(sk2, b"genuine"))


def test_verify_never_raises_on_malformed_inputs():
    sk, pk = crypto.seed_keypair(b"malformed-suite")
    sig = crypto.sign(sk, b"m")
    assert not crypto.verify(pk, b"m", b"")                      # empty
    assert not crypto.verify(pk, b"m", sig[:-1])                 # short
    assert not crypto.verify(pk, b"m", sig + b"\x00")            # long
    assert not crypto.verify(b"", b"m", sig)                     # no key
    assert not crypto.verify(b"\xff" * 32, b"m", sig)            # junk key
    # S >= L (scalar out of range) must be rejected, not reduced
    bad = bytearray(sig)
    bad[32:] = (crypto._L).to_bytes(32, "little")
    assert not crypto.verify(pk, b"m", bytes(bad))
    # non-canonical R (not a curve point)
    bad = bytearray(sig)
    bad[:32] = b"\x05" + b"\xff" * 31
    assert not crypto.verify(pk, b"m", bytes(bad))


def test_secret_length_is_enforced():
    with pytest.raises(ValueError):
        crypto.sign(b"short", b"m")
    with pytest.raises(ValueError):
        crypto.public_key(b"x" * 33)


def test_seed_keypair_is_deterministic_and_not_identity_derived():
    s1, p1 = crypto.seed_keypair(b"node-7")
    s2, p2 = crypto.seed_keypair(b"node-7")
    s3, p3 = crypto.seed_keypair(b"node-8")
    assert (s1, p1) == (s2, p2)
    assert p1 != p3 and s1 != s3
    assert crypto.public_key(s1) == p1
    # the KDF is keyed (personalized blake2b), not a plain hash of the
    # material: knowing the derivation SHAPE plus a public id is not
    # enough to recompute the secret
    assert s1 != hashlib.blake2b(b"node-7", digest_size=32).digest()
    assert crypto.verify(p1, b"m", crypto.sign(s1, b"m"))


def test_verify_cached_matches_verify_and_caches():
    sk, pk = crypto.seed_keypair(b"cache-suite")
    sig = crypto.sign(sk, b"m")
    assert crypto.verify_cached(pk, b"m", sig) is True
    assert crypto.verify_cached(pk, b"x", sig) is False
    # cached results are stable (pure function memo)
    assert crypto.verify_cached(pk, b"m", sig) is True
    assert crypto.verify_cached(pk, b"x", sig) is False
    # distinct triples never alias in the cache key
    sig2 = crypto.sign(sk, b"m2")
    assert crypto.verify_cached(pk, b"m2", sig2) is True
    assert crypto.verify_cached(pk, b"m2", sig) is False
