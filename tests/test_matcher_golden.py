"""Golden port of the reference's matcher integration scenario.

Mirrors ``crates/corro-types/src/pubsub.rs`` ``test_diff`` (the
matcher's only end-to-end behavior test): a 4-table schema with
generated JSON columns and composite pks, a LEFT-JOIN + json_object
subscription, then the exact event sequence — snapshot row, a new
matching service arriving as an insert, a removed service as a delete,
and an address change updating the rendered JSON.

Since round 5 the 4-table LEFT-JOIN subscription qualifies for the
pk-scoped incremental path: join rows key on the concatenated
base-table pks exactly like the reference's AST matcher, so the
address change arrives as an in-place UPDATE of the same row id —
the reference's own event shape.  (Changes on the left-joined
machine* tables degrade to a full refresh because the reverse join
path ``machines.id = consul_services.instance_id`` has no index —
``full_refresh_aliases`` — but consul_services changes, which drive
this scenario, stay scoped.)
"""

import asyncio
import json

import pytest

from corrosion_tpu.agent.testing import launch_test_agent, wait_for

SCHEMA = """
CREATE TABLE consul_services (
    node TEXT NOT NULL,
    id TEXT NOT NULL,
    name TEXT NOT NULL DEFAULT '',
    tags TEXT NOT NULL DEFAULT '[]',
    meta TEXT NOT NULL DEFAULT '{}',
    port INTEGER NOT NULL DEFAULT 0,
    address TEXT NOT NULL DEFAULT '',
    updated_at INTEGER NOT NULL DEFAULT 0,
    app_id INTEGER AS (CAST(JSON_EXTRACT(meta, '$.app_id') AS INTEGER)),
    app_name TEXT AS (JSON_EXTRACT(meta, '$.app_name')),
    instance_id TEXT AS (COALESCE(
        JSON_EXTRACT(meta, '$.machine_id'),
        SUBSTR(JSON_EXTRACT(meta, '$.alloc_id'), 1, 8))),
    organization_id INTEGER AS (
        CAST(JSON_EXTRACT(meta, '$.organization_id') AS INTEGER)),
    PRIMARY KEY (node, id)
);
CREATE TABLE machines (
    id TEXT NOT NULL PRIMARY KEY,
    node TEXT NOT NULL DEFAULT '',
    name TEXT NOT NULL DEFAULT '',
    machine_version_id TEXT NOT NULL DEFAULT '',
    app_id INTEGER NOT NULL DEFAULT 0,
    organization_id INTEGER NOT NULL DEFAULT 0,
    network_id INTEGER NOT NULL DEFAULT 0,
    updated_at INTEGER NOT NULL DEFAULT 0
);
CREATE TABLE machine_versions (
    machine_id TEXT NOT NULL,
    id TEXT NOT NULL DEFAULT '',
    config TEXT NOT NULL DEFAULT '{}',
    updated_at INTEGER NOT NULL DEFAULT 0,
    PRIMARY KEY (machine_id, id)
);
CREATE TABLE machine_version_statuses (
    machine_id TEXT NOT NULL,
    id TEXT NOT NULL,
    status TEXT NOT NULL DEFAULT '',
    updated_at INTEGER NOT NULL DEFAULT 0,
    PRIMARY KEY (machine_id, id)
);
"""

SUB_SQL = """SELECT json_object(
  'targets', json_array(cs.address||':'||cs.port),
  'labels',  json_object(
    '__metrics_path__', JSON_EXTRACT(cs.meta, '$.path'),
    'app',            cs.app_name,
    'vm_account_id',  cs.organization_id,
    'instance',       cs.instance_id
  )
)
FROM consul_services cs
  LEFT JOIN machines m                   ON m.id = cs.instance_id
  LEFT JOIN machine_versions mv          ON m.id = mv.machine_id
      AND m.machine_version_id = mv.id
  LEFT JOIN machine_version_statuses mvs ON m.id = mvs.machine_id
      AND m.machine_version_id = mvs.id
WHERE cs.node = 'test-hostname'
  AND (mvs.status IS NULL OR mvs.status = 'started')
  AND cs.name = 'app-prometheus'"""


def _expected(path, machine, address="127.0.0.1", port=1):
    return json.dumps({
        "targets": [f"{address}:{port}"],
        "labels": {
            "__metrics_path__": path,
            "app": None,
            "vm_account_id": None,
            "instance": machine,
        },
    }, separators=(",", ":"))


def _seed(agent, service, name, machine):
    agent.execute_transaction([
        ["INSERT INTO consul_services (node, id, name, address, port, meta)"
         " VALUES ('test-hostname', ?, ?, '127.0.0.1', 1, ?)",
         [service, name,
          json.dumps({"path": "/1", "machine_id": machine})]],
        ["INSERT INTO machines (id, machine_version_id) VALUES (?, ?)",
         [machine, f"mv-{machine}"]],
        ["INSERT INTO machine_versions (machine_id, id) VALUES (?, ?)",
         [machine, f"mv-{machine}"]],
        ["INSERT INTO machine_version_statuses (machine_id, id, status)"
         " VALUES (?, ?, 'started')", [machine, f"mv-{machine}"]],
    ])


def test_matcher_reference_diff_scenario():
    async def main():
        a = await launch_test_agent(schema=SCHEMA)
        try:
            # seed: one matching service, one with a different name
            _seed(a, "service-1", "app-prometheus", "m-1")
            _seed(a, "service-2", "not-app-prometheus", "m-2")

            handle = a.subs.subscribe(SUB_SQL)
            gen = handle.stream()
            ev = next(gen)
            assert "columns" in ev
            ev = next(gen)
            assert ev["row"][0] == 1  # RowId(1)
            assert json.loads(ev["row"][1][0]) == json.loads(
                _expected("/1", "m-1"))
            assert "eoq" in next(gen)

            # a new matching service arrives -> Insert, RowId 2, ChangeId 1
            _seed(a, "service-3", "app-prometheus", "m-3")
            ev = await asyncio.to_thread(next, gen)
            assert ev["change"][0] == "insert"
            assert ev["change"][1] == 2
            assert ev["change"][3] == 1
            assert json.loads(ev["change"][2][0]) == json.loads(
                _expected("/1", "m-3"))

            # service-1 removed -> Delete of RowId 1, ChangeId 2
            a.execute_transaction([
                ["DELETE FROM consul_services WHERE node = 'test-hostname'"
                 " AND id = 'service-1'"]
            ])
            ev = await asyncio.to_thread(next, gen)
            assert ev["change"][0] == "delete"
            assert ev["change"][1] == 1
            assert ev["change"][3] == 2

            # address change re-renders service-3's JSON: an in-place
            # Update of the same row id (pk-keyed join rows), exactly
            # the reference's event
            a.execute_transaction([
                ["UPDATE consul_services SET address = '127.0.0.2'"
                 " WHERE node = 'test-hostname' AND id = 'service-3'"]
            ])
            ev = await asyncio.to_thread(next, gen)
            assert ev["change"][0] == "update"
            assert ev["change"][1] == 2
            assert ev["change"][3] == 3
            assert json.loads(ev["change"][2][0]) == json.loads(
                _expected("/1", "m-3", address="127.0.0.2"))
            # final state: exactly one row, the updated service-3
            assert len(handle.rows) == 1
        finally:
            await a.stop()

    asyncio.run(main())
