"""Multi-chip sharding tests over the 8-device virtual CPU mesh.

Validates that the cluster-step kernels (epidemic tick, SWIM step) run
under real ``Mesh``/``NamedSharding`` placements, keep their output
shardings, and compute the same results as the unsharded path — i.e.
that XLA's inserted collectives are semantically transparent.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from __graft_entry__ import epidemic_shardings, swim_shardings
from corrosion_tpu.models.swim import SwimParams, swim_init, swim_step
from corrosion_tpu.sim.epidemic import (
    EpidemicConfig,
    epidemic_init,
    epidemic_tick,
)


@pytest.fixture(scope="module")
def mesh():
    devices = np.array(jax.devices()[:8]).reshape(2, 4)
    return Mesh(devices, ("seeds", "nodes"))


def _cfg(n_nodes=256):
    return EpidemicConfig(
        n_nodes=n_nodes,
        n_rows=4,
        ring0_size=16,
        loss=0.05,
        partition_blocks=2,
        heal_tick=2,
        sync_interval=2,
    )


def _batched_state(cfg, n_seeds):
    state = epidemic_init(cfg)
    return jax.tree.map(
        lambda x: jnp.broadcast_to(x, (n_seeds,) + x.shape), state
    )


def test_epidemic_tick_sharded_runs_and_keeps_shardings(mesh):
    cfg = _cfg()
    n_seeds = 4
    batched = _batched_state(cfg, n_seeds)
    shardings = epidemic_shardings(mesh, batched)
    batched = jax.device_put(batched, shardings)
    keys = jax.device_put(
        jax.random.split(jax.random.PRNGKey(0), n_seeds),
        NamedSharding(mesh, P("seeds")),
    )

    step = jax.jit(
        jax.vmap(lambda st, k: epidemic_tick(st, k, cfg)),
        out_shardings=shardings,
    )
    out = step(batched, keys)
    jax.block_until_ready(out)

    assert out.rows.shape == (n_seeds, cfg.n_nodes, cfg.n_rows)
    assert out.rows.sharding == NamedSharding(mesh, P("seeds", "nodes"))
    assert out.tick.sharding == NamedSharding(mesh, P("seeds"))
    # the writer's changeset spread somewhere: state changed on some node
    assert bool((np.asarray(out.msgs) > 0).any())


def test_epidemic_tick_sharded_matches_unsharded(mesh):
    """XLA-inserted collectives must not change the computed state."""
    cfg = _cfg()
    n_seeds = 4
    batched = _batched_state(cfg, n_seeds)
    keys = jax.random.split(jax.random.PRNGKey(7), n_seeds)

    plain = jax.jit(jax.vmap(lambda st, k: epidemic_tick(st, k, cfg)))(
        batched, keys
    )

    shardings = epidemic_shardings(mesh, batched)
    sharded_in = jax.device_put(batched, shardings)
    sharded_keys = jax.device_put(keys, NamedSharding(mesh, P("seeds")))
    sharded = jax.jit(
        jax.vmap(lambda st, k: epidemic_tick(st, k, cfg)),
        out_shardings=shardings,
    )(sharded_in, sharded_keys)

    for a, b in zip(jax.tree.leaves(plain), jax.tree.leaves(sharded)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_swim_step_sharded_view_matrix(mesh):
    n_nodes = 256
    sp = SwimParams(n_nodes=n_nodes)
    sw = swim_init(n_nodes)
    sw_shard = swim_shardings(mesh, sw)
    sw = jax.device_put(sw, sw_shard)
    alive = jax.device_put(
        jnp.ones((n_nodes,), bool), NamedSharding(mesh, P("nodes"))
    )

    swim = jax.jit(
        lambda st, k, t, a: swim_step(st, k, t, sp, a),
        out_shardings=sw_shard,
    )
    out = swim(sw, jax.random.PRNGKey(1), jnp.int32(0), alive)
    jax.block_until_ready(out)

    assert out.view.shape == (n_nodes, n_nodes)
    assert out.view.sharding == NamedSharding(mesh, P("nodes"))


def test_multi_tick_sharded_convergence(mesh):
    """Run several sharded ticks and check the epidemic actually converges
    to the writer's state across node shards (i.e. cross-shard delivery —
    hence the inserted collectives — really happens)."""
    cfg = EpidemicConfig(
        n_nodes=256,
        n_rows=4,
        ring0_size=32,
        fanout_ring0=3,
        fanout_global=3,
        max_transmissions=8,
        loss=0.0,
        sync_interval=2,
    )
    n_seeds = 2
    batched = _batched_state(cfg, n_seeds)
    target = np.asarray(epidemic_init(cfg).rows[0])
    shardings = epidemic_shardings(mesh, batched)
    batched = jax.device_put(batched, shardings)

    step = jax.jit(
        jax.vmap(lambda st, k: epidemic_tick(st, k, cfg)),
        out_shardings=shardings,
    )
    key = jax.random.PRNGKey(3)
    for _ in range(40):
        key, sub = jax.random.split(key)
        keys = jax.device_put(
            jax.random.split(sub, n_seeds), NamedSharding(mesh, P("seeds"))
        )
        batched = step(batched, keys)
        rows = np.asarray(batched.rows)
        if (rows == target[None, None, :]).all():
            break
    assert (np.asarray(batched.rows) == target[None, None, :]).all(), (
        "sharded epidemic did not converge in 40 ticks"
    )


def test_dryrun_multichip_inline_path():
    """With the conftest-provisioned 8-device backend, dryrun_multichip
    must take the in-process path and succeed."""
    import __graft_entry__ as ge

    assert jax.device_count() >= 8
    assert ge._backend_initialized()
    ge.dryrun_multichip(8)
