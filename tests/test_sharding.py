"""Multi-chip sharding tests over the 8-device virtual CPU mesh.

Validates that the cluster-step kernels (epidemic tick, SWIM step) run
under real ``Mesh``/``NamedSharding`` placements, keep their output
shardings, and compute the same results as the unsharded path — i.e.
that XLA's inserted collectives are semantically transparent.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from __graft_entry__ import epidemic_shardings, swim_shardings
from corrosion_tpu.models.swim import SwimParams, swim_init, swim_step
from corrosion_tpu.sim.epidemic import (
    EpidemicConfig,
    epidemic_init,
    epidemic_tick,
)


@pytest.fixture(scope="module")
def mesh():
    devices = np.array(jax.devices()[:8]).reshape(2, 4)
    return Mesh(devices, ("seeds", "nodes"))


def _cfg(n_nodes=256):
    return EpidemicConfig(
        n_nodes=n_nodes,
        n_rows=4,
        ring0_size=16,
        loss=0.05,
        partition_blocks=2,
        heal_tick=2,
        sync_interval=2,
    )


def _batched_state(cfg, n_seeds):
    state = epidemic_init(cfg)
    return jax.tree.map(
        lambda x: jnp.broadcast_to(x, (n_seeds,) + x.shape), state
    )


def test_epidemic_tick_sharded_runs_and_keeps_shardings(mesh):
    cfg = _cfg()
    n_seeds = 4
    batched = _batched_state(cfg, n_seeds)
    shardings = epidemic_shardings(mesh, batched)
    batched = jax.device_put(batched, shardings)
    keys = jax.device_put(
        jax.random.split(jax.random.PRNGKey(0), n_seeds),
        NamedSharding(mesh, P("seeds")),
    )

    step = jax.jit(
        jax.vmap(lambda st, k: epidemic_tick(st, k, cfg)),
        out_shardings=shardings,
    )
    out = step(batched, keys)
    jax.block_until_ready(out)

    assert out.rows.shape == (n_seeds, cfg.n_nodes, cfg.n_rows)
    assert out.rows.sharding == NamedSharding(mesh, P("seeds", "nodes"))
    assert out.tick.sharding == NamedSharding(mesh, P("seeds"))
    # the writer's changeset spread somewhere: state changed on some node
    assert bool((np.asarray(out.msgs) > 0).any())


def test_epidemic_tick_sharded_matches_unsharded(mesh):
    """XLA-inserted collectives must not change the computed state."""
    cfg = _cfg()
    n_seeds = 4
    batched = _batched_state(cfg, n_seeds)
    keys = jax.random.split(jax.random.PRNGKey(7), n_seeds)

    plain = jax.jit(jax.vmap(lambda st, k: epidemic_tick(st, k, cfg)))(
        batched, keys
    )

    shardings = epidemic_shardings(mesh, batched)
    sharded_in = jax.device_put(batched, shardings)
    sharded_keys = jax.device_put(keys, NamedSharding(mesh, P("seeds")))
    sharded = jax.jit(
        jax.vmap(lambda st, k: epidemic_tick(st, k, cfg)),
        out_shardings=shardings,
    )(sharded_in, sharded_keys)

    for a, b in zip(jax.tree.leaves(plain), jax.tree.leaves(sharded)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_swim_step_sharded_view_matrix(mesh):
    n_nodes = 256
    sp = SwimParams(n_nodes=n_nodes)
    sw = swim_init(n_nodes)
    sw_shard = swim_shardings(mesh, sw)
    sw = jax.device_put(sw, sw_shard)
    alive = jax.device_put(
        jnp.ones((n_nodes,), bool), NamedSharding(mesh, P("nodes"))
    )

    swim = jax.jit(
        lambda st, k, t, a: swim_step(st, k, t, sp, a),
        out_shardings=sw_shard,
    )
    out = swim(sw, jax.random.PRNGKey(1), jnp.int32(0), alive)
    jax.block_until_ready(out)

    assert out.view.shape == (n_nodes, n_nodes)
    assert out.view.sharding == NamedSharding(mesh, P("nodes"))


def test_multi_tick_sharded_convergence(mesh):
    """Run several sharded ticks and check the epidemic actually converges
    to the writer's state across node shards (i.e. cross-shard delivery —
    hence the inserted collectives — really happens)."""
    cfg = EpidemicConfig(
        n_nodes=256,
        n_rows=4,
        ring0_size=32,
        fanout_ring0=3,
        fanout_global=3,
        max_transmissions=8,
        loss=0.0,
        sync_interval=2,
    )
    n_seeds = 2
    batched = _batched_state(cfg, n_seeds)
    target = np.asarray(epidemic_init(cfg).rows[0])
    shardings = epidemic_shardings(mesh, batched)
    batched = jax.device_put(batched, shardings)

    step = jax.jit(
        jax.vmap(lambda st, k: epidemic_tick(st, k, cfg)),
        out_shardings=shardings,
    )
    key = jax.random.PRNGKey(3)
    for _ in range(40):
        key, sub = jax.random.split(key)
        keys = jax.device_put(
            jax.random.split(sub, n_seeds), NamedSharding(mesh, P("seeds"))
        )
        batched = step(batched, keys)
        rows = np.asarray(batched.rows)
        if (rows == target[None, None, :]).all():
            break
    assert (np.asarray(batched.rows) == target[None, None, :]).all(), (
        "sharded epidemic did not converge in 40 ticks"
    )


def test_dryrun_multichip_inline_path():
    """With the conftest-provisioned 8-device backend, dryrun_multichip
    must take the in-process path and succeed."""
    import __graft_entry__ as ge

    assert jax.device_count() >= 8
    assert ge._backend_initialized()
    ge.dryrun_multichip(8)


def test_sharded_broadcast_matches_unsharded():
    """The explicit-collective shard_map fabric (all_gather over the
    mesh's nodes axis + local scatter delivery) produces BITWISE the
    same step as the single-chip kernel for the same key."""
    from corrosion_tpu.models.broadcast import (
        BroadcastParams,
        broadcast_step,
    )
    from corrosion_tpu.models.sharded import sharded_broadcast_step
    from corrosion_tpu.ops.keys import DEFAULT_CODEC as C

    devices = np.array(jax.devices()[:8])
    nodes_mesh = Mesh(devices, ("nodes",))
    n, r = 256, 4
    params = BroadcastParams(
        n_nodes=n, fanout_ring0=0, fanout_global=3, ring0_size=1,
        max_transmissions=4, loss=0.1,
    )
    base = C.pack(jnp.ones((n, r), jnp.int32), jnp.ones((n, r), jnp.int32),
                  jnp.zeros((n, r), jnp.int32))
    news = C.pack(jnp.ones((r,), jnp.int32), jnp.full((r,), 2, jnp.int32),
                  jnp.ones((r,), jnp.int32))
    rows = base.at[0].set(news)
    tx = jnp.zeros((n,), jnp.int32).at[0].set(params.max_transmissions)
    msgs = jnp.zeros((n,), jnp.int32)

    step = sharded_broadcast_step(nodes_mesh, params)
    spec = NamedSharding(nodes_mesh, P("nodes"))
    s_rows = jax.device_put(rows, spec)
    s_tx = jax.device_put(tx, spec)
    s_msgs = jax.device_put(msgs, spec)

    key = jax.random.PRNGKey(7)
    for t in range(6):
        k = jax.random.fold_in(key, t)
        ref = broadcast_step(rows, tx, msgs, k, params)
        rows, tx, msgs = ref.rows, ref.tx_remaining, ref.msgs_sent
        s_rows, s_tx, s_msgs = step(s_rows, s_tx, s_msgs, k)
        assert jnp.array_equal(s_rows, rows), f"rows diverged at tick {t}"
        assert jnp.array_equal(s_tx, tx)
        assert jnp.array_equal(s_msgs, msgs)
    # the epidemic genuinely progressed across shard boundaries
    assert int((rows == news[None, :]).all(axis=1).sum()) > 8


def test_sharded_seq_sync_matches_unsharded():
    """The sequence-reassembly fabric (seq bitmaps all_gathered over the
    nodes axis, algebra replicated, rows committed per shard) is
    BITWISE the single-chip seq_sync_step for the same key."""
    from corrosion_tpu.models.sharded import sharded_seq_sync_step
    from corrosion_tpu.models.sync import SeqSyncParams, seq_sync_step

    devices = np.array(jax.devices()[:8])
    nodes_mesh = Mesh(devices, ("nodes",))
    n, s = 256, 32
    params = SeqSyncParams(
        n_nodes=n, n_seqs=s, peers_per_round=2, seqs_per_chunk=4,
        chunk_budget=3, loss=0.1,
    )
    bits = jnp.zeros((n, s), bool).at[0].set(True)
    # a second partial holder: complementary serving is in play
    bits = bits.at[1, : s // 2].set(True)
    msgs = jnp.zeros((n,), jnp.int32)

    step = sharded_seq_sync_step(nodes_mesh, params)
    spec = NamedSharding(nodes_mesh, P("nodes"))
    s_bits = jax.device_put(bits, spec)
    s_msgs = jax.device_put(msgs, spec)

    key = jax.random.PRNGKey(9)
    for t in range(12):
        k = jax.random.fold_in(key, t)
        bits, msgs = seq_sync_step(bits, msgs, k, params)
        s_bits, s_msgs = step(s_bits, s_msgs, k)
        assert jnp.array_equal(s_bits, bits), f"bits diverged at tick {t}"
        assert jnp.array_equal(s_msgs, msgs), f"msgs diverged at tick {t}"
    # knowledge actually spread beyond the seeded nodes
    assert int(bits.any(axis=1).sum()) > 2


def test_ring_fabric_matches_unsharded_bitwise():
    """The destination-sorted fabric (per-destination active-sender
    slots over all_to_all) at the lossless default cap is BITWISE the
    single-chip kernel AND the all_gather fabric, with zero overflow —
    including ring0 columns and loss."""
    from corrosion_tpu.models.broadcast import (
        BroadcastParams,
        broadcast_step,
    )
    from corrosion_tpu.models.sharded import sharded_broadcast_step_ring
    from corrosion_tpu.ops.keys import DEFAULT_CODEC as C

    devices = np.array(jax.devices()[:8])
    nodes_mesh = Mesh(devices, ("nodes",))
    n, r = 256, 4
    params = BroadcastParams(
        n_nodes=n, fanout_ring0=1, fanout_global=2, ring0_size=16,
        max_transmissions=4, loss=0.1,
    )
    base = C.pack(jnp.ones((n, r), jnp.int32), jnp.ones((n, r), jnp.int32),
                  jnp.zeros((n, r), jnp.int32))
    news = C.pack(jnp.ones((r,), jnp.int32), jnp.full((r,), 2, jnp.int32),
                  jnp.ones((r,), jnp.int32))
    rows = base.at[0].set(news)
    tx = jnp.zeros((n,), jnp.int32).at[0].set(params.max_transmissions)
    msgs = jnp.zeros((n,), jnp.int32)

    step = sharded_broadcast_step_ring(nodes_mesh, params)
    spec = NamedSharding(nodes_mesh, P("nodes"))
    s_rows = jax.device_put(rows, spec)
    s_tx = jax.device_put(tx, spec)
    s_msgs = jax.device_put(msgs, spec)

    key = jax.random.PRNGKey(3)
    for t in range(8):
        k = jax.random.fold_in(key, t)
        ref = broadcast_step(rows, tx, msgs, k, params)
        rows, tx, msgs = ref.rows, ref.tx_remaining, ref.msgs_sent
        s_rows, s_tx, s_msgs, overflow = step(s_rows, s_tx, s_msgs, k)
        assert int(overflow) == 0
        assert jnp.array_equal(s_rows, rows), f"rows diverged at tick {t}"
        assert jnp.array_equal(s_tx, tx)
        assert jnp.array_equal(s_msgs, msgs)
    assert int((rows == news[None, :]).all(axis=1).sum()) > 8


def test_sharded_exact_matches_packed_bitwise():
    """The mesh-native exact rejection sampler (sent_to bitmap + node
    state row-sharded over ``nodes``, replicated candidate draws,
    all_gathered validity masks) is BITWISE the single-chip
    ``packed_exact_tick`` per tick — infected set, per-node msg counts,
    AND the packed sent_to rows — at N=4096 on the 8-device virtual
    mesh, for a batch of seeds at the full headline shape (ring0 +
    loss + partition + sync)."""
    from corrosion_tpu.sim.calibrate import (
        HeadlineExactConfig,
        exact_shardings,
        packed_exact_init,
        packed_exact_tick,
        sharded_packed_exact_step,
    )

    cfg = HeadlineExactConfig(
        n_nodes=4096, fanout=4, ring0_size=256, max_transmissions=8,
        loss=0.05, partition_blocks=2, heal_tick=3, sync_interval=2,
        max_ticks=32, chunk_ticks=8,
    )
    mesh = Mesh(np.array(jax.devices()[:8]), ("nodes",))
    n_seeds = 2
    base = [jax.random.PRNGKey(11 + s) for s in range(n_seeds)]

    refs = [
        packed_exact_init(cfg, jax.random.fold_in(kk, 2**20))
        for kk in base
    ]
    batched = jax.vmap(
        lambda kk: packed_exact_init(cfg, jax.random.fold_in(kk, 2**20))
    )(jnp.stack(base))
    batched = jax.device_put(batched, exact_shardings(mesh))
    step = sharded_packed_exact_step(mesh, cfg)

    for t in range(5):
        keys_t = jnp.stack([jax.random.fold_in(kk, t) for kk in base])
        refs = [
            packed_exact_tick(r, jax.random.fold_in(kk, t), cfg)
            for r, kk in zip(refs, base)
        ]
        batched = step(batched, keys_t)
        for s in range(n_seeds):
            for field in ("infected", "msgs", "sent", "tx", "next_send"):
                np.testing.assert_array_equal(
                    np.asarray(getattr(batched, field)[s]),
                    np.asarray(getattr(refs[s], field)),
                    err_msg=f"{field} diverged at tick {t}, seed {s}",
                )
    # the epidemic genuinely progressed across shard boundaries
    assert 0.0 < float(np.asarray(batched.infected).mean()) < 1.0


def test_sharded_exact_negative_control():
    """The equality assertion above has discriminating power: the same
    sharded kernel driven by DIFFERENT per-seed keys must diverge from
    the single-chip reference within a few ticks."""
    from corrosion_tpu.sim.calibrate import (
        HeadlineExactConfig,
        exact_shardings,
        packed_exact_init,
        packed_exact_tick,
        sharded_packed_exact_step,
    )

    cfg = HeadlineExactConfig(
        n_nodes=4096, fanout=4, ring0_size=0, max_transmissions=8,
        max_ticks=32, chunk_ticks=8,
    )
    mesh = Mesh(np.array(jax.devices()[:8]), ("nodes",))
    good = jax.random.PRNGKey(11)
    evil = jax.random.PRNGKey(999)

    ref = packed_exact_init(cfg, jax.random.fold_in(good, 2**20))
    batched = jax.vmap(
        lambda kk: packed_exact_init(cfg, jax.random.fold_in(kk, 2**20))
    )(jnp.stack([good]))
    batched = jax.device_put(batched, exact_shardings(mesh))
    step = sharded_packed_exact_step(mesh, cfg)

    diverged = False
    for t in range(3):
        ref = packed_exact_tick(ref, jax.random.fold_in(good, t), cfg)
        batched = step(
            batched, jnp.stack([jax.random.fold_in(evil, t)])
        )
        if not np.array_equal(
            np.asarray(batched.infected[0]), np.asarray(ref.infected)
        ):
            diverged = True
            break
    assert diverged, "different keys produced identical trajectories"


@pytest.mark.parametrize("topology", ["het_ring", "wan_two_region"])
def test_sharded_dense_exact_topologies_match_packed(topology):
    """The scenario topologies hold across the DENSE mesh kernel too:
    _sharded_tick_local implements the same wan cross-drop and
    RTT-tier backoff as the single-chip oracle (regression: the
    sharded-dense path originally missed both, silently running
    uniform while every other kernel ran the family)."""
    from corrosion_tpu.sim.calibrate import (
        HeadlineExactConfig,
        exact_shardings,
        packed_exact_init,
        packed_exact_tick,
        sharded_packed_exact_step,
    )

    cfg = HeadlineExactConfig(
        n_nodes=4096, fanout=4, ring0_size=256, max_transmissions=8,
        loss=0.05, sync_interval=2, backoff_ticks=0.5,
        max_ticks=32, chunk_ticks=8, topology=topology,
    )
    mesh = Mesh(np.array(jax.devices()[:8]), ("nodes",))
    kk = jax.random.PRNGKey(21)
    ref = packed_exact_init(cfg, jax.random.fold_in(kk, 2**20))
    batched = jax.vmap(
        lambda k: packed_exact_init(cfg, jax.random.fold_in(k, 2**20))
    )(jnp.stack([kk]))
    batched = jax.device_put(batched, exact_shardings(mesh))
    step = sharded_packed_exact_step(mesh, cfg)
    for t in range(4):
        ref = packed_exact_tick(ref, jax.random.fold_in(kk, t), cfg)
        batched = step(batched, jnp.stack([jax.random.fold_in(kk, t)]))
        for field in ("infected", "msgs", "tx", "next_send"):
            np.testing.assert_array_equal(
                np.asarray(getattr(batched, field)[0]),
                np.asarray(getattr(ref, field)),
                err_msg=f"{field} diverged at tick {t} ({topology})",
            )
    assert bool(np.asarray(batched.infected).any())


def test_sharded_frontier_matches_single_chip_bitwise():
    """The mesh-native FRONTIER kernel (rings row-sharded, dense
    bookkeeping replicated per shard, only the per-round validity
    delta crossing the fabric) is BITWISE the single-chip
    ``frontier_exact_tick`` per tick — infected, msgs, tx, next_send
    AND the ring rows — at N=4096 on the 8-device mesh, full headline
    shape.  Through tests/test_frontier.py's oracle chain this pins
    sharded-sparse == sparse == packed_exact_tick."""
    from corrosion_tpu.models.sharded import sharded_frontier_exact_step
    from corrosion_tpu.sim.calibrate import (
        HeadlineExactConfig,
        frontier_exact_init,
        frontier_exact_tick,
        frontier_shardings,
    )

    cfg = HeadlineExactConfig(
        n_nodes=4096, fanout=4, ring0_size=256, max_transmissions=8,
        loss=0.05, partition_blocks=2, heal_tick=3, sync_interval=2,
        max_ticks=32, chunk_ticks=8,
    )
    mesh = Mesh(np.array(jax.devices()[:8]), ("nodes",))
    n_seeds = 2
    base = [jax.random.PRNGKey(11 + s) for s in range(n_seeds)]

    refs = [
        frontier_exact_init(cfg, jax.random.fold_in(kk, 2**20))
        for kk in base
    ]
    batched = jax.vmap(
        lambda kk: frontier_exact_init(cfg, jax.random.fold_in(kk, 2**20))
    )(jnp.stack(base))
    batched = jax.device_put(batched, frontier_shardings(mesh))
    step = sharded_frontier_exact_step(mesh, cfg)

    for t in range(5):
        keys_t = jnp.stack([jax.random.fold_in(kk, t) for kk in base])
        refs = [
            frontier_exact_tick(r, jax.random.fold_in(kk, t), cfg)
            for r, kk in zip(refs, base)
        ]
        batched = step(batched, keys_t)
        for s in range(n_seeds):
            for field in ("infected", "msgs", "ring", "tx", "next_send"):
                np.testing.assert_array_equal(
                    np.asarray(getattr(batched, field)[s]),
                    np.asarray(getattr(refs[s], field)),
                    err_msg=f"{field} diverged at tick {t}, seed {s}",
                )
    assert 0.0 < float(np.asarray(batched.infected).mean()) < 1.0


def test_sharded_frontier_negative_control():
    """Discriminating power: the sharded frontier kernel driven by
    different per-seed keys diverges from the single-chip reference
    within a few ticks."""
    from corrosion_tpu.models.sharded import sharded_frontier_exact_step
    from corrosion_tpu.sim.calibrate import (
        HeadlineExactConfig,
        frontier_exact_init,
        frontier_exact_tick,
        frontier_shardings,
    )

    cfg = HeadlineExactConfig(
        n_nodes=4096, fanout=4, ring0_size=0, max_transmissions=8,
        max_ticks=32, chunk_ticks=8,
    )
    mesh = Mesh(np.array(jax.devices()[:8]), ("nodes",))
    good = jax.random.PRNGKey(11)
    evil = jax.random.PRNGKey(999)

    ref = frontier_exact_init(cfg, jax.random.fold_in(good, 2**20))
    batched = jax.vmap(
        lambda kk: frontier_exact_init(cfg, jax.random.fold_in(kk, 2**20))
    )(jnp.stack([good]))
    batched = jax.device_put(batched, frontier_shardings(mesh))
    step = sharded_frontier_exact_step(mesh, cfg)

    diverged = False
    for t in range(3):
        ref = frontier_exact_tick(ref, jax.random.fold_in(good, t), cfg)
        batched = step(batched, jnp.stack([jax.random.fold_in(evil, t)]))
        if not np.array_equal(
            np.asarray(batched.infected[0]), np.asarray(ref.infected)
        ):
            diverged = True
            break
    assert diverged, "different keys produced identical trajectories"


def test_ring_fabric_small_cap_reports_overflow():
    """With a deliberately starved slot cap the fabric must not
    corrupt state silently: the overflow count reports the dropped
    demand, and every delivered row is still a true sender row."""
    from corrosion_tpu.models.broadcast import BroadcastParams
    from corrosion_tpu.models.sharded import (
        sharded_broadcast_step,
        sharded_broadcast_step_ring,
    )
    from corrosion_tpu.ops.keys import DEFAULT_CODEC as C

    devices = np.array(jax.devices()[:8])
    nodes_mesh = Mesh(devices, ("nodes",))
    n, r = 256, 4
    params = BroadcastParams(
        n_nodes=n, fanout_ring0=0, fanout_global=3, ring0_size=1,
        max_transmissions=8,
    )
    base = C.pack(jnp.ones((n, r), jnp.int32), jnp.ones((n, r), jnp.int32),
                  jnp.zeros((n, r), jnp.int32))
    news = C.pack(jnp.ones((r,), jnp.int32), jnp.full((r,), 2, jnp.int32),
                  jnp.ones((r,), jnp.int32))
    rows = base.at[0].set(news)
    # EVERY node active: demand far beyond a cap of 1
    tx = jnp.full((n,), params.max_transmissions, jnp.int32)
    rows = jnp.broadcast_to(news, (n, r)).at[1:].set(base[1:])
    msgs = jnp.zeros((n,), jnp.int32)

    step = sharded_broadcast_step_ring(nodes_mesh, params, slot_cap=1)
    spec = NamedSharding(nodes_mesh, P("nodes"))
    s_rows = jax.device_put(rows, spec)
    s_tx = jax.device_put(tx, spec)
    s_msgs = jax.device_put(msgs, spec)
    s_rows, s_tx, s_msgs, overflow = step(
        s_rows, s_tx, s_msgs, jax.random.PRNGKey(1)
    )
    assert int(overflow) > 0
    # no fabrication: every row is either the old row or the news row
    out = np.asarray(s_rows)
    legal = (
        (out == np.asarray(base)).all(axis=1)
        | (out == np.asarray(news)[None, :]).all(axis=1)
    )
    assert legal.all()


@pytest.mark.parametrize("overrides", [
    {},
    {"topology": "measured_ring", "rtt_tier_weights": (0, 0, 2, 2, 6, 1)},
    {"topology": "wan_two_region", "wan_cross_loss": 0.0,
     "wan_latency_ticks": 2},
], ids=["headline", "measured_ring", "wan_latency"])
def test_sharded_frontier_host_matches_single_chip_bitwise(overrides):
    """The MULTI-HOST frontier kernel — every O(N) leaf row-sharded
    over a ``hosts`` axis, infected/pending replicated by
    construction, ONLY the rejection loop's bitpacked validity deltas
    crossing the host fabric — is BITWISE the single-chip
    ``frontier_exact_tick`` per tick at N=256 on the 8-host mesh,
    across the headline shape and both new topology families
    (measured-RTT ring, tick-quantized WAN latency queue)."""
    from dataclasses import replace as _replace

    from corrosion_tpu.models.sharded import sharded_frontier_host_step
    from corrosion_tpu.sim.calibrate import (
        HeadlineExactConfig,
        frontier_exact_init,
        frontier_exact_tick,
        frontier_host_shardings,
    )

    cfg = _replace(
        HeadlineExactConfig(
            n_nodes=256, fanout=4, ring0_size=16, max_transmissions=8,
            loss=0.05, sync_interval=4, backoff_ticks=0.5, max_ticks=64,
        ),
        **overrides,
    )
    mesh = Mesh(np.array(jax.devices()[:8]), ("hosts",))
    n_seeds = 2
    base = [jax.random.PRNGKey(17 + s) for s in range(n_seeds)]

    refs = [
        frontier_exact_init(cfg, jax.random.fold_in(kk, 2**20))
        for kk in base
    ]
    batched = jax.vmap(
        lambda kk: frontier_exact_init(cfg, jax.random.fold_in(kk, 2**20))
    )(jnp.stack(base))
    batched = jax.device_put(batched, frontier_host_shardings(mesh))
    step = sharded_frontier_host_step(mesh, cfg)

    for t in range(6):
        keys_t = jnp.stack([jax.random.fold_in(kk, t) for kk in base])
        refs = [
            frontier_exact_tick(r, jax.random.fold_in(kk, t), cfg)
            for r, kk in zip(refs, base)
        ]
        batched = step(batched, keys_t)
        for s in range(n_seeds):
            for field in ("infected", "msgs", "ring", "tx", "next_send",
                          "pending"):
                np.testing.assert_array_equal(
                    np.asarray(getattr(batched, field)[s]),
                    np.asarray(getattr(refs[s], field)),
                    err_msg=f"{field} diverged at tick {t}, seed {s}",
                )
    assert 0.0 < float(np.asarray(batched.infected).mean())


def test_sharded_frontier_host_negative_control():
    """Discriminating power of the multi-host equality: a seeded
    corruption of ONE host's tx shard (a ring0 sender's remaining
    budget zeroed) desyncs the trajectory from the single-chip
    reference on the very next tick — the silenced node's msgs row
    stops counting, and the deliveries it owed never commit."""
    from corrosion_tpu.models.sharded import sharded_frontier_host_step
    from corrosion_tpu.sim.calibrate import (
        HeadlineExactConfig,
        frontier_exact_init,
        frontier_exact_tick,
        frontier_host_shardings,
    )

    cfg = HeadlineExactConfig(
        n_nodes=256, fanout=4, ring0_size=16, max_transmissions=8,
        loss=0.0, sync_interval=0, backoff_ticks=0.0, max_ticks=64,
    )
    mesh = Mesh(np.array(jax.devices()[:8]), ("hosts",))
    key = jax.random.PRNGKey(17)

    ref = frontier_exact_init(cfg, jax.random.fold_in(key, 2**20))
    batched = jax.vmap(
        lambda kk: frontier_exact_init(cfg, jax.random.fold_in(kk, 2**20))
    )(jnp.stack([key]))
    step = sharded_frontier_host_step(mesh, cfg)

    # one clean tick so the epidemic is live but far from saturated
    ref = frontier_exact_tick(ref, jax.random.fold_in(key, 0), cfg)
    batched = jax.device_put(batched, frontier_host_shardings(mesh))
    batched = step(batched, jnp.stack([jax.random.fold_in(key, 0)]))

    # zero a ring0 sender's remaining budget on its owning host's shard
    corrupt = batched.tx.at[0, 0].set(jnp.int32(0))
    assert int(corrupt[0, 0]) != int(batched.tx[0, 0])
    batched = batched._replace(tx=corrupt)
    diverged = False
    for t in range(1, 9):
        ref = frontier_exact_tick(ref, jax.random.fold_in(key, t), cfg)
        batched = step(batched, jnp.stack([jax.random.fold_in(key, t)]))
        if not np.array_equal(
            np.asarray(batched.msgs[0]), np.asarray(ref.msgs)
        ) or not np.array_equal(
            np.asarray(batched.infected[0]), np.asarray(ref.infected)
        ):
            diverged = True
            break
    assert diverged, "corrupted host shard produced an identical trajectory"


def test_host_mesh_alignment_guard():
    """The bitpacked delta exchange needs byte-aligned per-host rows:
    a mesh whose host count does not divide n_nodes into multiples of
    8 is rejected loudly, not silently mis-packed."""
    from corrosion_tpu.models.sharded import sharded_frontier_host_step
    from corrosion_tpu.sim.calibrate import HeadlineExactConfig

    cfg = HeadlineExactConfig(n_nodes=264, ring0_size=16)
    mesh = Mesh(np.array(jax.devices()[:8]), ("hosts",))
    with pytest.raises(ValueError, match="byte-aligned"):
        sharded_frontier_host_step(mesh, cfg)
